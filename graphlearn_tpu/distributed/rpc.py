"""Socket RPC substrate for the server-client deployment mode.

The reference rides torch.distributed.rpc/TensorPipe (ibv RDMA + uv
TCP, `distributed/rpc.py:236-292`).  A TPU-VM sampling tier has no
torch runtime to lean on, and the *data* plane between hosts is DCN
TCP anyway — so the control plane here is a deliberately small
threaded socket RPC:

  * frames: ``[u32 kind][u64 len][payload]`` — kind 0 = pickled
    control object, kind 1 = tensor-map bytes (`csrc/tensor_map.cc`
    serialization, no pickle on the sample-message path);
  * server: one daemon thread per connection, handlers looked up in a
    registry (the reference's `RpcCalleeBase`/`rpc_register`,
    `rpc.py:364-443`);
  * client: a connection pool so concurrent prefetch threads each own
    a socket.

Failure story (the resilience layer, `distributed/resilience.py`):

  * every request carries an **idempotency id** ``(client_token,
    seq)``; the server keeps a bounded per-client **replay cache** of
    encoded replies (with in-progress markers), so a request retried
    after a lost reply is answered from cache — **never executed
    twice** (the fetch handler pops a message; double execution would
    lose a batch);
  * the client applies a **per-request socket timeout**, severs and
    reopens the connection on ANY transport fault (a peer dying
    mid-frame must not leave a half-read stream to misparse the next
    reply), and retries under a `RetryPolicy` deadline with capped,
    seeded-jitter backoff — each retry emitted as an ``rpc.retry``
    flight-recorder event;
  * servers answer a built-in ``__ping__`` so callers can tell a slow
    peer (retry) from a dead one (`PeerLostError`).

Trusted-cluster assumption (same as TensorPipe): control frames use
pickle, so only run between your own hosts.
"""
from __future__ import annotations

import itertools
import pickle
import socket
import socketserver
import struct
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..native import parse_tensor_map, serialize_tensor_map

_HDR = struct.Struct('<IQ')
KIND_PICKLE = 0
KIND_TENSOR_MAP = 1

#: replay-cache bounds: encoded replies kept per client token (count
#: and bytes), and distinct client tokens tracked per server.  The
#: entry count must stay comfortably above any client's concurrent
#: request fan-out (prefetch threads): a retry whose cached reply was
#: pruned re-executes the handler — exactly the double execution the
#: cache exists to prevent.  64 entries vs the default prefetch of 4
#: leaves a 16x margin.
REPLAY_ENTRIES_PER_CLIENT = 64
REPLAY_BYTES_PER_CLIENT = 64 * 1024 * 1024
REPLAY_MAX_CLIENTS = 256
#: completed reply frames older than this are dropped regardless of
#: the caps: a retry only arrives within the client's retry deadline
#: (default 120s), so frames delivered long ago are pure dead weight —
#: without the horizon, fetch replies (hundreds of KB to MB each)
#: would pin the full byte budget per token on a long-running server.
REPLAY_RETAIN_SECS = 600.0


def _send_frame(sock: socket.socket, kind: int, payload: bytes) -> None:
  sock.sendall(_HDR.pack(kind, len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
  buf = bytearray()
  while len(buf) < n:
    chunk = sock.recv(min(n - len(buf), 1 << 20))
    if not chunk:
      raise ConnectionError('peer closed')
    buf += chunk
  return bytes(buf)


def _recv_frame(sock: socket.socket) -> Tuple[int, bytes]:
  kind, ln = _HDR.unpack(_recv_exact(sock, _HDR.size))
  return kind, _recv_exact(sock, ln)


_tmap_usable = True     # flipped off after a native-serialize failure


def _encode_obj(obj: Any) -> Tuple[int, bytes]:
  """Encode one value to its frame ``(kind, payload)``; dict-of-ndarray
  goes through the tensor-map path.  A native layer that cannot load
  degrades to pickle — slower, never wrong (the frame kind tells the
  receiver how to parse)."""
  global _tmap_usable
  if isinstance(obj, RawTensorMap):
    return KIND_TENSOR_MAP, bytes(obj)
  if (_tmap_usable and isinstance(obj, dict) and obj
      and all(isinstance(k, str) for k in obj)
      and all(isinstance(v, (np.ndarray, np.generic))
              for v in obj.values())):
    try:
      return KIND_TENSOR_MAP, serialize_tensor_map(obj)
    except Exception:               # noqa: BLE001
      # flip the fast path off ONLY when the native layer itself can't
      # load — a payload-specific failure (say an unsupported dtype in
      # one reply) falls back to pickle for THIS message without
      # demoting every well-formed tensor map for the process lifetime
      from .. import native
      if not native.available():
        _tmap_usable = False
  return KIND_PICKLE, pickle.dumps(obj, protocol=5)


def _decode_obj(kind: int, payload: bytes) -> Any:
  if kind == KIND_TENSOR_MAP:
    return parse_tensor_map(payload)
  return pickle.loads(payload)


def send_obj(sock: socket.socket, obj: Any) -> None:
  """Send one value; dict-of-ndarray goes through the tensor-map path."""
  _send_frame(sock, *_encode_obj(obj))


def recv_obj(sock: socket.socket) -> Any:
  return _decode_obj(*_recv_frame(sock))


class RawTensorMap(bytes):
  """Already-serialized tensor-map payload: `send_obj` frames it
  directly (no parse/re-serialize on the server's fetch hot path) and
  the receiving side parses it into the usual dict."""


class RpcError(RuntimeError):
  pass


class _RemoteError:
  """A handler exception shipped to the caller.  ``kind`` carries the
  original exception type name as a STRUCTURED field so clients can
  classify (e.g. a server-side `PeerLostError`) without sniffing the
  message text; it resurfaces as ``RpcError.remote_kind``.  ``extra``
  carries the exception's scalar attributes (an `AdmissionRejected`'s
  ``reason``/``retry_after_ms``/``queue_depth`` diagnostics) so the
  client can REBUILD the typed error faithfully instead of parsing
  its message."""

  def __init__(self, msg: str, kind: Optional[str] = None,
               extra: Optional[dict] = None):
    self.msg = msg
    self.kind = kind
    self.extra = extra


def _error_extra(exc: BaseException) -> Optional[dict]:
  """Scalar attributes of a handler exception, wire-safe."""
  out = {k: v for k, v in getattr(exc, '__dict__', {}).items()
         if v is None or isinstance(v, (str, int, float, bool))}
  return out or None


def _remote_to_error(out: '_RemoteError') -> RpcError:
  err = RpcError(out.msg)
  err.remote_kind = getattr(out, 'kind', None)
  err.remote_extra = getattr(out, 'extra', None)
  return err


class _TransportError(Exception):
  """Internal marker: the reply never arrived intact (connection
  severed, timed out, or the frame misparsed).  ALWAYS resets the
  socket and retries — never surfaces to callers directly."""


class _ReplayEntry:
  """One replay-cache slot: ``frame`` lands when execution completes;
  until then duplicates park on ``done`` instead of re-executing."""
  __slots__ = ('frame', 'done', 'done_at')

  def __init__(self):
    self.frame: Optional[Tuple[int, bytes]] = None
    self.done = threading.Event()
    self.done_at: Optional[float] = None

  def resolve(self, frame: Tuple[int, bytes]) -> None:
    self.frame = frame
    self.done_at = time.monotonic()
    self.done.set()


class _ReplayCache:
  """Bounded per-client-token reply cache (the server side of request
  idempotency).  ``begin`` either claims a fresh entry (caller must
  execute and `finish`), returns the existing one (caller replays), or
  reports the entry EVICTED — a retry whose cached reply was pruned
  must NOT silently re-execute (the fetch handler pops a message;
  re-running it would hand one client two different batches under one
  request id).  Eviction tracking is a per-client high-water mark over
  pruned seqs: client seqs are monotone, so ``seq <= watermark`` with
  no live entry means the reply existed once and is gone."""

  EVICTED = 'evicted'

  def __init__(self, max_entries: int = REPLAY_ENTRIES_PER_CLIENT,
               max_bytes: int = REPLAY_BYTES_PER_CLIENT,
               max_clients: int = REPLAY_MAX_CLIENTS):
    self._lock = threading.Lock()
    # guarded-by: self._lock
    self._clients: 'OrderedDict[str, OrderedDict[int, _ReplayEntry]]' = \
        OrderedDict()
    # bounded LRU: a mark only matters while a zombie client might
    # still retry; without a cap the server leaks one int per client
    # token EVER seen (the ISSUE's serving fleet recycles clients
    # continuously).  4x max_clients keeps marks well past the
    # per-client eviction horizon.
    self._evicted_marks: 'OrderedDict[str, int]' = OrderedDict()  # guarded-by: self._lock
    self._max_marks = 4 * max_clients
    self._max_entries = max_entries
    self._max_bytes = max_bytes
    self._max_clients = max_clients

  def occupancy(self) -> int:
    """Live entries across every client — the exactly-once cache's
    memory pressure (exported as the ``rpc.replay_cache_entries``
    gauge; near the eviction caps = retries at risk of
    `ReplayEvictedError`)."""
    with self._lock:
      return sum(len(per) for per in self._clients.values())

  def begin(self, token: str, seq: int):
    """Returns ``(entry, fresh)`` — ``fresh`` means the caller owns
    execution; otherwise replay (wait on ``entry.done`` if needed).
    Returns ``(None, EVICTED)`` when this seq's entry was pruned —
    the caller must answer with the typed eviction error instead of
    executing."""
    with self._lock:
      per = self._clients.get(token)
      if per is None:
        per = self._clients[token] = OrderedDict()
      self._clients.move_to_end(token)
      ent = per.get(seq)
      if ent is not None:
        per.move_to_end(seq)
        return ent, False
      if seq <= self._evicted_marks.get(token, -1):
        self._evicted_marks.move_to_end(token)
        return None, self.EVICTED
      ent = per[seq] = _ReplayEntry()
      self._prune_locked(token)
      return ent, True

  def _mark_evicted_locked(self, token: str, seq: int) -> None:
    cur = self._evicted_marks.get(token, -1)
    if seq > cur:
      self._evicted_marks[token] = seq
    self._evicted_marks.move_to_end(token)
    while len(self._evicted_marks) > self._max_marks:
      self._evicted_marks.popitem(last=False)

  def _prune_locked(self, token: str) -> None:
    per = self._clients[token]
    # time horizon first: delivered frames a retry can no longer ask
    # for (any retry lands within the client's deadline) are dead
    # weight whatever the caps say
    horizon = time.monotonic() - REPLAY_RETAIN_SECS
    for s in [s for s, e in per.items()
              if e.done_at is not None and e.done_at < horizon]:
      del per[s]
      self._mark_evicted_locked(token, s)
    total = sum(len(e.frame[1]) for e in per.values()
                if e.frame is not None)
    while len(per) > self._max_entries or total > self._max_bytes:
      victim = next((s for s, e in per.items() if e.frame is not None),
                    None)
      if victim is None:            # everything in flight: never evict
        break
      total -= len(per.pop(victim).frame[1])
      self._mark_evicted_locked(token, victim)
    while len(self._clients) > self._max_clients:
      stale = next((t for t, p in self._clients.items()
                    if t != token
                    and all(e.frame is not None for e in p.values())),
                   None)
      if stale is None:
        break
      # a whole-client eviction forgets its seqs too: keep the mark so
      # a zombie client's late retry cannot re-execute either
      if self._clients[stale]:
        self._mark_evicted_locked(stale, max(self._clients[stale]))
      del self._clients[stale]


class RpcServer:
  """Threaded request server with a name->handler registry."""

  def __init__(self, host: str = '0.0.0.0', port: int = 0):
    registry: Dict[str, Callable] = {}
    self._registry = registry
    active: set = set()
    closed = [False]
    alock = threading.Lock()
    replay = _ReplayCache()
    self._active, self._alock, self._closed = active, alock, closed
    self._replay = replay
    # liveness endpoint: answered straight from the registry, so a
    # probe exercises the same accept/dispatch path real requests use
    registry['__ping__'] = lambda: {'ok': True, 'time': time.time()}

    def _serve_one(sock) -> None:
      req = recv_obj(sock)
      if len(req) == 4:
        rid, name, args, kwargs = req
      else:                         # legacy 3-tuple, no idempotency id
        rid, (name, args, kwargs) = None, req
      ent = fresh = None
      if rid is not None:
        ent, fresh = replay.begin(str(rid[0]), int(rid[1]))
        if fresh == _ReplayCache.EVICTED:
          # the reply existed once and was pruned: answering the retry
          # by re-executing would break exactly-once — a typed error
          # (resilience.ReplayEvictedError client-side) is the honest
          # outcome
          _send_frame(sock, *_encode_obj(_RemoteError(
              f'replay entry for request {rid} was evicted before the '
              'retry arrived (cache pressure: raise '
              'REPLAY_ENTRIES_PER_CLIENT or lower prefetch fan-out)',
              kind='ReplayEvictedError')))
          return
        if not fresh:
          # retried request: the first execution owns the side effect;
          # park until its reply frame lands, then replay it verbatim.
          # The park outlives every configurable wait (retry deadline,
          # server fetch deadline) so a legitimately long first
          # execution is never failed out from under its retry.
          from .resilience import default_policy, fetch_deadline
          park = max(600.0, 2 * default_policy().deadline,
                     2 * fetch_deadline())
          if not ent.done.wait(timeout=park):
            _send_frame(sock, *_encode_obj(_RemoteError(
                'original execution still in flight')))
            return
          _send_frame(sock, *ent.frame)
          return
      frame = None
      try:
        fn = registry.get(name)
        try:
          if fn is None:
            raise RpcError(f'no handler registered for {name!r}')
          result = fn(*args, **kwargs)
        except Exception as exc:    # ship the error to the caller
          result = _RemoteError(f'{type(exc).__name__}: {exc}',
                                kind=type(exc).__name__,
                                extra=_error_extra(exc))
        try:
          frame = _encode_obj(result)
        except Exception as exc:    # unencodable result: still a reply
          frame = _encode_obj(
              _RemoteError(f'reply encoding failed: {exc}'))
      finally:
        # the entry must resolve even on BaseException (thread kill,
        # interpreter shutdown) — a permanently-pending entry would
        # park every future retry of this rid until their timeouts.
        # Cache BEFORE sending: if this connection died, the retry
        # (on a fresh connection) replays the frame instead of
        # re-executing a non-idempotent handler.
        if ent is not None and not ent.done.is_set():
          if frame is None:
            frame = _encode_obj(_RemoteError(
                'execution aborted before a reply was produced'))
          ent.resolve(frame)
      _send_frame(sock, *frame)

    class Handler(socketserver.BaseRequestHandler):
      def handle(self):
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        with alock:
          if closed[0]:
            # accepted just as shutdown() snapshotted the set: self-
            # close instead of serving a "dead" server's connection
            try:
              sock.close()
            except OSError:
              pass
            return
          active.add(sock)
        try:
          while True:
            _serve_one(sock)
        except (ConnectionError, EOFError, OSError):
          return
        finally:
          with alock:
            active.discard(sock)

    class Server(socketserver.ThreadingTCPServer):
      daemon_threads = True
      allow_reuse_address = True

    self._server = Server((host, port), Handler)
    self.host, self.port = self._server.server_address
    self._thread = threading.Thread(target=self._server.serve_forever,
                                    daemon=True)
    # live ops plane: replay-cache occupancy at scrape time (latest
    # RpcServer in the process wins the gauge — one server per
    # process outside tests; shutdown() unregisters so a dead
    # server's cache isn't pinned or reported as live)
    from ..telemetry.live import live
    self._occupancy_fn = replay.occupancy
    live.gauge('rpc.replay_cache_entries', fn=self._occupancy_fn)

  def register(self, name: str, fn: Callable) -> None:
    """Reference `rpc_register` (`distributed/rpc.py:401-420`)."""
    self._registry[name] = fn

  def start(self) -> None:
    self._thread.start()

  def shutdown(self) -> None:
    """Stop accepting AND sever live connections: handler threads are
    daemons blocked in recv, so without the severing a "shut down"
    server keeps answering pooled peers indefinitely — callers (and
    failure tests) must see a dead peer as ConnectionError, not as a
    healthy endpoint."""
    from ..telemetry.live import live
    live.unregister_gauge('rpc.replay_cache_entries',
                          fn=self._occupancy_fn)
    self._server.shutdown()
    self._server.server_close()
    with self._alock:
      self._closed[0] = True
      conns = list(self._active)
    for s in conns:
      try:
        s.shutdown(socket.SHUT_RDWR)
      except OSError:
        pass
      try:
        s.close()
      except OSError:
        pass


class RpcClient:
  """Per-thread pooled connections to one server address, with the
  resilience layer on every `request`: per-attempt socket timeout,
  reset-and-reconnect on any transport fault, idempotent request ids,
  deadline-bounded seeded backoff."""

  def __init__(self, host: str, port: int, policy=None):
    self.addr = (host, port)
    self._local = threading.local()
    self._all: list = []
    self._lock = threading.Lock()
    self._policy = policy
    self._token = uuid.uuid4().hex
    self._seq = itertools.count()
    self._closed = False

  def policy(self):
    if self._policy is None:
      from .resilience import default_policy
      self._policy = default_policy()
    return self._policy

  def _sock(self, timeout: Optional[float] = None) -> socket.socket:
    s = getattr(self._local, 'sock', None)
    if s is None:
      s = socket.create_connection(self.addr,
                                   timeout=timeout or 120)
      s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
      self._local.sock = s
      with self._lock:
        self._all.append(s)
    return s

  def _drop_sock(self) -> None:
    """Sever the calling thread's connection.  A transport fault
    leaves the stream position undefined (half-read frame); the only
    safe recovery is a fresh socket."""
    s = getattr(self._local, 'sock', None)
    if s is None:
      return
    self._local.sock = None
    with self._lock:
      try:
        self._all.remove(s)
      except ValueError:
        pass
    try:
      s.close()
    except OSError:
      pass

  def _roundtrip(self, rid, name: str, args, kwargs, timeout: float,
                 faults=()) -> Any:
    """One attempt: send the request, read the reply.  Any failure —
    connect, send, timeout, severed mid-frame, misparsed reply — is
    normalized to `_TransportError` so the retry loop treats the whole
    attempt atomically (and resets the socket)."""
    from ..testing import chaos
    try:
      sock = self._sock(timeout)
      sock.settimeout(timeout)
      send_obj(sock, (rid, name, args, kwargs))
    except Exception as e:
      raise _TransportError(f'send failed: {e}') from e
    dropped = False
    for f in faults:
      if f.action == 'drop':
        # sever AFTER the send: the server may already be executing —
        # the replay cache, not a re-execution, must answer the retry
        dropped = True
        try:
          sock.shutdown(socket.SHUT_RDWR)
        except OSError:
          pass
    if dropped:
      # the attempt FAILS deterministically: on a fast loopback the
      # reply can already sit in the receive buffer when the shutdown
      # lands, and reading it would silently un-inject the fault (the
      # retry-and-replay path under test would never run)
      raise _TransportError('injected connection drop')
    try:
      kind, payload = _recv_frame(sock)
    except Exception as e:
      raise _TransportError(f'recv failed: {e}') from e
    if any(f.action == 'corrupt' for f in faults):
      payload = chaos.corrupt_payload(payload)
    try:
      return _decode_obj(kind, payload)
    except Exception as e:
      raise _TransportError(f'reply misparsed: {e}') from e

  def request(self, name: str, *args, **kwargs) -> Any:
    """Synchronous call (reference `request_server`,
    `dist_client.py:79-98`); safe from multiple threads.  Transport
    faults retry under the policy deadline with the SAME request id
    (the server-side replay cache makes the retry exactly-once);
    application errors raise `RpcError` immediately."""
    from ..telemetry.recorder import recorder
    from ..testing import chaos
    from ..utils.profiling import metrics
    from .resilience import RetryExhausted
    if self._closed:
      raise RpcError('client closed')
    policy = self.policy()
    rid = (self._token, next(self._seq))
    deadline = time.monotonic() + policy.deadline
    attempt = 0
    while True:
      faults = chaos.rpc_faults(name)
      chaos.maybe_delay(faults)
      try:
        out = self._roundtrip(rid, name, args, kwargs,
                              policy.request_timeout, faults)
      except _TransportError as e:
        self._drop_sock()
        now = time.monotonic()
        if self._closed:
          raise RpcError('client closed') from e
        if now >= deadline:
          raise RetryExhausted(
              f'{name!r} to {self.addr} failed after {attempt + 1} '
              f'attempt(s) over {policy.deadline:.1f}s: {e}') from e
        delay = min(policy.delay(attempt), max(deadline - now, 0.0))
        metrics.inc('rpc.retries')
        recorder.emit('rpc.retry', op=name, attempt=attempt,
                      addr=f'{self.addr[0]}:{self.addr[1]}',
                      error=str(e), backoff_secs=round(delay, 4))
        time.sleep(delay)
        attempt += 1
        continue
      if isinstance(out, _RemoteError):
        if getattr(out, 'kind', None) == 'ReplayEvictedError':
          # typed: the server pruned this request's reply before the
          # retry arrived — re-execution was refused to keep
          # exactly-once, so the caller must treat the request as of
          # unknown outcome (not silently get a second execution)
          from .resilience import ReplayEvictedError
          raise ReplayEvictedError(out.msg)
        raise _remote_to_error(out)
      return out

  def request_once(self, name: str, *args, timeout: float = 2.0,
                   **kwargs) -> Any:
    """One attempt on a FRESH connection, no retries, no request id —
    the liveness-probe primitive (a pooled socket may be the wedged
    thing being diagnosed)."""
    s = socket.create_connection(self.addr, timeout=timeout)
    try:
      s.settimeout(timeout)
      s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
      send_obj(s, (None, name, args, kwargs))
      out = recv_obj(s)
    finally:
      try:
        s.close()
      except OSError:
        pass
    if isinstance(out, _RemoteError):
      raise _remote_to_error(out)
    return out

  def probe(self, timeout: float = 2.0) -> bool:
    """Is the server answering its built-in ``__ping__``?  The
    slow-peer / dead-peer discriminator."""
    try:
      return bool(self.request_once('__ping__', timeout=timeout))
    except Exception:               # noqa: BLE001 — any failure = dead
      return False

  def close(self) -> None:
    self._closed = True
    with self._lock:
      for s in self._all:
        try:
          s.close()
        except OSError:
          pass
      self._all.clear()
