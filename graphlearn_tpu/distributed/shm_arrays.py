"""POSIX-shm-backed numpy arrays for non-fork producer workers.

With the old ``fork`` start method, sampling workers inherited the
host dataset copy-on-write — zero-copy but fork-after-JAX is unsafe
(JAX's runtime is multithreaded; a fork can inherit held locks and
deadlock, which CPython warns about).  The default is now
``forkserver``: workers descend from a clean server process with no
JAX threads, and the dataset crosses the boundary through POSIX shared
memory — ONE copy at producer init, zero copies per worker, instead of
pickling the arrays into every child.

`share_dataset` converts a `HostDataset` / `HostHeteroDataset` into a
picklable `SharedDatasetHandle` plus the parent-side segments (close +
unlink them at shutdown); `SharedDatasetHandle.materialize` rebuilds
the dataset in a worker as zero-copy views over the attached segments.
"""
from __future__ import annotations

from multiprocessing import shared_memory
from typing import List, Optional, Tuple

import numpy as np

from .host_dataset import HostDataset, HostHeteroDataset


class SharedArrayHandle:
  """Picklable (name, shape, dtype) recipe for an shm-backed array."""

  def __init__(self, name: str, shape, dtype):
    self.name = name
    self.shape = tuple(shape)
    self.dtype = np.dtype(dtype)

  def attach(self) -> Tuple[np.ndarray, shared_memory.SharedMemory]:
    """Zero-copy view; caller must keep the returned segment alive for
    the array's lifetime."""
    shm = shared_memory.SharedMemory(name=self.name)
    arr = np.ndarray(self.shape, self.dtype, buffer=shm.buf)
    return arr, shm


def to_shared(arr: Optional[np.ndarray]):
  """Copy ``arr`` into a fresh shm segment.  Returns
  ``(handle, segment)`` (both None for a None array)."""
  if arr is None:
    return None, None
  arr = np.ascontiguousarray(arr)
  shm = shared_memory.SharedMemory(create=True, size=max(arr.nbytes, 1))
  view = np.ndarray(arr.shape, arr.dtype, buffer=shm.buf)
  view[...] = arr
  return SharedArrayHandle(shm.name, arr.shape, arr.dtype), shm


class SharedDatasetHandle:
  """Picklable reconstruction recipe for a host dataset in shm."""

  def __init__(self, kind: str, fields: dict, meta: dict):
    self.kind = kind              # 'homo' | 'hetero'
    self.fields = fields          # name -> handle | {key -> handle}
    self.meta = meta              # non-array fields

  def materialize(self):
    """Rebuild the dataset from shm.  Returns ``(dataset, segments)``;
    the worker must hold ``segments`` as long as the dataset lives."""
    segs: List[shared_memory.SharedMemory] = []

    def get(h):
      if h is None:
        return None
      arr, shm = h.attach()
      segs.append(shm)
      return arr

    if self.kind == 'homo':
      ds = HostDataset(
          get(self.fields['indptr']), get(self.fields['indices']),
          edge_ids=get(self.fields['edge_ids']),
          node_features=get(self.fields['node_features']),
          node_labels=get(self.fields['node_labels']),
          edge_features=get(self.fields['edge_features']))
      # shard identity survives the boundary so workers can build the
      # cross-server sampler (`host_dist_sampler.py`)
      ds.node_pb = get(self.fields.get('node_pb'))
      ds.partition_idx = self.meta.get('partition_idx')
      return ds, segs
    csr = {et: (get(ip), get(ix), get(ei))
           for et, (ip, ix, ei) in self.fields['csr'].items()}
    ds = HostHeteroDataset(
        csr, self.meta['num_nodes'],
        node_features={nt: get(h)
                       for nt, h in self.fields['node_features'].items()},
        node_labels={nt: get(h)
                     for nt, h in self.fields['node_labels'].items()},
        edge_features={et: get(h)
                       for et, h in self.fields['edge_features'].items()})
    pb = self.fields.get('node_pb')
    ds.node_pb = ({nt: get(h) for nt, h in pb.items()}
                  if pb is not None else None)
    ds.partition_idx = self.meta.get('partition_idx')
    return ds, segs


def share_dataset(ds):
  """``(SharedDatasetHandle, parent_segments)`` for a host dataset."""
  segs: List[shared_memory.SharedMemory] = []

  def put(arr):
    h, s = to_shared(arr)
    if s is not None:
      segs.append(s)
    return h

  if isinstance(ds, HostHeteroDataset):
    pb = getattr(ds, 'node_pb', None)
    fields = {
        'csr': {et: tuple(put(a) for a in csr)
                for et, csr in ds.csr.items()},
        'node_features': {nt: put(a)
                          for nt, a in ds.node_features.items()},
        'node_labels': {nt: put(a) for nt, a in ds.node_labels.items()},
        'edge_features': {et: put(a)
                          for et, a in ds.edge_features.items()},
        'node_pb': ({nt: put(a) for nt, a in pb.items()}
                    if pb is not None else None),
    }
    meta = {'num_nodes': dict(ds.num_nodes),
            'partition_idx': getattr(ds, 'partition_idx', None)}
    return SharedDatasetHandle('hetero', fields, meta), segs
  fields = {
      'indptr': put(ds.indptr), 'indices': put(ds.indices),
      'edge_ids': put(ds.edge_ids),
      'node_features': put(ds.node_features),
      'node_labels': put(ds.node_labels),
      'edge_features': put(ds.edge_features),
      'node_pb': put(getattr(ds, 'node_pb', None)),
  }
  meta = {'partition_idx': getattr(ds, 'partition_idx', None)}
  return SharedDatasetHandle('homo', fields, meta), segs


def release(segs) -> None:
  """Parent-side cleanup: close + unlink every segment."""
  for s in segs or ():
    try:
      s.close()
      s.unlink()
    except Exception:
      pass
