"""Loader deployment options (reference `distributed/dist_options.py:26-265`).

Three modes, same trio as the reference:

  * **Collocated** — sampling runs synchronously in the training
    process (`_BasicDistSamplingWorkerOptions` + `Collocated…`, `:119`).
  * **Mp** — a pool of sampling subprocesses per trainer feeding a
    `ShmChannel` (`MpDistSamplingWorkerOptions`, `:145-199`).
  * **Remote** — sampling runs on dedicated server hosts; the trainer
    pulls over sockets (`RemoteDistSamplingWorkerOptions`, `:202-258`).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Union

import numpy as np


def binary_num_negatives(batch: int, amount: float) -> int:
  """Binary-mode negative count for a ``batch``-edge seed slice — the
  ONE definition shared by the sampler, the capacity plan and the
  metadata collation (a rounding mismatch between them undersizes
  static buffers; it happened once)."""
  return int(np.ceil(batch * amount))


@dataclass
class HostSamplingConfig:
  """What the producers sample per seed batch (reference
  ``SamplingConfig``, `sampler/base.py:334-346`: the NODE/LINK/SUBGRAPH
  dispatch carried into sampling workers).

  Attributes:
    sampling_type: ``'node'`` (seed ids), ``'link'`` (seed edge pairs,
      optional third label column), or ``'subgraph'`` (induced
      enclosing subgraphs).
    neg_mode / neg_amount: link-mode negative sampling spec.
    input_type: hetero seed type — a node type (node mode) or an edge
      type 3-tuple (link mode); None for homogeneous datasets.
    peer_addrs: partitioned deployments only — ``[(host, port), ...]``
      of every partition's `PartitionService` (index = partition):
      producers fed a SHARD dataset build a cross-server
      `HostDistNeighborSampler` fanning each hop/feature lookup out to
      these peers (reference `_sample_one_hop` remote path,
      `dist_neighbor_sampler.py:542-598`).  None + full dataset =
      plain local sampler; None + shard dataset = refused.
  """
  sampling_type: str = 'node'
  neg_mode: Optional[str] = None       # 'binary' | 'triplet'
  neg_amount: float = 1.0
  input_type: Union[str, tuple, None] = None
  peer_addrs: Optional[tuple] = None

  def expansion_seeds(self, batch_size: int) -> int:
    """EXACT number of node seeds entering multi-hop expansion for a
    full seed batch — matches ``HostNeighborSampler``'s seed
    construction via :func:`binary_num_negatives`."""
    b = int(batch_size)
    if self.sampling_type != 'link':
      return b
    if self.neg_mode == 'binary':
      return 2 * b + 2 * binary_num_negatives(b, self.neg_amount)
    if self.neg_mode == 'triplet':
      return 2 * b + b * int(np.ceil(self.neg_amount))
    return 2 * b

  def label_cap(self, batch_size: int) -> int:
    """Static width of ``edge_label_index`` / ``edge_label``."""
    b = int(batch_size)
    if self.neg_mode == 'binary':
      return b + binary_num_negatives(b, self.neg_amount)
    return b

  def hetero_input_sizes(self, batch_size: int) -> dict:
    """Per-node-type seed counts entering hetero multi-hop expansion —
    the ``input_sizes`` of the capacity plan.  Node mode seeds one
    type; link mode seeds the input edge type's two endpoint types
    (merged when the relation is type-homophilous)."""
    b = int(batch_size)
    if self.sampling_type != 'link':
      assert isinstance(self.input_type, str), (
          'hetero node sampling needs a node-type input_type')
      return {self.input_type: b}
    s, _, d = self.input_type
    if self.neg_mode == 'binary':
      nn = binary_num_negatives(b, self.neg_amount)
      src_n, dst_n = b + nn, b + nn
    elif self.neg_mode == 'triplet':
      src_n, dst_n = b, b + b * int(np.ceil(self.neg_amount))
    else:
      src_n, dst_n = b, b
    if s == d:
      return {s: src_n + dst_n}
    return {s: src_n, d: dst_n}


@dataclass
class CollocatedDistSamplingWorkerOptions:
  """Sample in-process, synchronously."""
  use_native: bool = False       # host CPU sampler instead of device ops
  collect_features: bool = True


@dataclass
class MpDistSamplingWorkerOptions:
  """Spawn ``num_workers`` sampling subprocesses feeding a shm channel.

  Reference defaults: channel 64MB/worker, capacity scaled by pending
  batches (`dist_options.py:145-199`).
  """
  num_workers: int = 2
  worker_concurrency: int = 4           # pending batches per worker
  channel_capacity: Optional[int] = None  # default 4 * num_workers * conc
  channel_size: Union[int, str, None] = None  # default 64MB * num_workers
  collect_features: bool = True
  pin_memory: bool = False              # accepted for API parity; no-op
  #: 'forkserver' (default): workers descend from a clean, unthreaded
  #: server process; the dataset is staged into POSIX shm once and
  #: attached zero-copy per worker (`shm_arrays.share_dataset`).
  #: 'fork' is opt-in zero-copy CoW — SAFE ONLY IF the parent is
  #: effectively single-threaded at Process.start() time: JAX/XLA
  #: spawn runtime threads at first backend use, and a fork can
  #: inherit their held locks mid-operation (undebuggable child
  #: deadlocks; the CPython DeprecationWarning).  'spawn' also works
  #: (slower startup, shm staging as forkserver).
  mp_start_method: str = 'forkserver'

  def resolved_capacity(self) -> int:
    return (self.channel_capacity if self.channel_capacity is not None
            else 4 * self.num_workers * self.worker_concurrency)

  def resolved_size(self):
    if self.channel_size is not None:
      return self.channel_size
    return 64 * 1024 * 1024 * self.num_workers


@dataclass
class RemoteDistSamplingWorkerOptions:
  """Pull batches from sampling servers.

  Reference `dist_options.py:202-258`: server ranks, per-server buffer,
  client prefetch depth.
  """
  server_rank: Union[int, List[int], None] = None
  num_workers: int = 2
  worker_concurrency: int = 4
  buffer_capacity: int = 64
  buffer_size: Union[int, str] = '64MB'
  prefetch_size: int = 4
  collect_features: bool = True
  worker_key: str = ''
