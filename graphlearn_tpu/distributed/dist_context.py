"""Process identity for the host runtime.

Reference `distributed/dist_context.py:20-183`: every participating
process declares a role (worker / server / client) and a rank within
that role; global ranks interleave servers first then clients
(`dist_context.py:152-166`).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional


class DistRole(enum.Enum):
  WORKER = 1
  SERVER = 2
  CLIENT = 3


@dataclass
class DistContext:
  """Who this process is in the deployment."""
  role: DistRole
  rank: int
  world_size: int
  group_name: str = ''
  num_servers: int = 0
  num_clients: int = 0

  @property
  def is_worker(self) -> bool:
    return self.role == DistRole.WORKER

  @property
  def is_server(self) -> bool:
    return self.role == DistRole.SERVER

  @property
  def is_client(self) -> bool:
    return self.role == DistRole.CLIENT

  @property
  def global_rank(self) -> int:
    """Servers occupy global ranks [0, num_servers); clients follow
    (reference `dist_context.py:152-166`)."""
    if self.role == DistRole.CLIENT:
      return self.num_servers + self.rank
    return self.rank

  @property
  def global_world_size(self) -> int:
    if self.role == DistRole.WORKER:
      return self.world_size
    return self.num_servers + self.num_clients


_context: Optional[DistContext] = None


def init_worker_group(world_size: int, rank: int,
                      group_name: str = 'worker') -> DistContext:
  """Declare this process a collocated worker
  (reference `init_worker_group`, `dist_context.py:169`)."""
  global _context
  _context = DistContext(role=DistRole.WORKER, rank=rank,
                         world_size=world_size, group_name=group_name)
  return _context


def _set_context(ctx: DistContext) -> DistContext:
  global _context
  _context = ctx
  return ctx


def get_context() -> Optional[DistContext]:
  return _context
