"""Sampling server: producer pools serving remote trainer clients.

Reference `distributed/dist_server.py:38-227`: a server process owns
the dataset shard, builds an `MpSamplingProducer` + shm buffer per
client loader, and serves `fetch_one_sampled_message` pulls until the
clients ask it to exit.  The TPU deployment this enables: cheap CPU
hosts do the sampling, TPU VMs only train.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import numpy as np

from ..channel import ShmChannel
from .dist_context import DistContext, DistRole, _set_context
from .dist_options import (MpDistSamplingWorkerOptions,
                           RemoteDistSamplingWorkerOptions)
from .dist_sampling_producer import MpSamplingProducer
from .host_dataset import HostDataset
from .rpc import RpcServer


class DistServer:
  """Per-process server state + RPC handler methods
  (reference `dist_server.py:38-156`)."""

  def __init__(self, dataset: HostDataset):
    self.dataset = dataset
    self._producers: Dict[int, MpSamplingProducer] = {}
    self._channels: Dict[int, ShmChannel] = {}
    self._seeds: Dict[int, np.ndarray] = {}
    self._next_id = 0
    self._exit = threading.Event()
    self._lock = threading.Lock()

  # -- handlers ------------------------------------------------------------
  def get_dataset_meta(self):
    d = self.dataset
    from .host_dataset import HostHeteroDataset
    if isinstance(d, HostHeteroDataset):
      return {
          'hetero': True,
          'num_nodes': dict(d.num_nodes),
          'edge_types': [tuple(et) for et in d.edge_types],
          'feature_dims': {nt: f.shape[1]
                           for nt, f in d.node_features.items()},
          'has_labels': {nt: True for nt in d.node_labels},
      }
    return {
        'hetero': False,
        'num_nodes': d.num_nodes, 'num_edges': d.num_edges,
        'feature_dim': (d.node_features.shape[1]
                        if d.node_features is not None else 0),
        'has_labels': d.node_labels is not None,
    }

  def create_sampling_producer(self, opts: RemoteDistSamplingWorkerOptions,
                               fanouts, batch_size: int, seeds,
                               with_edge: bool = False,
                               shuffle: bool = False, seed: int = 0,
                               sampling_config=None) -> int:
    """Build a producer + buffer for one client loader
    (reference `dist_server.py:83-116`)."""
    channel = ShmChannel(opts.buffer_capacity, opts.buffer_size)
    mp_opts = MpDistSamplingWorkerOptions(
        num_workers=opts.num_workers,
        worker_concurrency=opts.worker_concurrency,
        collect_features=opts.collect_features)
    producer = MpSamplingProducer(
        self.dataset, fanouts, batch_size, channel, mp_opts,
        with_edge=with_edge, shuffle=shuffle, seed=seed,
        sampling_config=sampling_config)
    producer.init()
    seeds = np.asarray(seeds)
    with self._lock:
      pid = self._next_id
      self._next_id += 1
      self._producers[pid] = producer
      self._channels[pid] = channel
      self._seeds[pid] = seeds if seeds.ndim > 1 else seeds.reshape(-1)
    return pid

  def start_new_epoch_sampling(self, producer_id: int,
                               drop_last: bool = False) -> int:
    return self._producers[producer_id].produce_all(
        self._seeds[producer_id], drop_last=drop_last)

  def fetch_one_sampled_message(self, producer_id: int):
    """Blocking pull of one message (reference
    `fetch_one_sampled_message`, `dist_server.py:121-131`).  Returns
    the wire bytes untouched — they cross the socket as a tensor-map
    frame without a parse/re-serialize round trip (a producer's
    '#SPAN' context tensor rides through to the client intact)."""
    from ..telemetry.spans import span
    from .rpc import RawTensorMap
    with span('server.fetch', producer=producer_id):
      return RawTensorMap(self._channels[producer_id].recv_bytes())

  def destroy_sampling_producer(self, producer_id: int) -> None:
    with self._lock:
      producer = self._producers.pop(producer_id, None)
      channel = self._channels.pop(producer_id, None)
      self._seeds.pop(producer_id, None)
    if producer is not None:
      producer.shutdown()
    if channel is not None:
      channel.close()

  def exit(self) -> bool:
    self._exit.set()
    return True

  # -- lifecycle -----------------------------------------------------------
  def wait_for_exit(self, timeout: Optional[float] = None) -> bool:
    """Poll until a client requested exit (reference
    `wait_and_shutdown_server` poll loop, `dist_server.py:64-74`).
    Producers are destroyed either way — a timeout means the clients
    died, and leaking sampling subprocesses + SysV segments is worse
    than cutting them off."""
    done = self._exit.wait(timeout)
    for pid in list(self._producers):
      self.destroy_sampling_producer(pid)
    return done


_server: Optional[DistServer] = None
_rpc_server: Optional[RpcServer] = None


def init_server(num_servers: int, num_clients: int, rank: int,
                dataset: HostDataset, host: str = '0.0.0.0',
                port: int = 0) -> DistServer:
  """Stand up this process as sampling server ``rank``
  (reference `init_server`, `dist_server.py:158-190`).  Returns after
  binding; call `wait_for_exit` to serve until shutdown.  The bound
  port is at ``get_server().port`` (0 = auto-pick, for tests)."""
  global _server, _rpc_server
  _set_context(DistContext(role=DistRole.SERVER, rank=rank,
                           world_size=num_servers, group_name='server',
                           num_servers=num_servers,
                           num_clients=num_clients))
  srv = DistServer(dataset)
  rpc = RpcServer(host, port)
  for name in ('get_dataset_meta', 'create_sampling_producer',
               'start_new_epoch_sampling', 'fetch_one_sampled_message',
               'destroy_sampling_producer', 'exit'):
    rpc.register(name, getattr(srv, name))
  if getattr(dataset, 'node_pb', None) is not None and \
      not isinstance(getattr(dataset, 'node_pb'), dict):
    # shard-backed server: also serve this partition to peer samplers
    # (one-hop / node-data / out-edge handlers on the SAME port), so a
    # `HostSamplingConfig(peer_addrs=[every server's (host, port)])`
    # lets producers fan each hop out across the server fleet
    from .host_dist_sampler import PartitionService
    PartitionService(dataset, server=rpc)
  rpc.start()
  srv.port = rpc.port
  _server, _rpc_server = srv, rpc
  return srv


def get_server() -> Optional[DistServer]:
  return _server


def wait_and_shutdown_server(timeout: Optional[float] = None) -> None:
  global _server, _rpc_server
  if _server is not None:
    _server.wait_for_exit(timeout)
  if _rpc_server is not None:
    _rpc_server.shutdown()
  _server = _rpc_server = None
