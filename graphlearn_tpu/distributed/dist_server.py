"""Sampling server: producer pools serving remote trainer clients.

Reference `distributed/dist_server.py:38-227`: a server process owns
the dataset shard, builds an `MpSamplingProducer` + shm buffer per
client loader, and serves `fetch_one_sampled_message` pulls until the
clients ask it to exit.  The TPU deployment this enables: cheap CPU
hosts do the sampling, TPU VMs only train.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import numpy as np

from ..channel import ShmChannel
from .dist_context import DistContext, DistRole, _set_context
from .dist_options import (MpDistSamplingWorkerOptions,
                           RemoteDistSamplingWorkerOptions)
from .dist_sampling_producer import MpSamplingProducer
from .host_dataset import HostDataset
from .rpc import RpcServer


class DistServer:
  """Per-process server state + RPC handler methods
  (reference `dist_server.py:38-156`)."""

  def __init__(self, dataset: HostDataset):
    self.dataset = dataset
    self._producers: Dict[int, MpSamplingProducer] = {}
    self._channels: Dict[int, ShmChannel] = {}
    self._seeds: Dict[int, np.ndarray] = {}
    self._next_id = 0
    self._exit = threading.Event()
    self._lock = threading.Lock()
    self.rank = 0                   # set by init_server
    self.num_clients = 1            # set by init_server
    self._left_clients: set = set()
    self._serving = None            # ServingFrontend (attach_serving)

  # -- handlers ------------------------------------------------------------
  def get_dataset_meta(self):
    d = self.dataset
    from .host_dataset import HostHeteroDataset
    if isinstance(d, HostHeteroDataset):
      return {
          'hetero': True,
          'num_nodes': dict(d.num_nodes),
          'edge_types': [tuple(et) for et in d.edge_types],
          'feature_dims': {nt: f.shape[1]
                           for nt, f in d.node_features.items()},
          'has_labels': {nt: True for nt in d.node_labels},
      }
    return {
        'hetero': False,
        'num_nodes': d.num_nodes, 'num_edges': d.num_edges,
        'feature_dim': (d.node_features.shape[1]
                        if d.node_features is not None else 0),
        'has_labels': d.node_labels is not None,
    }

  def create_sampling_producer(self, opts: RemoteDistSamplingWorkerOptions,
                               fanouts, batch_size: int, seeds,
                               with_edge: bool = False,
                               shuffle: bool = False, seed: int = 0,
                               sampling_config=None) -> int:
    """Build a producer + buffer for one client loader
    (reference `dist_server.py:83-116`)."""
    channel = ShmChannel(opts.buffer_capacity, opts.buffer_size)
    mp_opts = MpDistSamplingWorkerOptions(
        num_workers=opts.num_workers,
        worker_concurrency=opts.worker_concurrency,
        collect_features=opts.collect_features)
    producer = MpSamplingProducer(
        self.dataset, fanouts, batch_size, channel, mp_opts,
        with_edge=with_edge, shuffle=shuffle, seed=seed,
        sampling_config=sampling_config)
    producer.init()
    seeds = np.asarray(seeds)
    with self._lock:
      pid = self._next_id
      self._next_id += 1
      self._producers[pid] = producer
      self._channels[pid] = channel
      self._seeds[pid] = seeds if seeds.ndim > 1 else seeds.reshape(-1)
    return pid

  def start_new_epoch_sampling(self, producer_id: int,
                               drop_last: bool = False,
                               epoch=None) -> int:
    # ``epoch`` fast-forwards a freshly ADOPTED producer (ISSUE 15) to
    # the loader's current epoch so its permutation stream and
    # (epoch, seq) batch seeds line up byte-identically with what the
    # dead server's producer would have produced
    return self._producers[producer_id].produce_all(
        self._seeds[producer_id], drop_last=drop_last,
        epoch=None if epoch is None else int(epoch))

  def fetch_one_sampled_message(self, producer_id: int):
    """Pull of one message (reference `fetch_one_sampled_message`,
    `dist_server.py:121-131`).  Returns the wire bytes untouched —
    they cross the socket as a tensor-map frame without a
    parse/re-serialize round trip (a producer's '#SPAN' context tensor
    rides through to the client intact).

    Liveness-guarded: the buffer pull is a timed poll interleaved with
    producer supervision, so a crashed sampling worker is restarted
    (its unacked batches replayed; the client's '#SEQ' dedup absorbs
    any double delivery) and an irrecoverable pool surfaces to the
    client as a `PeerLostError`-tagged RPC error instead of a request
    that never returns."""
    from ..telemetry.spans import span
    from .resilience import PeerLostError, fetch_deadline
    from .rpc import RawTensorMap, RpcError
    with span('server.fetch', producer=producer_id):
      channel = self._channels[producer_id]
      producer = self._producers[producer_id]
      timed = getattr(channel, 'recv_bytes_timeout', None)
      if timed is None:
        return RawTensorMap(channel.recv_bytes())
      patience = fetch_deadline()
      deadline = time.monotonic() + patience
      while True:
        data = timed(2.0)
        if data is not None:
          return RawTensorMap(data)
        # acks live client-side; supervise with unknown acks replays
        # the dead worker's FULL assignment (consumer dedup keeps the
        # epoch exact)
        # an irrecoverable pool already wrote its 'peer.lost'
        # post-mortem inside supervise() (with the worker/exitcode/
        # outstanding context) — no second dump here, the one-shot
        # per-reason dedup would discard it anyway
        _, lost = producer.supervise(None)
        if lost:
          raise PeerLostError(
              f'producer {producer_id}: worker restart budget '
              f'exhausted with {len(lost)} batch(es) unrecoverable '
              f'(exit codes {producer.dead_worker_exitcodes()})',
              peer=f'server-{self.rank}/producer-{producer_id}',
              outstanding=len(lost))
        if time.monotonic() > deadline:
          # alive-but-silent past the (generous) fetch deadline: an
          # ambiguous stall, NOT a proven peer loss — raise the plain
          # RPC error so degraded-mode clients don't amputate a
          # server whose pool may merely be stuck (PeerLostError is
          # reserved for the exhausted-budget arm above)
          raise RpcError(
              f'producer {producer_id}: no message within '
              f'{patience:.0f}s fetch deadline '
              f'({producer.alive_workers()} worker(s) alive — '
              'stalled or extremely slow pool)')

  def destroy_sampling_producer(self, producer_id: int) -> None:
    with self._lock:
      producer = self._producers.pop(producer_id, None)
      channel = self._channels.pop(producer_id, None)
      self._seeds.pop(producer_id, None)
    if producer is not None:
      producer.shutdown()
    if channel is not None:
      channel.close()

  # -- serving plane (ISSUE 9) ---------------------------------------------
  def attach_serving(self, frontend) -> None:
    """Attach a `serving.ServingFrontend`: `serve_infer` starts
    answering, and `heartbeat` grows the serving block (queue depth,
    in-flight batch, per-bucket compile status) — the overloaded-vs-
    dead discriminator for serving clients."""
    self._serving = frontend

  def serve_infer(self, seeds, deadline_ms=None, trace=None):
    """One online inference request (RPC handler).  Exactly-once:
    this handler runs under the replay cache like every RPC, so a
    retried request replays the cached reply instead of re-executing
    (and the engine's per-seed determinism makes even a hypothetical
    re-execution byte-identical).  `AdmissionRejected` travels back
    typed via the wire's structured error-kind field —
    `DistClient.serve` resurfaces it as the same class.  ``trace``
    is the caller's request-trace context: this handler's span
    (``serving.rpc``) is the cross-process edge under the router's
    root, and the frontend's per-request spans parent under it."""
    from ..telemetry.tracing import _new_id, child_ctx, tracer
    from ..testing import chaos
    chaos.serving_request_check('serve_infer')
    serving = self._serving
    if serving is None:
      from .rpc import RpcError
      raise RpcError(f'server {self.rank} has no serving tier '
                     'attached (attach_serving was never called)')
    # pre-mint the rpc span id so the frontend's child spans (queue
    # wait / dispatch slice) parent under a span recorded only after
    # the future resolves (spans are recorded on completion)
    rpc_sid = _new_id() if trace else None
    t0 = time.monotonic()
    try:
      fut = serving.submit(np.asarray(seeds), deadline_ms,
                           trace=child_ctx(trace, rpc_sid))
      # wait on the REQUEST's deadline (+ execution grace), not the
      # tier default: a caller that paid for a long deadline must not
      # be timed out at the default by its own server (the in-process
      # `ServingFrontend.infer` uses the same arithmetic)
      dl = (float(deadline_ms) if deadline_ms is not None
            else serving.admission.default_deadline_ms)
      res = fut.result(dl / 1e3 + 30.0)
    except Exception as e:          # noqa: BLE001 — record, re-raise
      dur = time.monotonic() - t0
      if trace:
        tracer.span('serving.rpc', trace, span_id=rpc_sid, t0=t0,
                    dur=dur, rank=self.rank,
                    error=f'{type(e).__name__}: {e}'[:160])
        tracer.resolve(trace, outcome='error', latency_ms=dur * 1e3)
      raise
    dur = time.monotonic() - t0
    if trace:
      tracer.span('serving.rpc', trace, span_id=rpc_sid, t0=t0,
                  dur=dur, rank=self.rank)
      tracer.resolve(trace, outcome='ok', latency_ms=dur * 1e3)
    out = {'nodes': np.asarray(res.nodes)}
    if res.x is not None:
      out['x'] = np.asarray(res.x)
    if res.logits is not None:
      out['logits'] = np.asarray(res.logits)
    return out

  def serving_swap(self, params, version=None):
    """Drain-free hot model swap RPC (ISSUE 13): validates the
    candidate against `offline_reference` parity before admitting
    traffic to it, rolls back on mismatch.  `SwapParityError` /
    `SwapValidationError` travel back typed via the wire's structured
    error-kind field (`DistClient.swap_model` resurfaces them as the
    same classes); runs under the replay cache like every RPC, so a
    retried swap replays its cached verdict instead of swapping
    twice."""
    serving = self._serving
    if serving is None:
      from .rpc import RpcError
      raise RpcError(f'server {self.rank} has no serving tier '
                     'attached (attach_serving was never called)')
    return serving.swap_model(params, version=version)

  def heartbeat(self) -> dict:
    """Liveness + health snapshot (the slow-peer / dead-peer
    discriminator `DistClient.heartbeat` keys off): which producers
    exist and how many of their workers are alive; with a serving
    tier attached, also its queue depth / in-flight batch count /
    per-bucket compile status, so a serving client can tell an
    OVERLOADED peer (deep queue, warm buckets) from a dead or
    still-compiling one."""
    with self._lock:
      producers = {pid: {'alive_workers': p.alive_workers(),
                         'dead_exitcodes': p.dead_worker_exitcodes(),
                         'restarts': p._restarts}
                   for pid, p in self._producers.items()}
    out = {'rank': self.rank, 'time': time.time(),
           'producers': producers}
    if self._serving is not None:
      out['serving'] = self._serving.stats()
    return out

  def health(self) -> dict:
    """The `/healthz` server component: a superset of `heartbeat` —
    per-producer supervision state with a per-producer ``healthy``
    verdict (any dead or irrecoverable worker flips the process
    unhealthy until supervision replaces it).  The serving tier
    reports through its OWN `/healthz` component, so this block
    stays about the sampling plane."""
    with self._lock:
      producers = {pid: p.health()
                   for pid, p in self._producers.items()}
    return {'rank': self.rank,
            'healthy': all(p['healthy'] for p in producers.values()),
            'producers': producers,
            'clients_left': sorted(self._left_clients),
            'serving_attached': self._serving is not None}

  def notify_leave(self, client_rank: int) -> bool:
    """Record an orderly client departure — `wait_for_exit`'s timeout
    diagnostics name the clients that never called this."""
    self._left_clients.add(int(client_rank))
    return True

  def exit(self, client_rank: Optional[int] = None) -> bool:
    if client_rank is not None:
      self._left_clients.add(int(client_rank))
    self._exit.set()
    return True

  # -- lifecycle -----------------------------------------------------------
  def wait_for_exit(self, timeout: Optional[float] = None) -> bool:
    """Poll until a client requested exit (reference
    `wait_and_shutdown_server` poll loop, `dist_server.py:64-74`).
    Producers are destroyed either way — a timeout means the clients
    died, and leaking sampling subprocesses + SysV segments is worse
    than cutting them off.  A timeout is LOGGED through the flight
    recorder with the clients that never said goodbye, instead of
    returning silently (the operator's first question is "which
    trainer hung?")."""
    done = self._exit.wait(timeout)
    if not done:
      from ..telemetry.recorder import recorder
      missing = sorted(set(range(self.num_clients))
                       - self._left_clients)
      recorder.emit('server.shutdown_timeout', rank=self.rank,
                    timeout_secs=timeout,
                    clients_never_exited=missing,
                    clients_left=sorted(self._left_clients),
                    live_producers=len(self._producers))
    for pid in list(self._producers):
      self.destroy_sampling_producer(pid)
    if self._serving is not None:
      # queued serving requests resolve with typed shutdown
      # rejections (never silently lost), then the executor stops
      self._serving.shutdown()
      self._serving = None
    return done


_server: Optional[DistServer] = None
_rpc_server: Optional[RpcServer] = None


def init_server(num_servers: int, num_clients: int, rank: int,
                dataset: HostDataset, host: str = '0.0.0.0',
                port: int = 0) -> DistServer:
  """Stand up this process as sampling server ``rank``
  (reference `init_server`, `dist_server.py:158-190`).  Returns after
  binding; call `wait_for_exit` to serve until shutdown.  The bound
  port is at ``get_server().port`` (0 = auto-pick, for tests)."""
  global _server, _rpc_server
  _set_context(DistContext(role=DistRole.SERVER, rank=rank,
                           world_size=num_servers, group_name='server',
                           num_servers=num_servers,
                           num_clients=num_clients))
  srv = DistServer(dataset)
  srv.rank = rank
  srv.num_clients = num_clients
  rpc = RpcServer(host, port)
  for name in ('get_dataset_meta', 'create_sampling_producer',
               'start_new_epoch_sampling', 'fetch_one_sampled_message',
               'destroy_sampling_producer', 'exit', 'heartbeat',
               'notify_leave', 'serve_infer', 'serving_swap'):
    rpc.register(name, getattr(srv, name))
  if getattr(dataset, 'node_pb', None) is not None and \
      not isinstance(getattr(dataset, 'node_pb'), dict):
    # shard-backed server: also serve this partition to peer samplers
    # (one-hop / node-data / out-edge handlers on the SAME port), so a
    # `HostSamplingConfig(peer_addrs=[every server's (host, port)])`
    # lets producers fan each hop out across the server fleet
    from .host_dist_sampler import PartitionService
    PartitionService(dataset, server=rpc)
  rpc.start()
  srv.port = rpc.port
  # live ops plane: one scrapeable endpoint per server process
  # (GLT_OPS_PORT, 0/unset = disabled) + this server's supervision
  # state on /healthz; no-ops entirely at the default
  from ..telemetry import opsserver
  from ..telemetry.live import live
  opsserver.maybe_start_from_env()
  srv._health_fn = srv.health       # pinned: unregister is fn-guarded
  live.register_health('server', srv._health_fn)
  _server, _rpc_server = srv, rpc
  return srv


def get_server() -> Optional[DistServer]:
  return _server


def wait_and_shutdown_server(timeout: Optional[float] = None) -> None:
  global _server, _rpc_server
  if _server is not None:
    _server.wait_for_exit(timeout)
    from ..telemetry.live import live
    live.unregister_health('server',
                           fn=getattr(_server, '_health_fn', None))
  if _rpc_server is not None:
    _rpc_server.shutdown()
  _server = _rpc_server = None
