"""Sampling producers: subprocess pool + collocated twin.

Reference `distributed/dist_sampling_producer.py:52-328`:
``DistMpSamplingProducer`` spawns N sampling workers which consume
SAMPLE_ALL commands from a task queue, iterate their seed slice, and
push messages into the shm channel; ``DistCollocatedSamplingProducer``
does the same synchronously in-process.  The workers are numpy/native-
only (no device).

Start method: ``forkserver`` by default — workers descend from a clean
server process with no JAX threads (fork-after-JAX can inherit held
runtime locks and deadlock, the CPython DeprecationWarning), and the
dataset crosses the boundary through POSIX shared memory
(`shm_arrays.share_dataset`: one copy at init, zero per worker).
``fork`` remains opt-in via ``MpDistSamplingWorkerOptions.
mp_start_method`` for callers whose parent process is known
single-threaded at spawn time (the copy-on-write zero-copy path);
safety invariant documented there.
"""
from __future__ import annotations

import enum
import multiprocessing as mp
import queue as queue_mod
from typing import List, Optional, Sequence

import numpy as np

from ..channel.base import ChannelBase
from .dist_options import MpDistSamplingWorkerOptions
from .host_dataset import HostDataset, HostHeteroDataset
from .host_sampler import HostHeteroNeighborSampler, HostNeighborSampler


class MpCommand(enum.Enum):
  SAMPLE_ALL = 0
  STOP = 1


def _make_sampler(dataset, fanouts, with_edge, collect_features, seed,
                  peer_addrs=None):
  """Homo/hetero host sampler by dataset kind; a SHARD dataset +
  ``peer_addrs`` builds the cross-server `HostDistNeighborSampler`
  (each worker owns its peer sockets — `RpcClient` connects lazily
  per thread, so construction after fork/forkserver is safe)."""
  if (getattr(dataset, 'node_pb', None) is not None
      and peer_addrs is not None):
    if isinstance(dataset, HostHeteroDataset):
      raise ValueError(
          'cross-server hetero sampling is not implemented in the host '
          'runtime; use the mesh engine '
          '(graphlearn_tpu.parallel.DistHeteroNeighborSampler)')
    from .host_dist_sampler import (HostDistNeighborSampler,
                                    connect_peers)
    return HostDistNeighborSampler(
        dataset, fanouts,
        connect_peers(list(peer_addrs), dataset.partition_idx),
        with_edge=with_edge, collect_features=collect_features,
        seed=seed)
  cls = (HostHeteroNeighborSampler
         if isinstance(dataset, HostHeteroDataset) else HostNeighborSampler)
  return cls(dataset, fanouts, with_edge=with_edge,
             collect_features=collect_features, seed=seed)


def _dispatch_sample(sampler, cfg, seeds_slice, batch_seed: int):
  """NODE/LINK/SUBGRAPH dispatch (reference `SamplingType` switch in
  `_sampling_worker_loop`, `dist_sampling_producer.py:110-135`)."""
  hetero = isinstance(sampler, HostHeteroNeighborSampler)
  if hetero and (cfg is None or cfg.input_type is None):
    raise ValueError(
        'hetero sampling needs a HostSamplingConfig with input_type '
        '(the seed node type, or the seed edge type in link mode)')
  if cfg is None or cfg.sampling_type == 'node':
    if hetero:
      return sampler.sample_from_nodes(cfg.input_type, seeds_slice,
                                       batch_seed=batch_seed)
    return sampler.sample_from_nodes(seeds_slice, batch_seed=batch_seed)
  if cfg.sampling_type == 'link':
    label = seeds_slice[:, 2] if seeds_slice.shape[1] > 2 else None
    if hetero:
      return sampler.sample_from_edges(
          cfg.input_type, seeds_slice[:, 0], seeds_slice[:, 1],
          label=label, neg_mode=cfg.neg_mode, neg_amount=cfg.neg_amount,
          batch_seed=batch_seed)
    return sampler.sample_from_edges(
        seeds_slice[:, 0], seeds_slice[:, 1], label=label,
        neg_mode=cfg.neg_mode, neg_amount=cfg.neg_amount,
        batch_seed=batch_seed)
  if cfg.sampling_type == 'subgraph':
    if hetero:
      # the reference's SubGraphOp is homogeneous-only
      # (`include/subgraph_op_base.h`); same boundary here
      raise ValueError('subgraph sampling is homogeneous-only')
    return sampler.sample_subgraph(seeds_slice, batch_seed=batch_seed)
  raise ValueError(f'unknown sampling_type {cfg.sampling_type!r}')


def _sampling_worker_loop(rank, dataset, fanouts, with_edge,
                          collect_features, channel, task_queue, seed,
                          sampling_config=None):
  """Body of one sampling subprocess (reference `_sampling_worker_loop`,
  `dist_sampling_producer.py:52-144`)."""
  from .shm_arrays import SharedDatasetHandle
  segs = None
  if isinstance(dataset, SharedDatasetHandle):
    # non-fork start: attach zero-copy shm views; hold the segments
    # for the process lifetime
    dataset, segs = dataset.materialize()  # noqa: F841 — keepalive
  sampler = _make_sampler(dataset, fanouts, with_edge, collect_features,
                          seed * 7919 + rank,
                          peer_addrs=getattr(sampling_config,
                                             'peer_addrs', None))
  while True:
    try:
      cmd, payload = task_queue.get(timeout=5.0)
    except queue_mod.Empty:
      continue
    if cmd == MpCommand.STOP:
      break
    seeds, batch_size, epoch = payload
    from ..telemetry.spans import span
    for lo in range(0, len(seeds), batch_size):
      # the producer-side span covers sample + send; the channel
      # injects its context into the message at send time, so the
      # consumer's collate span can link back to THIS trace (the
      # worker's recorder comes up via GLT_TELEMETRY_JSONL, which
      # spawn/forkserver children inherit)
      with span('producer.sample', worker=rank, epoch=epoch,
                offset=lo):
        msg = _dispatch_sample(
            sampler, sampling_config, seeds[lo:lo + batch_size],
            batch_seed=(epoch * 1000003 + rank) * 131071 + lo)
        # Epoch stamp lets consumers discard stale messages after an
        # early-terminated epoch (`DistLoader._recv_current_epoch`).
        msg['#EPOCH'] = np.int64(epoch)
        channel.send(msg)


class MpSamplingProducer:
  """N sampling subprocesses feeding ``channel``.

  Reference ``DistMpSamplingProducer`` (`dist_sampling_producer.py:
  147-260`): per-epoch ``produce_all`` splits the shuffled seed set
  into per-worker, batch-aligned ranges.
  """

  def __init__(self, dataset: HostDataset, num_neighbors: Sequence[int],
               batch_size: int, channel: ChannelBase,
               options: Optional[MpDistSamplingWorkerOptions] = None,
               with_edge: bool = False, shuffle: bool = False,
               seed: int = 0, sampling_config=None):
    self.opts = options or MpDistSamplingWorkerOptions()
    self.ds = dataset
    # keep dict-valued (per-edge-type) fanouts intact
    self.fanouts = (dict(num_neighbors) if isinstance(num_neighbors, dict)
                    else list(num_neighbors))
    self.batch_size = int(batch_size)
    self.channel = channel
    self.with_edge = with_edge
    self.shuffle = shuffle
    self.sampling_config = sampling_config
    self._rng = np.random.default_rng(seed)
    self._seed = seed
    self._epoch = 0
    self._ctx = mp.get_context(self.opts.mp_start_method)
    self._task_queues: List = []
    self._workers: List = []
    self.current_epoch = -1      # stamp of the last dispatched epoch

  def init(self) -> None:
    ds_arg = self.ds
    self._shm_segs = None
    if self._ctx.get_start_method() != 'fork':
      # stage the dataset into POSIX shm once; workers attach
      # zero-copy instead of unpickling a full copy each
      from .shm_arrays import share_dataset
      ds_arg, self._shm_segs = share_dataset(self.ds)
    for r in range(self.opts.num_workers):
      tq = self._ctx.Queue()
      w = self._ctx.Process(
          target=_sampling_worker_loop,
          args=(r, ds_arg, self.fanouts, self.with_edge,
                self.opts.collect_features, self.channel, tq, self._seed,
                self.sampling_config),
          daemon=True)
      w.start()
      self._task_queues.append(tq)
      self._workers.append(w)

  def num_batches(self, num_seeds: int) -> int:
    return (num_seeds + self.batch_size - 1) // self.batch_size

  def produce_all(self, seeds: np.ndarray, drop_last: bool = False) -> int:
    """Dispatch one epoch; returns the number of messages to expect.
    ``drop_last`` truncates *after* the shuffle, so the dropped
    remainder differs per epoch (torch DataLoader semantics).
    ``seeds`` is ``[E]`` node ids, or ``[E, 2|3]`` edge pairs
    (+labels) in link mode — shuffling/slicing is along axis 0."""
    seeds = np.asarray(seeds)
    if seeds.ndim == 1:
      seeds = seeds.reshape(-1)
    if self.shuffle:
      seeds = self._rng.permutation(seeds)
    if drop_last:
      seeds = seeds[:(len(seeds) // self.batch_size) * self.batch_size]
    nw = max(len(self._workers), 1)
    # batch-aligned contiguous slices (reference `:249-260`)
    n_batches = self.num_batches(len(seeds))
    per_worker = ((n_batches + nw - 1) // nw) * self.batch_size
    for r, tq in enumerate(self._task_queues):
      sl = seeds[r * per_worker:(r + 1) * per_worker]
      if len(sl):
        tq.put((MpCommand.SAMPLE_ALL, (sl, self.batch_size, self._epoch)))
    self.current_epoch = self._epoch
    self._epoch += 1
    return n_batches

  def alive_workers(self) -> int:
    """Liveness probe (the reference's 5s MP_STATUS_CHECK_INTERVAL
    watchdog, `dist_sampling_producer.py:39-41`): consumers use this
    to fail loudly instead of blocking forever on a channel no one
    will ever fill."""
    return sum(1 for w in self._workers if w.is_alive())

  def dead_worker_exitcodes(self):
    return [w.exitcode for w in self._workers if not w.is_alive()]

  def shutdown(self) -> None:
    for tq in self._task_queues:
      try:
        tq.put((MpCommand.STOP, None))
      except Exception:
        pass
    for w in self._workers:
      w.join(timeout=5.0)
      if w.is_alive():
        w.terminate()
    self._workers = []
    self._task_queues = []
    if getattr(self, '_shm_segs', None):
      from .shm_arrays import release
      release(self._shm_segs)
      self._shm_segs = None


class CollocatedSamplingProducer:
  """Synchronous in-process producer (reference
  ``DistCollocatedSamplingProducer``, `dist_sampling_producer.py:
  263-328`) — same message contract, no subprocesses, no channel."""

  def __init__(self, dataset: HostDataset, num_neighbors: Sequence[int],
               batch_size: int, with_edge: bool = False,
               collect_features: bool = True, shuffle: bool = False,
               seed: int = 0, sampling_config=None):
    self.sampler = _make_sampler(dataset, num_neighbors, with_edge,
                                 collect_features, seed,
                                 peer_addrs=getattr(sampling_config,
                                                    'peer_addrs', None))
    self.batch_size = int(batch_size)
    self.shuffle = shuffle
    self.sampling_config = sampling_config
    self._rng = np.random.default_rng(seed)

  def epoch(self, seeds: np.ndarray, drop_last: bool = False):
    seeds = np.asarray(seeds)
    if seeds.ndim == 1:
      seeds = seeds.reshape(-1)
    if self.shuffle:
      seeds = self._rng.permutation(seeds)
    if drop_last:
      seeds = seeds[:(len(seeds) // self.batch_size) * self.batch_size]
    for lo in range(0, len(seeds), self.batch_size):
      yield _dispatch_sample(self.sampler, self.sampling_config,
                             seeds[lo:lo + self.batch_size],
                             batch_seed=None)
