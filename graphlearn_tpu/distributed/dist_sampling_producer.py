"""Sampling producers: subprocess pool + collocated twin.

Reference `distributed/dist_sampling_producer.py:52-328`:
``DistMpSamplingProducer`` spawns N sampling workers which consume
SAMPLE_ALL commands from a task queue, iterate their seed slice, and
push messages into the shm channel; ``DistCollocatedSamplingProducer``
does the same synchronously in-process.  The workers are numpy/native-
only (no device).

Start method: ``forkserver`` by default — workers descend from a clean
server process with no JAX threads (fork-after-JAX can inherit held
runtime locks and deadlock, the CPython DeprecationWarning), and the
dataset crosses the boundary through POSIX shared memory
(`shm_arrays.share_dataset`: one copy at init, zero per worker).
``fork`` remains opt-in via ``MpDistSamplingWorkerOptions.
mp_start_method`` for callers whose parent process is known
single-threaded at spawn time (the copy-on-write zero-copy path);
safety invariant documented there.
"""
from __future__ import annotations

import enum
import multiprocessing as mp
import queue as queue_mod
import threading
from typing import List, Optional, Sequence

import numpy as np

from ..channel.base import ChannelBase
from .dist_options import MpDistSamplingWorkerOptions
from .host_dataset import HostDataset, HostHeteroDataset
from .host_sampler import HostHeteroNeighborSampler, HostNeighborSampler


class MpCommand(enum.Enum):
  SAMPLE_ALL = 0
  STOP = 1


def _make_sampler(dataset, fanouts, with_edge, collect_features, seed,
                  peer_addrs=None):
  """Homo/hetero host sampler by dataset kind; a SHARD dataset +
  ``peer_addrs`` builds the cross-server `HostDistNeighborSampler`
  (each worker owns its peer sockets — `RpcClient` connects lazily
  per thread, so construction after fork/forkserver is safe)."""
  if (getattr(dataset, 'node_pb', None) is not None
      and peer_addrs is not None):
    if isinstance(dataset, HostHeteroDataset):
      raise ValueError(
          'cross-server hetero sampling is not implemented in the host '
          'runtime; use the mesh engine '
          '(graphlearn_tpu.parallel.DistHeteroNeighborSampler)')
    from .host_dist_sampler import (HostDistNeighborSampler,
                                    connect_peers)
    return HostDistNeighborSampler(
        dataset, fanouts,
        connect_peers(list(peer_addrs), dataset.partition_idx),
        with_edge=with_edge, collect_features=collect_features,
        seed=seed)
  cls = (HostHeteroNeighborSampler
         if isinstance(dataset, HostHeteroDataset) else HostNeighborSampler)
  return cls(dataset, fanouts, with_edge=with_edge,
             collect_features=collect_features, seed=seed)


def _dispatch_sample(sampler, cfg, seeds_slice, batch_seed: int):
  """NODE/LINK/SUBGRAPH dispatch (reference `SamplingType` switch in
  `_sampling_worker_loop`, `dist_sampling_producer.py:110-135`)."""
  hetero = isinstance(sampler, HostHeteroNeighborSampler)
  if hetero and (cfg is None or cfg.input_type is None):
    raise ValueError(
        'hetero sampling needs a HostSamplingConfig with input_type '
        '(the seed node type, or the seed edge type in link mode)')
  if cfg is None or cfg.sampling_type == 'node':
    if hetero:
      return sampler.sample_from_nodes(cfg.input_type, seeds_slice,
                                       batch_seed=batch_seed)
    return sampler.sample_from_nodes(seeds_slice, batch_seed=batch_seed)
  if cfg.sampling_type == 'link':
    label = seeds_slice[:, 2] if seeds_slice.shape[1] > 2 else None
    if hetero:
      return sampler.sample_from_edges(
          cfg.input_type, seeds_slice[:, 0], seeds_slice[:, 1],
          label=label, neg_mode=cfg.neg_mode, neg_amount=cfg.neg_amount,
          batch_seed=batch_seed)
    return sampler.sample_from_edges(
        seeds_slice[:, 0], seeds_slice[:, 1], label=label,
        neg_mode=cfg.neg_mode, neg_amount=cfg.neg_amount,
        batch_seed=batch_seed)
  if cfg.sampling_type == 'subgraph':
    if hetero:
      # the reference's SubGraphOp is homogeneous-only
      # (`include/subgraph_op_base.h`); same boundary here
      raise ValueError('subgraph sampling is homogeneous-only')
    return sampler.sample_subgraph(seeds_slice, batch_seed=batch_seed)
  raise ValueError(f'unknown sampling_type {cfg.sampling_type!r}')


def _sampling_worker_loop(rank, dataset, fanouts, with_edge,
                          collect_features, channel, task_queue, seed,
                          sampling_config=None, progress_queue=None,
                          generation=0):
  """Body of one sampling subprocess (reference `_sampling_worker_loop`,
  `dist_sampling_producer.py:52-144`)."""
  from .shm_arrays import SharedDatasetHandle
  segs = None
  if isinstance(dataset, SharedDatasetHandle):
    # non-fork start: attach zero-copy shm views; hold the segments
    # for the process lifetime
    dataset, segs = dataset.materialize()  # noqa: F841 — keepalive
  sampler = _make_sampler(dataset, fanouts, with_edge, collect_features,
                          seed * 7919 + rank,
                          peer_addrs=getattr(sampling_config,
                                             'peer_addrs', None))
  while True:
    try:
      cmd, payload = task_queue.get(timeout=5.0)
    except queue_mod.Empty:
      continue
    if cmd == MpCommand.STOP:
      break
    seeds, batch_size, epoch, seqs = payload
    from ..telemetry.spans import span
    from ..testing import chaos
    for i, lo in enumerate(range(0, len(seeds), batch_size)):
      # fault-plan seam: a planned 'kill' hard-exits here, between
      # batches — the supervisor must restart us and replay what we
      # never delivered (the chaos suite's central scenario).  The
      # progress queue rides `flush` so acks for batches the channel
      # already holds survive the exit (see `worker_kill_check`).
      chaos.worker_kill_check(
          rank, epoch, generation,
          flush=(progress_queue,) if progress_queue is not None
          else ())
      # the producer-side span covers sample + send; the channel
      # injects its context into the message at send time, so the
      # consumer's collate span can link back to THIS trace (the
      # worker's recorder comes up via GLT_TELEMETRY_JSONL, which
      # spawn/forkserver children inherit)
      seq = int(seqs[i])
      with span('producer.sample', worker=rank, epoch=epoch,
                offset=lo):
        # batch content is a function of (epoch, seq) ONLY — a batch
        # replayed after a worker restart (possibly from a different
        # offset) is byte-identical to the original, so consumer-side
        # '#SEQ' dedup keeps epoch content exact under faults
        msg = _dispatch_sample(
            sampler, sampling_config, seeds[lo:lo + batch_size],
            batch_seed=(epoch * 1000003 + seq) * 131071)
        # Epoch stamp lets consumers discard stale messages after an
        # early-terminated epoch (`DistLoader._recv_current_epoch`);
        # the seq stamp is the per-batch identity replay dedup keys on.
        msg['#EPOCH'] = np.int64(epoch)
        msg['#SEQ'] = np.int64(seq)
        channel.send(msg)
      if progress_queue is not None:
        # progress ack AFTER the durable channel send: the channel
        # outlives us, so a sent batch never needs replay — the
        # supervisor replays only what sits between the last ack and
        # the crash (consumer-side '#SEQ' dedup absorbs the overlap)
        try:
          progress_queue.put((epoch, rank, seq))
        except Exception:           # noqa: BLE001 — teardown race
          pass


class MpSamplingProducer:
  """N sampling subprocesses feeding ``channel``.

  Reference ``DistMpSamplingProducer`` (`dist_sampling_producer.py:
  147-260`): per-epoch ``produce_all`` splits the shuffled seed set
  into per-worker, batch-aligned ranges.
  """

  def __init__(self, dataset: HostDataset, num_neighbors: Sequence[int],
               batch_size: int, channel: ChannelBase,
               options: Optional[MpDistSamplingWorkerOptions] = None,
               with_edge: bool = False, shuffle: bool = False,
               seed: int = 0, sampling_config=None):
    self.opts = options or MpDistSamplingWorkerOptions()
    self.ds = dataset
    # keep dict-valued (per-edge-type) fanouts intact
    self.fanouts = (dict(num_neighbors) if isinstance(num_neighbors, dict)
                    else list(num_neighbors))
    self.batch_size = int(batch_size)
    self.channel = channel
    self.with_edge = with_edge
    self.shuffle = shuffle
    self.sampling_config = sampling_config
    self._rng = np.random.default_rng(seed)
    self._seed = seed
    self._epoch = 0
    self._ctx = mp.get_context(self.opts.mp_start_method)
    self._task_queues: List = []
    self._workers: List = []
    self.current_epoch = -1      # stamp of the last dispatched epoch
    # supervision state: per-worker assignment ledger for the CURRENT
    # epoch ({rank: (seed_slice, seq_stamps)}), workers declared
    # irrecoverable, and the restart budget consumed so far
    self._assignments: dict = {}   # guarded-by: self._sup_lock
    self._lost: set = set()        # guarded-by: self._sup_lock
    self._restarts = 0             # guarded-by: self._sup_lock
    # worker progress acks, this epoch  # guarded-by: self._sup_lock
    self._sent_seqs: set = set()
    self._progress = None
    self._generations: dict = {}   # rank -> restart count
    # staged peer-lost bundle context  # guarded-by: self._sup_lock
    self._pending_postmortem: Optional[dict] = None
    # one supervisor at a time: the server runtime calls supervise()
    # from one RPC handler thread per in-flight fetch — without the
    # lock two threads can both restart the same dead worker (orphaned
    # duplicate process, double-billed restart budget)
    self._sup_lock = threading.Lock()

  def _spawn_worker(self, rank: int):
    tq = self._ctx.Queue()
    w = self._ctx.Process(
        target=_sampling_worker_loop,
        args=(rank, self._ds_arg, self.fanouts, self.with_edge,
              self.opts.collect_features, self.channel, tq, self._seed,
              self.sampling_config, self._progress,
              self._generations.get(rank, 0)),
        daemon=True)
    w.start()
    return tq, w

  def init(self) -> None:
    ds_arg = self.ds
    self._shm_segs = None
    if self._ctx.get_start_method() != 'fork':
      # stage the dataset into POSIX shm once; workers attach
      # zero-copy instead of unpickling a full copy each
      from .shm_arrays import share_dataset
      ds_arg, self._shm_segs = share_dataset(self.ds)
    self._ds_arg = ds_arg          # kept: restarts respawn from it
    self._progress = self._ctx.Queue()
    for r in range(self.opts.num_workers):
      tq, w = self._spawn_worker(r)
      self._task_queues.append(tq)
      self._workers.append(w)

  def num_batches(self, num_seeds: int) -> int:
    return (num_seeds + self.batch_size - 1) // self.batch_size

  def fast_forward(self, seeds: np.ndarray, epoch: int) -> None:
    """Advance this producer's epoch counter AND its shuffle RNG to
    ``epoch`` by drawing (and discarding) the skipped permutations —
    the partition-adoption path (ISSUE 15): a producer recreated on a
    survivor mid-run must produce epoch ``e`` byte-identical to what
    the dead server's producer would have (batch content is a
    function of (epoch, seq) + the epoch's permutation, and the
    permutation is the ``epoch``-th draw from the seeded stream)."""
    seeds = np.asarray(seeds)
    while self._epoch < int(epoch):
      if self.shuffle:
        self._rng.permutation(seeds)     # axis-0, node AND link mode
      self._epoch += 1

  def produce_all(self, seeds: np.ndarray, drop_last: bool = False,
                  epoch: Optional[int] = None) -> int:
    """Dispatch one epoch; returns the number of messages to expect.
    ``drop_last`` truncates *after* the shuffle, so the dropped
    remainder differs per epoch (torch DataLoader semantics).
    ``seeds`` is ``[E]`` node ids, or ``[E, 2|3]`` edge pairs
    (+labels) in link mode — shuffling/slicing is along axis 0.
    ``epoch`` fast-forwards a freshly created producer to that epoch
    before producing (`fast_forward` — the adoption path)."""
    from ..utils.checkpoint import pack_rng_state
    seeds = np.asarray(seeds)
    if seeds.ndim == 1:
      seeds = seeds.reshape(-1)
    if epoch is not None:
      self.fast_forward(seeds, epoch)
    # pre-shuffle RNG capture: a mid-epoch snapshot restores THIS
    # state so the resumed produce_all re-draws the same permutation
    # (batch content is a function of (epoch, seq) — identical shuffle
    # + identical stamps = byte-identical replays)
    self._pre_epoch_rng = pack_rng_state(self._rng)
    if self.shuffle:
      seeds = self._rng.permutation(seeds)
    if drop_last:
      seeds = seeds[:(len(seeds) // self.batch_size) * self.batch_size]
    with self._sup_lock:
      return self._produce_all_locked(seeds)

  def _produce_all_locked(self, seeds: np.ndarray) -> int:
    # under _sup_lock: the server runtime can run supervise() from a
    # fetch handler thread concurrently with a start-epoch RPC — an
    # unlocked respawn here would race it (duplicate replacement
    # workers, a replayed task enqueued on a queue this method is
    # about to replace)
    # an epoch boundary is a recovery point: workers that died late in
    # the previous epoch respawn BEFORE this epoch's assignments go
    # out (their queues would otherwise hold work no one ever does),
    # and the restart budget + lost set reset — the budget bounds
    # crash-looping within one epoch, not uptime across a long run
    self._restarts = 0
    self._lost.clear()
    for r, w in enumerate(self._workers):
      if not w.is_alive():
        from ..telemetry.recorder import recorder
        self._generations[r] = self._generations.get(r, 0) + 1
        tq, proc = self._spawn_worker(r)
        self._task_queues[r] = tq
        self._workers[r] = proc
        recorder.emit('producer.restart', worker=r, exitcode=w.exitcode,
                      replayed=0, restarts=self._restarts,
                      budget=None, at='epoch_boundary')
        from ..utils.profiling import metrics
        metrics.inc('producer.restarts_total')
    nw = max(len(self._workers), 1)
    # batch-aligned contiguous slices (reference `:249-260`)
    n_batches = self.num_batches(len(seeds))
    per_worker = ((n_batches + nw - 1) // nw) * self.batch_size
    batches_per_worker = per_worker // self.batch_size
    self._assignments = {}
    for r, tq in enumerate(self._task_queues):
      sl = seeds[r * per_worker:(r + 1) * per_worker]
      if len(sl):
        # '#SEQ' stamps: the global batch index of each batch in this
        # slice — the identity supervision replays and consumers
        # dedup on (unique within the epoch by construction)
        seqs = [r * batches_per_worker + i
                for i in range(self.num_batches(len(sl)))]
        self._assignments[r] = (sl, seqs)
        tq.put((MpCommand.SAMPLE_ALL,
                (sl, self.batch_size, self._epoch, seqs)))
    self.current_epoch = self._epoch
    self._epoch += 1
    self._sent_seqs = set()
    self._drain_progress()          # discard stale prior-epoch acks
    return n_batches

  def _drain_progress(self) -> None:
    """Fold worker progress acks for the CURRENT epoch into
    ``_sent_seqs`` (acks are ``(epoch, rank, seq)`` put after each
    durable channel send)."""
    # called from _produce_all_locked/_supervise_locked only
    # glint: holds=self._sup_lock
    if self._progress is None:
      return
    while True:
      try:
        ep, _, s = self._progress.get_nowait()
      except queue_mod.Empty:
        return
      except (OSError, ValueError):
        return                      # queue tearing down
      if ep == self.current_epoch:
        self._sent_seqs.add(s)

  def alive_workers(self) -> int:
    """Liveness probe (the reference's 5s MP_STATUS_CHECK_INTERVAL
    watchdog, `dist_sampling_producer.py:39-41`): consumers use this
    to fail loudly instead of blocking forever on a channel no one
    will ever fill."""
    return sum(1 for w in self._workers if w.is_alive())

  def dead_worker_exitcodes(self):
    return [w.exitcode for w in self._workers if not w.is_alive()]

  def health(self) -> dict:
    """Supervision state for `/healthz`: ``healthy`` means every
    spawned worker is currently alive and none is declared
    irrecoverable — a dead-but-restartable worker reads unhealthy
    until `supervise` replaces it (exactly the during-the-incident
    signal a liveness prober wants)."""
    alive = self.alive_workers()
    with self._sup_lock:
      lost, restarts = sorted(self._lost), self._restarts
    return {'healthy': alive == len(self._workers) and not lost,
            'alive_workers': alive,
            'num_workers': len(self._workers),
            'dead_exitcodes': self.dead_worker_exitcodes(),
            'lost_workers': lost,
            'restarts': restarts}

  def _unacked(self, rank: int, acked_seqs=None):
    """The (seed_slice, seqs) of ``rank``'s current-epoch batches with
    no delivery evidence: neither in the worker's own progress acks
    (``_sent_seqs`` — sent to the channel, which outlives the worker)
    nor in the consumer's optional ``acked_seqs``.  Replay of an
    already-sent batch would be harmless (consumer '#SEQ' dedup) but
    wasteful — and under a deterministic kill fault it would re-fire
    the fault forever."""
    # called from _supervise_locked only  # glint: holds=self._sup_lock
    sl, seqs = self._assignments.get(rank, (None, []))
    if sl is None:
      return None, []
    done = set(self._sent_seqs)
    if acked_seqs is not None:
      done |= set(acked_seqs)
    bs = self.batch_size
    keep = [i for i, s in enumerate(seqs) if s not in done]
    if not keep:
      return None, []
    parts = [sl[i * bs:(i + 1) * bs] for i in keep]
    return np.concatenate(parts, axis=0), [seqs[i] for i in keep]

  def supervise(self, acked_seqs=None):
    """Detect dead workers, restart them, and replay their unacked
    current-epoch batches (same '#SEQ' stamps + (epoch, seq)-derived
    batch seeds, so replays are byte-identical to what was lost).

    Returns ``(restarted, lost_seqs)``: workers restarted this call,
    and the outstanding seq stamps owned by workers past the restart
    budget (``GLT_MAX_WORKER_RESTARTS``) — permanently lost batches
    the caller must either subtract from the epoch (degraded mode) or
    raise `PeerLostError` over."""
    from ..telemetry.recorder import recorder
    from .resilience import max_worker_restarts
    with self._sup_lock:
      out = self._supervise_locked(acked_seqs, recorder,
                                   max_worker_restarts())
      pending = self._pending_postmortem
      self._pending_postmortem = None
    if pending is not None:
      # OUTSIDE the supervision lock: the bundle's health snapshot
      # calls back into `health()`, which takes `_sup_lock` (the
      # lock is not reentrant — dumping under it deadlocks)
      from ..telemetry import postmortem
      postmortem.dump('peer.lost', extra=pending)
    return out

  def _supervise_locked(self, acked_seqs, recorder, budget):
    self._drain_progress()
    restarted = 0
    lost_seqs: list = []
    for r, w in enumerate(self._workers):
      if w.is_alive():
        continue
      sl, seqs = self._unacked(r, acked_seqs)
      if r in self._lost or self._restarts >= budget:
        if r not in self._lost:
          self._lost.add(r)
          recorder.emit('peer.lost', peer=f'worker-{r}', peer_kind='worker',
                        exitcode=w.exitcode,
                        outstanding=len(seqs),
                        restarts=self._restarts, budget=budget)
          # black box: an irrecoverable worker pool is fatal — stage
          # a post-mortem for `supervise` to write AFTER releasing
          # `_sup_lock` (the bundle's health snapshot re-enters
          # `health()`, which needs the lock)
          self._pending_postmortem = {
              'peer': f'worker-{r}', 'exitcode': w.exitcode,
              'outstanding': len(seqs),
              'restarts': self._restarts, 'budget': budget}
        lost_seqs.extend(seqs)
        continue
      exitcode = w.exitcode
      self._restarts += 1
      self._generations[r] = self._generations.get(r, 0) + 1
      tq, proc = self._spawn_worker(r)
      self._task_queues[r] = tq
      self._workers[r] = proc
      if sl is not None and self.current_epoch >= 0:
        tq.put((MpCommand.SAMPLE_ALL,
                (sl, self.batch_size, self.current_epoch, seqs)))
        self._assignments[r] = (sl, seqs)
      recorder.emit('producer.restart', worker=r, exitcode=exitcode,
                    replayed=len(seqs), restarts=self._restarts,
                    budget=budget)
      from ..utils.profiling import metrics
      metrics.inc('producer.restarts_total')
      restarted += 1
    return restarted, lost_seqs

  # -- DataPlaneState (utils.checkpoint) ------------------------------------
  def state_dict(self) -> dict:
    """Producer positions: epoch counter, shuffle RNG (current AND the
    pre-shuffle state of the in-flight epoch), per-worker restart
    generations.  Worker processes are NOT captured — they are
    respawned fresh and replay deterministically from (epoch, seq)."""
    from ..utils.checkpoint import pack_bytes, pack_rng_state
    return {
        'epoch': self._epoch,
        'current_epoch': self.current_epoch,
        'rng': pack_rng_state(self._rng),
        'pre_epoch_rng': getattr(self, '_pre_epoch_rng',
                                 pack_rng_state(self._rng)),
        'generations': pack_bytes(dict(self._generations)),
    }

  def load_state_dict(self, state: dict, mid_epoch: bool = True) -> None:
    """``mid_epoch=True`` rewinds so the NEXT `produce_all` re-
    dispatches the interrupted epoch (same epoch number, same
    shuffle); False resumes at the epoch boundary."""
    from ..utils.checkpoint import restore_rng_state, unpack_bytes
    cur = int(np.asarray(state['current_epoch']))
    if mid_epoch:
      self._epoch = cur if cur >= 0 else 0
      restore_rng_state(self._rng, state['pre_epoch_rng'])
    else:
      self._epoch = int(np.asarray(state['epoch']))
      restore_rng_state(self._rng, state['rng'])
    self._generations = dict(unpack_bytes(state['generations']))

  def shutdown(self) -> None:
    for tq in self._task_queues:
      try:
        tq.put((MpCommand.STOP, None))
      except Exception:
        pass
    for w in self._workers:
      w.join(timeout=5.0)
      if w.is_alive():
        w.terminate()
    self._workers = []
    self._task_queues = []
    if getattr(self, '_shm_segs', None):
      from .shm_arrays import release
      release(self._shm_segs)
      self._shm_segs = None


class CollocatedSamplingProducer:
  """Synchronous in-process producer (reference
  ``DistCollocatedSamplingProducer``, `dist_sampling_producer.py:
  263-328`) — same message contract, no subprocesses, no channel."""

  def __init__(self, dataset: HostDataset, num_neighbors: Sequence[int],
               batch_size: int, with_edge: bool = False,
               collect_features: bool = True, shuffle: bool = False,
               seed: int = 0, sampling_config=None):
    self.sampler = _make_sampler(dataset, num_neighbors, with_edge,
                                 collect_features, seed,
                                 peer_addrs=getattr(sampling_config,
                                                    'peer_addrs', None))
    self.batch_size = int(batch_size)
    self.shuffle = shuffle
    self.sampling_config = sampling_config
    self._rng = np.random.default_rng(seed)

  def epoch(self, seeds: np.ndarray, drop_last: bool = False):
    seeds = np.asarray(seeds)
    if seeds.ndim == 1:
      seeds = seeds.reshape(-1)
    if self.shuffle:
      seeds = self._rng.permutation(seeds)
    if drop_last:
      seeds = seeds[:(len(seeds) // self.batch_size) * self.batch_size]
    for lo in range(0, len(seeds), self.batch_size):
      yield _dispatch_sample(self.sampler, self.sampling_config,
                             seeds[lo:lo + self.batch_size],
                             batch_seed=None)
