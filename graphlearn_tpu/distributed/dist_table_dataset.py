"""Table-fed distributed partitioning.

Counterpart of reference `distributed/dist_table_dataset.py:38-360`
(``DistTableRandomPartitioner`` / ``DistTableDataset``): each rank
streams ITS slice of the input tables (ODPS there; any `TableReader`
here — csv/npz/ODPS share the record formats) and the cluster runs the
cooperative partitioning pipeline of `DistRandomPartitioner`, writing
the standard on-disk layout.

Usage (every rank)::

    p = DistTableRandomPartitioner(
        out_dir, num_nodes,
        edge_table=f'edges_rank{r}.csv',      # this rank's edge slice
        node_table=f'nodes_rank{r}.csv',      # this rank's node range
        edge_id_offset=my_first_global_edge_id,
        rank=r, world_size=W, master_addr=..., master_port=...)
    p.partition()
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..data.table_dataset import TableLike, read_edge_table, read_node_table
from .dist_random_partitioner import DistRandomPartitioner, node_range


class DistTableRandomPartitioner(DistRandomPartitioner):
  """`DistRandomPartitioner` whose inputs stream from tables.

  Args:
    edge_table: this rank's edge records (``src, dst``).
    node_table: this rank's node records (``id, "f0:f1:..."``) — ids
      must cover exactly this rank's node range
      ``node_range(rank, world_size, num_nodes)``.
    label_table: optional ``(id, label)`` records for the same range.
    (remaining args as `DistRandomPartitioner`)
  """

  def __init__(self, output_dir, num_nodes: int,
               edge_table: TableLike,
               node_table: Optional[TableLike] = None,
               label_table: Optional[TableLike] = None,
               reader_batch_size: int = 65536, **kwargs):
    rows, cols = read_edge_table(edge_table, reader_batch_size)
    rank = kwargs.get('rank')
    world_size = kwargs.get('world_size')
    lo, hi = node_range(rank, world_size, num_nodes)
    node_feat = None
    if node_table is not None:
      # records arrive keyed by GLOBAL id within [lo, hi); rebase
      node_feat = _read_ranged_node_table(node_table, lo, hi,
                                          reader_batch_size)
    node_label = None
    if label_table is not None:
      from ..data.table_dataset import _as_reader
      ids, labs = [], []
      for batch in _as_reader(label_table).batches(reader_batch_size):
        ids.extend(int(r[0]) for r in batch)
        labs.extend(int(r[1]) for r in batch)
      idx = np.asarray(ids, np.int64)
      if len(idx) and (idx.min() < lo or idx.max() >= hi):
        raise ValueError(
            f'label table ids must lie in this rank\'s range '
            f'[{lo}, {hi}); got [{idx.min()}, {idx.max()}]')
      node_label = np.zeros(hi - lo, np.int64)
      node_label[idx - lo] = labs
    super().__init__(output_dir, num_nodes, (rows, cols),
                     node_feat, node_label, **kwargs)


def _read_ranged_node_table(table: TableLike, lo: int, hi: int,
                            batch_size: int) -> np.ndarray:
  """Node records with global ids in ``[lo, hi)`` -> ``[hi-lo, D]``."""
  from ..data.table_dataset import _as_reader, _decode_feat
  ids, feats = [], []
  for batch in _as_reader(table).batches(batch_size):
    ids.extend(int(r[0]) for r in batch)
    feats.extend(_decode_feat(r[1]) for r in batch)
  arr = np.asarray(feats, dtype=np.float32)
  idx = np.asarray(ids, dtype=np.int64)
  uniq = np.unique(idx)
  if (len(idx) != hi - lo or len(uniq) != hi - lo
      or (len(uniq) and (uniq[0] != lo or uniq[-1] != hi - 1))):
    lohi = (f'[{idx.min()}, {idx.max()}]' if len(idx) else '[]')
    raise ValueError(
        f'node table must cover ids [{lo}, {hi}) exactly once; got '
        f'{len(idx)} records ({len(uniq)} unique) in {lohi}')
  out = np.empty((hi - lo,) + arr.shape[1:], arr.dtype)
  out[idx - lo] = arr
  return out
