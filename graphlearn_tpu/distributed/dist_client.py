"""Trainer-side client of the sampling servers.

Reference `distributed/dist_client.py:24-98`: `init_client` joins the
deployment, loaders call `create_sampling_producer` on their target
server, and `shutdown_client` has client-0 tell every server to exit.
"""
from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .dist_context import DistContext, DistRole, _set_context, get_context
from .dist_options import RemoteDistSamplingWorkerOptions
from .rpc import RpcClient, RpcError


class RemoteProducerHandle:
  """One loader's producer living on a server."""

  def __init__(self, client: 'DistClient', server_idx: int,
               producer_id: int):
    self._client = client
    self._server_idx = server_idx
    self._pid = producer_id

  def start_new_epoch(self, drop_last: bool = False,
                      epoch: Optional[int] = None) -> int:
    kw = {} if epoch is None else {'epoch': int(epoch)}
    return self._client.request_server(
        self._server_idx, 'start_new_epoch_sampling', self._pid,
        drop_last=drop_last, **kw)

  def fetch(self, src=None):
    # ``src`` is the replacement-fetch routing hint (see
    # `MultiProducerHandle.fetch`); with one server there is only one
    # place to fetch from, so it is accepted and ignored
    from ..telemetry.spans import span
    with span('client.fetch', server=self._server_idx):
      return self._client.request_server(
          self._server_idx, 'fetch_one_sampled_message', self._pid)

  def destroy(self) -> None:
    # best-effort cleanup: ONE short attempt, no retry ladder — a
    # teardown against an already-dead server must not block for the
    # full retry deadline (the server reaps producers on exit anyway)
    try:
      self._client._rpcs[self._server_idx].request_once(
          'destroy_sampling_producer', self._pid, timeout=5.0)
    except Exception:
      pass


class MultiProducerHandle:
  """One loader fanned out over several servers (list-valued
  ``server_rank``, reference `dist_options.py:202-258`): each server
  samples a batch-aligned seed slice; fetches round-robin by each
  server's per-epoch message count.

  Elastic failover (ISSUE 15): ``creation_args`` (recorded by
  `DistClient.create_sampling_producer`) lets `adopt_server`
  recreate a dead server's producer — its exact seed slice and seed
  offset — on a SURVIVOR, fast-forwarded to the loader's current
  epoch, under the SAME handle index (= '#SRC' tag), so the channel's
  (source, seq) replay dedup + source-routed replacement fetches
  absorb the re-produced prefix and the epoch finishes with every
  expected batch, byte-identical."""

  def __init__(self, handles: List[RemoteProducerHandle],
               creation_args: Optional[List[tuple]] = None):
    self._handles = handles
    self._lock = threading.Lock()
    self._plan: List[int] = []      # handle idx per outstanding message
    self._pos = 0
    #: per-handle (opts, fanouts, batch_size, seeds, with_edge,
    #: shuffle, seed, sampling_config) — guarded-by: self._lock
    self._creation_args = creation_args or []
    self._epochs_started = 0        # guarded-by: self._lock
    self._last_drop_last = False    # guarded-by: self._lock
    self._adopted: dict = {}        # dead server_idx -> survivor idx

  @property
  def server_indices(self) -> List[int]:
    return [h._server_idx for h in self._handles]

  def adopt_server(self, client: 'DistClient', server_idx: int,
                   survivor_idx: Optional[int] = None) -> dict:
    """Recreate the dead server's producers on a survivor (exact
    completion instead of `drop_server`'s write-off).  Idempotent per
    dead server: repeat losses (several in-flight fetches failing in
    turn) only append the one replacement fetch each fetch consumed.
    Returns ``{'survivor', 'owed', 'recreated'}``; raises
    `AdoptionRefusedError` when no creation args were recorded or no
    survivor remains."""
    from ..parallel.partition_book import AdoptionRefusedError
    with self._lock:
      already = self._adopted.get(server_idx)
      if already is not None:
        self._plan.append(already[1])   # the failed fetch's refetch
        return {'survivor': already[0], 'owed': 1, 'recreated': 0}
      if not self._creation_args:
        raise AdoptionRefusedError(
            'this producer plan recorded no creation args — '
            'adoption unavailable (single-producer plans have no '
            'survivor to recreate on)')
      dead = [i for i, h in enumerate(self._handles)
              if h._server_idx == server_idx]
      if not dead:
        raise AdoptionRefusedError(
            f'server {server_idx} owns no handle of this plan')
      live = sorted({h._server_idx for i, h in enumerate(self._handles)
                     if i not in dead}
                    - {s for s, _ in self._adopted.values()}
                    - {server_idx})
      if survivor_idx is None:
        if not live:
          raise AdoptionRefusedError(
              f'no surviving server to adopt server {server_idx}\'s '
              'producers (one adoption per survivor)')
        survivor_idx = live[0]
      epoch = self._epochs_started - 1
      drop_last = self._last_drop_last
      owed = sum(1 for i in self._plan[self._pos:] if i in dead)
      dead_args = [(j, self._creation_args[j]) for j in dead]
    # RPCs outside the lock: producer creation + the fast-forwarded
    # epoch start can take seconds on a big slice
    recreated = 0
    for j, args in dead_args:
      new_h = client._create_one(survivor_idx, *args)
      new_h.start_new_epoch(drop_last, epoch=max(epoch, 0))
      with self._lock:
        self._handles[j] = new_h
      recreated += 1
    with self._lock:
      self._adopted[server_idx] = (survivor_idx, dead[0])
      # the fetch that surfaced the loss consumed a plan entry whose
      # message is still owed — put one back, routed at the adopted
      # handle (the re-produced prefix drains via replay discards +
      # source-routed replacements)
      self._plan.append(dead[0])
    return {'survivor': survivor_idx, 'owed': owed + 1,
            'recreated': recreated}

  def start_new_epoch(self, drop_last: bool = False) -> int:
    counts = [h.start_new_epoch(drop_last) for h in self._handles]
    with self._lock:
      self._epochs_started += 1
      self._last_drop_last = bool(drop_last)
      # interleave: h0, h1, ..., h0, h1, ... while counts last
      plan = []
      remaining = list(counts)
      while any(remaining):
        for i, r in enumerate(remaining):
          if r > 0:
            plan.append(i)
            remaining[i] -= 1
      self._plan = plan
      self._pos = 0
    return sum(counts)

  def fetch(self, src=None):
    """One planned fetch, or — ``src`` given — a replacement fetch
    routed to that handle.  A replacement replaces a message the
    consumer discarded as a worker-restart replay duplicate: the real
    undelivered message sits in THAT server's buffer, so round-robin
    would send the extra fetch to a server that owes nothing (blocking
    there until its fetch deadline and failing a healthy epoch)."""
    if src is not None:
      msg = self._handles[src].fetch()
      if isinstance(msg, dict):
        msg['#SRC'] = np.int64(src)
      return msg
    with self._lock:
      if self._pos >= len(self._plan):
        raise RpcError('no planned fetches remain (accounting bug, or '
                       'every server owing messages is gone)')
      idx = self._plan[self._pos]
      self._pos += 1
    msg = self._handles[idx].fetch()
    if isinstance(msg, dict):
      # source tag: each server's producer numbers its '#SEQ' stamps
      # from 0, so the consumer's replay dedup must key on
      # (source, seq) — without this, server B's batch 0 reads as a
      # replay of server A's batch 0 and gets discarded
      msg['#SRC'] = np.int64(idx)
    return msg

  def drop_server(self, server_idx: int) -> int:
    """Degraded mode: a server is lost for good — remove its remaining
    planned fetches so survivors finish the epoch.  Returns how many
    planned (not-yet-started) fetches it still owed; in-flight fetches
    that fail surface separately, one `PeerLostError` each."""
    with self._lock:
      dead = [i for i, h in enumerate(self._handles)
              if h._server_idx == server_idx]
      remaining = self._plan[self._pos:]
      kept = [i for i in remaining if i not in dead]
      self._plan = kept
      self._pos = 0
      return len(remaining) - len(kept)

  def destroy(self) -> None:
    for h in self._handles:
      h.destroy()


class DistClient:
  """Connections to every sampling server."""

  def __init__(self, server_addrs: Sequence[Tuple[str, int]], rank: int,
               num_clients: int):
    self.rank = rank
    self._rpcs: List[RpcClient] = [RpcClient(h, p) for h, p in server_addrs]
    self.num_servers = len(self._rpcs)
    self.num_clients = num_clients

  def request_server(self, server_idx: int, name: str, *args, **kwargs):
    """RPC to one server, classified on failure: a retry-exhausted
    request probes the peer — still answering its ping means SLOW
    (`RetryExhausted` propagates, caller may widen its deadline), not
    answering means DEAD (`PeerLostError`, emitted as a ``peer.lost``
    event).  A server-side `PeerLostError` (its producer pool died)
    re-raises typed on this side too."""
    from ..telemetry.recorder import recorder
    from .resilience import PeerLostError, RetryExhausted
    try:
      return self._rpcs[server_idx].request(name, *args, **kwargs)
    except PeerLostError:
      raise
    except RetryExhausted as e:
      if self._rpcs[server_idx].probe():
        raise                      # slow peer: alive but over budget
      addr = self._rpcs[server_idx].addr
      recorder.emit('peer.lost', peer=server_idx, peer_kind='server',
                    addr=f'{addr[0]}:{addr[1]}', op=name,
                    degraded=False, error=str(e))
      raise PeerLostError(
          f'server {server_idx} at {addr} is gone: {name!r} '
          f'exhausted retries and the liveness probe failed',
          peer=server_idx) from e
    except RpcError as e:
      if getattr(e, 'remote_kind', None) == 'PeerLostError':
        # the server executed but ITS producer pool is irrecoverable
        # (typed via the wire's structured error-kind field — never
        # sniffed out of the message text)
        raise PeerLostError(f'server {server_idx}: {e}',
                            peer=server_idx) from e
      raise

  def serve(self, seeds, server_idx: Optional[int] = None,
            deadline_ms: Optional[float] = None,
            trace: Optional[dict] = None) -> dict:
    """One online inference request against a server's serving tier
    (ISSUE 9): ``seeds`` (a few node ids) -> ``{'nodes': [k, W], 'x':
    [k, W, D] | 'logits': [k, C]}`` numpy arrays, byte-identical to
    the per-seed offline reference whatever the request was coalesced
    with.  Rides the full PR 4 resilience ladder via
    `request_server`: transport faults retry under the same request
    id (the server's replay cache keeps the retry exactly-once), a
    dead peer surfaces as `PeerLostError` — and a server-side
    admission refusal resurfaces TYPED as
    `serving.admission.AdmissionRejected` (wire error-kind field,
    never message-text sniffing), so callers can tell overload (back
    off / reroute) from failure.  Default server = ``rank %
    num_servers``, the producer round-robin convention.  ``trace``
    (a `telemetry.tracing` context dict) rides the RPC frame so the
    server's per-request spans join the caller's trace tree."""
    from ..serving.admission import AdmissionRejected
    if server_idx is None:
      server_idx = self.rank % self.num_servers
    seeds = np.asarray(seeds, np.int64).reshape(-1)
    try:
      return self.request_server(server_idx, 'serve_infer', seeds,
                                 deadline_ms=deadline_ms, trace=trace)
    except RpcError as e:
      if getattr(e, 'remote_kind', None) == 'AdmissionRejected':
        # rebuild the typed rejection FAITHFULLY from the wire's
        # structured extra field (reason / retry_after_ms / queue
        # diagnostics) — a fleet router keys its reroute-vs-raise
        # decision off `reason`, and a draining replica's retry-after
        # hint must survive the hop
        extra = getattr(e, 'remote_extra', None) or {}
        raise AdmissionRejected(
            f'server {server_idx} shed the request: {e}',
            reason=extra.get('reason', ''),
            queue_depth=extra.get('queue_depth'),
            limit=extra.get('limit'),
            waited_ms=extra.get('waited_ms'),
            retry_after_ms=extra.get('retry_after_ms')) from e
      raise

  def swap_model(self, params, server_idx: Optional[int] = None,
                 version: Optional[int] = None) -> dict:
    """Hot model swap on one server's serving tier (ISSUE 13):
    ships the candidate params, the server quiesces between coalesced
    runs, parity-checks against its offline reference, and commits or
    rolls back.  Typed `SwapParityError` / `SwapValidationError`
    resurface here as the same classes (wire error-kind field)."""
    from ..serving.swap import (SwapAbortedError, SwapParityError,
                                SwapValidationError)
    if server_idx is None:
      server_idx = self.rank % self.num_servers
    try:
      return self.request_server(server_idx, 'serving_swap', params,
                                 version=version)
    except RpcError as e:
      kind = getattr(e, 'remote_kind', None)
      if kind == 'SwapParityError':
        extra = getattr(e, 'remote_extra', None) or {}
        raise SwapParityError(f'server {server_idx}: {e}',
                              max_err=extra.get('max_err')) from e
      if kind == 'SwapValidationError':
        raise SwapValidationError(f'server {server_idx}: {e}') from e
      if kind == 'SwapAbortedError':
        raise SwapAbortedError(f'server {server_idx}: {e}') from e
      raise

  def heartbeat(self, server_idx: int, timeout: float = 2.0):
    """One-shot health snapshot from a server (fresh connection, no
    retries); ``None`` when the peer is unreachable."""
    try:
      return self._rpcs[server_idx].request_once('heartbeat',
                                                 timeout=timeout)
    except Exception:              # noqa: BLE001 — unreachable = None
      return None

  def get_dataset_meta(self, server_idx: int = 0):
    return self.request_server(server_idx, 'get_dataset_meta')

  def _create_one(self, idx: int, opts, fanouts, batch_size, seeds,
                  with_edge, shuffle, seed,
                  sampling_config=None) -> RemoteProducerHandle:
    # dict-valued (per-edge-type) fanouts must survive the RPC intact;
    # tuple keys pickle fine
    fanouts = (dict(fanouts) if isinstance(fanouts, dict)
               else list(fanouts))
    pid = self.request_server(
        idx, 'create_sampling_producer', opts, fanouts,
        int(batch_size), np.asarray(seeds), with_edge=with_edge,
        shuffle=shuffle, seed=seed, sampling_config=sampling_config)
    return RemoteProducerHandle(self, idx, pid)

  def create_sampling_producer(
      self, opts: RemoteDistSamplingWorkerOptions, fanouts,
      batch_size: int, seeds: np.ndarray, with_edge: bool = False,
      shuffle: bool = False, seed: int = 0, sampling_config=None):
    idx = opts.server_rank
    if idx is None:
      idx = self.rank % self.num_servers   # round-robin default
    if isinstance(idx, (list, tuple)):
      if len(idx) == 1:
        idx = idx[0]
      else:
        # fan out: split seeds batch-aligned across the listed servers
        # (axis 0: rows are edge pairs in link mode)
        seeds = np.asarray(seeds)
        n_batches = (len(seeds) + batch_size - 1) // batch_size
        per = ((n_batches + len(idx) - 1) // len(idx)) * batch_size
        handles, creation_args = [], []
        for j, sidx in enumerate(idx):
          sl = seeds[j * per:(j + 1) * per]
          if len(sl):
            args = (opts, fanouts, batch_size, sl, with_edge,
                    shuffle, seed + j, sampling_config)
            handles.append(self._create_one(sidx, *args))
            # recorded per handle: `adopt_server` recreates the exact
            # slice + seed offset on a survivor (ISSUE 15)
            creation_args.append(args)
        return MultiProducerHandle(handles, creation_args)
    return self._create_one(idx, opts, fanouts, batch_size, seeds,
                            with_edge, shuffle, seed, sampling_config)

  def shutdown(self, notify_servers: bool = True) -> None:
    """Every client says goodbye (`notify_leave` — the server's
    shutdown-timeout diagnostics name whoever didn't); client-0 then
    asks every server to exit (reference `shutdown_client`,
    `dist_client.py:54-76`)."""
    if notify_servers:
      for i in range(self.num_servers):
        try:
          self._rpcs[i].request_once('notify_leave', self.rank,
                                     timeout=2.0)
        except Exception:
          pass
        if self.rank == 0:
          # one short attempt: telling an already-dead server to exit
          # must not ride the retry ladder
          try:
            self._rpcs[i].request_once('exit', client_rank=self.rank,
                                       timeout=5.0)
          except Exception:
            pass
    for c in self._rpcs:
      c.close()


_client: Optional[DistClient] = None


def init_client(server_addrs: Sequence[Tuple[str, int]], rank: int = 0,
                num_clients: int = 1) -> DistClient:
  """Declare this process trainer client ``rank``
  (reference `init_client`, `dist_client.py:24-51`)."""
  global _client
  _set_context(DistContext(
      role=DistRole.CLIENT, rank=rank, world_size=num_clients,
      group_name='client', num_servers=len(server_addrs),
      num_clients=num_clients))
  _client = DistClient(server_addrs, rank, num_clients)
  return _client


def get_client() -> Optional[DistClient]:
  return _client


def shutdown_client(notify_servers: bool = True) -> None:
  global _client
  if _client is not None:
    _client.shutdown(notify_servers)
  _client = None
