"""Trainer-side client of the sampling servers.

Reference `distributed/dist_client.py:24-98`: `init_client` joins the
deployment, loaders call `create_sampling_producer` on their target
server, and `shutdown_client` has client-0 tell every server to exit.
"""
from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .dist_context import DistContext, DistRole, _set_context, get_context
from .dist_options import RemoteDistSamplingWorkerOptions
from .rpc import RpcClient


class RemoteProducerHandle:
  """One loader's producer living on a server."""

  def __init__(self, client: 'DistClient', server_idx: int,
               producer_id: int):
    self._client = client
    self._server_idx = server_idx
    self._pid = producer_id

  def start_new_epoch(self, drop_last: bool = False) -> int:
    return self._client.request_server(
        self._server_idx, 'start_new_epoch_sampling', self._pid,
        drop_last=drop_last)

  def fetch(self):
    from ..telemetry.spans import span
    with span('client.fetch', server=self._server_idx):
      return self._client.request_server(
          self._server_idx, 'fetch_one_sampled_message', self._pid)

  def destroy(self) -> None:
    try:
      self._client.request_server(
          self._server_idx, 'destroy_sampling_producer', self._pid)
    except Exception:
      pass


class MultiProducerHandle:
  """One loader fanned out over several servers (list-valued
  ``server_rank``, reference `dist_options.py:202-258`): each server
  samples a batch-aligned seed slice; fetches round-robin by each
  server's per-epoch message count."""

  def __init__(self, handles: List[RemoteProducerHandle]):
    self._handles = handles
    self._lock = threading.Lock()
    self._plan: List[int] = []      # handle idx per outstanding message
    self._pos = 0

  def start_new_epoch(self, drop_last: bool = False) -> int:
    counts = [h.start_new_epoch(drop_last) for h in self._handles]
    with self._lock:
      # interleave: h0, h1, ..., h0, h1, ... while counts last
      plan = []
      remaining = list(counts)
      while any(remaining):
        for i, r in enumerate(remaining):
          if r > 0:
            plan.append(i)
            remaining[i] -= 1
      self._plan = plan
      self._pos = 0
    return sum(counts)

  def fetch(self):
    with self._lock:
      idx = self._plan[self._pos % max(len(self._plan), 1)]
      self._pos += 1
    return self._handles[idx].fetch()

  def destroy(self) -> None:
    for h in self._handles:
      h.destroy()


class DistClient:
  """Connections to every sampling server."""

  def __init__(self, server_addrs: Sequence[Tuple[str, int]], rank: int,
               num_clients: int):
    self.rank = rank
    self._rpcs: List[RpcClient] = [RpcClient(h, p) for h, p in server_addrs]
    self.num_servers = len(self._rpcs)
    self.num_clients = num_clients

  def request_server(self, server_idx: int, name: str, *args, **kwargs):
    return self._rpcs[server_idx].request(name, *args, **kwargs)

  def get_dataset_meta(self, server_idx: int = 0):
    return self.request_server(server_idx, 'get_dataset_meta')

  def _create_one(self, idx: int, opts, fanouts, batch_size, seeds,
                  with_edge, shuffle, seed,
                  sampling_config=None) -> RemoteProducerHandle:
    # dict-valued (per-edge-type) fanouts must survive the RPC intact;
    # tuple keys pickle fine
    fanouts = (dict(fanouts) if isinstance(fanouts, dict)
               else list(fanouts))
    pid = self.request_server(
        idx, 'create_sampling_producer', opts, fanouts,
        int(batch_size), np.asarray(seeds), with_edge=with_edge,
        shuffle=shuffle, seed=seed, sampling_config=sampling_config)
    return RemoteProducerHandle(self, idx, pid)

  def create_sampling_producer(
      self, opts: RemoteDistSamplingWorkerOptions, fanouts,
      batch_size: int, seeds: np.ndarray, with_edge: bool = False,
      shuffle: bool = False, seed: int = 0, sampling_config=None):
    idx = opts.server_rank
    if idx is None:
      idx = self.rank % self.num_servers   # round-robin default
    if isinstance(idx, (list, tuple)):
      if len(idx) == 1:
        idx = idx[0]
      else:
        # fan out: split seeds batch-aligned across the listed servers
        # (axis 0: rows are edge pairs in link mode)
        seeds = np.asarray(seeds)
        n_batches = (len(seeds) + batch_size - 1) // batch_size
        per = ((n_batches + len(idx) - 1) // len(idx)) * batch_size
        handles = []
        for j, sidx in enumerate(idx):
          sl = seeds[j * per:(j + 1) * per]
          if len(sl):
            handles.append(self._create_one(
                sidx, opts, fanouts, batch_size, sl, with_edge,
                shuffle, seed + j, sampling_config))
        return MultiProducerHandle(handles)
    return self._create_one(idx, opts, fanouts, batch_size, seeds,
                            with_edge, shuffle, seed, sampling_config)

  def shutdown(self, notify_servers: bool = True) -> None:
    """Client-0 asks every server to exit
    (reference `shutdown_client`, `dist_client.py:54-76`)."""
    if notify_servers and self.rank == 0:
      for i in range(self.num_servers):
        try:
          self.request_server(i, 'exit')
        except Exception:
          pass
    for c in self._rpcs:
      c.close()


_client: Optional[DistClient] = None


def init_client(server_addrs: Sequence[Tuple[str, int]], rank: int = 0,
                num_clients: int = 1) -> DistClient:
  """Declare this process trainer client ``rank``
  (reference `init_client`, `dist_client.py:24-51`)."""
  global _client
  _set_context(DistContext(
      role=DistRole.CLIENT, rank=rank, world_size=num_clients,
      group_name='client', num_servers=len(server_addrs),
      num_clients=num_clients))
  _client = DistClient(server_addrs, rank, num_clients)
  return _client


def get_client() -> Optional[DistClient]:
  return _client


def shutdown_client(notify_servers: bool = True) -> None:
  global _client
  if _client is not None:
    _client.shutdown(notify_servers)
  _client = None
