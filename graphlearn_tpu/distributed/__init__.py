"""Process-based host runtime: producers, channels, server-client.

The SPMD device-mesh engine lives in :mod:`graphlearn_tpu.parallel`
(sampling *on* TPU via shard_map collectives).  This package is the
host-side complement — the reference's `python/distributed/` world
(`dist_context.py`, `dist_options.py`, `dist_sampling_producer.py`,
`dist_loader.py`, `dist_server.py`, `dist_client.py`): sampling
subprocess pools on CPU feeding the TPU trainer through shm channels,
and a server-client mode where dedicated sampling hosts feed remote
trainers over sockets.
"""
from .dist_client import (DistClient, get_client, init_client,
                          shutdown_client)
from .dist_context import (DistContext, DistRole, get_context,
                           init_worker_group)
from .dist_loader import (DistLinkNeighborLoader, DistLoader,
                          DistNeighborLoader, DistSubGraphLoader)
from .dist_options import (CollocatedDistSamplingWorkerOptions,
                           HostSamplingConfig,
                           MpDistSamplingWorkerOptions,
                           RemoteDistSamplingWorkerOptions)
from .dist_random_partitioner import (DistPartitionManager,
                                      DistRandomPartitioner, node_range)
from .dist_table_dataset import DistTableRandomPartitioner
from .dist_sampling_producer import (CollocatedSamplingProducer,
                                     MpSamplingProducer)
from .dist_server import (DistServer, get_server, init_server,
                          wait_and_shutdown_server)
from .resilience import (PeerLostError, RetryExhausted, RetryPolicy,
                         degraded_ok)
from .rpc import RpcError
from .host_dataset import HostDataset, HostHeteroDataset
from .host_dist_sampler import (HostDistNeighborSampler,
                                PartitionService, connect_peers)
from .host_sampler import HostHeteroNeighborSampler, HostNeighborSampler

__all__ = [
    'DistContext', 'DistRole', 'get_context', 'init_worker_group',
    'DistLoader', 'DistNeighborLoader', 'DistLinkNeighborLoader',
    'DistSubGraphLoader', 'HostSamplingConfig',
    'CollocatedDistSamplingWorkerOptions', 'MpDistSamplingWorkerOptions',
    'RemoteDistSamplingWorkerOptions',
    'CollocatedSamplingProducer', 'MpSamplingProducer',
    'DistServer', 'get_server', 'init_server', 'wait_and_shutdown_server',
    'DistClient', 'get_client', 'init_client', 'shutdown_client',
    'HostDataset', 'HostHeteroDataset', 'HostNeighborSampler',
    'HostHeteroNeighborSampler', 'HostDistNeighborSampler',
    'PartitionService', 'connect_peers',
    'DistPartitionManager', 'DistRandomPartitioner', 'node_range',
    'DistTableRandomPartitioner',
    'RetryPolicy', 'RetryExhausted', 'PeerLostError', 'RpcError',
    'degraded_ok',
]
