"""Host-side (numpy) dataset handle for sampling subprocesses.

Producer workers never touch the TPU: they sample on CPU with the
native ops (`csrc/cpu_ops.cc`, `csrc/inducer.cc`) over plain numpy
CSR + feature arrays.  With the default ``fork`` start method children
inherit these arrays copy-on-write — the zero-copy analog of the
reference's ForkingPickler shm reductions (`data/*.py` "Pickling
Registration").
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..typing import as_str


class HostDataset:
  """CSR topology + features/labels as host numpy arrays.

  Attributes:
    indptr / indices / edge_ids: CSR (``edge_ids`` optional).
    node_features: ``[N, D]`` or None.
    node_labels: ``[N]`` or None.
    edge_features: ``[E, De]`` indexed by GLOBAL edge id, or None.
  """

  def __init__(self, indptr, indices, edge_ids=None, node_features=None,
               node_labels=None, edge_features=None):
    self.indptr = np.ascontiguousarray(indptr, np.int64)
    self.indices = np.ascontiguousarray(indices, np.int64)
    self.edge_ids = (np.ascontiguousarray(edge_ids, np.int64)
                     if edge_ids is not None else None)
    self.node_features = (np.asarray(node_features)
                          if node_features is not None else None)
    self.node_labels = (np.asarray(node_labels)
                        if node_labels is not None else None)
    self.edge_features = (np.asarray(edge_features)
                          if edge_features is not None else None)
    #: set by `from_partition_dir`: this dataset is ONE partition's
    #: shard (local edges only over the global node space).  A plain
    #: `HostNeighborSampler` refuses such datasets — remote
    #: neighborhoods would silently come back empty; use
    #: `HostDistNeighborSampler` with peer services instead.
    self.node_pb: Optional[np.ndarray] = None
    self.partition_idx: Optional[int] = None

  @property
  def num_nodes(self) -> int:
    return len(self.indptr) - 1

  @property
  def num_edges(self) -> int:
    return len(self.indices)

  @classmethod
  def from_coo(cls, rows, cols, num_nodes: Optional[int] = None,
               node_features=None, node_labels=None,
               edge_features=None) -> 'HostDataset':
    """``edge_features`` rows follow the INPUT edge order (edge id i =
    i-th COO edge), matching `Dataset.init_edge_features`."""
    from ..native import coo_to_csr
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    n = int(num_nodes if num_nodes is not None
            else max(rows.max(initial=-1), cols.max(initial=-1)) + 1)
    indptr, indices, perm = coo_to_csr(rows, cols, n)
    return cls(indptr, indices, edge_ids=perm, node_features=node_features,
               node_labels=node_labels, edge_features=edge_features)

  @classmethod
  def from_dataset(cls, dataset) -> 'HostDataset':
    """Borrow the host copies inside a `graphlearn_tpu.data.Dataset`."""
    topo = dataset.get_graph().csr_topo
    feats = dataset.get_node_feature()
    labels = dataset.get_node_label()
    efeats = dataset.get_edge_feature()
    return cls(
        topo.indptr, topo.indices, edge_ids=topo.edge_ids,
        node_features=feats.host_get() if feats is not None else None,
        node_labels=np.asarray(labels) if labels is not None else None,
        edge_features=efeats.host_get() if efeats is not None else None)

  @classmethod
  def from_partition_dir(cls, root, partition_idx: int) -> 'HostDataset':
    """Load one partition's shard from the offline layout
    (`graphlearn_tpu.partition.load_partition`)."""
    from ..partition import load_partition
    from ..native import coo_to_csr
    p = load_partition(root, partition_idx)
    rows, cols = p['graph'].edge_index
    n = len(p['node_pb'].table)
    indptr, indices, perm = coo_to_csr(rows, cols, n)
    feats = None
    if p['node_feat'] is not None:
      d = p['node_feat'].feats.shape[1]
      feats = np.zeros((n, d), p['node_feat'].feats.dtype)
      feats[p['node_feat'].ids] = p['node_feat'].feats
    labels = None
    if p['node_label'] is not None:
      lab, ids = p['node_label']
      labels = np.zeros((n,), lab.dtype)
      labels[ids] = lab
    eids = p['graph'].eids[perm] if p['graph'].eids is not None else perm
    efeats = None
    if p.get('edge_feat') is not None:
      ef = p['edge_feat']
      e_total = int(p['meta'].get('num_edges',
                                  int(ef.ids.max(initial=-1)) + 1))
      efeats = np.zeros((e_total, ef.feats.shape[1]), ef.feats.dtype)
      efeats[ef.ids] = ef.feats
    ds = cls(indptr, indices, edge_ids=eids, node_features=feats,
             node_labels=labels, edge_features=efeats)
    ds.node_pb = np.asarray(p['node_pb'].table)
    ds.partition_idx = int(partition_idx)
    return ds


class HostHeteroDataset:
  """Per-edge-type CSR + per-node-type features/labels, host numpy.

  The heterogeneous twin of `HostDataset` for sampling subprocesses —
  the data the reference's hetero `DistNeighborSampler` reads through
  its per-etype `DistGraph` (`distributed/dist_neighbor_sampler.py:
  192-253` hetero path).

  Attributes:
    csr: ``{EdgeType: (indptr, indices, edge_ids)}`` in sampling
      direction src→dst (``edge_ids`` may be None).
    num_nodes: ``{NodeType: int}``.
    node_features / node_labels: ``{NodeType: array}`` (optional).
    edge_features: ``{EdgeType: [E, De]}`` by global eid (optional).
  """

  def __init__(self, csr, num_nodes, node_features=None, node_labels=None,
               edge_features=None):
    self.csr = {}
    for et, (indptr, indices, eids) in csr.items():
      self.csr[tuple(et)] = (
          np.ascontiguousarray(indptr, np.int64),
          np.ascontiguousarray(indices, np.int64),
          np.ascontiguousarray(eids, np.int64) if eids is not None
          else None)
    self.num_nodes = {nt: int(n) for nt, n in num_nodes.items()}
    self.node_features = {nt: np.asarray(v) for nt, v in
                          (node_features or {}).items()}
    self.node_labels = {nt: np.asarray(v) for nt, v in
                        (node_labels or {}).items()}
    self.edge_features = {tuple(et): np.asarray(v) for et, v in
                          (edge_features or {}).items()}
    #: see `HostDataset.node_pb` — here a per-node-type dict.
    self.node_pb = None
    self.partition_idx = None

  @property
  def edge_types(self):
    return tuple(self.csr.keys())

  @property
  def node_types(self):
    return tuple(sorted({t for (s, _, d) in self.csr for t in (s, d)}
                        | set(self.num_nodes)))

  @classmethod
  def from_coo(cls, edge_index_dict, num_nodes_dict=None,
               node_features=None, node_labels=None,
               edge_features=None) -> 'HostHeteroDataset':
    """Build from ``{EdgeType: (rows, cols)}`` COO dicts."""
    from ..native import coo_to_csr
    num_nodes = dict(num_nodes_dict or {})
    for (s, _, d), (rows, cols) in edge_index_dict.items():
      rows, cols = np.asarray(rows), np.asarray(cols)
      num_nodes[s] = max(num_nodes.get(s, 0),
                         int(rows.max(initial=-1)) + 1)
      num_nodes[d] = max(num_nodes.get(d, 0),
                         int(cols.max(initial=-1)) + 1)
    csr = {}
    for et, (rows, cols) in edge_index_dict.items():
      indptr, indices, perm = coo_to_csr(
          np.asarray(rows), np.asarray(cols), num_nodes[et[0]])
      csr[et] = (indptr, indices, perm)
    return cls(csr, num_nodes, node_features=node_features,
               node_labels=node_labels, edge_features=edge_features)

  @classmethod
  def from_dataset(cls, dataset) -> 'HostHeteroDataset':
    """Borrow the host copies inside a hetero `graphlearn_tpu.data.Dataset`."""
    assert dataset.is_hetero, 'use HostDataset for homogeneous datasets'
    csr = {}
    for et in dataset.get_edge_types():
      topo = dataset.get_graph(et).csr_topo
      csr[et] = (topo.indptr, topo.indices, topo.edge_ids)
    feats = {}
    for nt, f in (dataset.node_features or {}).items():
      feats[nt] = f.host_get()
    labels = {}
    if isinstance(dataset.node_labels, dict):
      for nt, lab in dataset.node_labels.items():
        labels[nt] = np.asarray(lab)
    efeats = {}
    if isinstance(dataset.edge_features, dict):
      for et, f in dataset.edge_features.items():
        efeats[tuple(et)] = f.host_get()
    return cls(csr, dataset.num_nodes_dict(), node_features=feats,
               node_labels=labels, edge_features=efeats)

  @classmethod
  def from_partition_dir(cls, root, partition_idx: int
                         ) -> 'HostHeteroDataset':
    """Load one hetero partition shard from the offline layout."""
    from ..partition import load_partition
    from ..native import coo_to_csr
    p = load_partition(root, partition_idx)
    assert p['meta']['hetero'], 'partition dir is homogeneous'
    num_nodes = {nt: len(pb.table) for nt, pb in p['node_pb'].items()}
    csr = {}
    for et, g in p['graph'].items():
      rows, cols = g.edge_index
      indptr, indices, perm = coo_to_csr(rows, cols, num_nodes[et[0]])
      eids = g.eids[perm] if g.eids is not None else perm
      csr[et] = (indptr, indices, eids)
    feats = {}
    for nt, f in (p['node_feat'] or {}).items():
      d = f.feats.shape[1]
      full = np.zeros((num_nodes[nt], d), f.feats.dtype)
      full[f.ids] = f.feats
      feats[nt] = full
    labels = {}
    for nt, (lab, ids) in (p['node_label'] or {}).items():
      full = np.zeros((num_nodes[nt],), lab.dtype)
      full[ids] = lab
      labels[nt] = full
    efeats = {}
    num_edges = p['meta'].get('num_edges', {})
    for et, f in (p.get('edge_feat') or {}).items():
      e_total = int(num_edges.get(as_str(et),
                                  int(f.ids.max(initial=-1)) + 1))
      full = np.zeros((e_total, f.feats.shape[1]), f.feats.dtype)
      full[f.ids] = f.feats
      efeats[et] = full
    ds = cls(csr, num_nodes, node_features=feats, node_labels=labels,
             edge_features=efeats)
    ds.node_pb = {nt: np.asarray(pb.table)
                  for nt, pb in p['node_pb'].items()}
    ds.partition_idx = int(partition_idx)
    return ds
