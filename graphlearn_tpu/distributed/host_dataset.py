"""Host-side (numpy) dataset handle for sampling subprocesses.

Producer workers never touch the TPU: they sample on CPU with the
native ops (`csrc/cpu_ops.cc`, `csrc/inducer.cc`) over plain numpy
CSR + feature arrays.  With the default ``fork`` start method children
inherit these arrays copy-on-write — the zero-copy analog of the
reference's ForkingPickler shm reductions (`data/*.py` "Pickling
Registration").
"""
from __future__ import annotations

from typing import Optional

import numpy as np


class HostDataset:
  """CSR topology + features/labels as host numpy arrays.

  Attributes:
    indptr / indices / edge_ids: CSR (``edge_ids`` optional).
    node_features: ``[N, D]`` or None.
    node_labels: ``[N]`` or None.
  """

  def __init__(self, indptr, indices, edge_ids=None, node_features=None,
               node_labels=None):
    self.indptr = np.ascontiguousarray(indptr, np.int64)
    self.indices = np.ascontiguousarray(indices, np.int64)
    self.edge_ids = (np.ascontiguousarray(edge_ids, np.int64)
                     if edge_ids is not None else None)
    self.node_features = (np.asarray(node_features)
                          if node_features is not None else None)
    self.node_labels = (np.asarray(node_labels)
                        if node_labels is not None else None)

  @property
  def num_nodes(self) -> int:
    return len(self.indptr) - 1

  @property
  def num_edges(self) -> int:
    return len(self.indices)

  @classmethod
  def from_coo(cls, rows, cols, num_nodes: Optional[int] = None,
               node_features=None, node_labels=None) -> 'HostDataset':
    from ..native import coo_to_csr
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    n = int(num_nodes if num_nodes is not None
            else max(rows.max(initial=-1), cols.max(initial=-1)) + 1)
    indptr, indices, perm = coo_to_csr(rows, cols, n)
    return cls(indptr, indices, edge_ids=perm, node_features=node_features,
               node_labels=node_labels)

  @classmethod
  def from_dataset(cls, dataset) -> 'HostDataset':
    """Borrow the host copies inside a `graphlearn_tpu.data.Dataset`."""
    topo = dataset.get_graph().csr_topo
    feats = dataset.get_node_feature()
    labels = dataset.get_node_label()
    return cls(
        topo.indptr, topo.indices, edge_ids=topo.edge_ids,
        node_features=feats.host_get() if feats is not None else None,
        node_labels=np.asarray(labels) if labels is not None else None)

  @classmethod
  def from_partition_dir(cls, root, partition_idx: int) -> 'HostDataset':
    """Load one partition's shard from the offline layout
    (`graphlearn_tpu.partition.load_partition`)."""
    from ..partition import load_partition
    from ..native import coo_to_csr
    p = load_partition(root, partition_idx)
    rows, cols = p['graph'].edge_index
    n = len(p['node_pb'].table)
    indptr, indices, perm = coo_to_csr(rows, cols, n)
    feats = None
    if p['node_feat'] is not None:
      d = p['node_feat'].feats.shape[1]
      feats = np.zeros((n, d), p['node_feat'].feats.dtype)
      feats[p['node_feat'].ids] = p['node_feat'].feats
    labels = None
    if p['node_label'] is not None:
      lab, ids = p['node_label']
      labels = np.zeros((n,), lab.dtype)
      labels[ids] = lab
    eids = p['graph'].eids[perm] if p['graph'].eids is not None else perm
    return cls(indptr, indices, edge_ids=eids, node_features=feats,
               node_labels=labels)
