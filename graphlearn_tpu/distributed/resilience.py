"""Resilience policy for the distributed runtime.

The reference's RPC tier leans on TensorPipe's internal reconnects;
our socket RPC (`distributed/rpc.py`) had none — a peer dying
mid-frame left the connection undefined and the next request
misparsed.  This module is the ONE place failure policy lives:

  * :class:`RetryPolicy` — deadline + capped exponential backoff with
    *seeded* jitter, so a retry schedule is reproducible under test
    (the chaos harness asserts exact retry counts);
  * a typed error hierarchy on top of ``RpcError``:
    :class:`RetryExhausted` (the peer may still be alive — the policy
    deadline ran out) and :class:`PeerLostError` (a liveness probe
    said the peer is gone, or a worker pool is irrecoverable);
  * :func:`degraded_ok` — the ``GLT_DEGRADED_OK=1`` opt-in that turns
    irrecoverable loss into a finished-but-flagged epoch instead of a
    raise.

Env knobs (all optional; `RetryPolicy.from_env` reads them once per
policy object, so tests can monkeypatch freely):

  * ``GLT_RPC_TIMEOUT`` — per-request socket timeout, seconds (30).
  * ``GLT_RPC_DEADLINE`` — total retry budget per logical request,
    seconds (120).
  * ``GLT_RPC_BACKOFF_BASE`` / ``GLT_RPC_BACKOFF_CAP`` — first and
    max backoff delay, seconds (0.05 / 2.0).
  * ``GLT_RPC_RETRY_SEED`` — jitter RNG seed (0).
  * ``GLT_DEGRADED_OK`` — 1 = finish epochs on surviving peers.
  * ``GLT_MAX_WORKER_RESTARTS`` — producer worker restart budget (3).
"""
from __future__ import annotations

import os
import random
from dataclasses import dataclass, field
from typing import Iterator, Optional

from .rpc import RpcError

TIMEOUT_ENV = 'GLT_RPC_TIMEOUT'
DEADLINE_ENV = 'GLT_RPC_DEADLINE'
BACKOFF_BASE_ENV = 'GLT_RPC_BACKOFF_BASE'
BACKOFF_CAP_ENV = 'GLT_RPC_BACKOFF_CAP'
RETRY_SEED_ENV = 'GLT_RPC_RETRY_SEED'
DEGRADED_ENV = 'GLT_DEGRADED_OK'
RESTARTS_ENV = 'GLT_MAX_WORKER_RESTARTS'
FETCH_DEADLINE_ENV = 'GLT_FETCH_DEADLINE'


class RetryExhausted(RpcError):
  """The retry deadline ran out.  The peer answered a liveness probe
  (or was never probed) — it may be slow, not dead; the caller decides
  whether that distinction matters."""


class PeerLostError(RpcError):
  """A peer is gone for good: the liveness probe failed after the
  retry deadline, or a producer worker pool exhausted its restart
  budget.  Carries enough diagnostics to act on from the log alone."""

  def __init__(self, msg: str, *, peer=None, received=None,
               expected=None, outstanding=None):
    super().__init__(msg)
    self.peer = peer
    self.received = received
    self.expected = expected
    self.outstanding = outstanding


def _env_float(name: str, default: float) -> float:
  try:
    return float(os.environ.get(name, default))
  except ValueError:
    return default


def _env_int(name: str, default: int) -> int:
  try:
    return int(os.environ.get(name, default))
  except ValueError:
    return default


def degraded_ok() -> bool:
  """``GLT_DEGRADED_OK=1``: finish the epoch on surviving peers (the
  loss flagged in telemetry) instead of raising `PeerLostError`."""
  return os.environ.get(DEGRADED_ENV, '') == '1'


def max_worker_restarts() -> int:
  return _env_int(RESTARTS_ENV, 3)


def fetch_deadline() -> float:
  """How long a server's fetch handler waits for a message from an
  ALIVE producer pool before declaring it stalled
  (``GLT_FETCH_DEADLINE``, default 600s).  Deliberately independent of
  — and much larger than — the RPC retry deadline: producing one batch
  slowly is normal; a pool silent for ten minutes is stuck."""
  return _env_float(FETCH_DEADLINE_ENV, 600.0)


@dataclass
class RetryPolicy:
  """Deadline-bounded capped exponential backoff with seeded jitter.

  Attributes:
    request_timeout: per-attempt socket timeout, seconds.
    deadline: total budget across attempts for ONE logical request —
      once exceeded, the next failure raises instead of retrying.
    base_delay / max_delay: backoff ladder ``base * 2**k`` capped at
      ``max_delay``.
    jitter: fraction of each delay drawn uniformly at random and
      ADDED (0.5 = up to +50%); the RNG is seeded, so two policies
      built with the same seed produce identical schedules — the
      determinism the chaos tests pin.
    seed: jitter RNG seed.
  """
  request_timeout: float = 30.0
  deadline: float = 120.0
  base_delay: float = 0.05
  max_delay: float = 2.0
  jitter: float = 0.5
  seed: int = 0
  _rng: random.Random = field(init=False, repr=False, compare=False,
                              default=None)

  def __post_init__(self):
    self._rng = random.Random(self.seed)

  @classmethod
  def from_env(cls, **overrides) -> 'RetryPolicy':
    kw = dict(
        request_timeout=_env_float(TIMEOUT_ENV, 30.0),
        deadline=_env_float(DEADLINE_ENV, 120.0),
        base_delay=_env_float(BACKOFF_BASE_ENV, 0.05),
        max_delay=_env_float(BACKOFF_CAP_ENV, 2.0),
        seed=_env_int(RETRY_SEED_ENV, 0))
    kw.update(overrides)
    return cls(**kw)

  def delay(self, attempt: int) -> float:
    """Backoff before retry number ``attempt`` (0-based): capped
    exponential plus seeded jitter."""
    d = min(self.base_delay * (2.0 ** attempt), self.max_delay)
    if self.jitter > 0:
      d += d * self.jitter * self._rng.random()
    return d

  def delays(self) -> Iterator[float]:
    """The full (unbounded) jittered schedule; callers stop at the
    deadline."""
    attempt = 0
    while True:
      yield self.delay(attempt)
      attempt += 1


#: policy used when callers pass none — one object per process so the
#: jitter stream is continuous, rebuilt lazily so tests that set env
#: knobs before first use see them.
_default: Optional[RetryPolicy] = None


def default_policy() -> RetryPolicy:
  global _default
  if _default is None:
    _default = RetryPolicy.from_env()
  return _default


def reset_default_policy() -> None:
  """Drop the cached process-default policy (tests re-knob the env)."""
  global _default
  _default = None
