"""Resilience policy for the distributed runtime.

The reference's RPC tier leans on TensorPipe's internal reconnects;
our socket RPC (`distributed/rpc.py`) had none — a peer dying
mid-frame left the connection undefined and the next request
misparsed.  This module is the ONE place failure policy lives:

  * :class:`RetryPolicy` — deadline + capped exponential backoff with
    *seeded* jitter, so a retry schedule is reproducible under test
    (the chaos harness asserts exact retry counts);
  * a typed error hierarchy on top of ``RpcError``:
    :class:`RetryExhausted` (the peer may still be alive — the policy
    deadline ran out) and :class:`PeerLostError` (a liveness probe
    said the peer is gone, or a worker pool is irrecoverable);
  * :func:`degraded_ok` — the ``GLT_DEGRADED_OK=1`` opt-in that turns
    irrecoverable loss into a finished-but-flagged epoch instead of a
    raise.

Env knobs (all optional; `RetryPolicy.from_env` reads them once per
policy object, so tests can monkeypatch freely):

  * ``GLT_RPC_TIMEOUT`` — per-request socket timeout, seconds (30).
  * ``GLT_RPC_DEADLINE`` — total retry budget per logical request,
    seconds (120).
  * ``GLT_RPC_BACKOFF_BASE`` / ``GLT_RPC_BACKOFF_CAP`` — first and
    max backoff delay, seconds (0.05 / 2.0).
  * ``GLT_RPC_RETRY_SEED`` — jitter RNG seed (0).
  * ``GLT_DEGRADED_OK`` — 1 = finish epochs on surviving peers.
  * ``GLT_MAX_WORKER_RESTARTS`` — producer worker restart budget (3).
"""
from __future__ import annotations

import os
import random
from dataclasses import dataclass, field
from typing import Iterator, Optional

from .rpc import RpcError

TIMEOUT_ENV = 'GLT_RPC_TIMEOUT'
DEADLINE_ENV = 'GLT_RPC_DEADLINE'
BACKOFF_BASE_ENV = 'GLT_RPC_BACKOFF_BASE'
BACKOFF_CAP_ENV = 'GLT_RPC_BACKOFF_CAP'
RETRY_SEED_ENV = 'GLT_RPC_RETRY_SEED'
DEGRADED_ENV = 'GLT_DEGRADED_OK'
RESTARTS_ENV = 'GLT_MAX_WORKER_RESTARTS'
FETCH_DEADLINE_ENV = 'GLT_FETCH_DEADLINE'
DISPATCH_DEADLINE_ENV = 'GLT_DISPATCH_DEADLINE'


class RetryExhausted(RpcError):
  """The retry deadline ran out.  The peer answered a liveness probe
  (or was never probed) — it may be slow, not dead; the caller decides
  whether that distinction matters."""


class PeerLostError(RpcError):
  """A peer is gone for good: the liveness probe failed after the
  retry deadline, or a producer worker pool exhausted its restart
  budget.  Carries enough diagnostics to act on from the log alone."""

  def __init__(self, msg: str, *, peer=None, received=None,
               expected=None, outstanding=None):
    super().__init__(msg)
    self.peer = peer
    self.received = received
    self.expected = expected
    self.outstanding = outstanding


class ReplayEvictedError(RpcError):
  """A retried request's replay-cache entry was pruned before the
  retry arrived: re-executing would break exactly-once (the fetch
  handler pops a message), so the server answers this typed error
  instead.  Under normal budgets a retry lands well inside the
  replay horizon — seeing this means the cache was under pressure
  (raise `REPLAY_ENTRIES_PER_CLIENT` or lower the prefetch fan-out)."""


class ReplicaLostError(RuntimeError):
  """A serving replica is gone (chaos-killed, crashed, or partitioned
  past the fleet router's eviction threshold).  Raised by replica
  handles on submit-to-a-dead-replica, and carried as the cause when
  the `FleetRouter` redrives that replica's in-flight requests onto a
  survivor.  ``replica`` names the lost handle."""

  def __init__(self, msg: str, *, replica=None):
    super().__init__(msg)
    self.replica = replica


class FailoverExhausted(RuntimeError):
  """The fleet router could not place (or re-place) a request: no
  healthy replica remained, or the request's one redrive was already
  spent when its second replica died too.  The request's future
  resolves with THIS — typed, never a silent drop — so the caller can
  tell a fleet-wide outage from a per-request shed."""

  def __init__(self, msg: str, *, replica=None, redriven: bool = False):
    super().__init__(msg)
    self.replica = replica
    self.redriven = redriven


class MeshStallError(RuntimeError):
  """A fused/mesh dispatch exceeded the configured dispatch deadline
  (``GLT_DISPATCH_DEADLINE``) — the signature of a collective whose
  participant died mid-``all_to_all`` (the program would otherwise
  hang forever).  Carries the last-known-healthy participant set so
  the operator (or the degraded-resume path) knows who survived."""

  def __init__(self, msg: str, *, healthy=None, deadline=None,
               scope: str = ''):
    super().__init__(msg)
    self.healthy = list(healthy) if healthy is not None else None
    self.deadline = deadline
    self.scope = scope


def _env_float(name: str, default: float) -> float:
  try:
    return float(os.environ.get(name, default))
  except ValueError:
    return default


def _env_int(name: str, default: int) -> int:
  try:
    return int(os.environ.get(name, default))
  except ValueError:
    return default


def degraded_ok() -> bool:
  """``GLT_DEGRADED_OK=1``: finish the epoch on surviving peers (the
  loss flagged in telemetry) instead of raising `PeerLostError`."""
  return os.environ.get(DEGRADED_ENV, '') == '1'


def max_worker_restarts() -> int:
  return _env_int(RESTARTS_ENV, 3)


def fetch_deadline() -> float:
  """How long a server's fetch handler waits for a message from an
  ALIVE producer pool before declaring it stalled
  (``GLT_FETCH_DEADLINE``, default 600s).  Deliberately independent of
  — and much larger than — the RPC retry deadline: producing one batch
  slowly is normal; a pool silent for ten minutes is stuck."""
  return _env_float(FETCH_DEADLINE_ENV, 600.0)


def dispatch_deadline() -> float:
  """``GLT_DISPATCH_DEADLINE`` — seconds a fused/mesh chunk dispatch
  may block before the watchdog converts the hang into a typed
  `MeshStallError`.  Default 0 = disabled: the right deadline is a
  multiple of the measured chunk wall (compiles included), which only
  the deployment knows."""
  return _env_float(DISPATCH_DEADLINE_ENV, 0.0)


def healthy_participants() -> list:
  """Best-effort last-known-healthy participant (process) set for
  `MeshStallError` diagnostics: every process index that answered the
  runtime's liveness view.  Single-controller meshes report
  ``[0, .., n-1]`` of live local processes (trivially healthy — the
  stall is then inside the collective itself); a multi-host runtime
  without a reachable KV store degrades to the local process index."""
  import jax
  try:
    return list(range(jax.process_count()))
  except Exception:               # noqa: BLE001 — uninitialized runtime
    return [0]


def run_with_deadline(fn, *args, deadline: Optional[float] = None,
                      scope: str = '', **kwargs):
  """Run ``fn(*args, **kwargs)`` under the dispatch watchdog.

  ``deadline`` None reads `dispatch_deadline()`; 0 disables (direct
  call, zero overhead).  With a deadline, the call runs on a helper
  thread and a timeout emits a ``mesh.stall`` event + raises
  `MeshStallError` with the last-known-healthy participant set.  The
  hung dispatch thread itself cannot be killed (XLA holds it) — the
  caller decides whether to roll back to a snapshot (degraded mode)
  or let the error end the job; either way the epoch is no longer
  silently wedged."""
  if deadline is None:
    deadline = dispatch_deadline()
  if not deadline or deadline <= 0:
    return fn(*args, **kwargs)
  import threading
  out: dict = {}

  def _run():
    try:
      out['value'] = fn(*args, **kwargs)
    except BaseException as e:      # noqa: BLE001 — forwarded below
      out['error'] = e

  t = threading.Thread(target=_run, daemon=True,
                       name=f'glt-dispatch-{scope or "chunk"}')
  t.start()
  t.join(deadline)
  if t.is_alive():
    healthy = healthy_participants()
    from ..telemetry.recorder import recorder
    recorder.emit('mesh.stall', scope=scope, deadline_secs=deadline,
                  healthy=healthy)
    err = MeshStallError(
        f'{scope or "dispatch"} still blocked after {deadline:.1f}s '
        f'(GLT_DISPATCH_DEADLINE) — a mesh participant likely died '
        f'mid-collective; last-known-healthy processes: {healthy}',
        healthy=healthy, deadline=deadline, scope=scope)
    # black box (ISSUE 12): dump the recorder ring + metrics snapshot
    # BEFORE raising — the degraded-rollback path may recover, but if
    # the process dies instead, this bundle is the only artifact.
    # One-shot per process; a no-op unless GLT_POSTMORTEM_DIR is set.
    from ..telemetry import postmortem
    postmortem.dump('mesh.stall', error=err)
    raise err
  if 'error' in out:
    raise out['error']
  return out['value']


@dataclass
class RetryPolicy:
  """Deadline-bounded capped exponential backoff with seeded jitter.

  Attributes:
    request_timeout: per-attempt socket timeout, seconds.
    deadline: total budget across attempts for ONE logical request —
      once exceeded, the next failure raises instead of retrying.
    base_delay / max_delay: backoff ladder ``base * 2**k`` capped at
      ``max_delay``.
    jitter: fraction of each delay drawn uniformly at random and
      ADDED (0.5 = up to +50%); the RNG is seeded, so two policies
      built with the same seed produce identical schedules — the
      determinism the chaos tests pin.
    seed: jitter RNG seed.
  """
  request_timeout: float = 30.0
  deadline: float = 120.0
  base_delay: float = 0.05
  max_delay: float = 2.0
  jitter: float = 0.5
  seed: int = 0
  _rng: random.Random = field(init=False, repr=False, compare=False,
                              default=None)

  def __post_init__(self):
    self._rng = random.Random(self.seed)

  @classmethod
  def from_env(cls, **overrides) -> 'RetryPolicy':
    kw = dict(
        request_timeout=_env_float(TIMEOUT_ENV, 30.0),
        deadline=_env_float(DEADLINE_ENV, 120.0),
        base_delay=_env_float(BACKOFF_BASE_ENV, 0.05),
        max_delay=_env_float(BACKOFF_CAP_ENV, 2.0),
        seed=_env_int(RETRY_SEED_ENV, 0))
    kw.update(overrides)
    return cls(**kw)

  def delay(self, attempt: int) -> float:
    """Backoff before retry number ``attempt`` (0-based): capped
    exponential plus seeded jitter."""
    d = min(self.base_delay * (2.0 ** attempt), self.max_delay)
    if self.jitter > 0:
      d += d * self.jitter * self._rng.random()
    return d

  def delays(self) -> Iterator[float]:
    """The full (unbounded) jittered schedule; callers stop at the
    deadline."""
    attempt = 0
    while True:
      yield self.delay(attempt)
      attempt += 1


#: policy used when callers pass none — one object per process so the
#: jitter stream is continuous, rebuilt lazily so tests that set env
#: knobs before first use see them.
_default: Optional[RetryPolicy] = None


def default_policy() -> RetryPolicy:
  global _default
  if _default is None:
    _default = RetryPolicy.from_env()
  return _default


def reset_default_policy() -> None:
  """Drop the cached process-default policy (tests re-knob the env)."""
  global _default
  _default = None
