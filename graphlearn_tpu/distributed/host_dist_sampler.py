"""Partition-aware host runtime: cross-server per-hop fan-out, stitch,
and remote feature lookup over the socket RPC.

The host-runtime twin of the mesh engine's all_to_all hop — and the
direct analog of the reference's core distributed act: per hop,
partition the frontier by the node partition book, sample locally for
owned ids, RPC the rest to their owners, and stitch the replies back
into frontier order (`distributed/dist_neighbor_sampler.py:542-598` +
`csrc/cuda/stitch_sample_results.cu`); features and labels fan out the
same way (`distributed/dist_feature.py:134-269`).

Differences from the reference, by design:
  * transport is the small threaded socket RPC (`distributed/rpc.py`)
    instead of torch TensorPipe — replies ride the tensor-map frame
    (no pickle on the data path);
  * edge-feature rows are collected AT SAMPLING TIME on the owning
    server (each hop/out-edge reply carries its rows) instead of a
    second per-eid lookup — edge ownership follows the sampled edge,
    so no edge partition book is needed;
  * strict link negatives reject against the LOCAL shard only, exactly
    like the reference's local rejection (`dist_neighbor_sampler.py:
    327-453`); the mesh engine is the place for globally-strict
    negatives (`parallel.dist_sampler.dist_edge_exists`).

Deployment: every sampling host runs a `PartitionService` over its
shard (standalone or on its `DistServer`'s RpcServer) and builds a
`HostDistNeighborSampler` with `RpcClient`s to its peers.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from .. import native
from .host_dataset import HostDataset
from .host_sampler import HostNeighborSampler, shard_out_edges
from .rpc import RpcClient, RpcServer


def _efeat_rows(ds: HostDataset, eids: np.ndarray,
                mask: np.ndarray) -> np.ndarray:
  """Edge-feature rows for masked eids (zero rows where masked)."""
  flat = np.where(mask, eids, 0).reshape(-1)
  rows = ds.edge_features[flat]
  rows = np.where(mask.reshape(-1)[:, None], rows, 0)
  return np.ascontiguousarray(rows.reshape(eids.shape + (-1,)))


class PartitionService:
  """Serves one partition shard to peer samplers (the role of the
  reference's `RpcSamplingCallee` + `RpcFeatureLookupCallee` +
  `RpcSubGraphCallee`, `distributed/dist_neighbor_sampler.py:57-86`,
  `dist_feature.py:39-48`).

  Args:
    dataset: shard `HostDataset` (``from_partition_dir``).
    server: optional existing `RpcServer` to register on (e.g. a
      `DistServer`'s); otherwise one is created on ``host:port``.
  """

  HANDLERS = ('peer_one_hop', 'peer_node_data', 'peer_out_edges')

  def __init__(self, dataset: HostDataset, host: str = '0.0.0.0',
               port: int = 0, server: Optional[RpcServer] = None):
    self.ds = dataset
    self._own_server = server is None
    self._server = server or RpcServer(host, port)
    for name in self.HANDLERS:
      self._server.register(name, getattr(self, name))
    if self._own_server:
      self._server.start()
    self.port = self._server.port
    self.host = self._server.host

  # -- handlers (all return dict-of-ndarray = tensor-map frames) ---------
  def peer_one_hop(self, srcs: np.ndarray, k: int, hop_seed: int,
                   with_edge: bool, want_efeats: bool):
    """One-hop sample of OWNED ``srcs`` on the local shard — the remote
    side of the reference's `RpcSamplingCallee.call`
    (`dist_neighbor_sampler.py:57-69`)."""
    nbrs, mask, eids = native.sample_one_hop(
        self.ds.indptr, self.ds.indices, np.asarray(srcs, np.int64),
        int(k), seed=int(hop_seed), edge_ids=self.ds.edge_ids,
        with_edge_ids=with_edge)
    out = {'nbrs': nbrs, 'mask': mask}
    if with_edge:
      out['eids'] = eids
      if want_efeats and self.ds.edge_features is not None:
        out['efeats'] = _efeat_rows(self.ds, eids, mask)
    return out

  def peer_node_data(self, ids: np.ndarray, want_feats: bool,
                     want_labels: bool):
    """Feature/label rows of OWNED ids (`RpcFeatureLookupCallee` →
    `local_get`, `dist_feature.py:39-48,122-132`)."""
    ids = np.asarray(ids, np.int64)
    out = {}
    if want_feats and self.ds.node_features is not None:
      out['nfeats'] = np.ascontiguousarray(self.ds.node_features[ids])
    if want_labels and self.ds.node_labels is not None:
      out['nlabels'] = np.ascontiguousarray(self.ds.node_labels[ids])
    return out

  def peer_out_edges(self, nodes: np.ndarray, with_edge: bool,
                     want_efeats: bool):
    """ALL local out-edges of OWNED ``nodes`` (the induced-subgraph
    remote scan, reference `RpcSubGraphCallee`,
    `dist_neighbor_sampler.py:71-86`)."""
    nodes = np.asarray(nodes, np.int64)
    src_pos, nbrs, eids = shard_out_edges(self.ds, nodes, with_edge)
    out = {'src_pos': src_pos, 'nbrs': nbrs}
    if eids is not None:
      out['eids'] = eids
      if want_efeats and self.ds.edge_features is not None:
        out['efeats'] = _efeat_rows(self.ds, eids,
                                    np.ones(eids.shape, bool))
    return out

  def shutdown(self) -> None:
    if self._own_server:
      self._server.shutdown()


def connect_peers(addrs: Sequence[Tuple[str, int]],
                  my_partition: int) -> Dict[int, RpcClient]:
  """``{partition_idx: RpcClient}`` for every peer but mine."""
  return {p: RpcClient(h, pt) for p, (h, pt) in enumerate(addrs)
          if p != my_partition}


class HostDistNeighborSampler(HostNeighborSampler):
  """Multi-hop sampler over a PARTITION SHARD with peer fan-out.

  Every data access of the base sampler is rerouted through the
  partition book: one-hop sampling, node feature/label collection, and
  the induced-subgraph out-edge scan each split ids into local (native
  ops on the shard) and remote (one RPC per owning peer) groups and
  stitch replies back into request order.  Strict link negatives
  reject against the local shard only (reference parity — see module
  docstring).

  Args:
    dataset: shard `HostDataset` with ``node_pb``/``partition_idx``
      set (``from_partition_dir``).
    peers: ``{partition_idx: RpcClient}`` to every other partition's
      `PartitionService` (see `connect_peers`).
  """

  def __init__(self, dataset: HostDataset, num_neighbors: Sequence[int],
               peers: Dict[int, RpcClient], **kwargs):
    if getattr(dataset, 'node_pb', None) is None or \
        dataset.partition_idx is None:
      raise ValueError(
          'HostDistNeighborSampler needs a partition shard with '
          'node_pb/partition_idx set (HostDataset.from_partition_dir); '
          'for a full local graph use HostNeighborSampler.')
    super().__init__(dataset, num_neighbors, **kwargs)
    self.node_pb = np.asarray(dataset.node_pb)
    self.my_part = int(dataset.partition_idx)
    self.peers = dict(peers)
    missing = (set(np.unique(self.node_pb).tolist())
               - {self.my_part} - set(self.peers))
    if missing:
      raise ValueError(f'no peer client for partitions {sorted(missing)}')
    self._efeat_ids = []
    self._efeat_rows = []
    self._node_data_memo = None

  # -- per-batch edge-feature accumulation -------------------------------
  def _begin_batch(self) -> None:
    self._efeat_ids = []
    self._efeat_rows = []
    self._node_data_memo = None

  def _want_efeats(self) -> bool:
    return (self.with_edge and self.collect_features
            and self._has_edge_features)

  def _cache_efeats(self, eids: np.ndarray, rows: np.ndarray) -> None:
    if len(eids):
      self._efeat_ids.append(np.asarray(eids, np.int64))
      self._efeat_rows.append(rows.reshape(len(eids), -1))

  # -- rerouted data accesses --------------------------------------------
  def _one_hop(self, frontier: np.ndarray, k: int, hop_seed: int):
    """Partition frontier by pb -> local sample + per-owner RPC ->
    index stitch (the reference `_sample_one_hop` + stitch,
    `dist_neighbor_sampler.py:542-598`)."""
    frontier = np.asarray(frontier, np.int64)
    owner = self.node_pb[frontier]
    n = len(frontier)
    nbrs = np.full((n, k), -1, np.int64)
    mask = np.zeros((n, k), bool)
    eids = np.full((n, k), -1, np.int64) if self.with_edge else None
    want_ef = self._want_efeats()
    for p in np.unique(owner):
      sel = np.where(owner == p)[0]
      srcs = frontier[sel]
      # per-owner seed: identical draws across owners would correlate
      # same-row samples when a frontier id appears under two owners
      seed_p = int(hop_seed) * 131 + int(p)
      if p == self.my_part:
        nb, mk, ei = native.sample_one_hop(
            self.ds.indptr, self.ds.indices, srcs, int(k), seed=seed_p,
            edge_ids=self.ds.edge_ids, with_edge_ids=self.with_edge)
        ef = (_efeat_rows(self.ds, ei, mk) if want_ef else None)
      else:
        r = self.peers[int(p)].request(
            'peer_one_hop', srcs, int(k), seed_p, self.with_edge,
            want_ef)
        nb, mk = r['nbrs'], r['mask'].astype(bool)
        ei = r.get('eids')
        ef = r.get('efeats')
      nbrs[sel] = nb
      mask[sel] = mk
      if self.with_edge and ei is not None:
        eids[sel] = ei
        if ef is not None:
          m = mk.reshape(-1)
          self._cache_efeats(ei.reshape(-1)[m],
                             ef.reshape(m.shape[0], -1)[m])
    return nbrs, mask, eids

  def _fanout_node_data(self, ids: np.ndarray, want_feats: bool,
                        want_labels: bool):
    """Grouped local+remote row collection, scattered back into id
    order (`DistFeature.async_get` + `_stitch`,
    `dist_feature.py:134-269`)."""
    ids = np.asarray(ids, np.int64)
    owner = self.node_pb[ids]
    nfeats = nlabels = None
    for p in np.unique(owner):
      sel = np.where(owner == p)[0]
      sub = ids[sel]
      if p == self.my_part:
        r = {}
        if want_feats and self.ds.node_features is not None:
          r['nfeats'] = self.ds.node_features[sub]
        if want_labels and self.ds.node_labels is not None:
          r['nlabels'] = self.ds.node_labels[sub]
      else:
        r = self.peers[int(p)].request('peer_node_data', sub,
                                       want_feats, want_labels)
      if 'nfeats' in r:
        if nfeats is None:
          nfeats = np.zeros((len(ids),) + r['nfeats'].shape[1:],
                            r['nfeats'].dtype)
        nfeats[sel] = r['nfeats']
      if 'nlabels' in r:
        if nlabels is None:
          nlabels = np.zeros((len(ids),) + r['nlabels'].shape[1:],
                             r['nlabels'].dtype)
        nlabels[sel] = r['nlabels']
    return nfeats, nlabels

  def _node_data(self, ids: np.ndarray):
    """Fetch features AND labels in ONE per-owner fan-out and memoize:
    `_finish` gathers both for the same node table, so the second
    gather must not pay another (P-1) round trips."""
    memo = self._node_data_memo
    if memo is not None and np.array_equal(memo[0], ids):
      return memo[1], memo[2]
    feats, labels = self._fanout_node_data(
        ids, self.collect_features and self._has_node_features,
        self._has_node_labels)
    self._node_data_memo = (np.asarray(ids), feats, labels)
    return feats, labels

  def _gather_node_features(self, ids: np.ndarray) -> np.ndarray:
    return self._node_data(ids)[0]

  def _gather_node_labels(self, ids: np.ndarray) -> np.ndarray:
    return self._node_data(ids)[1]

  def _gather_edge_features(self, eids: np.ndarray) -> np.ndarray:
    """Rows were collected at sampling time on the owning server (see
    module docstring); serve them from the per-batch cache."""
    eids = np.asarray(eids, np.int64)
    if not self._efeat_ids:
      d = (self.ds.edge_features.shape[1]
           if self.ds.edge_features is not None else 0)
      return np.zeros((len(eids), d), np.float32)
    cat_ids = np.concatenate(self._efeat_ids)
    cat_rows = np.concatenate(self._efeat_rows)
    order = np.argsort(cat_ids, kind='stable')
    sids = cat_ids[order]
    pos = np.clip(np.searchsorted(sids, eids), 0, len(sids) - 1)
    found = sids[pos] == eids
    if not found.all():
      raise RuntimeError(
          'edge-feature cache miss: an emitted eid was never sampled '
          f'({eids[~found][:5]} ...)')
    return cat_rows[order[pos]]

  def _closure_out_edges(self, nodes: np.ndarray):
    """Ownership-split induced-subgraph scan: local shard scan + one
    `peer_out_edges` RPC per remote owner (reference `_subgraph`
    cross-partition path, `dist_neighbor_sampler.py:456-516`)."""
    nodes = np.asarray(nodes, np.int64)
    owner = self.node_pb[nodes]
    want_ef = self._want_efeats()
    srcs_acc, nbrs_acc, eids_acc = [], [], []
    for p in np.unique(owner):
      sel = np.where(owner == p)[0]
      sub = nodes[sel]
      if p == self.my_part:
        sp, nb, ei = shard_out_edges(self.ds, sub, self.with_edge)
        if want_ef and ei is not None:
          self._cache_efeats(ei, _efeat_rows(
              self.ds, ei, np.ones(ei.shape, bool)))
      else:
        r = self.peers[int(p)].request('peer_out_edges', sub,
                                       self.with_edge, want_ef)
        sp, nb = r['src_pos'], r['nbrs']
        ei = r.get('eids')
        if want_ef and 'efeats' in r and ei is not None:
          self._cache_efeats(ei, r['efeats'])
      srcs_acc.append(sel[sp])
      nbrs_acc.append(nb)
      if self.with_edge and ei is not None:
        eids_acc.append(ei)
    src_pos = (np.concatenate(srcs_acc) if srcs_acc
               else np.empty(0, np.int64))
    nbrs = (np.concatenate(nbrs_acc) if nbrs_acc
            else np.empty(0, np.int64))
    eids = (np.concatenate(eids_acc)
            if (self.with_edge and eids_acc) else None)
    return src_pos, nbrs, eids
