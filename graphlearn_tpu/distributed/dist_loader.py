"""Trainer-side loader over a sample channel.

Reference `distributed/dist_loader.py:49-383`: pick a worker mode
(collocated / mp / remote), run the epoch protocol (produce_all, then
recv exactly the expected number of messages), and collate each flat
``SampleMessage`` into the training batch.  TPU twist: ragged host
messages are padded to **static capacities** here so every batch
compiles to the same XLA program, then staged with one `device_put`.
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import numpy as np

from ..channel import (ChannelBase, MpChannel, RemoteReceivingChannel,
                       SampleMessage, ShmChannel)
from ..loader.transform import Batch, HeteroBatch
from ..typing import as_str, reverse_edge_type
from ..utils.padding import (INVALID_ID, max_sampled_nodes,
                             next_power_of_two, round_up)
from ..utils.profiling import metrics, trace
from .dist_options import (CollocatedDistSamplingWorkerOptions,
                           HostSamplingConfig,
                           MpDistSamplingWorkerOptions,
                           RemoteDistSamplingWorkerOptions)
from .dist_sampling_producer import (CollocatedSamplingProducer,
                                     MpSamplingProducer)
from .host_dataset import HostDataset, HostHeteroDataset

WorkerOptions = Union[CollocatedDistSamplingWorkerOptions,
                      MpDistSamplingWorkerOptions,
                      RemoteDistSamplingWorkerOptions]


def edge_capacity(batch_size: int, fanouts: Sequence[int]) -> int:
  """Static bound on total sampled edges across hops — the ONE
  worst-case count (`utils.padding.max_sampled_edges`) rounded to the
  loader's lane multiple."""
  from ..utils.padding import max_sampled_edges
  return max(round_up(max_sampled_edges(batch_size, fanouts), 8), 8)


class DistLoader:
  """Channel-fed loader base (reference `dist_loader.py:49-383`).

  Args:
    dataset: `HostDataset` (sampling world's shard).
    num_neighbors: per-hop fanouts.
    input_nodes: seed ids.
    batch_size / shuffle / drop_last: epoch iteration controls.
    worker_options: deployment mode selector.
    to_device: stage collated batches onto the default device.
  """

  def __init__(self, dataset: Optional[HostDataset], num_neighbors,
               input_nodes, batch_size: int = 512, shuffle: bool = False,
               drop_last: bool = False,
               worker_options: Optional[WorkerOptions] = None,
               with_edge: bool = False, to_device: bool = True,
               seed: int = 0, sampling_config=None):
    if isinstance(num_neighbors, dict):
      self.fanouts = {tuple(k): [int(x) for x in v]
                      for k, v in num_neighbors.items()}
    else:
      self.fanouts = [int(k) for k in num_neighbors]
    self.batch_size = int(batch_size)
    # hetero node seeds come as ``(node_type, ids)`` (the reference's
    # hetero ``input_nodes`` contract, `loader/node_loader.py`)
    if (isinstance(input_nodes, (tuple, list)) and len(input_nodes) == 2
        and isinstance(input_nodes[0], str)):
      ntype, input_nodes = input_nodes
      if sampling_config is None:
        sampling_config = HostSamplingConfig(sampling_type='node',
                                             input_type=ntype)
      elif sampling_config.input_type is None:
        # copy: the caller's config object may be shared across loaders
        import dataclasses
        sampling_config = dataclasses.replace(sampling_config,
                                              input_type=ntype)
    seeds = np.asarray(input_nodes)
    self.seeds = seeds if seeds.ndim > 1 else seeds.reshape(-1)
    self.shuffle = shuffle
    self.drop_last = drop_last
    self.with_edge = with_edge
    self.to_device = to_device
    self.opts = worker_options or CollocatedDistSamplingWorkerOptions()
    self.sampling_config = sampling_config
    self._epoch_iter = None
    self._expected = 0
    self._received = 0
    self.is_hetero = isinstance(dataset, HostHeteroDataset)
    meta = None
    if dataset is None and isinstance(self.opts,
                                      RemoteDistSamplingWorkerOptions):
      # remote mode without a local dataset: the server's meta carries
      # what capacity planning needs (reference loaders likewise fetch
      # `get_dataset_meta` first, `dist_loader.py:202`)
      from .dist_client import get_client
      client = get_client()
      if client is not None:
        sr = self.opts.server_rank
        idx = (sr[0] if isinstance(sr, (list, tuple)) else (sr or 0))
        meta = client.get_dataset_meta(idx)
        self.is_hetero = bool(meta.get('hetero'))
    if self.is_hetero:
      etypes = (dataset.edge_types if dataset is not None
                else tuple(tuple(e) for e in meta['edge_types']))
      num_nodes = (dataset.num_nodes if dataset is not None
                   else meta['num_nodes'])
      self._init_hetero_caps(etypes, num_nodes)
    else:
      if isinstance(self.fanouts, dict):
        raise ValueError(
            'dict-valued num_neighbors implies a hetero dataset: pass a '
            'HostHeteroDataset, or init_client() first so the remote '
            "server's hetero meta is reachable")
      # link/subgraph modes feed more node seeds into expansion per
      # seed-batch slot (endpoints + negatives)
      exp_seeds = (sampling_config.expansion_seeds(self.batch_size)
                   if sampling_config is not None else self.batch_size)
      if dataset is not None:
        num_nodes = dataset.num_nodes
      elif meta is not None:
        num_nodes = meta['num_nodes']
      else:
        num_nodes = 1 << 30
      self.node_cap = round_up(
          min(max_sampled_nodes(exp_seeds, self.fanouts),
              exp_seeds + num_nodes), 8)
      self.edge_cap = edge_capacity(exp_seeds, self.fanouts)
      self.batch_cap = exp_seeds

    self.channel: Optional[ChannelBase] = None
    self._producer = None
    if isinstance(self.opts, MpDistSamplingWorkerOptions):
      self.channel = ShmChannel(self.opts.resolved_capacity(),
                                self.opts.resolved_size())
      self._producer = MpSamplingProducer(
          dataset, self.fanouts, self.batch_size, self.channel,
          self.opts, with_edge=with_edge, shuffle=shuffle, seed=seed,
          sampling_config=sampling_config)
      self._producer.init()
    elif isinstance(self.opts, RemoteDistSamplingWorkerOptions):
      from .dist_client import get_client
      client = get_client()
      assert client is not None, (
          'init_client() before RemoteDistSamplingWorkerOptions loaders')
      self._remote = client.create_sampling_producer(
          self.opts, self.fanouts, self.batch_size, self.seeds,
          with_edge=with_edge, shuffle=shuffle, seed=seed,
          sampling_config=sampling_config)
      self.channel = RemoteReceivingChannel(
          self._remote.fetch, self._num_batches(),
          self.opts.prefetch_size)
    else:
      self._producer = CollocatedSamplingProducer(
          dataset, self.fanouts, self.batch_size, with_edge=with_edge,
          collect_features=self.opts.collect_features, shuffle=shuffle,
          seed=seed, sampling_config=sampling_config)

  def _init_hetero_caps(self, etypes, num_nodes) -> None:
    """Static per-type capacity plan for hetero collation — the same
    planner the device hetero sampler compiles against
    (`sampler/hetero_neighbor_sampler.py::_plan_capacities`)."""
    from ..sampler.hetero_neighbor_sampler import (_plan_capacities,
                                                   normalize_fanouts)
    cfg = self.sampling_config
    if cfg is not None and cfg.sampling_type == 'subgraph':
      # the reference's SubGraphOp is homogeneous-only
      # (`include/subgraph_op_base.h`); reject at construction, not
      # as an opaque worker crash at iteration time
      raise ValueError('subgraph sampling is homogeneous-only')
    assert cfg is not None and cfg.input_type is not None, (
        'hetero loading needs a seed type: pass input_nodes=(ntype, ids) '
        'or edge_label_index=(etype, pairs)')
    etypes, fanouts, num_hops = normalize_fanouts(tuple(etypes),
                                                  self.fanouts)
    input_sizes = cfg.hetero_input_sizes(self.batch_size)
    ntypes, table_cap, _, edge_caps = _plan_capacities(
        etypes, fanouts, input_sizes, num_hops, dict(num_nodes))
    self.h_ntypes = ntypes
    self.h_node_cap = table_cap
    self.h_seed_cap = input_sizes
    self.h_edge_cap = {}
    for et in etypes:
      total = sum(ec.get(et, 0) for ec in edge_caps)
      if total > 0:
        self.h_edge_cap[reverse_edge_type(et)] = round_up(total, 8)
    self.h_num_hops = num_hops
    self.batch_cap = self.batch_size

  def _num_batches(self) -> int:
    n = len(self.seeds)
    if self.drop_last:
      return n // self.batch_size
    return (n + self.batch_size - 1) // self.batch_size

  def __len__(self) -> int:
    return self._num_batches()

  # -- epoch protocol (reference `__iter__`/`__next__`,
  # `dist_loader.py:246-272`) ---------------------------------------------
  def __iter__(self):
    self._seen_seqs = set()       # '#SEQ' stamps delivered this epoch
    self._degraded_lost = set()   # seqs written off in degraded mode
    if isinstance(self.opts, MpDistSamplingWorkerOptions):
      self._expected = self._producer.produce_all(self.seeds,
                                                  drop_last=self.drop_last)
      self._received = 0
    elif isinstance(self.opts, RemoteDistSamplingWorkerOptions):
      expected = self._remote.start_new_epoch(drop_last=self.drop_last)
      self.channel.reset(expected)
      self._expected = expected
      self._received = 0
    else:
      self._epoch_iter = self._producer.epoch(self.seeds,
                                              drop_last=self.drop_last)
    return self

  def __next__(self) -> Batch:
    from ..telemetry import spans
    # epoch exhaustion surfaces BEFORE the per-batch 'batch' root
    # span opens — an epoch end is not a batch and must not emit a
    # phantom near-zero span pair into the histogram/trace.  In
    # collocated mode that means the in-process sampling (inside
    # next()) runs outside the span; the channel-fed modes (the
    # production deployments) keep full recv+collate coverage.
    if self._epoch_iter is not None:
      msg = next(self._epoch_iter)
      with spans.span('batch', scope=type(self).__name__):
        return self._collate_batch(msg)
    if self._received >= self._expected:
      raise StopIteration
    with spans.span('batch', scope=type(self).__name__):
      with spans.span('recv'):
        with trace('dist_loader.recv'):
          msg = self._recv_current_epoch()
      self._received += 1
      return self._collate_batch(msg)

  def _collate_batch(self, msg: SampleMessage) -> Batch:
    """Collate under a 'collate' span carrying the producer's
    cross-process span context (injected into the message by the
    channel) as producer_trace/producer_span link fields."""
    from ..telemetry import spans
    # every channel receive path already stripped-and-parked the
    # message's '#SPAN' (ChannelTelemetry._park_span) — the parked
    # context is the one source of the producer link
    link = spans.link_fields(getattr(self.channel,
                                     'last_span_context', None))
    with spans.span('collate', **link):
      with trace('dist_loader.collate'):
        batch = self._collate_fn(msg)
    metrics.inc('dist_loader.batches')
    return batch

  #: timed-wait granularity of the supervision poll loops.
  RECV_POLL_SECS = 5.0

  def _recv_current_epoch(self) -> SampleMessage:
    """Receive, discarding stale-epoch messages left in the channel by
    an early-terminated previous epoch (`RemoteReceivingChannel` does
    its own stamp + '#SEQ' filtering).  Blocking waits are liveness-
    guarded: every wait is timed, and each timeout runs supervision —
    mp mode restarts dead workers and replays their unacked batches;
    remote mode heartbeats the servers.  Irrecoverable loss raises
    `PeerLostError` with diagnostics, or — ``GLT_DEGRADED_OK=1`` —
    finishes the epoch on survivors with the loss flagged in telemetry
    (a ``peer.lost`` event with ``degraded=True``)."""
    from ..telemetry.recorder import recorder
    from .resilience import PeerLostError, degraded_ok
    if isinstance(self.opts, RemoteDistSamplingWorkerOptions):
      while True:
        try:
          msg = self.channel.recv_timeout(self.RECV_POLL_SECS)
        except StopIteration:
          raise
        except PeerLostError as e:
          # the fallback ladder (ISSUE 15): ADOPT the dead server's
          # producers on a survivor (exact completion) → degraded
          # write-off (GLT_DEGRADED_OK) → typed raise
          if self._try_adopt_server(e):
            continue
          if not degraded_ok() or not hasattr(self._remote,
                                              'drop_server'):
            # single-server loaders have no survivors to finish on —
            # degraded mode needs a multi-server plan to fall back to
            e.peer_health = dict(getattr(self, '_peer_health', {}))
            raise
          # finish on survivors: write off what the dead peer still
          # owed (its planned fetches + this failed one) and keep
          # draining the rest of the plan
          owed = 1
          if e.peer is not None:
            owed += self._remote.drop_server(e.peer)
          self.channel.reduce_expected(owed)
          self._expected -= owed
          recorder.emit('peer.lost', peer=e.peer, peer_kind='server',
                        degraded=True, lost_batches=owed,
                        received=self._received,
                        expected=self._expected)
          if self._received >= self._expected:
            raise StopIteration from e
          continue
        if msg is not None:
          return msg
        # clean poll timeout: distinguish slow from dead via the
        # heartbeat (a dead server's in-flight fetch will also raise,
        # but the probe surfaces sooner and feeds diagnostics)
        self._probe_servers()
      # not reached
    cur = self._producer.current_epoch
    while True:
      # timed semaphore wait: blocking fast path, and ANY crashed
      # worker surfaces on the next timeout (a dead worker may hold an
      # outstanding seed slice that will never arrive).  The timed
      # recv itself closes the message-arrived-then-died race: a
      # message present at raise-decision time was drained.
      msg = self.channel.recv_timeout(self.RECV_POLL_SECS)
      if msg is None:
        _, lost = self._producer.supervise(self._seen_seqs)
        fresh_lost = set(lost) - self._degraded_lost
        if fresh_lost:
          if not degraded_ok():
            dead = self._producer.dead_worker_exitcodes()
            raise PeerLostError(
                f'{len(dead)} sampling worker(s) unrecoverable (exit '
                f'codes {dead}, restart budget spent) with '
                f'{self._expected - self._received} batch(es) '
                f'outstanding, {len(fresh_lost)} of them lost for '
                f'good; received {self._received}/{self._expected}',
                received=self._received, expected=self._expected,
                outstanding=len(fresh_lost))
          self._degraded_lost |= fresh_lost
          self._expected -= len(fresh_lost)
          recorder.emit('peer.lost', peer_kind='worker', degraded=True,
                        lost_batches=len(fresh_lost),
                        received=self._received,
                        expected=self._expected)
          if self._received >= self._expected:
            raise StopIteration
        continue
      stamp = msg.get('#EPOCH')
      if stamp is not None and int(np.asarray(stamp)) != cur:
        continue
      seq = msg.get('#SEQ')
      if seq is not None:
        seq = int(np.asarray(seq))
        if seq in self._seen_seqs:
          # replayed batch whose original got through (worker-restart
          # replay, or a resumed epoch's re-produced prefix)
          self.replayed_discarded = getattr(self, 'replayed_discarded',
                                            0) + 1
          continue
        if seq in self._degraded_lost:
          # written off as lost, then arrived after all (the worker's
          # send raced its own death): the epoch accounting already
          # subtracted it — delivering now would end the epoch one
          # batch early and silently drop a different healthy batch
          continue
        self._seen_seqs.add(seq)
      return msg

  def _try_adopt_server(self, err) -> bool:
    """Elastic server failover (ISSUE 15, the hetero-parity
    satellite): a dead sampling server's producers are RECREATED on a
    survivor — same seed slice, same seed offset, fast-forwarded to
    the current epoch — so the epoch finishes with EXACTLY the
    expected batch set, byte-identical (the channel's (source, seq)
    dedup + source-routed replacement fetches absorb the re-produced
    prefix).  Opt-in via ``GLT_SHARD_DIR`` (the operator's
    declaration that every partition is re-loadable at a survivor —
    replicated host datasets serve it directly); absent that, or
    without a multi-server plan, returns False and the documented
    ``GLT_DEGRADED_OK`` ladder applies."""
    import time as _time
    from ..parallel.failover import shard_dir_from_env
    from ..parallel.partition_book import AdoptionRefusedError
    from ..telemetry.recorder import recorder
    if (shard_dir_from_env() is None
        or not hasattr(self._remote, 'adopt_server')
        or err.peer is None):
      return False
    from .dist_client import get_client
    client = get_client()
    if client is None:
      return False
    t0 = _time.monotonic()
    try:
      info = self._remote.adopt_server(client, int(err.peer))
    except AdoptionRefusedError as e:
      recorder.emit('peer.lost', peer=err.peer, peer_kind='server',
                    degraded=False, adopted=False,
                    refused=str(e)[:200])
      return False
    secs = _time.monotonic() - t0
    if info['recreated']:
      from ..telemetry.live import live
      live.counter('partition.adoptions_total').inc()
      live.gauge('partition.recovery_secs').set(secs)
      recorder.emit('partition.adopt', partition=int(err.peer),
                    survivor=int(info['survivor']),
                    version=len(getattr(self._remote, '_adopted', ())),
                    owed=int(info['owed']), secs=round(secs, 6),
                    scope='server')
    return True

  def _probe_servers(self) -> None:
    """Heartbeat every server this loader draws from (remote mode).
    Fetch-path errors carry the authoritative failure; the probe's job
    is the diagnostics trail — the last observed health of every peer
    is kept at ``self._peer_health`` and attached to the
    `PeerLostError` (``.peer_health``) when the epoch finally fails,
    so the log tells slow-peer from dead-peer without reconstruction."""
    import time as _time
    from .dist_client import get_client
    client = get_client()
    if client is None:
      return
    idxs = (self._remote.server_indices
            if hasattr(self._remote, 'server_indices')
            else [self._remote._server_idx])
    health = getattr(self, '_peer_health', None)
    if health is None:
      health = self._peer_health = {}
    for idx in idxs:
      hb = client.heartbeat(idx)
      health[idx] = {'at': round(_time.time(), 3),
                     'alive': hb is not None,
                     'producers': (hb or {}).get('producers')}

  # -- message -> static-shape Batch (reference `dist_loader.py:286-383`) --
  def _collate_fn(self, msg: SampleMessage):
    if int(np.asarray(msg.get('#IS_HETERO', 0))):
      return self._collate_hetero(msg)
    nc, ec = self.node_cap, self.edge_cap
    ids = msg['ids']
    c = len(ids)
    node = np.full(nc, INVALID_ID, np.int32)
    node[:c] = ids
    e = len(msg['rows'])
    if e > ec:
      # induced-subgraph messages can exceed the sampled-tree bound;
      # grow in power-of-two buckets so consumers see few shapes
      ec = next_power_of_two(e)
    edge_index = np.full((2, ec), INVALID_ID, np.int32)
    edge_index[0, :e] = msg['rows']
    edge_index[1, :e] = msg['cols']
    x = y = edge = edge_attr = None
    if 'nfeats' in msg:
      d = msg['nfeats'].shape[1]
      x = np.zeros((nc, d), msg['nfeats'].dtype)
      x[:c] = msg['nfeats']
    if 'nlabels' in msg:
      y = np.zeros(nc, msg['nlabels'].dtype)
      y[:c] = msg['nlabels']
    if 'eids' in msg:
      edge = np.full(ec, INVALID_ID, np.int64)
      edge[:e] = msg['eids']
    if 'efeats' in msg:
      de = msg['efeats'].shape[1]
      edge_attr = np.zeros((ec, de), msg['efeats'].dtype)
      edge_attr[:e] = msg['efeats']
    batch = np.full(self.batch_cap, INVALID_ID, np.int64)
    batch[:len(msg['batch'])] = msg['batch']
    out = Batch(
        x=x, y=y, edge_index=edge_index, edge_attr=edge_attr, node=node,
        node_mask=node >= 0, edge_mask=edge_index[0] >= 0, edge=edge,
        batch=batch, batch_size=self.batch_size,
        num_sampled_nodes=msg.get('num_sampled_nodes'),
        metadata=self._collate_metadata(msg))
    if self.to_device:
      out = jax.device_put(out)
    return out

  def _collate_hetero(self, msg: SampleMessage) -> HeteroBatch:
    """Flat hetero message -> static-shape `HeteroBatch` (the hetero
    arm of reference `dist_loader.py:286-383`, keys ``f'{type}.x'``
    etc.).  Every batch pads to the SAME per-type capacities so the
    training step compiles once."""
    node_d, nm_d, x_d, y_d = {}, {}, {}, {}
    md = {'seed_local': {}, 'num_sampled_nodes': {}}
    for nt in self.h_ntypes:
      cap = self.h_node_cap[nt]
      ids = msg.get(f'{nt}.ids')
      node = np.full(cap, INVALID_ID, np.int32)
      c = 0
      if ids is not None:
        c = len(ids)
        node[:c] = ids
      node_d[nt] = node
      nm_d[nt] = node >= 0
      feats = msg.get(f'{nt}.nfeats')
      if feats is not None:
        x = np.zeros((cap, feats.shape[1]), feats.dtype)
        x[:c] = feats
        x_d[nt] = x
      labels = msg.get(f'{nt}.nlabels')
      if labels is not None:
        y = np.zeros(cap, labels.dtype)
        y[:c] = labels
        y_d[nt] = y
      sl = msg.get(f'{nt}.seed_local')
      if sl is not None:
        out = np.full(self.h_seed_cap.get(nt, len(sl)), INVALID_ID,
                      np.int64)
        out[:len(sl)] = sl
        md['seed_local'][nt] = out
      ns = msg.get(f'{nt}.num_sampled')
      if ns is not None:
        md['num_sampled_nodes'][nt] = ns
    ei_d, em_d, edge_d = {}, {}, {}
    ea_d = {}
    for et, ecap in self.h_edge_cap.items():
      key = as_str(et)
      rows = msg.get(f'{key}.rows')
      edge_index = np.full((2, ecap), INVALID_ID, np.int32)
      # every batch carries the SAME edge_dict key set (padded when an
      # etype sampled nothing) so jitted consumers see one pytree
      # structure across the epoch
      ev = (np.full(ecap, INVALID_ID, np.int64)
            if self.with_edge else None)
      if rows is not None:
        e = len(rows)
        edge_index[0, :e] = rows
        edge_index[1, :e] = msg[f'{key}.cols']
        eids = msg.get(f'{key}.eids')
        if ev is not None and eids is not None:
          ev[:e] = eids
        efeats = msg.get(f'{key}.efeats')
        if efeats is not None:
          ea = np.zeros((ecap, efeats.shape[1]), efeats.dtype)
          ea[:e] = efeats
          ea_d[et] = ea
      if ev is not None:
        edge_d[et] = ev
      ei_d[et] = edge_index
      em_d[et] = edge_index[0] >= 0
    cfg = self.sampling_config
    seed_t = cfg.input_type
    batch_t = seed_t if isinstance(seed_t, str) else seed_t[0]
    batch = np.full(self.batch_cap, INVALID_ID, np.int64)
    batch[:len(msg['batch'])] = msg['batch']
    extra = self._collate_metadata(msg)
    extra.pop('seed_local', None)    # homo key; hetero built per type
    md.update(extra)
    if self.with_edge:
      md['edge_dict'] = edge_d
    out = HeteroBatch(
        x_dict=x_d, y_dict=y_d, edge_index_dict=ei_d, node_dict=node_d,
        edge_attr_dict=ea_d,
        node_mask_dict=nm_d, edge_mask_dict=em_d,
        batch_dict={batch_t: batch}, batch_size=self.batch_size,
        metadata=md)
    if self.to_device:
      out = jax.device_put(out)
    return out

  def _collate_metadata(self, msg: SampleMessage) -> dict:
    """Lift ``#META.*`` keys into batch metadata, statically padded so
    tail batches reuse the same compiled programs (the link/subgraph
    label contracts of reference `dist_loader.py:286-383`)."""
    md = {'seed_local': msg.get('seed_local')}
    cfg = self.sampling_config
    bs = self.batch_size
    explicit_mask = None
    for k, v in msg.items():
      if not k.startswith('#META.'):
        continue
      name = k[len('#META.'):]
      if name == 'edge_label_index':
        cap = cfg.label_cap(bs) if cfg else bs
        out = np.full((2, cap), INVALID_ID, np.int64)
        out[:, :v.shape[1]] = v
        md[name] = out
        md['edge_label_mask'] = np.arange(cap) < v.shape[1]
      elif name == 'edge_label':
        cap = cfg.label_cap(bs) if cfg else bs
        out = np.zeros(cap, v.dtype)
        out[:len(v)] = v
        md[name] = out
      elif name == 'edge_label_mask':
        # producer-supplied validity (strict-negative ok flags); folded
        # into the width-derived mask after the loop
        explicit_mask = np.asarray(v, bool)
      elif name in ('src_index', 'dst_pos_index', 'mapping'):
        out = np.full(bs, INVALID_ID, np.int64)
        out[:len(v)] = v
        md[name] = out
        if name == 'src_index':
          # seed validity, not emission width: padded tail slots carry
          # si = -1 and must read invalid (matches the mesh samplers)
          md['pair_mask'] = out >= 0
      elif name == 'dst_neg_index':
        amount = v.shape[1]
        out = np.full((bs, amount), INVALID_ID, np.int64)
        out[:len(v)] = v
        md[name] = out
      else:
        md[name] = v
    if explicit_mask is not None:
      cap = cfg.label_cap(bs) if cfg else bs
      padded = np.zeros(cap, bool)
      padded[:len(explicit_mask)] = explicit_mask
      base = md.get('edge_label_mask')
      md['edge_label_mask'] = padded if base is None else padded & base
    return md

  # -- DataPlaneState (utils.checkpoint): mid-epoch snapshot/resume --------
  def state_dict(self) -> dict:
    """Epoch cursor for the mp (subprocess-producer) mode: producer
    positions + the '#SEQ' stamps already delivered this epoch.  A
    resumed epoch re-produces from the same (epoch, shuffle) and the
    consumer discards the already-seen prefix — remaining batches are
    byte-identical (batch content is a function of (epoch, seq))."""
    if not isinstance(self.opts, MpDistSamplingWorkerOptions):
      raise ValueError(
          'DistLoader snapshots cover the mp producer mode; remote '
          "mode's producers live in the server process (snapshot "
          'there), and collocated mode has no durable position')
    seen = np.asarray(sorted(getattr(self, '_seen_seqs', ())), np.int64)
    return {'producer': self._producer.state_dict(), 'seen': seen,
            'expected': int(self._expected)}

  def load_state_dict(self, state: dict) -> None:
    if not isinstance(self.opts, MpDistSamplingWorkerOptions):
      raise ValueError('DistLoader snapshots cover the mp mode')
    self._producer.load_state_dict(state['producer'], mid_epoch=True)
    self._resume_state = {
        'seen': set(int(s) for s in np.asarray(state['seen'])),
        'expected': int(np.asarray(state['expected']))}

  def resume_epoch(self):
    """Finish the interrupted epoch (call after `load_state_dict`):
    the producer re-dispatches the same epoch, already-delivered seqs
    are discarded on arrival (counted in ``replayed_discarded``), and
    the returned iterator yields exactly the remaining batches —
    byte-identical to what an uninterrupted epoch would have
    produced.  (``iter(loader)`` afterwards starts the NEXT epoch;
    this iterator does not re-trigger the epoch protocol.)"""
    r = getattr(self, '_resume_state', None)
    if r is None:
      raise ValueError('resume_epoch() needs load_state_dict() first')
    self._resume_state = None
    self._seen_seqs = set(r['seen'])
    self._degraded_lost = set()
    self.replayed_discarded = 0
    expected = self._producer.produce_all(self.seeds,
                                          drop_last=self.drop_last)
    # the snapshot's expected wins when degraded mode had already
    # written batches off before the snapshot
    self._expected = min(expected, r['expected'])
    self._received = len(self._seen_seqs)
    return _ResumedEpochIterator(self)

  def shutdown(self) -> None:
    # idempotent: __del__ re-enters after an explicit shutdown, and a
    # second remote destroy against a since-departed server would
    # waste its one-shot teardown attempt on a dead socket
    if getattr(self, '_shutdown_done', False):
      return
    self._shutdown_done = True
    if self._producer is not None and hasattr(self._producer, 'shutdown'):
      self._producer.shutdown()
    if isinstance(self.opts, RemoteDistSamplingWorkerOptions):
      self._remote.destroy()
    if self.channel is not None:
      self.channel.close()

  def __del__(self):
    try:
      self.shutdown()
    except Exception:
      pass


class _ResumedEpochIterator:
  """Continues an interrupted epoch WITHOUT re-entering the loader's
  epoch protocol: ``for batch in loader.resume_epoch()`` must not hit
  `DistLoader.__iter__` (which would dispatch a fresh epoch over the
  one just resumed)."""

  def __init__(self, loader: 'DistLoader'):
    self._loader = loader

  def __iter__(self):
    return self

  def __next__(self):
    return DistLoader.__next__(self._loader)


class DistNeighborLoader(DistLoader):
  """Node-wise distributed loader (reference
  `distributed/dist_neighbor_loader.py:27-94`)."""


class DistLinkNeighborLoader(DistLoader):
  """Link-prediction distributed loader (reference
  `distributed/dist_link_neighbor_loader.py:30-153`): seed edges +
  negatives sampled in the producers, link-label metadata
  (``edge_label_index``/``edge_label`` or triplet indices) collated
  statically padded.

  Args:
    edge_label_index: ``[2, E]`` (or ``(rows, cols)``) seed edges.
    edge_label: optional integer labels (binary mode applies the
      reference's +1 shift: 0 becomes the negative class).
    neg_sampling: ``'binary'`` / ``'triplet'`` or
      ``(mode, amount)``.
  """

  def __init__(self, dataset, num_neighbors, edge_label_index,
               edge_label=None, neg_sampling=None, **kwargs):
    input_type = None
    if (isinstance(edge_label_index, (tuple, list))
        and len(edge_label_index) == 2
        and isinstance(edge_label_index[0], (tuple, list))
        and len(edge_label_index[0]) == 3
        and all(isinstance(t, str) for t in edge_label_index[0])):
      # hetero seeds: (edge_type, pairs) — the reference's hetero
      # `edge_label_index` contract (`loader/link_loader.py`)
      input_type, edge_label_index = edge_label_index
      input_type = tuple(input_type)
    if isinstance(edge_label_index, (tuple, list)):
      rows, cols = edge_label_index
    else:
      ei = np.asarray(edge_label_index)
      rows, cols = ei[0], ei[1]
    mode, amount = None, 1.0
    if neg_sampling is not None:
      if isinstance(neg_sampling, (tuple, list)):
        mode, amount = neg_sampling[0], float(neg_sampling[1])
      elif isinstance(neg_sampling, str):
        mode = neg_sampling
      else:  # NegativeSampling-like
        mode = neg_sampling.mode
        amount = float(neg_sampling.amount)
    cols_arr = [np.asarray(rows, np.int64), np.asarray(cols, np.int64)]
    if edge_label is not None:
      lab = np.asarray(edge_label, np.int64)
      if mode == 'binary':
        lab = lab + 1     # reference +1 shift (`link_loader.py:146-186`)
      cols_arr.append(lab)
    seeds = np.stack(cols_arr, axis=1)
    cfg = HostSamplingConfig(sampling_type='link', neg_mode=mode,
                             neg_amount=amount, input_type=input_type)
    super().__init__(dataset, num_neighbors, seeds,
                     sampling_config=cfg, **kwargs)


class DistSubGraphLoader(DistLoader):
  """Induced-subgraph distributed loader (reference
  `distributed/dist_subgraph_loader.py:28-89`): each batch message is
  the enclosing subgraph of its seed set, with ``mapping`` locating
  the seeds in the node table (SEAL-style)."""

  def __init__(self, dataset, num_neighbors, input_nodes, **kwargs):
    super().__init__(dataset, num_neighbors, input_nodes,
                     sampling_config=HostSamplingConfig(
                         sampling_type='subgraph'),
                     **kwargs)
