"""Parallel in-cluster partitioning.

Counterpart of reference `distributed/dist_random_partitioner.py`
(:129-538): when the full graph doesn't fit one machine, every rank
holds a *slice* of the inputs (a contiguous node-id range, the edges
whose owner endpoint falls in that range, and the features/labels of
that range), and the ranks cooperatively produce the exact on-disk
layout of the offline partitioner (`partition/base.py`) — each rank
computes and writes its own ``part{rank}`` directory, rank 0 writes
the partition books and META.

Redesign notes (vs the reference):
  * the reference's `DistPartitionManager` rides torch.RPC callees
    pushing chunk values to owners (`dist_random_partitioner.py:
    40-126`); here the same push protocol runs over the repo's socket
    RPC (`distributed/rpc.py`) — one `RpcServer` per rank and a
    rendezvous through rank 0 (bulk arrays ride pickle-protocol-5
    frames, which keep numpy buffers contiguous);
  * chunked streaming loops become one vectorized numpy pass per
    destination rank (slices are already memory-bounded by 1/world);
  * ``num_parts == world_size`` as in the reference: rank r *is*
    partition r.

Usage (every rank)::

    p = DistRandomPartitioner(
        out_dir, num_nodes, (rows, cols), feats, labels,
        rank=r, world_size=W, master_addr='10.0.0.1', master_port=5678)
    p.partition()   # blocks until the whole cluster is done

The node-id range of rank r is ``[r*N/W, (r+1)*N/W)``; ``edge_index``
is the slice of edges this rank holds (any subset — ownership is
decided by the partition book, not by who holds the edge), and
``edge_id_offset`` gives their global edge ids.
"""
from __future__ import annotations

import json
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

from .rpc import RpcClient, RpcServer


def node_range(rank: int, world_size: int, num_nodes: int) -> Tuple[int, int]:
  """Contiguous id range owned by ``rank`` (reference chunking,
  `dist_random_partitioner.py:256-290`)."""
  per = -(-num_nodes // world_size)
  lo = min(rank * per, num_nodes)
  return lo, min(lo + per, num_nodes)


class DistPartitionManager:
  """Rendezvous + bulk push/accumulate substrate for one rank.

  Reference `DistPartitionManager` (`dist_random_partitioner.py:
  40-126`) with its rpc callees mapped to socket-RPC handlers:

    * ``hello/addrs`` — rank-0 rendezvous: every rank registers its
      server address, then polls for the full address map;
    * ``put(tag, payload)`` — append a tensor-map payload to the named
      buffer on the receiving rank;
    * ``barrier(name)`` — rank-0-counted global barrier.
  """

  def __init__(self, rank: int, world_size: int,
               master_addr: str, master_port: int,
               host: str = '127.0.0.1', poll: float = 0.05):
    self.rank = rank
    self.world_size = world_size
    self.poll = poll
    self._buffers: Dict[str, List[dict]] = {}
    self._buf_lock = threading.Lock()
    self._barriers: Dict[str, set] = {}

    if rank != 0 and master_port <= 0:
      raise ValueError('non-zero ranks need the master\'s bound port')
    port = master_port if rank == 0 else 0
    self.server = RpcServer(host=host, port=port)
    self.server.register('put', self._on_put)
    if rank == 0:
      self._addrs: Dict[int, Tuple[str, int]] = {
          0: (master_addr, self.server.port)}
      self.server.register('hello', self._on_hello)
      self.server.register('addrs', self._on_addrs)
      self.server.register('barrier_enter', self._on_barrier_enter)
      self.server.register('barrier_done', self._on_barrier_done)
    self.server.start()
    # rank 0 talks to itself on whatever port it actually bound
    # (master_port=0 means ephemeral — then out-of-band distribution
    # of `self.server.port` to the other ranks is the caller's job).
    self.master = RpcClient(
        master_addr, self.server.port if rank == 0 else master_port)
    self._peers: Dict[int, RpcClient] = {}

  # -- handlers (run on the server threads) -------------------------------
  def _on_put(self, tag: str, payload: dict):
    with self._buf_lock:
      self._buffers.setdefault(tag, []).append(payload)
    return True

  def _on_hello(self, rank: int, addr: Tuple[str, int]):
    self._addrs[int(rank)] = tuple(addr)
    return True

  def _on_addrs(self):
    if len(self._addrs) < self.world_size:
      return None
    return dict(self._addrs)

  def _on_barrier_enter(self, name: str, rank: int):
    self._barriers.setdefault(name, set()).add(rank)
    return True

  def _on_barrier_done(self, name: str):
    return len(self._barriers.get(name, ())) >= self.world_size

  # -- client side --------------------------------------------------------
  def _master_request(self, deadline: float, name: str, *args):
    """Master RPC that tolerates the master not listening yet (ranks
    may start in any order)."""
    while True:
      try:
        return self.master.request(name, *args)
      except (ConnectionError, OSError):
        if time.monotonic() > deadline:
          raise
        time.sleep(self.poll)

  def rendezvous(self, timeout: float = 120.0):
    deadline = time.monotonic() + timeout
    if self.rank != 0:
      self._master_request(deadline, 'hello', self.rank,
                           (self.server.host, self.server.port))
    while True:
      addrs = (self._master_request(deadline, 'addrs')
               if self.rank != 0 else
               (self._addrs if len(self._addrs) >= self.world_size
                else None))
      if addrs:
        break
      if time.monotonic() > deadline:
        raise TimeoutError('partitioner rendezvous timed out')
      time.sleep(self.poll)
    for r, (h, p) in addrs.items():
      r = int(r)
      if r != self.rank:
        self._peers[r] = RpcClient(h, p)

  def put_to(self, rank: int, tag: str, payload: Dict[str, np.ndarray]):
    """Append ``payload`` to buffer ``tag`` on ``rank`` (self included)."""
    if rank == self.rank:
      self._on_put(tag, payload)
    else:
      self._peers[rank].request('put', tag, payload)

  def take(self, tag: str, expect: int, timeout: float = 600.0
           ) -> List[dict]:
    """Block until ``expect`` payloads arrived under ``tag``; pop them."""
    deadline = time.monotonic() + timeout
    while True:
      with self._buf_lock:
        got = self._buffers.get(tag, [])
        if len(got) >= expect:
          return self._buffers.pop(tag)
      if time.monotonic() > deadline:
        raise TimeoutError(f'waiting for {expect} payloads under {tag!r}, '
                           f'have {len(got)}')
      time.sleep(self.poll)

  def barrier(self, name: str, timeout: float = 600.0):
    self.master.request('barrier_enter', name, self.rank)
    deadline = time.monotonic() + timeout
    while not self.master.request('barrier_done', name):
      if time.monotonic() > deadline:
        raise TimeoutError(f'barrier {name!r} timed out')
      time.sleep(self.poll)

  def shutdown(self):
    for c in self._peers.values():
      c.close()
    self.master.close()
    self.server.shutdown()


class DistRandomPartitioner:
  """Random partitioning computed by the cluster itself.

  Every rank holds 1/world of the inputs and writes partition
  ``rank``; the resulting directory is byte-compatible with
  `partition.load_partition` / `DistDataset.load`.

  Args:
    output_dir: shared (or per-rank local) output root.
    num_nodes: GLOBAL node count.
    edge_index: ``(rows, cols)`` — the slice of edges this rank holds.
    node_feat: ``[hi-lo, D]`` features of this rank's node range.
    node_label: ``[hi-lo]`` labels of this rank's node range.
    edge_id_offset: global id of this rank's first edge; this rank's
      edges get ids ``[offset, offset+len)``.
    rank / world_size / master_addr / master_port: cluster identity;
      rank 0's server doubles as the rendezvous point.
    seed: partition-book seed — all ranks derive the same book chunk
      deterministically from (seed, owner-rank).
  """

  def __init__(self, output_dir, num_nodes: int,
               edge_index: Tuple[np.ndarray, np.ndarray],
               node_feat: Optional[np.ndarray] = None,
               node_label: Optional[np.ndarray] = None,
               *, rank: int, world_size: int,
               master_addr: str = '127.0.0.1', master_port: int = 0,
               edge_id_offset: int = 0,
               edge_assign: str = 'by_src', seed: int = 0,
               host: str = '127.0.0.1'):
    self.output_dir = Path(output_dir)
    self.num_nodes = int(num_nodes)
    self.rows = np.asarray(edge_index[0], dtype=np.int64)
    self.cols = np.asarray(edge_index[1], dtype=np.int64)
    self.node_feat = node_feat
    self.node_label = node_label
    self.rank = rank
    self.world_size = world_size
    self.num_parts = world_size
    self.edge_id_offset = int(edge_id_offset)
    assert edge_assign in ('by_src', 'by_dst')
    self.edge_assign = edge_assign
    self.seed = seed
    self._mgr = DistPartitionManager(rank, world_size, master_addr,
                                     master_port, host=host)

  # -- the pipeline -------------------------------------------------------
  def partition(self) -> np.ndarray:
    """Run the cooperative pipeline; returns the full node partition
    book (every rank gets a copy)."""
    mgr = self._mgr
    try:
      mgr.rendezvous()
      node_pb = self._build_node_pb()
      self._exchange_graph(node_pb)
      if self.node_feat is not None:
        self._exchange_rows('node_feat', self.node_feat, node_pb)
      if self.node_label is not None:
        self._exchange_rows('node_label', self.node_label, node_pb)
      self._write(node_pb)
      mgr.barrier('done')
      # acked shutdown: rank 0's server is the barrier master, so it
      # must outlive every other rank's last 'barrier_done' poll —
      # each rank confirms it saw 'done' before rank 0 tears down.
      if self.rank != 0:
        mgr.master.request('barrier_enter', 'bye', self.rank)
      else:
        deadline = time.monotonic() + 60.0
        while len(mgr._barriers.get('bye', ())) < self.world_size - 1:
          if time.monotonic() > deadline:
            break  # stragglers already have their results; don't hang
          time.sleep(mgr.poll)
      return node_pb
    finally:
      mgr.shutdown()

  def _build_node_pb(self) -> np.ndarray:
    """Deterministic random book: every rank computes every chunk from
    (seed, chunk-owner), so no pb exchange is needed — the reference
    instead rpc-syncs chunk assignments (`dist_random_partitioner.py:
    292-340`); deriving from the shared seed removes that round."""
    pb = np.empty((self.num_nodes,), dtype=np.int8)
    for r in range(self.world_size):
      lo, hi = node_range(r, self.world_size, self.num_nodes)
      rng = np.random.default_rng((self.seed, r))
      pb[lo:hi] = rng.integers(0, self.num_parts, hi - lo, dtype=np.int8)
    return pb

  def _exchange_graph(self, node_pb: np.ndarray):
    owner_end = self.rows if self.edge_assign == 'by_src' else self.cols
    owner = node_pb[owner_end]
    eids = self.edge_id_offset + np.arange(len(self.rows), dtype=np.int64)
    for p in range(self.num_parts):
      sel = owner == p
      self._mgr.put_to(p, 'graph', {
          'rows': self.rows[sel], 'cols': self.cols[sel],
          'eids': eids[sel]})
    # rank 0 assembles the global edge book from everyone's owners.
    self._mgr.put_to(0, 'edge_pb', {'eids': eids,
                                    'owner': owner.astype(np.int8)})

  def _exchange_rows(self, tag: str, arr: np.ndarray, node_pb: np.ndarray):
    lo, hi = node_range(self.rank, self.world_size, self.num_nodes)
    arr = np.asarray(arr)
    assert arr.shape[0] == hi - lo, (
        f'{tag}: expected rows for node range [{lo},{hi}), '
        f'got {arr.shape[0]}')
    ids = np.arange(lo, hi, dtype=np.int64)
    pb = node_pb[lo:hi]
    for p in range(self.num_parts):
      sel = pb == p
      self._mgr.put_to(p, tag, {'ids': ids[sel], 'vals': arr[sel]})

  def _write(self, node_pb: np.ndarray):
    mgr = self._mgr
    pdir = self.output_dir / f'part{self.rank}'

    graph_parts = mgr.take('graph', self.world_size)
    rows = np.concatenate([g['rows'] for g in graph_parts])
    cols = np.concatenate([g['cols'] for g in graph_parts])
    eids = np.concatenate([g['eids'] for g in graph_parts])
    order = np.argsort(eids, kind='stable')
    gdir = pdir / 'graph'
    gdir.mkdir(parents=True, exist_ok=True)
    np.save(gdir / 'rows.npy', rows[order])
    np.save(gdir / 'cols.npy', cols[order])
    np.save(gdir / 'eids.npy', eids[order])

    if self.node_feat is not None:
      self._write_rows('node_feat', 'feats.npy', pdir)
    if self.node_label is not None:
      self._write_rows('node_label', 'labels.npy', pdir)

    if self.rank == 0:
      np.save(self.output_dir / 'node_pb.npy', node_pb)
      pbs = mgr.take('edge_pb', self.world_size)
      all_eids = np.concatenate([p['eids'] for p in pbs])
      all_owner = np.concatenate([p['owner'] for p in pbs])
      if not np.array_equal(np.sort(all_eids), np.arange(len(all_eids))):
        raise ValueError(
            'global edge ids are not a disjoint cover of '
            f'range({len(all_eids)}) — check each rank\'s '
            'edge_id_offset (overlap or gap)')
      edge_pb = np.empty((len(all_eids),), dtype=np.int8)
      edge_pb[all_eids] = all_owner
      np.save(self.output_dir / 'edge_pb.npy', edge_pb)
      meta = {'num_parts': self.num_parts, 'hetero': False,
              'edge_assign': self.edge_assign,
              'num_nodes': self.num_nodes}
      with open(self.output_dir / 'META.json', 'w') as f:
        json.dump(meta, f, indent=2)

  def _write_rows(self, tag: str, fname: str, pdir: Path):
    parts = self._mgr.take(tag, self.world_size)
    ids = np.concatenate([p['ids'] for p in parts])
    vals = np.concatenate([p['vals'] for p in parts])
    order = np.argsort(ids, kind='stable')
    d = pdir / tag
    d.mkdir(parents=True, exist_ok=True)
    np.save(d / fname, vals[order])
    np.save(d / 'ids.npy', ids[order])
