"""Checksummed, seqno-stamped write-ahead log for edge-insert events.

The durability half of the streaming ingestion plane (ISSUE 14): every
edge-insert batch is appended here BEFORE it touches the in-memory
delta-CSR, so a crash at any point between "the client was told ok"
and "the published graph holds the edge" is recoverable by replay.
The discipline is the same exactly-once, byte-identical-replay
contract as the RPC replay cache (PR 4) and the data-plane snapshots
(PR 6), applied to graph mutations:

  * **atomic append** — one record is one ``write()`` of a fully
    assembled buffer followed by flush+fsync; a record is either
    wholly in the file or detectably torn at the tail.
  * **torn-tail detection** — every record carries a CRC32 of its
    payload and a length; :meth:`WriteAheadLog.open` scans the file
    and TRUNCATES back to the last whole record when the tail is
    short or fails its checksum (the kill-mid-append carcass), so a
    restarted process replays exactly the whole-record prefix — no
    half-applied event batch, ever (``ingest.wal_truncate`` event).
  * **replay idempotent by seqno** — records are stamped with a
    monotone sequence number; recovery replays only records with
    ``seqno > applied_seqno`` (the compacted base's watermark), so a
    crash between a compaction snapshot and the WAL reset can never
    double-apply.

Record layout (little-endian)::

    [u32 crc32(payload)] [u64 seqno] [u32 nbytes] [payload]
    payload := [u32 count] [src int64*count] [dst int64*count]

File header: the 8-byte magic ``GLTWAL01`` followed by a u64 **base
seqno** — the highest seqno ever dropped by a compaction reset, so
sequence numbers stay globally monotone across resets (a fresh
append after a full compaction must not reuse a seqno the snapshot
watermark already covers).  A foreign or header-torn file is refused
loudly, not replayed as empty.

Chaos site ``ingest.wal`` (`testing.chaos`): ``fail`` raises before
any byte lands; ``truncate`` writes a partial record and raises — the
torn tail the next open must absorb.

Env knob: ``GLT_INGEST_WAL_DIR`` — the log directory (the ingest
pipeline also keeps its compacted-base snapshots under it).
"""
from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass
from pathlib import Path
from typing import Iterator, Optional

import numpy as np

WAL_DIR_ENV = 'GLT_INGEST_WAL_DIR'

_MAGIC = b'GLTWAL01'
_BASE = struct.Struct('<QQ')          # base seqno, base events —
# the sequence position and cumulative event count covered by records
# a compaction reset dropped (both survive resets, keeping seqnos and
# the lifetime event count globally monotone)
_HEAD_LEN = len(_MAGIC) + _BASE.size
_HDR = struct.Struct('<IQI')          # crc32(payload), seqno, nbytes


def wal_dir_from_env() -> Optional[str]:
  return os.environ.get(WAL_DIR_ENV) or None


def _fsync_dir(path: Path) -> None:
  """fsync a DIRECTORY so a just-created/renamed entry survives power
  loss (file-content fsync alone does not pin the dir entry)."""
  try:
    fd = os.open(path, os.O_RDONLY)
  except OSError:          # platform without dir-open support
    return
  try:
    os.fsync(fd)
  finally:
    os.close(fd)


class WalCorruptionError(RuntimeError):
  """The log is unreadable beyond recovery (bad magic / a foreign
  file) — torn TAILS are absorbed by truncation, a bad HEAD is not."""


@dataclass(frozen=True)
class WalRecord:
  """One replayable edge-insert batch."""
  seqno: int
  src: np.ndarray
  dst: np.ndarray

  @property
  def count(self) -> int:
    return int(self.src.shape[0])


def _encode_payload(src: np.ndarray, dst: np.ndarray) -> bytes:
  src = np.ascontiguousarray(src, np.int64)
  dst = np.ascontiguousarray(dst, np.int64)
  if src.shape != dst.shape or src.ndim != 1:
    raise ValueError(
        f'src/dst must be equal-length 1-D arrays, got {src.shape} '
        f'vs {dst.shape}')
  return (struct.pack('<I', len(src)) + src.tobytes() + dst.tobytes())


def _decode_payload(payload: bytes) -> tuple:
  (count,) = struct.unpack_from('<I', payload, 0)
  need = 4 + 16 * count
  if len(payload) != need:
    raise ValueError(f'payload holds {len(payload)} bytes, '
                     f'count={count} needs {need}')
  src = np.frombuffer(payload, np.int64, count, offset=4).copy()
  dst = np.frombuffer(payload, np.int64, count, offset=4 + 8 * count
                      ).copy()
  return src, dst


class WriteAheadLog:
  """One durable, replayable event log under ``directory/wal.log``.

  :meth:`open` (called by the constructor) performs the recovery
  scan: validate the header, walk the records, truncate a torn tail,
  and position the append cursor + next seqno after the last whole
  record.  All mutating state is guarded for the glint ``guarded-by``
  contract — appenders may race a scraper reading the counters.
  """

  def __init__(self, directory: Optional[str] = None,
               fsync: bool = True):
    import threading
    directory = directory or wal_dir_from_env()
    if directory is None:
      raise ValueError('WriteAheadLog needs a directory (argument or '
                       f'{WAL_DIR_ENV})')
    self.directory = Path(directory)
    self.directory.mkdir(parents=True, exist_ok=True)
    self.path = self.directory / 'wal.log'
    self.fsync = bool(fsync)
    self._lock = threading.Lock()
    self._file = None          # guarded-by: self._lock — persistent
    # append handle (one open per recovery scan, not per record)
    self._last_seqno = 0       # guarded-by: self._lock
    self._total_events = 0     # guarded-by: self._lock
    self._base_events = 0      # guarded-by: self._lock
    self._end_offset = 0       # guarded-by: self._lock
    self._truncations = 0      # guarded-by: self._lock
    self.open()
    # memory accounting (ISSUE 17): the durable bill is the cursor
    # position (valid bytes), not the file size — a torn tail awaiting
    # truncation is not retained state
    from ..telemetry.memaccount import register_tier
    register_tier('wal', lambda: int(self._end_offset))

  # -- recovery scan --------------------------------------------------------
  def open(self) -> None:
    """Scan the log, absorb a torn tail, position the cursor.  Safe
    to call again (a re-open re-derives the counters from disk)."""
    with self._lock:
      self._open_locked()

  def _open_locked(self) -> None:
    if self._file is not None:
      self._file.close()
      self._file = None
    if not self.path.exists():
      with open(self.path, 'wb') as f:
        f.write(_MAGIC + _BASE.pack(0, 0))
        f.flush()
        if self.fsync:
          os.fsync(f.fileno())
      if self.fsync:           # pin the new dir entry: an acked
        _fsync_dir(self.directory)  # append must survive power loss
      self._file = open(self.path, 'r+b')
      self._last_seqno = 0
      self._total_events = 0
      self._base_events = 0
      self._end_offset = _HEAD_LEN
      return
    blob = self.path.read_bytes()
    if len(blob) < _HEAD_LEN or blob[:len(_MAGIC)] != _MAGIC:
      raise WalCorruptionError(
          f'{self.path} does not start with the WAL header — '
          'refusing to replay a foreign or header-torn file')
    base, base_events = _BASE.unpack_from(blob, len(_MAGIC))
    off = _HEAD_LEN
    last_seqno = int(base)
    self._base_events = int(base_events)
    events = 0
    good_end = off
    torn = False
    while off < len(blob):
      if off + _HDR.size > len(blob):
        torn = True
        break
      crc, seqno, nbytes = _HDR.unpack_from(blob, off)
      payload = blob[off + _HDR.size: off + _HDR.size + nbytes]
      if len(payload) != nbytes or zlib.crc32(payload) != crc:
        torn = True
        break
      try:
        src, _dst = _decode_payload(payload)
      except ValueError:
        torn = True
        break
      last_seqno = seqno
      events += len(src)
      off += _HDR.size + nbytes
      good_end = off
    self._file = open(self.path, 'r+b')
    if torn:
      dropped = len(blob) - good_end
      self._file.truncate(good_end)
      self._file.flush()
      if self.fsync:
        os.fsync(self._file.fileno())
      self._truncations += 1
      from ..telemetry.recorder import recorder
      recorder.emit('ingest.wal_truncate', path=str(self.path),
                    offset=int(good_end), dropped_bytes=int(dropped),
                    last_seqno=int(last_seqno))
    self._last_seqno = last_seqno
    self._total_events = events
    self._end_offset = good_end

  # -- write side -----------------------------------------------------------
  def append(self, src, dst) -> int:
    """Durably append one edge-insert batch; returns its seqno.

    The record is assembled fully in memory and lands in ONE write +
    flush(+fsync) at the scanned end offset — appending after a
    recovered torn tail overwrites the carcass bytes, never splices
    into them.  Chaos ``ingest.wal``: ``fail`` raises with the log
    untouched; ``truncate`` lands HALF the record then raises (the
    kill-mid-append the next open truncates away).
    """
    from ..testing import chaos
    payload = _encode_payload(np.asarray(src), np.asarray(dst))
    actions = chaos.ingest_wal_faults('append')
    with self._lock:
      seqno = self._last_seqno + 1
      rec = _HDR.pack(zlib.crc32(payload), seqno, len(payload)) \
          + payload
      torn = 'truncate' in actions
      f = self._file
      f.seek(self._end_offset)
      f.write(rec[:max(len(rec) // 2, 1)] if torn else rec)
      f.flush()
      if self.fsync:
        os.fsync(f.fileno())
      if torn:
        raise chaos.InjectedFault(
            f'injected torn WAL append (seqno {seqno}: half a record '
            'on disk, process dies before the rest)')
      self._last_seqno = seqno
      self._total_events += len(np.asarray(src))
      self._end_offset += len(rec)
      return seqno

  def reset_to(self, seqno: int) -> None:
    """Drop every record with ``seqno <= watermark`` (the compaction
    epilogue: those events are durably inside the compacted base).
    The watermark is baked into the new header as the base seqno, so
    later appends continue the global sequence instead of reusing
    numbers the snapshot already covers.  Atomic: survivors are
    rewritten to a tmp file and renamed over the log — a kill
    mid-reset leaves the OLD log, whose extra records the seqno
    watermark makes harmless on replay."""
    seqno = int(seqno)
    keep = [rec for rec in self.replay() if rec.seqno > seqno]
    with self._lock:
      lifetime = self._base_events + self._total_events
    base_events = lifetime - sum(rec.count for rec in keep)
    tmp = self.path.with_suffix('.log.tmp')
    with open(tmp, 'wb') as f:
      f.write(_MAGIC + _BASE.pack(seqno, base_events))
      for rec in keep:
        payload = _encode_payload(rec.src, rec.dst)
        f.write(_HDR.pack(zlib.crc32(payload), rec.seqno,
                          len(payload)) + payload)
      f.flush()
      if self.fsync:
        os.fsync(f.fileno())
    os.replace(tmp, self.path)
    if self.fsync:
      _fsync_dir(self.directory)   # pin the rename itself
    with self._lock:
      self._open_locked()

  def close(self) -> None:
    """Release the persistent append handle (the log stays valid on
    disk; a later :meth:`open` re-acquires it)."""
    with self._lock:
      if self._file is not None:
        self._file.close()
        self._file = None

  # -- read side ------------------------------------------------------------
  def replay(self, after_seqno: int = 0) -> Iterator[WalRecord]:
    """Yield whole records with ``seqno > after_seqno`` in log order.
    Reads the scanned prefix only — a tail appended mid-iteration by
    another thread is the NEXT replay's business."""
    with self._lock:
      end = self._end_offset
    blob = self.path.read_bytes()[:end]
    off = _HEAD_LEN
    while off + _HDR.size <= len(blob):
      crc, seqno, nbytes = _HDR.unpack_from(blob, off)
      payload = blob[off + _HDR.size: off + _HDR.size + nbytes]
      if len(payload) != nbytes or zlib.crc32(payload) != crc:
        break                       # scanned end moved under us
      off += _HDR.size + nbytes
      if seqno <= after_seqno:
        continue
      src, dst = _decode_payload(payload)
      yield WalRecord(seqno=int(seqno), src=src, dst=dst)

  # -- counters -------------------------------------------------------------
  @property
  def last_seqno(self) -> int:
    with self._lock:
      return self._last_seqno

  @property
  def total_events(self) -> int:
    """Events across every whole record currently in the log."""
    with self._lock:
      return self._total_events

  @property
  def lifetime_events(self) -> int:
    """Events ever durably appended to this log, compaction resets
    included (the monotone appended-side of the lag gauge)."""
    with self._lock:
      return self._base_events + self._total_events

  @property
  def truncations(self) -> int:
    """Torn tails absorbed by this process's opens."""
    with self._lock:
      return self._truncations

  def stats(self) -> dict:
    with self._lock:
      return {'last_seqno': self._last_seqno,
              'total_events': self._total_events,
              'lifetime_events': self._base_events + self._total_events,
              'bytes': self._end_offset,
              'truncations': self._truncations}
