"""Streaming graph ingestion (ISSUE 14): WAL-backed delta-CSR with
version-fenced, RCU-published graph views — mutation-safe serving and
sampling while the graph itself is moving.

  * `wal` — checksummed, seqno-stamped write-ahead log (atomic
    append, torn-tail truncation, idempotent replay);
  * `delta` — delta-CSR segments merged at chunk seams, published
    behind a monotone ``graph_version`` (`StreamingGraph.pin` gives a
    reader one immutable view per dispatch);
  * `ingest` — the crash-consistent pipeline (log -> apply ->
    publish -> compact) with live metrics, healthz and post-mortem
    coverage.
"""
from .delta import DeltaSegment, GraphView, StreamingGraph, merge_delta_csr
from .ingest import IngestPipeline, compact_every_from_env, max_lag_from_env
from .wal import WalCorruptionError, WalRecord, WriteAheadLog, wal_dir_from_env

__all__ = [
    'DeltaSegment', 'GraphView', 'StreamingGraph', 'merge_delta_csr',
    'IngestPipeline', 'compact_every_from_env', 'max_lag_from_env',
    'WalCorruptionError', 'WalRecord', 'WriteAheadLog',
    'wal_dir_from_env',
]
