"""Delta-CSR segments + RCU-published graph versions.

The mutation half of the streaming ingestion plane (ISSUE 14).  Every
static structure in this repo — the sort-based sampling kernels, the
serving engine's warm bucket executables, the GNS bitmask, the fused
chunk loops — assumes the CSR it was handed never changes.  This
module makes change safe by never changing anything a reader holds:

  * **delta segments** — each applied edge-insert batch is one
    :class:`DeltaSegment` (the "chunk seam" merge unit);
  * **merge at seams** — :func:`merge_delta_csr` folds a segment into
    the base CSR touching only the DIRTY rows (one vectorized shift
    of the clean bulk + a per-dirty-row stable sort), producing
    arrays byte-identical to `utils.topo.coo_to_csr` over the full
    event-ordered edge list — so a quiesced streamed graph is
    indistinguishable from the same graph loaded statically (pinned
    by tests);
  * **RCU publish** — each merge lands as a NEW immutable
    :class:`GraphView` behind a monotonically increasing
    ``graph_version``; readers :meth:`StreamingGraph.pin` one view
    for the duration of a dispatch and can never observe a torn
    graph — writers replace the reference, they never mutate what a
    pinned view points at.

**Shape stability.**  Device consumers (the serving bucket programs,
the mesh steps) compile against array SHAPES; a graph that grew one
edge must not cost a recompile.  Published device indices ride a
power-of-two-padded buffer (``reserve_edges`` floors the initial
capacity); the shape changes only when the edge count crosses a
power of two — logarithmically many recompiles over any growth, the
same INVALID_ID-padding idiom as the serving bucket ladder.  The
padded tail is never read: every kernel bounds its window reads by
``indptr``.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np

from ..utils.padding import next_power_of_two
from ..utils.topo import coo_to_csr, ptr2ind


@dataclass(frozen=True)
class DeltaSegment:
  """One applied edge-insert batch (the chunk-seam merge unit).
  ``eids`` are the global event positions — the same consecutive ids
  `data.topology.CSRTopo` fabricates, so streamed and static edge
  identity agree."""
  src: np.ndarray
  dst: np.ndarray
  eids: np.ndarray

  @property
  def count(self) -> int:
    return int(self.src.shape[0])


def merge_delta_csr(indptr: np.ndarray, indices: np.ndarray,
                    eids: np.ndarray, seg: DeltaSegment
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
  """Fold one delta segment into a sorted CSR.

  Byte-identity contract: the result equals
  ``coo_to_csr(base_coo ++ segment_coo)`` — the base edges keep their
  within-row order, segment edges append in event order, and each
  DIRTY row is re-sorted by column with a STABLE sort (matching
  `coo_to_csr`'s stable lexsort, so duplicate columns tie-break by
  event order).  Clean rows move by one vectorized shift; the
  per-row python loop runs only over the segment's distinct source
  rows (the batch-sized dirty set, not the graph).
  """
  num_nodes = len(indptr) - 1
  src = np.asarray(seg.src, np.int64)
  if src.size and (src.min() < 0 or src.max() >= num_nodes):
    raise ValueError(
        f'delta source ids out of range for num_nodes={num_nodes}')
  add = np.bincount(src, minlength=num_nodes).astype(np.int64)
  new_indptr = np.zeros(num_nodes + 1, np.int64)
  np.cumsum(np.diff(indptr) + add, out=new_indptr[1:])
  e_new = int(new_indptr[-1])
  new_indices = np.empty(e_new, indices.dtype)
  new_eids = np.empty(e_new, eids.dtype)
  # shift the whole base in one scatter: edge at old position j of row
  # r lands at j + (new_indptr[r] - indptr[r])
  if len(indices):
    rows_of = ptr2ind(indptr)
    pos = np.arange(len(indices)) + (new_indptr[:-1] - indptr[:-1]
                                     )[rows_of]
    new_indices[pos] = indices
    new_eids[pos] = eids
  # segment edges at each dirty row's tail, in event order
  order = np.argsort(src, kind='stable')
  tail_base = new_indptr[src[order]] + np.diff(indptr)[src[order]]
  tail_off = np.arange(len(src)) - np.concatenate(
      [[0], np.cumsum(add)])[src[order]]
  tail_pos = tail_base + tail_off
  new_indices[tail_pos] = np.asarray(seg.dst)[order].astype(
      new_indices.dtype)
  new_eids[tail_pos] = np.asarray(seg.eids)[order].astype(
      new_eids.dtype)
  # re-sort only the dirty rows (stable: base order + event order are
  # both preserved among equal columns, = coo_to_csr's lexsort)
  for r in np.unique(src):
    lo, hi = int(new_indptr[r]), int(new_indptr[r + 1])
    sl = new_indices[lo:hi]
    perm = np.argsort(sl, kind='stable')
    new_indices[lo:hi] = sl[perm]
    new_eids[lo:hi] = new_eids[lo:hi][perm]
  return new_indptr, new_indices, new_eids


@dataclass(frozen=True)
class GraphView:
  """One immutable published graph version.

  ``indptr`` / ``indices`` / ``edge_ids`` are host arrays trimmed to
  the real edge count; ``indptr_dev`` / ``indices_dev`` are the
  device twins with ``indices_dev`` power-of-two padded (tail filled
  with 0 — a valid row index that no kernel ever dereferences, since
  reads are ``indptr``-bounded and masked).  A reader pins ONE view
  per dispatch; everything it touches through the view is frozen.
  """
  version: int
  indptr: np.ndarray
  indices: np.ndarray
  edge_ids: np.ndarray
  indptr_dev: object = field(repr=False, default=None)
  indices_dev: object = field(repr=False, default=None)

  @property
  def num_nodes(self) -> int:
    return len(self.indptr) - 1

  @property
  def num_edges(self) -> int:
    return int(self.indices.shape[0])

  def as_topo(self):
    """A `data.topology`-shaped host topology over this view (no
    re-sort: the view is already canonical sorted-CSR).  For the
    single-chip samplers and byte-identity tests."""
    from ..data.topology import CSRTopo
    topo = CSRTopo.__new__(CSRTopo)
    topo._indptr = self.indptr
    topo._indices = self.indices.astype(np.int32, copy=False)
    topo._edge_ids = self.edge_ids
    return topo

  def as_graph(self):
    """A device `data.graph.Graph` over THIS view's device arrays —
    what `Dataset.attach_stream` hands the samplers.  The padded
    indices buffer is shared with the serving engine's programs, so
    one publish feeds every reader."""
    from ..data.graph import Graph
    return Graph.from_device_arrays(self.indptr_dev, self.indices_dev)


class StreamingGraph:
  """A mutable graph publishing immutable `GraphView` versions.

  Writers: :meth:`apply_events` appends one delta segment and
  publishes the merged CSR as version ``N+1`` (RCU: the previous
  view stays valid for whoever pinned it).  Readers: :meth:`pin`
  returns the current view — one attribute read of an immutable
  object, safe from any thread, no lock on the read path.

  Args:
    indptr/indices/edge_ids: the base CSR (canonical sorted form —
      build through `CSRTopo`/`coo_to_csr` first).
    num_nodes: fixed node universe (edge inserts only — ISSUE 14;
      node inserts are follow-on work, see benchmarks/README r15).
    reserve_edges: floor for the padded device-indices capacity; size
      it to the expected growth so steady-state ingest publishes at
      ONE shape and the warm serving executables stay warm.
    device: build device twins of every published view (on by
      default; host-only consumers may pass ``device=False``).
  """

  def __init__(self, indptr, indices, edge_ids=None,
               num_nodes: Optional[int] = None,
               reserve_edges: int = 0, device: bool = True):
    indptr = np.asarray(indptr, np.int64)
    indices = np.asarray(indices)
    if num_nodes is not None and len(indptr) - 1 != int(num_nodes):
      raise ValueError(
          f'indptr implies {len(indptr) - 1} nodes, '
          f'num_nodes={num_nodes} was given')
    if edge_ids is None:
      edge_ids = np.arange(len(indices), dtype=np.int64)
    self._lock = threading.Lock()
    self._device = bool(device)
    self._edge_cap = next_power_of_two(
        max(int(reserve_edges), len(indices), 1))
    self._num_events = len(indices)          # guarded-by: self._lock
    self._view: GraphView = self._build_view(
        1, indptr, np.asarray(indices), np.asarray(edge_ids, np.int64))
    # memory accounting (ISSUE 17): host CSR arrays of the published
    # view + the padded device twins (reads the LIVE view, so tier
    # bytes track publishes without any hook in the write path)
    from ..telemetry.memaccount import register_tier

    def _stream_bytes():
      v = self._view
      total = 0
      for arr in (v.indptr, v.indices, v.edge_ids,
                  v.indptr_dev, v.indices_dev):
        total += int(getattr(arr, 'nbytes', 0) or 0)
      return total

    register_tier('streaming', _stream_bytes)

  def _build_view(self, version: int, indptr, indices, eids
                  ) -> GraphView:
    indptr_dev = indices_dev = None
    if self._device:
      import jax.numpy as jnp
      if len(indices) > self._edge_cap:
        self._edge_cap = next_power_of_two(len(indices))
      padded = np.zeros(self._edge_cap, np.int32)
      padded[:len(indices)] = indices
      indptr_dev = jnp.asarray(indptr.astype(
          np.int32 if int(indptr[-1]) < np.iinfo(np.int32).max
          else np.int64))
      indices_dev = jnp.asarray(padded)
    return GraphView(version=version, indptr=indptr,
                     indices=np.asarray(indices),
                     edge_ids=np.asarray(eids, np.int64),
                     indptr_dev=indptr_dev, indices_dev=indices_dev)

  # -- read side (lock-free) -------------------------------------------------
  def pin(self) -> GraphView:
    """The current published view.  Immutable — hold it for the whole
    dispatch and every read is from exactly one ``graph_version``."""
    return self._view

  @property
  def version(self) -> int:
    return self._view.version

  @property
  def num_nodes(self) -> int:
    return self._view.num_nodes

  @property
  def num_edges(self) -> int:
    return self._view.num_edges

  @property
  def edge_capacity(self) -> int:
    """Current padded device-indices capacity (a growth past it is
    the one event that changes a compiled consumer's shape)."""
    return self._edge_cap

  # -- write side ------------------------------------------------------------
  def apply_events(self, src, dst) -> GraphView:
    """Merge one edge-insert batch and publish it as the next
    version.  The merge builds entirely NEW arrays; the swap is one
    reference assignment under the writer lock — a concurrent reader
    holds either the old complete view or the new complete view."""
    src = np.asarray(src, np.int64).reshape(-1)
    dst = np.asarray(dst, np.int64).reshape(-1)
    if dst.size and (dst.min() < 0 or dst.max() >= self.num_nodes):
      # src is range-checked by the merge (it indexes indptr); dst
      # must be checked HERE — an out-of-range neighbor id would
      # publish cleanly and then read garbage at feature-gather time
      raise ValueError(
          f'delta destination ids out of range for '
          f'num_nodes={self.num_nodes}')
    with self._lock:
      prev = self._view
      seg = DeltaSegment(
          src=src, dst=dst,
          eids=np.arange(self._num_events,
                         self._num_events + len(src), dtype=np.int64))
      merged = self._merge_device(prev, seg)
      if merged is None:
        merged = merge_delta_csr(
            prev.indptr, prev.indices, prev.edge_ids, seg)
      new_indptr, new_indices, new_eids = merged
      view = self._build_view(prev.version + 1, new_indptr,
                              new_indices, new_eids)
      self._num_events += len(src)
      self._view = view
      return view

  def _merge_device(self, prev: GraphView, seg: DeltaSegment):
    """The r19 Pallas merge path: ``GLT_PALLAS_DELTA`` gates the
    rank-kernel merge (`ops.pallas_delta`), byte-identical to
    `merge_delta_csr` by contract; any disqualifying shape or
    lowering gap falls back to the host merge (``None`` return) with
    a ``pallas.fallback`` event — the fault-free default path never
    imports jax from here."""
    import os
    if os.environ.get('GLT_PALLAS_DELTA', '').strip().lower() not in (
        '1', 'true', 'on', 'yes'):
      return None
    from ..telemetry.recorder import recorder
    try:
      from ..ops.pallas_delta import merge_delta_csr_device
      merged = merge_delta_csr_device(
          prev.indptr, prev.indices, prev.edge_ids, seg)
    except ValueError:
      raise                        # contract errors surface as-is
    except Exception as ex:
      if recorder.enabled:
        recorder.emit('pallas.fallback', kernel='delta_merge',
                      reason=type(ex).__name__, events=seg.count)
      return None
    if recorder.enabled:
      recorder.emit('pallas.dispatch', kernel='delta_merge',
                    events=seg.count, version=prev.version + 1)
    return merged

  # -- DataPlaneState (utils.checkpoint): the compacted base ----------------
  def state_dict(self) -> dict:
    with self._lock:
      view = self._view
      num_events = self._num_events
    return {'indptr': view.indptr, 'indices': view.indices,
            'edge_ids': view.edge_ids,
            'version': np.int64(view.version),
            'num_events': np.int64(num_events),
            'edge_cap': np.int64(self._edge_cap)}

  def load_state_dict(self, state: dict) -> None:
    with self._lock:
      self._edge_cap = max(self._edge_cap,
                           int(np.asarray(state['edge_cap'])))
      self._num_events = int(np.asarray(state['num_events']))
      self._view = self._build_view(
          int(np.asarray(state['version'])),
          np.asarray(state['indptr'], np.int64),
          np.asarray(state['indices']),
          np.asarray(state['edge_ids'], np.int64))

  @classmethod
  def from_coo(cls, rows, cols, num_nodes: Optional[int] = None,
               reserve_edges: int = 0, device: bool = True
               ) -> 'StreamingGraph':
    """Build from a COO edge list through the SAME canonicalization
    as `data.topology.CSRTopo` (coo_to_csr, fabricated consecutive
    edge ids) — the static-load twin of a stream that ingested the
    same edges."""
    indptr, indices, eids = coo_to_csr(
        np.asarray(rows), np.asarray(cols), num_nodes)
    return cls(indptr, indices, eids, reserve_edges=reserve_edges,
               device=device)
