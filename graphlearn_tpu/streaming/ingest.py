"""Crash-consistent ingestion pipeline: WAL -> delta-CSR -> publish.

The orchestration layer of the streaming plane (ISSUE 14).  One
:class:`IngestPipeline` owns one :class:`~.wal.WriteAheadLog`, one
:class:`~.delta.StreamingGraph` and one compaction snapshot store
(`utils.checkpoint.SnapshotManager` — the PR 6 durability
discipline), and guarantees:

  * **exactly-once** — an edge-insert batch is durably logged BEFORE
    it is applied; recovery restores the newest compacted base and
    replays only WAL records past its ``applied_seqno`` watermark.
    Kill the process at any of the chaos seams (``ingest.wal``,
    ``ingest.apply``, ``ingest.compact``), restart, and the recovered
    graph is byte-identical to a fault-free run over the same event
    sequence — no edge lost, none applied twice (pinned by
    ``tests/test_streaming.py``).
  * **compaction** — every ``GLT_INGEST_COMPACT_EVERY`` applied
    batches the current base is snapshotted (atomic tmp+rename via
    the Checkpointer) with its seqno watermark, and the WAL is reset
    to the surviving suffix — recovery time stays bounded by the
    compaction cadence, not the stream's lifetime.
  * **observability** — live metrics (``ingest.events_total``,
    ``ingest.lag_events``, ``graph.version``,
    ``ingest.compactions_total``), an ``ingestion`` healthz component
    (unhealthy when the apply lag exceeds ``GLT_INGEST_MAX_LAG``),
    and a post-mortem bundle on ingestion faults — the same black-box
    story every other subsystem carries.

Env knobs: ``GLT_INGEST_WAL_DIR`` (log + snapshot root),
``GLT_INGEST_COMPACT_EVERY`` (applied batches between compactions,
default 64; 0 disables), ``GLT_INGEST_MAX_LAG`` (healthz lag bound in
EVENTS, default 100000).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional

import numpy as np

from .delta import StreamingGraph
from .wal import WriteAheadLog, wal_dir_from_env

COMPACT_EVERY_ENV = 'GLT_INGEST_COMPACT_EVERY'
MAX_LAG_ENV = 'GLT_INGEST_MAX_LAG'

DEFAULT_COMPACT_EVERY = 64
DEFAULT_MAX_LAG = 100_000


def _env_int(env: str, default: int) -> int:
  try:
    return int(os.environ.get(env, default))
  except ValueError:
    return default


def compact_every_from_env(default: int = DEFAULT_COMPACT_EVERY) -> int:
  return max(_env_int(COMPACT_EVERY_ENV, default), 0)


def max_lag_from_env(default: int = DEFAULT_MAX_LAG) -> int:
  return max(_env_int(MAX_LAG_ENV, default), 1)


class IngestPipeline:
  """Durable, observable edge-insert ingestion over one stream.

  Args:
    stream: the `StreamingGraph` to mutate (its published views are
      what samplers/serving pin).
    wal_dir: log + snapshot root (default ``GLT_INGEST_WAL_DIR``).
    compact_every: applied batches between compactions (default
      ``GLT_INGEST_COMPACT_EVERY``; 0 = never compact).
    max_lag: healthz bound on appended-but-unapplied EVENTS (default
      ``GLT_INGEST_MAX_LAG``).
    recover: replay the WAL tail over the newest compacted base at
      construction (the restart path; pass False to inspect state
      before replaying).
  """

  def __init__(self, stream: StreamingGraph,
               wal_dir: Optional[str] = None,
               compact_every: Optional[int] = None,
               max_lag: Optional[int] = None,
               recover: bool = True,
               shard_refresh=None):
    from ..utils.checkpoint import SnapshotManager
    wal_dir = wal_dir or wal_dir_from_env()
    if wal_dir is None:
      raise ValueError('IngestPipeline needs a WAL directory '
                       '(argument or GLT_INGEST_WAL_DIR)')
    self.stream = stream
    self.wal = WriteAheadLog(wal_dir)
    self.compact_every = (compact_every if compact_every is not None
                          else compact_every_from_env())
    self.max_lag = (int(max_lag) if max_lag is not None
                    else max_lag_from_env())
    #: compaction-seam hook (ISSUE 15): called after each durable
    #: base compaction so the failover `ShardStore`'s per-partition
    #: snapshots track the compacted topology — an adoption after a
    #: long ingest run loads the STREAMED graph, not the load-time
    #: one (`failover.ShardStore.refresh_cb`).  Failures are absorbed
    #: like a failed snapshot write: the previous durable shards win.
    self._shard_refresh = shard_refresh
    self._snap = SnapshotManager(
        os.path.join(str(wal_dir), 'base'), every=1)
    # one writer at a time: ingest/compact/recover hold this across
    # the whole append->apply(->compact) sequence, so WAL seqno order
    # == apply (event) order — the property that makes a restart's
    # seqno-ordered replay byte-identical to the live graph.
    # Reentrant: ingest() calls compact() while holding it.
    self._writer_lock = threading.RLock()
    self._lock = threading.Lock()
    self._applied_seqno = 0      # guarded-by: self._lock
    self._applied_events = 0     # guarded-by: self._lock
    self._applies_since_compact = 0  # guarded-by: self._lock
    self._compactions = 0        # guarded-by: self._lock
    self._last_fault = None      # guarded-by: self._lock
    self._closed = False
    from ..telemetry.live import live
    self._events_ctr = live.counter('ingest.events_total')
    self._compact_ctr = live.counter('ingest.compactions_total')
    self._gauge_fns = (self._lag_events, self._graph_version)
    live.gauge('ingest.lag_events', fn=self._gauge_fns[0])
    live.gauge('graph.version', fn=self._gauge_fns[1])
    self._health_fn = self.health
    live.register_health('ingestion', self._health_fn)
    if recover:
      self.recover()

  # -- gauges / health -------------------------------------------------------
  def _lag_events(self) -> float:
    """Appended-but-unapplied events: both sides are LIFETIME-
    monotone (the WAL header carries the event count its compaction
    resets dropped), so the gauge survives compactions and restarts."""
    return float(max(self.wal.lifetime_events - self.applied_events,
                     0))

  def _graph_version(self) -> float:
    return float(self.stream.version)

  @property
  def applied_seqno(self) -> int:
    with self._lock:
      return self._applied_seqno

  @property
  def applied_events(self) -> int:
    with self._lock:
      return self._applied_events

  def health(self) -> dict:
    """The ``ingestion`` healthz component: seqnos, lag, version,
    compactions, the last absorbed fault.  Unhealthy when the apply
    lag exceeds ``max_lag`` (ingestion fell behind the log — the
    freshness contract is broken) or a fault was recorded since the
    last clean apply."""
    lag = int(self._lag_events())
    with self._lock:
      fault = self._last_fault
      applied_seqno = self._applied_seqno
      applied_events = self._applied_events
      compactions = self._compactions
    block = {
        'healthy': lag <= self.max_lag and fault is None,
        'wal_seqno': self.wal.last_seqno,
        'applied_seqno': applied_seqno,
        'lag_events': lag,
        'max_lag': self.max_lag,
        'applied_events': applied_events,
        'graph_version': self.stream.version,
        'num_edges': self.stream.num_edges,
        'compactions': compactions,
        'wal_truncations': self.wal.truncations,
    }
    if fault is not None:
      block['last_fault'] = fault
    return block

  def close(self) -> None:
    """Unregister this pipeline's live-registry callbacks (the PR 12
    closure-pinning rule: a torn-down pipeline's gauges must not keep
    exporting — or keep the stream alive — for process lifetime)."""
    from ..telemetry.live import live
    if self._closed:
      return
    self._closed = True
    live.unregister_gauge('ingest.lag_events', fn=self._gauge_fns[0])
    live.unregister_gauge('graph.version', fn=self._gauge_fns[1])
    live.unregister_health('ingestion', fn=self._health_fn)
    self.wal.close()
    self._snap.close()

  # -- ingest ---------------------------------------------------------------
  def ingest(self, src, dst) -> int:
    """Durably log + apply + publish one edge-insert batch; returns
    the batch's WAL seqno.  Ordering is the crash-consistency
    contract: the WAL append lands FIRST (a crash after it replays
    the batch on restart), the delta merge commits RCU-style second
    (a crash between the two is the ``ingest.apply`` chaos case), a
    due compaction runs last.  Faults dump a post-mortem bundle and
    re-raise typed."""
    src = np.asarray(src, np.int64).reshape(-1)
    dst = np.asarray(dst, np.int64).reshape(-1)
    with self._writer_lock:
      seqno = self.wal.append(src, dst)     # durability first
      try:
        self._apply(seqno, src, dst)
      except Exception as e:                # noqa: BLE001 — typed
        self._record_fault('apply', e)      # re-raise below
        raise
      if self.compact_every > 0:
        with self._lock:
          due = self._applies_since_compact >= self.compact_every
        if due:
          self.compact()
      return seqno

  def _apply(self, seqno: int, src, dst) -> None:
    from ..testing import chaos
    chaos.ingest_apply_check(seqno)
    self.stream.apply_events(src, dst)
    with self._lock:
      self._applied_seqno = seqno
      self._applied_events += len(src)
      self._applies_since_compact += 1
      self._last_fault = None
    self._events_ctr.inc(len(src))

  def _record_fault(self, site: str, error: BaseException) -> None:
    from ..telemetry import postmortem
    from ..telemetry.recorder import recorder
    with self._lock:
      self._last_fault = f'{site}: {type(error).__name__}: {error}'
    recorder.emit('ingest.fault', site=site,
                  error=f'{type(error).__name__}: {error}'[:200])
    postmortem.dump(f'ingest.{site}', error,
                    extra={'wal_seqno': self.wal.last_seqno,
                           'applied_seqno': self.applied_seqno,
                           'graph_version': self.stream.version})

  # -- compaction -----------------------------------------------------------
  def compact(self) -> bool:
    """Snapshot the current base + seqno watermark (atomic publish),
    then reset the WAL to the surviving suffix.  A kill mid-compaction
    (chaos ``ingest.compact``) leaves the previous snapshot + the full
    WAL — replay over them reproduces the identical graph.  A FAILED
    snapshot write is absorbed (SnapshotManager contract): the WAL
    keeps the whole history, nothing is lost."""
    from ..telemetry.recorder import recorder
    from ..testing import chaos
    t0 = time.perf_counter()
    with self._writer_lock:
      with self._lock:
        watermark = self._applied_seqno
        applied_events = self._applied_events
      try:
        chaos.ingest_compact_check(watermark)
      except Exception as e:                # noqa: BLE001 — typed
        self._record_fault('compact', e)
        raise
      ok = self._snap.save(
          plane={'graph': self.stream.state_dict()},
          progress={'applied_seqno': np.int64(watermark),
                    'applied_events': np.int64(applied_events)})
      if ok:
        self.wal.reset_to(watermark)
      with self._lock:
        self._applies_since_compact = 0
        if ok:
          self._compactions += 1
      if ok and self._shard_refresh is not None:
        # refresh the durable failover shards at the compaction seam
        # (still under the writer lock: the shards must snapshot the
        # exact compacted state, not a concurrently advancing one)
        try:
          self._shard_refresh()
        except Exception as e:            # noqa: BLE001 — absorbed
          self._record_fault('shard_refresh', e)
    if ok:
      self._compact_ctr.inc()
    recorder.emit('ingest.compact', ok=bool(ok),
                  seqno=int(watermark), events=int(applied_events),
                  secs=round(time.perf_counter() - t0, 4))
    return bool(ok)

  # -- recovery -------------------------------------------------------------
  def recover(self) -> dict:
    """Restore the newest compacted base (if any), then replay the
    WAL tail past its watermark — idempotent by seqno, so running it
    on a fresh directory, after a clean shutdown, or after any chaos
    kill all land on the same graph.  Returns ``{'restored',
    'replayed_records', 'replayed_events', 'skipped_records',
    'applied_seqno'}`` and emits one ``ingest.replay`` event."""
    from ..telemetry.recorder import recorder
    t0 = time.perf_counter()
    restored = False
    snap = self._snap.restore_latest()
    with self._writer_lock:
      if snap is not None:
        # the stream is RESET to the snapshot base, so replay from
        # the snapshot watermark reconstructs everything durably
        # logged — correct even on a live pipeline that was ahead
        self.stream.load_state_dict(snap['plane']['graph'])
        watermark = int(np.asarray(snap['progress']['applied_seqno']))
        events = int(np.asarray(snap['progress']['applied_events']))
        restored = True
      else:
        # no base to reset to: the stream keeps what this process
        # already applied, so replay must start at the IN-MEMORY
        # watermark — from 0 it would re-apply every logged batch
        # (recover() on a live pipeline must be a no-op)
        with self._lock:
          watermark = self._applied_seqno
          events = self._applied_events
      replayed = replayed_events = skipped = 0
      for rec in self.wal.replay():
        if rec.seqno <= watermark:
          skipped += 1
          continue
        self._apply(rec.seqno, rec.src, rec.dst)
        watermark = rec.seqno
        replayed += 1
        replayed_events += rec.count
      with self._lock:
        self._applied_seqno = watermark
        self._applied_events = events + replayed_events
        self._last_fault = None
    out = {'restored': restored, 'replayed_records': replayed,
           'replayed_events': replayed_events,
           'skipped_records': skipped, 'applied_seqno': watermark,
           'secs': round(time.perf_counter() - t0, 4)}
    recorder.emit('ingest.replay', **out)
    return out

  def stats(self) -> dict:
    with self._lock:
      return {'applied_seqno': self._applied_seqno,
              'applied_events': self._applied_events,
              'compactions': self._compactions,
              'graph_version': self.stream.version,
              'wal': self.wal.stats()}
