"""Bucketed warm-executable inference engine (ISSUE 9 tentpole).

The training data plane compiles ONE program per epoch shape and
amortizes it over thousands of steps; online traffic arrives as
single-seed (or few-seed) queries whose natural shapes are all
different — compiled naively, every request is a 60 s compile.  The
engine applies the PR 5 INVALID_ID idiom to the traffic envelope
instead: a small ladder of **shape buckets** (``GLT_SERVING_BUCKETS``,
seed capacities), each served by ONE warm fused sample+gather(+model-
forward) executable; a coalesced batch pads its tail with INVALID_ID
up to the smallest bucket that fits.  `warmup` AOT-compiles every
bucket at server start, and after it NOTHING recompiles across the
whole envelope (pinned by the `_uncached_jit` per-callable compile
counters — the zero-recompile acceptance assertion).

**Per-seed determinism (the coalescing contract).**  A batch-keyed
sampler draws per *slot*, so a seed's neighborhood would change with
whoever it shares a bucket with — coalescing would alter answers.
The serving program instead vmaps the single-shot tree expansion
(`loader.fused_tree.expand_tree_levels`) per seed under a key folded
from ``(serve_key, seed_id)``: a seed's sampled tree is a pure
function of the engine seed and the node id — independent of bucket
capacity, slot position, and co-batched traffic.  That is what makes
the de-multiplexed per-request results byte-identical to the per-seed
offline reference (`offline_reference`) across bucket boundaries, and
what makes an RPC retry's re-execution indistinguishable from the
first run.

Identity fine print (pinned by tests/test_serving.py): ``nodes`` and
gathered ``x`` are byte-identical across EVERY bucket shape and any
co-batched traffic.  Fused-forward ``logits`` are byte-identical
within a bucket shape whatever the request rode with (each row's
matmul reads only its own row), and agree across DIFFERENT bucket
shapes only to float tolerance (~1e-6 — XLA retiles the matmul
reduction per shape; no compiler grants cross-shape bitwise
equality).  Per-request answers are therefore bitwise-reproducible
given (engine seed, bucket shape) — retries and replicas agree —
while cross-bucket logit identity is numerical, not bitwise.

**Tiered tables.**  With ``split_ratio < 1`` the device program emits
the sampled node ids only; features fill through the per-request
tiered `Feature.get` path — hot split gather + HBM cold-cache hits +
host-served misses with admission (`data.cold_cache`) — under the
``'serving'`` telemetry scope.  Zipf-skewed inference traffic is
exactly the workload that cache was built for (ROADMAP item 2
grounding: GNS, arXiv 2106.06150).
"""
from __future__ import annotations

import os
from contextlib import contextmanager
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import Dataset
from ..data.feature import _device_gather
from ..loader.fused import _uncached_jit
from ..loader.fused_tree import expand_tree_levels
from ..data.cold_cache import pinned_cold_enabled
from ..ops.pallas_gather import pallas_enabled
from ..ops.pallas_sample import fused_sample_enabled
from ..utils.padding import INVALID_ID

BUCKETS_ENV = 'GLT_SERVING_BUCKETS'
DEFAULT_BUCKETS = (1, 2, 4, 8, 16)


def resolve_buckets(spec=None) -> Tuple[int, ...]:
  """The seed-capacity ladder: an explicit sequence wins, else
  ``GLT_SERVING_BUCKETS`` (comma-separated ints), else the default.
  Returned sorted ascending, deduplicated, all positive."""
  if spec is None:
    env = os.environ.get(BUCKETS_ENV)
    if env:
      try:
        spec = [int(tok) for tok in env.split(',') if tok.strip()]
      except ValueError:
        spec = None
  if not spec:
    spec = DEFAULT_BUCKETS
  caps = sorted({int(c) for c in spec if int(c) > 0})
  if not caps:
    raise ValueError(f'no positive bucket capacities in {spec!r}')
  return tuple(caps)


@dataclass
class ServingResult:
  """De-multiplexed per-request inference output.

  ``nodes`` is ``[k, W]`` — each seed's sampled tree, all levels
  concatenated (widths ``1, k1, k1*k2, ...``; INVALID_ID where
  masked).  Exactly one of ``x`` (``[k, W, D]`` gathered features,
  model-less engines) and ``logits`` (``[k, C]``, engines with a
  model) is set."""
  nodes: np.ndarray
  x: Optional[np.ndarray] = None
  logits: Optional[np.ndarray] = None

  def slice(self, lo: int, hi: int) -> 'ServingResult':
    return ServingResult(
        nodes=self.nodes[lo:hi],
        x=None if self.x is None else self.x[lo:hi],
        logits=None if self.logits is None else self.logits[lo:hi])


class ServingEngine:
  """Warm bucketed single-shot inference over a `Dataset`.

  Args:
    data: homogeneous `Dataset`; the `Feature` may be tiered
      (``split_ratio < 1`` routes cold rows through the cache-aware
      host path) — the serving twin of the fused epoch drivers'
      tiered contract.
    num_neighbors: per-hop fanouts of the sampling tree.
    model: optional tree-layout model (`models.tree.TreeSAGE`
      signature: ``(xs, masks) -> [B, C]``); fused into the bucket
      program when the table is fully HBM-resident, run as a warm
      consume program after the host feature fill when tiered.
    params: model params (required with ``model``; see
      `init_params`).
    seed: the serve key — per-seed sampling derives from
      ``fold_in(key(seed), node_id)``, so two engines with one seed
      answer identically (replica consistency for free).
    buckets: seed-capacity ladder override (else
      ``GLT_SERVING_BUCKETS``).
  """

  def __init__(self, data: Dataset, num_neighbors: Sequence[int],
               model=None, params=None, seed: int = 0, buckets=None,
               stream=None):
    import threading
    if data.is_hetero:
      raise ValueError('ServingEngine is homogeneous-only (hetero '
                       'serving parity is ROADMAP item 4)')
    feat = data.node_features
    if feat is None:
      raise ValueError('ServingEngine needs node features')
    self.data = data
    self.fanouts = tuple(int(k) for k in num_neighbors)
    self.model = model
    self.params = params
    self.buckets = resolve_buckets(buckets)
    self._tiered = feat.hot_rows < feat.size(0)
    self._feat = feat
    # memory accounting (ISSUE 17): the hot tier is the engine's HBM
    # bill — resident bytes when materialised, the would-be bill
    # (rows x dim x itemsize) before lazy_init
    from ..telemetry.memaccount import register_tier

    def _hot_bytes(f=feat):
      h = getattr(f, '_hot', None)
      if h is not None:
        return int(getattr(h, 'nbytes', 0))
      try:
        return (int(f.hot_rows) * int(f.feature_dim)
                * int(np.dtype(f.dtype).itemsize))
      except Exception:
        return 0

    self._unregister_hot_tier = register_tier('hot', _hot_bytes)
    #: streaming ingestion (ISSUE 14): with a `StreamingGraph`
    #: attached (explicitly or via `Dataset.attach_stream`), every
    #: dispatch re-pins the newest published `GraphView` FIRST and
    #: reads topology only through that pinned view — one
    #: `graph_version` end to end per coalesced run, the
    #: `model_version`-style accounting the fleet heartbeats carry
    self._stream = (stream if stream is not None
                    else getattr(data, 'stream', None))
    self._pin_lock = threading.Lock()
    self._pin_holds = 0        # guarded-by: self._pin_lock
    if self._stream is not None:
      view = self._stream.pin()
      self.num_nodes = int(view.num_nodes)
      self.graph_version = int(view.version)
      indptr, indices = view.indptr_dev, view.indices_dev
    else:
      graph = data.get_graph()
      self.num_nodes = int(graph.num_nodes)
      self.graph_version = 0
      indptr, indices = graph.indptr, graph.indices
    # big tables as jit ARGUMENTS, never closures (`loader.fused`)
    self._dev = dict(indptr=indptr, indices=indices,
                     hot=None if self._tiered else feat.hot_tier,
                     id2index=(None if self._tiered
                               else feat._id2index_dev))
    self._key = jax.random.key(int(seed))
    self._seed = int(seed)
    self.level_widths = self._level_widths()
    self.tree_width = sum(self.level_widths)
    #: bumped by `set_params` (the hot-swap commit); surfaced in
    #: `compile_status` / heartbeats so fleet routing and swap
    #: validation can tell which version a replica answers with
    self.model_version = 0
    #: AOT executables `warmup` restored from (or published to) the
    #: persistent cache under GLT_AOT_CACHE_DIR: (program, cap) ->
    #: callable over the program's dynamic args.  `_dispatch` prefers
    #: these; empty without a cache dir (the default path unchanged).
    self._aot = {}
    self._aot_compiles = 0
    self._aot_restores = 0
    #: bucket capacity -> True once `warmup` compiled it
    self.warm = {cap: False for cap in self.buckets}
    # every program is chunk-bounded by construction (one bucket =
    # one static shape), so all opt into the persistent compile
    # cache under GLT_FUSED_COMPILE_CACHE=1 — ROADMAP item 6's
    # cold-start story rides the same seam as the fused epochs
    self._compiled_collect = _uncached_jit(self._collect_fn,
                                           cacheable=True)
    self._compiled_gather = _uncached_jit(self._gather_fn,
                                          static_argnums=(2,),
                                          cacheable=True)
    self._compiled_forward = _uncached_jit(self._forward_fn,
                                           static_argnums=(3,),
                                           cacheable=True)
    self._compiled_consume = _uncached_jit(self._consume_fn,
                                           cacheable=True)

  # -- static layout --------------------------------------------------------
  def _level_widths(self) -> Tuple[int, ...]:
    widths = [1]
    for k in self.fanouts:
      widths.append(widths[-1] * k)
    return tuple(widths)

  def max_request_seeds(self) -> int:
    return self.buckets[-1]

  def bucket_for(self, n_seeds: int) -> int:
    """Smallest capacity holding ``n_seeds`` (ValueError past the
    ladder — admission refuses those with a typed error instead)."""
    for cap in self.buckets:
      if n_seeds <= cap:
        return cap
    raise ValueError(f'{n_seeds} seeds exceed the largest bucket '
                     f'{self.buckets[-1]}')

  # -- traced programs ------------------------------------------------------
  def _seed_tree(self, indptr, indices, seed):
    """One seed's sampled tree: ``[W]`` concatenated level node ids,
    keyed by (serve_key, seed id) ONLY — the per-seed determinism the
    whole coalescing contract rests on."""
    valid = seed >= 0
    skey = jax.random.fold_in(self._key, jnp.where(valid, seed, 0))
    s1 = jnp.where(valid, seed, INVALID_ID).astype(jnp.int32)[None]
    levels, _masks = expand_tree_levels(indptr, indices, s1, skey,
                                        self.fanouts)
    return jnp.concatenate(levels)

  def _collect_fn(self, seeds: jax.Array, dev: dict) -> jax.Array:
    """``[cap]`` seeds -> ``[cap, W]`` sampled trees (no features) —
    the tiered path's device half."""
    return jax.vmap(
        lambda s: self._seed_tree(dev['indptr'], dev['indices'], s)
    )(seeds)

  def _split_levels(self, flat: jax.Array) -> List[jax.Array]:
    """``[cap, W, ...]`` -> per-level ``[cap * w_t, ...]`` tensors in
    the tree-layout order `models.tree.TreeSAGE` consumes (parent-
    major within each seed block — the same layout
    `expand_tree_levels` emits)."""
    out, off = [], 0
    cap = flat.shape[0]
    for w in self.level_widths:
      lvl = flat[:, off:off + w]
      out.append(lvl.reshape((cap * w,) + flat.shape[2:]))
      off += w
    return out

  def _gather_fn(self, seeds: jax.Array, dev: dict,
                 use_pallas: bool):
    """Fully-hot, model-less bucket program: sample + feature gather
    in ONE executable.  Returns ``(nodes [cap, W], x [cap, W, D])``."""
    nodes = self._collect_fn(seeds, dev)
    x = _device_gather(dev['hot'], nodes.reshape(-1), dev['id2index'],
                       use_pallas=use_pallas)
    return nodes, x.reshape(nodes.shape + (x.shape[-1],))

  def _forward_fn(self, seeds: jax.Array, params, dev: dict,
                  use_pallas: bool):
    """Fully-hot bucket program WITH the model forward fused in:
    sample + gather + tree-layout apply.  ``(nodes, logits)``."""
    nodes = self._collect_fn(seeds, dev)
    xs = [_device_gather(dev['hot'], lvl, dev['id2index'],
                         use_pallas=use_pallas)
          for lvl in self._split_levels(nodes)]
    masks = [lvl >= 0 for lvl in self._split_levels(nodes)]
    return nodes, self.model.apply(params, xs, masks)

  def _consume_fn(self, nodes: jax.Array, x: jax.Array, params):
    """Tiered consume program: host-filled ``[cap, W, D]`` features ->
    logits (the warm second half of a tiered bucket)."""
    xs = self._split_levels(x)
    masks = [lvl >= 0 for lvl in self._split_levels(nodes)]
    return self.model.apply(params, xs, masks)

  # -- host driver ----------------------------------------------------------
  def init_params(self, rng):
    """Init model params from the level shapes (host-cheap, shapes
    only) — the serving twin of `FusedTreeEpoch.init_state`."""
    if self.model is None:
      raise ValueError('init_params() needs a model')
    d = self._feat.feature_dim
    xs = [jnp.zeros((w, d), self._feat.dtype)
          for w in self.level_widths]
    masks = [jnp.ones((w,), jnp.bool_) for w in self.level_widths]
    self.params = self.model.init(rng, xs, masks)
    return self.params

  def _pad(self, seeds: np.ndarray, cap: int) -> jax.Array:
    out = np.full((cap,), INVALID_ID, np.int32)
    out[:len(seeds)] = np.asarray(seeds, np.int32)
    return jnp.asarray(out)

  def _run_prog(self, name: str, cap: int, jit_fn, dyn_args,
                call_args, statics=()):
    """Dispatch one bucket program: the AOT-restored executable when
    `warmup` installed one, else the `_uncached_jit` path.  A restored
    executable that fails AT CALL TIME (foreign device set, moved jax
    internals) is dropped and the dispatch falls back to the compile
    path — skip-to-recompile extends to runtime, not just load.
    ``statics`` are the CURRENT static-arg values: an AOT executable
    baked different ones at warmup (GLT_PALLAS toggled since) is
    bypassed for this call — env knobs keep their documented
    dispatch-time semantics (`_uncached_jit`)."""
    entry = self._aot.get((name, cap))
    if entry is not None:
      fn, baked = entry
      if baked != tuple(statics):
        return jit_fn(*call_args)    # toggle may flip back: keep the
        # entry, just don't serve this call from it
      try:
        return fn(*dyn_args)
      except Exception:             # noqa: BLE001 — recompile, never
        # fail the request on a bad cached executable
        self._aot.pop((name, cap), None)
        from ..telemetry.recorder import recorder
        recorder.emit('aot.cache_miss', program=name, bucket=cap,
                      reason='error')
    return jit_fn(*call_args)

  def _repin_graph(self) -> None:
    """Streaming fence: swap in the newest published `GraphView`
    BEFORE a dispatch starts.  RCU on the `_dev` dict — a dispatch
    already in flight keeps the dict (and the immutable view arrays)
    it captured; the swap is one reference assignment, so no reader
    ever sees half a graph.  Same-shape publishes (the steady state
    under `reserve_edges`) keep every warm executable warm — topology
    rides as program ARGUMENTS; a capacity growth changes the aval
    and recompiles once per doubling."""
    if self._stream is None:
      return
    view = self._stream.pin()
    if view.version == self.graph_version:
      return
    with self._pin_lock:
      if self._pin_holds > 0:      # hold_graph(): multi-dispatch
        return                     # comparison in flight, keep the
      view = self._stream.pin()    # version it started on
      if view.version == self.graph_version:
        return
      dev = dict(self._dev)
      dev['indptr'] = view.indptr_dev
      dev['indices'] = view.indices_dev
      self._dev = dev
      self.graph_version = int(view.version)

  @contextmanager
  def hold_graph(self):
    """Freeze the pinned ``graph_version`` across SEVERAL dispatches.
    A single dispatch is always torn-read-safe on its own; use this
    when comparing dispatches against each other — the swap parity
    probe runs one coalesced candidate against per-seed references,
    and a publish landing between them would make the byte-identity
    check span two graphs (a spurious rollback, not a caught bug)."""
    self._repin_graph()            # newest version, then freeze
    with self._pin_lock:
      self._pin_holds += 1
    try:
      yield self.graph_version
    finally:
      with self._pin_lock:
        self._pin_holds -= 1

  def _dispatch(self, padded: jax.Array,
                params=None) -> ServingResult:
    """One bucket dispatch (``padded`` already at a bucket capacity).
    Warm after `warmup`: every call is an in-memory executable hit.
    ``params`` overrides the installed model version for THIS dispatch
    (the hot-swap parity probe validates a candidate this way without
    admitting traffic to it).  The graph is PINNED once here (`dev`):
    a concurrent ingest publish lands in the next dispatch, never
    mid-run — the no-torn-reads contract."""
    params = self.params if params is None else params
    if self.model is not None and params is None:
      raise ValueError(
          'ServingEngine has a model but no params — call '
          'init_params(rng) (or set .params) before serving/warmup')
    self._repin_graph()
    dev = self._dev
    cap = int(padded.shape[0])
    if self._tiered:
      import time as _time
      _sc0 = _time.monotonic()
      nodes = self._run_prog('collect', cap, self._compiled_collect,
                             (padded, dev), (padded, dev))
      #: (monotonic t0, dur) of THIS dispatch's neighbor-sampling
      #: collect program — the frontend reads it to attach a
      #: `serving.sample_collect` span under each traced rider's
      #: dispatch slice (sampling vs feature-fill cost split)
      self.last_collect = (_sc0, _time.monotonic() - _sc0)
      nodes_h = np.asarray(nodes)
      # cross-request cold-id dedup (r11): one coalesced dispatch
      # carries several riders whose trees overlap heavily under
      # skewed traffic — fetch each DISTINCT id once per run, then
      # expand by the inverse map on device.  Every rider's rows are
      # byte-identical to the undeduped lookup; the host cold tier is
      # paid per unique id instead of per (rider, occurrence).
      flat = nodes_h.reshape(-1)
      uniq, inverse = np.unique(flat, return_inverse=True)
      # power-of-two padding (INVALID_ID rows read zero) keeps the
      # number of distinct gather shapes logarithmic — a raw uniq
      # length is content-dependent and would defeat the warm-
      # executable story one compile at a time
      from ..utils.padding import next_power_of_two
      upad = next_power_of_two(max(len(uniq), 1))
      uniq_p = np.full(upad, INVALID_ID, np.int64)
      uniq_p[:len(uniq)] = uniq
      # the per-request tiered lookup: hot split + HBM cold-cache +
      # host-served misses, 'serving' telemetry scope
      import time as _time
      _cf0 = _time.monotonic()
      x_u = self._feat.get(uniq_p, scope='serving')
      #: (monotonic t0, dur) of THIS dispatch's tiered fill — the
      #: frontend reads it to attach a `serving.cold_fill` span under
      #: each traced rider's dispatch slice
      self.last_cold_fill = (_cf0, _time.monotonic() - _cf0)
      x = jnp.take(x_u, jnp.asarray(inverse.astype(np.int32)), axis=0)
      x = x.reshape(nodes_h.shape + (x.shape[-1],))
      if self.model is None:
        return ServingResult(nodes=nodes_h, x=np.asarray(x))
      xj = jnp.asarray(x)
      logits = self._run_prog('consume', cap, self._compiled_consume,
                              (nodes, xj, params),
                              (nodes, xj, params))
      return ServingResult(nodes=nodes_h, logits=np.asarray(logits))
    if self.model is None:
      nodes, x = self._run_prog(
          'gather', cap, self._compiled_gather, (padded, dev),
          (padded, dev, pallas_enabled()),
          statics=(bool(pallas_enabled()),))
      return ServingResult(nodes=np.asarray(nodes), x=np.asarray(x))
    nodes, logits = self._run_prog(
        'forward', cap, self._compiled_forward,
        (padded, params, dev),
        (padded, params, dev, pallas_enabled()),
        statics=(bool(pallas_enabled()),))
    return ServingResult(nodes=np.asarray(nodes),
                         logits=np.asarray(logits))

  def infer(self, seeds, cap: Optional[int] = None,
            params=None) -> ServingResult:
    """Serve one (possibly coalesced) seed batch; results sliced back
    to ``len(seeds)``.  ``cap`` pins the bucket (the frontend picks it
    once per coalesced dispatch); default = smallest fitting.
    ``params`` overrides the installed model version for this call
    (hot-swap validation)."""
    seeds = np.asarray(seeds).reshape(-1)
    cap = self.bucket_for(len(seeds)) if cap is None else cap
    return self._dispatch(self._pad(seeds, cap),
                          params=params).slice(0, len(seeds))

  def offline_reference(self, seeds, cap: Optional[int] = None,
                        params=None) -> ServingResult:
    """The per-seed offline loader twin: every seed served ALONE —
    through the smallest bucket by default, or a pinned ``cap`` —
    the byte-identity reference the coalesced path is tested against
    (and what a non-coalescing baseline deployment would compute).
    See the class docstring's identity fine print for which outputs
    are bitwise vs float-tolerance equal across bucket shapes."""
    parts = [self.infer(np.asarray([s]), cap=cap, params=params)
             for s in np.asarray(seeds).reshape(-1)]
    return ServingResult(
        nodes=np.concatenate([p.nodes for p in parts]),
        x=(None if parts[0].x is None
           else np.concatenate([p.x for p in parts])),
        logits=(None if parts[0].logits is None
                else np.concatenate([p.logits for p in parts])))

  def validate_params(self, params) -> None:
    """Refuse a candidate param tree that cannot ride the warm bucket
    executables: structure/shape/dtype must match the installed tree
    leaf-for-leaf (params are program ARGUMENTS, so a conforming tree
    swaps with zero recompiles and a drifted one would silently
    recompile every bucket).  Raises ValueError naming the first
    diverging leaf."""
    if self.model is None:
      raise ValueError('validate_params on a model-less engine')
    if self.params is None:
      return
    old_s = jax.tree_util.tree_structure(self.params)
    new_s = jax.tree_util.tree_structure(params)
    if old_s != new_s:
      raise ValueError(
          f'param tree structure changed ({new_s} vs installed '
          f'{old_s}) — a hot swap must keep the architecture; '
          'deploy a new engine for a new architecture')
    def _dt(x):
      # dtype off the aval — no device-to-host copy for jax leaves
      d = getattr(x, 'dtype', None)
      return d if d is not None else np.asarray(x).dtype
    for (path, old_leaf), (_, new_leaf) in zip(
        jax.tree_util.tree_leaves_with_path(self.params),
        jax.tree_util.tree_leaves_with_path(params)):
      if (tuple(np.shape(old_leaf)) != tuple(np.shape(new_leaf))
          or _dt(old_leaf) != _dt(new_leaf)):
        raise ValueError(
            f'param leaf {jax.tree_util.keystr(path)} changed '
            f'shape/dtype ({np.shape(new_leaf)} vs '
            f'{np.shape(old_leaf)}) — refused (would recompile '
            'every warm bucket)')

  def set_params(self, params, version: Optional[int] = None) -> int:
    """Install a new model version (the hot-swap COMMIT — callers go
    through `serving.swap.hot_swap`, which quiesces and parity-checks
    first).  Validates via `validate_params`; returns the new
    ``model_version``."""
    self.validate_params(params)
    self.params = params
    self.model_version = (int(version) if version is not None
                          else self.model_version + 1)
    return self.model_version

  # -- persistent AOT executables (ISSUE 13) --------------------------------
  def _aot_fingerprint(self, program: str, cap: int, dyn_args,
                       static_args) -> dict:
    """The cache key material: everything that shapes the compiled
    bucket program.  The engine seed is included because the serve
    key is a traced CLOSURE constant — two engines with different
    seeds compile different programs that would answer differently."""
    leaves = jax.tree_util.tree_leaves(dyn_args)
    return {
        'program': program, 'cap': int(cap),
        'fanouts': list(self.fanouts),
        'num_nodes': int(self.num_nodes),
        # graph SHAPE + ingest version (ISSUE 14 satellite): the
        # padded edge capacity is what the executable's avals bake,
        # and the graph_version pins which published graph this
        # entry was warmed against — a mutated graph skips a stale
        # disk executable into a fresh compile instead of serving
        # against mismatched statics.  Deliberately conservative:
        # topology rides as program ARGUMENTS, so a same-capacity
        # executable would in fact be reusable across versions — the
        # version key trades warm-restores during LIVE ingest (each
        # replica warming at a moved version recompiles) for the
        # guarantee that no entry ever outlives the graph it was
        # validated against
        'num_edges': int(self._dev['indices'].shape[0]),
        'graph_version': int(self.graph_version),
        'feature': [int(self._feat.feature_dim), str(self._feat.dtype)],
        'tiered': bool(self._tiered),
        'model': repr(self.model),
        'seed': self._seed,
        'statics': [repr(s) for s in static_args],
        # .shape/.dtype read the aval — NEVER np.asarray, which would
        # pull the full graph/feature tables device-to-host just to
        # name their dtypes (per program per bucket, on the exact
        # warm-start path the cache exists to make fast)
        'avals': [f'{tuple(x.shape)}:{x.dtype}' for x in leaves],
        # r19 kernel toggles: dispatch resolves at trace time, so a
        # program compiled with a kernel ON must never be restored
        # into a process running with it OFF (same avals, different
        # lowering)
        'kernels': [bool(pallas_enabled()),
                    bool(fused_sample_enabled()),
                    bool(pinned_cold_enabled())],
        'jax': jax.__version__,
        'backend': jax.default_backend(),
        'devices': [str(d) for d in jax.devices()],
    }

  def _aot_install(self, cache, name: str, cap: int, jit_fn,
                   dyn_args, static_args) -> None:
    """Restore one bucket program from the persistent cache, or AOT
    lower+compile it and publish the executable for the next replica."""
    fp = self._aot_fingerprint(name, cap, dyn_args, static_args)
    fn = cache.load(fp)
    if fn is None:
      compiled = jit_fn.jitted.lower(*dyn_args, *static_args).compile()
      self._aot_compiles += 1
      cache.save(fp, compiled)
      fn = compiled
    else:
      self._aot_restores += 1
    self._aot[(name, cap)] = (fn, tuple(static_args))

  def _aot_warm_bucket(self, cache, cap: int,
                       padded: jax.Array) -> None:
    """Install every program this engine mode needs at capacity
    ``cap`` (hot: gather|forward; tiered: collect[+consume])."""
    use_pallas = bool(pallas_enabled())
    if self._tiered:
      self._aot_install(cache, 'collect', cap, self._compiled_collect,
                        (padded, self._dev), ())
      if self.model is not None:
        # consume's avals hang off collect's output: run the (now
        # AOT) collect once to shape them
        nodes = self._run_prog('collect', cap, self._compiled_collect,
                               (padded, self._dev),
                               (padded, self._dev))
        x0 = jnp.zeros(tuple(nodes.shape) + (self._feat.feature_dim,),
                       self._feat.dtype)
        self._aot_install(cache, 'consume', cap,
                          self._compiled_consume,
                          (nodes, x0, self.params), ())
    elif self.model is None:
      self._aot_install(cache, 'gather', cap, self._compiled_gather,
                        (padded, self._dev), (use_pallas,))
    else:
      self._aot_install(cache, 'forward', cap, self._compiled_forward,
                        (padded, self.params, self._dev),
                        (use_pallas,))

  def warmup(self, aot_cache='env') -> dict:
    """AOT-compile every bucket program at server start (the tiered
    host fill + consume included), so the first real request — and
    every one after — hits a warm executable.  With
    ``GLT_AOT_CACHE_DIR`` set (or an `AotExecutableCache` passed),
    bucket executables are restored from the persistent cache instead
    of recompiling — the warm-from-disk replica-replacement path —
    and fresh compiles are published back for the next replica.
    Returns ``{'buckets': {...}, 'compiles': n, 'secs': wall,
    'aot_restored': k}``."""
    import time
    from ..utils.profiling import metrics
    if aot_cache == 'env':
      from . import aot_cache as _aot_mod
      cache = _aot_mod.from_env()
    else:
      cache = aot_cache
    t0 = time.perf_counter()
    self._repin_graph()               # warm against the newest version
    n = min(self.num_nodes, 8)
    before = self.compile_count()
    restores_before = self._aot_restores
    for cap in self.buckets:
      # valid ids (0..n-1 cycled) + one INVALID tail slot when the
      # bucket has room: both the masked and unmasked arms warm up
      seeds = np.arange(cap, dtype=np.int32) % n
      if cap > 1:
        seeds[-1] = INVALID_ID
      padded = jnp.asarray(seeds)
      if cache is not None:
        self._aot_warm_bucket(cache, cap, padded)
      self._dispatch(padded)
      self.warm[cap] = True
    secs = time.perf_counter() - t0
    compiles = self.compile_count() - before
    metrics.inc('serving.warmup.secs', secs)
    return {'buckets': dict(self.warm), 'compiles': compiles,
            'secs': round(secs, 3),
            # restores counted by THIS warmup (not a lifetime delta —
            # a re-warm that restores over a prior compile still
            # reports its restores)
            'aot_restored': self._aot_restores - restores_before}

  def compile_count(self) -> int:
    """Total compiles across the engine's programs (the
    `_uncached_jit` per-callable counters, plus AOT lower+compiles
    the persistent cache could not serve) — snapshot before traffic,
    compare after: a nonzero delta after `warmup` means a shape
    escaped the bucket ladder.  Zero after a warmup that restored
    every bucket from ``GLT_AOT_CACHE_DIR`` — the warm-start pin."""
    return self._aot_compiles + sum(fn.compiles for fn in (
        self._compiled_collect, self._compiled_gather,
        self._compiled_forward, self._compiled_consume))

  def compile_status(self) -> dict:
    """Per-bucket warm status + compile counters (the heartbeat's
    serving block)."""
    return {'buckets': {str(c): bool(w) for c, w in self.warm.items()},
            'compiles': self.compile_count(),
            'aot_programs': len(self._aot),
            'model_version': self.model_version,
            'graph_version': self.graph_version,
            'tiered': self._tiered}
