"""Admission control for the online serving plane (ISSUE 9).

An inference tier that accepts everything collapses under overload:
queues grow without bound, every request's latency climbs together,
and p99 dies long before throughput does.  The admission controller
keeps the tier SLO-gated instead:

  * **bounded queue** — at most ``GLT_SERVING_QUEUE_DEPTH`` requests
    may wait; an arrival past the bound is REFUSED at the door with a
    typed :class:`AdmissionRejected` carrying queue-depth diagnostics
    (the caller sees *why*, and can back off or route elsewhere);
  * **per-request deadlines** — every request carries a deadline
    (default ``GLT_SERVING_DEADLINE_MS``); a request still queued when
    its deadline passes is SHED with the same typed error, never
    silently dropped (its future always resolves — a lost request is
    the one failure mode a serving tier may not have);
  * **typed load-shedding** — both refusal arms raise
    :class:`AdmissionRejected` with a ``reason`` (``queue_full`` /
    ``deadline`` / ``too_large`` / ``shutdown``) so callers and the
    chaos/retry layers can tell shed from crash.

Deliberately import-light (threading/time/collections only — no jax):
`distributed.dist_client` maps remote rejections onto this type
without pulling the device stack into a pure-client process.
"""
from __future__ import annotations

import collections
import os
import threading
import time
from typing import List, Optional

#: env knobs (documented in benchmarks/README "Online serving (r9)" +
#: "Fleet serving & failover (r14)")
QUEUE_DEPTH_ENV = 'GLT_SERVING_QUEUE_DEPTH'
DEADLINE_ENV = 'GLT_SERVING_DEADLINE_MS'
DRAIN_RETRY_ENV = 'GLT_SERVING_DRAIN_RETRY_MS'

DEFAULT_QUEUE_DEPTH = 256
DEFAULT_DEADLINE_MS = 200.0
#: retry-after hint handed out with ``reason='draining'`` rejections —
#: the hot-swap cutover is a parity check over warm executables, so
#: tens of milliseconds covers it
DEFAULT_DRAIN_RETRY_MS = 50.0


def _env_pos(name: str, default, cast):
  raw = os.environ.get(name)
  if raw is None:
    return default
  try:
    v = cast(raw)
    return v if v > 0 else default
  except ValueError:
    return default


def queue_depth_from_env() -> int:
  return _env_pos(QUEUE_DEPTH_ENV, DEFAULT_QUEUE_DEPTH, int)


#: cached live-counter handles (resolved once — the admission lock is
#: held at every tick site, so the tick must stay a dict increment,
#: not a registry resolution; lazy import keeps this module
#: import-light for pure-client processes, which never reach a tick)
_shed_counters: dict = {}
_admitted_counter = None


def _tick_shed(reason: str) -> None:
  c = _shed_counters.get(reason)
  if c is None:
    from ..telemetry.live import live
    c = _shed_counters[reason] = live.counter(
        'serving.shed_total', labels={'reason': reason})
  c.inc()


def _tick_admitted() -> None:
  global _admitted_counter
  if _admitted_counter is None:
    from ..telemetry.live import live
    _admitted_counter = live.counter('serving.admitted_total')
  _admitted_counter.inc()


def deadline_ms_from_env() -> float:
  return _env_pos(DEADLINE_ENV, DEFAULT_DEADLINE_MS, float)


def drain_retry_ms_from_env() -> float:
  return _env_pos(DRAIN_RETRY_ENV, DEFAULT_DRAIN_RETRY_MS, float)


class AdmissionRejected(RuntimeError):
  """A request the serving tier refused or shed — a LOAD signal, not a
  crash.  ``reason`` is one of ``queue_full`` (bounded queue at
  capacity on arrival), ``deadline`` (still queued past its deadline),
  ``too_large`` (more seeds than the largest shape bucket),
  ``draining`` (brief hot-swap cutover — retry after
  ``retry_after_ms`` and the NEW model version answers),
  ``shutdown`` (tier stopping).  ``queue_depth``/``limit`` carry the
  controller state at refusal time and ``waited_ms`` how long a shed
  request sat queued — the diagnostics an operator needs to size the
  bucket ladder and queue bound."""

  def __init__(self, msg: str, *, reason: str = '',
               queue_depth: Optional[int] = None,
               limit: Optional[int] = None,
               waited_ms: Optional[float] = None,
               retry_after_ms: Optional[float] = None):
    super().__init__(msg)
    self.reason = reason
    self.queue_depth = queue_depth
    self.limit = limit
    self.waited_ms = waited_ms
    self.retry_after_ms = retry_after_ms


class ServingFuture:
  """One request's pending result: resolves exactly once, with a value
  or an error (`AdmissionRejected` for shed, anything else for an
  executor fault).  ``result`` re-raises the error — the resolve path
  that silently loses a request does not exist."""

  __slots__ = ('_done', '_value', '_error', 'done_monotonic')

  def __init__(self):
    self._done = threading.Event()
    self._value = None
    self._error: Optional[BaseException] = None
    self.done_monotonic: Optional[float] = None

  def set_result(self, value) -> None:
    self._value = value
    self.done_monotonic = time.monotonic()
    self._done.set()

  def set_error(self, err: BaseException) -> None:
    self._error = err
    self.done_monotonic = time.monotonic()
    self._done.set()

  def done(self) -> bool:
    return self._done.is_set()

  def result(self, timeout: Optional[float] = None):
    if not self._done.wait(timeout):
      raise TimeoutError('serving request still in flight')
    if self._error is not None:
      raise self._error
    return self._value


class Request:
  """One admitted inference request: ``seeds`` (a small int sequence),
  its absolute ``deadline`` (monotonic seconds), arrival time, and the
  future its caller is waiting on."""

  __slots__ = ('seeds', 'arrived', 'deadline', 'future', 'trace')

  def __init__(self, seeds, deadline_s: float,
               trace: Optional[dict] = None):
    self.seeds = seeds
    self.arrived = time.monotonic()
    self.deadline = self.arrived + deadline_s
    self.future = ServingFuture()
    self.trace = trace               # request-trace context (tracing)

  def expired(self, now: Optional[float] = None) -> bool:
    return (now if now is not None else time.monotonic()) > self.deadline

  def waited_ms(self, now: Optional[float] = None) -> float:
    now = now if now is not None else time.monotonic()
    return 1e3 * (now - self.arrived)


class AdmissionController:
  """The bounded FIFO between request producers and the coalescing
  executor loop.

  ``submit`` either admits (emitting ``serving.admit``) or raises
  `AdmissionRejected` (emitting ``serving.shed``).  ``take`` hands the
  executor a coalescible run of requests — FIFO order, total seed
  count capped at the target bucket — shedding any queued request
  whose deadline already passed (typed resolve + ``serving.shed``, so
  the caller blocked on its future learns immediately, not at its RPC
  timeout).
  """

  def __init__(self, max_queue: Optional[int] = None,
               default_deadline_ms: Optional[float] = None,
               max_request_seeds: Optional[int] = None):
    self.max_queue = int(max_queue if max_queue is not None
                         else queue_depth_from_env())
    self.default_deadline_ms = float(
        default_deadline_ms if default_deadline_ms is not None
        else deadline_ms_from_env())
    self.max_request_seeds = max_request_seeds
    self._q: 'collections.deque[Request]' = collections.deque()
    self._lock = threading.Lock()
    self._arrived = threading.Condition(self._lock)
    self._closed = False
    #: drain DEPTH, not a boolean: overlapping hot-swap windows (two
    #: swaps racing on one tier) must not let the first one's exit
    #: reopen admission while the second still holds the cutover
    self._draining = 0              # guarded-by: self._lock
    self.drain_retry_after_ms = drain_retry_ms_from_env()
    #: optional SLO feed, called as ``slo_feed(reason, waited_ms)``
    #: for sheds that should BURN latency error budget (queue_full /
    #: deadline — the tier failing its callers).  INTENTIONAL sheds
    #: (draining cutover, shutdown, malformed too_large) are exempt:
    #: a replica mid-hot-swap is not failing, and must not flip its
    #: burn-rate alarms as if it were (ISSUE 13 satellite).
    self.slo_feed = None
    #: monotone counters for heartbeat/stats (read under the lock)
    self.admitted = 0
    self.shed = {'queue_full': 0, 'deadline': 0, 'too_large': 0,
                 'shutdown': 0, 'draining': 0}

  # -- producer side --------------------------------------------------------
  def submit(self, seeds, deadline_ms: Optional[float] = None,
             trace: Optional[dict] = None) -> Request:
    """Admit one request or raise typed.  ``seeds`` is a sequence of
    int node ids; ``deadline_ms`` overrides the default SLO budget;
    ``trace`` is the request-trace context riding the serve path
    (a door shed resolves it failed — shed traces are tail-retained)."""
    from ..telemetry.recorder import recorder
    from ..telemetry.tracing import tracer
    n = len(seeds)
    dl = float(deadline_ms if deadline_ms is not None
               else self.default_deadline_ms)
    with self._lock:
      if self._closed:
        self.shed['shutdown'] += 1
        _tick_shed('shutdown')
        recorder.emit('serving.shed', reason='shutdown', seeds=n,
                      queue_depth=len(self._q))
        tracer.resolve(trace, outcome='shed')
        raise AdmissionRejected('serving tier is shutting down',
                                reason='shutdown')
      if self._draining:
        # the hot-swap cutover window: the tier is quiescing between
        # coalesced runs (queued requests stay queued — no flush) and
        # refuses NEW arrivals with a retry-after hint; the retry
        # lands on the new model version
        self.shed['draining'] += 1
        _tick_shed('draining')
        recorder.emit('serving.shed', reason='draining', seeds=n,
                      queue_depth=len(self._q),
                      retry_after_ms=self.drain_retry_after_ms)
        tracer.resolve(trace, outcome='shed')
        raise AdmissionRejected(
            'serving tier is draining for a hot model swap — retry '
            f'after ~{self.drain_retry_after_ms:.0f}ms',
            reason='draining', queue_depth=len(self._q),
            retry_after_ms=self.drain_retry_after_ms)
      if (self.max_request_seeds is not None
          and n > self.max_request_seeds):
        self.shed['too_large'] += 1
        _tick_shed('too_large')
        recorder.emit('serving.shed', reason='too_large', seeds=n,
                      limit=self.max_request_seeds,
                      queue_depth=len(self._q))
        tracer.resolve(trace, outcome='shed')
        raise AdmissionRejected(
            f'request carries {n} seeds; the largest serving bucket '
            f'holds {self.max_request_seeds} — split the request or '
            'widen GLT_SERVING_BUCKETS',
            reason='too_large', limit=self.max_request_seeds,
            queue_depth=len(self._q))
      if len(self._q) >= self.max_queue:
        self.shed['queue_full'] += 1
        _tick_shed('queue_full')
        if self.slo_feed is not None:
          self.slo_feed('queue_full', 0.0)
        recorder.emit('serving.shed', reason='queue_full', seeds=n,
                      queue_depth=len(self._q), limit=self.max_queue)
        tracer.resolve(trace, outcome='shed')
        raise AdmissionRejected(
            f'serving queue at capacity ({len(self._q)}/'
            f'{self.max_queue} requests waiting) — overload; retry '
            'with backoff or raise GLT_SERVING_QUEUE_DEPTH',
            reason='queue_full', queue_depth=len(self._q),
            limit=self.max_queue)
      req = Request(seeds, dl / 1e3, trace=trace)
      self._q.append(req)
      self.admitted += 1
      _tick_admitted()
      recorder.emit('serving.admit', seeds=n, queue_depth=len(self._q),
                    deadline_ms=dl)
      self._arrived.notify_all()
    return req

  # -- executor side --------------------------------------------------------
  def _shed_expired_locked(self, now: float) -> None:
    from ..telemetry.recorder import recorder
    from ..telemetry.tracing import tracer
    kept: 'collections.deque[Request]' = collections.deque()
    for req in self._q:
      if req.expired(now):
        self.shed['deadline'] += 1
        _tick_shed('deadline')
        waited = req.waited_ms(now)
        if self.slo_feed is not None:
          self.slo_feed('deadline', waited)
        recorder.emit('serving.shed', reason='deadline',
                      seeds=len(req.seeds), queue_depth=len(self._q),
                      waited_ms=round(waited, 3))
        req.future.set_error(AdmissionRejected(
            f'deadline passed after {waited:.1f}ms in queue '
            '(executor saturated — shed, not silently dropped)',
            reason='deadline', waited_ms=waited,
            queue_depth=len(self._q)))
        tracer.resolve(req.trace, outcome='shed', latency_ms=waited)
      else:
        kept.append(req)
    self._q = kept

  def take(self, max_seeds: int, max_wait_s: float,
           poll_s: float = 0.005, block: bool = True) -> List[Request]:
    """Return a FIFO run of requests whose total seed count fits
    ``max_seeds``.  The run closes when the budget fills or
    ``max_wait_s`` has passed since the FIRST request of the run
    arrived (bounded added latency — the coalescing SLO knob).
    Expired requests are shed, never returned.  ``block=True`` waits
    for work to exist; ``block=False`` returns ``[]`` immediately on
    an empty queue.  ``[]`` after `close`."""
    poll_s = max(poll_s, 1e-3)     # a zero poll would busy-spin the
    # coalescing wait at 100% CPU for the whole max_wait window
    with self._lock:
      while True:
        self._shed_expired_locked(time.monotonic())
        if self._closed:
          return []
        if self._q:
          break
        if not block:
          return []
        self._arrived.wait(timeout=0.1)
      wait_until = self._q[0].arrived + max_wait_s
      # hold the lock only across queue scans: waiting for stragglers
      # must not block producers out of submit
      while True:
        total = 0
        full = False
        for req in self._q:
          total += len(req.seeds)
          if total >= max_seeds:
            full = True
            break
        now = time.monotonic()
        if full or now >= wait_until or self._closed:
          break
        self._arrived.wait(timeout=min(poll_s,
                                       max(wait_until - now, 1e-4)))
        self._shed_expired_locked(time.monotonic())
        if not self._q:
          # everything shed while we waited: restart on the next
          # arrival (a fresh run, a fresh wait window)
          return []
      self._shed_expired_locked(time.monotonic())
      run: List[Request] = []
      total = 0
      while self._q and total + len(self._q[0].seeds) <= max_seeds:
        req = self._q.popleft()
        run.append(req)
        total += len(req.seeds)
      if not run and self._q:
        # head request alone exceeds max_seeds: admission should have
        # refused it (max_request_seeds), but never deadlock on it —
        # and the shed is counted/emitted like every other typed shed
        from ..telemetry.recorder import recorder
        req = self._q.popleft()
        self.shed['too_large'] += 1
        _tick_shed('too_large')
        recorder.emit('serving.shed', reason='too_large',
                      seeds=len(req.seeds), limit=max_seeds,
                      queue_depth=len(self._q))
        req.future.set_error(AdmissionRejected(
            f'request with {len(req.seeds)} seeds exceeds the '
            f'largest bucket ({max_seeds})', reason='too_large',
            limit=max_seeds))
      return run

  def depth(self) -> int:
    # lock-free: len() of a deque is atomic in CPython, and the
    # queue-depth gauge is sampled by the time-series cadence loop —
    # a scrape or sweep must never contend with submit() for _lock
    return len(self._q)

  def set_draining(self, on: bool) -> None:
    """Enter/leave the hot-swap cutover window: while on, NEW
    arrivals are refused ``reason='draining'`` with a retry-after
    hint; requests already queued stay queued (no flush — they are
    served by whichever version wins the swap).  Reference-counted:
    each ``True`` must be paired with a ``False``, and admission
    reopens only when the LAST window closes."""
    with self._lock:
      self._draining = max(self._draining + (1 if on else -1), 0)
      if not self._draining:
        self._arrived.notify_all()

  def draining(self) -> bool:
    with self._lock:
      return self._draining > 0

  def stats(self) -> dict:
    with self._lock:
      return {'queue_depth': len(self._q),
              'max_queue': self.max_queue,
              'admitted': self.admitted,
              'draining': self._draining > 0,
              'shed': dict(self.shed)}

  def close(self) -> None:
    """Resolve every queued request with a typed shutdown rejection —
    a stopping tier still answers everyone (one ``serving.shed`` per
    drained request, like every other typed shed)."""
    from ..telemetry.recorder import recorder
    from ..telemetry.tracing import tracer
    with self._lock:
      self._closed = True
      while self._q:
        req = self._q.popleft()
        self.shed['shutdown'] += 1
        _tick_shed('shutdown')
        recorder.emit('serving.shed', reason='shutdown',
                      seeds=len(req.seeds), queue_depth=len(self._q),
                      waited_ms=round(req.waited_ms(), 3))
        req.future.set_error(AdmissionRejected(
            'serving tier shut down before dispatch',
            reason='shutdown'))
        tracer.resolve(req.trace, outcome='shed',
                       latency_ms=req.waited_ms())
      self._arrived.notify_all()
