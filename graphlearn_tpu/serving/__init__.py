"""Online inference serving plane (ISSUE 9).

Turns the `DistServer`/`DistClient` runtime into an SLO-gated
inference tier: shape-bucketed warm fused sample+gather(+forward)
executables (`engine`), a bounded-queue admission controller with
typed load-shedding (`admission`), and a request coalescer + executor
loop (`frontend`).  Wire-up: build a `ServingEngine` over the served
`Dataset`, wrap it in a `ServingFrontend`, and
`DistServer.attach_serving(frontend)` — clients call
`DistClient.serve`.

Knobs: ``GLT_SERVING_BUCKETS``, ``GLT_SERVING_MAX_WAIT_MS``,
``GLT_SERVING_QUEUE_DEPTH``, ``GLT_SERVING_DEADLINE_MS``
(benchmarks/README "Online serving (r9)").
"""
from .admission import (AdmissionController, AdmissionRejected,
                        ServingFuture)
from .engine import ServingEngine, ServingResult, resolve_buckets
from .frontend import ServingFrontend

__all__ = [
    'AdmissionController', 'AdmissionRejected', 'ServingFuture',
    'ServingEngine', 'ServingResult', 'resolve_buckets',
    'ServingFrontend',
]
