"""Online inference serving plane (ISSUE 9).

Turns the `DistServer`/`DistClient` runtime into an SLO-gated
inference tier: shape-bucketed warm fused sample+gather(+forward)
executables (`engine`), a bounded-queue admission controller with
typed load-shedding (`admission`), and a request coalescer + executor
loop (`frontend`).  Wire-up: build a `ServingEngine` over the served
`Dataset`, wrap it in a `ServingFrontend`, and
`DistServer.attach_serving(frontend)` — clients call
`DistClient.serve`.

Fleet resilience (ISSUE 13): `FleetRouter` spreads traffic over N
replicas with heartbeat-classified routing and exactly-once request
redrive on replica loss (`router`); `swap.hot_swap` swaps model
versions drain-free behind a parity check; `aot_cache` persists
bucket executables under ``GLT_AOT_CACHE_DIR`` so replacements warm
from disk instead of recompiling.

Closed-loop elasticity (ISSUE 19): `ElasticController` sizes the
fleet from the SLO-burn/queue/headroom signal plane (scale-out
admits only warm, verified replicas; scale-in drains and retires the
coldest), and `parallel.handoff` moves partition ownership planned —
fence then one-bump cutover, zero degraded window.

Knobs: ``GLT_SERVING_BUCKETS``, ``GLT_SERVING_MAX_WAIT_MS``,
``GLT_SERVING_QUEUE_DEPTH``, ``GLT_SERVING_DEADLINE_MS``
(benchmarks/README "Online serving (r9)"); ``GLT_AOT_CACHE_DIR``,
``GLT_FLEET_HEARTBEAT_MS``, ``GLT_FLEET_OVERLOAD_RATIO``,
``GLT_SERVING_DRAIN_RETRY_MS`` ("Fleet serving & failover (r14)");
``GLT_SCALE_*``, ``GLT_FLEET_FLAP_WINDOW_S`` ("Elastic autoscaling &
planned handoff (r20)").
"""
from .admission import (AdmissionController, AdmissionRejected,
                        ServingFuture)
from .aot_cache import AotExecutableCache
from .autoscaler import ElasticController, ScaleAbortedError
from .engine import ServingEngine, ServingResult, resolve_buckets
from .frontend import ServingFrontend
from .router import FleetRouter, LocalReplica, RemoteReplica, RouterFuture
from .swap import (SwapAbortedError, SwapParityError,
                   SwapValidationError, hot_swap)

__all__ = [
    'AdmissionController', 'AdmissionRejected', 'ServingFuture',
    'AotExecutableCache',
    'ElasticController', 'ScaleAbortedError',
    'ServingEngine', 'ServingResult', 'resolve_buckets',
    'ServingFrontend',
    'FleetRouter', 'LocalReplica', 'RemoteReplica', 'RouterFuture',
    'SwapAbortedError', 'SwapParityError', 'SwapValidationError',
    'hot_swap',
]
