"""Closed-loop elastic autoscaling: scale the fleet from SLO burn.

ISSUE 19 tentpole (ROADMAP item 3's last step from "survives faults"
to "operates itself").  The `ElasticController` closes the loop the
earlier PRs opened one side at a time: PR 12 exports per-replica SLO
burn rates, PR 13 makes replica death survivable and AOT warm-start
nearly free, PR 17 exports ``fleet.headroom_qps`` — and until now a
human read all of it and changed nothing.  The controller runs a
periodic evaluation over the router's heartbeat signal feed
(`FleetRouter.heartbeats`: short/long-window burn, admission queue
depth, headroom) and:

  * **scales out** when the worst short- or long-window burn crosses
    ``out_burn`` or any queue is near its bound: spawn a replica
    (the caller's factory — expected to AOT-warm-restore from the
    shared ``GLT_AOT_CACHE_DIR``), verify it (healthy heartbeat, not
    draining/closed, and the ``compile_count()==0`` warm pin — a
    cold replica would answer its first requests at compile latency,
    the exact spike the scale-out is trying to absorb), and only
    then `FleetRouter.add_replica` it;
  * **scales in** when every window's burn is under ``in_burn`` and
    queues are idle: pick the COLDEST replica (lowest short-window
    qps), flip its admission door to draining (the PR 13 hot-swap
    drain machinery — queued work finishes, new arrivals shed typed
    with the retry hint), wait for quiesce, then retire it
    (`remove_replica` + `close`, which unregisters its
    observability).

**Hysteresis** keeps the loop stable: ``out_burn`` and ``in_burn``
are separated (a fleet that just scaled out reads burn between the
thresholds and does nothing), each direction has its own cooldown
(``GLT_SCALE_COOLDOWN_S`` = ``"out,in"`` — burn spikes scale out
fast, scale-in never flaps), and min/max replica bounds are hard
stops.  Every considered decision emits a ``scale.decision`` event
carrying the signal snapshot that justified it and lands in the
in-memory decision ledger (`decisions()`).  A decision that fails
mid-flight (chaos ``scale.spawn`` fault, warmup fault, quiesce
timeout) rolls back typed — the partial replica is closed, a drained
victim is un-drained, a postmortem bundle is dumped — and RE-ARMS:
the failed direction's cooldown is not spent, so the next evaluation
retries immediately.

Knobs (benchmarks/README "Elastic autoscaling & planned handoff
(r20)"): ``GLT_SCALE_EVAL_S``, ``GLT_SCALE_COOLDOWN_S``,
``GLT_SCALE_MIN`` / ``GLT_SCALE_MAX``, ``GLT_SCALE_OUT_BURN`` /
``GLT_SCALE_IN_BURN``.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..telemetry import postmortem
from ..telemetry.live import live
from ..telemetry.recorder import recorder

EVAL_ENV = 'GLT_SCALE_EVAL_S'
COOLDOWN_ENV = 'GLT_SCALE_COOLDOWN_S'
MIN_ENV = 'GLT_SCALE_MIN'
MAX_ENV = 'GLT_SCALE_MAX'
OUT_BURN_ENV = 'GLT_SCALE_OUT_BURN'
IN_BURN_ENV = 'GLT_SCALE_IN_BURN'

DEFAULT_EVAL_S = 1.0
#: (out, in) cooldowns: out short (a burn spike must add capacity
#: fast), in long (retiring capacity is never urgent)
DEFAULT_COOLDOWN_S = (3.0, 15.0)
DEFAULT_MIN_REPLICAS = 1
DEFAULT_MAX_REPLICAS = 8
#: scale-out above this worst-window burn (1.0 = spending the budget)
DEFAULT_OUT_BURN = 1.0
#: scale-in only below this on EVERY window — the hysteresis gap
#: between in_burn and out_burn is what keeps the loop from flapping
DEFAULT_IN_BURN = 0.1
#: queue_depth/max_queue at/above which scale-out triggers even
#: without burn (the queue is the leading indicator; burn lags a
#: window behind)
DEFAULT_QUEUE_RATIO = 0.7


def _env_float(name: str, default: float) -> float:
  try:
    return float(os.environ.get(name, default))
  except ValueError:
    return default


def _env_int(name: str, default: int) -> int:
  try:
    return int(os.environ.get(name, default))
  except ValueError:
    return default


def cooldowns_from_env() -> Tuple[float, float]:
  """``GLT_SCALE_COOLDOWN_S`` as ``"out,in"`` (one value = both)."""
  raw = os.environ.get(COOLDOWN_ENV)
  if not raw:
    return DEFAULT_COOLDOWN_S
  try:
    parts = [float(p) for p in raw.split(',')]
  except ValueError:
    return DEFAULT_COOLDOWN_S
  if len(parts) == 1:
    return (parts[0], parts[0])
  return (parts[0], parts[1])


class ScaleAbortedError(RuntimeError):
  """A scale decision failed mid-flight and was rolled back typed
  (spawn fault, warm-pin failure, quiesce timeout).  ``stage`` names
  where it died."""

  def __init__(self, msg: str, stage: Optional[str] = None):
    super().__init__(msg)
    self.stage = stage


class ElasticController:
  """The closed-loop fleet sizer (see module doc).

  Args:
    router: the `FleetRouter` whose fleet is managed.
    spawn_fn: zero-arg replica factory for scale-out — builds engine
      + frontend (AOT warm restore from the shared cache) and returns
      an UNREGISTERED handle (`LocalReplica` / `RemoteReplica`); the
      controller verifies it and admits it, or closes it on fault.
    min_replicas / max_replicas: hard fleet-size bounds (else
      ``GLT_SCALE_MIN`` / ``GLT_SCALE_MAX``).
    eval_s: evaluation cadence (else ``GLT_SCALE_EVAL_S``).
    cooldown_s: (out, in) seconds (else ``GLT_SCALE_COOLDOWN_S``).
    out_burn / in_burn: hysteresis thresholds on the worst-window
      burn (else ``GLT_SCALE_OUT_BURN`` / ``GLT_SCALE_IN_BURN``).
    queue_ratio: queue-fullness fraction that triggers scale-out on
      its own (the leading indicator).
    warm_pin: require ``engine.compile_count() == 0`` on a spawned
      replica (skipped for handles without an engine, e.g. remotes).
    quiesce_timeout_s: drain budget for scale-in before rollback.
    clock: injectable monotonic source (tests drive decisions
      deterministically).
    auto_start: run the evaluation thread.
  """

  def __init__(self, router, spawn_fn: Callable[[], object],
               min_replicas: Optional[int] = None,
               max_replicas: Optional[int] = None,
               eval_s: Optional[float] = None,
               cooldown_s: Optional[Tuple[float, float]] = None,
               out_burn: Optional[float] = None,
               in_burn: Optional[float] = None,
               queue_ratio: float = DEFAULT_QUEUE_RATIO,
               warm_pin: bool = True,
               quiesce_timeout_s: float = 10.0,
               clock=time.monotonic, auto_start: bool = True):
    self._router = router
    self._spawn_fn = spawn_fn
    self.min_replicas = (min_replicas if min_replicas is not None
                         else _env_int(MIN_ENV, DEFAULT_MIN_REPLICAS))
    self.max_replicas = (max_replicas if max_replicas is not None
                         else _env_int(MAX_ENV, DEFAULT_MAX_REPLICAS))
    self.eval_s = (eval_s if eval_s is not None
                   else _env_float(EVAL_ENV, DEFAULT_EVAL_S))
    cd = cooldown_s if cooldown_s is not None else cooldowns_from_env()
    self.cooldown_out_s, self.cooldown_in_s = float(cd[0]), float(cd[1])
    self.out_burn = (out_burn if out_burn is not None
                     else _env_float(OUT_BURN_ENV, DEFAULT_OUT_BURN))
    self.in_burn = (in_burn if in_burn is not None
                    else _env_float(IN_BURN_ENV, DEFAULT_IN_BURN))
    self.queue_ratio = float(queue_ratio)
    self.warm_pin = bool(warm_pin)
    self.quiesce_timeout_s = float(quiesce_timeout_s)
    self._clock = clock
    self._lock = threading.Lock()
    #: the decision ledger: every considered decision, in order, with
    #: its signal snapshot and outcome (`decisions()` copies it out)
    self._decisions: List[Dict] = []  # guarded-by: self._lock
    self._last_out = -1e18           # guarded-by: self._lock
    self._last_in = -1e18            # guarded-by: self._lock
    self._closed = False
    self._thread: Optional[threading.Thread] = None
    self._m_scale = {
        d: live.counter('scale.replicas', labels={'dir': d})
        for d in ('out', 'in')}
    if auto_start:
      self.start()

  # -- lifecycle ------------------------------------------------------------
  def start(self) -> None:
    if self._thread is not None:
      return
    self._thread = threading.Thread(target=self._loop, daemon=True,
                                    name='glt-elastic-controller')
    self._thread.start()

  def close(self) -> None:
    self._closed = True
    t = self._thread
    if t is not None:
      t.join(self.eval_s + 5.0)
    self._thread = None

  def _loop(self) -> None:
    while not self._closed:
      try:
        self.evaluate()
      except Exception:             # noqa: BLE001 — the loop must
        # outlive any single bad evaluation (a dead controller scales
        # nothing ever again)
        pass
      time.sleep(self.eval_s)

  # -- signals --------------------------------------------------------------
  def signals(self) -> Dict:
    """Aggregate the router's heartbeat feed into the decision
    signals: worst short/long-window burn across live replicas, worst
    queue-fullness fraction, summed headroom, live-replica count.
    Replicas without a heartbeat yet contribute burn/queue 0 — a
    freshly admitted replica's empty SLO window reads burn 0 by the
    `SloTracker` idle contract, so the first post-scale-out
    evaluation cannot immediately re-trigger."""
    short_burn = long_burn = queue_frac = 0.0
    headroom = 0.0
    have_headroom = False
    replicas = 0
    for name, ent in self._router.heartbeats().items():
      if ent['state'] in ('dead', 'quarantined'):
        continue
      replicas += 1
      serving = ent['serving'] or {}
      windows = (serving.get('slo') or {}).get('windows') or []
      if windows:
        short_burn = max(short_burn,
                         float(windows[0].get('burn_rate') or 0.0))
        long_burn = max(long_burn,
                        float(windows[-1].get('burn_rate') or 0.0))
      depth, max_q = serving.get('queue_depth'), serving.get('max_queue')
      if depth is not None and max_q:
        queue_frac = max(queue_frac, float(depth) / float(max_q))
      hr = serving.get('headroom_qps')
      if hr is not None:
        headroom += float(hr)
        have_headroom = True
    return {'replicas': replicas,
            'short_burn': round(short_burn, 4),
            'long_burn': round(long_burn, 4),
            'queue_frac': round(queue_frac, 4),
            'headroom_qps': (round(headroom, 3) if have_headroom
                             else None)}

  # -- the evaluation loop --------------------------------------------------
  def evaluate(self, now: Optional[float] = None) -> Optional[Dict]:
    """One closed-loop pass: read signals, decide, act.  Returns the
    ledger record of the decision considered (None = steady state —
    no event, no record: an idle fleet must not flood the flight
    recorder at the evaluation cadence)."""
    now = self._clock() if now is None else now
    sig = self.signals()
    n = sig['replicas']
    if n == 0:
      return None                    # nothing alive to read signals
      # from — replica survival is the router's job, not ours
    want_out = (sig['short_burn'] > self.out_burn
                or sig['long_burn'] > self.out_burn
                or sig['queue_frac'] >= self.queue_ratio)
    want_in = (not want_out
               and sig['short_burn'] < self.in_burn
               and sig['long_burn'] < self.in_burn
               and sig['queue_frac'] < self.queue_ratio / 2)
    if want_out:
      if n >= self.max_replicas:
        return self._record('out', sig, 'held:bounds', now)
      with self._lock:
        cooling = now - self._last_out < self.cooldown_out_s
      if cooling:
        return self._record('out', sig, 'held:cooldown', now)
      return self._scale_out(sig, now)
    if want_in:
      if n <= self.min_replicas:
        return self._record('in', sig, 'held:bounds', now)
      with self._lock:
        cooling = now - self._last_in < self.cooldown_in_s
      if cooling:
        return self._record('in', sig, 'held:cooldown', now)
      return self._scale_in(sig, now)
    return None                      # between thresholds: hysteresis

  def decisions(self) -> List[Dict]:
    with self._lock:
      return [dict(d) for d in self._decisions]

  def _record(self, direction: str, sig: Dict, outcome: str,
              now: float, replica: Optional[str] = None,
              error: Optional[str] = None) -> Dict:
    rec = {'dir': direction, 'outcome': outcome, 'replica': replica,
           'at': now, 'error': error, **sig}
    with self._lock:
      self._decisions.append(rec)
    recorder.emit('scale.decision', dir=direction, outcome=outcome,
                  replica=replica, error=error, **sig)
    return rec

  # -- scale-out ------------------------------------------------------------
  def _verify_replica(self, handle) -> None:
    """The admission bar for a freshly spawned replica: a healthy
    heartbeat (serving, not draining, not closed) and — when the
    handle exposes its engine — the ``compile_count()==0`` warm pin:
    every bucket restored from the shared AOT cache, so the replica's
    first request is served at warm latency, not compile latency."""
    hb = handle.heartbeat()
    serving = (hb or {}).get('serving')
    if not serving:
      raise ScaleAbortedError(
          f'spawned replica {handle.name!r} answered no heartbeat',
          stage='verify')
    if serving.get('closed') or serving.get('draining'):
      raise ScaleAbortedError(
          f'spawned replica {handle.name!r} is '
          f'{"closed" if serving.get("closed") else "draining"} at '
          'admission time', stage='verify')
    engine = getattr(getattr(handle, 'frontend', None), 'engine', None)
    if self.warm_pin and engine is not None:
      compiles = engine.compile_count()
      if compiles != 0:
        raise ScaleAbortedError(
            f'warm-restore pin failed on {handle.name!r}: '
            f'compile_count()=={compiles} after warmup — the shared '
            'GLT_AOT_CACHE_DIR did not cover every bucket; admitting '
            'it would serve first requests at compile latency',
            stage='verify')

  def _scale_out(self, sig: Dict, now: float) -> Dict:
    from ..testing import chaos
    handle = None
    try:
      chaos.scale_spawn_check()
      handle = self._spawn_fn()
      if handle is None:
        raise ScaleAbortedError('spawn_fn returned no replica',
                                stage='spawn')
      self._verify_replica(handle)
      self._router.add_replica(handle)
    except Exception as e:          # noqa: BLE001 — every spawn fault
      # rolls back typed and re-arms (cooldown NOT spent)
      if handle is not None:
        try:
          handle.close()
        except Exception:           # noqa: BLE001 — best-effort
          pass
      postmortem.dump('autoscale.scale_out_fault', error=e,
                      extra={'signals': sig})
      return self._record('out', sig, 'rolled_back', now,
                          replica=getattr(handle, 'name', None),
                          error=f'{type(e).__name__}: {e}')
    with self._lock:
      self._last_out = now
    self._m_scale['out'].inc()
    return self._record('out', sig, 'ok', now, replica=handle.name)

  # -- scale-in -------------------------------------------------------------
  def _pick_coldest(self) -> Optional[str]:
    """The scale-in victim: the healthy replica with the lowest
    short-window qps (ties broken by name for determinism)."""
    best = None
    for name, ent in sorted(self._router.heartbeats().items()):
      if ent['state'] != 'healthy':
        continue
      windows = ((ent['serving'] or {}).get('slo') or {}) \
          .get('windows') or []
      qps = float(windows[0].get('qps') or 0.0) if windows else 0.0
      if best is None or qps < best[1]:
        best = (name, qps)
    return best[0] if best else None

  def _scale_in(self, sig: Dict, now: float) -> Dict:
    victim = self._pick_coldest()
    if victim is None:
      return self._record('in', sig, 'held:no_victim', now)
    handle = self._router.get_replica(victim)
    frontend = getattr(handle, 'frontend', None)
    if handle is None or frontend is None:
      return self._record('in', sig, 'held:no_victim', now,
                          replica=victim)
    draining = False
    try:
      # the PR 13 drain machinery: flip the door, let queued work
      # finish, shed new arrivals typed with the retry hint —
      # clients that honor retry_after_ms land on survivors
      frontend.admission.set_draining(True)
      draining = True
      deadline = time.monotonic() + self.quiesce_timeout_s
      while not frontend.quiesced():
        if time.monotonic() > deadline:
          raise ScaleAbortedError(
              f'replica {victim!r} did not quiesce within '
              f'{self.quiesce_timeout_s:g}s of draining — '
              'un-draining and keeping it', stage='quiesce')
        time.sleep(0.005)
    except Exception as e:          # noqa: BLE001 — rollback: the
      # victim goes straight back into rotation, no capacity change
      if draining:
        try:
          frontend.admission.set_draining(False)
        except Exception:           # noqa: BLE001 — best-effort
          pass
      postmortem.dump('autoscale.scale_in_fault', error=e,
                      extra={'signals': sig, 'replica': victim})
      return self._record('in', sig, 'rolled_back', now,
                          replica=victim,
                          error=f'{type(e).__name__}: {e}')
    # quiesced: retire — out of rotation first (nothing new routes
    # there), then close (shutdown unregisters its observability)
    self._router.remove_replica(victim)
    try:
      handle.close()
    except Exception:               # noqa: BLE001 — best-effort; the
      # replica is already out of rotation either way
      pass
    with self._lock:
      self._last_in = now
    self._m_scale['in'].inc()
    return self._record('in', sig, 'ok', now, replica=victim)
