"""Persistent AOT executable cache for the serving bucket ladder
(ISSUE 13 tentpole, ROADMAP item 2b).

Fused serve programs compile in 60–70 s (BENCH_r04) and the bucket
ladder holds several of them — so the dominant cost of replacing a
lost replica, or scaling one out, is not process start but the warmup
recompile of executables that are BYTE-IDENTICAL to what every other
replica already runs.  This cache persists each bucket's compiled
executable to ``GLT_AOT_CACHE_DIR`` keyed by a full program
fingerprint — (program name, bucket capacity, graph/feature/model
signature, engine seed, abstract arg signature, device set, jax
version) — so a restarted or autoscaled replica deserializes the
ladder from disk in seconds.

Durability discipline (the `SnapshotManager` rules, PR 6):

  * **atomic publish** — entries are written to a same-directory tmp
    file and ``os.replace``'d into place, so a concurrent reader (or
    a second replica warming from the same shared directory) sees
    either the whole entry or none of it, never a torn write;
  * **corrupt-entry skip-to-recompile** — every entry carries a
    sha256 of its serialized-executable payload; an unpicklable file,
    a checksum mismatch, or a deserialization failure falls back to a
    recompile (one ``aot.cache_miss`` event with the reason), NEVER a
    crash and never a wrong executable;
  * **stale-entry skip** — the stored fingerprint is compared field-
    for-field against the requested one (a key collision, a jax
    upgrade, a changed graph) and a mismatch recompiles;
  * **write failures absorbed** — a failed save (disk full, chaos
    ``aot.cache:fail``) costs the NEXT process a compile, this one
    nothing.

Chaos site ``aot.cache`` (``op='save'``/``'load'``): ``fail`` raises
into the absorbing arms above; ``corrupt`` scrambles the payload
before publish, so a later load exercises the checksum path against a
real durable bad entry.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import time
from pathlib import Path
from typing import Any, Callable, Dict, Optional

AOT_CACHE_DIR_ENV = 'GLT_AOT_CACHE_DIR'

#: entry format version — bumped on layout change, stale-skips old files
_FORMAT = 1


def cache_dir_from_env() -> Optional[str]:
  d = os.environ.get(AOT_CACHE_DIR_ENV)
  return d if d else None


def from_env() -> Optional['AotExecutableCache']:
  """The process's cache, or None when ``GLT_AOT_CACHE_DIR`` is unset
  (the default: serving warmup compiles exactly as before)."""
  d = cache_dir_from_env()
  return AotExecutableCache(d) if d else None


def fingerprint_key(fingerprint: Dict[str, Any]) -> str:
  """Stable file-name key for one fingerprint dict (sha256 over its
  sorted-key JSON — the fingerprint itself is ALSO stored in the
  entry and compared field-for-field on load, so a hash collision
  degrades to a stale-skip, not a wrong executable)."""
  import json
  blob = json.dumps(fingerprint, sort_keys=True, default=repr)
  return hashlib.sha256(blob.encode()).hexdigest()[:32]


def _tick_hit() -> None:
  from ..telemetry.live import live
  live.counter('aot.cache_hits_total').inc()


def _tick_miss() -> None:
  from ..telemetry.live import live
  live.counter('aot.cache_misses_total').inc()


class AotExecutableCache:
  """Directory of serialized XLA executables, one file per
  (fingerprint) entry, shared safely between concurrent replicas."""

  def __init__(self, root):
    self.root = Path(root)
    self.root.mkdir(parents=True, exist_ok=True)
    # memory accounting (ISSUE 17): on-disk executable bytes,
    # re-walked at scrape time (entries come and go between scrapes)
    from ..telemetry.memaccount import register_tier

    def _aot_bytes():
      try:
        return sum(p.stat().st_size
                   for p in self.root.glob('*.aotx'))
      except OSError:
        return 0

    register_tier('aot', _aot_bytes)

  def _path(self, key: str) -> Path:
    return self.root / f'{key}.aotx'

  # -- read side ------------------------------------------------------------
  def load(self, fingerprint: Dict[str, Any]) -> Optional[Callable]:
    """Deserialize the executable for ``fingerprint``; None on any
    absent/stale/corrupt/unreadable entry (one ``aot.cache_miss``
    event with the reason — the caller recompiles)."""
    from ..telemetry.recorder import recorder
    from ..testing import chaos
    key = fingerprint_key(fingerprint)
    program = fingerprint.get('program')
    bucket = fingerprint.get('cap')
    path = self._path(key)
    t0 = time.perf_counter()

    def miss(reason: str) -> None:
      recorder.emit('aot.cache_miss', program=program, bucket=bucket,
                    key=key, reason=reason)
      _tick_miss()

    try:
      chaos.aot_cache_faults('load')
      if not path.exists():
        miss('absent')
        return None
      rec = pickle.loads(path.read_bytes())
    except chaos.InjectedFault:
      miss('unreadable')
      return None
    except Exception:               # noqa: BLE001 — torn/garbage file
      miss('corrupt')
      return None
    try:
      if (not isinstance(rec, dict) or rec.get('format') != _FORMAT
          or rec.get('fingerprint') != fingerprint):
        miss('stale')
        return None
      payload = rec['payload']
      if hashlib.sha256(payload).hexdigest() != rec.get('sha256'):
        miss('corrupt')
        return None
      from jax.experimental import serialize_executable
      fn = serialize_executable.deserialize_and_load(
          payload, rec['in_tree'], rec['out_tree'])
    except Exception:               # noqa: BLE001 — bad payload,
      # moved jax internals, foreign device set: recompile, never
      # crash the warmup (and never run a questionable executable)
      miss('corrupt')
      return None
    recorder.emit('aot.cache_hit', program=program, bucket=bucket,
                  key=key, secs=round(time.perf_counter() - t0, 3))
    _tick_hit()
    return fn

  # -- write side -----------------------------------------------------------
  def save(self, fingerprint: Dict[str, Any], compiled) -> bool:
    """Serialize + atomically publish one compiled executable.
    Returns False (absorbing the error) on any failure — a cache that
    cannot write costs the next replica a compile, not this one its
    serving tier."""
    from ..testing import chaos
    key = fingerprint_key(fingerprint)
    path = self._path(key)
    tmp = path.with_name(f'{path.name}.tmp.{os.getpid()}')
    try:
      actions = chaos.aot_cache_faults('save')
      from jax.experimental import serialize_executable
      payload, in_tree, out_tree = serialize_executable.serialize(
          compiled)
      if 'corrupt' in actions:
        # durable bad entry: scramble AFTER the checksum is taken so
        # a later load sees a real integrity failure
        buf = bytearray(payload)
        buf[::7] = bytes((b ^ 0xFF) for b in buf[::7])
        payload_out = bytes(buf)
      else:
        payload_out = payload
      rec = {'format': _FORMAT, 'fingerprint': fingerprint,
             'sha256': hashlib.sha256(payload).hexdigest(),
             'payload': payload_out,
             'in_tree': in_tree, 'out_tree': out_tree,
             'saved_at': time.time()}
      tmp.write_bytes(pickle.dumps(rec, protocol=5))
      os.replace(tmp, path)
      return True
    except Exception:               # noqa: BLE001 — absorbed
      try:
        tmp.unlink(missing_ok=True)
      except OSError:
        pass
      return False

  def entries(self) -> list:
    return sorted(p.name for p in self.root.glob('*.aotx'))
