"""Fleet router: replica failover with exactly-once request redrive
(ISSUE 13 tentpole, ROADMAP item 2).

PR 9's serving plane is one engine: losing it kills every in-flight
request and its replacement pays the full bucket-ladder compile
before answering anything.  The `FleetRouter` spreads traffic over N
replicas and makes replica loss, overload, and the hot-swap cutover
invisible to callers:

  * **health-classified routing** — the router polls each replica's
    ``heartbeat()`` serving block (the PR 9 overloaded-vs-dead
    discriminator) and classifies it ``healthy`` / ``overloaded``
    (deep queue or slow heartbeat — kept in rotation at REDUCED
    weight, because a slow replica still serves) / ``draining``
    (mid-hot-swap — skipped for new traffic, NOT evicted) / ``dead``
    (consecutive heartbeat misses — evicted).  A replica that comes
    back (a flap) is re-admitted on its next good heartbeat — unless
    it flapped dead→healthy ≥3 times inside
    ``GLT_FLEET_FLAP_WINDOW_S``, in which case it is ``quarantined``
    (weight 0, typed in ``stats()['quarantined']``) and re-admitted
    only after an exponential backoff: a flapping heartbeat must not
    keep absorbing redrives it will lose again (ISSUE 19).
  * **exactly-once redrive** — every routed request sits in an
    in-flight ledger until its future resolves.  When a replica is
    evicted, its unresolved requests are REDRIVEN onto a survivor —
    at most once each (the ledger's ``redriven`` bit), so a second
    loss resolves the future with a typed
    :class:`~graphlearn_tpu.distributed.resilience.FailoverExhausted`
    instead of bouncing forever.  Nothing is silently dropped (every
    `RouterFuture` resolves) and nothing is double-answered (the
    first resolution wins; the engines' per-seed determinism makes a
    racing duplicate byte-identical anyway).  Remote replicas add the
    PR 4 layer underneath: transport retries ride idempotent request
    ids against the server replay cache.
  * **typed door decisions** — an ``AdmissionRejected`` with reason
    ``queue_full`` or ``draining`` makes the router try the next
    replica; only when EVERY replica refuses does the rejection reach
    the caller (with the draining arm's ``retry_after_ms`` hint).

Chaos site ``serving.replica`` (kill / delay / flap) drives the
kill-one-replica-mid-bench acceptance run (`bench_serving --fleet`).

Knobs: ``GLT_FLEET_HEARTBEAT_MS`` (monitor cadence),
``GLT_FLEET_OVERLOAD_RATIO`` (queue-depth fraction classified
overloaded) — benchmarks/README "Fleet serving & failover (r14)" —
and ``GLT_FLEET_FLAP_WINDOW_S`` (the flap-damping window,
benchmarks/README "Elastic autoscaling & planned handoff (r20)").
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..distributed.resilience import FailoverExhausted, ReplicaLostError
from ..telemetry.recorder import recorder
from ..telemetry.tracing import tracer
from .admission import AdmissionRejected, ServingFuture
from .engine import ServingResult

HEARTBEAT_ENV = 'GLT_FLEET_HEARTBEAT_MS'
OVERLOAD_ENV = 'GLT_FLEET_OVERLOAD_RATIO'
FLAP_WINDOW_ENV = 'GLT_FLEET_FLAP_WINDOW_S'

DEFAULT_HEARTBEAT_MS = 200.0
DEFAULT_OVERLOAD_RATIO = 0.8
DEFAULT_FLAP_WINDOW_S = 10.0

#: dead→healthy readmits inside the flap window before quarantine
_FLAP_QUARANTINE_COUNT = 3

#: replica states (the classification vocabulary of `check_replicas`)
REPLICA_STATES = ('healthy', 'overloaded', 'draining', 'quarantined',
                  'dead')

#: scheduling weight per state: healthy replicas are picked 4x as
#: often as overloaded ones; draining/quarantined/dead get no new
#: traffic
_STATE_WEIGHT = {'healthy': 4, 'overloaded': 1, 'draining': 0,
                 'quarantined': 0, 'dead': 0}


def heartbeat_ms_from_env() -> float:
  from .admission import _env_pos
  return _env_pos(HEARTBEAT_ENV, DEFAULT_HEARTBEAT_MS, float)


def flap_window_s_from_env() -> float:
  from .admission import _env_pos
  return _env_pos(FLAP_WINDOW_ENV, DEFAULT_FLAP_WINDOW_S, float)


def overload_ratio_from_env() -> float:
  from .admission import _env_pos
  v = _env_pos(OVERLOAD_ENV, DEFAULT_OVERLOAD_RATIO, float)
  return v if v <= 1 else DEFAULT_OVERLOAD_RATIO


class _ChaosReplicaMixin:
  """Shared `serving.replica` chaos seam: ``kill`` makes the handle
  dead for good, ``flap`` unreachable for ``secs``, ``delay`` sleeps
  in place (inside `testing.chaos.replica_faults`)."""

  _dead = False
  _flap_until = 0.0

  def _chaos(self, op: str) -> None:
    from ..testing import chaos
    for f in chaos.replica_faults(self.name, op):
      if f.action == 'kill':
        self.kill()
      elif f.action == 'flap':
        self._flap_until = time.monotonic() + f.secs

  def reachable(self) -> bool:
    return not self._dead and time.monotonic() >= self._flap_until

  def kill(self) -> None:
    self._dead = True


class LocalReplica(_ChaosReplicaMixin):
  """In-process replica handle over a `ServingFrontend` — the fleet
  bench / test shape (N engines in one process).  `kill` freezes the
  frontend's executor COLD (its queued requests never resolve — the
  lost-process failure the router's redrive exists for), unlike
  `ServingFrontend.shutdown` which resolves everything typed."""

  def __init__(self, name: str, frontend):
    self.name = name
    self.frontend = frontend
    if not getattr(frontend, 'name', ''):
      frontend.name = name           # thread the fleet identity into
      # the executor chaos seam (replica-targeted dispatch faults)

  def submit(self, seeds, deadline_ms: Optional[float] = None,
             trace: Optional[dict] = None) -> ServingFuture:
    self._chaos('submit')
    if not self.reachable():
      raise ReplicaLostError(f'replica {self.name!r} is unreachable',
                             replica=self.name)
    return self.frontend.submit(seeds, deadline_ms, trace=trace)

  def heartbeat(self) -> Optional[dict]:
    self._chaos('heartbeat')
    if not self.reachable():
      return None
    return {'serving': self.frontend.stats()}

  def kill(self) -> None:
    # freeze, don't drain: stop the executor cold without resolving
    # anything queued or taken — exactly what a killed replica
    # process leaves behind (`ServingFrontend._frozen`).  The live
    # registry IS released (a dead process's exporters vanish too):
    # without this an in-process fleet host would pin the killed
    # engine's tables behind gauge/SLO closures for process lifetime.
    super().kill()
    self.frontend._frozen = True
    self.frontend._closed = True
    try:
      self.frontend._unregister_observability()
    except Exception:               # noqa: BLE001 — best-effort
      pass

  def close(self) -> None:
    if not self._dead:
      self.frontend.shutdown()


class RemoteReplica(_ChaosReplicaMixin):
  """Replica handle over a `DistClient` serving connection: submits
  run `DistClient.serve` (PR 4 idempotent request ids + replay cache
  — a transport retry of a redriven-adjacent request can never
  double-execute server-side) on a per-request daemon thread so the
  router's submit stays non-blocking."""

  def __init__(self, name: str, client, server_idx: int):
    self.name = name
    self._client = client
    self._idx = int(server_idx)

  def submit(self, seeds, deadline_ms: Optional[float] = None,
             trace: Optional[dict] = None) -> ServingFuture:
    self._chaos('submit')
    if not self.reachable():
      raise ReplicaLostError(f'replica {self.name!r} is unreachable',
                             replica=self.name)
    fut = ServingFuture()
    seeds = np.asarray(seeds)

    def run():
      try:
        out = self._client.serve(seeds, server_idx=self._idx,
                                 deadline_ms=deadline_ms,
                                 trace=trace)
        fut.set_result(ServingResult(nodes=out['nodes'],
                                     x=out.get('x'),
                                     logits=out.get('logits')))
      except Exception as e:        # noqa: BLE001 — typed resolve
        fut.set_error(e)

    threading.Thread(target=run, daemon=True,
                     name=f'glt-fleet-{self.name}').start()
    return fut

  def heartbeat(self) -> Optional[dict]:
    self._chaos('heartbeat')
    if not self.reachable():
      return None
    return self._client.heartbeat(self._idx)

  def close(self) -> None:
    pass                             # the client owns the connection


class _LedgerEntry:
  """One routed, unresolved request."""

  __slots__ = ('rid', 'seeds', 'deadline_ms', 'replica', 'inner',
               'redriven', 'generation', 'error', 'error_at',
               'trace', 't0')

  def __init__(self, rid: int, seeds, deadline_ms, replica: str,
               inner: ServingFuture, trace: Optional[dict] = None):
    self.rid = rid
    self.seeds = seeds
    self.deadline_ms = deadline_ms
    self.replica = replica
    self.inner = inner
    self.redriven = False
    self.generation = 0
    self.error: Optional[BaseException] = None
    self.error_at: Optional[float] = None
    self.trace = trace
    self.t0 = time.monotonic()

  def set_error(self, err: BaseException) -> None:
    self.error = err
    self.error_at = time.monotonic()

  def abandoned(self, now: float, grace_s: float) -> bool:
    """RESOLVED (inner done, or terminal router error) but unconsumed
    for longer than ``grace_s`` — the caller timed out or never
    called ``result()``.  Only resolved entries qualify: a pending
    one may still be legitimately redriven and collected."""
    done_at = self.error_at if self.error is not None \
        else self.inner.done_monotonic
    return done_at is not None and (now - done_at) > grace_s


class RouterFuture:
  """A routed request's pending result.  `result` follows the ledger:
  if the router redrives the request onto a survivor mid-wait, the
  wait transparently moves to the new replica's future; a terminal
  router decision (`FailoverExhausted`) raises typed.  Resolves
  exactly once from the caller's point of view.

  ``done_monotonic`` mirrors `ServingFuture`'s resolve stamp so
  open-loop drivers measure scheduled-arrival latency through the
  router too; it must be CAPTURED at resolve (`result` consumes the
  ledger entry — the inner future is unreachable afterwards)."""

  __slots__ = ('_router', '_rid', 'done_monotonic')

  def __init__(self, router: 'FleetRouter', rid: int):
    self._router = router
    self._rid = rid
    self.done_monotonic: Optional[float] = None

  def done(self) -> bool:
    entry = self._router._entry(self._rid)
    return entry is None or entry.error is not None or entry.inner.done()

  def result(self, timeout: Optional[float] = None):
    deadline = time.monotonic() + (timeout if timeout is not None
                                   else 3600.0)
    while True:
      entry = self._router._entry(self._rid)
      if entry is None:
        raise RuntimeError('router future already consumed (or '
                           'swept as abandoned after '
                           f'{self._router.abandon_grace_s:.0f}s '
                           'unconsumed)')
      if entry.error is not None:
        self._router._finish(self._rid, 'error')
        raise entry.error
      remaining = deadline - time.monotonic()
      if remaining <= 0:
        raise TimeoutError('fleet request still in flight')
      try:
        # short slices: a redrive re-points entry.inner while we wait
        res = entry.inner.result(min(0.05, remaining))
      except TimeoutError:
        continue
      except AdmissionRejected:
        self._router._finish(self._rid, 'shed')
        raise
      except BaseException:
        self._router._finish(self._rid, 'error')
        raise
      self.done_monotonic = (getattr(entry.inner, 'done_monotonic',
                                     None) or time.monotonic())
      self._router._finish(self._rid, 'ok')
      return res


class FleetRouter:
  """Health-routed fan-in over N replica handles (see module doc).

  Args:
    replicas: list of handles (each with ``name`` / ``submit`` /
      ``heartbeat`` / ``close``) — `LocalReplica` / `RemoteReplica`.
    heartbeat_ms: monitor cadence (else ``GLT_FLEET_HEARTBEAT_MS``).
    overload_ratio: queue_depth/max_queue at/above which a replica is
      classified overloaded (else ``GLT_FLEET_OVERLOAD_RATIO``).
    slow_ms: a heartbeat slower than this classifies the replica
      overloaded (alive but struggling — reduced weight, not evicted:
      the overloaded-vs-dead discriminator).
    dead_after: consecutive heartbeat misses before eviction.
    flap_window_s: sliding window for flap damping (≥3 dead→healthy
      readmits inside it quarantines the replica; else
      ``GLT_FLEET_FLAP_WINDOW_S``).
    quarantine_backoff_s: base of the exponential re-admit backoff
      (doubles per quarantine of the same replica).
    auto_start: run the heartbeat monitor thread.  Tests pass False
      and pump `check_replicas` deterministically.
  """

  def __init__(self, replicas: List, heartbeat_ms: Optional[float] = None,
               overload_ratio: Optional[float] = None,
               slow_ms: float = 250.0, dead_after: int = 2,
               abandon_grace_s: float = 300.0,
               flap_window_s: Optional[float] = None,
               quarantine_backoff_s: float = 1.0,
               auto_start: bool = True):
    if not replicas:
      raise ValueError('FleetRouter needs at least one replica')
    self._lock = threading.Lock()
    #: replica table: name -> {'handle', 'state', 'misses', 'hb',
    #: 'hb_ms', 'readmits', 'quarantines', 'quarantine_until'} (the
    #: router's one source of routing truth)
    self._replicas: Dict[str, dict] = {  # guarded-by: self._lock
        r.name: self._new_entry(r) for r in replicas}
    if len(self._replicas) != len(replicas):
      raise ValueError('replica names must be unique')
    #: in-flight redrive ledger: rid -> _LedgerEntry, pruned on
    #: resolve — the exactly-once failover bookkeeping
    self._ledger: Dict[int, _LedgerEntry] = {}  # guarded-by: self._lock
    self._next_rid = 0              # guarded-by: self._lock
    self._rr = 0                    # guarded-by: self._lock
    self._cycle: List[str] = []     # guarded-by: self._lock
    self.heartbeat_ms = (heartbeat_ms if heartbeat_ms is not None
                         else heartbeat_ms_from_env())
    self.overload_ratio = (overload_ratio if overload_ratio is not None
                           else overload_ratio_from_env())
    self.slow_ms = float(slow_ms)
    self.dead_after = int(dead_after)
    #: resolved-but-never-collected entries older than this are
    #: swept from the ledger (a caller that timed out and walked
    #: away must not grow the ledger or the in_flight gauge forever)
    self.abandon_grace_s = float(abandon_grace_s)
    self.swept = 0                  # guarded-by: self._lock
    #: fleet accounting (the acceptance arithmetic: submitted ==
    #: resolved_ok + resolved_shed + resolved_error + ledger)
    self.submitted = 0              # guarded-by: self._lock
    # guarded-by: self._lock
    self.resolved = {'ok': 0, 'shed': 0, 'error': 0}
    self.redriven = 0               # guarded-by: self._lock
    self.evictions = 0              # guarded-by: self._lock
    self.quarantines = 0            # guarded-by: self._lock
    self.flap_window_s = (flap_window_s if flap_window_s is not None
                          else flap_window_s_from_env())
    self.quarantine_backoff_s = float(quarantine_backoff_s)
    self._rebuild_cycle_locked()
    self._closed = False
    self._monitor: Optional[threading.Thread] = None
    # live ops plane: replica counts by state + failover counters,
    # and a 'fleet' /healthz component with the per-replica states
    # and their last heartbeat serving blocks (per-replica SLO feed)
    from ..telemetry.live import live
    self._m_redrives = live.counter('fleet.redrives_total')
    self._m_evictions = live.counter('fleet.evictions_total')
    self._m_quarantines = live.counter('fleet.quarantines_total')
    self._gauge_regs = []
    for st in REPLICA_STATES:
      fn = self._state_count_fn(st)
      live.gauge('fleet.replicas', labels={'state': st}, fn=fn)
      self._gauge_regs.append(('fleet.replicas', {'state': st}, fn))
    self._health_fn = self._health
    live.register_health('fleet', self._health_fn)
    if auto_start:
      self.start()

  # -- lifecycle ------------------------------------------------------------
  def start(self) -> None:
    if self._monitor is not None:
      return
    self._monitor = threading.Thread(target=self._monitor_loop,
                                     daemon=True,
                                     name='glt-fleet-monitor')
    self._monitor.start()

  def close(self, close_replicas: bool = False) -> None:
    self._closed = True
    t = self._monitor
    if t is not None:
      t.join(self.heartbeat_ms / 1e3 + 5.0)
    self._monitor = None
    from ..telemetry.live import live
    live.unregister_health('fleet', fn=self._health_fn)
    for name, labels, fn in self._gauge_regs:
      live.unregister_gauge(name, labels, fn=fn)
    if close_replicas:
      with self._lock:
        handles = [e['handle'] for e in self._replicas.values()]
      for h in handles:
        try:
          h.close()
        except Exception:           # noqa: BLE001 — best-effort
          pass

  @staticmethod
  def _new_entry(handle) -> dict:
    return {'handle': handle, 'state': 'healthy', 'misses': 0,
            'hb': None, 'hb_ms': None, 'readmits': [],
            'quarantines': 0, 'quarantine_until': 0.0}

  # -- elastic membership ---------------------------------------------------
  def add_replica(self, handle) -> None:
    """Admit a new replica into rotation (the elastic scale-out seam,
    ISSUE 19).  The caller verifies health/warmth FIRST — the
    `ElasticController` only calls this after a good heartbeat and
    the ``compile_count()==0`` warm pin — so the replica enters the
    cycle at full weight immediately."""
    with self._lock:
      if handle.name in self._replicas:
        raise ValueError(f'replica {handle.name!r} already registered')
      self._replicas[handle.name] = self._new_entry(handle)
      self._rebuild_cycle_locked()

  def remove_replica(self, name: str):
    """Retire a replica from rotation (elastic scale-in): pops its
    table entry and redrives anything still stranded in its lane onto
    survivors (a properly quiesced drain leaves nothing).  Returns
    the handle (the caller owns shutdown), None if unknown."""
    with self._lock:
      ent = self._replicas.pop(name, None)
      if ent is None:
        return None
      self._rebuild_cycle_locked()
      stranded = [e for e in self._ledger.values()
                  if e.replica == name and e.error is None
                  and not e.inner.done()]
    moved = 0
    for entry in stranded:
      if self._redrive(entry, lost=name):
        moved += 1
    recorder.emit('serving.failover', replica=name, event='retire',
                  state='removed', redriven=moved)
    return ent['handle']

  def _monitor_loop(self) -> None:
    while not self._closed:
      try:
        self.check_replicas()
      except Exception:             # noqa: BLE001 — the monitor must
        # outlive any single bad heartbeat
        pass
      time.sleep(self.heartbeat_ms / 1e3)

  # -- routing --------------------------------------------------------------
  def _rebuild_cycle_locked(self) -> None:
    cycle: List[str] = []
    for name, ent in self._replicas.items():
      cycle.extend([name] * _STATE_WEIGHT[ent['state']])
    self._cycle = cycle

  def _pick_order(self) -> List[str]:
    """Routing candidates, weighted-round-robin: healthy replicas
    appear 4x as often as overloaded in the cycle; the rotation
    pointer spreads consecutive requests."""
    with self._lock:
      cycle = self._cycle
      if not cycle:
        return []
      start = self._rr % len(cycle)
      self._rr += 1
      rotated = cycle[start:] + cycle[:start]
    seen, order = set(), []
    for name in rotated:
      if name not in seen:
        seen.add(name)
        order.append(name)
    return order

  def submit(self, seeds,
             deadline_ms: Optional[float] = None) -> RouterFuture:
    """Route one request onto a replica; returns its `RouterFuture`.
    Door rejections that another replica could absorb (``queue_full``
    / ``draining``) reroute; a replica that errors at the door is
    counted a miss and skipped.  Raises the last typed rejection (or
    `FailoverExhausted`) only when EVERY replica refused."""
    last_err: Optional[BaseException] = None
    trace = tracer.mint()            # None when tracing is off
    for name in self._pick_order():
      with self._lock:
        ent = self._replicas.get(name)
        handle = ent['handle'] if ent else None
      if handle is None:
        continue
      try:
        inner = handle.submit(seeds, deadline_ms, trace=trace)
      except AdmissionRejected as e:
        if e.reason in ('queue_full', 'draining', 'shutdown'):
          last_err = e
          continue                   # reroute-able door rejection (a
          # cleanly shut-down replica refuses typed while survivors
          # still serve — that must not reach the caller)
        raise
      except ValueError:
        # malformed REQUEST (empty seeds / ids outside the node
        # space, frontend.submit's validation): the client's error,
        # not the replica's — re-raise without charging a miss (two
        # bad inputs must not evict a healthy fleet)
        raise
      except Exception as e:        # noqa: BLE001 — door failure:
        # count it against the replica and try the next one
        last_err = e
        self._note_miss(name)
        continue
      with self._lock:
        rid = self._next_rid
        self._next_rid += 1
        entry = _LedgerEntry(rid, np.asarray(seeds), deadline_ms,
                             name, inner, trace=trace)
        self._ledger[rid] = entry
        self.submitted += 1
        # close the submit/evict race: if the replica was evicted (or
        # elastically REMOVED) BETWEEN handle.submit and this insert,
        # the eviction's stranded snapshot missed the entry — redrive
        # it ourselves (outside the lock), or its future would freeze
        # forever
        ent = self._replicas.get(name)
        evicted_in_window = ent is None or ent['state'] == 'dead'
      if evicted_in_window and not inner.done():
        self._redrive(entry, lost=name)
      return RouterFuture(self, rid)
    if isinstance(last_err, AdmissionRejected):
      raise last_err
    states = self.replica_states()
    if any(s == 'draining' for s in states.values()) and \
        not any(s in ('healthy', 'overloaded') for s in states.values()):
      # every live replica is mid-cutover (a coordinated swap): that
      # is the documented DRAINING arm with its retry hint, not a
      # fleet-wide outage — draining replicas carry weight 0 so the
      # loop never even reached their typed rejection
      from .admission import drain_retry_ms_from_env
      hint = drain_retry_ms_from_env()
      raise AdmissionRejected(
          'every live replica is draining for a hot swap — retry '
          f'after ~{hint:.0f}ms', reason='draining',
          retry_after_ms=hint) from last_err
    raise FailoverExhausted(
        f'no replica accepted the request (states: {states})'
        ) from last_err

  def infer(self, seeds, deadline_ms: Optional[float] = None,
            timeout: float = 30.0):
    """Blocking submit+wait convenience."""
    return self.submit(seeds, deadline_ms).result(timeout)

  # -- ledger ---------------------------------------------------------------
  def _entry(self, rid: int) -> Optional[_LedgerEntry]:
    with self._lock:
      return self._ledger.get(rid)

  def _finish(self, rid: int, outcome: str) -> None:
    with self._lock:
      entry = self._ledger.pop(rid, None)
      if entry is not None:
        self.resolved[outcome] += 1
    if entry is not None and entry.trace is not None:
      # the request-trace ROOT: span_id == trace_id, so every child
      # recorded under the minted context parents here (span() nulls
      # the self-parent into a proper root)
      dur = time.monotonic() - entry.t0
      tracer.span('serving.route', entry.trace,
                  span_id=entry.trace['t'], t0=entry.t0, dur=dur,
                  replica=entry.replica, outcome=outcome)
      tracer.resolve(entry.trace, outcome=outcome,
                     latency_ms=dur * 1e3)

  # -- health classification ------------------------------------------------
  def _note_miss(self, name: str) -> None:
    evict = False
    with self._lock:
      ent = self._replicas.get(name)
      if ent is None:
        return
      ent['misses'] += 1
      if ent['misses'] >= self.dead_after and ent['state'] != 'dead':
        evict = True
    if evict:
      self._evict(name)

  def _classify_locked(self, ent: dict, hb: dict,
                       hb_ms: float) -> str:
    serving = (hb or {}).get('serving') or {}
    if serving.get('draining'):
      return 'draining'
    depth = serving.get('queue_depth')
    max_q = serving.get('max_queue')
    if hb_ms > self.slow_ms:
      return 'overloaded'           # alive but slow: reduced weight,
      # NOT evicted — the discriminator's whole point
    if depth is not None and max_q:
      if depth / max_q >= self.overload_ratio:
        return 'overloaded'
    return 'healthy'

  def check_replicas(self) -> Dict[str, str]:
    """One monitor pass: heartbeat every replica, reclassify, evict
    the dead (redriving their in-flight requests), re-admit returned
    flappers.  Returns the post-pass state map.  Tests call this
    directly for deterministic pumping."""
    with self._lock:
      names = list(self._replicas)
    for name in names:
      with self._lock:
        ent = self._replicas.get(name)
        handle = ent['handle'] if ent else None
      if handle is None:
        continue
      t0 = time.monotonic()
      try:
        hb = handle.heartbeat()
      except Exception:             # noqa: BLE001 — unreachable
        hb = None
      hb_ms = 1e3 * (time.monotonic() - t0)
      if hb is None:
        self._note_miss(name)
        continue
      if ((hb.get('serving') or {}).get('closed')):
        # a cleanly shut-down frontend still ANSWERS heartbeats
        # (queue 0, draining False) — without this it would classify
        # healthy at full weight while refusing every submit.  Treat
        # it as a miss: it leaves rotation after dead_after passes
        # (its queue was already resolved typed at shutdown, so the
        # eviction's redrive sweep finds nothing stranded).
        self._note_miss(name)
        continue
      now = time.monotonic()
      with self._lock:
        ent = self._replicas.get(name)
        if ent is None:
          continue
        ent['misses'] = 0
        ent['hb'] = hb
        ent['hb_ms'] = round(hb_ms, 3)
        was = ent['state']
        if was == 'quarantined' and now < ent['quarantine_until']:
          continue                   # backoff running: a good beat
          # does NOT re-admit yet — that free readmit is the flap
          # churn the damper exists to stop
        ent['state'] = self._classify_locked(ent, hb, hb_ms)
        readmitted = was in ('dead', 'quarantined') \
            and ent['state'] != 'dead'
        quarantined = False
        if readmitted and was == 'dead':
          # flap damping (ISSUE 19): count dead→live readmits in the
          # sliding window; at the threshold, quarantine with an
          # exponential backoff (doubling per quarantine).  The
          # readmit history is NOT cleared on quarantine — window
          # pruning ages it out, so a replica that flaps again right
          # after re-admission re-quarantines immediately, backing
          # off further each time.
          ent['readmits'] = [t for t in ent['readmits']
                             if now - t <= self.flap_window_s]
          ent['readmits'].append(now)
          if len(ent['readmits']) >= _FLAP_QUARANTINE_COUNT:
            ent['state'] = 'quarantined'
            ent['quarantines'] += 1
            ent['quarantine_until'] = now + self.quarantine_backoff_s \
                * (2 ** (ent['quarantines'] - 1))
            self.quarantines += 1
            quarantined = True
            readmitted = False
        self._rebuild_cycle_locked()
      if quarantined:
        self._m_quarantines.inc()
        recorder.emit('serving.failover', replica=name,
                      event='quarantine', state='quarantined',
                      redriven=0)
      elif readmitted:
        recorder.emit('serving.failover', replica=name,
                      event='readmit', state=ent['state'],
                      redriven=0)
    # ledger hygiene: prune resolved entries whose caller never
    # collected them (a client-side timeout abandons its
    # RouterFuture; without this the ledger and the /healthz
    # in_flight count grow for router lifetime)
    now = time.monotonic()
    with self._lock:
      for rid in [rid for rid, e in self._ledger.items()
                  if e.abandoned(now, self.abandon_grace_s)]:
        del self._ledger[rid]
        self.swept += 1
    return self.replica_states()

  def replica_states(self) -> Dict[str, str]:
    with self._lock:
      return {n: e['state'] for n, e in self._replicas.items()}

  def heartbeats(self) -> Dict[str, dict]:
    """Per-replica state + last heartbeat ``serving`` block — the
    `ElasticController`'s signal feed (SLO burn windows, queue depth,
    headroom) read off the monitor's existing polls, no extra RPCs."""
    with self._lock:
      return {n: {'state': e['state'],
                  'serving': (e['hb'] or {}).get('serving')}
              for n, e in self._replicas.items()}

  def get_replica(self, name: str):
    """The named replica's handle (None if unknown) — the scale-in
    path drains/retires through it."""
    with self._lock:
      ent = self._replicas.get(name)
      return ent['handle'] if ent else None

  # -- failover -------------------------------------------------------------
  def _evict(self, name: str) -> None:
    """A replica crossed the dead threshold: take it out of rotation
    and redrive its unresolved in-flight requests onto survivors —
    each at most ONCE (the ledger bit)."""
    with self._lock:
      ent = self._replicas.get(name)
      if ent is None or ent['state'] == 'dead':
        return
      ent['state'] = 'dead'
      self.evictions += 1
      self._rebuild_cycle_locked()
      stranded = [e for e in self._ledger.values()
                  if e.replica == name and e.error is None
                  and not e.inner.done()]
    self._m_evictions.inc()
    moved = 0
    for entry in stranded:
      if self._redrive(entry, lost=name):
        moved += 1
    recorder.emit('serving.failover', replica=name, event='evict',
                  state='dead', redriven=moved)

  def _redrive(self, entry: _LedgerEntry, lost: str) -> bool:
    """Move one stranded request to a survivor (exactly once)."""
    if entry.redriven:
      entry.set_error(FailoverExhausted(
          f'request {entry.rid} lost its second replica ({lost!r}) '
          'after one redrive — giving up typed',
          replica=lost, redriven=True))
      recorder.emit('serving.failover', replica=lost,
                    event='exhausted', state='dead', redriven=0)
      return False
    cause = ReplicaLostError(f'replica {lost!r} evicted with request '
                             f'{entry.rid} in flight', replica=lost)
    for name in self._pick_order():
      if name == lost:
        continue
      with self._lock:
        ent = self._replicas.get(name)
        handle = ent['handle'] if ent else None
      if handle is None:
        continue
      try:
        inner = handle.submit(entry.seeds, entry.deadline_ms,
                              trace=entry.trace)
      except Exception:             # noqa: BLE001 — try the next
        continue
      with self._lock:
        entry.redriven = True
        entry.replica = name
        entry.generation += 1
        entry.inner = inner
        self.redriven += 1
        # same race on the redrive hop: the survivor may have been
        # evicted between its submit and this update, in which case
        # ITS eviction snapshot missed the entry — the second loss
        # resolves typed below (redriven is already spent)
        ent = self._replicas.get(name)
        lost_again = ent is not None and ent['state'] == 'dead'
      self._m_redrives.inc()
      recorder.emit('serving.failover', replica=lost, event='redrive',
                    state='dead', redriven=1)
      if lost_again and not inner.done():
        self._redrive(entry, lost=name)
      return True
    entry.set_error(FailoverExhausted(
        f'request {entry.rid}: no survivor accepted the redrive from '
        f'{lost!r}', replica=lost, redriven=False))
    entry.error.__cause__ = cause
    recorder.emit('serving.failover', replica=lost, event='exhausted',
                  state='dead', redriven=0)
    return False

  # -- observability --------------------------------------------------------
  def _state_count_fn(self, state: str):
    def count() -> int:
      with self._lock:
        return sum(1 for e in self._replicas.values()
                   if e['state'] == state)
    return count

  def stats(self) -> dict:
    with self._lock:
      return {
          'replicas': {n: {'state': e['state'], 'misses': e['misses'],
                           'hb_ms': e['hb_ms']}
                       for n, e in self._replicas.items()},
          'submitted': self.submitted,
          'resolved': dict(self.resolved),
          'in_flight': len(self._ledger),
          'swept': self.swept,
          'redriven': self.redriven,
          'evictions': self.evictions,
          'quarantined': self.quarantines,
      }

  def make_scraper(self, registry=None, include_self: bool = True,
                   scrape_ms: Optional[float] = None):
    """A `telemetry.federation.FleetScraper` pre-populated with this
    router's replica handles (`LocalReplica`s federate through their
    heartbeats; `RemoteReplica`s through their ops endpoints when
    they expose ``ops_url``) — one call wires ``/fleet`` for any
    router-holding process (`OpsServer.attach_fleet`).  With
    ``include_self`` the hosting process's own registry joins as
    replica ``self``, so fleet aggregates cover the router's SLO /
    admission gauges too."""
    from ..telemetry.federation import FleetScraper
    scraper = FleetScraper(scrape_ms=scrape_ms)
    with self._lock:
      handles = [(n, e['handle']) for n, e in self._replicas.items()]
    for name, handle in handles:
      url = getattr(handle, 'ops_url', None)
      if url:
        scraper.add_url(name, url)
      else:
        scraper.add_local_replica(name, handle)
    if include_self:
      if registry is None:
        from ..telemetry.live import live as registry
      scraper.add_registry('self', registry)
    return scraper

  def _health(self) -> dict:
    """The `/healthz` fleet component: healthy while ANY replica can
    take traffic; carries each replica's state and its last heartbeat
    serving block (queue depth, model version, per-replica SLO
    windows) so one scrape reads the whole fleet."""
    with self._lock:
      replicas = {}
      any_up = False
      for n, e in self._replicas.items():
        serving = (e['hb'] or {}).get('serving') or {}
        replicas[n] = {'state': e['state'], 'misses': e['misses'],
                       'hb_ms': e['hb_ms'],
                       'model_version': serving.get('model_version'),
                       'queue_depth': serving.get('queue_depth'),
                       'slo': serving.get('slo')}
        if e['state'] in ('healthy', 'overloaded'):
          any_up = True
      return {'healthy': any_up, 'replicas': replicas,
              'in_flight': len(self._ledger),
              'redriven': self.redriven, 'evictions': self.evictions}
