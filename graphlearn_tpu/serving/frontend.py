"""Request coalescing + executor loop over warm bucket executables.

The `ServingFrontend` is the glue of the online tier: producers
(`DistServer.serve_infer` handler threads, or in-process callers)
``submit`` single-seed / few-seed requests through the
`AdmissionController`; ONE executor thread drains the bounded queue
in coalesced runs — FIFO requests packed until the largest bucket
fills or ``GLT_SERVING_MAX_WAIT_MS`` has passed since the run's first
arrival — dispatches each run through the engine's warm bucket
program, and de-multiplexes per-request slices back onto the waiting
futures.  Per-seed sampling determinism (`serving.engine`) is what
makes the slices byte-identical to serving each request alone.

Latency anatomy of one request (all spans/events in the flight
recorder): queue wait (bounded by max-wait + the in-flight dispatch),
``serving.infer`` span (the device dispatch + tiered host fill),
demux.  ``serving.request`` events carry the end-to-end
``latency_ms`` the bench's percentile table is built from.

Coalescing is a LATENCY/THROUGHPUT dial, not a correctness one:
``GLT_SERVING_MAX_WAIT_MS=0`` degrades to serve-every-request-alone
(lowest added latency, one dispatch per request); large values
amortize dispatch overhead across deeper buckets under load.  Under
an arrival burst the wait never binds — the queue fills a bucket
immediately and the tier runs back-to-back dispatches.
"""
from __future__ import annotations

import os
import threading
import time
from typing import List, Optional

import numpy as np

from ..telemetry import postmortem
from ..telemetry.live import live
from ..telemetry.memaccount import CapacityModel
from ..telemetry.recorder import recorder
from ..telemetry.slo import SloTracker
from ..telemetry.spans import span
from ..telemetry.tracing import tracer
from .admission import AdmissionController, AdmissionRejected, Request
from .engine import ServingEngine, ServingResult

MAX_WAIT_ENV = 'GLT_SERVING_MAX_WAIT_MS'
DEFAULT_MAX_WAIT_MS = 2.0


def max_wait_ms_from_env() -> float:
  raw = os.environ.get(MAX_WAIT_ENV)
  if raw is None:
    return DEFAULT_MAX_WAIT_MS
  try:
    return max(float(raw), 0.0)
  except ValueError:
    return DEFAULT_MAX_WAIT_MS


class ServingFrontend:
  """Admission + coalescing + warm-executable execution.

  Args:
    engine: a `ServingEngine` (warmed by `start`, see below).
    max_wait_ms: coalescing window (else ``GLT_SERVING_MAX_WAIT_MS``).
    max_queue / default_deadline_ms: admission bounds (else the
      ``GLT_SERVING_QUEUE_DEPTH`` / ``GLT_SERVING_DEADLINE_MS``
      defaults).
    auto_start: start the executor thread (and run `engine.warmup`
      when not yet warm) immediately.  Tests pass ``False`` and pump
      deterministically with `pump_once`.
  """

  def __init__(self, engine: ServingEngine,
               max_wait_ms: Optional[float] = None,
               max_queue: Optional[int] = None,
               default_deadline_ms: Optional[float] = None,
               auto_start: bool = True, warmup: bool = True,
               name: str = ''):
    self.engine = engine
    #: fleet identity (set by `router.LocalReplica` when unset):
    #: rides the executor chaos seam so plans can target one replica
    self.name = name
    self.max_wait_s = (max_wait_ms if max_wait_ms is not None
                       else max_wait_ms_from_env()) / 1e3
    self.admission = AdmissionController(
        max_queue=max_queue, default_deadline_ms=default_deadline_ms,
        max_request_seeds=engine.max_request_seeds())
    self._closed = False
    #: crash-simulation hook (`serving.router.LocalReplica.kill` /
    #: chaos ``serving.replica:kill``): a frozen frontend stops COLD —
    #: taken runs are dropped unresolved (their futures freeze exactly
    #: like a killed process's would), nothing sheds typed.  The fleet
    #: router's redrive is what turns this into zero lost requests.
    self._frozen = False
    self._thread: Optional[threading.Thread] = None
    self._lock = threading.Lock()
    #: held by the executor across each coalesced run; `swap.hot_swap`
    #: acquires it to quiesce BETWEEN runs (the drain-free cutover
    #: point — no dispatch is ever interrupted, no queue is flushed)
    self._dispatch_gate = threading.Lock()
    #: serializes whole hot_swap attempts (two concurrent swaps on
    #: one tier must not interleave their drain windows or probes)
    self._swap_lock = threading.Lock()
    #: executor-side counters (heartbeat/stats; executor thread only
    #: writes, readers take the lock for a consistent snapshot —
    #: enforced by glint's guarded-by pass)
    self.in_flight = 0          # guarded-by: self._lock
    self.served_requests = 0    # guarded-by: self._lock
    self.served_seeds = 0       # guarded-by: self._lock
    self.dispatches = 0         # guarded-by: self._lock
    self.failed = 0             # guarded-by: self._lock
    # live ops plane (ISSUE 12): typed handles for the hot path
    # (registration is once, ticking is a dict increment), gauges
    # evaluated at scrape time, per-bucket latency histograms, and
    # the SLO tracker (targets from GLT_SERVING_SLO_P99_MS/_QPS).
    # "Latest frontend wins" for the gauges/health — the contract of
    # a process that restarts its serving tier.
    self._m_requests = live.counter('serving.requests_total')
    self._m_seeds = live.counter('serving.seeds_total')
    self._m_dispatches = live.counter('serving.dispatches_total')
    self._m_failed = live.counter('serving.failed_total')
    # fn-gauges retain self through their callbacks — tracked so
    # shutdown() can unregister them (fn-identity guarded: a newer
    # frontend's replacements survive a stale one's shutdown).  The
    # fill ratio is an fn-gauge over `_last_fill` rather than a
    # stored value for the same reason: a dead tier must not keep
    # exporting its final dispatch's fill as live state.
    self._last_fill: Optional[float] = None
    _depth_fn = self.admission.depth
    _in_flight_fn = self._in_flight_snapshot
    _fill_fn = self._fill_snapshot
    live.gauge('serving.queue_depth', fn=_depth_fn)
    live.gauge('serving.in_flight', fn=_in_flight_fn)
    live.gauge('serving.coalesce_fill_ratio', fn=_fill_fn)
    self._gauge_regs = [('serving.queue_depth', _depth_fn),
                        ('serving.in_flight', _in_flight_fn),
                        ('serving.coalesce_fill_ratio', _fill_fn)]
    self._lat_hists: dict = {}
    #: per-request admission→pickup wait (always on — the metrics
    #: plane is not the data plane; byte-identity concerns results
    #: and the exemplar-free /metrics text)
    self._m_queue_wait = live.histogram('serving.queue_wait')
    self.slo = SloTracker(registry=live)
    #: per-bucket EWMA serve-cost → fleet.headroom_qps (the ROADMAP
    #: item 3 admission signal; fed after every coalesced dispatch)
    self.capacity = CapacityModel(slo=self.slo, registry=live)
    # budget-burning sheds (queue_full/deadline — the tier failing
    # its callers) feed the SLO window as failures; INTENTIONAL sheds
    # (draining cutover, shutdown) are exempt by the admission
    # controller's feed contract — a replica mid-hot-swap must not
    # burn error budget or trip burn-rate alarms (ISSUE 13 satellite)
    self.admission.slo_feed = self._slo_shed_feed
    # bound method pinned once — unregister compares by identity
    self._health_fn = self._health
    live.register_health('serving', self._health_fn)
    if auto_start:
      self.start(warmup=warmup)

  # -- lifecycle ------------------------------------------------------------
  def start(self, warmup: bool = True) -> None:
    if self._thread is not None:
      return
    from ..telemetry import opsserver
    opsserver.maybe_start_from_env()
    if warmup and not all(self.engine.warm.values()):
      self.engine.warmup()
    self._thread = threading.Thread(target=self._loop, daemon=True,
                                    name='glt-serving-executor')
    self._thread.start()

  def shutdown(self, timeout: float = 10.0) -> None:
    """Stop the executor; every queued request resolves with a typed
    shutdown rejection (never silently lost)."""
    self._closed = True
    self.admission.close()
    t = self._thread
    if t is not None:
      t.join(timeout)
    self._thread = None
    self._unregister_observability()

  def _unregister_observability(self) -> None:
    """Drop this frontend's live-registry callbacks (health fn,
    gauges, SLO tracker) — the closure-pinning cleanup PR 12's gauge
    lifecycle established.  Shared by `shutdown` and the fleet
    kill-simulation path (`router.LocalReplica.kill`), which freezes
    the data plane WITHOUT resolving requests but must still release
    the registry (a killed process's exporters vanish too)."""
    live.unregister_health('serving', fn=self._health_fn)
    for gname, gfn in self._gauge_regs:
      live.unregister_gauge(gname, fn=gfn)
    self.capacity.close()
    self.slo.close()

  # -- producer side --------------------------------------------------------
  def submit(self, seeds, deadline_ms: Optional[float] = None,
             trace: Optional[dict] = None):
    """Admit one request; returns its `ServingFuture` (raises
    `AdmissionRejected` at the door when the queue is at bound, and
    `ValueError` for a MALFORMED request — empty, or seed ids outside
    ``[0, num_nodes)``; the engine's gathers CLAMP out-of-range ids,
    so without this check a bogus id would come back as a plausible
    answer for the wrong node instead of an error).  ``trace`` is the
    request-trace context minted by the router (or the RPC handler's
    child context) — it rides the queued request so the executor can
    attribute queue wait / dispatch slice / cold fill per request."""
    seeds = np.asarray(seeds, np.int64).reshape(-1)
    if seeds.size == 0:
      raise ValueError('a serving request needs at least one seed')
    if seeds.min() < 0 or seeds.max() >= self.engine.num_nodes:
      bad = seeds[(seeds < 0) | (seeds >= self.engine.num_nodes)]
      raise ValueError(
          f'seed id(s) {bad[:8].tolist()} outside [0, '
          f'{self.engine.num_nodes}) — refused (a clamped gather '
          'would silently answer for a different node)')
    return self.admission.submit(seeds, deadline_ms,
                                 trace=trace).future

  def infer(self, seeds, deadline_ms: Optional[float] = None,
            timeout: Optional[float] = None) -> ServingResult:
    """Blocking submit+wait convenience (the in-process client)."""
    dl = (deadline_ms if deadline_ms is not None
          else self.admission.default_deadline_ms)
    fut = self.submit(seeds, deadline_ms)
    # the wait outlives the deadline by a grace window: a request
    # PICKED before its deadline still completes (classic SLO
    # semantics — shed applies to queued requests only)
    return fut.result(timeout if timeout is not None
                      else dl / 1e3 + 30.0)

  # -- executor side --------------------------------------------------------
  def _loop(self) -> None:
    while not self._closed and not self._frozen:
      try:
        self.pump_once()
      except Exception:             # noqa: BLE001 — pump_once resolves
        # per-request errors onto futures; anything escaping here is a
        # harness bug, and dying silently would hang every later
        # caller — keep the loop alive
        if self._closed:
          return

  def pump_once(self, block: bool = True) -> int:
    """Drain ONE coalesced run end to end; returns requests served
    (0 = nothing to do / everything shed).  The executor loop calls
    this forever (``block=True``: wait for work); tests call it
    directly — ``block=False`` returns 0 immediately on an empty
    queue instead of waiting."""
    run = self.admission.take(self.engine.max_request_seeds(),
                              self.max_wait_s, block=block)
    if self._frozen:
      # simulated process death: the popped run is LOST unresolved —
      # the dead-replica shape the fleet redrive exists for
      return 0
    if not run:
      return 0
    with self._lock:
      self.in_flight = len(run)
    try:
      # the hot-swap quiesce point: a swap acquires this gate, so a
      # run never straddles a version change (and a swap never
      # interrupts a run)
      with self._dispatch_gate:
        return self._execute(run)
    finally:
      with self._lock:
        self.in_flight = 0

  def _execute(self, run: List[Request]) -> int:
    from ..testing import chaos
    sizes = [len(r.seeds) for r in run]
    total = sum(sizes)
    cap = self.engine.bucket_for(total)
    now = time.monotonic()
    recorder.emit('serving.coalesce', requests=len(run), seeds=total,
                  bucket=cap,
                  waited_ms=round(1e3 * (now - run[0].arrived), 3))
    for req in run:
      # admission enqueue → coalesce pickup, per request: the wait
      # the coalescing executor imposed (histogram always; a span
      # only when the request carries a trace context)
      wait_s = max(now - req.arrived, 0.0)
      self._m_queue_wait.observe(wait_s)
      if req.trace is not None:
        tracer.span('serving.queue_wait', req.trace, t0=req.arrived,
                    dur=wait_s)
    try:
      # chaos seam (executor flavor): a 'delay' here simulates a slow/
      # stuck dispatch — queued requests behind it expire and shed; a
      # 'drop' kills this dispatch with a typed error on every rider
      chaos.serving_request_check('dispatch', replica=self.name)
      with span('serving.infer', bucket=cap, requests=len(run),
                seeds=total):
        batch = self.engine.infer(
            np.concatenate([r.seeds for r in run]), cap=cap)
    except Exception as e:          # noqa: BLE001 — typed resolve,
      # never a silent drop: every rider of the failed dispatch gets
      # the error (an RPC handler re-raises it to its client)
      with self._lock:
        self.failed += len(run)
      self._m_failed.inc(len(run))
      for req in run:
        lat = req.waited_ms()
        if req.trace is not None:
          tracer.span('serving.dispatch_slice', req.trace, t0=now,
                      dur=time.monotonic() - now, bucket=cap,
                      requests=len(run),
                      error=f'{type(e).__name__}: {e}'[:160])
          tracer.resolve(req.trace, outcome='error', latency_ms=lat)
        req.future.set_error(e)
        self.slo.observe(lat, ok=False)
        recorder.emit('serving.request', seeds=len(req.seeds),
                      bucket=cap, coalesced=len(run), ok=False,
                      latency_ms=round(lat, 3),
                      error=f'{type(e).__name__}: {e}'[:160])
      if not isinstance(e, AdmissionRejected):
        # the black box: an executor fault is one of the fatal-ish
        # conditions an operator wants the last-N window for (typed
        # sheds are load signals, not faults — no bundle for those)
        postmortem.dump('serving.executor_fault', error=e,
                        extra={'bucket': cap, 'requests': len(run)})
      return 0
    off = 0
    self._last_fill = round(total / cap, 4) if cap else 0.0
    cold = getattr(self.engine, 'last_cold_fill', None)
    coll = getattr(self.engine, 'last_collect', None)
    hist = self._lat_hists.get(cap)
    if hist is None:
      hist = self._lat_hists[cap] = live.histogram(
          'serving.request_latency', labels={'bucket': cap})
    for req, k in zip(run, sizes):
      lat = req.waited_ms()
      if req.trace is not None:
        # record + resolve BEFORE the future fires: when a caller
        # (the RPC handler, the router) wakes, this request's spans
        # are already retained — /trace right after a serve returns
        # the complete tree, no eventual-consistency window
        end = time.monotonic()
        sid = tracer.span('serving.dispatch_slice', req.trace,
                          t0=now, dur=end - now, bucket=cap,
                          requests=len(run))
        if coll is not None and coll[0] >= now:
          # the engine's neighbor-sampling collect inside THIS
          # dispatch — with cold_fill below it splits the dispatch
          # into sampling cost vs feature-fill cost per trace
          tracer.span('serving.sample_collect', req.trace,
                      parent_id=sid, t0=coll[0], dur=coll[1])
        if cold is not None and cold[0] >= now:
          # the engine's tiered host fill inside THIS dispatch, one
          # view per traced rider (each tree stays self-contained)
          tracer.span('serving.cold_fill', req.trace, parent_id=sid,
                      t0=cold[0], dur=cold[1])
        tracer.resolve(req.trace, outcome='ok', latency_ms=lat)
      req.future.set_result(batch.slice(off, off + k))
      off += k
      # the trace_id lands as this bucket's OpenMetrics exemplar —
      # report.py jumps from the p99 bucket to the captured trace
      hist.observe(lat / 1e3,
                   exemplar=(req.trace['t'] if req.trace is not None
                             else None))
      self.slo.observe(lat, ok=True)
      recorder.emit('serving.request', seeds=k, bucket=cap,
                    coalesced=len(run), ok=True,
                    latency_ms=round(lat, 3))
    self.capacity.observe(cap, len(run), time.monotonic() - now)
    with self._lock:
      self.served_requests += len(run)
      self.served_seeds += total
      self.dispatches += 1
    self._m_requests.inc(len(run))
    self._m_seeds.inc(total)
    self._m_dispatches.inc()
    return len(run)

  # -- model lifecycle ------------------------------------------------------
  def swap_model(self, params, version: Optional[int] = None,
                 **kwargs) -> dict:
    """Drain-free hot model swap (see `serving.swap.hot_swap`):
    quiesce between coalesced runs, parity-check the candidate
    against the offline reference, commit-or-roll-back — zero dropped
    requests either way."""
    from .swap import hot_swap
    return hot_swap(self, params, version=version, **kwargs)

  def _slo_shed_feed(self, reason: str, waited_ms: float) -> None:
    self.slo.observe(waited_ms, ok=False)

  def quiesced(self) -> bool:
    """No queued work and no in-flight coalesced run — the drain
    point a planned retirement (elastic scale-in, ISSUE 19) waits for
    after flipping the admission door to draining: past it, shutdown
    resolves nothing but the already-empty queue."""
    return self.admission.depth() == 0 \
        and self._in_flight_snapshot() == 0

  # -- observability --------------------------------------------------------
  def _in_flight_snapshot(self) -> int:
    with self._lock:
      return self.in_flight

  def _fill_snapshot(self) -> Optional[float]:
    return self._last_fill

  def stats(self) -> dict:
    """The heartbeat serving block: queue depth, in-flight batch
    size, served/shed counters, per-bucket compile status, SLO
    window state."""
    with self._lock:
      out = {'in_flight': self.in_flight,
             'served_requests': self.served_requests,
             'served_seeds': self.served_seeds,
             'dispatches': self.dispatches,
             'failed': self.failed}
    out.update(self.admission.stats())
    out['closed'] = self._closed
    out['compile_status'] = self.engine.compile_status()
    out['model_version'] = self.engine.model_version
    out['max_wait_ms'] = round(self.max_wait_s * 1e3, 3)
    hr = self.capacity._headroom()
    if hr is not None:
      out['headroom_qps'] = hr     # the heartbeat copy of the gauge
    out['slo'] = self.slo.snapshot()
    return out

  def _health(self) -> dict:
    """The `/healthz` serving component: the heartbeat block plus a
    ``healthy`` verdict — unhealthy once closed, or if the executor
    thread was started and has since died (every queued caller would
    hang on its future but for the admission deadline)."""
    out = self.stats()
    executor_dead = (self._thread is not None
                     and not self._thread.is_alive())
    out['executor_alive'] = (self._thread is not None
                             and self._thread.is_alive())
    # a DRAINING tier is healthy: the hot-swap cutover sheds typed on
    # purpose and must not flip /healthz to 503 as if it were failing
    # (out['draining'] rides in from admission.stats() for routers)
    out['healthy'] = not self._closed and not executor_dead
    return out
