"""Drain-free hot model swap for the serving tier (ISSUE 13).

A model upgrade on a single-engine tier (PR 9) meant tearing the
frontend down: every queued request resolved with a shutdown
rejection and the replacement paid the full warmup before answering.
`hot_swap` replaces that with a versioned in-place swap that drops
NOTHING:

  1. **quiesce, don't flush** — admission enters ``draining``: NEW
     arrivals are refused typed (``reason='draining'`` with a
     ``retry_after_ms`` hint — a fleet router reroutes them, a bare
     client retries onto the new version) while requests ALREADY
     queued stay queued.  The executor finishes its in-flight
     coalesced run and parks at the dispatch gate — the swap happens
     BETWEEN runs, never under one.
  2. **validate before admitting** — the candidate params run a probe
     batch through the warm coalesced path and are compared against
     the engine's per-seed `offline_reference` UNDER THE SAME
     candidate: sampled nodes must match byte-identically and logits
     to float tolerance (the engine identity fine print).  This
     proves the candidate answers consistently through every serving
     path before any caller sees it.
  3. **commit or roll back** — parity passes: `ServingEngine.
     set_params` installs the candidate and bumps ``model_version``
     (tree structure/shape/dtype must match — the warm executables
     take params as an argument, so a conforming swap is
     ZERO-recompile).  Parity fails: the prior version keeps serving,
     the queued requests it still owes are served by it, and the
     caller gets a typed :class:`SwapParityError` plus a
     ``serving.swap`` event with ``rolled_back=True``.

Either way the drain window closes and the queue resumes — zero
dropped requests is the contract, pinned by tests.
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..telemetry.recorder import recorder


class SwapValidationError(ValueError):
  """The candidate params cannot ride the warm executables (tree
  structure / leaf shape / dtype drift) — refused before the drain
  window even opens."""


class SwapParityError(RuntimeError):
  """The candidate FAILED the offline-reference parity probe: the
  coalesced path and the per-seed reference disagreed under the new
  params.  The swap rolled back — the prior version is still serving
  and nothing was dropped.  ``max_err`` carries the worst logit
  divergence observed."""

  def __init__(self, msg: str, max_err: Optional[float] = None):
    super().__init__(msg)
    self.max_err = max_err


class SwapAbortedError(RuntimeError):
  """The swap never reached its parity probe: the executor failed to
  quiesce within the gate timeout (a stuck in-flight dispatch).  The
  prior version was never displaced and keeps serving — but this is
  an EXECUTOR-health signal, not a model-parity verdict, so it gets
  its own type (and still one ``serving.swap`` event, per the
  one-event-per-attempt schema contract)."""


def _tick(outcome: str) -> None:
  from ..telemetry.live import live
  live.counter('serving.swaps_total',
               labels={'outcome': outcome}).inc()


def _parity_probe(engine, params, probe_seeds, atol: float
                  ) -> float:
  """Run the candidate through the coalesced path and the per-seed
  offline reference; returns the max divergence (raises
  `SwapParityError` past tolerance).  Sampled nodes must agree
  BYTE-identically (params cannot change sampling — a mismatch means
  a broken executable, the exact thing to catch before traffic).
  `hold_graph` freezes the streaming graph version across the two
  paths: under live ingest a publish between them would otherwise
  fail a good candidate (ISSUE 14)."""
  with engine.hold_graph():
    cand = engine.infer(probe_seeds, params=params)
    ref = engine.offline_reference(probe_seeds, params=params)
  if not np.array_equal(cand.nodes, ref.nodes):
    raise SwapParityError(
        'candidate sampled different nodes through the coalesced '
        'path than the per-seed reference — corrupted executable or '
        'nondeterministic program; rolled back')
  max_err = 0.0
  for a, b in ((cand.logits, ref.logits), (cand.x, ref.x)):
    if a is None or b is None:
      continue
    err = float(np.max(np.abs(np.asarray(a, np.float64)
                              - np.asarray(b, np.float64))))
    max_err = max(max_err, err)
    if not np.isfinite(err) or err > atol:
      raise SwapParityError(
          f'candidate parity probe diverged (max |Δ| = {err:.3e} > '
          f'{atol:.1e}) between the coalesced path and the per-seed '
          'offline reference; rolled back', max_err=err)
  return max_err


def hot_swap(frontend, params, version: Optional[int] = None,
             probe_seeds=None, atol: float = 1e-4,
             gate_timeout_s: float = 30.0) -> dict:
  """Swap the frontend's engine onto new ``params`` without dropping
  a request.  Returns ``{'version', 'parity_max_err', 'drained_ms'}``
  on success; raises `SwapValidationError` (bad candidate shape,
  refused up front) or `SwapParityError` (probe mismatch, rolled
  back).  ``probe_seeds`` defaults to a small deterministic sample of
  the node space; ``atol`` is the logit tolerance (the engine's
  cross-shape identity is numerical, ~1e-6 — see its fine print)."""
  engine = frontend.engine
  if engine.model is None:
    raise SwapValidationError('hot_swap needs a model-serving engine')
  try:
    # refuse a malformed candidate BEFORE the drain window opens —
    # shape drift must cost the caller an error, not the tier a pause
    engine.validate_params(params)
  except ValueError as e:
    raise SwapValidationError(str(e)) from e
  if probe_seeds is None:
    n = engine.num_nodes
    probe_seeds = np.unique(
        np.linspace(0, n - 1, num=min(4, n)).astype(np.int64))
  t0 = time.monotonic()
  admission = frontend.admission
  # whole-attempt serialization: a second concurrent swap waits here,
  # outside any drain window — interleaved windows would let the
  # first swap's exit reopen admission under the second's probe
  swap_lock = getattr(frontend, '_swap_lock', None)
  if swap_lock is not None:
    swap_lock.acquire()
  admission.set_draining(True)
  gate_acquired = False
  try:
    # the quiesce point: the executor holds this gate across each
    # coalesced run, so acquiring it means we sit BETWEEN runs
    gate_acquired = frontend._dispatch_gate.acquire(
        timeout=gate_timeout_s)
    if not gate_acquired:
      drained_ms = 1e3 * (time.monotonic() - t0)
      recorder.emit('serving.swap', version=version, ok=False,
                    rolled_back=False, parity_max_err=None,
                    drained_ms=round(drained_ms, 3),
                    error=f'executor did not quiesce within '
                          f'{gate_timeout_s}s')
      _tick('aborted')
      raise SwapAbortedError(
          f'executor did not quiesce within {gate_timeout_s}s '
          '(in-flight dispatch stuck) — swap aborted, prior version '
          'still serving')
    try:
      max_err = _parity_probe(engine, params, probe_seeds, atol)
      new_version = engine.set_params(params, version)
    except Exception as e:          # noqa: BLE001 — ANY probe/commit
      # failure rolls back: the prior version was never displaced and
      # keeps serving the queue the moment the drain window closes
      if not isinstance(e, SwapParityError):
        e = SwapParityError(
            f'swap probe failed ({type(e).__name__}: {e}) — rolled '
            'back, prior version still serving')
      drained_ms = 1e3 * (time.monotonic() - t0)
      recorder.emit('serving.swap', version=version, ok=False,
                    rolled_back=True,
                    parity_max_err=getattr(e, 'max_err', None),
                    drained_ms=round(drained_ms, 3),
                    error=f'{type(e).__name__}: {e}'[:200])
      _tick('rolled_back')
      raise e
  finally:
    if gate_acquired:
      frontend._dispatch_gate.release()
    admission.set_draining(False)
    if swap_lock is not None:
      swap_lock.release()
  drained_ms = 1e3 * (time.monotonic() - t0)
  recorder.emit('serving.swap', version=new_version, ok=True,
                rolled_back=False, parity_max_err=round(max_err, 9),
                drained_ms=round(drained_ms, 3))
  _tick('ok')
  return {'version': new_version,
          'parity_max_err': max_err,
          'drained_ms': round(drained_ms, 3)}
