"""Adaptive HBM victim cache over cold-tier feature rows.

The tiered store's static ``split_ratio`` slice (`sort_by_in_degree`
hot prefix) leaves every cold lookup a synchronous host gather on the
batch critical path — BENCH_r05 measured the tiered mesh loader
*losing* throughput to the untiered one (250.6 vs 282.0 seeds/s, cold
hit rate 0.329).  PyTorch-Direct and Global Neighbor Sampling
(PAPERS.md) both show that a small dynamically-maintained device cache
plus overlapped cold access recovers most of the fully-resident
throughput.  This module is that cache, TPU-shaped:

  * **rows live in HBM** as a fixed-budget ``[C, D]`` ring; admissions
    update them with batched ``at[].set`` from rows that are already
    on device post-overlay — cached bytes NEVER round-trip through the
    host, and a hit is served by a device gather;
  * **policy lives on the host** as a CLOCK (second-chance) ring over
    the id tags: the per-batch cold-id multiset is analyzed where it
    already exists (the cold-overlay planning is host-side), so hit
    detection costs one vectorized ``searchsorted`` against a sorted
    mirror and no device sync of its own;
  * **admission is frequency-based**: candidates are ranked by their
    multiplicity in the batch's cold-id multiset (ids a batch touches
    many times are worth a slot most), and residents touched since the
    last sweep survive one eviction pass (the second-chance bit) — so
    a scan-like burst of one-touch ids cannot flush the reused set.

Three consumers share it: the single-chip `data.feature.Feature`
mixed path (`DeviceColdCache`), the mesh engines' cold overlay
(`MeshColdCache`, per-device shards), and the tiered fused epochs
(same `MeshColdCache`, served between chunk dispatches).

Knobs: ``GLT_COLD_CACHE_ROWS`` (rows per device; 0 disables,
unset/'auto' = `DEFAULT_BUDGET_FRACTION` of the cold rows).
"""
from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: 'auto' budget: fraction of the (per-partition max) cold rows kept
#: in the HBM ring.  15% matches the bench sweep's upper point and
#: keeps the cache an order of magnitude below the hot tier's spend.
DEFAULT_BUDGET_FRACTION = 0.15

#: per-admission-wave cap, as a fraction of capacity.  When the
#: batch's miss set exceeds the cache (the common steady state for a
#: beyond-HBM working set), admitting EVERY miss would churn the whole
#: ring each batch — residents never live long enough to earn hits and
#: the admission scatter dominates the overlay.  Capping the wave
#: keeps turnover bounded (a resident survives >= 1/frac waves even
#: untouched), lets the second-chance bit actually protect reused
#: rows, and cuts the per-batch plan/scatter cost by the same factor.
ADMIT_WAVE_FRACTION = 0.25

_ENV_ROWS = 'GLT_COLD_CACHE_ROWS'


def resolve_cache_rows(spec, cold_rows: int) -> int:
  """Resolve a ``cold_cache_rows`` knob: int = rows per device
  (0 disables), None/'auto' = ``GLT_COLD_CACHE_ROWS`` when set, else
  `DEFAULT_BUDGET_FRACTION` of ``cold_rows``."""
  if spec in (None, 'auto'):
    env = os.environ.get(_ENV_ROWS)
    if env is not None:
      try:
        return max(int(env), 0)
      except ValueError:
        pass
    if cold_rows <= 0:
      return 0
    return int(np.ceil(cold_rows * DEFAULT_BUDGET_FRACTION))
  return max(int(spec), 0)


class ClockShardCache:
  """CLOCK second-chance id→slot policy for ONE device shard.

  Holds only host-side metadata (tags, reference bits, the hand, the
  decayed visit-frequency sketch); the cached ROWS live in the owning
  cache's device array, addressed by the slot indices this class
  assigns.  All operations are vectorized over the batch's id arrays
  — no per-id python on the hot path.

  Admission ranking (r11): candidates are scored by the shard's
  `ops.gns.DecayedSketch` — the batch's cold-id multiset folded into
  an exponentially-decayed cross-batch visit count — instead of the
  per-batch multiset alone.  An id the stream revisits every few
  batches now outranks a one-batch burst, and the SAME sketch-selected
  residents feed the GNS sampling bias (`ops.gns.cached_set_bits`),
  so admission and sampling share one notion of "hot".  Cache
  contents never change batch bytes (PR 5's byte-identity contract),
  so the ranking change is invisible outside hit rates.
  """

  def __init__(self, capacity: int, bounds=None):
    from ..ops.gns import DecayedSketch
    self.capacity = int(capacity)
    self.ids = np.full(self.capacity, -1, np.int64)
    self.ref = np.zeros(self.capacity, np.uint8)
    self.hand = 0
    # with PartitionBook bounds attached the sketch also keeps the
    # decayed per-range visit histogram (gns.range_hotness export)
    self.sketch = DecayedSketch(bounds=bounds)
    #: bumped on every committed admission wave — consumers (the GNS
    #: bitmask refresh) rebuild derived state only when this moved
    self.version = 0
    self._sorted_ids = np.empty(0, np.int64)
    self._sorted_slots = np.empty(0, np.int32)

  @property
  def size(self) -> int:
    return len(self._sorted_ids)

  def _rebuild(self) -> None:
    occ = np.nonzero(self.ids >= 0)[0]
    order = np.argsort(self.ids[occ], kind='stable')
    self._sorted_ids = self.ids[occ][order]
    self._sorted_slots = occ[order].astype(np.int32)

  def lookup(self, ids: np.ndarray, active: Optional[np.ndarray] = None
             ) -> Tuple[np.ndarray, np.ndarray]:
    """``(hit, slot)`` for an id array of any shape; ``active`` masks
    which entries participate (e.g. the batch's cold mask).  Hits set
    the second-chance bit (the CLOCK "touch")."""
    ids = np.asarray(ids, np.int64)
    hit = np.zeros(ids.shape, bool)
    slot = np.zeros(ids.shape, np.int32)
    if self.size == 0:
      return hit, slot
    if active is not None:
      # probe only the active (cold) positions: the node table is
      # mostly hot/padding, and the searchsorted is the per-batch
      # host cost of every overlay
      sel = np.nonzero(active)
      sub = ids[sel]
      pos = np.clip(np.searchsorted(self._sorted_ids, sub), 0,
                    self.size - 1)
      h = self._sorted_ids[pos] == sub
      s = self._sorted_slots[pos]
      hit[sel] = h
      slot[sel] = np.where(h, s, 0)
      if h.any():
        self.ref[s[h]] = 1
      return hit, slot
    pos = np.clip(np.searchsorted(self._sorted_ids, ids), 0,
                  self.size - 1)
    hit = self._sorted_ids[pos] == ids
    slot = np.where(hit, self._sorted_slots[pos], 0).astype(np.int32)
    if hit.any():
      self.ref[slot[hit]] = 1
    return hit, slot

  def plan_admissions(self, cand_ids: np.ndarray,
                      cand_counts: Optional[np.ndarray] = None
                      ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Assign ring slots to (unique, not-resident) candidate ids.

    The batch's cold-id multiset (``cand_counts``) is folded into the
    shard's decayed visit-frequency sketch, and candidates are ranked
    by SKETCH score (descending) — cross-batch reuse outranks a
    one-batch burst; on a fresh sketch the ranking reduces exactly to
    the old per-batch multiset order.  Free slots fill first; the
    remainder comes from one batched CLOCK sweep: residents with a
    clear reference bit are victims in hand order, residents touched
    since the last sweep survive it (their bit is cleared — the
    second chance).  Returns ``(admitted_ids, slots, evicted)``; call
    `commit` after the device rows were written.
    """
    cand_ids = np.asarray(cand_ids, np.int64)
    if cand_ids.size == 0 or self.capacity == 0:
      return (np.empty(0, np.int64), np.empty(0, np.int32), 0)
    if cand_counts is None:
      cand_counts = np.ones(len(cand_ids), np.int64)
    self.sketch.update(cand_ids, cand_counts)
    order = np.lexsort((cand_ids, -self.sketch.score(cand_ids)))
    # bounded wave: empty slots may always fill, but EVICTING
    # admissions are capped at `ADMIT_WAVE_FRACTION` of the ring (see
    # the constant's rationale — full-ring churn earns no hits)
    n_free = int(np.count_nonzero(self.ids < 0))
    wave = max(int(self.capacity * ADMIT_WAVE_FRACTION), 1)
    cand = cand_ids[order][:min(self.capacity, n_free + wave)]
    free = np.nonzero(self.ids < 0)[0]
    n_free = min(len(free), len(cand))
    slots = [free[:n_free].astype(np.int32)]
    need = len(cand) - n_free
    evicted = 0
    if need > 0:
      sweep = (self.hand + np.arange(self.capacity)) % self.capacity
      occ = self.ids[sweep] >= 0
      fresh = self.ref[sweep] == 0
      clear = occ & fresh
      cand_pos = np.nonzero(clear)[0]
      if len(cand_pos) >= need:
        # batched CLOCK: victims are the first `need` clear-bit slots
        # in hand order; slots the hand passed over keep residency but
        # lose their bit (the second chance) — slots BEYOND the hand's
        # stop keep their bit, so reuse is only re-asserted where the
        # hand actually swept
        stop = cand_pos[need - 1]
        victims = sweep[cand_pos[:need]]
        self.ref[sweep[:stop + 1]] = 0
        self.hand = (int(sweep[stop]) + 1) % self.capacity
      else:
        # not enough clear bits in a full revolution: every slot ages
        # (the hand swept the whole ring), remainder comes from the
        # touched residents in hand order
        victims = np.concatenate([sweep[clear],
                                  sweep[occ & ~fresh]])[:need]
        self.ref[:] = 0
        if len(victims):
          self.hand = (int(victims[-1]) + 1) % self.capacity
      evicted = len(victims)
      if evicted:
        slots.append(victims.astype(np.int32))
    out_slots = np.concatenate(slots)
    return cand[:len(out_slots)], out_slots, evicted

  def commit(self, ids: np.ndarray, slots: np.ndarray) -> None:
    if len(ids):
      self.ids[slots] = ids
      self.ref[slots] = 0
      self.version += 1
    self._rebuild()

  def resident_ids(self) -> np.ndarray:
    """The current residents (sorted) — the dynamic half of the GNS
    cached set (`ops.gns.cached_set_bits`)."""
    return self._sorted_ids

  # -- DataPlaneState (utils.checkpoint): rings + the visit sketch --------
  def state_dict(self) -> dict:
    return {'ids': self.ids.copy(), 'ref': self.ref.copy(),
            'hand': self.hand, 'sketch': self.sketch.state_dict()}

  def load_state_dict(self, state: dict) -> None:
    ids = np.asarray(state['ids'], np.int64)
    if ids.shape != self.ids.shape:
      raise ValueError(
          f'cold-cache snapshot capacity {ids.shape[0]} does not match '
          f'this cache ({self.capacity}); resume with the same '
          f'GLT_COLD_CACHE_ROWS the snapshot was taken under')
    self.ids = ids
    self.ref = np.asarray(state['ref'], np.uint8).copy()
    self.hand = int(np.asarray(state['hand']))
    if 'sketch' in state:
      # pre-r11 snapshots carry no sketch: residency restores, the
      # learned visit frequencies restart cold (documented fallback)
      self.sketch.load_state_dict(state['sketch'])
    self.version += 1
    self._rebuild()


class CacheStats:
  """Flat counters shared by every cache flavor; consumers fold them
  into their own telemetry planes (the mesh samplers into
  ``exchange_stats``, the single-chip Feature into the global metrics
  registry)."""

  __slots__ = ('hits', 'misses', 'admits', 'evicts')

  def __init__(self):
    self.hits = self.misses = self.admits = self.evicts = 0

  def snapshot(self) -> dict:
    return {'hits': self.hits, 'misses': self.misses,
            'admits': self.admits, 'evicts': self.evicts}


#: scope -> backing-store keys of the four labeled live counters,
#: resolved (and registered for the /metrics rendering) once per scope
_CACHE_METRIC_KEYS: dict = {}


def _cache_metric_keys(scope: str):
  keys = _CACHE_METRIC_KEYS.get(scope)
  if keys is None:
    from ..telemetry.live import live
    labels = {'scope': scope}
    keys = _CACHE_METRIC_KEYS[scope] = (
        live.counter('cache.hits_total', labels=labels).key,
        live.counter('cache.misses_total', labels=labels).key,
        live.counter('cache.admits_total', labels=labels).key,
        live.counter('cache.evicts_total', labels=labels).key)
  return keys


def emit_cache_events(scope: str, hits: int, misses: int, admits: int,
                      evicts: int) -> None:
  """Per-overlay-batch flight-recorder events (only when the recorder
  is on; zero-count kinds are skipped so the JSONL stays signal).

  Always mirrors the counts into the live metrics vocabulary
  (``cache.*_total{scope=...}``, one lock acquisition) — the scrape
  must see cache economics even when the flight recorder is off.
  Registration goes through the live registry so the labeled
  per-scope instances render on ``/metrics`` (an instance the
  registry never saw would exist only in ``/varz``); the typed
  handles are resolved ONCE per scope (`_cache_metric_keys`), so the
  per-overlay-batch tick is a plain multi-key increment."""
  from ..utils.profiling import metrics
  hk, mk, ak, ek = _cache_metric_keys(scope)
  pairs = [(k, float(v)) for k, v in
           ((hk, hits), (mk, misses), (ak, admits), (ek, evicts))
           if v]
  if pairs:
    metrics.inc_many(pairs)
  from ..telemetry.recorder import recorder
  if not recorder.enabled:
    return
  if hits:
    recorder.emit('cache.hit', scope=scope, count=int(hits))
  if misses:
    recorder.emit('cache.miss', scope=scope, count=int(misses))
  if admits:
    recorder.emit('cache.admit', scope=scope, count=int(admits))
  if evicts:
    recorder.emit('cache.evict', scope=scope, count=int(evicts))


# -- single-device flavor (data.feature.Feature) ---------------------------

@jax.jit
def _serve_rows(x, rows_cache, hit, slot):
  """``x[i] = rows_cache[slot[i]] where hit`` — the device half of a
  cache hit (rows never leave HBM)."""
  return jnp.where(hit[:, None], rows_cache[slot], x)


@functools.partial(jax.jit, donate_argnums=(0,))
def _admit_rows(rows_cache, x, src, dst):
  """``rows_cache[dst[j]] = x[src[j]]`` — batched admission from rows
  already on device; padded entries carry ``dst == capacity`` and are
  dropped by the scatter."""
  return rows_cache.at[dst].set(x[src], mode='drop')


def _pad_pow2(n: int) -> int:
  from ..utils.padding import next_power_of_two
  return next_power_of_two(max(int(n), 1))


class DeviceColdCache:
  """Single-device victim cache: one `ClockShardCache` policy + a
  ``[C, D]`` HBM row ring + the jitted serve/admit programs.  Keys are
  the caller's choice (the Feature uses storage row indices, so the
  cache composes with ``id2index`` remaps for free)."""

  def __init__(self, capacity: int, dim: int, dtype,
               device: Optional[jax.Device] = None):
    self.policy = ClockShardCache(capacity)
    rows = jnp.zeros((max(int(capacity), 1), int(dim)), dtype)
    self.rows = (jax.device_put(rows, device) if device is not None
                 else rows)
    self.stats = CacheStats()
    # memory accounting (ISSUE 17): the row ring is the cache's whole
    # HBM bill (policy state is host-side numpy, negligible)
    from ..telemetry.memaccount import register_tier
    register_tier('cold_cache',
                  lambda r=self.rows: int(getattr(r, 'nbytes', 0)))

  @property
  def capacity(self) -> int:
    return self.policy.capacity

  def lookup(self, ids: np.ndarray,
             active: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """``(hit, slot)`` over ``ids`` (ticks the hit counter); callers
    drop hits from their host gather and then `serve_hits`."""
    hit, slot = self.policy.lookup(ids, active)
    self.stats.hits += int(hit.sum())
    return hit, slot

  def serve_hits(self, x: jax.Array, hit: np.ndarray,
                 slot: np.ndarray) -> jax.Array:
    if not hit.any():
      return x
    return _serve_rows(x, self.rows, jnp.asarray(hit),
                       jnp.asarray(slot))

  def admit(self, x: jax.Array, ids: np.ndarray,
            miss: np.ndarray) -> Tuple[int, int]:
    """Admit this batch's (corrected, on-device) miss rows: dedup the
    miss multiset, rank by multiplicity, write winners into the ring
    with one padded ``at[].set``.  Returns ``(admits, evicts)``."""
    self.stats.misses += int(miss.sum())
    if not miss.any() or self.capacity == 0:
      return 0, 0
    uniq, first, counts = np.unique(np.asarray(ids)[miss],
                                    return_index=True,
                                    return_counts=True)
    adm_ids, slots, evicted = self.policy.plan_admissions(uniq, counts)
    if not len(adm_ids):
      return 0, 0
    # src = position in x of the FIRST occurrence of each admitted id
    pos_of = dict(zip(uniq.tolist(),
                      np.nonzero(miss)[0][first].tolist()))
    src = np.asarray([pos_of[i] for i in adm_ids.tolist()], np.int32)
    a_pad = _pad_pow2(len(adm_ids))
    src_p = np.zeros(a_pad, np.int32)
    dst_p = np.full(a_pad, self.capacity, np.int32)    # dropped
    src_p[:len(src)] = src
    dst_p[:len(slots)] = slots
    self.rows = _admit_rows(self.rows, x, jnp.asarray(src_p),
                            jnp.asarray(dst_p))
    self.policy.commit(adm_ids, slots)
    self.stats.admits += len(adm_ids)
    self.stats.evicts += evicted
    return len(adm_ids), evicted

  # -- DataPlaneState: tag ring + clock hand + the HBM row ring -----------
  def state_dict(self) -> dict:
    return {'policy': self.policy.state_dict(),
            'rows': np.asarray(self.rows)}

  def load_state_dict(self, state: dict) -> None:
    self.policy.load_state_dict(state['policy'])
    self.rows = jax.device_put(
        np.asarray(state['rows'], self.rows.dtype),
        next(iter(self.rows.devices())))


# -- pinned-host zero-copy cold gather (r19, ISSUE 18) ---------------------

_PINNED_ENV = 'GLT_PALLAS_COLD'


def pinned_cold_enabled() -> bool:
  """Re-read ``GLT_PALLAS_COLD`` on every mixed-path build (kill
  switch, the `pallas_gather.pallas_enabled` discipline)."""
  return os.environ.get(_PINNED_ENV, '').strip().lower() in (
      '1', 'true', 'on', 'yes')


def _host_memory_sharding(dev):
  """Best available host-side memory placement for ``dev``:
  ``pinned_host`` where the backend has it (TPU — device-initiated
  DMA reads the buffer without a host staging copy), else the
  backend's plain host kind (CPU tier-1: the gather program is the
  exact functional twin, just without the zero-copy property).
  Returns ``(sharding, kind)``."""
  from jax.sharding import SingleDeviceSharding
  kinds = {m.kind for m in dev.addressable_memories()}
  for kind in ('pinned_host', 'unpinned_host'):
    if kind in kinds:
      return SingleDeviceSharding(dev, memory_kind=kind), kind
  return SingleDeviceSharding(dev), 'device'


class PinnedColdBuffer:
  """Cold-tier feature rows resident in pinned HOST memory, served by
  a device-initiated jitted gather (PyTorch-Direct / GIDS style —
  PAPERS.md arXiv 2101.07956, 2306.16384).

  The PR 5 overlay's cold fill is ``np.take`` on the host followed by
  a full-batch transfer — the host CPU touches every cold byte twice
  (gather + copy into the transfer buffer).  Here the cold rows are
  device_put ONCE into the accelerator-visible host memory kind and
  every per-batch fill is one compiled ``take`` whose output lands in
  device memory: the irregular access moves into the gather program
  (device-initiated DMA over PCIe/ICI on TPU), the host stops
  touching feature bytes per batch.  Byte parity with the ``np.take``
  path is exact — same rows, same dtype cast (applied once at build
  instead of per batch) — and pinned by tests/test_pallas_sample.py.

  Owns the ``pinned_host`` memaccount tier: the buffer is that
  tier's whole bill, so ``memory.tier_bytes{tier=pinned_host}``
  tracks it live on /metrics.

  Roofline note (r19): the fill is bandwidth-bound on the host link
  (PCIe gen3 ~12 GB/s practical per direction; ICI-attached hosts
  more), so the ceiling is link bandwidth x batch cold bytes — the
  ``np.take`` path it replaces was never near that line because the
  per-batch host gather + staging copy are latency/dispatch-bound
  (the r18 roofline's 1.355 GB/s untiered-XLA comparison point).
  The guarded bench row (`benchmarks/bench_pallas_sample.py`,
  ``pallas.feature_lookup_gbps``) holds the pinned path above that
  line on hardware; CPU tier-1 pins byte parity only."""

  def __init__(self, rows_np: np.ndarray, dim: int, dtype,
               device: Optional[jax.Device] = None):
    dev = device if device is not None else jax.devices()[0]
    arr = np.ascontiguousarray(rows_np)
    if dtype is not None:
      arr = arr.astype(dtype, copy=False)
    if arr.ndim != 2 or arr.shape[1] != int(dim):
      raise ValueError(f'expected [rows, {dim}] cold block, got '
                       f'{arr.shape}')
    sharding, self.memory_kind = _host_memory_sharding(dev)
    self.rows = jax.device_put(arr, sharding)
    from jax.sharding import SingleDeviceSharding
    self._gather = jax.jit(
        lambda rows, idx: jnp.take(rows, idx, axis=0),
        out_shardings=SingleDeviceSharding(dev))
    # capability probe: run one tiny gather end-to-end NOW so a
    # backend that cannot lower host-memory gathers fails here, at
    # build, where the caller can fall back — never per batch
    np.asarray(self._gather(self.rows, jnp.zeros((1,), jnp.int32)))
    from ..telemetry.memaccount import register_tier
    register_tier('pinned_host',
                  lambda r=self.rows: int(getattr(r, 'nbytes', 0)))

  def gather(self, idx: np.ndarray) -> jax.Array:
    """``[B] -> [B, D]`` device rows; indices are buffer-relative
    (caller subtracts the hot-row base) and must be in range."""
    return self._gather(self.rows, jnp.asarray(
        np.ascontiguousarray(idx, np.int32)))


def make_pinned_cold_buffer(rows_np, dim: int, dtype,
                            device=None) -> Optional[PinnedColdBuffer]:
  """`PinnedColdBuffer` when ``GLT_PALLAS_COLD`` is on and the
  backend can serve it, else None (the caller keeps the host
  ``np.take`` path — transparent fallback, byte-identical output).
  Emits the kernel dispatch/fallback event once, at build."""
  from ..telemetry.recorder import recorder
  if not pinned_cold_enabled():
    return None
  try:
    buf = PinnedColdBuffer(rows_np, dim, dtype, device=device)
  except ValueError:
    raise                          # contract errors surface as-is
  except Exception as ex:
    if recorder.enabled:
      recorder.emit('pallas.fallback', kernel='cold_gather',
                    reason=type(ex).__name__)
    return None
  if recorder.enabled:
    recorder.emit('pallas.dispatch', kernel='cold_gather',
                  rows=int(buf.rows.shape[0]),
                  memory_kind=str(buf.memory_kind))
  return buf


# -- mesh flavor (dist samplers + tiered fused epochs) ---------------------

@functools.lru_cache(maxsize=None)
def _mesh_cache_programs(mesh, axis: str):
  """Per-mesh jitted serve/admit programs over ``[P, ...]`` sharded
  stacks (cached like `_cold_overlay_programs`)."""
  from ..parallel.shard_map_compat import shard_map
  from jax.sharding import PartitionSpec as P
  s2, s3 = P(axis, None), P(axis, None, None)

  def _serve(x, rows, hit, slot):
    return jnp.where(hit[0][:, None], rows[0][slot[0]], x[0])[None]

  serve = jax.jit(shard_map(_serve, mesh=mesh,
                            in_specs=(s3, s3, s2, s2), out_specs=s3))

  def _admit(rows, x, src, dst):
    return rows[0].at[dst[0]].set(x[0][src[0]], mode='drop')[None]

  admit = jax.jit(shard_map(_admit, mesh=mesh,
                            in_specs=(s3, s3, s2, s2), out_specs=s3),
                  donate_argnums=(0,))
  return serve, admit


class MeshColdCache:
  """Per-device victim caches for the mesh engines: ``P`` (locally:
  ``len(host_parts)``) independent `ClockShardCache` policies over a
  ``[P, C, D]`` sharded HBM row stack.  Each device caches the cold
  rows *it* requested (requester-side, like PyTorch-Direct's per-GPU
  cache) — hits are served by a purely local gather, no collective.

  The host-side plan/commit calls take the same ``[pl, cap]`` stacked
  id/mask layout the cold-overlay planners already produce, and the
  device calls take the put function the sampler already owns
  (`put_stacked_host_local` on multi-host, a sharded `device_put`
  under a single controller) — so one cache implementation serves the
  per-batch loaders, the pipelined overlay, and the fused chunk path.
  """

  def __init__(self, capacity: int, dim: int, dtype, num_local: int,
               mesh, axis: str, put_stacked, bounds=None):
    self.capacity = int(capacity)
    self.mesh, self.axis = mesh, axis
    self._put = put_stacked
    self.shards = [ClockShardCache(capacity, bounds=bounds)
                   for _ in range(num_local)]
    self.rows = put_stacked(
        np.zeros((num_local, max(self.capacity, 1), int(dim)), dtype))
    self.stats = CacheStats()
    from ..telemetry.memaccount import register_tier
    register_tier('cold_cache',
                  lambda r=self.rows: int(getattr(r, 'nbytes', 0)))
    self._hotness_fns = ()
    if bounds is not None:
      # the sketches' decayed range mass becomes the live top-K
      # gns.range_hotness{partition=} gauges (evaluated at scrape)
      from ..ops.gns import register_hotness_gauges
      self._hotness_fns = register_hotness_gauges(
          lambda: [sh.sketch for sh in self.shards],
          max(len(np.asarray(bounds)) - 1, 1))

  @property
  def enabled(self) -> bool:
    return self.capacity > 0

  @property
  def version(self) -> int:
    """Sum of the shard ring versions — moved iff any shard's
    residency changed (the GNS bitmask refresh trigger)."""
    return sum(sh.version for sh in self.shards)

  def resident_ids(self) -> np.ndarray:
    """Union of every local shard's residents (global ids) — the
    dynamic half of the GNS cached set."""
    if not self.shards:
      return np.empty(0, np.int64)
    return np.unique(np.concatenate(
        [sh.resident_ids() for sh in self.shards]))

  def lookup(self, ids_l: np.ndarray, active: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized per-shard lookup over the stacked ``[pl, cap]`` id
    table.  Returns ``(hit [pl, cap], slot [pl, cap])``."""
    hit = np.zeros(ids_l.shape, bool)
    slot = np.zeros(ids_l.shape, np.int32)
    for j, sh in enumerate(self.shards):
      hit[j], slot[j] = sh.lookup(ids_l[j], active[j])
    self.stats.hits += int(hit.sum())
    return hit, slot

  def serve(self, x: jax.Array, hit: np.ndarray,
            slot: np.ndarray) -> jax.Array:
    # only a SINGLE controller may skip the dispatch on a locally
    # empty hit set — multiple controllers must all run the same
    # programs on the global arrays or they diverge
    if not hit.any() and jax.process_count() == 1:
      return x
    serve, _ = _mesh_cache_programs(self.mesh, self.axis)
    return serve(x, self.rows, self._put(hit), self._put(slot))

  def admit(self, x: jax.Array, ids_l: np.ndarray,
            miss: np.ndarray) -> Tuple[int, int]:
    """Admit the batch's miss rows (already corrected on device in
    ``x``).  The padded admission width is the max over LOCAL shards;
    multi-controller callers must agree on it globally — pass the
    agreed value through `admit_width` / `admit_planned`."""
    plans = self.plan_admissions(ids_l, miss)
    return self.commit_admissions(x, plans, self.admit_width(plans))

  def plan_admissions(self, ids_l: np.ndarray, miss: np.ndarray):
    self.stats.misses += int(miss.sum())
    plans = []
    for j, sh in enumerate(self.shards):
      m = miss[j]
      if not m.any() or self.capacity == 0:
        plans.append((np.empty(0, np.int64), np.empty(0, np.int32),
                      np.empty(0, np.int32), 0))
        continue
      uniq, first, counts = np.unique(ids_l[j][m], return_index=True,
                                      return_counts=True)
      adm, slots, ev = sh.plan_admissions(uniq, counts)
      pos_of = dict(zip(uniq.tolist(),
                        np.nonzero(m)[0][first].tolist()))
      src = np.asarray([pos_of[i] for i in adm.tolist()], np.int32)
      plans.append((adm, slots, src, ev))
    return plans

  def admit_width(self, plans) -> int:
    """Local padded admission width (power of two); multi-controller
    callers fold this into their capacity handshake."""
    n = max((len(p[0]) for p in plans), default=0)
    return _pad_pow2(n) if n else 0

  def commit_admissions(self, x: jax.Array, plans,
                        width: int) -> Tuple[int, int]:
    """Execute planned admissions at the (globally agreed) padded
    ``width``.  Returns ``(admits, evicts)``."""
    if width == 0:
      return 0, 0
    pl = len(self.shards)
    src_p = np.zeros((pl, width), np.int32)
    dst_p = np.full((pl, width), self.capacity, np.int32)  # dropped
    admits = evicts = 0
    for j, (adm, slots, src, ev) in enumerate(plans):
      src_p[j, :len(src)] = src
      dst_p[j, :len(slots)] = slots
      admits += len(adm)
      evicts += ev
    _, admit = _mesh_cache_programs(self.mesh, self.axis)
    self.rows = admit(self.rows, x, self._put(src_p),
                      self._put(dst_p))
    for sh, (adm, slots, _src, _ev) in zip(self.shards, plans):
      sh.commit(adm, slots)
    self.stats.admits += admits
    self.stats.evicts += evicts
    return admits, evicts

  # -- DataPlaneState: per-shard tag rings + the sharded HBM row stack ----
  def state_dict(self) -> dict:
    return {'shards': [sh.state_dict() for sh in self.shards],
            'rows': np.asarray(jax.device_get(self.rows))}

  def load_state_dict(self, state: dict) -> None:
    shard_states = state['shards']
    if len(shard_states) != len(self.shards):
      raise ValueError(
          f'cold-cache snapshot has {len(shard_states)} shards, this '
          f'mesh cache holds {len(self.shards)}')
    for sh, st in zip(self.shards, shard_states):
      sh.load_state_dict(st)
    self.rows = self._put(np.asarray(state['rows']))
