"""Two-tier feature store: HBM-resident hot rows + host-DRAM cold rows.

TPU-native replacement for the reference's ``UnifiedTensor``/``Feature``
stack (`csrc/cuda/unified_tensor.cu:29-96` — per-row warp gather across
{local HBM, peer-GPU HBM via NVLink, pinned host via UVA};
`data/feature.py:31-280` — split_ratio hot/cold split + DeviceGroup
sharding).  TPUs have no UVA and no per-warp gather kernel to write: the
idiomatic mapping is

  * **hot tier**: the first ``split_ratio`` fraction of rows (callers
    pre-sort by hotness, see :func:`~graphlearn_tpu.data.reorder.
    sort_by_in_degree`) lives as a `jax.Array` in device HBM; lookups
    are a single fused XLA gather feeding the MXU directly.
  * **cold tier**: remaining rows stay in TPU-VM host DRAM (numpy);
    misses are gathered on host and `device_put` once per batch —
    the explicit, async analog of the reference's UVA reads.

The reference's ``DeviceGroup`` replication/sharding across NVLink
cliques maps to sharding the hot tier over a `jax.sharding.Mesh` (see
:mod:`graphlearn_tpu.parallel`); single-device behavior is here.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.pallas_gather import gather_rows, pallas_enabled
from ..utils.padding import next_power_of_two
from ..utils.tensor import convert_to_array


@functools.partial(jax.jit, static_argnames=('use_pallas',))
def _device_gather(hot: jax.Array, ids: jax.Array, id2index, *,
                   use_pallas: bool) -> jax.Array:
  # `use_pallas` is part of the jit cache key so the GLT_PALLAS
  # kill-switch keeps working mid-process (resolved per call outside).
  valid = ids >= 0
  idx = jnp.where(valid, ids, 0).astype(jnp.int32)
  if id2index is not None:
    idx = id2index[idx].astype(jnp.int32)
    valid = valid & (idx >= 0)
    idx = jnp.where(valid, idx, 0)
  if use_pallas:
    out = gather_rows(hot, idx)
  else:
    out = jnp.take(hot, idx, axis=0)
  return jnp.where(valid[:, None], out, 0)


class _DeviceFeatsShim:
  """Stand-in for ``_host_feats`` when the table was constructed from
  a device array: shape/dtype metadata come from the device array;
  element access (rare — `host_get` and test assertions) pulls the
  table to host ONCE and caches it."""

  def __init__(self, arr: jax.Array):
    self._arr = arr
    self._np = None

  shape = property(lambda self: self._arr.shape)
  dtype = property(lambda self: self._arr.dtype)
  ndim = property(lambda self: self._arr.ndim)

  def _pull(self) -> np.ndarray:
    if self._np is None:
      self._np = np.asarray(self._arr)
    return self._np

  def __getitem__(self, key):
    return self._pull()[key]

  def __array__(self, dtype=None):
    a = self._pull()
    return a if dtype is None else a.astype(dtype)


class Feature:
  """Hot/cold split feature table addressed by global ids.

  Args:
    feature_array: ``[N, D]`` host array, rows assumed ordered
      hottest-first when ``split_ratio < 1`` (use ``sort_by_in_degree``).
    id2index: optional ``[max_id+1]`` map from global id to storage row
      (produced by hotness reordering); identity when ``None``.
    split_ratio: fraction of rows resident in device HBM.  ``1.0`` pins
      everything on device (DMA mode analog), ``0.0`` keeps everything
      on host (CPU mode analog).
    device: optional explicit device for the hot tier.
    dtype: optional storage dtype for the hot tier (e.g. ``bfloat16`` —
      halves HBM footprint and feeds the MXU natively).
    cold_cache_rows: HBM victim-cache budget over the cold tier
      (`data.cold_cache`): ``'auto'`` (default) sizes it to
      ``GLT_COLD_CACHE_ROWS`` or 15% of the cold rows, an int pins it,
      0 disables.  Cache hits are served by a device gather (the cold
      bytes stay in HBM across batches); only misses pay the host
      gather + transfer.  Values are byte-identical either way.
  """

  def __init__(self, feature_array, id2index: Optional[np.ndarray] = None,
               split_ratio: float = 1.0,
               device: Optional[jax.Device] = None,
               dtype=None, cold_cache_rows='auto'):
    if isinstance(feature_array, jax.Array):
      # device-native construction (tables produced on device — e.g.
      # `benchmarks/common.build_products_device`): the array IS the
      # hot tier; pulling it to host just to re-upload would cost a
      # full tunnel round trip per GB.
      if float(split_ratio) != 1.0:
        raise ValueError('device-resident feature input requires '
                         'split_ratio == 1.0 (a cold tier lives on '
                         'host by definition)')
      feats = feature_array if feature_array.ndim > 1 \
          else feature_array[:, None]
      self._host_feats = _DeviceFeatsShim(feats)
      self._id2index_host = (np.asarray(id2index, dtype=np.int64)
                             if id2index is not None
                             and not isinstance(id2index, jax.Array)
                             else None)
      self.split_ratio = 1.0
      self._device = device
      self._dtype = dtype
      hot = feats if dtype is None else feats.astype(dtype)
      if device is not None and device not in feats.devices():
        # an explicit device that differs from where the table lives
        # must move it — silently keeping the old placement made the
        # `device=` argument a no-op on the device-native path
        hot = jax.device_put(hot, device)
      self._hot = hot
      self._id2index_dev = (None if id2index is None
                            else jnp.asarray(id2index, jnp.int32))
      self.hot_rows = feats.shape[0]
      self._cache_rows = 0
      self._cold_cache = None
      self._pinned_cold = None
      self._pinned_failed = False
      self.cold_stats = {'lookups': 0, 'cold_lookups': 0}
      return
    feats = convert_to_array(feature_array)
    if feats.ndim == 1:
      feats = feats[:, None]
    self._host_feats = feats
    self._id2index_host = (np.asarray(id2index, dtype=np.int64)
                           if id2index is not None else None)
    self.split_ratio = float(split_ratio)
    self._device = device
    self._dtype = dtype
    self._hot = None            # jax.Array [hot_rows, D] (lazy)
    self._id2index_dev = None   # jax.Array (lazy)
    n = feats.shape[0]
    self.hot_rows = int(round(n * self.split_ratio))
    self.hot_rows = max(0, min(self.hot_rows, n))
    from .cold_cache import resolve_cache_rows
    # the cache only bites on the MIXED path (0 < hot_rows < n): the
    # fully-host path ships whole batches and the fully-HBM path has
    # no cold tier to cache
    self._cache_rows = (
        resolve_cache_rows(cold_cache_rows, n - self.hot_rows)
        if 0 < self.hot_rows < n else 0)
    self._cold_cache = None     # DeviceColdCache (lazy, see lazy_init)
    self._pinned_cold = None    # PinnedColdBuffer (lazy, env-gated)
    self._pinned_failed = False
    #: host-side cold accounting: lookups = valid ids per __getitem__,
    #: cold_lookups = ids past the hot tier (the cache denominator)
    self.cold_stats = {'lookups': 0, 'cold_lookups': 0}

  # -- lazy device residency (reference `Feature.lazy_init*`,
  # `data/feature.py:208-258`) -------------------------------------------
  def lazy_init(self):
    if self._hot is not None or self.hot_rows == 0:
      return
    dev = self._device or jax.devices()[0]
    hot = self._host_feats[:self.hot_rows]
    if self._dtype is not None:
      hot = hot.astype(self._dtype)
    self._hot = jax.device_put(hot, dev)
    if self._id2index_host is not None:
      self._id2index_dev = jax.device_put(self._id2index_host, dev)
    if self._cache_rows and self._cold_cache is None:
      from .cold_cache import DeviceColdCache
      self._cold_cache = DeviceColdCache(
          self._cache_rows, self.feature_dim, self.dtype, dev)

  @property
  def shape(self):
    return self._host_feats.shape

  @property
  def dtype(self):
    return self._dtype or self._host_feats.dtype

  @property
  def feature_dim(self) -> int:
    return self._host_feats.shape[1]

  def size(self, dim: int = 0) -> int:
    return self._host_feats.shape[dim]

  @property
  def hot_tier(self) -> Optional[jax.Array]:
    """The device-resident block (rows ``[0, hot_rows)``), for callers
    that gather inside jit when the whole table is HBM-resident."""
    self.lazy_init()
    return self._hot

  # -- lookup -------------------------------------------------------------
  def __getitem__(self, ids) -> jax.Array:
    """Gather rows by global id onto the device (see :meth:`get`)."""
    return self.get(ids)

  def get(self, ids, scope: str = 'feature') -> jax.Array:
    """Gather rows by global id onto the device.

    Counterpart of reference `Feature.__getitem__`
    (`data/feature.py:141-154`) → `GatherTensorKernel`.  Invalid ids
    (< 0, the padding sentinel) return zero rows, so padded batches
    flow straight into the model.

    Device-resident ids with a fully-HBM table take an all-device
    path: the reference's ids are already on-GPU likewise; a host
    round-trip here would serialize every batch on transfer latency.

    ``scope`` tags this lookup's cold-cache telemetry
    (``cache.hit``/``cache.miss``/... events): the epoch loaders use
    the default ``'feature'``; the online serving plane's per-request
    tiered path passes ``'serving'`` so a dashboard can split
    training-epoch from inference-traffic cache behavior out of one
    event stream.  Values are scope-independent (byte-identical).
    """
    self.lazy_init()
    if (isinstance(ids, jax.Array)
        and self.hot_rows >= self._host_feats.shape[0]):
      return self._device_get(ids)
    if self._id2index_dev is not None and self._id2index_host is None:
      # device-native table with a device-only id2index: the host
      # remap below would silently SKIP the mapping — route host ids
      # through the all-device path instead (table is fully hot by
      # the device-native constructor's contract)
      return self._device_get(jnp.asarray(np.asarray(ids),
                                          dtype=jnp.int32))
    ids_host = np.asarray(ids)
    valid = ids_host >= 0
    idx = np.where(valid, ids_host, 0)
    if self._id2index_host is not None:
      idx = self._id2index_host[idx]
      valid &= idx >= 0  # partial maps hold -1 for unmapped ids
      idx = np.where(valid, idx, 0)
    d = self.feature_dim

    if self.hot_rows >= self._host_feats.shape[0]:
      # Fully HBM-resident: one device gather — per-row DMA kernel on
      # TPU (`ops/pallas_gather.py`), fused XLA gather elsewhere.
      out = gather_rows(self._hot, jnp.asarray(idx.astype(np.int32)))
      return jnp.where(jnp.asarray(valid)[:, None], out, 0)

    cold_sel = valid & (idx >= self.hot_rows)
    self.cold_stats['lookups'] += int(valid.sum())
    self.cold_stats['cold_lookups'] += int(cold_sel.sum())
    if self.hot_rows == 0:
      # Fully host-resident: gather on host, one transfer.
      out = np.zeros((len(ids_host), d), dtype=self._host_feats.dtype)
      out[valid] = self._host_feats[idx[valid]]
      return jnp.asarray(out if self._dtype is None
                         else out.astype(self._dtype))
    if not cold_sel.any():
      out = gather_rows(self._hot, jnp.asarray(idx.astype(np.int32)))
      return jnp.where(jnp.asarray(valid)[:, None], out, 0)

    # chaos seam: the host cold tier is a service that can die
    # mid-epoch; a planned 'fail' raises here, on the batch that
    # needed it (the snapshot/resume layer turns it into a finished
    # epoch instead of a lost one)
    from ..testing import chaos
    chaos.cold_service_check('feature')
    # Mixed: device gather for hot rows; cold rows first checked
    # against the HBM victim cache (`data.cold_cache` — hits are a
    # device gather, the bytes never leave HBM); residual misses are
    # host-gathered into a COMPACT [n_miss_pad, D] buffer
    # (power-of-two padded so the number of compiled variants stays
    # logarithmic) and expanded on device by a per-row rank map.
    # Ships only the miss bytes — a full-[B, D] staging buffer or a
    # dynamic scatter is 10-200x slower (the former in transfer, the
    # latter recompiling on every batch's cold count).
    hot_idx = np.where(cold_sel, 0, idx)
    out = gather_rows(self._hot, jnp.asarray(hot_idx.astype(np.int32)))
    cache = self._cold_cache
    if cache is not None:
      hit, slot = cache.lookup(idx, cold_sel)
      miss_sel = cold_sel & ~hit
    else:
      hit = slot = None
      miss_sel = cold_sel
    n_miss = int(miss_sel.sum())
    pinned = self._pinned_buffer()
    if pinned is not None:
      # r19 zero-copy path (ISSUE 18): the cold block already lives in
      # the accelerator-visible host memory kind; one device-initiated
      # compiled gather replaces host np.take + per-batch transfer.
      # Same rows, same dtype cast (paid once at build) — the output
      # is byte-identical to the compact path below.
      rel = np.where(miss_sel, idx - self.hot_rows, 0).astype(np.int32)
      cold_rows = pinned.gather(rel)
    else:
      cold_pad = next_power_of_two(n_miss)
      compact = np.zeros((cold_pad, d), dtype=self._host_feats.dtype)
      compact[:n_miss] = self._host_feats[idx[miss_sel]]
      if self._dtype is not None:
        compact = compact.astype(self._dtype)
      # rank[i] = position of row i's value in the compact buffer
      rank = np.cumsum(miss_sel) - 1
      rank = np.where(miss_sel, rank, 0).astype(np.int32)
      cold_rows = jnp.take(jnp.asarray(compact), jnp.asarray(rank),
                           axis=0)
    hot_ok = jnp.asarray(valid & ~cold_sel)[:, None]
    cold_ok = jnp.asarray(miss_sel)[:, None]
    x = jnp.where(hot_ok, out, jnp.where(cold_ok, cold_rows, 0))
    if cache is not None:
      x = cache.serve_hits(x, hit, slot)
      admits, evicts = cache.admit(x, idx, miss_sel)
      from .cold_cache import emit_cache_events
      emit_cache_events(scope, int(hit.sum()), n_miss, admits,
                        evicts)
    return x

  def _device_get(self, ids: jax.Array) -> jax.Array:
    """All-device gather (fully-hot tables, device ids): no host sync."""
    return _device_gather(self._hot, ids, self._id2index_dev,
                          use_pallas=pallas_enabled())

  def _pinned_buffer(self):
    """The lazily built `data.cold_cache.PinnedColdBuffer` over the
    cold block, or None — ``GLT_PALLAS_COLD`` is re-read per batch
    (kill switch), the build/probe runs at most once (a backend that
    failed the probe falls back to the compact host path for the
    process lifetime, never re-probing per batch)."""
    from .cold_cache import make_pinned_cold_buffer, pinned_cold_enabled
    if not pinned_cold_enabled():
      return None
    if self._pinned_cold is None and not self._pinned_failed:
      dev = self._device or jax.devices()[0]
      self._pinned_cold = make_pinned_cold_buffer(
          self._host_feats[self.hot_rows:], self.feature_dim,
          self._dtype, dev)
      if self._pinned_cold is None:
        self._pinned_failed = True
    return self._pinned_cold

  # -- DataPlaneState (utils.checkpoint): the dynamic cache only ----------
  # (the hot tier and host table are reconstructed from the dataset —
  # snapshotting gigabytes of static rows would be pure dead weight)
  def state_dict(self) -> dict:
    self.lazy_init()
    if self._cold_cache is None:
      return {'has_cache': 0}
    return {'has_cache': 1, 'cache': self._cold_cache.state_dict()}

  def load_state_dict(self, state: dict) -> None:
    self.lazy_init()
    if not int(np.asarray(state.get('has_cache', 0))):
      return
    if self._cold_cache is None:
      return                       # cache disabled this run: warmth lost
    self._cold_cache.load_state_dict(state['cache'])

  def host_get(self, ids=None) -> np.ndarray:
    """Host-side gather (reference ``Feature.cpu_get``,
    `data/feature.py:156`); full table when ``ids`` is None."""
    if ids is None:
      return self._host_feats
    ids = np.asarray(ids)
    valid = ids >= 0
    idx = np.where(valid, ids, 0)
    if self._id2index_host is not None:
      idx = self._id2index_host[idx]
      valid &= idx >= 0
      idx = np.where(valid, idx, 0)
    out = np.zeros((len(ids), self.feature_dim),
                   dtype=self._host_feats.dtype)
    out[valid] = self._host_feats[idx[valid]]
    return out

  def __repr__(self):
    return (f'Feature(shape={self._host_feats.shape}, '
            f'split_ratio={self.split_ratio}, hot_rows={self.hot_rows})')
