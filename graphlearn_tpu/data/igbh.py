"""IGBH (Illinois Graph Benchmark, heterogeneous) on-disk ingestion.

Torch-free reader for the IGBH npy layout the reference consumes
through its `IGBHeteroDataset` (`examples/igbh/dataset.py:51-157`):

    <root>/<size>/processed/
        <src>__<rel>__<dst>/edge_index.npy        # [E, 2] int
        <node_type>/node_feat.npy                 # [N, D]
        paper/node_label_19.npy | node_label_2K.npy

Sizes: tiny / small / medium / large / full.  Splits follow the
reference's convention: paper ids ordered so train = first 60%,
val = next 20%, test = the rest (`dataset.py:151-157`).

``mmap=True`` (default) keeps feature tables on disk until sliced —
at IGBH-large (~600 M nodes) materializing them up front is neither
possible nor needed: the partitioner streams chunks and the tiered
distributed store (`DistHeteroDataset.from_full_graph(split_ratio=…)`)
keeps only hot rows in HBM.
"""
from __future__ import annotations

from pathlib import Path
from typing import Dict, Optional

import numpy as np

__all__ = ['load_igbh_dir', 'igbh_num_classes', 'partition_igbh']

#: label-file -> class-count mapping (reference `dataset.py:96`)
LABEL_FILES = {False: ('node_label_19.npy', 19),
               True: ('node_label_2K.npy', 2983)}


def igbh_num_classes(use_label_2k: bool = False) -> int:
  return LABEL_FILES[bool(use_label_2k)][1]


def load_igbh_dir(root, dataset_size: str = 'tiny',
                  use_label_2k: bool = False, mmap: bool = True,
                  in_memory: Optional[bool] = None,
                  add_reverse: bool = True,
                  symmetrize_cites: bool = True) -> Dict:
  """Read an IGBH directory.

  Returns ``{'edge_index_dict': {(s, rel, d): (rows, cols)},
  'node_feat_dict': {ntype: [N, D]}, 'paper_labels': [N_paper],
  'num_nodes_dict': {...}, 'train_idx'/'val_idx'/'test_idx': [...]}``.
  Edge/feature dirs are DISCOVERED (``<s>__<rel>__<d>`` naming), so
  the large/full extras (journal, conference) come in automatically.

  The reference trains on a CONSTRUCTED graph, not the raw relations
  (`dataset.py:79-96`): ``add_reverse`` synthesizes
  ``(d, rev_<rel>, s)`` for every cross-type relation (so e.g. author
  -> paper message passing and sampling exist), and
  ``symmetrize_cites`` rebuilds ``paper cites paper`` as
  both-directions + one self-loop per paper (the reference's
  to_undirected + remove/add_self_loops).  Both default on to match
  the reference recipe; reversed/symmetrized relations materialize
  those edge arrays (the rest stay mmap).
  """
  if in_memory is not None:      # reference flag name, inverted sense
    mmap = not in_memory
  base = Path(root) / dataset_size / 'processed'
  if not base.is_dir():
    raise FileNotFoundError(f'IGBH processed dir not found: {base}')
  mode = 'r' if mmap else None
  edge_index_dict = {}
  node_feat_dict = {}
  for d in sorted(base.iterdir()):
    if not d.is_dir():
      continue
    if '__' in d.name:
      p = d / 'edge_index.npy'
      if p.exists():
        s, rel, t = d.name.split('__')
        ei = np.load(p, mmap_mode=mode)
        edge_index_dict[(s, rel, t)] = (ei[:, 0], ei[:, 1])
    else:
      p = d / 'node_feat.npy'
      if p.exists():
        node_feat_dict[d.name] = np.load(p, mmap_mode=mode)
  if 'paper' not in node_feat_dict:
    raise FileNotFoundError(f'no paper/node_feat.npy under {base}')
  if symmetrize_cites and ('paper', 'cites', 'paper') in edge_index_dict:
    r, c = edge_index_dict[('paper', 'cites', 'paper')]
    r = np.asarray(r, np.int64)
    c = np.asarray(c, np.int64)
    keep = r != c                       # remove_self_loops
    n_paper = int(node_feat_dict['paper'].shape[0])
    # both directions, COALESCED (to_undirected dedupes), + self loops
    key = np.unique(np.concatenate([r[keep] * n_paper + c[keep],
                                    c[keep] * n_paper + r[keep]]))
    loops = np.arange(n_paper, dtype=np.int64)
    edge_index_dict[('paper', 'cites', 'paper')] = (
        np.concatenate([key // n_paper, loops]),
        np.concatenate([key % n_paper, loops]))
  if add_reverse:
    for (s, rel, t) in list(edge_index_dict):
      if s != t:
        r, c = edge_index_dict[(s, rel, t)]
        edge_index_dict[(t, f'rev_{rel}', s)] = (np.asarray(c),
                                                 np.asarray(r))
  label_file, _ = LABEL_FILES[bool(use_label_2k)]
  labels = np.load(base / 'paper' / label_file, mmap_mode=mode)
  labels = np.asarray(labels).reshape(-1).astype(np.int64)
  num_nodes = {nt: f.shape[0] for nt, f in node_feat_dict.items()}
  n_paper = num_nodes['paper']
  n_train = int(n_paper * 0.6)
  n_val = int(n_paper * 0.2)
  return {
      'edge_index_dict': edge_index_dict,
      'node_feat_dict': node_feat_dict,
      'paper_labels': labels,
      'num_nodes_dict': num_nodes,
      'train_idx': np.arange(0, n_train),
      'val_idx': np.arange(n_train, n_train + n_val),
      'test_idx': np.arange(n_train + n_val, n_paper),
  }


def partition_igbh(root, out_dir, num_parts: int,
                   dataset_size: str = 'tiny',
                   use_label_2k: bool = False, seed: int = 0) -> None:
  """Write the offline HETERO partition layout for an IGBH dir —
  feeds `DistHeteroDataset.from_partition_dir` /
  `HostHeteroDataset.from_partition_dir` (the role of reference
  `examples/igbh/partition.py`)."""
  from ..partition import RandomPartitioner
  d = load_igbh_dir(root, dataset_size, use_label_2k)
  RandomPartitioner(
      out_dir, num_parts, d['num_nodes_dict'],
      {et: (np.asarray(r), np.asarray(c))
       for et, (r, c) in d['edge_index_dict'].items()},
      node_feat={nt: np.asarray(f)
                 for nt, f in d['node_feat_dict'].items()},
      node_label={'paper': d['paper_labels'].astype(np.int32)},
      seed=seed).partition()
