"""CSR topology container.

Counterpart of reference `data/graph.py:28-122` (``CSRTopo``): accepts a
COO edge list, CSR, or CSC and canonicalizes to CSR on the host.  The
device-resident handle lives in :mod:`graphlearn_tpu.data.graph`.
"""
from __future__ import annotations

from typing import Optional, Tuple, Union

import numpy as np

from ..utils import (convert_to_array, coo_to_csr, csr_to_coo,
                     degrees_from_indptr)


class CSRTopo:
  """Canonical topology in CSR form (host numpy arrays).

  Args:
    edge_index: ``(rows, cols)`` pair or ``[2, E]`` array when
      ``layout='COO'``; ``(indptr, indices)`` when ``layout`` is
      ``'CSR'``/``'CSC'``.
    edge_ids: optional per-edge global ids; fabricated as consecutive
      ints when absent (matching reference semantics).
    layout: one of ``'COO' | 'CSR' | 'CSC'``.
    num_nodes: optional node-count override (ids may exceed max seen).
  """

  def __init__(
      self,
      edge_index: Union[np.ndarray, Tuple[np.ndarray, np.ndarray]],
      edge_ids: Optional[np.ndarray] = None,
      layout: str = 'COO',
      num_nodes: Optional[int] = None,
  ):
    layout = layout.upper()
    if layout == 'COO':
      edge_index = convert_to_array(edge_index)
      if isinstance(edge_index, (tuple, list)):
        rows, cols = np.asarray(edge_index[0]), np.asarray(edge_index[1])
      else:
        rows, cols = edge_index[0], edge_index[1]
      self._indptr, self._indices, self._edge_ids = coo_to_csr(
          rows, cols, num_nodes, edge_ids)
    elif layout in ('CSR', 'CSC'):
      indptr, indices = edge_index
      self._indptr = np.asarray(convert_to_array(indptr), dtype=np.int64)
      self._indices = np.asarray(convert_to_array(indices))
      if edge_ids is None:
        edge_ids = np.arange(len(self._indices), dtype=np.int64)
      self._edge_ids = np.asarray(convert_to_array(edge_ids))
      if layout == 'CSC':
        # Reference accepts CSC by transposing into CSR (data/graph.py).
        # The node count encoded in len(indptr)-1 must survive the
        # round-trip even when trailing nodes are isolated, and source
        # ids (the CSC indices) may exceed the destination count.
        n = max(num_nodes or 0, len(self._indptr) - 1,
                int(self._indices.max(initial=-1)) + 1)
        rows, cols = csr_to_coo(self._indptr, self._indices)
        self._indptr, self._indices, self._edge_ids = coo_to_csr(
            cols, rows, n, self._edge_ids)
      else:
        # Re-sort columns within rows: downstream binary-search ops
        # (`ops/negative.py:edge_in_csr`) require sorted-CSR, which
        # user-provided CSR input does not guarantee.
        rows, cols = csr_to_coo(self._indptr, self._indices)
        n = max(num_nodes or 0, len(self._indptr) - 1)
        self._indptr, self._indices, self._edge_ids = coo_to_csr(
            rows, cols, n, self._edge_ids)
    else:
      raise ValueError(f'Unsupported layout {layout!r}')
    self._indices = self._indices.astype(np.int32, copy=False)

  @property
  def indptr(self) -> np.ndarray:
    return self._indptr

  @property
  def indices(self) -> np.ndarray:
    return self._indices

  @property
  def edge_ids(self) -> np.ndarray:
    return self._edge_ids

  @property
  def num_nodes(self) -> int:
    return len(self._indptr) - 1

  @property
  def num_edges(self) -> int:
    return len(self._indices)

  @property
  def degrees(self) -> np.ndarray:
    """Out-degree of every node (reference `CSRTopo.degrees`)."""
    return degrees_from_indptr(self._indptr)

  @property
  def max_degree(self) -> int:
    if not hasattr(self, '_max_degree'):
      d = self.degrees
      self._max_degree = int(d.max()) if len(d) else 0
    return self._max_degree

  def to_coo(self) -> Tuple[np.ndarray, np.ndarray]:
    return csr_to_coo(self._indptr, self._indices)

  def to_csc(self) -> 'CSRTopo':
    rows, cols = self.to_coo()
    # Bipartite-style topologies may reference column ids beyond the
    # row count; the transpose must cover them.
    n = max(self.num_nodes, int(self._indices.max(initial=-1)) + 1)
    return CSRTopo((cols, rows), edge_ids=self._edge_ids, layout='COO',
                   num_nodes=n)

  def __repr__(self):
    return (f'CSRTopo(num_nodes={self.num_nodes}, '
            f'num_edges={self.num_edges})')
