from .topology import CSRTopo
from .graph import Graph
