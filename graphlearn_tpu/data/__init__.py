from .topology import CSRTopo
from .graph import Graph
from .feature import Feature
from .reorder import sort_by_in_degree, sort_by_hotness
from .dataset import Dataset
from .table_dataset import (CsvTableReader, NpzTableReader, OdpsTableReader,
                            TableDataset, TableReader, read_edge_table,
                            read_node_table)
from .ogb import (load_ogb_dir, ogb_to_dataset, partition_ogb,
                  save_binary)
from .igbh import igbh_num_classes, load_igbh_dir, partition_igbh
