"""Hotness reordering for the feature cache.

Counterpart of reference `data/reorder.py:19-31`
(``sort_by_in_degree``): order feature rows so the most-accessed nodes
occupy the leading rows, which the :class:`~graphlearn_tpu.data.feature.
Feature` store pins in HBM.  In-degree is the access proxy — under
uniform neighbor sampling a node is touched proportionally to how many
edges point at it.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .topology import CSRTopo


def sort_by_in_degree(
    feature_array: np.ndarray,
    split_ratio: float,
    csr_topo: CSRTopo,
) -> Tuple[np.ndarray, np.ndarray]:
  """Reorder rows hottest-first by in-degree.

  Args:
    feature_array: ``[N, D]`` host features indexed by global id.
    split_ratio: fraction destined for the HBM tier (only used to report
      how much of the table the reorder actually protects; the full
      permutation is applied regardless, matching the reference).
    csr_topo: out-edge CSR; in-degree is computed by counting each id's
      appearances in ``indices``.

  Returns:
    ``(reordered_feats, id2index)`` where
    ``reordered_feats[id2index[v]] == feature_array[v]``.
  """
  feats = np.asarray(feature_array)
  in_deg = np.bincount(csr_topo.indices, minlength=feats.shape[0])
  in_deg = in_deg[:feats.shape[0]]
  del split_ratio  # full permutation either way; ratio applied by Feature
  return sort_by_hotness(feats, in_deg)


def sort_by_hotness(
    feature_array: np.ndarray,
    hotness: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
  """Same contract with an arbitrary hotness score (e.g. sampling
  probabilities from :func:`graphlearn_tpu.ops.cal_nbr_prob`, the
  frequency-partitioner signal)."""
  feats = np.asarray(feature_array)
  order = np.argsort(-np.asarray(hotness), kind='stable')
  id2index = np.empty(feats.shape[0], dtype=np.int64)
  id2index[order] = np.arange(feats.shape[0], dtype=np.int64)
  return feats[order], id2index
