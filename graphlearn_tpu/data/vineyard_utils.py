"""Vineyard (GraphScope) in-memory graph-store connectors — gated.

Counterpart of reference `data/vineyard_utils.py:15-55` +
`csrc/cpu/vineyard_utils.cc` (optional, behind ``WITH_VINEYARD``):
read CSR topology and vertex/edge feature columns straight from a
vineyard object store shared with GraphScope.

Vineyard is not part of this image (and its client is Linux-x86
specific); the API surface is kept so GraphScope deployments can drop
in the real client — every function imports lazily and raises with
guidance otherwise, exactly like the reference's build-time gate.
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np


def _client():
  try:
    import vineyard  # noqa: F401
    return vineyard
  except ImportError as e:
    raise ImportError(
        'vineyard is not installed; these connectors need a GraphScope '
        'deployment (pip install vineyard-graphlearn or use '
        'CsvTableReader/NpzTableReader ingestion instead)') from e


def vineyard_to_csr(sock: str, object_id: str, v_label: int, e_label: int,
                    edge_dir: str = 'out'
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
  """CSR of one (vertex-label, edge-label) fragment
  (reference ``vineyard_to_csr``, `py_export.cc:52-56`)."""
  vy = _client()
  client = vy.connect(sock)
  frag = client.get(vy.ObjectID(object_id))
  raise NotImplementedError(
      f'wire the GraphScope fragment accessors for {type(frag)} here; '
      'the TPU data plane consumes (indptr, indices, edge_ids) numpy '
      'arrays via CSRTopo')


def load_vertex_feature_from_vineyard(sock: str, object_id: str,
                                      cols: List[str], v_label: int
                                      ) -> np.ndarray:
  """Vertex feature columns (reference ``LoadVertexFeatures``)."""
  _client()
  raise NotImplementedError(
      'map the fragment vertex table columns to a [N, D] numpy array '
      'and feed Dataset.init_node_features')


def load_edge_feature_from_vineyard(sock: str, object_id: str,
                                    cols: List[str], e_label: int
                                    ) -> np.ndarray:
  """Edge feature columns (reference ``LoadEdgeFeatures``)."""
  _client()
  raise NotImplementedError(
      'map the fragment edge table columns to a [E, D] numpy array '
      'and feed Dataset.init_edge_features')
