"""Vineyard (GraphScope) connectors — a documented NON-GOAL.

The reference optionally reads CSR topology and feature columns from a
vineyard object store shared with GraphScope (`data/vineyard_utils.py:
15-55`, `csrc/cpu/vineyard_utils.cc:1-247`, behind ``WITH_VINEYARD``).
This framework does not implement that integration:

  * vineyard's client is not available in TPU-VM images and cannot be
    validated here; shipping accessor code that has never executed
    against a real fragment would be pretend-coverage;
  * the integration's VALUE in the reference is zero-copy handoff from
    GraphScope's sampling-adjacent services on the same host — a
    deployment topology that does not exist on TPU pods, where data
    arrives via GCS/files into host DRAM anyway.

Supported ingestion paths with the same outcome (arrays into
`Dataset.init_graph` / `init_node_features` / `init_edge_features`):

  * `graphlearn_tpu.data.table_dataset` — csv / npz / ODPS-style
    record readers (reference `TableDataset` parity);
  * any numpy/arrow pipeline producing ``(rows, cols)`` +
    ``[N, D]`` / ``[E, D]`` arrays.

The reference API names are kept as explicit tombstones so a
GraphScope user gets actionable guidance instead of an AttributeError.
"""
from __future__ import annotations

_MSG = ('vineyard/GraphScope integration is a documented non-goal of '
        'graphlearn_tpu (no vineyard client on TPU-VM images; see '
        'data/vineyard_utils.py for rationale). Export the fragment '
        'to numpy/npz and use Dataset.init_graph / '
        'data.table_dataset readers instead.')


def vineyard_to_csr(*args, **kwargs):
  """Reference ``vineyard_to_csr`` (`py_export.cc:52-56`): non-goal."""
  raise NotImplementedError(_MSG)


def load_vertex_feature_from_vineyard(*args, **kwargs):
  """Reference ``LoadVertexFeatures``: non-goal."""
  raise NotImplementedError(_MSG)


def load_edge_feature_from_vineyard(*args, **kwargs):
  """Reference ``LoadEdgeFeatures``: non-goal."""
  raise NotImplementedError(_MSG)
