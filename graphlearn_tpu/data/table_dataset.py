"""Tabular ingestion: build a `Dataset` from table readers.

Counterpart of reference `data/table_dataset.py:30-162` (``TableDataset``),
which streams ODPS (MaxCompute) tables through ``common_io`` readers —
edge tables of ``(src, dst)`` records and node tables of
``(id, "f0:f1:...:fd")`` records — into ``Dataset.init_*``.

TPU redesign: the reader is a small pluggable protocol instead of a
hard ``common_io`` dependency, so the same record formats ingest from
whatever the cluster actually has:

  * `CsvTableReader` — local/NFS csv or tsv files;
  * `NpzTableReader` — columnar ``.npz`` dumps;
  * `OdpsTableReader` — the reference's source, used when ``common_io``
    is importable (PAI images), otherwise raising with guidance.

Record formats are the reference's exactly (edge: two int64 columns;
node: int64 id + colon-joined floats, bytes or str), so PAI table dumps
port 1:1.
"""
from __future__ import annotations

import csv
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..typing import EdgeType, NodeType
from .dataset import Dataset


class TableReader:
  """Minimal reader protocol: iterate batches of records (tuples)."""

  def batches(self, batch_size: int) -> Iterator[List[tuple]]:
    raise NotImplementedError


class CsvTableReader(TableReader):
  """CSV/TSV file of records; delimiter auto-sniffed from the suffix."""

  def __init__(self, path, delimiter: Optional[str] = None):
    self.path = Path(path)
    if delimiter is None:
      delimiter = '\t' if self.path.suffix in ('.tsv', '.txt') else ','
    self.delimiter = delimiter

  def batches(self, batch_size: int) -> Iterator[List[tuple]]:
    with open(self.path, newline='') as f:
      reader = csv.reader(f, delimiter=self.delimiter)
      buf: List[tuple] = []
      for row in reader:
        if not row:
          continue
        buf.append(tuple(row))
        if len(buf) >= batch_size:
          yield buf
          buf = []
      if buf:
        yield buf


class NpzTableReader(TableReader):
  """Columnar ``.npz``: keys are columns, records are zipped rows."""

  def __init__(self, path, columns: Optional[Sequence[str]] = None):
    self.path = Path(path)
    self.columns = columns

  def batches(self, batch_size: int) -> Iterator[List[tuple]]:
    data = np.load(self.path, allow_pickle=False)
    cols = list(self.columns or data.files)
    arrays = [data[c] for c in cols]
    n = len(arrays[0])
    if any(len(a) != n for a in arrays):
      raise ValueError(
          f'npz columns {cols} have mismatched lengths '
          f'{[len(a) for a in arrays]}')
    for lo in range(0, n, batch_size):
      hi = min(lo + batch_size, n)
      yield list(zip(*(a[lo:hi] for a in arrays)))


class OdpsTableReader(TableReader):
  """ODPS table via ``common_io`` (reference `table_dataset.py:82-95`);
  available only on PAI images that ship the reader."""

  def __init__(self, table: str, reader_threads: int = 10,
               reader_capacity: int = 10240):
    try:
      import common_io  # noqa: F401
    except ImportError as e:
      raise ImportError(
          'OdpsTableReader needs the PAI `common_io` package; use '
          'CsvTableReader/NpzTableReader for file-based tables') from e
    self.table = table
    self.reader_threads = reader_threads
    self.reader_capacity = reader_capacity

  def batches(self, batch_size: int) -> Iterator[List[tuple]]:
    import common_io
    reader = common_io.table.TableReader(
        self.table, num_threads=self.reader_threads,
        capacity=self.reader_capacity)
    try:
      while True:
        try:
          yield list(reader.read(batch_size,
                                 allow_smaller_final_batch=True))
        except common_io.exception.OutOfRangeException:
          return
    finally:
      reader.close()


TableLike = Union[TableReader, str, Path]


def _as_reader(table: TableLike) -> TableReader:
  if isinstance(table, TableReader):
    return table
  p = Path(table)
  if p.suffix == '.npz':
    return NpzTableReader(p)
  if p.suffix in ('.csv', '.tsv', '.txt'):
    return CsvTableReader(p)
  return OdpsTableReader(str(table))


def read_edge_table(table: TableLike, batch_size: int = 65536
                    ) -> Tuple[np.ndarray, np.ndarray]:
  """Stream ``(src, dst)`` records into two int64 arrays
  (reference edge loop, `table_dataset.py:80-106`)."""
  rows, cols = [], []
  for batch in _as_reader(table).batches(batch_size):
    rows.append(np.array([r[0] for r in batch], dtype=np.int64))
    cols.append(np.array([r[1] for r in batch], dtype=np.int64))
  if not rows:
    return (np.zeros(0, np.int64), np.zeros(0, np.int64))
  return np.concatenate(rows), np.concatenate(cols)


def _decode_feat(v) -> List[float]:
  if isinstance(v, bytes):
    v = v.decode()
  if isinstance(v, str):
    return [float(x) for x in v.split(':')]
  return list(np.asarray(v, dtype=np.float64).ravel())


def read_node_table(table: TableLike, batch_size: int = 65536
                    ) -> np.ndarray:
  """Stream ``(id, "f0:f1:...")`` records into an id-ordered ``[N, D]``
  float32 array (reference node loop + sort, `table_dataset.py:
  108-140`): features land at row ``id``."""
  ids, feats = [], []
  for batch in _as_reader(table).batches(batch_size):
    ids.extend(int(r[0]) for r in batch)
    feats.extend(_decode_feat(r[1]) for r in batch)
  if not ids:
    return np.zeros((0, 0), np.float32)
  arr = np.asarray(feats, dtype=np.float32)
  idx = np.asarray(ids, dtype=np.int64)
  uniq = np.unique(idx)
  if len(uniq) != len(idx) or uniq[0] != 0 or uniq[-1] != len(idx) - 1:
    raise ValueError(
        f'node table ids must form a permutation of range({len(idx)}); '
        f'got {len(uniq)} unique ids in [{uniq[0]}, {uniq[-1]}]')
  out = np.empty_like(arr)
  out[idx] = arr
  return out


class TableDataset(Dataset):
  """`Dataset` built from edge/node tables.

  Mirrors reference ``TableDataset.load`` (`data/table_dataset.py:
  30-162`), with reader plumbing generalized and CUDA placement args
  mapped to the TPU feature-store knobs.
  """

  def load(self,
           edge_tables: Optional[Dict[EdgeType, TableLike]] = None,
           node_tables: Optional[Dict[NodeType, TableLike]] = None,
           sort_func=None,
           split_ratio: float = 1.0,
           directed: bool = True,
           reader_batch_size: int = 65536,
           label=None,
           device=None,
           **kwargs) -> 'TableDataset':
    assert isinstance(edge_tables, dict) and edge_tables
    assert isinstance(node_tables, dict) and node_tables
    edge_hetero = len(edge_tables) > 1
    node_hetero = len(node_tables) > 1

    edges = {et: read_edge_table(t, reader_batch_size)
             for et, t in edge_tables.items()}
    feats = {nt: read_node_table(t, reader_batch_size)
             for nt, t in node_tables.items()}
    num_nodes = {nt: f.shape[0] for nt, f in feats.items()}

    if not directed:
      edges = {et: (np.concatenate([r, c]), np.concatenate([c, r]))
               for et, (r, c) in edges.items()}

    if edge_hetero or node_hetero:
      self.init_graph(edges, layout='COO', num_nodes=num_nodes,
                      device=device)
      self.init_node_features(feats, sort_func=sort_func,
                              split_ratio=split_ratio, device=device)
    else:
      (et, (r, c)), = edges.items()
      (nt, f), = feats.items()
      self.init_graph((r, c), layout='COO', num_nodes=f.shape[0],
                      device=device)
      self.init_node_features(f, sort_func=sort_func,
                              split_ratio=split_ratio, device=device)
    if label is not None:
      self.init_node_labels(label)
    return self
