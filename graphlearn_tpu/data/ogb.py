"""OGB on-disk layout ingestion (offline-friendly, torch-free).

The reference consumes OGB datasets through the `ogb` package
(`examples/train_sage_ogbn_products.py:20-30`,
`examples/igbh/dataset.py`); that package needs network access and
torch.  This module reads the layouts OGB materializes ON DISK, so a
host that already holds the data (e.g. a TPU-VM with a mounted bucket)
can ingest without either dependency:

  * the **raw CSV layout** (``<root>/raw/edge.csv.gz``,
    ``node-feat.csv.gz``, ``node-label.csv.gz``,
    ``num-node-list.csv.gz``; splits under
    ``<root>/split/<name>/{train,valid,test}.csv.gz``) — what
    ``ogb.nodeproppred`` unzips for every node-property dataset;
  * a **binary layout** (``edge_index.npy``/``.npz`` + optional
    ``node_feat.npy``, ``node_label.npy``, ``train_idx.npy``,
    ``valid_idx.npy``, ``test_idx.npy``) — the fast path users export
    once with `save_binary` and load in seconds at products scale.

`load_ogb_dir` auto-detects the layout; `ogb_to_dataset` builds the
single-chip `Dataset`; `partition_ogb` writes the offline partition
layout the distributed engines load (`partition/base.py`).
"""
from __future__ import annotations

import gzip
import os
from pathlib import Path
from typing import Dict, Optional

import numpy as np

__all__ = ['load_ogb_dir', 'ogb_to_dataset', 'partition_ogb',
           'save_binary']


def _squeeze_labels(label) -> Optional[np.ndarray]:
  """[N] / [N, 1] -> [N]; multi-task [N, K>1] keeps its shape
  (flattening would silently misalign labels with nodes)."""
  if label is None:
    return None
  label = np.asarray(label)
  if label.ndim == 1 or label.shape[1] == 1:
    return label.reshape(-1)
  return label


def _read_csv_gz(path: Path, dtype) -> np.ndarray:
  """Comma-separated .csv.gz -> ndarray (no pandas dependency)."""
  with gzip.open(path, 'rt') as f:
    first = f.readline()
    ncols = first.count(',') + 1
  data = np.loadtxt(path, delimiter=',', dtype=dtype, ndmin=2)
  return data if ncols > 1 else data.reshape(-1)


def _find_split_dir(root: Path) -> Optional[Path]:
  split = root / 'split'
  if not split.is_dir():
    return None
  subs = sorted(d for d in split.iterdir() if d.is_dir())
  return subs[0] if subs else split


def load_ogb_dir(root) -> Dict[str, np.ndarray]:
  """Read an OGB node-property dataset directory.

  Returns ``{'edge_index': [2, E], 'num_nodes': int,
  'node_feat': [N, D] | None, 'node_label': [N] (single-task) or
  [N, K] (multi-task, e.g. ogbn-proteins) | None,
  'train_idx'/'valid_idx'/'test_idx': [..] | None}``.
  """
  root = Path(root)
  if not root.exists():
    raise FileNotFoundError(f'OGB dataset dir not found: {root}')
  # binary layout first (fast path)
  for stem in ('edge_index.npy', 'edge_index.npz'):
    p = root / stem
    if p.exists():
      return _load_binary(root)
  raw = root / 'raw'
  if not (raw / 'edge.csv.gz').exists():
    raise FileNotFoundError(
        f'neither binary (edge_index.npy) nor raw CSV (raw/edge.csv.gz) '
        f'layout under {root}')
  edges = _read_csv_gz(raw / 'edge.csv.gz', np.int64)
  edge_index = edges.T                          # [2, E]
  nn_path = raw / 'num-node-list.csv.gz'
  if nn_path.exists():
    num_nodes = int(np.atleast_1d(_read_csv_gz(nn_path, np.int64))[0])
  else:
    num_nodes = int(edge_index.max()) + 1
  out = {'edge_index': edge_index, 'num_nodes': num_nodes,
         'node_feat': None, 'node_label': None,
         'train_idx': None, 'valid_idx': None, 'test_idx': None}
  nf = raw / 'node-feat.csv.gz'
  if nf.exists():
    out['node_feat'] = _read_csv_gz(nf, np.float32)
  nl = raw / 'node-label.csv.gz'
  if nl.exists():
    out['node_label'] = _squeeze_labels(_read_csv_gz(nl, np.int64))
  split = _find_split_dir(root)
  if split is not None:
    for name in ('train', 'valid', 'test'):
      p = split / f'{name}.csv.gz'
      if p.exists():
        out[f'{name}_idx'] = np.atleast_1d(
            _read_csv_gz(p, np.int64).reshape(-1))
  return out


def _load_binary(root: Path) -> Dict[str, np.ndarray]:
  def maybe(stem):
    for suffix in ('.npy', '.npz'):
      p = root / f'{stem}{suffix}'
      if p.exists():
        d = np.load(p)
        return d[d.files[0]] if hasattr(d, 'files') else d
    return None
  ei = maybe('edge_index')
  if ei.shape[0] != 2:
    ei = ei.T
  feat = maybe('node_feat')
  label = maybe('node_label')
  n = maybe('num_nodes')
  num_nodes = (int(np.atleast_1d(n)[0]) if n is not None
               else (feat.shape[0] if feat is not None
                     else int(ei.max()) + 1))
  return {'edge_index': np.asarray(ei, np.int64), 'num_nodes': num_nodes,
          'node_feat': feat,
          'node_label': _squeeze_labels(label),
          'train_idx': maybe('train_idx'), 'valid_idx': maybe('valid_idx'),
          'test_idx': maybe('test_idx')}


def save_binary(root, out_dir) -> None:
  """One-time raw-CSV -> binary conversion (seconds to reload after)."""
  d = load_ogb_dir(root)
  out = Path(out_dir)
  out.mkdir(parents=True, exist_ok=True)
  np.save(out / 'edge_index.npy', d['edge_index'])
  np.save(out / 'num_nodes.npy', np.array([d['num_nodes']]))
  for key in ('node_feat', 'node_label', 'train_idx', 'valid_idx',
              'test_idx'):
    if d[key] is not None:
      np.save(out / f'{key}.npy', d[key])


def ogb_to_dataset(root, split_ratio: float = 1.0,
                   sort_hot: bool = False, dtype=None):
  """Build a single-chip `Dataset` (+ split indices) from an OGB dir.

  ``sort_hot`` applies the in-degree hot-row reorder before the
  hot/cold feature split (`sort_by_in_degree`, reference
  `data/reorder.py:19-31` — the `train_sage_ogbn_products` recipe).
  Returns ``(dataset, splits)`` with ``splits = {'train': ..., ...}``.
  """
  from .dataset import Dataset
  from .reorder import sort_by_in_degree
  d = load_ogb_dir(root)
  rows, cols = d['edge_index']
  ds = Dataset().init_graph((rows, cols), layout='COO',
                            num_nodes=d['num_nodes'])
  if d['node_feat'] is not None:
    ds.init_node_features(
        d['node_feat'],
        sort_func=sort_by_in_degree if sort_hot else None,
        split_ratio=split_ratio, dtype=dtype)
  if d['node_label'] is not None:
    ds.init_node_labels(d['node_label'].astype(np.int32))
  splits = {k: d[f'{k}_idx'] for k in ('train', 'valid', 'test')
            if d[f'{k}_idx'] is not None}
  return ds, splits


def partition_ogb(root, out_dir, num_parts: int, seed: int = 0) -> None:
  """Write the offline partition layout for an OGB dir — feeds
  `DistDataset.from_partition_dir` / `HostDataset.from_partition_dir`
  (reference `examples/distributed/partition_ogbn_dataset.py`)."""
  from ..partition import RandomPartitioner
  d = load_ogb_dir(root)
  RandomPartitioner(out_dir, num_parts, d['num_nodes'],
                    (d['edge_index'][0], d['edge_index'][1]),
                    node_feat=d['node_feat'],
                    node_label=(d['node_label'].astype(np.int32)
                                if d['node_label'] is not None else None),
                    seed=seed).partition()
