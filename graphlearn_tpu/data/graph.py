"""Device-resident graph handle.

TPU-native counterpart of reference `data/graph.py:125-239` + the
native CSR holder (`csrc/cuda/graph.cu`, `include/graph.h:36-130`).
The reference's three residency modes (CPU / ZERO_COPY UVA / CUDA HBM)
collapse into two on TPU: topology as `jax.Array`s in device HBM
(``'device'``, the fast path — what DMA mode is on GPU), or pinned on
the TPU-VM host (``'host'``, for graphs larger than HBM; gathers are
then staged per batch).  There is no UVA on TPU; the ZERO_COPY
equivalent is host-resident arrays + explicit async `device_put`.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .topology import CSRTopo


class Graph:
  """A graph object holding topology ready for device sampling.

  Args:
    csr_topo: canonical CSR topology.
    mode: ``'device'`` (HBM-resident, default) or ``'host'``.
    device: optional explicit `jax.Device`.
    with_edge_ids: materialize edge ids on device (needed when
      downstream wants edge features / provenance).
  """

  def __init__(self, csr_topo: CSRTopo, mode: str = 'device',
               device: Optional[jax.Device] = None,
               with_edge_ids: bool = True):
    mode = mode.lower()
    if mode not in ('device', 'host'):
      raise ValueError(f'Unsupported graph mode {mode!r}')
    self.csr_topo = csr_topo
    self.mode = mode
    self._device = device
    self.with_edge_ids = with_edge_ids
    self._indptr = None
    self._indices = None
    self._edge_ids = None

  # Lazy init mirrors reference `data/graph.py:160-188` (`lazy_init`).
  def lazy_init(self):
    if self._indptr is not None:
      return
    if self.mode == 'host':
      dev = _host_device()
    else:
      dev = self._device or jax.devices()[0]
    # indptr entries index edges: narrow to int32 only when safe.
    ptr_dtype = (np.int32 if self.csr_topo.num_edges < np.iinfo(np.int32).max
                 else np.int64)
    self._indptr = jax.device_put(
        np.asarray(self.csr_topo.indptr, dtype=ptr_dtype), dev)
    self._indices = jax.device_put(
        np.asarray(self.csr_topo.indices, dtype=np.int32), dev)
    if self.with_edge_ids:
      eids = np.asarray(self.csr_topo.edge_ids)
      # int32 when the id space allows — halves HBM footprint.
      if eids.size == 0 or eids.max() < np.iinfo(np.int32).max:
        eids = eids.astype(np.int32)
      self._edge_ids = jax.device_put(eids, dev)

  @property
  def indptr(self) -> jax.Array:
    self.lazy_init()
    return self._indptr

  @property
  def indices(self) -> jax.Array:
    self.lazy_init()
    return self._indices

  @property
  def edge_ids(self) -> Optional[jax.Array]:
    self.lazy_init()
    return self._edge_ids

  @property
  def num_nodes(self) -> int:
    return self.csr_topo.num_nodes

  @property
  def num_edges(self) -> int:
    return self.csr_topo.num_edges

  @property
  def max_degree(self) -> int:
    return self.csr_topo.max_degree

  def __repr__(self):
    return (f'Graph(num_nodes={self.num_nodes}, num_edges={self.num_edges}, '
            f'mode={self.mode!r})')


def _host_device() -> jax.Device:
  """Best-effort host (CPU) device for host-resident topology."""
  for d in jax.devices():
    if d.platform == 'cpu':
      return d
  try:
    return jax.devices('cpu')[0]
  except RuntimeError:
    return jax.devices()[0]
