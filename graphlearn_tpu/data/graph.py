"""Device-resident graph handle.

TPU-native counterpart of reference `data/graph.py:125-239` + the
native CSR holder (`csrc/cuda/graph.cu`, `include/graph.h:36-130`).
The reference's three residency modes (CPU / ZERO_COPY UVA / CUDA HBM)
collapse into two on TPU: topology as `jax.Array`s in device HBM
(``'device'``, the fast path — what DMA mode is on GPU), or pinned on
the TPU-VM host (``'host'``, for graphs larger than HBM; gathers are
then staged per batch).  There is no UVA on TPU; the ZERO_COPY
equivalent is host-resident arrays + explicit async `device_put`.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .topology import CSRTopo


class DeviceCSRTopo:
  """CSR topology whose arrays already live on device.

  The device-native construction path: graphs built *on* the TPU
  (synthetic benchmarks, on-device ETL, arrays produced by another jit
  program) wrap here without a host round trip — ``np.asarray`` on a
  1 GB device array would pull it through the tunnel just to push it
  back.  The caller guarantees canonical sorted-CSR form (the
  host-side :class:`~graphlearn_tpu.data.topology.CSRTopo` constructor
  is where un-canonical input gets fixed up).  Host-only consumers
  (``to_coo`` etc.) intentionally do not exist on this shim; accessing
  ``indptr``/``indices`` yields the device arrays.
  """

  def __init__(self, indptr, indices, edge_ids=None):
    self._indptr = indptr
    self._indices = indices
    self._edge_ids = edge_ids
    self._max_degree = None

  indptr = property(lambda self: self._indptr)
  indices = property(lambda self: self._indices)
  edge_ids = property(lambda self: self._edge_ids)

  @property
  def num_nodes(self) -> int:
    return self._indptr.shape[0] - 1

  @property
  def num_edges(self) -> int:
    return self._indices.shape[0]

  @property
  def degrees(self) -> jax.Array:
    return self._indptr[1:] - self._indptr[:-1]

  @property
  def max_degree(self) -> int:
    if self._max_degree is None:
      self._max_degree = int(jnp.max(self.degrees))   # one scalar pull
    return self._max_degree

  def __repr__(self):
    return (f'DeviceCSRTopo(num_nodes={self.num_nodes}, '
            f'num_edges={self.num_edges})')


class Graph:
  """A graph object holding topology ready for device sampling.

  Args:
    csr_topo: canonical CSR topology.
    mode: ``'device'`` (HBM-resident, default) or ``'host'``.
    device: optional explicit `jax.Device`.
    with_edge_ids: materialize edge ids on device (needed when
      downstream wants edge features / provenance).
  """

  def __init__(self, csr_topo: CSRTopo, mode: str = 'device',
               device: Optional[jax.Device] = None,
               with_edge_ids: bool = True):
    mode = mode.lower()
    if mode not in ('device', 'host'):
      raise ValueError(f'Unsupported graph mode {mode!r}')
    self.csr_topo = csr_topo
    self.mode = mode
    self._device = device
    self.with_edge_ids = with_edge_ids
    self._indptr = None
    self._indices = None
    self._edge_ids = None

  @classmethod
  def from_device_arrays(cls, indptr: jax.Array, indices: jax.Array,
                         edge_ids: Optional[jax.Array] = None) -> 'Graph':
    """Wrap device-resident sorted-CSR arrays without a host round
    trip (see :class:`DeviceCSRTopo`).  Dtypes are narrowed on device
    (indices/edge_ids to int32; indptr to int32 when the edge count
    allows), mirroring what `lazy_init` does for host input."""
    num_edges = indices.shape[0]
    ptr_dtype = (jnp.int32 if num_edges < np.iinfo(np.int32).max
                 else jnp.int64)
    g = cls.__new__(cls)
    g.csr_topo = DeviceCSRTopo(indptr.astype(ptr_dtype),
                               indices.astype(jnp.int32),
                               None if edge_ids is None
                               else edge_ids.astype(jnp.int32))
    g.mode = 'device'
    g._device = None
    g.with_edge_ids = edge_ids is not None
    g._indptr = g.csr_topo.indptr
    g._indices = g.csr_topo.indices
    g._edge_ids = g.csr_topo.edge_ids
    return g

  # Lazy init mirrors reference `data/graph.py:160-188` (`lazy_init`).
  def lazy_init(self):
    if self._indptr is not None:
      return
    if self.mode == 'host':
      dev = _host_device()
    else:
      dev = self._device or jax.devices()[0]
    # indptr entries index edges: narrow to int32 only when safe.
    ptr_dtype = (np.int32 if self.csr_topo.num_edges < np.iinfo(np.int32).max
                 else np.int64)
    self._indptr = jax.device_put(
        np.asarray(self.csr_topo.indptr, dtype=ptr_dtype), dev)
    self._indices = jax.device_put(
        np.asarray(self.csr_topo.indices, dtype=np.int32), dev)
    if self.with_edge_ids:
      eids = np.asarray(self.csr_topo.edge_ids)
      # int32 when the id space allows — halves HBM footprint.
      if eids.size == 0 or eids.max() < np.iinfo(np.int32).max:
        eids = eids.astype(np.int32)
      self._edge_ids = jax.device_put(eids, dev)

  @property
  def indptr(self) -> jax.Array:
    self.lazy_init()
    return self._indptr

  @property
  def indices(self) -> jax.Array:
    self.lazy_init()
    return self._indices

  @property
  def edge_ids(self) -> Optional[jax.Array]:
    self.lazy_init()
    return self._edge_ids

  @property
  def num_nodes(self) -> int:
    return self.csr_topo.num_nodes

  @property
  def num_edges(self) -> int:
    return self.csr_topo.num_edges

  @property
  def max_degree(self) -> int:
    return self.csr_topo.max_degree

  def __repr__(self):
    return (f'Graph(num_nodes={self.num_nodes}, num_edges={self.num_edges}, '
            f'mode={self.mode!r})')


def _host_device() -> jax.Device:
  """Best-effort host (CPU) device for host-resident topology."""
  for d in jax.devices():
    if d.platform == 'cpu':
      return d
  try:
    return jax.devices('cpu')[0]
  except RuntimeError:
    return jax.devices()[0]
