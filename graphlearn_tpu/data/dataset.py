"""User-facing data manager: graphs + features + labels, homo or hetero.

Counterpart of reference `data/dataset.py:29-336` (``Dataset``): owns the
device graph handles, the two-tier feature stores and label arrays, for
a homogeneous graph or a dict-of-edge-type heterogeneous one.  The
reference's IPC/ForkingPickler machinery has no TPU counterpart — JAX is
single-controller per host; cross-process handoff is replaced by the
host-side producer pipeline (:mod:`graphlearn_tpu.channel`).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Union

import numpy as np

from ..typing import EdgeType, NodeType, as_str
from ..utils.tensor import convert_to_array
from .feature import Feature
from .graph import Graph
from .reorder import sort_by_in_degree
from .topology import CSRTopo


class Dataset:
  """Holds graph topology, features and labels ready for sampling.

  All ``init_*`` methods accept either a single value (homogeneous) or a
  ``dict`` keyed by node/edge type (heterogeneous), mirroring reference
  `data/dataset.py:44-219`.
  """

  def __init__(self,
               graph: Union[Graph, Dict[EdgeType, Graph], None] = None,
               node_features=None, edge_features=None, node_labels=None,
               node_split=None):
    self.graph = graph
    self.node_features = node_features
    self.edge_features = edge_features
    self.node_labels = node_labels
    self.node_split = node_split

  # -- graph --------------------------------------------------------------
  def init_graph(self, edge_index=None, edge_ids=None, layout='COO',
                 graph_mode: str = 'device', device=None,
                 num_nodes=None):
    """Build device graph handle(s) from COO/CSR/CSC input.

    Mirrors reference `Dataset.init_graph` (`data/dataset.py:44-100`).
    ``edge_index`` may be a dict keyed by ``EdgeType`` for hetero.
    """
    if edge_index is None:
      return self
    # retain the explicit hetero counts for num_nodes_dict()
    self._explicit_num_nodes = num_nodes if isinstance(num_nodes, dict) \
        else None
    import jax

    def _is_device_csr(ei):
      # BOTH halves must be device arrays: a mixed (jax.Array, numpy)
      # pair used to slip through on the first element alone and
      # reach `Graph.from_device_arrays` with a host indices array
      return (isinstance(ei, (tuple, list)) and len(ei) == 2
              and isinstance(ei[0], jax.Array)
              and isinstance(ei[1], jax.Array))

    def _check_device_csr(ei, nn, etype=None):
      # the device-native path trusts the arrays as canonical CSR; the
      # one cheap invariant we CAN check is the indptr row count
      # against an explicit num_nodes (shape metadata, no device sync)
      if nn is None:
        return
      got = int(ei[0].shape[0]) - 1
      if got != int(nn):
        where = f' for edge type {etype!r}' if etype is not None else ''
        raise ValueError(
            f'device CSR indptr{where} implies {got} nodes '
            f'(indptr.shape[0] - 1) but num_nodes={int(nn)} was given')

    if layout == 'CSR' and _is_device_csr(edge_index):
      # device-native path: arrays already on device in canonical
      # sorted-CSR form (see `Graph.from_device_arrays`) — no host
      # round trip, no re-sort
      _check_device_csr(edge_index,
                        num_nodes if not isinstance(num_nodes, dict)
                        else None)
      self.graph = Graph.from_device_arrays(edge_index[0], edge_index[1],
                                            edge_ids=edge_ids)
      return self
    if (layout == 'CSR' and isinstance(edge_index, dict)
        and all(_is_device_csr(ei) for ei in edge_index.values())):
      # hetero device-native path (per-etype device CSR)
      if num_nodes is not None:
        for etype, ei in edge_index.items():
          if isinstance(num_nodes, dict):
            # keyed by edge type, or by node type (the CSR row count
            # is the SOURCE type's node count) — same resolution as
            # the host path below
            nn = num_nodes.get(etype)
            if nn is None and isinstance(etype, tuple):
              nn = num_nodes.get(etype[0])
          else:
            # a scalar applies to every etype's row dimension, the
            # host path's behavior
            nn = num_nodes
          _check_device_csr(ei, nn, etype=etype)
      self.graph = {
          etype: Graph.from_device_arrays(
              ei[0], ei[1],
              edge_ids=(edge_ids.get(etype)
                        if isinstance(edge_ids, dict) else None))
          for etype, ei in edge_index.items()
      }
      return self
    if isinstance(edge_index, dict):
      topos = {}
      for etype, ei in edge_index.items():
        eids = edge_ids.get(etype) if isinstance(edge_ids, dict) else None
        lay = layout.get(etype) if isinstance(layout, dict) else layout
        if isinstance(num_nodes, dict):
          # keyed by edge type, or by node type (the CSR row dimension
          # is the *source* type's node count)
          nn = num_nodes.get(etype)
          if nn is None and isinstance(etype, tuple):
            nn = num_nodes.get(etype[0])
        else:
          nn = num_nodes
        topos[etype] = CSRTopo(ei, edge_ids=eids, layout=lay, num_nodes=nn)
      self.graph = {
          etype: Graph(t, mode=graph_mode, device=device)
          for etype, t in topos.items()
      }
    else:
      topo = CSRTopo(edge_index, edge_ids=edge_ids, layout=layout,
                     num_nodes=num_nodes)
      self.graph = Graph(topo, mode=graph_mode, device=device)
    return self

  def attach_stream(self, stream) -> 'Dataset':
    """Back this dataset's (homogeneous) topology with a streaming
    graph (`streaming.StreamingGraph`, ISSUE 14): ``self.graph``
    becomes a device `Graph` over the stream's CURRENT pinned view
    and ``self.stream`` carries the handle version-fencing consumers
    re-pin from — the `ServingEngine` per coalesced run, the mesh
    samplers at dispatch/chunk seams.  Static consumers that read
    ``self.graph`` once keep whatever version was pinned when they
    read it (a complete graph, never a torn one); call again after a
    quiesce to re-snapshot."""
    if self.edge_features is not None:
      raise NotImplementedError(
          'attach_stream on a dataset with edge features is not '
          'supported yet — streamed edges get eids past the frozen '
          'edge-feature table (and the published device graph '
          'carries no edge_ids to gather by)')
    self.stream = stream
    self.graph = stream.pin().as_graph()
    return self

  # -- features ------------------------------------------------------------
  def init_node_features(self, node_feature_data=None, id2idx=None,
                         sort_func: Optional[Callable] = None,
                         split_ratio: float = 1.0, device=None, dtype=None):
    """Create node feature store(s).

    ``sort_func`` (e.g. :func:`sort_by_in_degree`) reorders rows
    hottest-first and supplies the id→row map, exactly the reference's
    cache-ordering hook (`data/dataset.py:102-162`).
    """
    if node_feature_data is None:
      return self
    if isinstance(node_feature_data, dict):
      self.node_features = {}
      for ntype, feats in node_feature_data.items():
        i2i = id2idx.get(ntype) if isinstance(id2idx, dict) else None
        self.node_features[ntype] = self._build_feature(
            feats, i2i, sort_func, split_ratio, device, dtype,
            topo=self._topo_for_ntype(ntype))
    else:
      topo = self.graph.csr_topo if isinstance(self.graph, Graph) else None
      self.node_features = self._build_feature(
          node_feature_data, id2idx, sort_func, split_ratio, device, dtype,
          topo=topo)
    return self

  def _topo_for_ntype(self, ntype: NodeType) -> Optional[CSRTopo]:
    if not isinstance(self.graph, dict):
      return None
    candidate = None
    for (src, _, dst), g in self.graph.items():
      if dst == ntype:   # in-degree hotness counts incoming edges
        return g.csr_topo
      if src == ntype:
        candidate = g.csr_topo
    return candidate

  def _build_feature(self, feats, id2idx, sort_func, split_ratio, device,
                     dtype, topo: Optional[CSRTopo]) -> Feature:
    import jax
    if isinstance(feats, jax.Array):
      if sort_func is not None:
        # the hotness reorder runs on HOST rows before upload; on a
        # device-resident table it would be silently skipped — and a
        # fully-hot table (the device-native contract) has no cold
        # tier for the ordering to matter to.  Reorder before
        # `device_put` and pass `id2idx`, or drop the sorter.
        raise ValueError(
            'sort_func cannot reorder a device-resident feature '
            'table; apply the reorder on host (and pass id2idx) '
            'before putting the table on device')
      # device-native tables go straight to Feature (which validates
      # split_ratio == 1.0); convert_to_array would pull them to host
      return Feature(feats, id2index=id2idx, split_ratio=split_ratio,
                     device=device, dtype=dtype)
    feats = convert_to_array(feats)
    if sort_func is not None and id2idx is None and topo is not None \
        and 0.0 < split_ratio < 1.0:
      # Contract: sort_func(feats, split_ratio, topo) -> (feats, id2index),
      # i.e. `sort_by_in_degree`-shaped.  Score-based sorters
      # (`sort_by_hotness`) take precomputed scores — apply those before
      # init and pass `id2idx` instead.
      feats, id2idx = sort_func(feats, split_ratio, topo)
    return Feature(feats, id2index=id2idx, split_ratio=split_ratio,
                   device=device, dtype=dtype)

  def init_edge_features(self, edge_feature_data=None, id2idx=None,
                         split_ratio: float = 1.0, device=None, dtype=None):
    """Mirrors reference `Dataset.init_edge_features`
    (`data/dataset.py:164-205`)."""
    if edge_feature_data is None:
      return self
    if isinstance(edge_feature_data, dict):
      self.edge_features = {
          etype: Feature(convert_to_array(f),
                         id2index=(id2idx.get(etype)
                                   if isinstance(id2idx, dict) else None),
                         split_ratio=split_ratio, device=device, dtype=dtype)
          for etype, f in edge_feature_data.items()
      }
    else:
      self.edge_features = Feature(convert_to_array(edge_feature_data),
                                   id2index=id2idx, split_ratio=split_ratio,
                                   device=device, dtype=dtype)
    return self

  def init_node_labels(self, node_label_data=None):
    """Mirrors reference `Dataset.init_node_labels`
    (`data/dataset.py:207-219`)."""
    if node_label_data is None:
      return self
    import jax
    if isinstance(node_label_data, jax.Array):
      # device-native labels: already where collation needs them
      self.node_labels = node_label_data
      self._device_labels = {None: node_label_data}
      return self
    if isinstance(node_label_data, dict):
      # device arrays stay device-resident (the get_node_label_device
      # cache path recognizes them); host values convert as before
      self.node_labels = {
          k: v if isinstance(v, jax.Array) else convert_to_array(v)
          for k, v in node_label_data.items()}
    else:
      self.node_labels = convert_to_array(node_label_data)
    self._device_labels = None      # re-upload on next collate
    return self

  def get_node_label_device(self, ntype: Optional[NodeType] = None):
    """Device-resident label array, uploaded once and cached — batch
    collation gathers labels on device (a per-batch host gather would
    round-trip the sampled node table through the host)."""
    lab = self.get_node_label(ntype)
    if lab is None:
      return None
    cache = getattr(self, '_device_labels', None)
    if cache is None:
      cache = self._device_labels = {}
    if ntype not in cache:
      import jax
      import jax.numpy as jnp
      cache[ntype] = (lab if isinstance(lab, jax.Array)
                      else jnp.asarray(np.asarray(lab)))
    return cache[ntype]

  def num_nodes_dict(self) -> Dict[NodeType, int]:
    """Per-node-type counts for hetero graphs: explicit ``init_graph``
    counts and feature-store row counts (both include isolated nodes)
    merged with topology src- AND dst-side counts.  Samplers use this
    to size negative draws and capacity plans correctly."""
    out: Dict[NodeType, int] = {}
    explicit = getattr(self, '_explicit_num_nodes', None)
    if explicit:
      for key, n in explicit.items():
        # keyed by node type, or by edge type (count of its src type)
        nt = key[0] if isinstance(key, tuple) else key
        out[nt] = max(out.get(nt, 0), int(n))
    if isinstance(self.node_features, dict):
      for nt, f in self.node_features.items():
        out[nt] = max(out.get(nt, 0), f.size(0))
    if isinstance(self.graph, dict):
      for (s, _, d), g in self.graph.items():
        out[s] = max(out.get(s, 0), g.num_nodes)
        dmax = int(g.csr_topo.indices.max(initial=-1)) + 1
        out[d] = max(out.get(d, 0), dmax)
    return out

  # -- typed getters (reference `data/dataset.py:230-278`) ------------------
  def get_graph(self, etype: Optional[EdgeType] = None):
    if isinstance(self.graph, dict):
      return self.graph.get(etype) if etype is not None else self.graph
    return self.graph

  def get_node_feature(self, ntype: Optional[NodeType] = None):
    if isinstance(self.node_features, dict):
      return self.node_features.get(ntype)
    return self.node_features

  def get_edge_feature(self, etype: Optional[EdgeType] = None):
    if isinstance(self.edge_features, dict):
      return self.edge_features.get(etype)
    return self.edge_features

  def get_node_label(self, ntype: Optional[NodeType] = None):
    if isinstance(self.node_labels, dict):
      return self.node_labels.get(ntype)
    return self.node_labels

  def get_node_types(self):
    ntypes = set()
    if isinstance(self.graph, dict):
      for (src, _, dst) in self.graph:
        ntypes.add(src)
        ntypes.add(dst)
    if isinstance(self.node_features, dict):
      ntypes.update(self.node_features.keys())
    if isinstance(self.node_labels, dict):
      ntypes.update(self.node_labels.keys())
    return sorted(ntypes)

  def get_edge_types(self):
    if isinstance(self.graph, dict):
      return list(self.graph.keys())
    return None

  @property
  def is_hetero(self) -> bool:
    return isinstance(self.graph, dict)

  def __repr__(self):
    if self.is_hetero:
      etypes = ', '.join(as_str(e) for e in self.graph)
      return f'Dataset(hetero, edge_types=[{etypes}])'
    return f'Dataset(graph={self.graph!r})'
