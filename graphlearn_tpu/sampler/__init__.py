from .base import (
    EdgeSamplerInput,
    HeteroSamplerOutput,
    NegativeSampling,
    NodeSamplerInput,
    SamplerOutput,
    SamplingConfig,
    SamplingType,
    BaseSampler,
)
from .neighbor_sampler import NeighborSampler
from .negative_sampler import RandomNegativeSampler
