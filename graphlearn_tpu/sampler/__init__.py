from .base import (
    EdgeSamplerInput,
    HeteroSamplerOutput,
    NegativeSampling,
    NodeSamplerInput,
    SamplerOutput,
    SamplingConfig,
    SamplingType,
    BaseSampler,
)
from .neighbor_sampler import NeighborSampler
from .hetero_neighbor_sampler import HeteroNeighborSampler
from .negative_sampler import RandomNegativeSampler
