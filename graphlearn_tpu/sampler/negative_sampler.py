"""Standalone random negative sampler.

Counterpart of reference `sampler/negative_sampler.py:21-51` — a thin
class over the device op (`ops/negative.py`), returning a stacked
``[2, req_num]`` edge_index like the reference.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..data.graph import Graph
from ..ops.negative import sample_negative


class RandomNegativeSampler:
  """Draw random non-edges from a device graph.

  Args:
    graph: device graph handle.
    seed: PRNG seed.
  """

  def __init__(self, graph: Graph, seed: int = 0):
    self.graph = graph
    self._base_key = jax.random.key(seed)
    self._step = 0

  def sample(self, req_num: int, trials_num: int = 5,
             padding: bool = True) -> jax.Array:
    """Returns ``[2, req_num]`` edge_index of sampled negative pairs.

    ``padding=True`` guarantees a full output (possibly containing a
    few false negatives), matching reference semantics.
    """
    self._step += 1
    key = jax.random.fold_in(self._base_key, self._step)
    res = sample_negative(
        self.graph.indptr, self.graph.indices, int(req_num), key,
        trials=int(trials_num), strict=True, padding=padding)
    return jnp.stack([res.rows, res.cols])
