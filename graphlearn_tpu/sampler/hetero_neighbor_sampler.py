"""Heterogeneous multi-hop neighbor sampling.

TPU-native re-design of the reference's hetero path
(`sampler/neighbor_sampler.py:192-253`: per-hop per-edge-type lazy CUDA
samplers + per-node-type hetero inducer, `csrc/cuda/inducer.cu:149+`)
as ONE jitted XLA program per static config.

Semantics (matching the reference's contract):
  * Each stored edge type ``(src, rel, dst)`` is sampled *from* nodes
    of type ``src``, discovering neighbors of type ``dst`` with that
    type's per-hop fanout.
  * Node tables are per node type, deduplicated across hops in
    first-occurrence order (seeds of the input type occupy ``0..B-1``).
  * Sampled edges are emitted under the REVERSED edge type
    (`reverse_edge_type`, reference `:236-243`) with transposed
    direction — ``edge_index[0]`` = neighbor-side (``dst``-type local
    id), ``edge_index[1]`` = seed-side (``src``-type local id) — so
    messages flow discovered→seed for PyG-style aggregation, exactly
    like the homogeneous transposed emission.
  * Hop ``h`` frontier of a node type = the nodes first discovered at
    hop ``h-1`` (static table windows masked by dynamic counts).
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..data.graph import Graph
from ..ops.neighbor import sample_one_hop
from ..ops.unique import init_node, induce_next
from ..typing import EdgeType, NodeType, reverse_edge_type
from ..utils.padding import INVALID_ID, round_up
from .base import (BaseSampler, HeteroSamplerOutput, NodeSamplerInput)


def normalize_fanouts(etypes: Tuple[EdgeType, ...], num_neighbors):
  """Resolve ``num_neighbors`` (shared list or per-etype dict) into
  ``(etypes, fanouts, num_hops)`` — etypes absent from a dict spec
  don't participate.  Shared by the single-host and distributed hetero
  samplers."""
  if isinstance(num_neighbors, dict):
    fanouts = {et: tuple(int(k) for k in num_neighbors[et])
               for et in etypes if et in num_neighbors}
    etypes = tuple(et for et in etypes if et in fanouts)
  else:
    fan = tuple(int(k) for k in num_neighbors)
    fanouts = {et: fan for et in etypes}
  num_hops = max((len(f) for f in fanouts.values()), default=0)
  return etypes, fanouts, num_hops


def _plan_capacities(
    etypes: Sequence[EdgeType],
    fanouts: Dict[EdgeType, Tuple[int, ...]],
    input_sizes: Dict[NodeType, int],
    num_hops: int,
    num_nodes: Dict[NodeType, int],
):
  """Host-side static-shape plan.

  Returns per-ntype table capacities, per-(hop, ntype) frontier
  capacities, and per-(hop, etype) edge capacities — the hetero analog
  of the reference's `_max_sampled_nodes` bound
  (`sampler/neighbor_sampler.py:595-612`).  ``input_sizes`` gives the
  seed count per seeded node type (link sampling seeds two types).
  """
  ntypes = sorted({t for (s, _, d) in etypes for t in (s, d)}
                  | set(input_sizes))
  frontier = {nt: int(input_sizes.get(nt, 0)) for nt in ntypes}
  frontier_caps = [dict(frontier)]
  table_cap = {nt: frontier[nt] for nt in ntypes}
  edge_caps: List[Dict[EdgeType, int]] = []
  for h in range(num_hops):
    add = {nt: 0 for nt in ntypes}
    ecap: Dict[EdgeType, int] = {}
    for et in etypes:
      s, _, d = et
      k = fanouts[et][h] if h < len(fanouts[et]) else 0
      if k <= 0 or frontier[s] == 0:
        continue
      ecap[et] = frontier[s] * k
      add[d] += frontier[s] * k
    frontier = {nt: min(add[nt], num_nodes.get(nt, add[nt]))
                for nt in ntypes}
    frontier_caps.append(dict(frontier))
    for nt in ntypes:
      table_cap[nt] = min(table_cap[nt] + add[nt],
                          input_sizes.get(nt, 0)
                          + num_nodes.get(nt, 1 << 60))
    edge_caps.append(ecap)
  table_cap = {nt: round_up(max(c, 1), 8) for nt, c in table_cap.items()}
  return ntypes, table_cap, frontier_caps, edge_caps


@functools.partial(
    jax.jit,
    static_argnames=('etypes', 'fanouts_t', 'seed_types', 'num_hops',
                     'table_caps', 'frontier_caps_t', 'with_edge',
                     'sort_locality'))
def _hetero_multihop(
    graphs,           # dict etype -> (indptr, indices, edge_ids|None)
    seeds_t: Tuple[jax.Array, ...],   # aligned with seed_types
    key: jax.Array,
    *,
    etypes: Tuple[EdgeType, ...],
    fanouts_t: Tuple[Tuple[int, ...], ...],   # aligned with etypes
    seed_types: Tuple[NodeType, ...],
    num_hops: int,
    table_caps: Tuple[Tuple[NodeType, int], ...],
    frontier_caps_t: Tuple[Tuple[Tuple[NodeType, int], ...], ...],
    with_edge: bool,
    sort_locality: bool = True,
):
  caps = dict(table_caps)
  fanouts = dict(zip(etypes, fanouts_t))
  frontier_caps = [dict(fc) for fc in frontier_caps_t]
  ntypes = list(caps.keys())

  # per-ntype inducer state; seeded types (one for node sampling, the
  # two endpoint types for link sampling) start with their seed sets.
  states = {}
  seed_locals = {}
  seed_by_type = dict(zip(seed_types, seeds_t))
  for nt in ntypes:
    if nt in seed_by_type:
      states[nt], seed_locals[nt] = init_node(seed_by_type[nt], caps[nt])
    else:
      states[nt] = init_node(
          jnp.full((1,), INVALID_ID, jnp.int32), caps[nt])[0]

  # frontier windows: (start, cap) per ntype.
  fr_start = {nt: jnp.zeros((), jnp.int32) for nt in ntypes}

  rows_acc = {et: [] for et in etypes}
  cols_acc = {et: [] for et in etypes}
  eids_acc = {et: [] for et in etypes}
  nsn = {nt: [states[nt].count] for nt in ntypes}

  for h in range(num_hops):
    # Snapshot hop-start state: frontiers are nodes discovered at h-1.
    hop_start_count = {nt: states[nt].count for nt in ntypes}
    frontiers = {}
    for nt in ntypes:
      fcap = frontier_caps[h].get(nt, 0)
      if fcap <= 0:
        frontiers[nt] = None
        continue
      slots = fr_start[nt] + jnp.arange(fcap, dtype=jnp.int32)
      valid = slots < hop_start_count[nt]
      nodes = states[nt].nodes[
          jnp.clip(slots, 0, caps[nt] - 1)]
      frontiers[nt] = (jnp.where(valid, nodes, INVALID_ID),
                       jnp.where(valid, slots, -1))

    for ei, et in enumerate(etypes):
      s, _, d = et
      k = fanouts[et][h] if h < len(fanouts[et]) else 0
      if k <= 0 or frontiers.get(s) is None:
        continue
      fr_nodes, fr_local = frontiers[s]
      indptr, indices, edge_ids = graphs[et]
      hop_key = jax.random.fold_in(jax.random.fold_in(key, h), ei)
      res = sample_one_hop(indptr, indices, fr_nodes, int(k), hop_key,
                           edge_ids, with_edge_ids=with_edge,
                           sort_locality=sort_locality)
      states[d], rows, cols, _ = induce_next(
          states[d], fr_local, res.nbrs, res.mask)
      rows_acc[et].append(rows)
      cols_acc[et].append(cols)
      if with_edge:
        eids_acc[et].append(
            jnp.where(rows >= 0, res.eids.reshape(-1), INVALID_ID))

    for nt in ntypes:
      fr_start[nt] = hop_start_count[nt]
      nsn[nt].append(states[nt].count)

  node = {nt: states[nt].nodes for nt in ntypes}
  node_count = {nt: states[nt].count for nt in ntypes}
  # Emit under reversed etypes with transposed direction.
  row_out, col_out, eid_out, emask_out = {}, {}, {}, {}
  for et in etypes:
    if not rows_acc[et]:
      continue
    rev = reverse_edge_type(et)
    r = jnp.concatenate(rows_acc[et])
    c = jnp.concatenate(cols_acc[et])
    row_out[rev] = r
    col_out[rev] = c
    emask_out[rev] = r >= 0
    if with_edge:
      eid_out[rev] = jnp.concatenate(eids_acc[et])
  num_sampled_nodes = {
      nt: jnp.concatenate([jnp.stack(v)[:1],
                           jnp.stack(v)[1:] - jnp.stack(v)[:-1]])
      for nt, v in nsn.items()}
  return (node, node_count, row_out, col_out,
          eid_out if with_edge else None, emask_out, seed_locals,
          num_sampled_nodes)


class HeteroNeighborSampler(BaseSampler):
  """Uniform hetero multi-hop sampler over a dict of device graphs.

  Args:
    graphs: ``{EdgeType: Graph}`` (sampling direction src→dst).
    num_neighbors: per-hop fanouts — list (shared by all etypes) or
      ``{EdgeType: list}``.
    num_nodes: optional per-ntype node counts for tighter capacity
      planning (defaults derived from topologies).
  """

  def __init__(self, graphs: Dict[EdgeType, Graph], num_neighbors,
               device=None, with_edge: bool = False,
               num_nodes: Optional[Dict[NodeType, int]] = None,
               seed: int = 0, sort_locality: bool = True):
    self.sort_locality = bool(sort_locality)
    self.graphs = dict(graphs)
    self.etypes, self.fanouts, self.num_hops = normalize_fanouts(
        tuple(sorted(self.graphs.keys())), num_neighbors)
    self.with_edge = with_edge
    self.device = device
    self._num_nodes = dict(num_nodes or {})
    for (s, _, d), g in self.graphs.items():
      self._num_nodes[s] = max(self._num_nodes.get(s, 0), g.num_nodes)
      dmax = int(g.csr_topo.indices.max(initial=-1)) + 1
      self._num_nodes[d] = max(self._num_nodes.get(d, 0), dmax)
    self._base_key = jax.random.key(seed)
    self._step = 0

  def _next_key(self) -> jax.Array:
    self._step += 1
    return jax.random.fold_in(self._base_key, self._step)

  def _run_multihop(self, seeds_by_type: Dict[NodeType, jax.Array]):
    """One fused hetero multi-hop from per-type seed sets; returns the
    raw pieces plus per-type seed-local maps."""
    input_sizes = {nt: int(s.shape[0]) for nt, s in seeds_by_type.items()}
    ntypes, table_cap, frontier_caps, _ = _plan_capacities(
        self.etypes, self.fanouts, input_sizes, self.num_hops,
        self._num_nodes)
    graphs = {}
    for et in self.etypes:
      g = self.graphs[et]
      graphs[et] = (g.indptr, g.indices,
                    g.edge_ids if self.with_edge else None)
    seed_types = tuple(sorted(seeds_by_type))
    return _hetero_multihop(
        graphs, tuple(seeds_by_type[nt] for nt in seed_types),
        self._next_key(),
        etypes=self.etypes,
        fanouts_t=tuple(self.fanouts[et] for et in self.etypes),
        seed_types=seed_types,
        num_hops=self.num_hops,
        table_caps=tuple(sorted(table_cap.items())),
        frontier_caps_t=tuple(
            tuple(sorted(fc.items())) for fc in frontier_caps),
        with_edge=self.with_edge, sort_locality=self.sort_locality)

  def sample_from_nodes(self, inputs: NodeSamplerInput,
                        **kwargs) -> HeteroSamplerOutput:
    input_type = inputs.input_type
    assert input_type is not None, 'hetero sampling needs input_type'
    seeds = jnp.asarray(np.asarray(inputs.node, dtype=np.int32))
    (node, node_count, row, col, eid, emask, seed_locals,
     nsn) = self._run_multihop({input_type: seeds})
    return HeteroSamplerOutput(
        node=node, node_count=node_count, row=row, col=col, edge=eid,
        edge_mask=emask, batch={input_type: seeds},
        num_sampled_nodes=nsn,
        edge_types=[reverse_edge_type(et) for et in self.etypes],
        metadata={'seed_local': seed_locals[input_type],
                  'input_type': input_type})

  def sample_from_edges(self, inputs, neg_sampling=None,
                        **kwargs) -> HeteroSamplerOutput:
    """Hetero link-prediction sampling.

    Counterpart of the reference's hetero ``sample_from_edges``
    (`sampler/neighbor_sampler.py:255-381`): seed edges of one edge
    type; endpoints (+ sampled negatives of the dst type) seed their
    respective node-type tables, multi-hop expand, and the metadata
    carries PyG's link-label indices *per endpoint type*:
    ``edge_label_index[0]`` indexes the src-type table,
    ``edge_label_index[1]`` the dst-type table.
    """
    from ..ops.negative import sample_negative
    from .base import NegativeSampling
    from .neighbor_sampler import _triplet_neg_dst

    et = inputs.input_type
    assert et is not None, 'hetero link sampling needs input_type=etype'
    assert et in self.graphs, f'unknown edge type {et}'
    s_t, _, d_t = et
    neg = neg_sampling or inputs.neg_sampling
    neg = NegativeSampling.cast(neg)
    src = jnp.asarray(np.asarray(inputs.row, dtype=np.int32))
    dst = jnp.asarray(np.asarray(inputs.col, dtype=np.int32))
    b = src.shape[0]
    pair_valid = (src >= 0) & (dst >= 0)
    g = self.graphs[et]
    key = self._next_key()

    if neg is not None and neg.is_binary():
      num_neg = neg.sample_size(b)
      nres = sample_negative(g.indptr, g.indices, num_neg, key,
                             strict=True, padding=True,
                             num_cols=self._num_nodes[d_t])
      src_seeds = jnp.concatenate([src, nres.rows])
      dst_seeds = jnp.concatenate([dst, nres.cols])
    elif neg is not None:        # triplet
      amount = int(np.ceil(float(neg.amount)))
      num_neg = b * amount
      neg_dst = _triplet_neg_dst(g.indptr, g.indices, src, key,
                                 amount=amount,
                                 num_nodes=self._num_nodes[d_t])
      src_seeds = src
      dst_seeds = jnp.concatenate([dst, neg_dst.reshape(-1)])
    else:
      num_neg = 0
      src_seeds, dst_seeds = src, dst

    if s_t == d_t:
      seeds_by_type = {s_t: jnp.concatenate([src_seeds, dst_seeds])}
    else:
      seeds_by_type = {s_t: src_seeds, d_t: dst_seeds}
    (node, node_count, row, col, eid, emask, seed_locals,
     nsn) = self._run_multihop(seeds_by_type)
    if s_t == d_t:
      ns = src_seeds.shape[0]
      sl_src = seed_locals[s_t][:ns]
      sl_dst = seed_locals[s_t][ns:]
    else:
      sl_src = seed_locals[s_t]
      sl_dst = seed_locals[d_t]

    if neg is not None and neg.is_binary():
      pos_label = (jnp.asarray(np.asarray(inputs.label))
                   if inputs.label is not None
                   else jnp.ones((b,), jnp.int32))
      metadata = {
          'edge_label_index': jnp.stack([sl_src, sl_dst]),
          'edge_label': jnp.concatenate(
              [pos_label, jnp.zeros((num_neg,), pos_label.dtype)]),
          'edge_label_mask': jnp.concatenate(
              [pair_valid, jnp.ones((num_neg,), jnp.bool_)]),
      }
    elif neg is not None:
      metadata = {
          'src_index': sl_src,
          'dst_pos_index': sl_dst[:b],
          'dst_neg_index': sl_dst[b:].reshape(b, -1),
          'pair_mask': pair_valid,
      }
    else:
      pos_label = (jnp.asarray(np.asarray(inputs.label))
                   if inputs.label is not None
                   else jnp.ones((b,), jnp.int32))
      metadata = {
          'edge_label_index': jnp.stack([sl_src, sl_dst]),
          'edge_label': pos_label,
          'edge_label_mask': pair_valid,
      }
    metadata['input_type'] = et
    # seed_local aligns 1:1 with `batch` (the POSITIVE endpoints only),
    # matching the node-loader pattern consumers rely on; negatives'
    # locals live in edge_label_index / dst_neg_index.
    if s_t == d_t:
      batch = {s_t: jnp.concatenate([src, dst])}
      metadata['seed_local'] = {
          s_t: jnp.concatenate([sl_src[:b], sl_dst[:b]])}
    else:
      batch = {s_t: src, d_t: dst}
      metadata['seed_local'] = {s_t: sl_src[:b], d_t: sl_dst[:b]}
    return HeteroSamplerOutput(
        node=node, node_count=node_count, row=row, col=col, edge=eid,
        edge_mask=emask, batch=batch,
        num_sampled_nodes=nsn,
        edge_types=[reverse_edge_type(e) for e in self.etypes],
        metadata=metadata)
