"""Multi-hop neighbor sampling engine.

TPU-native re-design of the reference `sampler/neighbor_sampler.py`
(:37-627) — the class that fuses per-hop uniform sampling
(`csrc/cuda/random_sampler.cu`), dedup/relabel (`csrc/cuda/inducer.cu`)
and negative sampling into PyG-shaped `SamplerOutput`s.

Design notes (vs the reference):
  * The whole multi-hop loop is ONE jitted XLA program per static
    config ``(batch_size, fanouts, with_edge)``; hop results are
    accumulated with static capacities (`utils.padding.
    max_sampled_nodes` — the same bound the reference computes at
    `sampler/neighbor_sampler.py:595-612` to size its inducer).
  * Each hop samples the *frontier of newly discovered unique nodes*
    (exactly the reference's ``InduceNext`` contract) — frontier slots
    are a static window over the accumulated node table, masked by the
    dynamic node count.
  * Edges are emitted transposed (row=neighbor, col=seed-side) for PyG
    message passing, matching `sampler/neighbor_sampler.py:159-166`.
  * Randomness: `jax.random` threefry keys folded per call — counter
    based like curand Philox, reproducible across hosts.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..data.graph import Graph
from ..ops.neighbor import sample_one_hop, cal_nbr_prob
from ..ops.pallas_sample import fused_sample_enabled, sample_one_hop_auto
from ..ops.pallas_window import prepare_window_table
from ..ops.negative import edge_in_csr, sample_negative
from ..ops.subgraph import induced_subgraph
from ..ops.unique import InducerState, induce_next, init_node
from ..utils.padding import INVALID_ID, max_sampled_nodes, round_up
from .base import (BaseSampler, EdgeSamplerInput, NegativeSampling,
                   NodeSamplerInput, SamplerOutput)


@functools.partial(
    jax.jit,
    static_argnames=('fanouts', 'node_cap', 'with_edge', 'sort_locality',
                     'use_fused', 'win_e'))
def _multihop_sample(
    indptr: jax.Array,
    indices: jax.Array,
    edge_ids: Optional[jax.Array],
    seeds: jax.Array,
    key: jax.Array,
    win_table: Optional[jax.Array] = None,
    *,
    fanouts: Tuple[int, ...],
    node_cap: int,
    with_edge: bool,
    sort_locality: bool = True,
    use_fused: bool = False,
    win_e: int = 0,
):
  """One fused multi-hop sample. Returns raw pytree pieces.

  seeds: ``[B]`` global ids, INVALID_ID-padded.
  """
  b = seeds.shape[0]
  # The node table GROWS hop by hop instead of starting at the final
  # bound: `induce_next` sorts (table capacity + B*k) elements every
  # hop, so an early hop carrying the full multi-hop capacity (~60x
  # the live entries at hop 1 for fanout [15,10,5]) triples the total
  # sort work for nothing.  Capacities are static per hop; the state
  # pads up right before each hop's insertion.
  cap = min(b, node_cap)
  state, seed_local = init_node(seeds, cap)

  # hop-0 frontier: the deduped seeds occupy table slots [0, count).
  f_cap = b
  slots = jnp.arange(f_cap, dtype=jnp.int32)
  fr_valid = slots < state.count
  frontier = jnp.where(fr_valid, state.nodes[jnp.clip(slots, 0, cap - 1)],
                       INVALID_ID)
  frontier_local = jnp.where(fr_valid, slots, -1)

  rows_acc, cols_acc, eids_acc = [], [], []
  hop_node_counts = [state.count]
  hop_edge_counts = []

  for i, k in enumerate(fanouts):
    hop_key = jax.random.fold_in(key, i)
    # dispatch resolves at trace time: use_fused is a static arg, so
    # flipping GLT_PALLAS_SAMPLE recompiles onto the Pallas kernel
    # (value-identical draws either way — see ops/pallas_sample.py)
    res = sample_one_hop_auto(
        indptr, indices, frontier, int(k), hop_key, edge_ids,
        with_edge_ids=with_edge, sort_locality=sort_locality,
        table=((win_table, win_e) if win_table is not None else None),
        use_fused=use_fused)
    new_cap = min(cap + f_cap * int(k), node_cap)
    if new_cap > cap:
      state = InducerState(
          nodes=jnp.concatenate([
              state.nodes,
              jnp.full((new_cap - cap,), INVALID_ID, state.nodes.dtype)]),
          count=state.count)
      cap = new_cap
    state, rows, cols, prev_cnt = induce_next(
        state, frontier_local, res.nbrs, res.mask)
    rows_acc.append(rows)
    cols_acc.append(cols)
    if with_edge:
      eids_acc.append(jnp.where(rows >= 0, res.eids.reshape(-1), INVALID_ID))
    hop_node_counts.append(state.count)
    hop_edge_counts.append(jnp.sum(rows >= 0))

    # next frontier = nodes appended this hop: table slots [prev, count).
    f_cap = f_cap * int(k)
    slots = prev_cnt + jnp.arange(f_cap, dtype=jnp.int32)
    fr_valid = slots < state.count
    frontier = jnp.where(
        fr_valid, state.nodes[jnp.clip(slots, 0, cap - 1)], INVALID_ID)
    frontier_local = jnp.where(fr_valid, slots, -1)

  if cap < node_cap:
    # consumers expect the [node_cap] table shape
    state = InducerState(
        nodes=jnp.concatenate([
            state.nodes,
            jnp.full((node_cap - cap,), INVALID_ID, state.nodes.dtype)]),
        count=state.count)

  row = jnp.concatenate(rows_acc) if rows_acc else jnp.zeros((0,), jnp.int32)
  col = jnp.concatenate(cols_acc) if cols_acc else jnp.zeros((0,), jnp.int32)
  edge = jnp.concatenate(eids_acc) if (with_edge and eids_acc) else None
  # cumulative -> per-hop new-node counts.
  cum = jnp.stack(hop_node_counts)
  num_sampled_nodes = jnp.concatenate(
      [cum[:1], cum[1:] - cum[:-1]]).astype(jnp.int32)
  num_sampled_edges = (jnp.stack(hop_edge_counts).astype(jnp.int32)
                       if hop_edge_counts else jnp.zeros((0,), jnp.int32))
  return (state.nodes, state.count, row, col, edge, row >= 0, seed_local,
          num_sampled_nodes, num_sampled_edges)


@functools.partial(jax.jit, static_argnames=('amount', 'num_nodes'))
def _triplet_neg_dst(indptr: jax.Array, indices: jax.Array, src: jax.Array,
                     key: jax.Array, *, amount: int, num_nodes: int
                     ) -> jax.Array:
  """Per-source negative destinations with strict rejection (up to 5
  trials), the vectorized analog of the curand retry loop
  (`csrc/cuda/random_negative_sampler.cu:56-94`)."""
  b = src.shape[0]
  trials = 5
  cand = jax.random.randint(key, (trials, b * amount), 0, num_nodes,
                            dtype=jnp.int32)
  rows = jnp.tile(jnp.repeat(src, amount)[None, :], (trials, 1))
  exists = edge_in_csr(indptr, indices, rows.reshape(-1), cand.reshape(-1))
  ok = ~exists.reshape(trials, b * amount)
  pick = jnp.where(jnp.any(ok, axis=0), jnp.argmax(ok, axis=0), trials - 1)
  out = cand[pick, jnp.arange(b * amount)]
  return out.reshape(b, amount)


class NeighborSampler(BaseSampler):
  """Uniform multi-hop neighbor sampler over a device `Graph`.

  Mirrors the reference `NeighborSampler` (`sampler/neighbor_sampler.py:
  37-627`) for the homogeneous case; hetero lives in
  `hetero_neighbor_sampler.py`.

  Args:
    graph: device graph handle.
    num_neighbors: per-hop fanouts, e.g. ``[15, 10, 5]``.
    with_edge: emit global edge ids.
    with_neg: build the negative-sampling path (link loaders).
    seed: PRNG seed (counter-based; each call folds in a step id).
  """

  def __init__(
      self,
      graph: Graph,
      num_neighbors: Sequence[int],
      device=None,
      with_edge: bool = False,
      with_neg: bool = False,
      strategy: str = 'random',
      seed: int = 0,
      sort_locality: bool = True,
  ):
    self.graph = graph
    self.num_neighbors = tuple(int(k) for k in num_neighbors)
    self.device = device
    self.with_edge = with_edge
    self.with_neg = with_neg
    self.strategy = strategy
    # sorted-frontier gather locality (~25% faster hops at scale);
    # turn off to reproduce pre-sort per-seed draws for a pinned key
    self.sort_locality = bool(sort_locality)
    self._base_key = jax.random.key(seed)
    self._step = 0
    self._win_table = None   # lazy prepare_window_table cache (r19)

  # -- helpers --------------------------------------------------------------

  def _next_key(self) -> jax.Array:
    self._step += 1
    return jax.random.fold_in(self._base_key, self._step)

  def _fused_state(self):
    """``(use_fused, win_table, win_e)`` for `_multihop_sample` —
    GLT_PALLAS_SAMPLE is re-read per call (kill switch; the static
    arg makes a flip recompile onto/off the kernel), and the O(E)
    window repack is cached once per sampler."""
    if not fused_sample_enabled():
      return False, None, 0
    if self._win_table is None:
      self._win_table = prepare_window_table(self.graph.indices)
    tbl, e = self._win_table
    return True, tbl, int(e)

  def node_capacity(self, batch_size: int) -> int:
    cap = max_sampled_nodes(batch_size, self.num_neighbors)
    cap = min(cap, batch_size + self.graph.num_nodes)
    return round_up(cap, 8)

  # -- node sampling --------------------------------------------------------

  def sample_from_nodes(self, inputs: NodeSamplerInput,
                        **kwargs) -> SamplerOutput:
    """Reference `sampler/neighbor_sampler.py:138-190`."""
    seeds = jnp.asarray(np.asarray(inputs.node, dtype=np.int32))
    b = seeds.shape[0]
    node_cap = self.node_capacity(b)
    use_fused, win_table, win_e = self._fused_state()
    (nodes, count, row, col, edge, emask, seed_local, nsn,
     nse) = _multihop_sample(
         self.graph.indptr, self.graph.indices,
         self.graph.edge_ids if self.with_edge else None,
         seeds, self._next_key(), win_table,
         fanouts=self.num_neighbors, node_cap=node_cap,
         with_edge=self.with_edge, sort_locality=self.sort_locality,
         use_fused=use_fused, win_e=win_e)
    return SamplerOutput(
        node=nodes, node_count=count, row=row, col=col, edge=edge,
        edge_mask=emask, batch=seeds,
        num_sampled_nodes=nsn, num_sampled_edges=nse,
        metadata={'seed_local': seed_local})

  # -- link sampling --------------------------------------------------------

  def sample_from_edges(self, inputs: EdgeSamplerInput,
                        neg_sampling: Optional[NegativeSampling] = None,
                        **kwargs) -> SamplerOutput:
    """Link-prediction sampling with binary/triplet negatives.

    Reference `sampler/neighbor_sampler.py:255-381`: seeds are the
    positive endpoints plus sampled negatives; metadata carries the
    local label indices PyG expects.
    """
    neg = neg_sampling or inputs.neg_sampling
    src = jnp.asarray(np.asarray(inputs.row, dtype=np.int32))
    dst = jnp.asarray(np.asarray(inputs.col, dtype=np.int32))
    b = src.shape[0]
    # Static-batch padding: (-1, -1) pairs are mask-outs, never examples.
    pair_valid = (src >= 0) & (dst >= 0)
    key = self._next_key()

    if neg is None:
      seeds = jnp.concatenate([src, dst])
      out = self.sample_from_nodes(NodeSamplerInput(node=seeds))
      sl = out.metadata['seed_local']
      out.metadata = {
          'edge_label_index': jnp.stack([sl[:b], sl[b:2 * b]]),
          'edge_label': (inputs.label if inputs.label is not None
                         else jnp.ones((b,), jnp.int32)),
          'edge_label_mask': pair_valid,
          'seed_local': sl,
      }
      return out

    if neg.is_binary():
      num_neg = neg.sample_size(b)
      nres = sample_negative(
          self.graph.indptr, self.graph.indices, num_neg, key,
          strict=True, padding=True)
      seeds = jnp.concatenate([src, dst, nres.rows, nres.cols])
      out = self.sample_from_nodes(NodeSamplerInput(node=seeds))
      sl = out.metadata['seed_local']
      pos_label = (inputs.label if inputs.label is not None
                   else jnp.ones((b,), jnp.int32))
      edge_label_index = jnp.stack([
          jnp.concatenate([sl[:b], sl[2 * b:2 * b + num_neg]]),
          jnp.concatenate([sl[b:2 * b], sl[2 * b + num_neg:]]),
      ])
      # Binary labels get the reference's +1 shift semantics applied at
      # the loader (`loader/link_loader.py:146-186`); raw here: pos
      # labels then zeros.
      edge_label = jnp.concatenate(
          [pos_label, jnp.zeros((num_neg,), pos_label.dtype)])
      edge_label_mask = jnp.concatenate(
          [pair_valid, jnp.ones((num_neg,), jnp.bool_)])
      out.metadata = {
          'edge_label_index': edge_label_index,
          'edge_label': edge_label,
          'edge_label_mask': edge_label_mask,
          'seed_local': sl,
      }
      return out

    # triplet: per-positive-edge negative destinations.
    amount = int(np.ceil(float(neg.amount)))
    num_neg = b * amount
    neg_dst = _triplet_neg_dst(
        self.graph.indptr, self.graph.indices, src, key,
        amount=amount, num_nodes=self.graph.num_nodes)
    seeds = jnp.concatenate([src, dst, neg_dst.reshape(-1)])
    out = self.sample_from_nodes(NodeSamplerInput(node=seeds))
    sl = out.metadata['seed_local']
    out.metadata = {
        'src_index': sl[:b],
        'dst_pos_index': sl[b:2 * b],
        'dst_neg_index': sl[2 * b:].reshape(b, amount),
        'pair_mask': pair_valid,
        'seed_local': sl,
    }
    return out

  # (triplet negative sampling lives in module-level `_triplet_neg_dst`
  # so graph arrays are passed in concrete — a jitted *method* touching
  # `self.graph.indptr` would run the graph's lazy device_put inside
  # tracing and leak tracers into the handle.)

  # -- induced subgraph -----------------------------------------------------

  def subgraph(self, inputs: NodeSamplerInput,
               max_degree: Optional[int] = None,
               **kwargs) -> SamplerOutput:
    """Multi-hop closure then induced edges among collected nodes.

    Reference `sampler/neighbor_sampler.py:409-433` (used by
    `SubGraphLoader` / SEAL).

    Args:
      max_degree: static per-node window for the induced-edge scan;
        defaults to the graph's max degree (exact).  On power-law
        graphs with huge hubs pass a smaller cap to bound the
        ``[node_cap * max_degree]`` intermediate (truncates hub rows).
    """
    seeds = jnp.asarray(np.asarray(inputs.node, dtype=np.int32))
    b = seeds.shape[0]
    node_cap = self.node_capacity(b)
    use_fused, win_table, win_e = self._fused_state()
    (nodes, count, _row, _col, _edge, _emask, seed_local, nsn,
     _nse) = _multihop_sample(
         self.graph.indptr, self.graph.indices, None,
         seeds, self._next_key(), win_table,
         fanouts=self.num_neighbors, node_cap=node_cap, with_edge=False,
         sort_locality=self.sort_locality,
         use_fused=use_fused, win_e=win_e)
    max_deg = max(int(max_degree) if max_degree else self.graph.max_degree, 1)
    sub = induced_subgraph(
        self.graph.indptr, self.graph.indices, nodes,
        max_degree=max_deg,
        edge_ids=self.graph.edge_ids if self.with_edge else None,
        with_edge_ids=self.with_edge)
    return SamplerOutput(
        node=nodes, node_count=count, row=sub.rows, col=sub.cols,
        edge=sub.eids, edge_mask=sub.edge_mask, batch=seeds,
        num_sampled_nodes=nsn, num_sampled_edges=None,
        metadata={'seed_local': seed_local, 'mapping': seed_local})

  # -- frequency-partitioner support ---------------------------------------

  def sample_prob(self, seed_ids, num_nodes: Optional[int] = None
                  ) -> jax.Array:
    """Per-node visit probability under this sampler's fanout schedule.

    Reference `sampler/neighbor_sampler.py:435-562` (`sample_prob` /
    `cal_nbr_prob`) — drives the `FrequencyPartitioner`.
    """
    n = num_nodes or self.graph.num_nodes
    prob = jnp.zeros((n,), jnp.float32)
    seed_ids = jnp.asarray(np.asarray(seed_ids, dtype=np.int32))
    valid = seed_ids >= 0  # INVALID_ID-padded seed batches are welcome
    prob = prob.at[jnp.where(valid, seed_ids, 0)].max(
        valid.astype(jnp.float32))
    for k in self.num_neighbors:
      hop = cal_nbr_prob(self.graph.indptr, self.graph.indices, prob, int(k))
      prob = jnp.minimum(prob + hop, 1.0)
    return prob
