"""Sampler contract: I/O dataclasses and the abstract sampler.

TPU-native re-design of the reference sampler vocabulary
(`graphlearn_torch/python/sampler/base.py`): the same PyG-compatible
field names (``node/row/col/edge/batch``), but every array is a fixed
capacity `jax.Array` with validity masks instead of a ragged
`torch.Tensor`, so a whole `SamplerOutput` is a pytree that can cross
`jit`/`shard_map` boundaries unchanged.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..typing import EdgeType, NodeType, NumNeighbors
from ..utils.mixin import CastMixin


@dataclasses.dataclass
class NodeSamplerInput(CastMixin):
  """Seed nodes for node-wise sampling.

  Mirrors reference `sampler/base.py:44-74`; ``node`` is INVALID_ID-
  padded to the loader's static batch size.

  Args:
    node: ``[B]`` global seed node ids.
    input_type: node type for hetero sampling.
  """
  node: Union[np.ndarray, jax.Array]
  input_type: Optional[NodeType] = None

  def __len__(self) -> int:
    return len(self.node)

  def __getitem__(self, index) -> 'NodeSamplerInput':
    return NodeSamplerInput(self.node[index], self.input_type)


@dataclasses.dataclass(frozen=True)
class NegativeSampling(CastMixin):
  """Negative edge sampling configuration.

  Mirrors reference `sampler/base.py:76-145` (binary / triplet modes,
  float ``amount`` ratio).
  """
  mode: str = 'binary'
  amount: Union[int, float] = 1

  def __post_init__(self):
    if self.mode not in ('binary', 'triplet'):
      raise ValueError(f"Unsupported negative sampling mode {self.mode!r}")
    if self.amount <= 0:
      raise ValueError('amount must be positive')

  def is_binary(self) -> bool:
    return self.mode == 'binary'

  def is_triplet(self) -> bool:
    return self.mode == 'triplet'

  def sample_size(self, num_pos: int) -> int:
    return int(np.ceil(float(self.amount) * num_pos))


@dataclasses.dataclass
class EdgeSamplerInput(CastMixin):
  """Seed edges for link-wise sampling.

  Mirrors reference `sampler/base.py:148-203`.

  Args:
    row / col: ``[B]`` global endpoint ids.
    label: optional ``[B]`` edge labels.
    input_type: edge type for hetero sampling.
    neg_sampling: negative sampling spec.
  """
  row: Union[np.ndarray, jax.Array]
  col: Union[np.ndarray, jax.Array]
  label: Optional[Union[np.ndarray, jax.Array]] = None
  input_type: Optional[EdgeType] = None
  neg_sampling: Optional[NegativeSampling] = None

  def __len__(self) -> int:
    return len(self.row)

  def __getitem__(self, index) -> 'EdgeSamplerInput':
    return EdgeSamplerInput(
        self.row[index], self.col[index],
        self.label[index] if self.label is not None else None,
        self.input_type, self.neg_sampling)


class SamplerOutput(CastMixin):
  """Homogeneous sampling result — a static-shape pytree.

  Mirrors reference `sampler/base.py:206-239` with the TPU padding
  contract:

  Attributes:
    node: ``[node_capacity]`` global node ids in insertion order
      (seeds first), INVALID_ID-padded; local index of ``node[i]`` = i.
    node_count: scalar — number of valid entries in ``node``.
    row / col: ``[edge_capacity]`` local COO (-1 when masked).  As in
      the reference, edges are emitted *transposed* for PyG message
      passing (`sampler/neighbor_sampler.py:159-166`): ``row`` is the
      neighbor and ``col`` the seed side.
    edge: ``[edge_capacity]`` global edge ids or None.
    edge_mask: ``[edge_capacity]`` validity.
    batch: ``[B]`` original (global) seed ids, INVALID_ID-padded.
    num_sampled_nodes / num_sampled_edges: per-hop counts.
    metadata: extra payload (e.g. link-prediction label indices).
  """

  def __init__(self, node, node_count, row, col, edge=None, edge_mask=None,
               batch=None, num_sampled_nodes=None, num_sampled_edges=None,
               device=None, metadata=None):
    self.node = node
    self.node_count = node_count
    self.row = row
    self.col = col
    self.edge = edge
    self.edge_mask = edge_mask
    self.batch = batch
    self.num_sampled_nodes = num_sampled_nodes
    self.num_sampled_edges = num_sampled_edges
    self.device = device
    self.metadata = metadata if metadata is not None else {}

  @property
  def batch_size(self) -> int:
    return 0 if self.batch is None else int(self.batch.shape[0])

  def tree_flatten(self):
    children = (self.node, self.node_count, self.row, self.col, self.edge,
                self.edge_mask, self.batch, self.num_sampled_nodes,
                self.num_sampled_edges, self.metadata)
    return children, (self.device,)

  @classmethod
  def tree_unflatten(cls, aux, children):
    (node, node_count, row, col, edge, edge_mask, batch, nsn, nse,
     metadata) = children
    return cls(node, node_count, row, col, edge, edge_mask, batch, nsn, nse,
               aux[0], metadata)

  def __repr__(self):
    return (f'SamplerOutput(node={getattr(self.node, "shape", None)}, '
            f'edges={getattr(self.row, "shape", None)})')


jax.tree_util.register_pytree_node(
    SamplerOutput,
    lambda s: s.tree_flatten(),
    SamplerOutput.tree_unflatten)


class HeteroSamplerOutput(CastMixin):
  """Heterogeneous sampling result keyed by node/edge type.

  Mirrors reference `sampler/base.py:242-297`.

  Attributes:
    node: ``Dict[NodeType, [cap] ids]`` (+ ``node_count`` dict).
    row / col / edge / edge_mask: ``Dict[EdgeType, [cap] arrays]``.
    batch: ``Dict[NodeType, [B] seed ids]`` (seed types only).
    edge_types: declared edge types (includes empty ones).
    metadata: extra payload.
  """

  def __init__(self, node, node_count, row, col, edge=None, edge_mask=None,
               batch=None, num_sampled_nodes=None, num_sampled_edges=None,
               edge_types=None, device=None, metadata=None):
    self.node = node
    self.node_count = node_count
    self.row = row
    self.col = col
    self.edge = edge
    self.edge_mask = edge_mask
    self.batch = batch
    self.num_sampled_nodes = num_sampled_nodes
    self.num_sampled_edges = num_sampled_edges
    self.edge_types = edge_types
    self.device = device
    self.metadata = metadata if metadata is not None else {}

  def get_edge_index(self) -> Dict[EdgeType, Any]:
    """Local COO per edge type (reference `sampler/base.py:283-297`)."""
    out = {}
    for etype in (self.edge_types or self.row.keys()):
      if etype in self.row:
        out[etype] = jnp.stack([self.row[etype], self.col[etype]])
    return out

  def tree_flatten(self):
    children = (self.node, self.node_count, self.row, self.col, self.edge,
                self.edge_mask, self.batch, self.num_sampled_nodes,
                self.num_sampled_edges, self.metadata)
    return children, (tuple(self.edge_types or ()), self.device)

  @classmethod
  def tree_unflatten(cls, aux, children):
    (node, node_count, row, col, edge, edge_mask, batch, nsn, nse,
     metadata) = children
    return cls(node, node_count, row, col, edge, edge_mask, batch, nsn, nse,
               list(aux[0]), aux[1], metadata)

  def __repr__(self):
    return (f'HeteroSamplerOutput(node_types={list(self.node)}, '
            f'edge_types={list(self.row)})')


jax.tree_util.register_pytree_node(
    HeteroSamplerOutput,
    lambda s: s.tree_flatten(),
    HeteroSamplerOutput.tree_unflatten)


class SamplingType(enum.Enum):
  """Reference `sampler/base.py:325-331`."""
  NODE = 0
  LINK = 1
  SUBGRAPH = 2
  RANDOM_WALK = 3


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
  """Bundle of sampling options carried to (distributed) workers.

  Mirrors reference `sampler/base.py:334-346`.
  """
  sampling_type: SamplingType
  num_neighbors: Optional[NumNeighbors]
  batch_size: int
  shuffle: bool
  drop_last: bool
  with_edge: bool
  collect_features: bool
  with_neg: bool
  with_weight: bool = False
  edge_dir: str = 'out'
  seed: Optional[int] = None


class BaseSampler:
  """Abstract sampler interface (reference `sampler/base.py:348-400`)."""

  def sample_from_nodes(self, inputs: NodeSamplerInput, **kwargs):
    raise NotImplementedError

  def sample_from_edges(self, inputs: EdgeSamplerInput, **kwargs):
    raise NotImplementedError

  def subgraph(self, inputs: NodeSamplerInput, **kwargs):
    raise NotImplementedError
