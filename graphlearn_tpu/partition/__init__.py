from .base import (PartitionerBase, cat_feature_cache, load_partition)
from .random_partitioner import RandomPartitioner
from .frequency_partitioner import FrequencyPartitioner
