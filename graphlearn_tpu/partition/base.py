"""Offline graph/feature partitioning with an on-disk layout.

Counterpart of reference `partition/base.py` (647 LoC): assign nodes to
partitions, cut edges by src (or dst) ownership, split features, plan
per-partition hot-feature caches, and persist everything for the
distributed runtime to load.  Differences by design:

  * storage is ``.npy``/JSON instead of ``torch.save`` pickles;
  * partition books can be dense tables (reference-compatible) or
    contiguous ranges (`RangePartitionBook`) — the TPU-friendly O(P)
    form produced when ``relabel=True`` reorders node ids so each
    partition owns a contiguous range (what the ICI all-to-all
    sampling path wants).

On-disk layout (homo)::

    root/
      META.json                        # num_parts, counts, hetero flag
      node_pb.npy  edge_pb.npy         # dense books (or *_bounds.npy)
      part{i}/graph/{rows,cols,eids}.npy
      part{i}/node_feat/{feats,ids,cache_feats,cache_ids}.npy
      part{i}/node_label/labels.npy    # labels for owned ids

Hetero adds one subdirectory level keyed by ``as_str(type)``, exactly
like the reference's layout (`partition/base.py:337-456`).
"""
from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from ..typing import (EdgeType, GraphPartitionData, FeaturePartitionData,
                      NodeType, PartitionBook, RangePartitionBook,
                      TablePartitionBook, as_str, edge_type_from_str)


class PartitionerBase:
  """Orchestrates node → graph → feature partitioning and saves to disk.

  Args:
    output_dir: root of the on-disk layout.
    num_parts: number of partitions.
    num_nodes: node count (dict per ntype for hetero).
    edge_index: ``(rows, cols)`` (dict per etype for hetero).
    node_feat / node_label: optional arrays (dicts for hetero).
    edge_feat: optional ``[E, De]`` edge features in input edge order
      (dict per etype for hetero) — partitioned by the edge partition
      book, the reference's separate ``edge_feat_pb`` world
      (`distributed/dist_dataset.py:183-193`).
    edge_assign: ``'by_src'`` or ``'by_dst'`` edge ownership
      (reference `partition/base.py:218-290` chunked variant).
    cache_ratio: fraction of hottest *remote* rows each partition
      caches (the FrequencyPartitioner's budget analog).
  """

  def __init__(self, output_dir, num_parts: int, num_nodes,
               edge_index, node_feat=None, node_label=None,
               edge_assign: str = 'by_src', cache_ratio: float = 0.0,
               edge_feat=None):
    self.output_dir = Path(output_dir)
    self.num_parts = int(num_parts)
    self.num_nodes = num_nodes
    self.edge_index = edge_index
    self.node_feat = node_feat
    self.node_label = node_label
    self.edge_feat = edge_feat
    assert edge_assign in ('by_src', 'by_dst')
    self.edge_assign = edge_assign
    self.cache_ratio = float(cache_ratio)
    self.is_hetero = isinstance(edge_index, dict)

  # -- node assignment: subclasses override -------------------------------
  def partition_node(self, ntype: Optional[NodeType] = None) -> np.ndarray:
    """Return ``[N]`` partition id per node."""
    raise NotImplementedError

  def node_hotness(self, ntype: Optional[NodeType] = None
                   ) -> Optional[np.ndarray]:
    """Optional ``[num_parts, N]`` per-partition access hotness used
    for cache planning; None disables caching."""
    return None

  # -- orchestration ------------------------------------------------------
  def partition(self) -> None:
    """Run the full pipeline and write the layout
    (reference `PartitionerBase.partition`, `partition/base.py:337`)."""
    self.output_dir.mkdir(parents=True, exist_ok=True)
    if self.is_hetero:
      node_pbs: Dict[NodeType, np.ndarray] = {}
      for nt in sorted(self._ntypes()):
        node_pbs[nt] = self.partition_node(nt)
        np.save(self.output_dir / f'node_pb_{nt}.npy', node_pbs[nt])
      for et, (rows, cols) in self.edge_index.items():
        owner_nt = et[0] if self.edge_assign == 'by_src' else et[2]
        edge_pb = self._partition_graph(
            np.asarray(rows), np.asarray(cols), node_pbs[owner_nt],
            subdir=('graph', as_str(et)), etype=et)
        if self.edge_feat and et in self.edge_feat:
          self._partition_edge_feat(np.asarray(self.edge_feat[et]),
                                    edge_pb,
                                    subdir=('edge_feat', as_str(et)))
      if self.node_feat:
        for nt, feats in self.node_feat.items():
          self._partition_feat(np.asarray(feats), node_pbs[nt],
                               self.node_hotness(nt),
                               subdir=('node_feat', nt))
      if self.node_label:
        for nt, labels in self.node_label.items():
          self._partition_label(np.asarray(labels), node_pbs[nt],
                                subdir=('node_label', nt))
      meta = {
          'num_parts': self.num_parts, 'hetero': True,
          'node_types': sorted(self._ntypes()),
          'edge_types': [as_str(et) for et in self.edge_index],
          'edge_assign': self.edge_assign,
          'num_nodes': {nt: int(self.num_nodes[nt])
                        for nt in self._ntypes()},
          'num_edges': {as_str(et): int(len(ei[0]))
                        for et, ei in self.edge_index.items()},
      }
    else:
      node_pb = self.partition_node()
      np.save(self.output_dir / 'node_pb.npy', node_pb)
      rows, cols = self.edge_index
      edge_pb = self._partition_graph(np.asarray(rows),
                                      np.asarray(cols), node_pb,
                                      subdir=('graph',))
      if self.edge_feat is not None:
        self._partition_edge_feat(np.asarray(self.edge_feat), edge_pb,
                                  subdir=('edge_feat',))
      if self.node_feat is not None:
        self._partition_feat(np.asarray(self.node_feat), node_pb,
                             self.node_hotness(), subdir=('node_feat',))
      if self.node_label is not None:
        self._partition_label(np.asarray(self.node_label), node_pb,
                              subdir=('node_label',))
      meta = {'num_parts': self.num_parts, 'hetero': False,
              'edge_assign': self.edge_assign,
              'num_nodes': int(self.num_nodes),
              'num_edges': int(len(rows))}
    with open(self.output_dir / 'META.json', 'w') as f:
      json.dump(meta, f, indent=2)

  def _ntypes(self):
    nts = set()
    for (s, _, d) in self.edge_index:
      nts.add(s)
      nts.add(d)
    return nts

  def _partition_graph(self, rows, cols, owner_pb, subdir, etype=None):
    """Cut edges by the owner node's partition; edge pb follows.

    Reference `partition/base.py:218-290` streams chunks to bound
    memory; numpy boolean selection covers the same sizes here.
    """
    owner = rows if self.edge_assign == 'by_src' else cols
    edge_pb = owner_pb[owner].astype(np.int8)
    pb_name = ('edge_pb.npy' if etype is None
               else f'edge_pb_{as_str(etype)}.npy')
    np.save(self.output_dir / pb_name, edge_pb)
    eids = np.arange(len(rows), dtype=np.int64)
    for p in range(self.num_parts):
      sel = edge_pb == p
      d = self.output_dir / f'part{p}'
      for s in subdir:
        d = d / s
      d.mkdir(parents=True, exist_ok=True)
      np.save(d / 'rows.npy', rows[sel])
      np.save(d / 'cols.npy', cols[sel])
      np.save(d / 'eids.npy', eids[sel])
    return edge_pb

  def _partition_edge_feat(self, feats, edge_pb, subdir):
    """Split edge features by the edge partition book (the reference's
    ``edge_feat_pb`` layout, `distributed/dist_dataset.py:183-193`)."""
    eids_all = np.arange(feats.shape[0], dtype=np.int64)
    for p in range(self.num_parts):
      own = edge_pb == p
      d = self.output_dir / f'part{p}'
      for s in subdir:
        d = d / s
      d.mkdir(parents=True, exist_ok=True)
      np.save(d / 'feats.npy', feats[own])
      np.save(d / 'ids.npy', eids_all[own])

  def _partition_feat(self, feats, node_pb, hotness, subdir):
    """Split features by ownership + plan per-partition hot caches
    (reference `_partition_node_feat` + `_cache_node`,
    `partition/base.py:292-315`, `frequency_partitioner.py:168-203`)."""
    n = feats.shape[0]
    ids_all = np.arange(n, dtype=np.int64)
    for p in range(self.num_parts):
      own = node_pb == p
      d = self.output_dir / f'part{p}'
      for s in subdir:
        d = d / s
      d.mkdir(parents=True, exist_ok=True)
      np.save(d / 'feats.npy', feats[own])
      np.save(d / 'ids.npy', ids_all[own])
      if self.cache_ratio > 0.0:
        budget = int(n * self.cache_ratio)
        remote = ~own
        if hotness is not None:
          score = np.where(remote, hotness[p], -np.inf)
        else:
          score = np.where(remote, 1.0, -np.inf)  # arbitrary remote rows
        k = min(budget, int(remote.sum()))
        cache_ids = np.argsort(-score, kind='stable')[:k].astype(np.int64)
        np.save(d / 'cache_ids.npy', cache_ids)
        np.save(d / 'cache_feats.npy', feats[cache_ids])

  def _partition_label(self, labels, node_pb, subdir):
    for p in range(self.num_parts):
      own = node_pb == p
      d = self.output_dir / f'part{p}'
      for s in subdir:
        d = d / s
      d.mkdir(parents=True, exist_ok=True)
      np.save(d / 'labels.npy', labels[own])
      np.save(d / 'ids.npy', np.nonzero(own)[0].astype(np.int64))


# -- loading ---------------------------------------------------------------

def _load_dir_feat(d: Path) -> Optional[FeaturePartitionData]:
  if not (d / 'feats.npy').exists():
    return None
  cache_feats = cache_ids = None
  if (d / 'cache_feats.npy').exists():
    cache_feats = np.load(d / 'cache_feats.npy')
    cache_ids = np.load(d / 'cache_ids.npy')
  return FeaturePartitionData(
      feats=np.load(d / 'feats.npy'), ids=np.load(d / 'ids.npy'),
      cache_feats=cache_feats, cache_ids=cache_ids)


def load_partition(root, part_idx: int):
  """Load one partition (reference `load_partition`,
  `partition/base.py:502-603`).

  Returns a dict with keys: ``meta``, ``graph``, ``node_feat``,
  ``node_label``, ``node_pb``, ``edge_pb`` — each a per-type dict when
  hetero.
  """
  root = Path(root)
  with open(root / 'META.json') as f:
    meta = json.load(f)
  out = {'meta': meta}
  pdir = root / f'part{part_idx}'
  if meta['hetero']:
    out['node_pb'] = {
        nt: TablePartitionBook(np.load(root / f'node_pb_{nt}.npy'),
                               meta['num_parts'])
        for nt in meta['node_types']}
    out['edge_pb'] = {}
    out['graph'] = {}
    for ets in meta['edge_types']:
      et = edge_type_from_str(ets)
      out['edge_pb'][et] = TablePartitionBook(
          np.load(root / f'edge_pb_{ets}.npy'), meta['num_parts'])
      g = pdir / 'graph' / ets
      out['graph'][et] = GraphPartitionData(
          edge_index=(np.load(g / 'rows.npy'), np.load(g / 'cols.npy')),
          eids=np.load(g / 'eids.npy'))
    out['node_feat'] = {}
    out['node_label'] = {}
    for nt in meta['node_types']:
      f = _load_dir_feat(pdir / 'node_feat' / nt)
      if f is not None:
        out['node_feat'][nt] = f
      ld = pdir / 'node_label' / nt
      if (ld / 'labels.npy').exists():
        out['node_label'][nt] = (np.load(ld / 'labels.npy'),
                                 np.load(ld / 'ids.npy'))
    out['edge_feat'] = {}
    for ets in meta['edge_types']:
      f = _load_dir_feat(pdir / 'edge_feat' / ets)
      if f is not None:
        out['edge_feat'][edge_type_from_str(ets)] = f
    if not out['edge_feat']:
      out['edge_feat'] = None
  else:
    out['node_pb'] = TablePartitionBook(np.load(root / 'node_pb.npy'),
                                        meta['num_parts'])
    out['edge_pb'] = TablePartitionBook(np.load(root / 'edge_pb.npy'),
                                        meta['num_parts'])
    g = pdir / 'graph'
    out['graph'] = GraphPartitionData(
        edge_index=(np.load(g / 'rows.npy'), np.load(g / 'cols.npy')),
        eids=np.load(g / 'eids.npy'))
    out['node_feat'] = _load_dir_feat(pdir / 'node_feat')
    out['edge_feat'] = _load_dir_feat(pdir / 'edge_feat')
    ld = pdir / 'node_label'
    out['node_label'] = ((np.load(ld / 'labels.npy'),
                          np.load(ld / 'ids.npy'))
                         if (ld / 'labels.npy').exists() else None)
  return out


def cat_feature_cache(part_feat: FeaturePartitionData
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
  """Merge cached hot rows with owned rows into one local store.

  Counterpart of reference `cat_feature_cache`
  (`partition/base.py:606-647`): cached rows go FIRST (they're the hot
  tier `Feature` pins in HBM), then owned rows.  Returns
  ``(feats, ids, id2index)`` where ``id2index`` maps global id → local
  row (-1 if absent).
  """
  if part_feat.cache_feats is None or len(part_feat.cache_ids) == 0:
    feats, ids = part_feat.feats, part_feat.ids
  else:
    feats = np.concatenate([part_feat.cache_feats, part_feat.feats])
    ids = np.concatenate([part_feat.cache_ids, part_feat.ids])
  max_id = int(ids.max()) if len(ids) else -1
  id2index = np.full((max_id + 1,), -1, dtype=np.int64)
  # later (owned) entries win if an id is both cached and owned
  id2index[ids] = np.arange(len(ids), dtype=np.int64)
  return feats, ids, id2index
