"""Random node partitioner.

Counterpart of reference `partition/random_partitioner.py:27-85`:
node partition book = a random permutation folded modulo num_parts
(balanced to within one node per partition).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..typing import NodeType
from .base import PartitionerBase


class RandomPartitioner(PartitionerBase):

  def __init__(self, *args, seed: Optional[int] = None, **kwargs):
    super().__init__(*args, **kwargs)
    self._rng = np.random.default_rng(seed)

  def partition_node(self, ntype: Optional[NodeType] = None) -> np.ndarray:
    n = (self.num_nodes[ntype] if isinstance(self.num_nodes, dict)
         else self.num_nodes)
    pb = np.empty(n, dtype=np.int8)
    perm = self._rng.permutation(n)
    for p in range(self.num_parts):
      pb[perm[p::self.num_parts]] = p
    return pb
