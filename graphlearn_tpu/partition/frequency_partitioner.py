"""Hotness-driven partitioner.

Counterpart of reference `partition/frequency_partitioner.py:26-203`:
given per-partition access probabilities (from
``NeighborSampler.sample_prob`` over each trainer's seed set — the
vectorized `cal_nbr_prob` propagation), assign node chunks to the
partition that gains the most (own hotness minus competitors'), and
let the base class cache each partition's hottest remote rows.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Union

import numpy as np

from ..typing import NodeType
from .base import PartitionerBase


class FrequencyPartitioner(PartitionerBase):
  """Args (beyond PartitionerBase):
    probs: ``[num_parts, N]`` per-partition hotness (dict for hetero);
      row ``p`` is partition ``p``'s visit probability per node.
    chunk_size: assignment granularity (reference default 10000).
  """

  def __init__(self, *args, probs=None, chunk_size: int = 10000, **kwargs):
    super().__init__(*args, **kwargs)
    assert probs is not None, 'FrequencyPartitioner needs probs'
    self.probs = probs
    self.chunk_size = int(chunk_size)

  def _probs_for(self, ntype: Optional[NodeType]):
    if isinstance(self.probs, dict):
      return np.asarray(self.probs[ntype])
    return np.asarray(self.probs)

  def partition_node(self, ntype: Optional[NodeType] = None) -> np.ndarray:
    probs = self._probs_for(ntype)          # [P, N]
    num_parts, n = probs.shape
    assert num_parts == self.num_parts
    cap = -(-n // self.num_parts)           # per-partition node budget
    pb = np.full(n, -1, dtype=np.int8)
    assigned = np.zeros(self.num_parts, dtype=np.int64)

    # Greedy chunk assignment maximizing own-hotness advantage
    # (reference `frequency_partitioner.py:104-128`): score each chunk
    # for partition p as sum(own prob) - mean(others' prob).  The
    # chunk granularity adapts so every partition sees >= 8 chunks —
    # the fixed reference default degenerates on small graphs (e.g.
    # 2 chunks for 4 partitions leaves partitions empty).
    eff_chunk = self.chunk_size
    if n // max(eff_chunk, 1) < self.num_parts * 4:
      eff_chunk = max(1, -(-n // (self.num_parts * 8)))
    chunks = [slice(i, min(i + eff_chunk, n))
              for i in range(0, n, eff_chunk)]
    # visit chunks in a deterministic shuffled order for balance
    rng = np.random.default_rng(0)
    for ci in rng.permutation(len(chunks)):
      sl = chunks[ci]
      chunk_probs = probs[:, sl]            # [P, c]
      tot = chunk_probs.sum(axis=1)         # [P]
      others = (tot.sum() - tot) / max(self.num_parts - 1, 1)
      gain = tot - others
      order = np.argsort(-gain, kind='stable')
      for p in order:
        if assigned[p] + (sl.stop - sl.start) <= cap + eff_chunk:
          pb[sl] = p
          assigned[p] += sl.stop - sl.start
          break
      else:
        p = int(np.argmin(assigned))
        pb[sl] = p
        assigned[p] += sl.stop - sl.start
    return pb

  def node_hotness(self, ntype: Optional[NodeType] = None) -> np.ndarray:
    return self._probs_for(ntype)
