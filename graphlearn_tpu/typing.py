"""Shared type vocabulary for graphlearn_tpu.

TPU-native re-design of the reference type vocabulary
(graphlearn_torch/python/typing.py:25-87).  Tensors are `jax.Array` /
`numpy.ndarray` instead of `torch.Tensor`; partition books gain a
computed (range-based) variant that is arithmetic instead of a lookup
table, because on TPU an O(1) computed owner function avoids keeping an
N-entry table in HBM and keeps the distributed sampling path fully
inside XLA.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, NamedTuple, Optional, Tuple, Union

import jax
import numpy as np

# Types for basic graph entities ##############################################

#: Node types are denoted by a single string.
NodeType = str

#: Edge types are denoted by a triplet of strings ``(src, rel, dst)``.
EdgeType = Tuple[str, str, str]

EDGE_TYPE_STR_SPLIT = '__'


def as_str(type: Union[NodeType, EdgeType]) -> str:
  """Canonical string form of a node or edge type.

  Mirrors reference `typing.py:34` (``as_str``).
  """
  if isinstance(type, NodeType):
    return type
  if isinstance(type, (list, tuple)) and len(type) == 3:
    return EDGE_TYPE_STR_SPLIT.join(type)
  return ''


def edge_type_from_str(s: str) -> Union[NodeType, EdgeType]:
  """Inverse of :func:`as_str` for edge types."""
  parts = s.split(EDGE_TYPE_STR_SPLIT)
  if len(parts) == 3:
    return tuple(parts)
  return s


def reverse_edge_type(etype: EdgeType) -> EdgeType:
  """Reverse an edge type, adding/stripping the ``rev_`` prefix.

  Mirrors reference `typing.py:42-53`.
  """
  src, edge, dst = etype
  if not src == dst:
    if edge.split('_', 1)[0] == 'rev':  # undirected edge with `rev_` prefix.
      edge = edge.split('_', 1)[1]
    else:
      edge = 'rev_' + edge
  return (dst, edge, src)


#: Anything acceptable as dense tensor data on the host side.
TensorDataType = Union[jax.Array, np.ndarray]

# Types for partition data ####################################################


class GraphPartitionData(NamedTuple):
  """Data and indexing info of a graph partition.

  Mirrors reference `typing.py:56-62`.
  """
  # edge index (rows, cols)
  edge_index: Tuple[np.ndarray, np.ndarray]
  # edge ids corresponding to `edge_index`
  eids: np.ndarray


class FeaturePartitionData(NamedTuple):
  """Data and indexing info of a node/edge feature partition.

  Mirrors reference `typing.py:64-71`.
  """
  feats: np.ndarray
  ids: np.ndarray
  cache_feats: Optional[np.ndarray]
  cache_ids: Optional[np.ndarray]


HeteroGraphPartitionData = Dict[EdgeType, GraphPartitionData]
HeteroFeaturePartitionData = Dict[Union[NodeType, EdgeType],
                                  FeaturePartitionData]

# Types for partition books ###################################################


class PartitionBook:
  """Maps global entity ids to owning partition.

  The reference uses a dense ``torch.Tensor`` lookup table
  (`typing.py:77`).  On TPU we additionally support a *range* partition
  book (contiguous ownership ranges) whose lookup is a vectorized
  ``searchsorted`` — O(log P) arithmetic with O(P) memory, which keeps
  the owner computation jittable and HBM-free for billion-node graphs.
  """

  def __getitem__(self, ids):
    raise NotImplementedError

  @property
  def num_partitions(self) -> int:
    raise NotImplementedError

  def to_device(self):
    """Return a jittable representation (jax arrays)."""
    raise NotImplementedError


class TablePartitionBook(PartitionBook):
  """Dense per-id owner table (reference-compatible)."""

  def __init__(self, table: np.ndarray, num_partitions: Optional[int] = None):
    self.table = np.asarray(table)
    self._num_partitions = (int(num_partitions) if num_partitions is not None
                            else int(self.table.max()) + 1 if self.table.size
                            else 1)
    self._device_table = None

  def __getitem__(self, ids):
    if isinstance(ids, jax.Array):
      return self.to_device()[ids]
    return self.table[np.asarray(ids)]

  def __len__(self):
    return len(self.table)

  @property
  def num_partitions(self) -> int:
    return self._num_partitions

  def to_device(self):
    import jax.numpy as jnp
    if self._device_table is None:
      self._device_table = jnp.asarray(self.table)
    return self._device_table


class RangePartitionBook(PartitionBook):
  """Contiguous-range ownership: partition ``p`` owns ids in
  ``[bounds[p], bounds[p+1])``.

  TPU-native replacement for dense partition books: after (re)labeling
  nodes so each partition owns a contiguous id range, the owner lookup
  becomes ``searchsorted(bounds, ids, 'right') - 1``.
  """

  def __init__(self, bounds: np.ndarray):
    # bounds: [P+1] monotonically nondecreasing, bounds[0] == 0.
    self.bounds = np.asarray(bounds, dtype=np.int64)
    assert self.bounds.ndim == 1 and len(self.bounds) >= 2

  def __getitem__(self, ids):
    import jax.numpy as jnp
    if isinstance(ids, jax.Array):
      return (jnp.searchsorted(jnp.asarray(self.bounds), ids, side='right')
              - 1).astype(jnp.int32)
    return (np.searchsorted(self.bounds, np.asarray(ids), side='right')
            - 1).astype(np.int32)

  def __len__(self):
    return int(self.bounds[-1])

  @property
  def num_partitions(self) -> int:
    return len(self.bounds) - 1

  def to_device(self):
    import jax.numpy as jnp
    return jnp.asarray(self.bounds)


HeteroNodePartitionDict = Dict[NodeType, PartitionBook]
HeteroEdgePartitionDict = Dict[EdgeType, PartitionBook]

# Types for neighbor sampling #################################################

InputNodes = Union[TensorDataType, NodeType, Tuple[NodeType, TensorDataType]]
EdgeIndexTensor = Union[TensorDataType, Tuple[TensorDataType, TensorDataType]]
InputEdges = Union[EdgeIndexTensor, EdgeType, Tuple[EdgeType, EdgeIndexTensor]]
NumNeighbors = Union[List[int], Dict[EdgeType, List[int]]]


@dataclasses.dataclass
class Split:
  """A train/val/test id split."""
  train: Optional[np.ndarray] = None
  val: Optional[np.ndarray] = None
  test: Optional[np.ndarray] = None
