"""Cross-process shared-memory channel.

Wraps the native SysV-shm MPMC ring queue (`csrc/shm_queue.cc`, the
TPU-host twin of the reference's `csrc/shm_queue.cc:138-151` +
`SampleQueue`).  Messages are tensor-map serialized in C
(`csrc/tensor_map.cc`) — no pickle on the hot path.  The channel is
picklable by shmid so producer subprocesses attach to the same segment
(reference `py_export.cc:132-140` pickles `SampleQueue` the same way).
"""
from __future__ import annotations

from ..native import ShmQueue
from ..utils.units import parse_size
from .base import ChannelBase, SampleMessage


class ShmChannel(ChannelBase):
  """Fixed-capacity shm ring of sample messages.

  Args:
    capacity: max queued messages (reference ``ShmChannel(capacity,...)``,
      `channel/shm_channel.py:24-60`).
    shm_size: total shared-memory budget in bytes, or a string like
      ``'64MB'``; per-slot size = shm_size / capacity.
  """

  def __init__(self, capacity: int = 64, shm_size='64MB'):
    shm_bytes = parse_size(shm_size)
    slot = max(int(shm_bytes) // max(capacity, 1), 4096)
    self._q = ShmQueue(num_slots=capacity, slot_bytes=slot)

  def send(self, msg: SampleMessage) -> None:
    # carries the sender's ambient span context (telemetry.spans) —
    # the '#SPAN' uint8 tensor rides the C tensor-map like any array
    self._send_traced('send', self._q.put, msg)

  def recv(self) -> SampleMessage:
    return self._recv_traced('recv', self._q.get)

  def _occupancy(self) -> int:
    try:
      return int(self._q.qsize())
    except Exception:             # noqa: BLE001 — native probe only
      return -1

  def recv_timeout(self, timeout: float):
    """Dequeue with a timeout; ``None`` when nothing arrived — the
    hook liveness watchdogs need (blocking fast path preserved).
    Strips the producer's span context like :meth:`recv` does."""
    return self._park_span(self._q.get_timed(timeout))

  def recv_bytes(self) -> bytes:
    """Dequeue one message still in tensor-map wire form — lets the
    server forward it over RPC without a parse/re-serialize round trip."""
    return self._q.get_bytes()

  def recv_bytes_timeout(self, timeout: float):
    """Timed `recv_bytes` (``None`` on timeout) — the server's fetch
    handler polls with this so a dead producer pool surfaces as an
    RPC error to the client instead of a forever-blocked request."""
    return self._q.get_bytes_timed(timeout)

  def empty(self) -> bool:
    return self._q.empty()

  def pin_memory(self) -> None:
    """No-op on TPU hosts: there is no cudaHostRegister analog — the
    consumer's `jax.device_put` path already staged through host DRAM
    (reference `ShmChannel.pin_memory`, `channel/shm_channel.py:47`)."""

  def close(self) -> None:
    self._q.close()

  def __reduce__(self):
    return (_attach, (self._q,))


def _attach(q):
  ch = ShmChannel.__new__(ShmChannel)
  ch._q = q
  return ch
