"""Pull-based prefetching channel for server-fed loaders.

Reference `channel/remote_channel.py:23-85`: the client keeps
``prefetch_size`` async fetches in flight against a sampling server's
message buffer and hands results to the trainer in order.  Here the
fetch is any callable (the `DistClient` binds it to a socket RPC); a
small thread pool keeps the pipeline full — the asyncio/torch-future
machinery of the reference collapses to ``concurrent.futures``.

Epoch hygiene: messages carry an ``'#EPOCH'`` stamp.  If the consumer
abandons an epoch early, leftover messages (including ones already in
flight) surface on the next epoch and are *discarded by stamp* rather
than delivered as training data; each discard issues a replacement
fetch, so accounting stays exact.

Failure hygiene (the resilience layer): messages also carry a
``'#SEQ'`` batch-identity stamp.  A supervisor that restarted a dead
sampling worker replays its unacknowledged batches; replays the
original DID deliver surface here as duplicate seqs and are discarded
without being counted — the epoch finishes with exactly the expected
number of UNIQUE batches, no lost and no duplicated work.  And
:meth:`recv_timeout` waits on the in-flight future with a real
deadline, so `DistLoader`'s poll-and-supervise loop works against the
remote channel instead of blocking forever in ``.result()`` on a dead
peer (the timed-out fetch stays in flight; a *failed* fetch is dropped
and transparently resubmitted by the next fill).
"""
from __future__ import annotations

import collections
import concurrent.futures as cf
import time
from typing import Callable, Optional

import numpy as np

from .base import ChannelBase, SampleMessage

EPOCH_KEY = '#EPOCH'
SEQ_KEY = '#SEQ'
SRC_KEY = '#SRC'


class RemoteReceivingChannel(ChannelBase):
  """Prefetch ``num_expected`` messages per epoch from ``fetch_fn``.

  Args:
    fetch_fn: blocking callable returning one `SampleMessage`.
    num_expected: messages per epoch (loader's batch count).
    prefetch_size: in-flight fetches (reference default 4,
      `dist_options.py:202-258`).
  """

  def __init__(self, fetch_fn: Callable[[], SampleMessage],
               num_expected: int, prefetch_size: int = 4):
    self._fetch = fetch_fn
    # source-routed replacements: when a discard frees a fetch slot,
    # the real undelivered message sits in the DISCARDED message's
    # server buffer — a fetch_fn that takes a ``src`` hint lets the
    # replacement go there instead of round-robin (a fetch to a server
    # that owes nothing blocks out its whole fetch deadline)
    try:
      import inspect
      self._src_aware = 'src' in inspect.signature(fetch_fn).parameters
    except (TypeError, ValueError):
      self._src_aware = False
    self._num_expected = num_expected
    self._prefetch = max(1, prefetch_size)
    self._pool = cf.ThreadPoolExecutor(max_workers=self._prefetch)
    self._pending: collections.deque = collections.deque()
    self._received = 0
    self._epoch = -1
    self._seen_seqs: set = set()
    self.duplicates_discarded = 0    # run-total, for tests/telemetry

  def _replace_discarded(self, msg) -> None:
    """A discarded message (stale epoch or replay duplicate) consumed
    one fetch; re-issue it against the same source so accounting stays
    exact AND placed where the owed message actually is."""
    src = msg.get(SRC_KEY)
    if self._src_aware and src is not None:
      self._pending.append(
          self._pool.submit(self._fetch, int(np.asarray(src))))
    # else: _fill() tops the pipeline back up on the next call

  def reset(self, num_expected: Optional[int] = None,
            epoch: Optional[int] = None) -> None:
    """Start a new epoch.  In-flight fetches are kept — their results
    are filtered by epoch stamp when they surface."""
    if num_expected is not None:
      self._num_expected = num_expected
    self._epoch = self._epoch + 1 if epoch is None else epoch
    self._received = 0
    self._seen_seqs = set()

  def reduce_expected(self, k: int) -> None:
    """Degraded mode: ``k`` of this epoch's messages are known lost
    for good (a dead peer past its deadline) — stop waiting for them."""
    self._num_expected = max(self._received,
                             self._num_expected - int(k))

  def _fill(self) -> None:
    want = min(self._prefetch, self._num_expected - self._received)
    while len(self._pending) < want:
      self._pending.append(self._pool.submit(self._fetch))

  def send(self, msg: SampleMessage) -> None:
    raise RuntimeError('RemoteReceivingChannel is receive-only')

  def _recv(self, timeout: Optional[float]) -> Optional[SampleMessage]:
    if self._received >= self._num_expected:
      raise StopIteration
    deadline = (None if timeout is None
                else time.monotonic() + timeout)
    while True:
      if self._received >= self._num_expected:
        raise StopIteration        # dedup/degrade closed the epoch
      self._fill()
      if not self._pending:
        self._pending.append(self._pool.submit(self._fetch))
      head = self._pending[0]
      remaining = (None if deadline is None
                   else deadline - time.monotonic())
      if remaining is not None and remaining <= 0:
        return None
      done, _ = cf.wait([head], timeout=remaining)
      if not done:
        # clean timeout: the fetch STAYS in flight (no lost message,
        # no resubmit storm) — the caller runs its liveness checks
        # and polls again
        return None
      self._pending.popleft()
      # a FAILED fetch propagates (fetch_fn already retried under its
      # policy; what escapes is RetryExhausted / PeerLostError) — the
      # message it owed is still owed, and the next _fill() resubmits
      msg = head.result()
      stamp = msg.get(EPOCH_KEY)
      if stamp is not None and int(np.asarray(stamp)) != self._epoch:
        # stale message from an abandoned epoch; refetch from the
        # same source
        self._replace_discarded(msg)
        continue
      seq = msg.get(SEQ_KEY)
      if seq is not None:
        # identity = (source, seq): independent producers (one per
        # server in a fanout plan) each number their seqs from 0
        src = msg.get(SRC_KEY)
        key = (int(np.asarray(src)) if src is not None else 0,
               int(np.asarray(seq)))
        if key in self._seen_seqs:
          # replayed batch whose original got through: discard, don't
          # count — the source-routed replacement keeps accounting
          # exact
          self.duplicates_discarded += 1
          self._replace_discarded(msg)
          continue
        self._seen_seqs.add(key)
      self._received += 1
      # strip + park the producer's span context (telemetry.spans) —
      # it crossed the server RPC as an ordinary '#SPAN' tensor
      return self._park_span(msg)

  def recv(self) -> SampleMessage:
    return self._recv(None)

  def recv_timeout(self, timeout: float):
    """Timed receive (``None`` on timeout) — the deadline applies to
    the WAIT, while the underlying fetch keeps running; see the module
    docstring for why a timeout never loses a message."""
    return self._recv(timeout)

  def empty(self) -> bool:
    return not self._pending

  def close(self) -> None:
    self._pool.shutdown(wait=False, cancel_futures=True)
