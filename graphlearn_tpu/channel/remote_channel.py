"""Pull-based prefetching channel for server-fed loaders.

Reference `channel/remote_channel.py:23-85`: the client keeps
``prefetch_size`` async fetches in flight against a sampling server's
message buffer and hands results to the trainer in order.  Here the
fetch is any callable (the `DistClient` binds it to a socket RPC); a
small thread pool keeps the pipeline full — the asyncio/torch-future
machinery of the reference collapses to ``concurrent.futures``.
"""
from __future__ import annotations

import collections
import concurrent.futures as cf
from typing import Callable, Optional

from .base import ChannelBase, SampleMessage

# Server returns this key to signal the epoch's message stream is done.
END_OF_EPOCH = '#END_OF_EPOCH'


class RemoteReceivingChannel(ChannelBase):
  """Prefetch ``num_expected`` messages per epoch from ``fetch_fn``.

  Args:
    fetch_fn: blocking callable returning one `SampleMessage`.
    num_expected: messages per epoch (loader's batch count).
    prefetch_size: in-flight fetches (reference default 4,
      `dist_options.py:202-258`).
  """

  def __init__(self, fetch_fn: Callable[[], SampleMessage],
               num_expected: int, prefetch_size: int = 4):
    self._fetch = fetch_fn
    self._num_expected = num_expected
    self._prefetch = max(1, prefetch_size)
    self._pool = cf.ThreadPoolExecutor(max_workers=self._prefetch)
    self._pending: collections.deque = collections.deque()
    self._issued = 0
    self._received = 0

  def reset(self, num_expected: Optional[int] = None) -> None:
    """Start a new epoch (reference re-creates the channel per epoch)."""
    if num_expected is not None:
      self._num_expected = num_expected
    self._issued = 0
    self._received = 0
    self._pending.clear()

  def _fill(self) -> None:
    while (self._issued < self._num_expected
           and len(self._pending) < self._prefetch):
      self._pending.append(self._pool.submit(self._fetch))
      self._issued += 1

  def send(self, msg: SampleMessage) -> None:
    raise RuntimeError('RemoteReceivingChannel is receive-only')

  def recv(self) -> SampleMessage:
    if self._received >= self._num_expected:
      raise StopIteration
    self._fill()
    msg = self._pending.popleft().result()
    self._received += 1
    self._fill()
    return msg

  def empty(self) -> bool:
    return not self._pending

  def close(self) -> None:
    self._pool.shutdown(wait=False, cancel_futures=True)
