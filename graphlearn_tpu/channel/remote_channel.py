"""Pull-based prefetching channel for server-fed loaders.

Reference `channel/remote_channel.py:23-85`: the client keeps
``prefetch_size`` async fetches in flight against a sampling server's
message buffer and hands results to the trainer in order.  Here the
fetch is any callable (the `DistClient` binds it to a socket RPC); a
small thread pool keeps the pipeline full — the asyncio/torch-future
machinery of the reference collapses to ``concurrent.futures``.

Epoch hygiene: messages carry an ``'#EPOCH'`` stamp.  If the consumer
abandons an epoch early, leftover messages (including ones already in
flight) surface on the next epoch and are *discarded by stamp* rather
than delivered as training data; each discard issues a replacement
fetch, so accounting stays exact.
"""
from __future__ import annotations

import collections
import concurrent.futures as cf
from typing import Callable, Optional

import numpy as np

from .base import ChannelBase, SampleMessage

EPOCH_KEY = '#EPOCH'


class RemoteReceivingChannel(ChannelBase):
  """Prefetch ``num_expected`` messages per epoch from ``fetch_fn``.

  Args:
    fetch_fn: blocking callable returning one `SampleMessage`.
    num_expected: messages per epoch (loader's batch count).
    prefetch_size: in-flight fetches (reference default 4,
      `dist_options.py:202-258`).
  """

  def __init__(self, fetch_fn: Callable[[], SampleMessage],
               num_expected: int, prefetch_size: int = 4):
    self._fetch = fetch_fn
    self._num_expected = num_expected
    self._prefetch = max(1, prefetch_size)
    self._pool = cf.ThreadPoolExecutor(max_workers=self._prefetch)
    self._pending: collections.deque = collections.deque()
    self._received = 0
    self._epoch = -1

  def reset(self, num_expected: Optional[int] = None,
            epoch: Optional[int] = None) -> None:
    """Start a new epoch.  In-flight fetches are kept — their results
    are filtered by epoch stamp when they surface."""
    if num_expected is not None:
      self._num_expected = num_expected
    self._epoch = self._epoch + 1 if epoch is None else epoch
    self._received = 0

  def _fill(self) -> None:
    want = min(self._prefetch, self._num_expected - self._received)
    while len(self._pending) < want:
      self._pending.append(self._pool.submit(self._fetch))

  def send(self, msg: SampleMessage) -> None:
    raise RuntimeError('RemoteReceivingChannel is receive-only')

  def recv(self) -> SampleMessage:
    if self._received >= self._num_expected:
      raise StopIteration
    while True:
      self._fill()
      if not self._pending:
        self._pending.append(self._pool.submit(self._fetch))
      msg = self._pending.popleft().result()
      stamp = msg.get(EPOCH_KEY)
      if stamp is not None and int(np.asarray(stamp)) != self._epoch:
        continue     # stale message from an abandoned epoch; refetch
      self._received += 1
      # strip + park the producer's span context (telemetry.spans) —
      # it crossed the server RPC as an ordinary '#SPAN' tensor
      return self._park_span(msg)

  def empty(self) -> bool:
    return not self._pending

  def close(self) -> None:
    self._pool.shutdown(wait=False, cancel_futures=True)
