"""multiprocessing.Queue channel (reference `channel/mp_channel.py:21-34`).

Slower than `ShmChannel` (pickle per message) but size-unbounded and
dependency-free; the debugging/fallback transport.
"""
from __future__ import annotations

import multiprocessing as mp
import queue as queue_mod

from .base import ChannelBase, SampleMessage


class MpChannel(ChannelBase):

  def __init__(self, maxsize: int = 0):
    self._q = mp.get_context('spawn').Queue(maxsize)

  def send(self, msg: SampleMessage) -> None:
    # carries the sender's ambient span context (telemetry.spans)
    self._send_traced('send', self._q.put, msg)

  def recv(self) -> SampleMessage:
    return self._recv_traced('recv', self._q.get)

  def recv_timeout(self, timeout: float):
    """Timed dequeue (``None`` on timeout) — same watchdog contract as
    `ShmChannel.recv_timeout`."""
    try:
      return self._park_span(self._q.get(timeout=timeout))
    except queue_mod.Empty:
      return None

  def _occupancy(self) -> int:
    try:
      return int(self._q.qsize())
    except (NotImplementedError, OSError):
      return -1

  def empty(self) -> bool:
    return self._q.empty()

  def close(self) -> None:
    self._q.close()
