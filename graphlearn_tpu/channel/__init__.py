"""Sample-message channels: producer -> consumer transport.

TPU-native counterpart of the reference `python/channel/`
(`channel/base.py`, `shm_channel.py`, `mp_channel.py`,
`remote_channel.py`): typed queues carrying flat ``SampleMessage``
dicts from sampling producers to the training process.
"""
from .base import ChannelBase, SampleMessage
from .mp_channel import MpChannel
from .remote_channel import RemoteReceivingChannel
from .shm_channel import ShmChannel

__all__ = ['ChannelBase', 'SampleMessage', 'ShmChannel', 'MpChannel',
           'RemoteReceivingChannel']
