"""Channel contract (reference `channel/base.py:24-42`).

A ``SampleMessage`` is a flat ``Dict[str, np.ndarray]`` — the
process-portable form of one sampled mini-batch (the reference uses
``Dict[str, torch.Tensor]``, `channel/base.py:24`).  Key conventions
(mirroring `distributed/dist_neighbor_sampler.py:600-673`):

  * ``'#IS_HETERO'``: uint8 scalar flag.
  * ``'#META.<name>'``: loader metadata entries.
  * homo: ``ids / rows / cols / eids / nfeats / nlabels / batch ...``
  * hetero: ``'<type>.ids'``, ``'<src>__<rel>__<dst>.rows'``, ...
"""
from __future__ import annotations

import abc
from typing import Dict

import numpy as np

SampleMessage = Dict[str, np.ndarray]


class ChannelBase(abc.ABC):
  """Abstract producer->consumer sample-message queue."""

  @abc.abstractmethod
  def send(self, msg: SampleMessage) -> None:
    """Enqueue one message (blocks when full)."""

  @abc.abstractmethod
  def recv(self) -> SampleMessage:
    """Dequeue one message (blocks when empty)."""

  def empty(self) -> bool:
    raise NotImplementedError

  def close(self) -> None:
    pass
