"""Channel contract (reference `channel/base.py:24-42`).

A ``SampleMessage`` is a flat ``Dict[str, np.ndarray]`` — the
process-portable form of one sampled mini-batch (the reference uses
``Dict[str, torch.Tensor]``, `channel/base.py:24`).  Key conventions
(mirroring `distributed/dist_neighbor_sampler.py:600-673`):

  * ``'#IS_HETERO'``: uint8 scalar flag.
  * ``'#META.<name>'``: loader metadata entries.
  * homo: ``ids / rows / cols / eids / nfeats / nlabels / batch ...``
  * hetero: ``'<type>.ids'``, ``'<src>__<rel>__<dst>.rows'``, ...
"""
from __future__ import annotations

import abc
import time
from typing import Dict

import numpy as np

SampleMessage = Dict[str, np.ndarray]

#: a send/recv that blocks longer than this counts as a ring STALL —
#: the producer outran the consumer (send) or starved it (recv).
STALL_SECS = 0.01


class ChannelTelemetry:
  """Ring occupancy/stall instrumentation shared by the channels.

  Concrete channels wrap their blocking queue ops in :meth:`_timed`:
  every call ticks ``channel.<op>.calls`` in the metrics registry;
  calls that blocked past `STALL_SECS` tick ``channel.<op>.stalls`` /
  ``.stall_secs`` and emit a ``channel.stall`` flight-recorder event
  carrying the ring occupancy when the transport exposes one
  (`_occupancy`; -1 = unknown).  Cheap when the recorder is off: two
  perf_counter reads and two counter ticks per message.

  Span propagation (`telemetry.spans`): :meth:`_send_traced` injects
  the sender's ambient span context into the message (a uint8 tensor
  under ``'#SPAN'`` — every transport ships it like any other array);
  :meth:`_recv_traced` strips it and parks it at
  :attr:`last_span_context`, so a consumer can causally link its
  recv/collate spans to the producer's trace.  Both are single
  attribute checks when the recorder is off.
  """

  #: span context of the most recently received message (None when the
  #: producer ran recorder-off or predates span propagation).
  last_span_context = None

  def _occupancy(self) -> int:
    """Messages currently queued; -1 when the transport can't say."""
    return -1

  def _timed(self, op: str, fn, *args):
    from ..telemetry.recorder import recorder
    from ..utils.profiling import metrics
    t0 = time.perf_counter()
    out = fn(*args)
    dt = time.perf_counter() - t0
    metrics.inc(f'channel.{op}.calls')
    if dt > STALL_SECS:
      metrics.inc(f'channel.{op}.stalls')
      metrics.inc(f'channel.{op}.stall_secs', dt)
      recorder.emit('channel.stall', op=op, secs=round(dt, 6),
                    occupancy=self._occupancy(),
                    channel=type(self).__name__)
    return out

  def _send_traced(self, op: str, fn, msg):
    from ..telemetry import spans
    spans.inject(msg)
    try:
      return self._timed(op, fn, msg)
    except ValueError:
      if spans.SPAN_KEY not in msg:
        raise
      # the context tensor pushed a message that fit before past a
      # fixed transport budget (shm slot size): drop the LINK, never
      # the message — enabling telemetry must not fail sends that
      # succeed with it off
      msg.pop(spans.SPAN_KEY, None)
      return self._timed(op, fn, msg)

  def _park_span(self, msg):
    """THE strip-and-park contract (one definition for every receive
    path: blocking recv, timed recv, remote prefetch): pop the
    message's '#SPAN' context and expose it at `last_span_context`."""
    if msg is not None:
      from ..telemetry import spans
      self.last_span_context = spans.extract(msg)
    return msg

  def _recv_traced(self, op: str, fn, *args):
    return self._park_span(self._timed(op, fn, *args))


class ChannelBase(ChannelTelemetry, abc.ABC):
  """Abstract producer->consumer sample-message queue."""

  @abc.abstractmethod
  def send(self, msg: SampleMessage) -> None:
    """Enqueue one message (blocks when full)."""

  @abc.abstractmethod
  def recv(self) -> SampleMessage:
    """Dequeue one message (blocks when empty)."""

  def recv_timeout(self, timeout: float):
    """Dequeue with a deadline; ``None`` when nothing arrived in time.
    The liveness-watchdog primitive: every consumer poll loop
    (`DistLoader._recv_current_epoch`) interleaves timed waits with
    peer/worker supervision, so a dead producer surfaces as an error
    instead of a hang.  Implementations must strip-and-park the span
    context exactly like :meth:`recv`."""
    raise NotImplementedError(
        f'{type(self).__name__} has no timed receive')

  def empty(self) -> bool:
    raise NotImplementedError

  def close(self) -> None:
    pass
