"""CastMixin. Counterpart of reference `utils/mixin.py`."""
from __future__ import annotations


class CastMixin:
  """Allows flexible construction: ``T.cast(x)`` accepts an existing
  instance, a tuple of args, a dict of kwargs, or a single value."""

  @classmethod
  def cast(cls, *args, **kwargs):
    if len(args) == 1 and len(kwargs) == 0:
      elem = args[0]
      if elem is None:
        return None
      if isinstance(elem, CastMixin):
        return elem
      if isinstance(elem, tuple):
        return cls(*elem)
      if isinstance(elem, dict):
        return cls(**elem)
    return cls(*args, **kwargs)
