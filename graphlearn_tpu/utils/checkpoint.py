"""Checkpoint/resume for training state.

The reference has NO checkpointing (SURVEY §5: examples rely on
user-level ``torch.save``) — this module is beyond parity: an
orbax-backed store for arbitrary pytrees (train state, optimizer,
step counters) with a synchronous save/restore API shaped like the
examples need it.  Falls back to a numpy+pickle layout when orbax is
unavailable, so checkpoints work in any environment.

Usage::

    ckpt = Checkpointer('/ckpts/run1')
    ckpt.save(step, state)                  # keeps the newest K
    state = ckpt.restore(template=state)    # None if empty
    step = ckpt.latest_step()
"""
from __future__ import annotations

import pickle
import shutil
from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np


def _try_orbax():
  try:
    import orbax.checkpoint as ocp
    return ocp
  except Exception:  # pragma: no cover - baked into this env, gate anyway
    return None


class Checkpointer:
  """Step-indexed pytree checkpoints under one directory.

  Args:
    directory: checkpoint root (created on first save).
    max_to_keep: retain the newest K step directories.
    use_orbax: force the backend; default auto (orbax if importable).
  """

  def __init__(self, directory, max_to_keep: int = 3,
               use_orbax: Optional[bool] = None):
    self.directory = Path(directory)
    self.max_to_keep = int(max_to_keep)
    ocp = _try_orbax() if use_orbax in (None, True) else None
    self._orbax = (ocp is not None) if use_orbax is None else use_orbax
    if self._orbax and ocp is None:
      raise RuntimeError('orbax requested but not importable')
    self._ckptr = ocp.PyTreeCheckpointer() if self._orbax else None

  # -- paths --------------------------------------------------------------
  def _step_dir(self, step: int) -> Path:
    return self.directory / f'step_{int(step):012d}'

  def all_steps(self):
    if not self.directory.exists():
      return []
    out = []
    for p in self.directory.iterdir():
      if p.name.startswith('step_'):
        try:
          out.append(int(p.name[5:]))
        except ValueError:
          continue
    return sorted(out)

  def latest_step(self) -> Optional[int]:
    steps = self.all_steps()
    return steps[-1] if steps else None

  # -- save/restore -------------------------------------------------------
  def save(self, step: int, tree: Any) -> Path:
    self.directory.mkdir(parents=True, exist_ok=True)
    d = self._step_dir(step)
    tmp = d.with_suffix('.tmp')
    if tmp.exists():
      shutil.rmtree(tmp)
    host_tree = jax.tree_util.tree_map(np.asarray, tree)
    if self._orbax:
      self._ckptr.save(tmp, host_tree)
    else:
      tmp.mkdir(parents=True)
      leaves, treedef = jax.tree_util.tree_flatten(host_tree)
      np.savez(tmp / 'leaves.npz',
               **{f'l{i}': v for i, v in enumerate(leaves)})
      with open(tmp / 'treedef.pkl', 'wb') as f:
        pickle.dump(treedef, f, protocol=5)
    if d.exists():
      shutil.rmtree(d)
    tmp.rename(d)                      # atomic publish
    self._gc()
    return d

  def restore(self, template: Any = None, step: Optional[int] = None
              ) -> Optional[Any]:
    """Load the given (default: latest) step; ``None`` when empty.

    ``template`` (a pytree of the expected structure) is required for
    the fallback backend and recommended for orbax (restores with
    matching dtypes/shapes).
    """
    step = step if step is not None else self.latest_step()
    if step is None:
      return None
    d = self._step_dir(step)
    if self._orbax:
      host_template = (None if template is None else
                       jax.tree_util.tree_map(np.asarray, template))
      return self._ckptr.restore(d, item=host_template)
    if template is None:
      raise ValueError('fallback backend needs a template pytree')
    with open(d / 'treedef.pkl', 'rb') as f:
      treedef = pickle.load(f)
    data = np.load(d / 'leaves.npz')
    leaves = [data[f'l{i}'] for i in range(len(data.files))]
    return jax.tree_util.tree_unflatten(treedef, leaves)

  def _gc(self):
    steps = self.all_steps()
    for s in steps[:-self.max_to_keep]:
      shutil.rmtree(self._step_dir(s), ignore_errors=True)
