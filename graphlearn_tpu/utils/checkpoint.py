"""Checkpoint/resume for training state AND the data plane.

The reference has NO checkpointing (SURVEY §5: examples rely on
user-level ``torch.save``) — this module is beyond parity twice over:

  * :class:`Checkpointer` — an orbax-backed store for arbitrary
    pytrees (train state, optimizer, step counters) with a synchronous
    save/restore API shaped like the examples need it.  Falls back to
    a numpy+pickle layout when orbax is unavailable, so checkpoints
    work in any environment.  ``restore(template=)`` VALIDATES the
    loaded tree against the template (structure, dtypes, shapes) and
    raises :class:`CheckpointMismatchError` naming the first diverging
    path — a stale checkpoint must fail loudly, not restore garbage.
  * the **DataPlaneState protocol** + :class:`SnapshotManager` —
    durable mid-epoch snapshots of every stateful data-plane component
    (loader cursors + permutation RNGs, producer positions, cold-cache
    rings, fused-epoch chunk progress), so a preempted process resumes
    with byte-identical remaining batches.  ``torch.save`` captures
    model weights but not loader position, sampler RNG, or cache
    state; this captures all of them at the fused drivers' chunk
    boundaries (the natural recovery points).

DataPlaneState protocol (duck-typed — no base class to inherit):

  * ``state_dict() -> dict`` — a pytree of numpy-compatible leaves
    (arrays / ints / packed bytes via :func:`pack_rng_state` /
    :func:`pack_bytes`) capturing everything needed to resume;
  * ``load_state_dict(state) -> None`` — restore from such a tree
    (leaves may come back as 0-d numpy arrays; coerce with ``int()``).

Usage::

    ckpt = Checkpointer('/ckpts/run1')
    ckpt.save(step, state)                  # keeps the newest K
    state = ckpt.restore(template=state)    # None if empty
    step = ckpt.latest_step()

    snap = SnapshotManager('/ckpts/run1/plane', every=2)
    fused.attach_snapshots(snap)            # saves at chunk boundaries
    # after a preemption, in a fresh process:
    fused.attach_snapshots(snap)
    state = fused.restore_from_snapshot(state)   # mid-epoch rewind
    state, stats = fused.run(state)              # finishes the epoch

Env knobs: ``GLT_SNAPSHOT_DIR`` (default snapshot root — enables
snapshotting in drivers that were not handed a manager explicitly),
``GLT_SNAPSHOT_EVERY`` (chunk boundaries between saves, default 1).
"""
from __future__ import annotations

import os
import pickle
import shutil
import time
from pathlib import Path
from typing import Any, Dict, Optional

import jax
import numpy as np

SNAPSHOT_DIR_ENV = 'GLT_SNAPSHOT_DIR'
SNAPSHOT_EVERY_ENV = 'GLT_SNAPSHOT_EVERY'


class CheckpointMismatchError(ValueError):
  """A restored checkpoint does not match the caller's template: the
  tree structure differs, or a leaf's dtype/shape diverges.  ``path``
  names the first diverging tree path — the actionable datum (a stale
  checkpoint restoring silently is how a resumed job trains on
  garbage)."""

  def __init__(self, msg: str, path: str = ''):
    super().__init__(msg)
    self.path = path


def _try_orbax():
  try:
    import orbax.checkpoint as ocp
    return ocp
  except Exception:  # pragma: no cover - baked into this env, gate anyway
    return None


def _leaf_paths(tree) -> Dict[str, Any]:
  """Flatten a pytree to ``{'/a/b[0]': leaf}`` using key paths — the
  mismatch diagnostics' vocabulary."""
  flat, _ = jax.tree_util.tree_flatten_with_path(tree)
  return {jax.tree_util.keystr(kp): v for kp, v in flat}


def validate_tree(restored: Any, template: Any) -> None:
  """Raise `CheckpointMismatchError` (first diverging path) unless
  ``restored`` matches ``template`` in structure and per-leaf
  dtype/shape.  Scalar-vs-0-d-array differences are tolerated (the
  numpy backend round-trips python ints through 0-d arrays)."""
  r_def = jax.tree_util.tree_structure(restored)
  t_def = jax.tree_util.tree_structure(template)
  if r_def != t_def:
    r_paths = set(_leaf_paths(restored))
    t_paths = set(_leaf_paths(template))
    diverging = sorted((r_paths - t_paths) | (t_paths - r_paths))
    path = diverging[0] if diverging else '<root>'
    raise CheckpointMismatchError(
        f'checkpoint tree structure does not match the template '
        f'(first diverging path: {path}; checkpoint has '
        f'{r_def.num_leaves} leaves, template {t_def.num_leaves})',
        path=path)
  r_leaves = _leaf_paths(restored)
  for path, t_leaf in _leaf_paths(template).items():
    r_leaf = r_leaves[path]
    r_arr, t_arr = np.asarray(r_leaf), np.asarray(t_leaf)
    if r_arr.shape != t_arr.shape:
      raise CheckpointMismatchError(
          f'checkpoint leaf {path} has shape {r_arr.shape}, template '
          f'expects {t_arr.shape}', path=path)
    if r_arr.dtype != t_arr.dtype:
      raise CheckpointMismatchError(
          f'checkpoint leaf {path} has dtype {r_arr.dtype}, template '
          f'expects {t_arr.dtype}', path=path)


def pack_bytes(obj: Any) -> np.ndarray:
  """Pickle an arbitrary host object into a uint8 array so it rides a
  numpy-leaf pytree (RNG states hold 128-bit ints numpy cannot
  represent directly)."""
  return np.frombuffer(pickle.dumps(obj, protocol=5), np.uint8).copy()


def unpack_bytes(arr) -> Any:
  return pickle.loads(np.asarray(arr, np.uint8).tobytes())


def pack_rng_state(rng: np.random.Generator) -> np.ndarray:
  """Capture a numpy Generator's full bit-generator state as a
  checkpointable leaf."""
  return pack_bytes(rng.bit_generator.state)


def restore_rng_state(rng: np.random.Generator, packed) -> None:
  rng.bit_generator.state = unpack_bytes(packed)


class Checkpointer:
  """Step-indexed pytree checkpoints under one directory.

  Args:
    directory: checkpoint root (created on first save).
    max_to_keep: retain the newest K step directories.
    use_orbax: force the backend; default auto (orbax if importable).
  """

  def __init__(self, directory, max_to_keep: int = 3,
               use_orbax: Optional[bool] = None):
    self.directory = Path(directory)
    self.max_to_keep = int(max_to_keep)
    ocp = _try_orbax() if use_orbax in (None, True) else None
    self._orbax = (ocp is not None) if use_orbax is None else use_orbax
    if self._orbax and ocp is None:
      raise RuntimeError('orbax requested but not importable')
    self._ckptr = ocp.PyTreeCheckpointer() if self._orbax else None

  # -- paths --------------------------------------------------------------
  def _step_dir(self, step: int) -> Path:
    return self.directory / f'step_{int(step):012d}'

  def all_steps(self):
    if not self.directory.exists():
      return []
    out = []
    for p in self.directory.iterdir():
      if p.name.startswith('step_'):
        try:
          out.append(int(p.name[5:]))
        except ValueError:
          continue
    return sorted(out)

  def latest_step(self) -> Optional[int]:
    steps = self.all_steps()
    return steps[-1] if steps else None

  # -- save/restore -------------------------------------------------------
  def save(self, step: int, tree: Any) -> Path:
    from ..testing import chaos
    self.directory.mkdir(parents=True, exist_ok=True)
    d = self._step_dir(step)
    tmp = d.with_suffix('.tmp')
    if tmp.exists():
      shutil.rmtree(tmp)
    # chaos seam: a planned 'fail' dies before any byte is written; a
    # 'truncate' writes a PARTIAL tmp dir and dies before the atomic
    # rename — either way the previous published snapshot stays the
    # durable latest (what the kill-mid-write acceptance pins)
    faults = chaos.on('checkpoint.io', step=int(step),
                      path=str(self.directory))
    if any(f.action == 'fail' for f in faults):
      raise OSError(f'injected checkpoint write failure (step {step})')
    host_tree = jax.tree_util.tree_map(np.asarray, tree)
    truncate = any(f.action == 'truncate' for f in faults)
    if self._orbax and not truncate:
      self._ckptr.save(tmp, host_tree)
    else:
      tmp.mkdir(parents=True)
      leaves, treedef = jax.tree_util.tree_flatten(host_tree)
      np.savez(tmp / 'leaves.npz',
               **{f'l{i}': v for i, v in enumerate(leaves)})
      with open(tmp / 'treedef.pkl', 'wb') as f:
        pickle.dump(treedef, f, protocol=5)
      if truncate:
        # cut the leaves file mid-stream, like a kill during the
        # write, then die WITHOUT publishing: the .tmp carcass must
        # never shadow the last good step
        with open(tmp / 'leaves.npz', 'r+b') as f:
          f.truncate(max(f.seek(0, 2) // 2, 1))
        raise OSError(
            f'injected truncated checkpoint write (step {step})')
    if d.exists():
      shutil.rmtree(d)
    tmp.rename(d)                      # atomic publish
    self._gc()
    return d

  def restore(self, template: Any = None, step: Optional[int] = None
              ) -> Optional[Any]:
    """Load the given (default: latest) step; ``None`` when empty.

    ``template`` (a pytree of the expected structure) is optional but
    recommended: when given, the restored tree is VALIDATED against it
    (structure + per-leaf dtype/shape, both backends) and a divergence
    raises `CheckpointMismatchError` naming the first diverging path.
    """
    step = step if step is not None else self.latest_step()
    if step is None:
      return None
    d = self._step_dir(step)
    if self._orbax:
      host_template = (None if template is None else
                       jax.tree_util.tree_map(np.asarray, template))
      try:
        out = self._ckptr.restore(d, item=host_template)
      except CheckpointMismatchError:
        raise
      except Exception as e:        # noqa: BLE001 — typed below
        if template is None:
          raise
        # orbax raises its own (untyped) structure errors before our
        # validation can run — re-restore in the SAVED structure and
        # diff that against the template for the diverging-path
        # diagnostic, falling back to the raw orbax message
        try:
          raw = self._ckptr.restore(d)
        except Exception:           # noqa: BLE001 — carcass unreadable
          raise CheckpointMismatchError(
              f'checkpoint at {d} does not match the template and '
              f'could not be read structurally: {e}') from e
        validate_tree(raw, host_template)
        raise CheckpointMismatchError(
            f'checkpoint at {d} does not match the template: {e}'
        ) from e
    else:
      with open(d / 'treedef.pkl', 'rb') as f:
        treedef = pickle.load(f)
      data = np.load(d / 'leaves.npz')
      leaves = [data[f'l{i}'] for i in range(len(data.files))]
      out = jax.tree_util.tree_unflatten(treedef, leaves)
    if template is not None:
      validate_tree(out, template)
    return out

  def _gc(self):
    steps = self.all_steps()
    for s in steps[:-self.max_to_keep]:
      shutil.rmtree(self._step_dir(s), ignore_errors=True)


# -- data-plane snapshots ----------------------------------------------------

def snapshot_dir_from_env() -> Optional[str]:
  """``GLT_SNAPSHOT_DIR`` — the opt-in that lets drivers build their
  own `SnapshotManager` when none was attached explicitly."""
  return os.environ.get(SNAPSHOT_DIR_ENV) or None


def snapshot_every_from_env(default: int = 1) -> int:
  try:
    return max(int(os.environ.get(SNAPSHOT_EVERY_ENV, default)), 1)
  except ValueError:
    return default


class SnapshotManager:
  """Durable epoch-state snapshots for one training job.

  One manager owns one snapshot directory and a save cadence
  (``every`` chunk boundaries between saves — `GLT_SNAPSHOT_EVERY`).
  The payload is a single pytree ``{'plane': <component states>,
  'progress': <epoch/chunk cursor + partial stats>, 'train':
  <TrainState, host copies>}`` written through `Checkpointer` (atomic
  tmp+rename publish; a kill mid-write leaves the previous snapshot as
  the durable latest).  Monotone snapshot indices double as the
  Checkpointer step, so ``restore_latest`` is always the newest
  published state.

  A FAILED save (disk full, injected `checkpoint.io` fault) is
  absorbed: the epoch continues, the failure lands in telemetry
  (``snapshot.save`` with ``ok=False``) — losing one snapshot's
  durability must not kill the training it exists to protect.
  """

  def __init__(self, directory: Optional[str] = None,
               every: Optional[int] = None, max_to_keep: int = 2,
               use_orbax: Optional[bool] = False):
    directory = directory or snapshot_dir_from_env()
    if directory is None:
      raise ValueError('SnapshotManager needs a directory (argument '
                       'or GLT_SNAPSHOT_DIR)')
    # numpy backend by default: snapshot payloads carry packed-bytes
    # leaves and nested progress dicts that orbax's strict typed
    # restore refuses without a full template (which a fresh process
    # restoring mid-epoch does not have yet)
    self._ckpt = Checkpointer(directory, max_to_keep=max_to_keep,
                              use_orbax=use_orbax)
    self.every = max(int(every), 1) if every is not None \
        else snapshot_every_from_env()
    self._save_idx = 0
    self._boundaries = 0
    # live ops plane: snapshot AGES at scrape time (a save-age gauge
    # growing past the cadence = durability silently stalled — the
    # exact condition the absorbed-failure contract can hide).
    # Latest manager in the process wins the gauge.
    self._last_save_mono: Optional[float] = None
    self._last_restore_mono: Optional[float] = None
    from ..telemetry.live import live
    # bound methods pinned ONCE: each `self._save_age` access builds
    # a fresh bound-method object, so close()'s fn-identity check
    # must compare against the exact objects registered here
    self._age_fns = (self._save_age, self._restore_age)
    live.gauge('snapshot.save_age_seconds', fn=self._age_fns[0])
    live.gauge('snapshot.restore_age_seconds', fn=self._age_fns[1])

  def close(self) -> None:
    """Unregister this manager's age gauges.  Call when snapshotting
    legitimately ENDS (training finished): otherwise the save-age
    keeps growing on a process that stopped saving on purpose — a
    guaranteed false 'durability stalled' alarm — and the gauge
    closure pins the manager for process lifetime.  fn-identity
    guarded: a newer manager's gauges survive an old one's close."""
    from ..telemetry.live import live
    live.unregister_gauge('snapshot.save_age_seconds',
                          fn=self._age_fns[0])
    live.unregister_gauge('snapshot.restore_age_seconds',
                          fn=self._age_fns[1])

  def _save_age(self) -> Optional[float]:
    if self._last_save_mono is None:
      return None
    return round(time.monotonic() - self._last_save_mono, 3)

  def _restore_age(self) -> Optional[float]:
    if self._last_restore_mono is None:
      return None
    return round(time.monotonic() - self._last_restore_mono, 3)

  @property
  def directory(self) -> Path:
    return self._ckpt.directory

  def due(self) -> bool:
    """Tick one chunk boundary; True when this boundary should save
    (every Nth, counting from the first)."""
    due = self._boundaries % self.every == 0
    self._boundaries += 1
    return due

  def save(self, plane: dict, progress: dict,
           train: Any = None) -> bool:
    """Write one snapshot; returns False (and records the failure)
    instead of raising when the write fails."""
    from ..telemetry.recorder import recorder
    payload = {'plane': plane, 'progress': progress}
    if train is not None:
      payload['train'] = jax.tree_util.tree_map(np.asarray, train)
    self._save_idx += 1
    t0 = time.perf_counter()
    from .profiling import metrics
    try:
      self._ckpt.save(self._save_idx, payload)
    except OSError as e:
      metrics.inc('snapshot.save_failures_total')
      recorder.emit('snapshot.save', index=self._save_idx, ok=False,
                    error=str(e), dir=str(self.directory))
      return False
    self._last_save_mono = time.monotonic()
    metrics.inc('snapshot.saves_total')
    recorder.emit('snapshot.save', index=self._save_idx, ok=True,
                  secs=round(time.perf_counter() - t0, 4),
                  dir=str(self.directory),
                  epoch=progress.get('epoch'),
                  next_chunk=progress.get('next_chunk'))
    return True

  def restore_latest(self) -> Optional[dict]:
    """Load the newest READABLE published snapshot payload (``None``
    when the directory holds none) and emit ``snapshot.restore``.

    An unreadable newest snapshot (torn disk, a crash on a
    filesystem whose dir rename is not atomic) is SKIPPED to the next
    older step — ``max_to_keep > 1`` retains older snapshots exactly
    for this — with the failure recorded (``snapshot.restore`` with
    ``ok=False``); only when every retained snapshot is unreadable
    does the newest error propagate."""
    from ..telemetry.recorder import recorder
    t0 = time.perf_counter()
    steps = self._ckpt.all_steps()
    if not steps:
      return None
    first_err = None
    for step in reversed(steps):
      try:
        out = self._ckpt.restore(step=step)
      except Exception as e:          # noqa: BLE001 — skip-to-older
        first_err = first_err if first_err is not None else e
        recorder.emit('snapshot.restore', index=step, ok=False,
                      dir=str(self.directory), error=repr(e))
        continue
      self._save_idx = step          # later saves continue the index
      self._last_restore_mono = time.monotonic()
      recorder.emit('snapshot.restore', index=step,
                    secs=round(time.perf_counter() - t0, 4),
                    dir=str(self.directory),
                    epoch=_scalar(out.get('progress', {}).get('epoch')),
                    next_chunk=_scalar(
                        out.get('progress', {}).get('next_chunk')))
      return out
    raise first_err


def _scalar(v):
  """0-d-array-tolerant int coercion for restored progress fields."""
  if v is None:
    return None
  return int(np.asarray(v))
