"""Host/device tensor helpers.

TPU-native counterpart of reference `utils/tensor.py` (convert_to_tensor,
share_memory, id2idx).  Host arrays are numpy; device arrays are jax.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np


def convert_to_array(data: Any, dtype: Optional[np.dtype] = None):
  """Convert input (nested dicts / lists / tuples / arrays, or torch
  tensors if torch happens to be importable) into numpy arrays.

  Mirrors reference `utils/tensor.py:convert_to_tensor` but lands on the
  host (numpy): graph construction is a host-side activity; arrays move
  to TPU HBM explicitly via `jnp.asarray` / `jax.device_put` at
  `Graph`/`Feature` init time.
  """
  if data is None:
    return None
  if isinstance(data, dict):
    return {k: convert_to_array(v, dtype) for k, v in data.items()}
  if isinstance(data, (list, tuple)) and len(data) > 0 and (
      hasattr(data[0], '__array__') or isinstance(data[0], (list, tuple))):
    return type(data)(convert_to_array(v, dtype) for v in data)
  if hasattr(data, 'detach'):  # torch tensor without importing torch
    data = data.detach().cpu().numpy()
  arr = np.asarray(data)
  if dtype is not None:
    arr = arr.astype(dtype, copy=False)
  return arr


def id2idx(ids: Union[np.ndarray, jax.Array], max_id: Optional[int] = None):
  """Build a dense id->index map: ``out[ids[i]] = i``, -1 elsewhere.

  Mirrors reference `utils/tensor.py:28-36` (id2idx), used by `Feature`
  to map global ids onto storage rows.
  """
  ids = np.asarray(ids)
  n = int(max_id) + 1 if max_id is not None else (int(ids.max()) + 1
                                                  if ids.size else 0)
  out = np.full((n,), -1, dtype=np.int64)
  out[ids] = np.arange(len(ids), dtype=np.int64)
  return out


def to_device(tree, device: Optional[jax.Device] = None):
  """Move a pytree of host arrays onto a device (default: first device)."""
  if device is None:
    return jax.tree_util.tree_map(jnp.asarray, tree)
  return jax.device_put(tree, device)


def to_host(tree) -> Any:
  """Move a pytree of jax arrays back to host numpy."""
  return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)
