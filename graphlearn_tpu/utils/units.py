"""Size-string parsing. Counterpart of reference `utils/units.py`."""
from __future__ import annotations

from typing import Union

UNITS = {
    'KB': 2**10, 'MB': 2**20, 'GB': 2**30, 'TB': 2**40,
    'K': 2**10, 'M': 2**20, 'G': 2**30, 'T': 2**40,
    'B': 1,
}


def parse_size(size: Union[int, float, str]) -> int:
  """Parse '512MB' / '4GB' / 1024 / '10%'-free numbers into bytes."""
  if isinstance(size, (int, float)):
    return int(size)
  s = size.strip().upper().replace(' ', '')
  for unit in ('KB', 'MB', 'GB', 'TB', 'K', 'M', 'G', 'T', 'B'):
    if s.endswith(unit):
      return int(float(s[:-len(unit)]) * UNITS[unit])
  return int(float(s))


def format_size(num_bytes: int) -> str:
  for unit, scale in (('TB', 2**40), ('GB', 2**30), ('MB', 2**20),
                      ('KB', 2**10)):
    if num_bytes >= scale:
      return f'{num_bytes / scale:.2f}{unit}'
  return f'{num_bytes}B'
