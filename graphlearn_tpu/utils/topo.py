"""Topology conversions (COO <-> CSR/CSC) on the host.

Counterpart of reference `utils/topo.py:22-75` (coo_to_csr/csc, ptr2ind)
but numpy-based: topology construction is an offline/host step; the
device consumes the resulting static CSR arrays.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def coo_to_csr(
    rows: np.ndarray,
    cols: np.ndarray,
    num_nodes: Optional[int] = None,
    edge_ids: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
  """Sort a COO edge list into CSR.

  Returns ``(indptr[num_nodes+1], indices[E], edge_ids[E])``.  If
  ``edge_ids`` is None the original COO positions are used, matching the
  reference semantics where `CSRTopo` fabricates consecutive edge ids
  (`data/graph.py:28-122`).
  """
  rows = np.asarray(rows)
  cols = np.asarray(cols)
  max_row = int(rows.max(initial=-1))
  if num_nodes is None:
    num_nodes = int(max(max_row, cols.max(initial=-1))) + 1
  elif max_row >= num_nodes:
    # Row ids index indptr; columns may exceed the row count (bipartite
    # CSR), so only rows are range-checked.
    raise ValueError(
        f'source node id {max_row} out of range for num_nodes={num_nodes}')
  if len(rows) and int(min(rows.min(), cols.min())) < 0:
    raise ValueError('edge endpoint ids must be non-negative')
  if edge_ids is None:
    edge_ids = np.arange(len(rows), dtype=np.int64)
  else:
    edge_ids = np.asarray(edge_ids)
  # Sort by (row, col): within-row-sorted columns let the negative
  # sampler and subgraph op use binary search for edge membership
  # (`ops/negative.py:edge_in_csr`).  Original edge order is preserved
  # through `edge_ids`.
  perm = np.lexsort((cols, rows))
  sorted_rows = rows[perm]
  indices = cols[perm]
  edge_ids = edge_ids[perm]
  counts = np.bincount(sorted_rows, minlength=num_nodes)
  indptr = np.zeros(num_nodes + 1, dtype=np.int64)
  np.cumsum(counts, out=indptr[1:])
  return indptr, indices, edge_ids


def coo_to_csc(rows, cols, num_nodes=None, edge_ids=None):
  """CSC = CSR of the transposed graph."""
  return coo_to_csr(cols, rows, num_nodes, edge_ids)


def ptr2ind(indptr: np.ndarray) -> np.ndarray:
  """Expand a CSR ptr array into per-edge row ids.

  Counterpart of reference `utils/topo.py:ptr2ind`.
  """
  indptr = np.asarray(indptr)
  n = len(indptr) - 1
  return np.repeat(np.arange(n, dtype=indptr.dtype), np.diff(indptr))


def csr_to_coo(indptr, indices) -> Tuple[np.ndarray, np.ndarray]:
  return ptr2ind(indptr), np.asarray(indices)


def degrees_from_indptr(indptr: np.ndarray) -> np.ndarray:
  return np.diff(np.asarray(indptr))
