"""Hetero sampler-output merging/formatting helpers.

Counterparts of reference `utils/common.py:55-98`
(``merge_hetero_sampler_output`` — combine partial hetero results from
different partitions into one — and ``format_hetero_sampler_output`` —
give every declared type a presence so downstream collation never
key-errors).  TPU twist: outputs are statically padded, so the merge
concatenates per-type tables and re-deduplicates with a capacity-bound
`unique_stable`, remapping both sides' local edge indices through the
merged table.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax.numpy as jnp

from ..ops.unique import unique_stable
from ..typing import EdgeType, NodeType
from .padding import INVALID_ID, round_up


def format_hetero_sampler_output(out, ntypes: Sequence[NodeType] = (),
                                 etypes: Sequence[EdgeType] = (),
                                 node_cap: int = 8, edge_cap: int = 8):
  """Ensure every declared node/edge type is present (empty padded
  entries), so consumers can index unconditionally — reference
  `format_hetero_sampler_output` (`utils/common.py:85-98`).

  ``node_cap``/``edge_cap`` size the filled-in entries; pass the same
  per-type capacities the present batches use so jitted consumers see
  one shape per type across batches."""
  for nt in ntypes:
    if nt not in out.node:
      out.node[nt] = jnp.full((node_cap,), INVALID_ID, jnp.int32)
      out.node_count[nt] = jnp.zeros((), jnp.int32)
  for et in etypes:
    et = tuple(et)
    if et not in out.row:
      out.row[et] = jnp.full((edge_cap,), -1, jnp.int32)
      out.col[et] = jnp.full((edge_cap,), -1, jnp.int32)
      if out.edge_mask is not None:
        out.edge_mask[et] = jnp.zeros((edge_cap,), bool)
      if out.edge is not None:
        out.edge[et] = jnp.full((edge_cap,), INVALID_ID, jnp.int32)
  if out.edge_types is not None:
    declared = {tuple(e) for e in out.edge_types}
    out.edge_types = list(out.edge_types) + [
        tuple(e) for e in etypes if tuple(e) not in declared]
  return out


def merge_hetero_sampler_output(a, b, node_caps: Optional[
    Dict[NodeType, int]] = None):
  """Merge two `HeteroSamplerOutput`s into one (reference
  `merge_hetero_sampler_output`, `utils/common.py:55-82`: the
  distributed hetero path merges per-partition partials).

  Node tables concatenate per type and re-deduplicate in
  first-occurrence order (``a``'s locals stay stable when ``a``'s
  table has no internal duplicates); both sides' edge indices are
  remapped through the merged table.  ``node_caps`` bounds each merged
  table (default: sum of the two capacities).
  """
  from ..sampler.base import HeteroSamplerOutput

  node, node_count, remap = {}, {}, {}
  for nt in set(a.node) | set(b.node):
    xa = a.node.get(nt)
    xb = b.node.get(nt)
    if xa is None or xb is None:
      src = a if xb is None else b
      node[nt] = src.node[nt]
      node_count[nt] = src.node_count[nt]
      n_a = 0 if xa is None else xa.shape[0]
      remap[nt] = (jnp.arange(node[nt].shape[0] + n_a, dtype=jnp.int32),
                   n_a)
      continue
    cap = (node_caps or {}).get(
        nt, round_up(xa.shape[0] + xb.shape[0], 8))
    combined = jnp.concatenate([xa, xb])
    valid = jnp.concatenate([
        jnp.arange(xa.shape[0]) < a.node_count[nt],
        jnp.arange(xb.shape[0]) < b.node_count[nt]])
    res = unique_stable(combined, cap, valid=valid)
    node[nt] = res.values
    node_count[nt] = res.count
    remap[nt] = (res.inverse, xa.shape[0])

  def _remap_side(ids, nt, side_b: bool):
    inv, n_a = remap[nt]
    off = n_a if side_b else 0
    safe = jnp.clip(ids + off, 0, inv.shape[0] - 1)
    return jnp.where(ids >= 0, inv[safe], -1)

  any_edge = (a.edge is not None) or (b.edge is not None)
  row, col, edge, emask = {}, {}, {}, {}
  for et in list(dict.fromkeys(list(a.row) + list(b.row))):
    # emission convention (transform.py / models): row[K] holds
    # K[0]-type locals (message sources), col[K] holds K[2]-type locals
    s, _, d = et
    parts_r, parts_c, parts_e, parts_m = [], [], [], []
    for side, out in ((False, a), (True, b)):
      if et not in out.row:
        continue
      r = _remap_side(out.row[et], s, side)
      parts_r.append(r)
      parts_c.append(_remap_side(out.col[et], d, side))
      # sides lacking edge ids / masks pad to THEIR edge width so the
      # concatenated arrays stay aligned with row/col
      if any_edge:
        if out.edge is not None and et in out.edge:
          parts_e.append(out.edge[et])
        else:
          parts_e.append(jnp.full(r.shape, INVALID_ID,
                                  jnp.asarray(INVALID_ID).dtype))
      if out.edge_mask is not None and et in out.edge_mask:
        parts_m.append(out.edge_mask[et])
      else:
        parts_m.append(out.row[et] >= 0)
    row[et] = jnp.concatenate(parts_r)
    col[et] = jnp.concatenate(parts_c)
    if any_edge:
      edge[et] = jnp.concatenate(parts_e)
    # a merged-away duplicate can't invalidate an edge, but clipped
    # overflow (cap reached) must
    emask[et] = (jnp.concatenate(parts_m)
                 & (row[et] >= 0) & (col[et] >= 0))

  # first-occurrence order (a raw set would hash-randomize the order
  # across processes, desyncing jitted consumers that iterate it)
  etypes = list(dict.fromkeys(
      [tuple(e) for e in list(a.edge_types or a.row)
       + list(b.edge_types or b.row)]))
  batch = dict(a.batch or {})
  for nt, v in (b.batch or {}).items():
    # both partials contribute seeds for a shared seed type
    batch[nt] = (jnp.concatenate([batch[nt], v]) if nt in batch else v)
  return HeteroSamplerOutput(
      node=node, node_count=node_count, row=row, col=col,
      edge=edge or None, edge_mask=emask, batch=batch or None,
      edge_types=etypes, metadata={**(b.metadata or {}),
                                   **(a.metadata or {})})
