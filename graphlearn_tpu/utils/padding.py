"""Static-shape padding helpers — the backbone of the TPU design.

XLA traces a program once per shape; the reference's ragged outputs
(variable neighbor counts, growing unique-node sets) become fixed
capacities with validity masks here.  These helpers centralize the
pad/mask/bucket conventions used by every op.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

#: Sentinel for an invalid/padded node or edge id.
INVALID_ID = -1


def round_up(x: int, multiple: int) -> int:
  return -(-int(x) // int(multiple)) * int(multiple)


def next_power_of_two(x: int) -> int:
  if x <= 1:
    return 1
  return 1 << (int(x) - 1).bit_length()


def pad_1d(arr: np.ndarray, size: int, fill=INVALID_ID,
           strict: Optional[bool] = None) -> np.ndarray:
  """Pad (or truncate) a host 1-D array to a static size.

  Truncation that cuts NON-fill entries is a capacity bug in the
  caller, not routine padding — it emits a ``padding.truncate``
  flight-recorder event so the loss surfaces instead of vanishing,
  and raises when ``strict`` is True (default: env
  ``GLT_STRICT_PADDING=1``).
  """
  import os
  arr = np.asarray(arr)
  if len(arr) > size:
    tail = arr[size:]
    dropped = int((tail != fill).sum()) if tail.size else 0
    if dropped:
      from ..telemetry.recorder import recorder
      recorder.emit('padding.truncate', requested=int(len(arr)),
                    size=int(size), dropped=dropped)
      if strict or (strict is None
                    and os.environ.get('GLT_STRICT_PADDING') == '1'):
        raise ValueError(
            f'pad_1d would truncate {dropped} valid entries '
            f'({len(arr)} -> {size}); the caller undersized a static '
            'capacity')
  out = np.full((size,), fill, dtype=arr.dtype)
  n = min(len(arr), size)
  out[:n] = arr[:n]
  return out


def bucket_size(n: int, buckets: Optional[Sequence[int]] = None,
                multiple: int = 128) -> int:
  """Pick a padded size for `n`: smallest bucket >= n, or round up to a
  lane multiple.  Bucketing bounds the number of distinct compiled
  programs when batch tails vary."""
  if buckets:
    for b in sorted(buckets):
      if n <= b:
        return int(b)
  return round_up(max(n, 1), multiple)


def max_sampled_nodes(batch_size: int, num_neighbors: Sequence[int]) -> int:
  """Worst-case unique-node capacity of a multi-hop sample.

  The reference computes the same bound to size its inducer
  (`sampler/neighbor_sampler.py:595-612`); here it fixes the static
  shape of the relabeled node set.
  """
  total = batch_size
  frontier = batch_size
  for k in num_neighbors:
    frontier = frontier * int(k)
    total += frontier
  return total


def max_sampled_edges(batch_size: int, num_neighbors: Sequence[int]) -> int:
  """Worst-case sampled-edge capacity of a multi-hop sample."""
  total = 0
  frontier = batch_size
  for k in num_neighbors:
    frontier = frontier * int(k)
    total += frontier
  return total
