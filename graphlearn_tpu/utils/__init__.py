from .common import (format_hetero_sampler_output,
                     merge_hetero_sampler_output)
from .device import (assign_device, ensure_device, get_available_devices,
                     is_tpu_available)
from .mixin import CastMixin
from .padding import (INVALID_ID, bucket_size, max_sampled_edges,
                      max_sampled_nodes, next_power_of_two, pad_1d, round_up)
from .profiling import (Metrics, capture, metrics, start_trace,
                        step_annotation, stop_trace, trace)
from .tensor import convert_to_array, id2idx, to_device, to_host


def __getattr__(name):
  # checkpoint symbols are lazy: importing the module can pull orbax
  # (~4s), which every process importing the library would otherwise
  # pay — including each mp sampling producer subprocess.
  if name in ('Checkpointer', 'CheckpointMismatchError',
              'SnapshotManager'):
    from . import checkpoint
    return getattr(checkpoint, name)
  raise AttributeError(f'module {__name__!r} has no attribute {name!r}')
from .topo import (coo_to_csc, coo_to_csr, csr_to_coo, degrees_from_indptr,
                   ptr2ind)
from .units import format_size, parse_size
