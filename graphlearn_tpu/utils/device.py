"""Device discovery / assignment helpers.

Counterpart of reference `utils/device.py:21-53`
(get_available_device/assign_device/ensure_device) for the JAX backend.
"""
from __future__ import annotations

from typing import List, Optional

import jax


def get_available_devices(platform: Optional[str] = None) -> List[jax.Device]:
  """All visible accelerator devices (TPU chips, or CPU fallback)."""
  try:
    if platform is not None:
      return jax.devices(platform)
    return jax.devices()
  except RuntimeError:
    return jax.devices('cpu')


def assign_device(rank: int = 0) -> jax.Device:
  """Round-robin assignment of a device to a worker rank."""
  devs = get_available_devices()
  return devs[rank % len(devs)]


def ensure_device(device=None) -> jax.Device:
  """Normalize a device argument: None -> default device."""
  if device is None:
    return get_available_devices()[0]
  if isinstance(device, jax.Device):
    return device
  if isinstance(device, int):
    return assign_device(device)
  raise ValueError(f'Unrecognized device: {device!r}')


def is_tpu_available() -> bool:
  try:
    return any(d.platform == 'tpu' for d in jax.devices())
  except RuntimeError:
    return False
