"""Tracing, profiling and lightweight metrics.

The reference has NO tracing/profiling subsystem (SURVEY §5: wall-clock
prints in benchmarks only) — this module is deliberately beyond parity:

  * :func:`trace` — context manager emitting a `jax.profiler`
    TraceAnnotation (visible in xprof/tensorboard timelines) and
    feeding the wall-clock metrics registry;
  * :func:`start_trace` / :func:`stop_trace` — capture an xprof trace
    directory viewable in TensorBoard's profile plugin;
  * :class:`Metrics` — process-local counters/timers the loaders and
    channels tick (batches produced, edges sampled, bytes moved), with
    a one-line JSON snapshot for logs and the bench harness.
"""
from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Dict, Iterator, Optional

import jax


class Metrics:
  """Thread-safe counter/timer registry.

  >>> metrics.inc('loader.batches')
  >>> with metrics.timer('sampler.one_hop'):
  ...   ...
  >>> metrics.snapshot()
  {'loader.batches': 1, 'sampler.one_hop.secs': 0.01, ...}
  """

  def __init__(self):
    self._lock = threading.Lock()
    self._counts: Dict[str, float] = {}

  def inc(self, name: str, value: float = 1.0) -> None:
    with self._lock:
      self._counts[name] = self._counts.get(name, 0) + value

  def inc_many(self, pairs) -> None:
    """Apply several increments under ONE lock acquisition, so a
    concurrent `snapshot` sees all of them or none.  This is what
    keeps a multi-key encoding (the log2 histogram's bucket + count +
    secs triple) tear-free under a live scrape: a snapshot taken
    between two plain `inc` calls would show ``count != sum(buckets)``.
    """
    with self._lock:
      for name, value in pairs:
        self._counts[name] = self._counts.get(name, 0) + value

  @contextlib.contextmanager
  def timer(self, name: str) -> Iterator[None]:
    t0 = time.perf_counter()
    try:
      yield
    finally:
      dt = time.perf_counter() - t0
      self.inc(f'{name}.secs', dt)
      self.inc(f'{name}.calls')

  def snapshot(self) -> Dict[str, float]:
    with self._lock:
      return dict(self._counts)

  def reset(self) -> None:
    with self._lock:
      self._counts.clear()

  def dump(self) -> str:
    return json.dumps(
        {k: round(v, 6) for k, v in sorted(self.snapshot().items())})


#: process-global registry (the reference has none; loaders tick this)
metrics = Metrics()


@contextlib.contextmanager
def trace(name: str, registry: Optional[Metrics] = None) -> Iterator[None]:
  """Annotate a host-side region: shows up on the xprof timeline AND
  accumulates wall-clock in the metrics registry."""
  reg = registry if registry is not None else metrics
  with jax.profiler.TraceAnnotation(name):
    with reg.timer(name):
      yield


def start_trace(log_dir: str) -> None:
  """Begin an xprof capture (TensorBoard profile plugin format)."""
  jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
  jax.profiler.stop_trace()


@contextlib.contextmanager
def capture(log_dir: str) -> Iterator[None]:
  """Trace a whole block: ``with capture('/tmp/xprof'): train()``."""
  start_trace(log_dir)
  try:
    yield
  finally:
    stop_trace()


@contextlib.contextmanager
def step_annotation(name: str, step_num: int) -> Iterator[None]:
  """xprof STEP marker (`jax.profiler.StepTraceAnnotation`): dispatches
  wrapped in this show up as numbered steps on the TensorBoard profile
  timeline.  The fused epoch drivers wrap each program dispatch so a
  `--trace-dir` capture segments by epoch/chunk."""
  with jax.profiler.StepTraceAnnotation(name, step_num=int(step_num)):
    yield
