"""Induced-subgraph loader (SEAL-style link prediction).

Counterpart of reference `loader/subgraph_loader.py:27-98`
(``SubGraphLoader``): for each seed batch, take the multi-hop closure,
then materialize ALL edges among the collected nodes (the `SubGraphOp`
path, `csrc/cuda/subgraph_op.cu`), exposing ``mapping`` — the local
positions of the seeds — in batch metadata.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..data.dataset import Dataset
from ..sampler.base import NodeSamplerInput
from ..sampler.neighbor_sampler import NeighborSampler
from .node_loader import NodeLoader
from .transform import Batch


class SubGraphLoader(NodeLoader):
  """Loader yielding induced subgraphs around seed batches.

  Args:
    data: Dataset with a homogeneous graph.
    num_neighbors: per-hop fanouts bounding the closure.
    input_nodes: seed ids.
    max_degree: optional per-node cap for the induced-edge scan
      (bounds the intermediate on hub-heavy graphs).
  """

  def __init__(self, data: Dataset, num_neighbors: Sequence[int],
               input_nodes, batch_size: int = 1, shuffle: bool = False,
               drop_last: bool = False, with_edge: bool = False,
               max_degree: Optional[int] = None, device=None,
               seed: Optional[int] = None, **kwargs):
    sampler = NeighborSampler(
        data.get_graph(), num_neighbors, device=device,
        with_edge=with_edge, seed=seed or 0)
    super().__init__(data, sampler, input_nodes, batch_size=batch_size,
                     shuffle=shuffle, drop_last=drop_last, seed=seed,
                     **kwargs)
    self.max_degree = max_degree

  def _produce(self, seed_iter) -> Batch:
    seeds = next(seed_iter)
    out = self.sampler.subgraph(NodeSamplerInput(node=seeds),
                                max_degree=self.max_degree)
    return self._collate_fn(out)
