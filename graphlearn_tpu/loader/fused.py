"""Whole-epoch fused training: sample → collate → train in ONE program.

The per-batch path (`NeighborLoader` + `make_supervised_step`) dispatches
several XLA programs per step — sample, label gather, feature gather,
train step — each ~1 ms of device work on the headline config, so host
dispatch latency is a visible fraction of the epoch.  The reference has
the same shape (its loader feeds a separate DDP step per batch,
`examples/train_sage_ogbn_products.py:90-130`) and eats the overhead in
CUDA-stream pipelining; the TPU-idiomatic answer is stronger: put the
WHOLE epoch under one `jax.jit` as a `lax.scan` over seed batches.

  * seeds for all steps upload once per epoch as a ``[S, B]`` array;
  * the scan body = multi-hop sample → device collate → optax update,
    compiled once and reused for every epoch of the same length;
  * no host↔device chatter inside the epoch at all — the host enqueues
    one program and blocks on the final state.

Constraints (checked at construction):
  * features and labels must be fully device-resident
    (``Feature.split_ratio == 1.0``) — a host cold tier needs a host
    round trip per batch, which is exactly what `NeighborLoader`'s
    prefetching path is for;
  * homogeneous graphs (the hetero per-type dict collation is
    per-batch territory).

This is a TPU-first capability with no reference counterpart: the
torch loader cannot fuse Python-loop epochs into one graph.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..data.dataset import Dataset
from ..models.train import TrainState, make_supervised_step
from ..sampler.neighbor_sampler import NeighborSampler, _multihop_sample
from ..utils.profiling import metrics
from .node_loader import SeedBatcher
from .transform import Batch, _gather_labels


class EpochStats:
  """Lazy epoch statistics: holds DEVICE arrays; any numeric access
  syncs.  Epoch loops that don't read stats dispatch epochs back to
  back with zero host↔device round trips — on a tunneled chip each
  eager ``float()`` costs a full RTT, which measured SLOWER than the
  per-batch loop before this was made lazy."""

  def __init__(self, losses: jax.Array, correct: jax.Array,
               valid: jax.Array):
    self.losses = losses

    self._correct = correct
    self._valid = valid

  @property
  def loss(self) -> float:
    return float(self.losses.mean())

  @property
  def correct(self) -> int:
    return int(self._correct)

  @property
  def seeds(self) -> int:
    return int(self._valid)

  @property
  def accuracy(self) -> float:
    return self.correct / max(self.seeds, 1)

  def __getitem__(self, key: str):
    return getattr(self, key)

  def __repr__(self):
    return f'EpochStats(steps={self.losses.shape[0]}, <lazy>)'


class FusedEpoch:
  """One-program supervised training epochs over neighbor sampling.

  Example::

      fused = FusedEpoch(dataset, [15, 10, 5], train_idx, apply_fn, tx,
                         batch_size=1024, shuffle=True, seed=0)
      for epoch in range(10):
        state, stats = fused.run(state)
        print(stats['loss'], stats['accuracy'])

  Args:
    data: `Dataset` with a homogeneous graph, fully device-resident
      features (``split_ratio == 1.0``) and integer labels.
    num_neighbors: per-hop fanouts.
    input_nodes: seed ids (or boolean mask) — e.g. the train split.
    apply_fn / tx: model apply function and optax transformation, the
      same pair `make_supervised_step` takes.
    batch_size / shuffle / drop_last / seed: epoch iteration controls
      (`SeedBatcher` semantics — the tail batch is INVALID_ID-padded).
    sort_locality: forwarded to the sampler's hop kernel.
  """

  def __init__(self, data: Dataset, num_neighbors: Sequence[int],
               input_nodes, apply_fn: Callable,
               tx: optax.GradientTransformation, batch_size: int,
               shuffle: bool = True, drop_last: bool = False,
               seed: Optional[int] = None, sort_locality: bool = True):
    if data.is_hetero:
      raise ValueError('FusedEpoch is homogeneous-only; use the '
                       'per-batch NeighborLoader for hetero graphs')
    feat = data.node_features
    if feat is None:
      raise ValueError('FusedEpoch needs node features')
    if feat.hot_rows < feat.size(0):
      raise ValueError(
          f'FusedEpoch needs fully device-resident features '
          f'(split_ratio == 1.0); this Feature keeps '
          f'{feat.size(0) - feat.hot_rows} rows on host. '
          f'Use NeighborLoader(prefetch=2) for tiered tables.')
    labels = data.get_node_label_device()
    if labels is None:
      raise ValueError('FusedEpoch needs node labels')

    self.data = data
    self.batch_size = int(batch_size)
    self.fanouts = tuple(int(k) for k in num_neighbors)
    self.sort_locality = bool(sort_locality)

    graph = data.get_graph()
    self._indptr = graph.indptr
    self._indices = graph.indices
    self._feat = feat
    self._labels = labels

    # identical capacity arithmetic to the per-batch sampler, so fused
    # and per-batch programs see the same static shapes
    ref = NeighborSampler(graph, self.fanouts, seed=0)
    self._node_cap = ref.node_capacity(self.batch_size)

    input_nodes = np.asarray(input_nodes)
    if input_nodes.dtype == np.bool_:
      input_nodes = np.nonzero(input_nodes)[0]
    self._batcher = SeedBatcher(input_nodes, self.batch_size, shuffle,
                                drop_last, seed)
    self._base_key = jax.random.key(seed or 0)
    self._epoch_idx = 0
    self._step = make_supervised_step(apply_fn, tx, self.batch_size)
    self._compiled = jax.jit(self._epoch_fn, donate_argnums=(0,))

  def __len__(self) -> int:
    return len(self._batcher)

  # -- the one program ------------------------------------------------------

  def _epoch_fn(self, state: TrainState, seeds_all: jax.Array,
                key: jax.Array):
    """``[S, B]`` seed batches → S fused sample+collate+train steps."""

    def body(state, xs):
      i, seeds = xs
      (nodes, _count, row, col, _edge, emask, seed_local, _nsn,
       _nse) = _multihop_sample(
           self._indptr, self._indices, None, seeds,
           jax.random.fold_in(key, i),
           fanouts=self.fanouts, node_cap=self._node_cap,
           with_edge=False, sort_locality=self.sort_locality)
      batch = Batch(
          x=self._feat._device_get(nodes),
          y=_gather_labels(self._labels, nodes),
          edge_index=jnp.stack([row, col]),
          node=nodes, node_mask=nodes >= 0, edge_mask=emask,
          batch=seeds, batch_size=self.batch_size,
          metadata={'seed_local': seed_local})
      state, loss, correct = self._step(state, batch)
      return state, (loss, correct, jnp.sum(seeds >= 0))

    steps = jnp.arange(seeds_all.shape[0], dtype=jnp.int32)
    state, (losses, corrects, valids) = jax.lax.scan(
        body, state, (steps, seeds_all))
    return state, losses, jnp.sum(corrects), jnp.sum(valids)

  # -- host driver ----------------------------------------------------------

  def run(self, state: TrainState) -> Tuple[TrainState, dict]:
    """Run one epoch; returns ``(state, stats)`` with per-step losses,
    their mean, and train accuracy over this epoch's seeds.

    The input ``state`` is DONATED to the epoch program (its buffers
    are reused for the output state) — thread the returned state
    forward and don't touch the argument again, exactly as with a
    donated jitted train step.

    ``stats`` is LAZY (`EpochStats`): reading ``.loss`` etc. syncs on
    the epoch; a loop that ignores it never blocks."""
    seeds = np.stack(list(self._batcher))          # [S, B], host shuffle
    self._epoch_idx += 1
    key = jax.random.fold_in(self._base_key, self._epoch_idx)
    state, losses, correct, valid = self._compiled(
        state, jnp.asarray(seeds), key)
    metrics.inc('loader.batches', seeds.shape[0])
    return state, EpochStats(losses, correct, valid)
