"""Whole-epoch fused training: sample → collate → train in ONE program.

The per-batch path (`NeighborLoader` + `make_supervised_step`) dispatches
several XLA programs per step — sample, label gather, feature gather,
train step — each ~1 ms of device work on the headline config, so host
dispatch latency is a visible fraction of the epoch.  The reference has
the same shape (its loader feeds a separate DDP step per batch,
`examples/train_sage_ogbn_products.py:90-130`) and eats the overhead in
CUDA-stream pipelining; the TPU-idiomatic answer is stronger: put the
WHOLE epoch under one `jax.jit` as a `lax.scan` over seed batches.

  * seeds for all steps upload once per epoch as a ``[S, B]`` array;
  * the scan body = multi-hop sample → device collate → optax update,
    compiled once and reused for every epoch of the same length;
  * no host↔device chatter inside the epoch at all — the host enqueues
    one program and blocks on the final state.

Constraints (checked at construction):
  * homogeneous graphs (the hetero per-type dict collation is
    per-batch territory).

TIERED Features (``split_ratio < 1``) run as **tiered fused epochs**
(ISSUE 5): each chunk of ``max_steps_per_program`` (or the auto
``GLT_FUSED_COLD_CHUNK`` bound) dispatches a sample-only collect
scan, then the host cold service fills ``x`` per step through the
cache-aware tiered `Feature` lookup (HBM victim-cache hits are a
device gather; misses host-gather + admit — `data.cold_cache`), then
a train scan consumes the corrected batches.  The fused dispatch
structure survives tiering at O(S/chunk) programs.

This is a TPU-first capability with no reference counterpart: the
torch loader cannot fuse Python-loop epochs into one graph.
"""
from __future__ import annotations

import contextlib
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..data.dataset import Dataset
from ..data.feature import _device_gather
from ..models.train import (TrainState, make_extracted_eval_step,
                            make_extracted_supervised_step)
from ..ops.negative import sample_negative
from ..ops.pallas_gather import pallas_enabled
from ..ops.pallas_sample import fused_sample_enabled
from ..ops.pallas_window import prepare_window_table
from ..sampler.base import NegativeSampling
from ..sampler.neighbor_sampler import (NeighborSampler, _multihop_sample,
                                        _triplet_neg_dst)
from ..utils.profiling import metrics, step_annotation
from .link_loader import EdgeSeedBatcher
from .node_loader import SeedBatcher
from .transform import Batch, _gather_labels


@contextlib.contextmanager
def _fresh_compile():
  """Force any compile inside the block to bypass the persistent
  compilation cache.  Executing a DESERIALIZED cached fused-epoch
  executable crashes the tunneled TPU worker ("TPU device error")
  while the same program compiled fresh runs clean — reproduced both
  ways back to back (see benchmarks/README).

  Two latches must be defeated (both verified against jax 0.9):

  * ``jax_enable_compilation_cache`` is consulted through
    ``compilation_cache.is_cache_used``, which CACHES its answer at
    the process's first compile — so flipping the flag alone is a
    no-op once any setup compile has latched the cache on (this
    exact failure shipped a cache-HIT "fused compile" of 2 s where a
    fresh compile takes ~70 s).  ``reset_cache()`` clears that latch
    before and after the block, so compiles inside re-evaluate the
    (disabled) flag and compiles after re-latch it fresh.
  * the cache DIR itself also latches at first use; never touched
    here.

  The flag flip uses the State's thread-local context manager, but
  the latch reset is PROCESS-global: a compile racing on another
  thread during the block can latch the cache off for itself (safe
  direction — it merely recompiles).  Neither knob is part of the
  jit trace context, so nothing here retraces or invalidates
  already-compiled epochs.  Both symbols live in jax._src (no
  stability guarantee); if an upgrade moves them, the degraded path
  disables the persistent cache for the REST OF THE PROCESS and
  warns — crash avoidance beats cache reuse, and a scoped restore
  would be theater (the global flag alone cannot un-latch an
  already-enabled cache, the exact no-op this function exists to
  avoid).  Best effort only: against a cache latched on BEFORE the
  first fused dispatch even that may not bite — the warning tells
  the operator to pin jax or clear the cache dir."""
  # Both symbols live in jax._src (no stability guarantee) and were
  # verified against jax 0.9.x; `tests/test_fused_epoch.py::
  # test_fresh_compile_internals_present` fails loudly on an upgrade
  # that moves them, instead of silently taking the degraded
  # process-wide-disable path below (ADVICE r4).
  try:
    from jax._src import compilation_cache as _cc
    from jax._src.config import enable_compilation_cache as _state
    _reset = _cc.reset_cache
  except (ImportError, AttributeError):
    _reset = _state = None
  if _state is not None and _reset is not None:
    _reset()
    try:
      with _state(False):
        yield
    finally:
      _reset()
    return
  import warnings
  warnings.warn(
      'jax internals moved (jax._src.compilation_cache/config): the '
      'fused-program compilation-cache bypass cannot be scoped; '
      'disabling the persistent compilation cache process-wide for '
      'safety (see loader.fused._fresh_compile)', stacklevel=3)
  jax.config.update('jax_enable_compilation_cache', False)
  yield


#: `fast_compile` option: skip the EXPENSIVE LLVM passes for a big
#: scan program whose COMPILE wall, not runtime, is the cost — dev
#: iteration and CPU-mesh validation.  Measured at the bench shape
#: (P=8, fanout [15,10,5], 3-layer 256-hidden SAGE): ~38% off the
#: scan compile.  Deliberately NOT `xla_backend_optimization_level=0`:
#: that leaves the graph so unfused that CPU codegen gets SLOWER at
#: big shapes (measured: the B=512 compile blew past 2x baseline).
_FAST_COMPILE_OPTIONS = {'xla_llvm_disable_expensive_passes': True}


def _uncached_jit(fn, fast_compile: bool = False,
                  cacheable: bool = False, **jit_kwargs):
  """`jax.jit` whose every call runs under `_fresh_compile` — the
  bypass is attached to the callable ONCE, so no dispatch site can
  forget it.  Compiles (the first call and the donated-layout
  recompile on the second) skip the persistent cache; in-memory
  executable hits are unaffected.  Use this for any products-scale
  scan program.  ``fast_compile`` trades runtime for compile wall
  (see `_FAST_COMPILE_OPTIONS`).

  ``GLT_FUSED_COMPILE_CACHE=1`` opts back INTO the persistent cache,
  but only for callables built with ``cacheable=True`` (the fused
  classes pass it when ``max_steps_per_program`` bounds the program):
  the r5 re-test of the r3 "deserialized executable crashes the TPU
  worker" finding showed a CHUNKED tree-epoch program loading from
  the cache and running value-pulled-correct in a fresh process
  (12.3 s vs 67.7 s fresh, identical losses) — the r3 crash is now
  attributed to the tunnel's ~70 s execution watchdog killing
  FULL-LENGTH programs (whose "successful" fresh runs were elided,
  benchmarks/README "Execution watchdog"), so full-length programs
  never opt in.  The env var is read at DISPATCH time, not wrap
  time, so a harness that sets it after construction (or clears it
  between epochs) still takes effect.

  Every dispatch feeds the telemetry plane: an in-memory executable
  hit ticks ``fused.compile.hits``; a dispatch that compiled ticks
  ``fused.compile.misses`` + ``fused.compile.secs`` and emits a
  ``fused.compile`` flight-recorder event whose ``secs`` is the wall
  of that dispatch (compile + first execution — the same definition
  bench.py's compile numbers use).

  The returned callable also keeps PER-CALLABLE counters —
  ``call.calls`` and ``call.compiles`` — so a caller can pin "this
  program never recompiled" without diffing the process-global
  metrics registry (the serving plane's zero-recompile-after-warmup
  acceptance assertion, `serving.engine`)."""
  import os as _os
  import time as _time
  from ..telemetry.recorder import recorder
  if fast_compile:
    jit_kwargs = dict(jit_kwargs,
                      compiler_options=_FAST_COMPILE_OPTIONS)
  compiled = jax.jit(fn, **jit_kwargs)
  name = getattr(fn, '__qualname__', None) or getattr(
      fn, '__name__', 'jit_fn')

  def _cache_size() -> int:
    try:
      return compiled._cache_size()
    except Exception:             # noqa: BLE001 — jax internals moved
      return -1

  def call(*args, **kwargs):
    use_cache = (cacheable and
                 _os.environ.get('GLT_FUSED_COMPILE_CACHE') == '1')
    before = _cache_size()
    t0 = _time.perf_counter()
    call.calls += 1
    if use_cache:
      out = compiled(*args, **kwargs)
    else:
      with _fresh_compile():
        out = compiled(*args, **kwargs)
    after = _cache_size()
    if after >= 0 and after > before:
      dt = _time.perf_counter() - t0
      call.compiles += 1
      metrics.inc('fused.compile.misses')
      metrics.inc('fused.compile.secs', dt)
      recorder.emit('fused.compile', fn=name, secs=round(dt, 3),
                    persistent_cache=bool(use_cache))
    elif after >= 0:
      metrics.inc('fused.compile.hits')
    return out

  call.jitted = compiled         # escape hatch for lower()/inspection
  call.calls = 0
  call.compiles = 0
  return call


#: every `_uncached_jit` program attribute a fused epoch driver (this
#: module, `loader.fused_tree`, `parallel.fused`) may hold — the scan
#: set of `driver_compile_count`
_COMPILED_ATTRS = ('_compiled', '_compiled_eval', '_compiled_collect',
                   '_compiled_train', '_compiled_eval_consume',
                   '_compiled_auc_consume')


def driver_compile_count(driver) -> int:
  """Total XLA compiles across a fused driver's `_uncached_jit`
  programs (the per-callable counters) — the epoch-driver twin of
  `serving.engine.ServingEngine.compile_count`.  Snapshot it before a
  steady-state window and compare after: a nonzero delta means an
  epoch shape escaped chunking/bucketing and silently paid a compile
  (the exact failure `max_steps_per_program` and the serving bucket
  ladder exist to prevent)."""
  return sum(getattr(driver, a).compiles for a in _COMPILED_ATTRS
             if getattr(driver, a, None) is not None
             and hasattr(getattr(driver, a), 'compiles'))


#: default steps per tiered-fused chunk when the auto budget does not
#: bind (override with GLT_FUSED_COLD_CHUNK)
DEFAULT_COLD_CHUNK = 8
#: auto chunk budget: bytes of stacked collect output per chunk the
#: host cold-service phase holds live (the stacked feature tensor
#: dominates)
COLD_CHUNK_BYTES = 1 << 30


def resolve_cold_chunk(per_step_bytes: int, total_steps: int) -> int:
  """Steps per tiered-fused chunk: ``GLT_FUSED_COLD_CHUNK`` wins;
  otherwise `DEFAULT_COLD_CHUNK` clamped so one chunk's stacked
  collect output stays under `COLD_CHUNK_BYTES`."""
  import os as _os
  env = _os.environ.get('GLT_FUSED_COLD_CHUNK')
  if env:
    try:
      return max(min(int(env), total_steps), 1)
    except ValueError:
      pass
  by_mem = max(COLD_CHUNK_BYTES // max(per_step_bytes, 1), 1)
  return max(min(DEFAULT_COLD_CHUNK, by_mem, total_steps), 1)


class _SnapshotHooks:
  """Chunk-boundary snapshot/resume for the fused epoch drivers (the
  `utils.checkpoint` DataPlaneState protocol, driver-shaped) — shared
  by the single-chip classes here and the mesh drivers in
  `parallel.fused`, so the save/restore contracts cannot drift.

  Also hosts `_init_fused_sampling`, the r19 Pallas fused-sampler
  resolution shared by the homo/link drivers (hetero stays on the
  XLA path).

  Lifecycle::

      snap = fused.attach_snapshots()        # GLT_SNAPSHOT_DIR, or
      fused.attach_snapshots(SnapshotManager(dir, every=2))
      state, stats = fused.run(state)        # saves at chunk seams
      # ... preemption; in a fresh process, same constructor args:
      fused.attach_snapshots(snap_dir_manager)
      state = fused.restore_from_snapshot(state)   # mid-epoch rewind
      state, stats = fused.run(state)              # finishes the epoch

  The snapshot payload holds (a) the DATA-PLANE state — epoch
  counter, batcher RNG (epoch-start capture: resume RE-DRAWS the
  interrupted epoch's permutation), cold-cache rings — (b) the epoch
  PROGRESS (next chunk offset + per-step losses/correct/valid
  accumulated so far), and (c) the TrainState as host copies.  Resume
  is byte-identical: same permutation, same ``fold_in(epoch_key,
  chunk_offset)`` key schedule, partial stats stitched back in front
  of the freshly computed remainder.
  """

  _snap = None
  _resume_progress = None
  _use_fused = False
  _win_e = 0

  def _init_fused_sampling(self, graph) -> None:
    """Resolve GLT_PALLAS_SAMPLE once per driver (the epoch programs
    compile once, so the dispatch is baked per driver — value-
    identical either way) and stage the O(E) window repack into the
    jit-argument dict so the kernel's DMA table rides the same
    no-closure discipline as the other big tables."""
    self._use_fused = fused_sample_enabled()
    self._win_e = 0
    self._dev['win2d'] = None
    if self._use_fused:
      win2d, e = prepare_window_table(graph.indices)
      self._dev['win2d'] = win2d
      self._win_e = int(e)

  def attach_snapshots(self, manager=None):
    """Attach a `SnapshotManager` (``None`` builds one from
    ``GLT_SNAPSHOT_DIR`` when set; returns the manager or None)."""
    if manager is None:
      from ..utils.checkpoint import (SnapshotManager,
                                      snapshot_dir_from_env)
      if snapshot_dir_from_env() is None:
        return None
      manager = SnapshotManager()
    self._snap = manager
    return manager

  # -- per-driver state hooks (overridden by the mesh drivers) ------------
  def data_plane_state(self) -> dict:
    st = {'epoch_idx': self._epoch_idx,
          'dispatch_idx': getattr(self, '_dispatch_idx', 0),
          'batcher': self._batcher.state_dict()}
    feat = getattr(self, '_feat', None)
    if feat is not None and getattr(self, '_tiered', False):
      st['feat'] = feat.state_dict()
    return st

  def load_data_plane_state(self, plane: dict) -> None:
    # run() pre-increments the epoch counter, so the rewound value is
    # "one before the interrupted epoch"; the batcher rewinds its RNG
    # to that epoch's start so run() re-draws the same permutation
    self._epoch_idx = int(np.asarray(plane['epoch_idx'])) - 1
    self._dispatch_idx = int(np.asarray(plane.get('dispatch_idx', 0)))
    self._batcher.load_state_dict(plane['batcher'], mid_epoch=True)
    feat = getattr(self, '_feat', None)
    if feat is not None and 'feat' in plane:
      feat.load_state_dict(plane['feat'])

  def _state_to_device(self, train_host):
    """Host TrainState pytree → device, driver-appropriately (the
    mesh drivers replicate over their mesh instead)."""
    return jax.tree_util.tree_map(jnp.asarray, train_host)

  def restore_from_snapshot(self, state_template):
    """Load the newest snapshot: rewind the data plane and return the
    TrainState to continue from (validated against
    ``state_template``'s structure/dtypes/shapes —
    `CheckpointMismatchError` on a stale snapshot).  ``None`` when the
    directory holds no snapshot; the caller keeps its fresh state."""
    if self._snap is None:
      raise ValueError('restore_from_snapshot() needs '
                       'attach_snapshots() first')
    payload = self._snap.restore_latest()
    if payload is None:
      return None
    from ..utils.checkpoint import validate_tree
    self.load_data_plane_state(payload['plane'])
    self._resume_progress = payload['progress']
    train = payload.get('train')
    if train is None:
      return None
    validate_tree(train,
                  jax.tree_util.tree_map(np.asarray, state_template))
    return self._state_to_device(train)

  # -- run()-side helpers -------------------------------------------------
  def _take_resume(self, chunk_steps: int):
    """Pop the pending resume progress (one epoch continuation per
    restore).  Returns ``(skip_before, losses_list, correct, valid,
    extra)`` — ``extra`` carries driver-specific partials (the mesh
    tree driver's hop counts)."""
    prog = self._resume_progress
    if prog is None:
      return 0, [], None, None, {}
    self._resume_progress = None
    saved_chunk = int(np.asarray(prog.get('chunk_steps', chunk_steps)))
    if saved_chunk != chunk_steps:
      from ..utils.checkpoint import CheckpointMismatchError
      raise CheckpointMismatchError(
          f'snapshot was taken with chunk size {saved_chunk}, this '
          f'process resolves {chunk_steps} — resume with the same '
          f'GLT_FUSED_COLD_CHUNK / max_steps_per_program',
          path='progress.chunk_steps')
    losses = np.asarray(prog['losses'])
    losses_list = [losses] if losses.size else []
    correct = prog.get('correct')
    valid = prog.get('valid')
    extra = {k: v for k, v in prog.items()
             if k not in ('losses', 'correct', 'valid', 'epoch',
                          'next_chunk', 'chunk_steps')}
    return (int(np.asarray(prog['next_chunk'])), losses_list, correct,
            valid, extra)

  def _save_chunk_snapshot(self, state, next_chunk: int,
                           chunk_steps: int, losses, correct, valid,
                           force: bool = False, extra_fn=None,
                           **extra) -> None:
    """One chunk-boundary save when due (``force`` bypasses the
    cadence — epoch-entry rollback targets and epoch-end saves).
    ``extra_fn`` defers expensive extras (a device sync) to the saves
    that actually happen."""
    if self._snap is None:
      return
    if not force and not self._snap.due():
      return
    if extra_fn is not None:
      extra = {**extra, **extra_fn()}
    progress = {
        'epoch': self._epoch_idx, 'next_chunk': int(next_chunk),
        'chunk_steps': int(chunk_steps),
        'losses': (np.concatenate([np.asarray(l) for l in losses])
                   if losses else np.zeros((0,), np.float32)),
    }
    if correct is not None:
      progress['correct'] = np.asarray(correct)
    if valid is not None:
      progress['valid'] = np.asarray(valid)
    for k, v in extra.items():
      if v is not None:
        progress[k] = np.asarray(v)
    self._snap.save(self.data_plane_state(), progress, train=state)


class EpochStats:
  """Lazy epoch statistics: holds DEVICE arrays; any numeric access
  syncs.  Epoch loops that don't read stats dispatch epochs back to
  back with zero host↔device round trips — on a tunneled chip each
  eager ``float()`` costs a full RTT, which measured SLOWER than the
  per-batch loop before this was made lazy."""

  def __init__(self, losses: jax.Array, correct: jax.Array,
               valid: jax.Array):
    self.losses = losses

    self._correct = correct
    self._valid = valid

  @property
  def loss(self) -> float:
    return float(self.losses.mean())

  @property
  def correct(self) -> int:
    return int(self._correct)

  @property
  def seeds(self) -> int:
    return int(self._valid)

  @property
  def accuracy(self) -> float:
    return self.correct / max(self.seeds, 1)

  def __getitem__(self, key: str):
    return getattr(self, key)

  def __repr__(self):
    return f'EpochStats(steps={self.losses.shape[0]}, <lazy>)'


class _SupervisedScanEpoch(_SnapshotHooks):
  """Shared epoch driver for the supervised fused twins: subclasses
  supply ``_sample_collate(seeds, key, dev, use_pallas) -> batch`` and
  ``_step(state, batch) -> (state, loss, correct)`` plus the
  ``_batcher`` / ``_base_key`` / ``_dev`` / ``_compiled`` state; this
  mixin owns the scan body and the host driver so the donation and
  stats contracts cannot drift between the homo and hetero paths."""

  def __len__(self) -> int:
    return len(self._batcher)

  def _epoch_fn(self, state: TrainState, seeds_all: jax.Array,
                key: jax.Array, dev: dict, use_pallas: bool):
    """``[S, B]`` seed batches → S fused sample+collate+train steps."""

    def body(state, xs):
      i, seeds = xs
      batch = self._sample_collate(seeds, jax.random.fold_in(key, i),
                                   dev, use_pallas)
      new_state, loss, correct = self._step(state, batch)
      # fully-padded steps (epoch-length chunking) must be state
      # no-ops: zero grads still move adam's moments/bias correction
      any_valid = jnp.any(seeds >= 0)
      state = jax.tree_util.tree_map(
          lambda new, old: jnp.where(any_valid, new, old),
          new_state, state)
      return state, (loss, correct, jnp.sum(seeds >= 0))

    steps = jnp.arange(seeds_all.shape[0], dtype=jnp.int32)
    state, (losses, corrects, valids) = jax.lax.scan(
        body, state, (steps, seeds_all))
    return state, losses, jnp.sum(corrects), jnp.sum(valids)

  def _chunks(self, seeds: np.ndarray):
    """Yield ``(chunk_offset, real_steps, [chunk, B] piece)``: the
    epoch split into fixed-size dispatches of ONE compiled program
    (VERDICT r4 #4 — every epoch length reuses one compile; the
    tail pads with INVALID_ID rows, which the scan body no-ops).
    Tiered epochs without an explicit ``max_steps_per_program`` get
    the auto cold-chunk bound (`resolve_cold_chunk`) — each chunk's
    stacked collect output must fit the host cold-service budget."""
    s = seeds.shape[0]
    chunk = getattr(self, '_chunk', None)
    if chunk is None and getattr(self, '_tiered', False):
      chunk = resolve_cold_chunk(self._collect_step_bytes(), s)
    chunk = chunk or s
    for c0 in range(0, s, chunk):
      part = seeds[c0:c0 + chunk]
      real = part.shape[0]
      if real < chunk:
        pad = np.full((chunk - real,) + seeds.shape[1:], -1,
                      seeds.dtype)
        part = np.concatenate([part, pad])
      yield c0, real, part

  def run(self, state: TrainState) -> Tuple[TrainState, 'EpochStats']:
    """Run one epoch; returns ``(state, stats)``.

    The input ``state`` is DONATED to the epoch program (its buffers
    are reused for the output state) — thread the returned state
    forward and don't touch the argument again, exactly as with a
    donated jitted train step.  ``stats`` is LAZY (`EpochStats`):
    reading ``.loss`` etc. syncs on the epoch; a loop that ignores it
    never blocks.  With ``max_steps_per_program`` set, per-chunk keys
    derive from (epoch, chunk offset): same draw distribution as the
    single-program epoch, different stream."""
    from ..telemetry.spans import span
    from ..testing import chaos
    seeds = np.stack(list(self._batcher))          # [S, B], host shuffle
    self._epoch_idx += 1
    key = jax.random.fold_in(self._base_key, self._epoch_idx)
    parts = list(self._chunks(seeds))
    chunk_steps = parts[0][2].shape[0] if parts else 0
    # mid-epoch resume (attach_snapshots/restore_from_snapshot):
    # chunks before `skip` already ran pre-preemption — their stats
    # come from the snapshot, the permutation and key schedule are
    # re-derived identically, and only the remainder dispatches
    skip, losses, correct, valid, _ = self._take_resume(chunk_steps)
    with span('fused.epoch', scope=type(self).__name__,
              epoch=self._epoch_idx, steps=seeds.shape[0],
              tiered=getattr(self, '_tiered', False)):
      for c0, real, part in parts:
        if c0 < skip:
          continue
        # single-program epochs keep the r4 key schedule exactly
        ck = key if len(parts) == 1 else jax.random.fold_in(key, c0)
        # chaos seam: a planned kill dies here, between chunk
        # dispatches — exactly what a preemption hits
        chaos.fused_dispatch_check(chunk=c0, epoch=self._epoch_idx)
        with span('fused.dispatch', chunk=c0):
          with step_annotation('fused_epoch', self._next_dispatch()):
            if getattr(self, '_tiered', False):
              state, ls, c, v = self._run_tiered_chunk(state, part, ck)
            else:
              state, ls, c, v = self._compiled(
                  state, jnp.asarray(part), ck, self._dev,
                  pallas_enabled())
        losses.append(ls[:real])
        correct = c if correct is None else correct + c
        valid = v if valid is None else valid + v
        self._save_chunk_snapshot(state, c0 + part.shape[0],
                                  chunk_steps, losses, correct, valid)
    metrics.inc('loader.batches', seeds.shape[0])
    return state, EpochStats(jnp.concatenate(losses), correct, valid)

  def _next_dispatch(self) -> int:
    """Monotone per-loader dispatch counter — the xprof step number of
    each fused program dispatch (one per chunk)."""
    self._dispatch_idx = getattr(self, '_dispatch_idx', 0) + 1
    return self._dispatch_idx

  def compile_count(self) -> int:
    """Total compiles across this driver's programs (see
    `driver_compile_count`)."""
    return driver_compile_count(self)

  # -- tiered fused epochs (cold-cache service between dispatches) ----------

  def _run_tiered_chunk(self, state, part: np.ndarray, ck):
    """One tiered chunk: compiled sample-only collect scan → host
    cold service (the Feature's cache-aware mixed lookup fills x) →
    compiled train scan.  Returns ``(state, losses, correct,
    valid)`` matching the untiered chunk program."""
    batches = self._compiled_collect(jnp.asarray(part), ck, self._dev)
    batches = self._fill_cold_x(batches)
    return self._compiled_train(state, batches)

  def _fill_cold_x(self, batches):
    """The between-dispatch cold service: per step, one cache-aware
    tiered Feature lookup (`data.feature.Feature.__getitem__` — cache
    hits device-served, misses host-gathered + admitted)."""
    from ..telemetry.spans import span
    nodes_h = np.asarray(batches.node)             # [c, cap], one sync
    with span('feature.cold_overlay', scope=type(self).__name__,
              steps=nodes_h.shape[0]):
      xs = [self._feat[nodes_h[i]] for i in range(nodes_h.shape[0])]
    batches.x = jnp.stack(xs)
    return batches

  def _collect_fn(self, seeds_all: jax.Array, key: jax.Array,
                  dev: dict):
    """Sample-only scan: the chunk's batches WITHOUT x (the cold
    service fills it between dispatches)."""

    def body(_, xs):
      i, seeds = xs
      return 0, self._collect_batch(seeds, jax.random.fold_in(key, i),
                                    dev)

    steps = jnp.arange(seeds_all.shape[0], dtype=jnp.int32)
    _, batches = jax.lax.scan(body, 0, (steps, seeds_all))
    return batches

  def _train_chunk_fn(self, state: TrainState, batches):
    def body(state, batch):
      new_state, loss, correct = self._step(state, batch)
      any_valid = jnp.any(batch.batch >= 0)
      state = jax.tree_util.tree_map(
          lambda new, old: jnp.where(any_valid, new, old),
          new_state, state)
      return state, (loss, correct, jnp.sum(batch.batch >= 0))

    state, (losses, corrects, valids) = jax.lax.scan(
        body, state, batches)
    return state, losses, jnp.sum(corrects), jnp.sum(valids)

  def _eval_consume_fn(self, params, batches):
    def body(carry, batch):
      correct, total = self._eval_step(params, batch)
      return carry, (correct, total)

    _, (c, t) = jax.lax.scan(body, 0, batches)
    return jnp.sum(c), jnp.sum(t)

  def _eval_fn(self, params, seeds_all: jax.Array, key: jax.Array,
               dev: dict, use_pallas: bool):
    """Scan twin of a `make_eval_step` loop over ``[S, B]`` seeds —
    accuracy on the seed slots via the subclass's ``_eval_step``."""

    def body(carry, xs):
      i, seeds = xs
      batch = self._sample_collate(seeds, jax.random.fold_in(key, i),
                                   dev, use_pallas)
      correct, total = self._eval_step(params, batch)
      return carry, (correct, total)

    steps = jnp.arange(seeds_all.shape[0], dtype=jnp.int32)
    _, (correct, total) = jax.lax.scan(body, 0, (steps, seeds_all))
    return jnp.sum(correct), jnp.sum(total)

  def evaluate(self, params, input_nodes) -> float:
    """Accuracy over ``input_nodes`` (e.g. the test split) as one scan
    program — the fused counterpart of a `make_eval_step` loop."""
    ids = np.asarray(input_nodes)
    if ids.dtype == np.bool_:
      ids = np.nonzero(ids)[0]
    if ids.size == 0:
      raise ValueError('evaluate() got an empty split')
    ev = SeedBatcher(ids, self.batch_size, shuffle=False)
    seeds = np.stack(list(ev))
    # eval keys live in their own fold DOMAIN (base -> 0 -> 1); train
    # keys are base -> epoch with epoch >= 1, so no epoch-counter
    # value (wraparound included) can alias a train sampling key
    key = jax.random.fold_in(jax.random.fold_in(self._base_key, 0), 1)
    parts = list(self._chunks(seeds))
    correct = total = 0
    for c0, _real, part in parts:
      ck = key if len(parts) == 1 else jax.random.fold_in(key, c0)
      if getattr(self, '_tiered', False):
        batches = self._compiled_collect(jnp.asarray(part), ck,
                                         self._dev)
        batches = self._fill_cold_x(batches)
        c, t = self._compiled_eval_consume(params, batches)
      else:
        c, t = self._compiled_eval(params, jnp.asarray(part), ck,
                                   self._dev, pallas_enabled())
      correct += int(c)
      total += int(t)
    return correct / max(total, 1)


class FusedEpoch(_SupervisedScanEpoch):
  """One-program supervised training epochs over neighbor sampling.

  Example::

      fused = FusedEpoch(dataset, [15, 10, 5], train_idx, apply_fn, tx,
                         batch_size=1024, shuffle=True, seed=0)
      for epoch in range(10):
        state, stats = fused.run(state)
        print(stats['loss'], stats['accuracy'])

  Args:
    data: `Dataset` with a homogeneous graph, fully device-resident
      features (``split_ratio == 1.0``) and integer labels.
    num_neighbors: per-hop fanouts.
    input_nodes: seed ids (or boolean mask) — e.g. the train split.
    apply_fn / tx: model apply function and optax transformation, the
      same pair `make_supervised_step` takes.
    batch_size / shuffle / drop_last / seed: epoch iteration controls
      (`SeedBatcher` semantics — the tail batch is INVALID_ID-padded).
    sort_locality: forwarded to the sampler's hop kernel.
    remat: rematerialize the model forward in the backward pass
      (`jax.checkpoint`).  The fused program holds the sampler's
      buffers AND the training activations live together; at large
      ``batch_size x fanout`` products that joint peak can exceed HBM
      where the separate per-batch programs fit — remat trades the
      recompute FLOPs for that headroom.
    max_steps_per_program: run each epoch as ceil(S/chunk) dispatches
      of ONE compiled ``[chunk, B]`` program instead of one
      ``[S, B]`` program per epoch length (VERDICT r4 #4: a changed
      epoch length reused nothing and recompiled ~70 s).  Tail steps
      pad with INVALID_ID and are state no-ops.  Also keeps each
      dispatch under the tunneled chip's ~70 s execution watchdog.
  """

  def __init__(self, data: Dataset, num_neighbors: Sequence[int],
               input_nodes, apply_fn: Callable,
               tx: optax.GradientTransformation, batch_size: int,
               shuffle: bool = True, drop_last: bool = False,
               seed: Optional[int] = None, sort_locality: bool = True,
               remat: bool = False,
               max_steps_per_program: Optional[int] = None):
    if data.is_hetero:
      raise ValueError('FusedEpoch is homogeneous-only; use the '
                       'per-batch NeighborLoader for hetero graphs')
    self._chunk = (int(max_steps_per_program)
                   if max_steps_per_program else None)
    feat = data.node_features
    if feat is None:
      raise ValueError('FusedEpoch needs node features')
    # tiered Feature (split_ratio < 1): the epoch runs as a tiered
    # fused epoch — sample-only collect scans, the cache-aware cold
    # service between dispatches, train scans (module docstring)
    self._tiered = feat.hot_rows < feat.size(0)
    self._feat = feat
    labels = data.get_node_label_device()
    if labels is None:
      raise ValueError('FusedEpoch needs node labels')

    self.data = data
    self.batch_size = int(batch_size)
    self.fanouts = tuple(int(k) for k in num_neighbors)
    self.sort_locality = bool(sort_locality)

    graph = data.get_graph()
    # The big tables go through the jit boundary as ARGUMENTS, never
    # closures: a closed-over device array becomes a jaxpr CONSTANT
    # bundled with the program — on a tunneled chip the ~1 GB feature
    # table made the fused compile take >20 minutes; as parameters the
    # already-resident buffers are just referenced.
    self._dev = dict(indptr=graph.indptr, indices=graph.indices,
                     hot=None if self._tiered else feat.hot_tier,
                     id2index=(None if self._tiered
                               else feat._id2index_dev),
                     labels=labels)
    self._init_fused_sampling(graph)

    # identical capacity arithmetic to the per-batch sampler, so fused
    # and per-batch programs see the same static shapes
    ref = NeighborSampler(graph, self.fanouts, seed=0)
    self._node_cap = ref.node_capacity(self.batch_size)

    input_nodes = np.asarray(input_nodes)
    if input_nodes.dtype == np.bool_:
      input_nodes = np.nonzero(input_nodes)[0]
    self._batcher = SeedBatcher(input_nodes, self.batch_size, shuffle,
                                drop_last, seed)
    self._base_key = jax.random.key(seed or 0)
    self._epoch_idx = 0
    step_apply = jax.checkpoint(apply_fn) if remat else apply_fn
    # ONE extract per apply variant pins the train and eval paths to
    # the same batch-field contract
    self._step = make_extracted_supervised_step(
        self._extract_with(step_apply), tx, self.batch_size)
    self._eval_step = make_extracted_eval_step(
        self._extract_with(apply_fn), self.batch_size)
    # only chunk-bounded programs may opt into the persistent
    # compilation cache (see `_uncached_jit`)
    cacheable = self._chunk is not None
    self._compiled = _uncached_jit(self._epoch_fn, donate_argnums=(0,),
                             static_argnums=(4,), cacheable=cacheable)
    self._compiled_eval = _uncached_jit(self._eval_fn,
                                        static_argnums=(4,),
                                        cacheable=cacheable)
    if self._tiered:
      self._compiled_collect = _uncached_jit(self._collect_fn,
                                             cacheable=cacheable)
      self._compiled_train = _uncached_jit(self._train_chunk_fn,
                                           donate_argnums=(0,),
                                           cacheable=cacheable)
      self._compiled_eval_consume = _uncached_jit(self._eval_consume_fn,
                                                  cacheable=cacheable)

  def _collect_step_bytes(self) -> int:
    return (self._node_cap * self._feat.feature_dim
            * np.dtype(self._feat.dtype).itemsize)

  def _collect_batch(self, seeds: jax.Array, key: jax.Array,
                     dev: dict) -> Batch:
    """Sample-only scan-body front half for tiered stores: everything
    `_sample_collate` produces EXCEPT x (the cold service fills it
    from the cache-aware Feature between dispatches)."""
    (nodes, _count, row, col, _edge, emask, seed_local, _nsn,
     _nse) = _multihop_sample(
         dev['indptr'], dev['indices'], None, seeds, key, dev['win2d'],
         fanouts=self.fanouts, node_cap=self._node_cap,
         with_edge=False, sort_locality=self.sort_locality,
         use_fused=self._use_fused, win_e=self._win_e)
    return Batch(
        x=None,
        y=_gather_labels(dev['labels'], nodes),
        edge_index=jnp.stack([row, col]),
        node=nodes, node_mask=nodes >= 0, edge_mask=emask,
        batch=seeds, batch_size=self.batch_size,
        metadata={'seed_local': seed_local})

  @staticmethod
  def _extract_with(apply):
    def extract(params, batch):
      logits = apply(params, batch.x, batch.edge_index, batch.edge_mask)
      return logits, batch.y, batch.batch
    return extract

  # __len__ / _epoch_fn / run come from _SupervisedScanEpoch

  def _sample_collate(self, seeds: jax.Array, key: jax.Array,
                      dev: dict, use_pallas: bool) -> Batch:
    """The shared scan-body front half: one fused multi-hop sample +
    all-device collation (same programs as the per-batch path).
    ``use_pallas`` comes from the host driver so the GLT_PALLAS
    kill-switch keeps working between epochs (the per-batch contract,
    `data/feature.py:39-40`)."""
    (nodes, _count, row, col, _edge, emask, seed_local, _nsn,
     _nse) = _multihop_sample(
         dev['indptr'], dev['indices'], None, seeds, key, dev['win2d'],
         fanouts=self.fanouts, node_cap=self._node_cap,
         with_edge=False, sort_locality=self.sort_locality,
         use_fused=self._use_fused, win_e=self._win_e)
    return Batch(
        x=_device_gather(dev['hot'], nodes, dev['id2index'],
                         use_pallas=use_pallas),
        y=_gather_labels(dev['labels'], nodes),
        edge_index=jnp.stack([row, col]),
        node=nodes, node_mask=nodes >= 0, edge_mask=emask,
        batch=seeds, batch_size=self.batch_size,
        metadata={'seed_local': seed_local})

class FusedHeteroEpoch(_SupervisedScanEpoch):
  """One-program supervised training epochs on a HETERO graph.

  The hetero twin of `FusedEpoch`: the scan body runs the fused
  per-type multi-hop program (`sampler.hetero_neighbor_sampler.
  _hetero_multihop` — the same program the per-batch
  `HeteroNeighborSampler` dispatches), collates per-type feature
  dicts on device, and applies a supervised step whose loss lives on
  the seed type's slots — the objective of the reference's HGT / RGNN
  examples (`examples/hetero/train_hgt_mag.py:90-130`,
  `examples/igbh/train_rgnn.py`).

  ``apply_fn(params, x_dict, edge_index_dict, edge_mask_dict)`` must
  return the TARGET type's logits (the `HGT`/`RGCN`/`HeteroConv`
  model contract).

  Args:
    data: hetero `Dataset`; every node type's features fully
      device-resident, labels present for the seed type.
    num_neighbors: per-hop fanouts (list or ``{EdgeType: list}``).
    input_nodes: ``(node_type, ids)`` seed spec.
    apply_fn / tx: model apply + optax transform.
    batch_size / shuffle / drop_last / seed: epoch controls.
    remat: checkpoint the model forward (see `FusedEpoch`).
  """

  def __init__(self, data: Dataset, num_neighbors, input_nodes,
               apply_fn: Callable, tx: optax.GradientTransformation,
               batch_size: int, shuffle: bool = True,
               drop_last: bool = False, seed: Optional[int] = None,
               sort_locality: bool = True, remat: bool = False,
               max_steps_per_program: Optional[int] = None):
    self._chunk = (int(max_steps_per_program)
                   if max_steps_per_program else None)
    from ..sampler.hetero_neighbor_sampler import (HeteroNeighborSampler,
                                                   _plan_capacities)
    if not data.is_hetero:
      raise ValueError('FusedHeteroEpoch needs a hetero Dataset; use '
                       'FusedEpoch for homogeneous graphs')
    if (not isinstance(input_nodes, tuple)
        or not isinstance(input_nodes[0], str)):
      raise ValueError('input_nodes must be (node_type, ids)')
    self.input_type, ids = input_nodes
    feats = data.node_features
    if not isinstance(feats, dict) or not feats:
      raise ValueError('FusedHeteroEpoch needs per-type node features')
    for nt, f in feats.items():
      if f.hot_rows < f.size(0):
        raise ValueError(
            f'feature table for {nt!r} keeps rows on host; '
            f'FusedHeteroEpoch needs split_ratio == 1.0 everywhere '
            f'(use NeighborLoader(prefetch=2) for tiered tables)')
    labels = data.get_node_label_device(self.input_type)
    if labels is None:
      raise ValueError(
          f'FusedHeteroEpoch needs labels for {self.input_type!r}')

    self.data = data
    self.batch_size = int(batch_size)
    self.sort_locality = bool(sort_locality)

    graphs = {et: data.get_graph(et) for et in data.get_edge_types()}
    # reuse the per-batch sampler's planning so fused and per-batch
    # programs share static shapes and the same _hetero_multihop
    ref = HeteroNeighborSampler(graphs, num_neighbors,
                                num_nodes=data.num_nodes_dict(), seed=0,
                                sort_locality=sort_locality)
    self._etypes = ref.etypes
    self._fanouts_t = tuple(ref.fanouts[et] for et in ref.etypes)
    self._num_hops = ref.num_hops
    ntypes, table_cap, frontier_caps, _ = _plan_capacities(
        ref.etypes, ref.fanouts, {self.input_type: self.batch_size},
        ref.num_hops, ref._num_nodes)
    self._table_caps = tuple(sorted(table_cap.items()))
    self._frontier_caps_t = tuple(
        tuple(sorted(fc.items())) for fc in frontier_caps)

    # big tables as jit arguments, not closures (see FusedEpoch note)
    self._dev = dict(
        graphs={et: (g.indptr, g.indices, None)
                for et, g in graphs.items()},
        hot={nt: f.hot_tier for nt, f in feats.items()},
        id2index={nt: f._id2index_dev for nt, f in feats.items()},
        labels=labels)

    ids = np.asarray(ids)
    if ids.dtype == np.bool_:
      ids = np.nonzero(ids)[0]
    self._batcher = SeedBatcher(ids, self.batch_size, shuffle,
                                drop_last, seed)
    self._base_key = jax.random.key(seed or 0)
    self._epoch_idx = 0
    step_apply = jax.checkpoint(apply_fn) if remat else apply_fn
    self._step = make_extracted_supervised_step(
        self._extract_with(step_apply), tx, self.batch_size)
    self._eval_step = make_extracted_eval_step(
        self._extract_with(apply_fn), self.batch_size)
    cacheable = self._chunk is not None
    self._compiled = _uncached_jit(self._epoch_fn, donate_argnums=(0,),
                             static_argnums=(4,), cacheable=cacheable)
    self._compiled_eval = _uncached_jit(self._eval_fn,
                                        static_argnums=(4,),
                                        cacheable=cacheable)

  def _extract_with(self, apply):
    it = self.input_type

    def extract(params, batch):
      logits = apply(params, batch.x_dict, batch.edge_index_dict,
                     batch.edge_mask_dict)
      return logits, batch.y_dict[it], batch.batch_dict[it]

    return extract

  def _sample_collate(self, seeds: jax.Array, key: jax.Array,
                      dev: dict, use_pallas: bool):
    from ..sampler.hetero_neighbor_sampler import _hetero_multihop
    from .transform import HeteroBatch
    (node, _cnt, row, col, _eid, emask, seed_locals, _nsn) = \
        _hetero_multihop(
            dev['graphs'], (seeds,), key,
            etypes=self._etypes, fanouts_t=self._fanouts_t,
            seed_types=(self.input_type,), num_hops=self._num_hops,
            table_caps=self._table_caps,
            frontier_caps_t=self._frontier_caps_t,
            with_edge=False, sort_locality=self.sort_locality)
    x_dict = {nt: _device_gather(dev['hot'][nt], ids,
                                 dev['id2index'][nt],
                                 use_pallas=use_pallas)
              for nt, ids in node.items() if nt in dev['hot']}
    y = _gather_labels(dev['labels'], node[self.input_type])
    ei_dict = {et: jnp.stack([row[et], col[et]]) for et in row}
    return HeteroBatch(
        x_dict=x_dict, y_dict={self.input_type: y},
        edge_index_dict=ei_dict,
        edge_attr_dict={},
        node_dict=dict(node),
        node_mask_dict={nt: ids >= 0 for nt, ids in node.items()},
        edge_mask_dict=dict(emask),
        batch_dict={self.input_type: seeds},
        batch_size=self.batch_size,
        metadata={'seed_local': seed_locals[self.input_type]})


def _as_edge_pairs(edge_label_index):
  """Normalize ``(rows, cols)`` / ``[2, E]`` seed-edge forms — one
  definition for `FusedLinkEpoch.__init__` and its `evaluate`."""
  if isinstance(edge_label_index, (tuple, list)):
    rows, cols = edge_label_index
    return rows, cols
  ei = np.asarray(edge_label_index)
  return ei[0], ei[1]


class FusedLinkEpoch(_SnapshotHooks):
  """One-program link-prediction (unsupervised) training epochs.

  The link twin of `FusedEpoch`, fusing the `LinkNeighborLoader` +
  unsupervised-step loop: the scan body draws negatives, expands
  multi-hop neighborhoods around the positive + negative endpoints,
  collates, and applies the binary (sigmoid) or triplet (max-margin)
  link loss — the objective of the reference's unsupervised SAGE
  (`examples/graph_sage_unsup_ppi.py:41-45`).

  The seed/negative/metadata assembly mirrors
  `sampler.neighbor_sampler.NeighborSampler.sample_from_edges`
  (binary: `neighbor_sampler.py:255-282`, triplet: `:284-300`) in
  functional form (keys passed in, not held); the parity test pins
  the two paths together.

  Args:
    data: `Dataset` with fully device-resident features (labels
      optional — link training is label-free unless ``edge_label``).
    num_neighbors: per-hop fanouts.
    edge_label_index: ``[2, E]`` (or ``(rows, cols)``) seed edges.
    apply_fn / tx: model apply fn (emits embeddings) + optax transform.
    batch_size: seed-EDGE batch size.
    neg_sampling: `NegativeSampling` spec or mode string
      (default binary, amount 1).
    edge_label: optional ``[E]`` positive labels (binary mode gets the
      reference's +1 shift: 0 = sampled negative).
    remat: checkpoint the model forward — same merged-program HBM
      hazard as `FusedEpoch` (and the link seed width is LARGER:
      ``2B + negatives`` endpoints per batch).
  """

  def __init__(self, data: Dataset, num_neighbors, edge_label_index,
               apply_fn: Callable, tx: optax.GradientTransformation,
               batch_size: int, neg_sampling='binary', edge_label=None,
               shuffle: bool = True, drop_last: bool = False,
               seed: Optional[int] = None, sort_locality: bool = True,
               remat: bool = False,
               max_steps_per_program: Optional[int] = None):
    if data.is_hetero:
      raise ValueError('FusedLinkEpoch is homogeneous-only')
    self._chunk = (int(max_steps_per_program)
                   if max_steps_per_program else None)
    feat = data.node_features
    if feat is None:
      raise ValueError('FusedLinkEpoch needs node features')
    # tiered Feature: tiered fused epochs (see FusedEpoch)
    self._tiered = feat.hot_rows < feat.size(0)
    self._feat = feat
    self.data = data
    self.batch_size = int(batch_size)
    self.fanouts = tuple(int(k) for k in num_neighbors)
    self.sort_locality = bool(sort_locality)
    self.neg = NegativeSampling.cast(neg_sampling)

    graph = data.get_graph()
    self._num_nodes = graph.num_nodes
    # big tables as jit arguments, not closures (see FusedEpoch note)
    self._dev = dict(indptr=graph.indptr, indices=graph.indices,
                     hot=None if self._tiered else feat.hot_tier,
                     id2index=(None if self._tiered
                               else feat._id2index_dev),
                     labels=data.get_node_label_device())
    self._init_fused_sampling(graph)

    rows, cols = _as_edge_pairs(edge_label_index)
    self._batcher = EdgeSeedBatcher(rows, cols, edge_label,
                                    self.batch_size, shuffle, drop_last,
                                    seed)

    b = self.batch_size
    if self.neg.is_binary():
      self._num_neg = self.neg.sample_size(b)
      seed_width = 2 * b + 2 * self._num_neg
    else:
      self._amount = int(np.ceil(float(self.neg.amount)))
      self._num_neg = b * self._amount
      seed_width = 2 * b + self._num_neg
    ref = NeighborSampler(graph, self.fanouts, seed=0)
    self._node_cap = ref.node_capacity(seed_width)

    self._base_key = jax.random.key(seed or 0)
    self._epoch_idx = 0
    from ..models.train import make_unsupervised_step
    step_apply = jax.checkpoint(apply_fn) if remat else apply_fn
    self._apply = apply_fn            # un-remat'd: evaluate() is fwd-only
    self._step = make_unsupervised_step(step_apply, tx)
    cacheable = self._chunk is not None
    self._compiled = _uncached_jit(self._epoch_fn, donate_argnums=(0,),
                             static_argnums=(6,), cacheable=cacheable)
    self._compiled_eval = _uncached_jit(self._auc_fn,
                                        static_argnums=(5,),
                                        cacheable=cacheable)
    if self._tiered:
      self._compiled_collect = _uncached_jit(self._link_collect_fn,
                                             cacheable=cacheable)
      self._compiled_train = _uncached_jit(self._link_train_fn,
                                           donate_argnums=(0,),
                                           cacheable=cacheable)
      self._compiled_auc_consume = _uncached_jit(self._auc_consume_fn,
                                                 cacheable=cacheable)

  def __len__(self) -> int:
    return len(self._batcher)

  # -- tiered fused epochs (see FusedEpoch): the cold-service and
  # chunk-budget helpers are shared with the supervised twins via
  # `_SupervisedScanEpoch` — one body, so a fix cannot miss a twin
  _collect_step_bytes = FusedEpoch._collect_step_bytes
  _fill_cold_x = _SupervisedScanEpoch._fill_cold_x
  compile_count = _SupervisedScanEpoch.compile_count

  def _link_collect_fn(self, srcs: jax.Array, dsts: jax.Array,
                       labs: jax.Array, key: jax.Array, dev: dict):
    """Sample-only link scan (negatives + expansion + metadata, no
    feature gather) for one chunk."""

    def body(_, xs):
      i, src, dst, lab = xs
      return 0, self._link_batch(src, dst, lab,
                                 jax.random.fold_in(key, i), dev,
                                 False, collect_x=False)

    steps = jnp.arange(srcs.shape[0], dtype=jnp.int32)
    _, batches = jax.lax.scan(body, 0, (steps, srcs, dsts, labs))
    return batches

  def _link_train_fn(self, state: TrainState, batches,
                     srcs: jax.Array, dsts: jax.Array):
    def body(state, xs):
      batch, src, dst = xs
      new_state, loss = self._step(state, batch)
      any_valid = jnp.any((src >= 0) & (dst >= 0))
      state = jax.tree_util.tree_map(
          lambda new, old: jnp.where(any_valid, new, old),
          new_state, state)
      return state, (loss, jnp.sum((src >= 0) & (dst >= 0)))

    state, (losses, valids) = jax.lax.scan(body, state,
                                           (batches, srcs, dsts))
    return state, losses, jnp.sum(valids)

  def _auc_consume_fn(self, params, batches):
    def body(carry, batch):
      return carry, self._auc_score(params, batch)

    _, (wins, totals) = jax.lax.scan(body, 0, batches)
    return jnp.sum(wins), jnp.sum(totals)

  def _auc_score(self, params, batch):
    """Embed one batch and accumulate the pairwise (pos > neg) win
    counts — the batched rank-sum AUC body, shared by the
    single-program `_auc_fn` and the tiered `_auc_consume_fn`."""
    b = self.batch_size
    emb = self._apply(params, batch.x, batch.edge_index,
                      batch.edge_mask)
    eli = batch.metadata['edge_label_index']        # [2, b + nn]
    mask = batch.metadata['edge_label_mask']
    score = (emb[eli[0]] * emb[eli[1]]).sum(-1)
    # binary layout is static: first b slots positive, rest negative
    ps, ns = score[:b], score[b:]
    pv, nv = mask[:b], mask[b:]
    pair_ok = pv[:, None] & nv[None, :]
    # float32 accumulation: int32 pair counts overflow past ~2k
    # products-scale batches (b * nn pairs each)
    wins = (jnp.sum((ps[:, None] > ns[None, :]) & pair_ok,
                    dtype=jnp.float32)
            + 0.5 * jnp.sum((ps[:, None] == ns[None, :]) & pair_ok,
                            dtype=jnp.float32))
    return wins, jnp.sum(pair_ok, dtype=jnp.float32)

  def _auc_fn(self, params, srcs: jax.Array, dsts: jax.Array,
              key: jax.Array, dev: dict, use_pallas: bool):
    """Scan body of `evaluate`: per batch, draw strict negatives,
    expand + embed, score endpoint pairs, and accumulate the
    pairwise (pos > neg) win counts — the batched rank-sum AUC."""

    def body(carry, xs):
      i, src, dst = xs
      batch = self._link_batch(src, dst, None,
                               jax.random.fold_in(key, i), dev,
                               use_pallas)
      return carry, self._auc_score(params, batch)

    steps = jnp.arange(srcs.shape[0], dtype=jnp.int32)
    _, (wins, totals) = jax.lax.scan(body, 0, (steps, srcs, dsts))
    return jnp.sum(wins), jnp.sum(totals)

  def evaluate(self, params, edge_label_index, seed: int = 0) -> float:
    """Held-out link AUC over ``edge_label_index`` as ONE scan
    program — the fused counterpart of the reference's unsupervised
    eval loop (score held-out positives against freshly drawn strict
    negatives; `examples/graph_sage_unsup_ppi.py` computes the same
    ranking metric on host).  Scores are embedding dot products (the
    binary link objective's logit); the batched rank-sum estimator
    averages all pos x neg comparisons per batch.  Binary mode only
    (triplet mode's per-src negatives make precision@rank the right
    metric instead)."""
    if not self.neg.is_binary():
      raise ValueError('evaluate() needs binary negative sampling')
    rows, cols = _as_edge_pairs(edge_label_index)
    if len(np.asarray(rows)) == 0:
      raise ValueError('evaluate() got an empty split')
    ev = EdgeSeedBatcher(rows, cols, None, self.batch_size,
                         shuffle=False)
    srcs, dsts = [], []
    for r, c, _ in ev:
      srcs.append(r)
      dsts.append(c)
    # eval fold domain disjoint from train epochs (see
    # _SupervisedScanEpoch.evaluate)
    key = jax.random.fold_in(jax.random.fold_in(self._base_key, 0),
                             1 + seed)
    srcs, dsts = np.stack(srcs), np.stack(dsts)
    if self._tiered:
      s = srcs.shape[0]
      chunk = self._chunk or resolve_cold_chunk(
          self._collect_step_bytes(), s)
      wins = total = 0.0
      for c0 in range(0, s, chunk):
        sp = jnp.asarray(srcs[c0:c0 + chunk])
        dp = jnp.asarray(dsts[c0:c0 + chunk])
        ck = (key if s <= chunk else jax.random.fold_in(key, c0))
        batches = self._compiled_collect(sp, dp, jnp.ones_like(sp),
                                         ck, self._dev)
        batches = self._fill_cold_x(batches)
        w, t = self._compiled_auc_consume(params, batches)
        wins += float(w)
        total += float(t)
      return wins / max(total, 1.0)
    wins, total = self._compiled_eval(
        params, jnp.asarray(srcs), jnp.asarray(dsts),
        key, self._dev, pallas_enabled())
    return float(wins) / max(float(total), 1.0)

  def _link_batch(self, src: jax.Array, dst: jax.Array,
                  label: Optional[jax.Array], key: jax.Array,
                  dev: dict, use_pallas: bool,
                  collect_x: bool = True) -> Batch:
    """Functional seeds+negatives+metadata assembly (see class doc).
    ``collect_x=False`` skips the feature gather (tiered collect scans
    — the cold service fills x between dispatches)."""
    b = self.batch_size
    pair_valid = (src >= 0) & (dst >= 0)
    k_neg = jax.random.fold_in(key, 0)
    k_hop = jax.random.fold_in(key, 1)
    pos_label = (label if label is not None
                 else jnp.ones((b,), jnp.int32))

    if self.neg.is_binary():
      nn = self._num_neg
      nres = sample_negative(dev['indptr'], dev['indices'], nn, k_neg,
                             strict=True, padding=True)
      seeds = jnp.concatenate([src, dst, nres.rows, nres.cols])
      sl, out = self._expand(seeds, k_hop, dev)
      metadata = {
          'edge_label_index': jnp.stack([
              jnp.concatenate([sl[:b], sl[2 * b:2 * b + nn]]),
              jnp.concatenate([sl[b:2 * b], sl[2 * b + nn:]])]),
          'edge_label': jnp.concatenate(
              [pos_label, jnp.zeros((nn,), pos_label.dtype)]),
          'edge_label_mask': jnp.concatenate(
              [pair_valid, jnp.ones((nn,), jnp.bool_)]),
          'seed_local': sl,
      }
    else:
      amount = self._amount
      neg_dst = _triplet_neg_dst(dev['indptr'], dev['indices'], src,
                                 k_neg, amount=amount,
                                 num_nodes=self._num_nodes)
      seeds = jnp.concatenate([src, dst, neg_dst.reshape(-1)])
      sl, out = self._expand(seeds, k_hop, dev)
      metadata = {
          'src_index': sl[:b],
          'dst_pos_index': sl[b:2 * b],
          'dst_neg_index': sl[2 * b:].reshape(b, amount),
          'pair_mask': pair_valid,
          'seed_local': sl,
      }
    nodes, row, col, emask = out
    return Batch(
        x=(_device_gather(dev['hot'], nodes, dev['id2index'],
                          use_pallas=use_pallas) if collect_x
           else None),
        y=(_gather_labels(dev['labels'], nodes)
           if dev['labels'] is not None else None),
        edge_index=jnp.stack([row, col]),
        node=nodes, node_mask=nodes >= 0, edge_mask=emask,
        batch=seeds, batch_size=self.batch_size, metadata=metadata)

  def _expand(self, seeds: jax.Array, key: jax.Array, dev: dict):
    (nodes, _count, row, col, _edge, emask, seed_local, _nsn,
     _nse) = _multihop_sample(
         dev['indptr'], dev['indices'], None, seeds, key, dev['win2d'],
         fanouts=self.fanouts, node_cap=self._node_cap,
         with_edge=False, sort_locality=self.sort_locality,
         use_fused=self._use_fused, win_e=self._win_e)
    return seed_local, (nodes, row, col, emask)

  def _epoch_fn(self, state: TrainState, srcs: jax.Array,
                dsts: jax.Array, labels: Optional[jax.Array],
                key: jax.Array, dev: dict, use_pallas: bool):
    def body(state, xs):
      i, src, dst, lab = xs
      batch = self._link_batch(src, dst, lab,
                               jax.random.fold_in(key, i), dev,
                               use_pallas)
      new_state, loss = self._step(state, batch)
      # padded chunk-tail steps are state no-ops (see FusedEpoch)
      any_valid = jnp.any((src >= 0) & (dst >= 0))
      state = jax.tree_util.tree_map(
          lambda new, old: jnp.where(any_valid, new, old),
          new_state, state)
      return state, (loss, jnp.sum((src >= 0) & (dst >= 0)))

    steps = jnp.arange(srcs.shape[0], dtype=jnp.int32)
    labs = (labels if labels is not None
            else jnp.ones_like(srcs))             # constant positive label
    state, (losses, valids) = jax.lax.scan(
        body, state, (steps, srcs, dsts, labs))
    return state, losses, jnp.sum(valids)

  def run(self, state: TrainState) -> Tuple[TrainState, 'EpochStats']:
    """One epoch; ``state`` is DONATED (thread the returned one).
    ``stats.seeds`` counts valid seed EDGES; accuracy is meaningless
    for the unsupervised objective and reads 0."""
    srcs, dsts, labs = [], [], []
    for r, c, lab in self._batcher:
      srcs.append(r)
      dsts.append(c)
      if lab is not None:
        # reference +1 shift (loader/link_loader.py:146-186): user
        # labels move up so 0 means "sampled negative"; only VALID
        # pair slots shift — the batcher zero-pads the tail, and a
        # padded slot must not read as a phantom positive to metadata
        # consumers that skip edge_label_mask
        labs.append(np.where((r >= 0) & (c >= 0), lab + 1, 0)
                    if self.neg.is_binary() else lab)
    srcs = np.stack(srcs)
    dsts = np.stack(dsts)
    labels = np.stack(labs).astype(np.int32) if labs else None
    self._epoch_idx += 1
    key = jax.random.fold_in(self._base_key, self._epoch_idx)
    s = srcs.shape[0]
    chunk = self._chunk or s
    losses, valid = [], None

    def piece(a, c0, fill=-1):
      part = a[c0:c0 + chunk]
      if part.shape[0] < chunk:
        part = np.concatenate([
            part, np.full((chunk - part.shape[0], a.shape[1]), fill,
                          a.dtype)])
      return jnp.asarray(part)

    if self._tiered and self._chunk is None:
      chunk = resolve_cold_chunk(self._collect_step_bytes(), s)
    n_chunks = (s + chunk - 1) // chunk
    from ..testing import chaos
    # mid-epoch resume: see _SupervisedScanEpoch.run (same contract,
    # link stats carry valid-pair counts instead of correct)
    skip, losses, _corr, valid, _ = self._take_resume(chunk)
    for c0 in range(0, s, chunk):
      if c0 < skip:
        continue
      real = min(chunk, s - c0)
      ck = key if n_chunks == 1 else jax.random.fold_in(key, c0)
      chaos.fused_dispatch_check(chunk=c0, epoch=self._epoch_idx)
      self._dispatch_idx = getattr(self, '_dispatch_idx', 0) + 1
      with step_annotation('fused_link_epoch', self._dispatch_idx):
        # chunk-tail label padding uses the established invalid
        # sentinel 0 ("sampled negative"/masked), NOT -1: a -1
        # label reaching a metadata consumer that skips
        # edge_label_mask would index class tables out of range
        lab_piece = (piece(labels, c0, fill=0)
                     if labels is not None else None)
        if self._tiered:
          sp, dp = piece(srcs, c0), piece(dsts, c0)
          batches = self._compiled_collect(
              sp, dp, lab_piece if lab_piece is not None
              else jnp.ones_like(sp), ck, self._dev)
          batches = self._fill_cold_x(batches)
          state, ls, v = self._compiled_train(state, batches, sp, dp)
        else:
          state, ls, v = self._compiled(
              state, piece(srcs, c0), piece(dsts, c0), lab_piece,
              ck, self._dev, pallas_enabled())
      losses.append(ls[:real])
      valid = v if valid is None else valid + v
      self._save_chunk_snapshot(state, c0 + chunk, chunk, losses,
                                None, valid)
    metrics.inc('loader.batches', s)
    return state, EpochStats(jnp.concatenate(losses),
                             jnp.zeros((), jnp.int32), valid)
