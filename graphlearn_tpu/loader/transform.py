"""SamplerOutput → model-ready batch pytrees.

Counterpart of reference `loader/transform.py:25-104` (``to_data`` /
``to_hetero_data`` building `torch_geometric.data.Data`/`HeteroData`).
The TPU analog of a PyG ``Data`` is a static-shape pytree of
`jax.Array`s that crosses `jit` boundaries unchanged: same field names
(``x / y / edge_index / edge_attr / batch``), plus the validity masks
the padding contract requires.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..typing import EdgeType, NodeType
from ..sampler.base import HeteroSamplerOutput, SamplerOutput


def _contains_array(v) -> bool:
  if hasattr(v, 'shape') or hasattr(v, 'dtype'):
    return True
  if isinstance(v, dict):
    return any(_contains_array(x) for x in v.values())
  if isinstance(v, (list, tuple)):
    return any(_contains_array(x) for x in v)
  return False


def _split_metadata(metadata: Dict):
  """Split metadata into (dynamic array-valued, static hashable) parts
  so batches stay jit-compatible pytrees even when samplers attach
  strings (e.g. ``input_type``).  Containers holding arrays (the
  hetero ``seed_local`` per-type dict) count as dynamic."""
  dyn, static = {}, {}
  for k, v in metadata.items():
    if _contains_array(v):
      dyn[k] = v
    else:
      static[k] = v
  return dyn, tuple(sorted(static.items()))


class Batch:
  """PyG-``Data``-shaped mini-batch (homogeneous), as a pytree.

  Attributes:
    x: ``[node_cap, D]`` node features (zero rows where padded).
    y: ``[node_cap]`` node labels (0 where padded) or None.
    edge_index: ``[2, edge_cap]`` local COO, -1 where masked; transposed
      for message passing (row = neighbor/source, col = target) exactly
      as the reference emits it.
    edge_attr: ``[edge_cap, De]`` edge features or None.
    node: ``[node_cap]`` global node ids (INVALID_ID padded).
    node_mask: ``[node_cap]`` validity.
    edge_mask: ``[edge_cap]`` validity.
    edge: ``[edge_cap]`` global edge ids or None.
    batch: ``[B]`` global seed ids.
    batch_size: static seed count (padded slots included).
    metadata: link-prediction labels etc. (``edge_label`` /
      ``edge_label_index`` / ``edge_label_mask`` / triplet indices).
  """

  def __init__(self, x=None, y=None, edge_index=None, edge_attr=None,
               node=None, node_mask=None, edge_mask=None, edge=None,
               batch=None, batch_size: int = 0, num_sampled_nodes=None,
               num_sampled_edges=None, metadata=None):
    self.x = x
    self.y = y
    self.edge_index = edge_index
    self.edge_attr = edge_attr
    self.node = node
    self.node_mask = node_mask
    self.edge_mask = edge_mask
    self.edge = edge
    self.batch = batch
    self.batch_size = batch_size
    self.num_sampled_nodes = num_sampled_nodes
    self.num_sampled_edges = num_sampled_edges
    self.metadata = metadata if metadata is not None else {}

  def tree_flatten(self):
    dyn_md, static_md = _split_metadata(self.metadata)
    children = (self.x, self.y, self.edge_index, self.edge_attr, self.node,
                self.node_mask, self.edge_mask, self.edge, self.batch,
                self.num_sampled_nodes, self.num_sampled_edges, dyn_md)
    return children, (self.batch_size, static_md)

  @classmethod
  def tree_unflatten(cls, aux, children):
    (x, y, edge_index, edge_attr, node, node_mask, edge_mask, edge, batch,
     nsn, nse, metadata) = children
    metadata = dict(metadata)
    metadata.update(dict(aux[1]))
    return cls(x, y, edge_index, edge_attr, node, node_mask, edge_mask, edge,
               batch, aux[0], nsn, nse, metadata)

  def __repr__(self):
    shp = lambda a: getattr(a, 'shape', None)
    return (f'Batch(x={shp(self.x)}, edge_index={shp(self.edge_index)}, '
            f'batch_size={self.batch_size})')


jax.tree_util.register_pytree_node(
    Batch, lambda b: b.tree_flatten(), Batch.tree_unflatten)


class HeteroBatch:
  """PyG-``HeteroData``-shaped mini-batch: per-type dicts of arrays."""

  def __init__(self, x_dict=None, y_dict=None, edge_index_dict=None,
               edge_attr_dict=None, node_dict=None, node_mask_dict=None,
               edge_mask_dict=None, batch_dict=None, batch_size: int = 0,
               metadata=None):
    self.x_dict = x_dict or {}
    self.y_dict = y_dict or {}
    self.edge_index_dict = edge_index_dict or {}
    self.edge_attr_dict = edge_attr_dict or {}
    self.node_dict = node_dict or {}
    self.node_mask_dict = node_mask_dict or {}
    self.edge_mask_dict = edge_mask_dict or {}
    self.batch_dict = batch_dict or {}
    self.batch_size = batch_size
    self.metadata = metadata if metadata is not None else {}

  def tree_flatten(self):
    dyn_md, static_md = _split_metadata(self.metadata)
    children = (self.x_dict, self.y_dict, self.edge_index_dict,
                self.edge_attr_dict, self.node_dict, self.node_mask_dict,
                self.edge_mask_dict, self.batch_dict, dyn_md)
    return children, (self.batch_size, static_md)

  @classmethod
  def tree_unflatten(cls, aux, children):
    (x, y, ei, ea, node, nm, em, batch, metadata) = children
    metadata = dict(metadata)
    metadata.update(dict(aux[1]))
    return cls(x, y, ei, ea, node, nm, em, batch, aux[0], metadata)

  def __repr__(self):
    return (f'HeteroBatch(node_types={list(self.node_dict)}, '
            f'edge_types={list(self.edge_index_dict)})')


jax.tree_util.register_pytree_node(
    HeteroBatch, lambda b: b.tree_flatten(), HeteroBatch.tree_unflatten)


@jax.jit
def _gather_labels(labels: jax.Array, ids: jax.Array) -> jax.Array:
  valid = ids >= 0
  idx = jnp.where(valid, ids, 0)
  out = labels[idx]
  mask = valid.reshape(valid.shape + (1,) * (out.ndim - 1))
  return jnp.where(mask, out, 0)


def to_data(
    out: SamplerOutput,
    node_feature=None,
    node_label=None,
    edge_feature=None,
) -> Batch:
  """Assemble a `Batch` from a `SamplerOutput` + gathered features.

  Mirrors reference `loader/transform.py:25-53` (``to_data``):
  feature/label tensors are indexed by the sampled global node ids;
  metadata (link labels) is forwarded.
  """
  x = node_feature[out.node] if node_feature is not None else None
  y = None
  if node_label is not None:
    if isinstance(node_label, jax.Array) and isinstance(out.node,
                                                       jax.Array):
      # all-device label gather: no host round trip per batch
      y = _gather_labels(node_label, out.node)
    else:
      import numpy as np
      ids = np.asarray(out.node)
      valid = ids >= 0
      lab = np.asarray(node_label)
      yv = np.zeros((len(ids),) + lab.shape[1:], dtype=lab.dtype)
      yv[valid] = lab[ids[valid]]
      y = jnp.asarray(yv)
  edge_attr = None
  if edge_feature is not None and out.edge is not None:
    edge_attr = edge_feature[out.edge]
  edge_index = jnp.stack([out.row, out.col])
  return Batch(
      x=x, y=y, edge_index=edge_index, edge_attr=edge_attr,
      node=out.node, node_mask=out.node >= 0, edge_mask=out.edge_mask,
      edge=out.edge, batch=out.batch, batch_size=out.batch_size,
      num_sampled_nodes=out.num_sampled_nodes,
      num_sampled_edges=out.num_sampled_edges,
      metadata=dict(out.metadata))


def collate(data, out) -> Any:
  """Dispatch a sampler output through the right collation against a
  `Dataset` — the one shared implementation behind every loader's
  ``_collate_fn`` (reference `loader/node_loader.py:85-113`)."""
  if isinstance(out, HeteroSamplerOutput):
    label_dict = None
    if isinstance(data.node_labels, dict):
      label_dict = {nt: data.get_node_label_device(nt)
                    for nt in data.node_labels}
    return to_hetero_data(
        out,
        node_feature_dict=data.node_features
        if isinstance(data.node_features, dict) else None,
        node_label_dict=label_dict,
        edge_feature_dict=data.edge_features
        if isinstance(data.edge_features, dict) else None)
  return to_data(
      out,
      node_feature=data.get_node_feature(),
      node_label=data.get_node_label_device(),
      edge_feature=(data.get_edge_feature()
                    if out.edge is not None else None))


def to_hetero_data(
    out: HeteroSamplerOutput,
    node_feature_dict: Optional[Dict[NodeType, Any]] = None,
    node_label_dict: Optional[Dict[NodeType, Any]] = None,
    edge_feature_dict: Optional[Dict[EdgeType, Any]] = None,
) -> HeteroBatch:
  """Assemble a `HeteroBatch` (reference `loader/transform.py:56-104`)."""
  import numpy as np
  x_dict, y_dict, nm_dict = {}, {}, {}
  for ntype, ids in out.node.items():
    nm_dict[ntype] = ids >= 0
    if node_feature_dict and ntype in node_feature_dict:
      x_dict[ntype] = node_feature_dict[ntype][ids]
    if node_label_dict and ntype in node_label_dict:
      lab = node_label_dict[ntype]
      if isinstance(lab, jax.Array) and isinstance(ids, jax.Array):
        y_dict[ntype] = _gather_labels(lab, ids)
      else:
        ids_h = np.asarray(ids)
        valid = ids_h >= 0
        lab = np.asarray(lab)
        yv = np.zeros((len(ids_h),) + lab.shape[1:], dtype=lab.dtype)
        yv[valid] = lab[ids_h[valid]]
        y_dict[ntype] = jnp.asarray(yv)
  ei_dict, em_dict, ea_dict = {}, {}, {}
  for etype in out.row:
    ei_dict[etype] = jnp.stack([out.row[etype], out.col[etype]])
    if out.edge_mask is not None and etype in out.edge_mask:
      em_dict[etype] = out.edge_mask[etype]
    if (edge_feature_dict and etype in edge_feature_dict
        and out.edge is not None and etype in out.edge):
      ea_dict[etype] = edge_feature_dict[etype][out.edge[etype]]
  batch_size = 0
  if out.batch:
    batch_size = max(int(v.shape[0]) for v in out.batch.values())
  return HeteroBatch(
      x_dict=x_dict, y_dict=y_dict, edge_index_dict=ei_dict,
      edge_attr_dict=ea_dict, node_dict=dict(out.node),
      node_mask_dict=nm_dict, edge_mask_dict=em_dict,
      batch_dict=dict(out.batch or {}), batch_size=batch_size,
      metadata=dict(out.metadata))
