"""Node-wise mini-batch loader.

Counterpart of reference `loader/node_loader.py:27-113` (``NodeLoader``):
iterate seed ids in (optionally shuffled) batches, run the sampler, and
collate features/labels into a `Batch` pytree.  Where the reference
leans on `torch.utils.data.DataLoader` for seed batching, the TPU
version batches on the host with numpy and **pads the tail batch to the
static batch size** so every step reuses one compiled program.
"""
from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from ..data.dataset import Dataset
from ..sampler.base import BaseSampler, NodeSamplerInput
from ..utils.padding import INVALID_ID, pad_1d
from ..utils.profiling import metrics, trace
from .prefetch import PrefetchingLoader
from .transform import Batch, collate


class SeedBatcher:
  """Host-side seed iterator: shuffle, slice, pad to static size.

  ``seeds`` may be ``[E]`` node ids or ``[E, K]`` rows (link-mode
  (src, dst[, label]) triples); shuffling/slicing is along axis 0 and
  padding fills whole rows with INVALID_ID."""

  def __init__(self, seeds: np.ndarray, batch_size: int,
               shuffle: bool = False, drop_last: bool = False,
               seed: Optional[int] = None):
    seeds = np.asarray(seeds)
    self.seeds = seeds if seeds.ndim > 1 else seeds.reshape(-1)
    self.batch_size = int(batch_size)
    self.shuffle = shuffle
    self.drop_last = drop_last
    self._rng = np.random.default_rng(seed)
    self.epochs_started = 0
    self._epoch_start_rng = None   # packed rng state at last __iter__

  def __len__(self) -> int:
    n = len(self.seeds)
    if self.drop_last:
      return n // self.batch_size
    return -(-n // self.batch_size)

  def __iter__(self):
    """Each epoch is a PRIVATE iterator (own order, own position):
    an abandoned consumer — e.g. an orphaned prefetch worker — can
    never steal batches from a later epoch."""
    from ..utils.checkpoint import pack_rng_state
    # epoch-START rng snapshot: a mid-epoch resume must re-draw THIS
    # epoch's permutation, which requires the state BEFORE the draw
    self._epoch_start_rng = pack_rng_state(self._rng)
    self.epochs_started += 1
    n = len(self.seeds)
    order = (self._rng.permutation(n) if self.shuffle
             else np.arange(n))
    return self._epoch(order)

  # -- DataPlaneState (utils.checkpoint) ----------------------------------
  def state_dict(self) -> dict:
    """Cursor + RNG capture: ``rng`` is the CURRENT stream (epoch-
    boundary resume point) and ``epoch_rng`` the state at the last
    epoch's start (mid-epoch resume re-draws that epoch's permutation
    byte-identically)."""
    from ..utils.checkpoint import pack_rng_state
    return {'rng': pack_rng_state(self._rng),
            'epoch_rng': (self._epoch_start_rng
                          if self._epoch_start_rng is not None
                          else pack_rng_state(self._rng)),
            'epochs_started': self.epochs_started}

  def load_state_dict(self, state: dict, mid_epoch: bool = False
                      ) -> None:
    """``mid_epoch=True`` rewinds the RNG to the interrupted epoch's
    START (the next ``__iter__`` re-draws the same permutation) and
    rolls the epoch counter back so that re-draw is not double-
    counted; False resumes at the epoch boundary."""
    from ..utils.checkpoint import restore_rng_state
    self.epochs_started = int(np.asarray(state['epochs_started']))
    if mid_epoch:
      restore_rng_state(self._rng, state['epoch_rng'])
      self.epochs_started = max(self.epochs_started - 1, 0)
    else:
      restore_rng_state(self._rng, state['rng'])

  def _epoch(self, order: np.ndarray):
    n = len(self.seeds)
    pos = 0
    while pos < n:
      end = pos + self.batch_size
      if end > n and self.drop_last:
        return
      batch = self.seeds[order[pos:end]].astype(np.int32)
      pos = end
      if len(batch) < self.batch_size:
        if batch.ndim > 1:
          pad = np.full((self.batch_size - len(batch),) + batch.shape[1:],
                        INVALID_ID, batch.dtype)
          batch = np.concatenate([batch, pad])
        else:
          batch = pad_1d(batch, self.batch_size, INVALID_ID)
      yield batch


class NodeLoader(PrefetchingLoader):
  """Base loader: seeds → sampler → collate.

  Args:
    data: the `Dataset` (graph + features + labels).
    sampler: any `BaseSampler` with ``sample_from_nodes``.
    input_nodes: ``[N]`` seed ids (e.g. the train split).
    batch_size / shuffle / drop_last: epoch iteration controls.
    seed: shuffling seed.
    prefetch: batches prepared ahead on a worker thread (0 = off;
      2 = double buffering — overlaps the next batch's host-side
      sampling + cold-tier gather + transfer dispatch with the current
      device step; see `loader.prefetch.PrefetchIterator`).
  """

  def __init__(self, data: Dataset, sampler: BaseSampler, input_nodes,
               batch_size: int = 1, shuffle: bool = False,
               drop_last: bool = False, seed: Optional[int] = None,
               prefetch: int = 0, **kwargs):
    self.prefetch = int(prefetch)
    self.data = data
    self.sampler = sampler
    self.input_type = None
    if isinstance(input_nodes, tuple) and isinstance(input_nodes[0], str):
      # Hetero seeds: (node_type, ids) — reference `InputNodes`
      # (`typing.py:83`).
      self.input_type, input_nodes = input_nodes
    input_nodes = np.asarray(input_nodes)
    if input_nodes.dtype == np.bool_:
      input_nodes = np.nonzero(input_nodes)[0]
    self._batcher = SeedBatcher(input_nodes, batch_size, shuffle, drop_last,
                                seed)
    self.batch_size = int(batch_size)

  def __len__(self) -> int:
    return len(self._batcher)

  def _produce(self, seed_iter) -> Batch:
    seeds = next(seed_iter)
    with trace('loader.sample'):
      out = self.sampler.sample_from_nodes(
          NodeSamplerInput(node=seeds, input_type=self.input_type))
    with trace('loader.collate'):
      batch = self._collate_fn(out)
    metrics.inc('loader.batches')
    metrics.inc('loader.seeds', int((seeds >= 0).sum()))
    return batch

  def _collate_fn(self, out):
    """Gather features/labels for sampled nodes and build the batch
    (reference `loader/node_loader.py:85-113`)."""
    return collate(self.data, out)
