"""Neighbor-sampling mini-batch loader (the headline single-chip API).

Counterpart of reference `loader/neighbor_loader.py:27-106`
(``NeighborLoader``): a `NodeLoader` wired to a `NeighborSampler`.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..data.dataset import Dataset
from ..sampler.neighbor_sampler import NeighborSampler
from .node_loader import NodeLoader


class NeighborLoader(NodeLoader):
  """Multi-hop uniform neighbor-sampling loader.

  Example::

      loader = NeighborLoader(dataset, [15, 10, 5], train_idx,
                              batch_size=1024, shuffle=True)
      for batch in loader:
        loss = train_step(state, batch)

  Args:
    data: `Dataset` with an initialized homogeneous graph.
    num_neighbors: per-hop fanouts.
    input_nodes: seed ids (or boolean mask).
    with_edge: emit global edge ids (+ edge features if present).
    seed: PRNG seed for sampling & shuffling.
  """

  def __init__(self, data: Dataset, num_neighbors, input_nodes,
               batch_size: int = 1, shuffle: bool = False,
               drop_last: bool = False, with_edge: bool = False,
               device=None, seed: Optional[int] = None, **kwargs):
    if data.is_hetero:
      from ..sampler.hetero_neighbor_sampler import HeteroNeighborSampler
      sampler = HeteroNeighborSampler(
          data.get_graph(), num_neighbors, device=device,
          with_edge=with_edge, num_nodes=data.num_nodes_dict(),
          seed=seed or 0)
    else:
      sampler = NeighborSampler(
          data.get_graph(), num_neighbors, device=device,
          with_edge=with_edge, seed=seed or 0)
    super().__init__(data, sampler, input_nodes, batch_size=batch_size,
                     shuffle=shuffle, drop_last=drop_last, seed=seed,
                     **kwargs)
