"""Whole-epoch fused TREE-layout training: the TPU-first flagship path.

`FusedEpoch` fuses the subgraph pipeline (sample → dedup → gather →
`SAGEConv` scatter aggregation) into one program; this module goes one
design level deeper and removes the subgraph itself.  The scan body
keeps the sampler's native tree layout end to end:

  * per hop, `ops.neighbor.sample_one_hop` expands the level frontier
    to a ``[F_t, k]`` window tensor — no dedup, NO SORT (the
    capacity-bounded unique that dominates the subgraph sampler's
    device time is structurally unnecessary here);
  * features gather per level; aggregation inside `models.tree.
    TreeSAGE` is reshape + masked mean — NO SCATTER, forward or
    backward;
  * supervised CE on the seed level + optax update.

Measured v5e decomposition that motivated this (r5, products scale,
fanout [15,10,5], batch 1024): subgraph fused step ~440 ms/step =
~104 ms sort-based sampling + ~7 ms collation + ~205 ms model
(scatter-dominated) + overheads.  The tree path replaces both
dominant terms with streaming ops and lands at **35.6 ms/step**
(f32; 32.8 bf16), decomposed (steady-state AOT protocol) as
~19.8 ms sampling + ~9.9 ms feature gather + ~5.9 ms model+optax.
That residual is the chip's GATHER-DESCRIPTOR bound, not slack: the
step issues ~2.2 M descriptor-bound gathers (938k feature rows +
~937k neighbor-id elements + ~340k indptr degrees), and at the
measured ~80 M descriptors/s (`ops/pallas_gather.py` roofline) the
analytic floor is ~27 ms — the step runs at ~76% of it.
``replace=True`` window-free draws were measured within 7% of the
Gumbel-top-k path (the descriptors dominate either way), so the
without-replacement default stands.  One-time cost note: the FIRST
execution of a freshly loaded program carries ~5-7 s of on-chip
program load on the tunneled setup; steady-state timings start at
the second execution (two independent timing paths agree at
~36 ms/step).

Also the epoch-length compile story (VERDICT r4 #4):
``max_steps_per_program`` runs the epoch as ceil(S/chunk) dispatches
of ONE compiled ``[chunk, B]`` program — every epoch length reuses the
same executable (tail steps are INVALID_ID-padded; a fully-invalid
step is a guarded no-op on the state).  The axon-tunneled chip also
enforces a ~70 s single-program execution watchdog, which chunking
keeps every dispatch under.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..data.dataset import Dataset
from ..data.feature import _device_gather
from ..models.train import TrainState
from ..ops.pallas_gather import pallas_enabled
from ..ops.pallas_sample import sample_one_hop_auto
from .fused import _SupervisedScanEpoch, _uncached_jit
from .node_loader import SeedBatcher
from .transform import _gather_labels


def expand_tree_levels(indptr, indices, seeds, key, fanouts, *,
                       sort_locality: bool = False):
  """The bucketed single-shot tree expansion: ``[B]`` seeds → per-level
  ``(levels, masks)`` lists (``levels[t]`` is ``[B * k_1 ... k_t]``
  node ids, INVALID_ID where masked).  ONE definition shared by the
  epoch drivers here and the online serving plane
  (`serving.engine.ServingEngine` — which vmaps it per seed so a
  seed's tree depends only on (key, seed), never on batch
  composition), so the level layout the model consumes cannot drift
  between training and serving."""
  levels, masks = [seeds], [seeds >= 0]
  frontier = seeds
  for i, k in enumerate(fanouts):
    # `sample_one_hop_auto` re-reads GLT_PALLAS_SAMPLE at trace time;
    # the epoch drivers compile once per config so the choice is baked
    # per program (value-identical either way)
    res = sample_one_hop_auto(indptr, indices, frontier, k,
                              jax.random.fold_in(key, i),
                              sort_locality=sort_locality)
    nxt = jnp.where(res.mask, res.nbrs, -1).reshape(-1)
    levels.append(nxt)
    masks.append(nxt >= 0)
    frontier = nxt
  return levels, masks


class FusedTreeEpoch(_SupervisedScanEpoch):
  """One-program tree-layout supervised epochs (see module docstring).

  Example::

      model = TreeSAGE(hidden_features=256, out_features=47,
                       num_layers=3)
      fused = FusedTreeEpoch(ds, [15, 10, 5], train_idx, model, tx,
                             batch_size=1024, seed=0)
      state = fused.init_state(jax.random.key(0))
      for _ in range(epochs):
        state, stats = fused.run(state)
      acc = fused.evaluate(state.params, test_idx)

  Args:
    data: `Dataset`, homogeneous, fully device-resident features +
      labels (same contract as `FusedEpoch`).
    num_neighbors: per-hop fanouts; ``len == model.num_layers``.
    input_nodes: seed ids (or boolean mask).
    model: a `models.tree.TreeSAGE` (or any flax module with the same
      ``(xs, masks) -> [B, C]`` signature).
    tx: optax transformation.
    batch_size / shuffle / drop_last / seed: epoch controls.
    max_steps_per_program: split each epoch into dispatches of at most
      this many steps, all served by ONE compiled program (None = the
      whole epoch as one program, compiled per epoch length).
    remat: `jax.checkpoint` the model apply.
  """

  def __init__(self, data: Dataset, num_neighbors: Sequence[int],
               input_nodes, model, tx: optax.GradientTransformation,
               batch_size: int, shuffle: bool = True,
               drop_last: bool = False, seed: Optional[int] = None,
               max_steps_per_program: Optional[int] = None,
               remat: bool = False):
    if data.is_hetero:
      raise ValueError('FusedTreeEpoch is homogeneous-only')
    feat = data.node_features
    if feat is None or feat.hot_rows < feat.size(0):
      raise ValueError(
          'FusedTreeEpoch needs fully device-resident features '
          '(split_ratio == 1.0)')
    labels = data.get_node_label_device()
    if labels is None:
      raise ValueError('FusedTreeEpoch needs node labels')
    self.data = data
    self.model = model
    self.tx = tx
    self.batch_size = int(batch_size)
    self.fanouts = tuple(int(k) for k in num_neighbors)
    if getattr(model, 'num_layers', len(self.fanouts)) != \
        len(self.fanouts):
      raise ValueError(
          f'model.num_layers={model.num_layers} must equal '
          f'len(num_neighbors)={len(self.fanouts)}')
    graph = data.get_graph()
    # big tables as jit ARGUMENTS, never closures (`loader.fused`)
    self._dev = dict(indptr=graph.indptr, indices=graph.indices,
                     hot=feat.hot_tier, id2index=feat._id2index_dev,
                     labels=labels)
    input_nodes = np.asarray(input_nodes)
    if input_nodes.dtype == np.bool_:
      input_nodes = np.nonzero(input_nodes)[0]
    self._batcher = SeedBatcher(input_nodes, self.batch_size, shuffle,
                                drop_last, seed)
    self._base_key = jax.random.key(seed or 0)
    self._epoch_idx = 0
    self._chunk = (int(max_steps_per_program)
                   if max_steps_per_program else None)
    apply = model.apply
    self._apply = jax.checkpoint(apply) if remat else apply
    self._eval_apply = apply
    # chunk-bounded programs may opt into the persistent compilation
    # cache via GLT_FUSED_COMPILE_CACHE=1 (see loader.fused._uncached_jit)
    cacheable = self._chunk is not None
    self._compiled = _uncached_jit(self._epoch_fn, donate_argnums=(0,),
                                   static_argnums=(4,),
                                   cacheable=cacheable)
    self._compiled_eval = _uncached_jit(self._eval_fn,
                                        static_argnums=(4,),
                                        cacheable=cacheable)

  def __len__(self) -> int:
    return len(self._batcher)

  def init_state(self, rng) -> TrainState:
    """Init params from one dummy tree batch (host-cheap: shapes
    only)."""
    from ..telemetry.spans import span
    with span('fused.init_state', scope=type(self).__name__):
      d = self.data.node_features.feature_dim
      sizes = [self.batch_size]
      for k in self.fanouts:
        sizes.append(sizes[-1] * k)
      xs = [jnp.zeros((s, d), self.data.node_features.dtype)
            for s in sizes]
      masks = [jnp.ones((s,), jnp.bool_) for s in sizes]
      params = self.model.init(rng, xs, masks)
      return TrainState(params, self.tx.init(params),
                        jnp.zeros((), jnp.int32))

  # -- tree expansion + collation (the scan-body front half) --------------

  def _expand(self, seeds: jax.Array, key: jax.Array, dev: dict,
              use_pallas: bool):
    # no sort: the tree gather is rate-bound by rows/s either way (r5
    # roofline), and the locality sort is the subgraph sampler's
    # dominant device cost
    levels, masks = expand_tree_levels(dev['indptr'], dev['indices'],
                                       seeds, key, self.fanouts,
                                       sort_locality=False)
    xs = [_device_gather(dev['hot'], lvl, dev['id2index'],
                         use_pallas=use_pallas) for lvl in levels]
    y = _gather_labels(dev['labels'], seeds)
    return xs, masks, y

  # -- the one program ------------------------------------------------------

  def _epoch_fn(self, state: TrainState, seeds_all: jax.Array,
                key: jax.Array, dev: dict, use_pallas: bool):
    b = self.batch_size

    def body(state, xs_in):
      i, seeds = xs_in
      xs, masks, y = self._expand(seeds, jax.random.fold_in(key, i),
                                  dev, use_pallas)

      def loss_fn(params):
        logits = self._apply(params, xs, masks)
        valid = (seeds >= 0).astype(logits.dtype)
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits, y.astype(jnp.int32))
        return (ce * valid).sum() / jnp.maximum(valid.sum(), 1.0), \
            logits

      (loss, logits), grads = jax.value_and_grad(
          loss_fn, has_aux=True)(state.params)
      updates, opt_state = self.tx.update(grads, state.opt_state,
                                          state.params)
      params = optax.apply_updates(state.params, updates)
      new_state = TrainState(params, opt_state, state.step + 1)
      # fully-padded steps (epoch-length chunking) must be no-ops:
      # zero grads still move adam's moments/bias correction
      any_valid = jnp.any(seeds >= 0)
      state = jax.tree_util.tree_map(
          lambda new, old: jnp.where(any_valid, new, old),
          new_state, state)
      valid = seeds >= 0
      correct = jnp.sum(
          (jnp.argmax(logits, axis=-1) == y) & valid)
      return state, (loss, correct, jnp.sum(valid))

    steps = jnp.arange(seeds_all.shape[0], dtype=jnp.int32)
    state, (losses, corrects, valids) = jax.lax.scan(
        body, state, (steps, seeds_all))
    return state, losses, jnp.sum(corrects), jnp.sum(valids)

  def _eval_fn(self, params, seeds_all: jax.Array, key: jax.Array,
               dev: dict, use_pallas: bool):
    def body(carry, xs_in):
      i, seeds = xs_in
      xs, masks, y = self._expand(seeds, jax.random.fold_in(key, i),
                                  dev, use_pallas)
      logits = self._eval_apply(params, xs, masks)
      valid = seeds >= 0
      correct = jnp.sum((jnp.argmax(logits, axis=-1) == y) & valid)
      return carry, (correct, jnp.sum(valid))

    steps = jnp.arange(seeds_all.shape[0], dtype=jnp.int32)
    _, (correct, total) = jax.lax.scan(body, 0, (steps, seeds_all))
    return jnp.sum(correct), jnp.sum(total)

  # host driver (`run` / `evaluate` / `_chunks` / `__len__`) comes
  # from `_SupervisedScanEpoch` — one chunking implementation for the
  # whole fused family, so the key-schedule and padded-tail contracts
  # cannot drift between the subgraph and tree paths
