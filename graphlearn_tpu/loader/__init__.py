from .transform import Batch, HeteroBatch, to_data, to_hetero_data
from .node_loader import NodeLoader, SeedBatcher
from .neighbor_loader import NeighborLoader
