from .transform import Batch, HeteroBatch, to_data, to_hetero_data
from .node_loader import NodeLoader, SeedBatcher
from .prefetch import PrefetchIterator
from .neighbor_loader import NeighborLoader
from .link_loader import EdgeSeedBatcher, LinkLoader, LinkNeighborLoader
from .subgraph_loader import SubGraphLoader
from .fused import (EpochStats, FusedEpoch, FusedHeteroEpoch,
                    FusedLinkEpoch)
from .fused_tree import FusedTreeEpoch
