"""Background-thread batch prefetch (double buffering).

The cold-tier feature path host-gathers rows and ``device_put``s them
inside the batch critical path (`data/feature.py:156-187`) — the
synchronous analog of the reference's UVA reads
(`csrc/cuda/unified_tensor.cu:202+`), which overlap with GPU compute
for free.  `PrefetchIterator` restores that overlap on TPU: a worker
thread runs the loader's host work (sampling prep, cold gather, the
async ``device_put`` dispatch) for the NEXT batch while the caller's
current step executes on device.  JAX dispatch is thread-safe and
async, so the handed-over batch is already in flight when the consumer
receives it.

Loaders expose this as ``prefetch=N`` (0 = off, the synchronous
default; 2 = classic double buffering).
"""
from __future__ import annotations

import queue
import threading
from typing import Iterator


class PrefetchingLoader:
  """Mixin: epoch iteration with optional background prefetch.

  Subclasses implement ``_produce(seed_iter)`` (one batch or raise
  StopIteration) and keep their seed source at ``self._batcher`` (the
  default ``__iter__`` starts an epoch over ``iter(self._batcher)``;
  override for a different source).  Guarantees: each epoch runs on a
  PRIVATE seed iterator; ``iter(loader)`` always starts a NEW epoch
  while ``iter()`` on the RETURNED iterator continues it (torch
  DataLoader semantics, identical for prefetch 0 and > 0); starting a
  new epoch closes the previous epoch's worker — an abandoned
  ``prefetch > 0`` epoch can neither steal the next epoch's batches
  nor leak its thread.
  """

  prefetch: int = 0

  def __iter__(self):
    ctl = getattr(self, '_adaptive', None)
    sampler = getattr(self, 'sampler', None)
    ewma = (sampler is not None
            and getattr(sampler, '_ewma_model', None) is not None)
    if ctl is not None or ewma:
      # join any still-live prefetch worker BEFORE retuning: a worker
      # mid-_produce must not trace against the new capacity while
      # the finished epoch's telemetry is being attributed to the old
      self.close()
      if getattr(self, '_epoch_count', 0) > 0:
        if ctl is not None:
          ctl.on_epoch_end()
        if ewma:
          # EWMA capacity retune (ISSUE 20c) shares the epoch seam:
          # observed attribution deltas resize the per-destination
          # exchange capacities before the next epoch compiles
          sampler.capacity_retune()
      self._epoch_count = getattr(self, '_epoch_count', 0) + 1
    return self._start_epoch(iter(self._batcher))

  def _start_epoch(self, seed_iter):
    # close AND join any previous worker: it may be mid-_produce, and
    # two workers on one loader would race the sampler's stateful PRNG
    # key counter (non-reproducible batches)
    self.close()
    self._seed_iter = seed_iter
    if self.prefetch:
      it = PrefetchIterator(self._epoch_gen(seed_iter), self.prefetch)
      self._active_prefetch = it
      return it
    return _SyncEpochIterator(self, seed_iter)

  def close(self) -> None:
    """Stop an abandoned prefetch worker and drop its buffered batches
    (depth x device-stacked pytrees otherwise stay resident until the
    next epoch or loader GC).  Call after breaking out of a
    ``prefetch > 0`` epoch early."""
    prev = getattr(self, '_active_prefetch', None)
    if prev is not None:
      prev.close()
      prev.join()
      self._active_prefetch = None

  def _epoch_gen(self, seed_iter):
    while True:
      try:
        yield self._produce(seed_iter)
      except StopIteration:
        return

  def __next__(self):
    # legacy direct-next path: consumes the most recent epoch's stream.
    # With an active prefetch worker, delegate — calling _produce here
    # would race the worker on the same seed generator.
    it = getattr(self, '_active_prefetch', None)
    if it is not None:
      return next(it)
    return self._produce(self._seed_iter)

  def _produce(self, seed_iter):
    raise NotImplementedError

  def _pipeline_acquire(self, seed_iter):
    """First half of the one-deep dispatch/finish pipeline: hand back
    batch k's in-flight handle (dispatched during batch k-1), or —
    pipeline cold, at epoch start — batch k's raw seeds for the caller
    to dispatch.  Raises StopIteration at epoch end, and MUST be
    called before the per-batch root span opens so an exhausted epoch
    cannot emit an empty ``batch`` span.  Pipeline state is keyed on
    the seed-iterator identity, so a new epoch (or an abandoned one)
    can never consume a stale in-flight batch."""
    if getattr(self, '_pending_src', None) is not seed_iter:
      self._pending, self._pending_src = None, seed_iter
    cur, self._pending = self._pending, None
    if cur is None:
      return None, next(seed_iter)     # StopIteration ends the epoch
    return cur, None

  def _pipelined(self, acquired, seed_iter, dispatch_flat, finish):
    """Second half, inside the batch span: issue the device work for
    batch k+1 before running batch k's host finish (``finish``) — the
    asynchronous double-buffered cold overlay of the tiered mesh
    loaders.  The host gather + transfer of batch k's cold rows then
    overlaps with batch k+1's sampling compute, instead of serializing
    after it.  Batches are byte-identical to the unpipelined path —
    only the host/device interleaving changes."""
    cur, flat = acquired
    if cur is None:
      cur = dispatch_flat(flat)
    try:
      self._pending = dispatch_flat(next(seed_iter))
    except StopIteration:
      pass
    return finish(cur)


class _SyncEpochIterator:
  """One synchronous epoch: ``iter()`` returns itself, so a warm-up
  ``next()`` followed by a for-loop CONTINUES the epoch — the same
  contract as the prefetching iterator."""

  def __init__(self, loader: 'PrefetchingLoader', seed_iter):
    self._loader = loader
    self._seed_iter = seed_iter

  def __iter__(self):
    return self

  def __next__(self):
    return self._loader._produce(self._seed_iter)


class _Failure:
  """Exception holder crossing the thread boundary."""

  def __init__(self, exc: BaseException):
    self.exc = exc


class PrefetchIterator:
  """Iterate ``it`` on a daemon worker thread, ``depth`` items ahead.

  Exceptions raised by the producer re-raise at the consumer's
  ``__next__``; abandoning the iterator mid-epoch stops the worker
  (the bounded queue is polled against a stop flag, so the thread
  never blocks forever on a reader that went away).
  """

  _DONE = object()

  def __init__(self, it: Iterator, depth: int = 2):
    self._q: queue.Queue = queue.Queue(maxsize=max(1, int(depth)))
    self._stop = threading.Event()
    self._thread = threading.Thread(
        target=self._run, args=(it,), daemon=True,
        name='glt-prefetch')
    self._thread.start()

  def _run(self, it) -> None:
    try:
      for item in it:
        if not self._put(item):
          return
      self._put(self._DONE)
    except BaseException as e:           # noqa: B036 — forwarded
      self._put(_Failure(e))

  def _put(self, item) -> bool:
    while not self._stop.is_set():
      try:
        self._q.put(item, timeout=0.1)
        return True
      except queue.Full:
        continue
    return False

  def __iter__(self):
    return self

  def __next__(self):
    if self._stop.is_set():
      raise StopIteration
    item = self._q.get()
    if item is self._DONE:
      self._stop.set()
      raise StopIteration
    if isinstance(item, _Failure):
      self._stop.set()
      raise item.exc
    return item

  def close(self) -> None:
    """Stop the worker and drop buffered batches."""
    self._stop.set()
    try:
      while True:
        self._q.get_nowait()
    except queue.Empty:
      pass

  def join(self, timeout: float = None) -> None:
    """Wait for the worker thread to exit (call after `close`)."""
    self._thread.join(timeout)

  def __del__(self):
    try:
      self.close()
    except Exception:
      pass
