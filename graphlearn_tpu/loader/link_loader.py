"""Link-prediction loaders.

Counterparts of reference `loader/link_loader.py:35-216` (``LinkLoader``)
and `loader/link_neighbor_loader.py:27-149` (``LinkNeighborLoader``):
iterate seed *edges*, sample around their endpoints (+negatives), and
collate batches carrying link-label metadata.

Reference semantics kept:
  * binary mode with user labels applies the +1 shift so label 0 means
    "negative sample" (`link_loader.py:146-186`);
  * metadata names match PyG: ``edge_label_index`` / ``edge_label`` for
    binary, ``src_index`` / ``dst_pos_index`` / ``dst_neg_index`` for
    triplet — plus the TPU padding masks.
"""
from __future__ import annotations

from typing import Iterator, Optional, Sequence

import numpy as np

from ..data.dataset import Dataset
from ..sampler.base import (BaseSampler, EdgeSamplerInput, NegativeSampling,
                            SamplerOutput)
from ..utils.padding import INVALID_ID, pad_1d
from .node_loader import SeedBatcher
from .prefetch import PrefetchingLoader
from .transform import Batch, collate


class EdgeSeedBatcher:
  """Batch (row, col, label) edge seeds with static-size tail padding."""

  def __init__(self, rows, cols, labels=None, batch_size: int = 1,
               shuffle: bool = False, drop_last: bool = False,
               seed: Optional[int] = None):
    self.rows = np.asarray(rows).reshape(-1)
    self.cols = np.asarray(cols).reshape(-1)
    assert len(self.rows) == len(self.cols)
    self.labels = None if labels is None else np.asarray(labels).reshape(-1)
    self._idx = SeedBatcher(np.arange(len(self.rows)), batch_size, shuffle,
                            drop_last, seed)

  def __len__(self):
    return len(self._idx)

  def __iter__(self):
    """Epoch-private iterator (see `SeedBatcher.__iter__`)."""
    for idx in self._idx:
      valid = idx >= 0
      safe = np.where(valid, idx, 0)
      r = np.where(valid, self.rows[safe], INVALID_ID).astype(np.int32)
      c = np.where(valid, self.cols[safe], INVALID_ID).astype(np.int32)
      lab = None
      if self.labels is not None:
        lab = np.where(valid, self.labels[safe], 0)
      yield r, c, lab

  # -- DataPlaneState: cursor state lives in the index batcher ------------
  def state_dict(self) -> dict:
    return self._idx.state_dict()

  def load_state_dict(self, state: dict, mid_epoch: bool = False
                      ) -> None:
    self._idx.load_state_dict(state, mid_epoch=mid_epoch)


class LinkLoader(PrefetchingLoader):
  """Base link loader: seed edges → sampler.sample_from_edges → collate.

  Args:
    data: the Dataset.
    sampler: sampler implementing ``sample_from_edges``.
    edge_label_index: ``[2, E]`` (or (rows, cols)) seed edges.
    edge_label: optional ``[E]`` labels.
    neg_sampling: `NegativeSampling` spec or mode string.
  """

  def __init__(self, data: Dataset, sampler: BaseSampler, edge_label_index,
               edge_label=None, neg_sampling=None, batch_size: int = 1,
               shuffle: bool = False, drop_last: bool = False,
               seed: Optional[int] = None, prefetch: int = 0, **kwargs):
    self.prefetch = int(prefetch)
    self.data = data
    self.sampler = sampler
    self.input_type = None
    if (isinstance(edge_label_index, tuple)
        and isinstance(edge_label_index[0], tuple)
        and len(edge_label_index[0]) == 3):
      # Hetero seed edges: (edge_type, (rows, cols)) — reference
      # `InputEdges` (`typing.py:87`).
      self.input_type, edge_label_index = edge_label_index
    if isinstance(edge_label_index, (tuple, list)):
      rows, cols = edge_label_index
    else:
      ei = np.asarray(edge_label_index)
      rows, cols = ei[0], ei[1]
    self.neg_sampling = NegativeSampling.cast(neg_sampling)
    self._batcher = EdgeSeedBatcher(rows, cols, edge_label, batch_size,
                                    shuffle, drop_last, seed)
    self.batch_size = int(batch_size)

  def __len__(self):
    return len(self._batcher)

  def _produce(self, seed_iter) -> Batch:
    r, c, lab = next(seed_iter)
    if lab is not None and self.neg_sampling is not None \
        and self.neg_sampling.is_binary():
      # Reference +1 shift: user labels move up, 0 = negative class
      # (`loader/link_loader.py:146-186`).  Only VALID pair slots
      # shift — the batcher zero-pads the tail, and a padded slot must
      # not read as a phantom positive to metadata consumers that skip
      # edge_label_mask (same contract as FusedLinkEpoch.run).
      lab = np.where((r >= 0) & (c >= 0), lab + 1, 0)
    out = self.sampler.sample_from_edges(
        EdgeSamplerInput(row=r, col=c, label=lab,
                         input_type=self.input_type,
                         neg_sampling=self.neg_sampling))
    return self._collate_fn(out)

  def _collate_fn(self, out) -> Batch:
    return collate(self.data, out)


class LinkNeighborLoader(LinkLoader):
  """Link loader with multi-hop neighbor expansion around endpoints.

  Mirrors reference `loader/link_neighbor_loader.py:27-149`; the
  workhorse of unsupervised SAGE
  (`examples/graph_sage_unsup_ppi.py:41-45`).
  """

  def __init__(self, data: Dataset, num_neighbors: Sequence[int],
               edge_label_index, edge_label=None, neg_sampling=None,
               batch_size: int = 1, shuffle: bool = False,
               drop_last: bool = False, with_edge: bool = False,
               device=None, seed: Optional[int] = None, **kwargs):
    if data.is_hetero:
      from ..sampler.hetero_neighbor_sampler import HeteroNeighborSampler
      sampler = HeteroNeighborSampler(
          data.get_graph(), num_neighbors, device=device,
          with_edge=with_edge, num_nodes=data.num_nodes_dict(),
          seed=seed or 0)
    else:
      from ..sampler.neighbor_sampler import NeighborSampler
      sampler = NeighborSampler(
          data.get_graph(), num_neighbors, device=device,
          with_edge=with_edge, with_neg=neg_sampling is not None,
          seed=seed or 0)
    super().__init__(data, sampler, edge_label_index, edge_label,
                     neg_sampling, batch_size, shuffle, drop_last, seed,
                     **kwargs)
