"""Unsupervised SAGE on a bipartite user-item graph — hetero link loader.

TPU counterpart of reference `examples/hetero/bipartite_sage_unsup.py`:
a hetero `LinkNeighborLoader` seeded with ``(user, clicks, item)``
edges samples around both endpoint types (+ strict item-space
negatives), a per-edge-type SAGE (HeteroConv factory mode) embeds both
types, and the dot-product link objective trains them jointly.
Held-out interactions are ranked against random pairs.

Usage::

    python examples/hetero/bipartite_sage_unsup.py [--epochs 10] [--cpu]
"""
import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))

import numpy as np

U, I = 'user', 'item'
ET = (U, 'clicks', I)
ET_REV = (I, 'rev_clicks', U)


def synthetic(nu=2000, ni=400, taste=8, deg=10, d=32, seed=0):
  rng = np.random.default_rng(seed)
  ut = rng.integers(0, taste, nu)       # user taste group
  it = rng.integers(0, taste, ni)       # item taste group
  rows = np.repeat(np.arange(nu), deg)
  match = rng.random(nu * deg) < 0.8
  by_taste = [np.nonzero(it == t)[0] for t in range(taste)]
  cols = np.empty(nu * deg, np.int64)
  for t in range(taste):
    m = ut[rows] == t
    pool = by_taste[t] if len(by_taste[t]) else np.arange(ni)
    cols[m] = pool[rng.integers(0, len(pool), m.sum())]
  cols[~match] = rng.integers(0, ni, (~match).sum())
  # weakly informative features: a faint taste direction in noise
  proto = rng.normal(0, 1, (taste, d)).astype(np.float32)
  ufeat = 0.5 * proto[ut] + rng.standard_normal((nu, d)).astype(np.float32)
  ifeat = 0.5 * proto[it] + rng.standard_normal((ni, d)).astype(np.float32)
  return rows, cols, ufeat, ifeat


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--epochs', type=int, default=10)
  ap.add_argument('--batch-size', type=int, default=512)
  ap.add_argument('--hidden', type=int, default=64)
  ap.add_argument('--cpu', action='store_true')
  args = ap.parse_args()

  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import flax.linen as nn
  import jax.numpy as jnp
  import optax
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.loader import LinkNeighborLoader, NeighborLoader
  from graphlearn_tpu.models import HeteroConv, SAGEConv
  from graphlearn_tpu.sampler import NegativeSampling

  urow, icol, ufeat, ifeat = synthetic()
  nu, ni = len(ufeat), len(ifeat)
  rng = np.random.default_rng(2)

  # hold out 10% of interactions for ranking eval
  m = len(urow)
  perm = rng.permutation(m)
  heldout, train = perm[:m // 10], perm[m // 10:]
  tr_u, tr_i = urow[train], icol[train]

  ds = (Dataset()
        .init_graph({ET: (tr_u, tr_i), ET_REV: (tr_i, tr_u)},
                    layout='COO', num_nodes={U: nu, I: ni})
        .init_node_features({U: ufeat, I: ifeat}, split_ratio=1.0))
  loader = LinkNeighborLoader(
      ds, [8, 8], (ET, (tr_u, tr_i)),
      neg_sampling=NegativeSampling('binary', 1.0),
      batch_size=args.batch_size, shuffle=True, seed=0)

  hidden = args.hidden
  etypes = None  # resolved from the first batch

  class BiSAGE(nn.Module):
    etypes: tuple

    @nn.compact
    def __call__(self, x_dict, ei_dict, em_dict):
      h = {nt: nn.Dense(hidden)(x) for nt, x in x_dict.items()}
      for li in range(2):
        conv = HeteroConv(self.etypes, hidden,
                          make_conv=lambda: SAGEConv(hidden),
                          name=f'conv{li}')
        h = conv(h, ei_dict, em_dict)
        if li == 0:
          h = {nt: nn.relu(v) for nt, v in h.items()}
      return h

  batch0 = next(iter(loader))
  etypes = tuple(batch0.edge_index_dict.keys())
  model = BiSAGE(etypes)
  tx = optax.adam(3e-3)
  params = model.init(jax.random.key(0), batch0.x_dict,
                      batch0.edge_index_dict, batch0.edge_mask_dict)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      h = model.apply(p, batch.x_dict, batch.edge_index_dict,
                      batch.edge_mask_dict)
      eli = batch.metadata['edge_label_index']
      lab = jnp.minimum(batch.metadata['edge_label'], 1).astype(jnp.float32)
      mask = batch.metadata['edge_label_mask']
      eu = h[U][jnp.clip(eli[0], 0, h[U].shape[0] - 1)]
      ev = h[I][jnp.clip(eli[1], 0, h[I].shape[0] - 1)]
      logit = jnp.sum(eu * ev, axis=-1)
      ls = optax.sigmoid_binary_cross_entropy(logit, lab)
      w = (mask & (eli[0] >= 0) & (eli[1] >= 0)).astype(jnp.float32)
      return (ls * w).sum() / jnp.maximum(w.sum(), 1.0)
    loss, g = jax.value_and_grad(loss_fn)(params)
    upd, opt = tx.update(g, opt, params)
    return optax.apply_updates(params, upd), opt, loss

  for epoch in range(args.epochs):
    tot = cnt = 0
    for batch in loader:
      params, opt, loss = step(params, opt, batch)
      tot += float(loss)
      cnt += 1
    print(f'epoch {epoch}: link loss {tot / max(cnt, 1):.4f}')

  # full-type embeddings via node loaders, then rank held-out pairs
  @jax.jit
  def embed(params, batch):
    return model.apply(params, batch.x_dict, batch.edge_index_dict,
                       batch.edge_mask_dict)

  def all_embeddings(ntype, count):
    emb = np.zeros((count, hidden), np.float32)
    el = NeighborLoader(ds, [8, 8], (ntype, np.arange(count)),
                        batch_size=args.batch_size)
    for b in el:
      h = embed(params, b)
      seeds = np.asarray(b.batch_dict[ntype])
      valid = seeds >= 0
      sl = np.asarray(b.metadata['seed_local'])[valid]
      emb[seeds[valid]] = np.asarray(h[ntype])[sl]
    return emb

  uemb, iemb = all_embeddings(U, nu), all_embeddings(I, ni)
  pos_s = (uemb[urow[heldout]] * iemb[icol[heldout]]).sum(1)
  neg_s = (uemb[rng.integers(0, nu, len(heldout))]
           * iemb[rng.integers(0, ni, len(heldout))]).sum(1)
  auc = (pos_s[:, None] > neg_s[None, :]).mean()
  print(f'held-out interaction AUC: {auc:.4f}')


if __name__ == '__main__':
  main()
