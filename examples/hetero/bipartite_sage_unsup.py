"""Unsupervised SAGE on a bipartite user-item graph.

TPU counterpart of reference `examples/hetero/bipartite_sage_unsup.py`:
learn user/item embeddings from observed interactions with a
link-prediction objective, then rank held-out interactions.  The
reference drives a hetero LinkNeighborLoader; until the hetero link
loader lands here, the bipartite graph is homogenized with offset item
ids (item j -> nu + j) — the standard bipartite-to-homo embedding
construction, sampling and objective unchanged.

Usage::

    python examples/hetero/bipartite_sage_unsup.py [--epochs 5] [--cpu]
"""
import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))

import numpy as np


def synthetic(nu=2000, ni=400, taste=8, deg=10, seed=0):
  rng = np.random.default_rng(seed)
  ut = rng.integers(0, taste, nu)       # user taste group
  it = rng.integers(0, taste, ni)       # item taste group
  rows = np.repeat(np.arange(nu), deg)
  match = rng.random(nu * deg) < 0.8
  by_taste = [np.nonzero(it == t)[0] for t in range(taste)]
  cols = np.empty(nu * deg, np.int64)
  for t in range(taste):
    m = ut[rows] == t
    pool = by_taste[t] if len(by_taste[t]) else np.arange(ni)
    cols[m] = pool[rng.integers(0, len(pool), m.sum())]
  cols[~match] = rng.integers(0, ni, (~match).sum())
  return rows, cols, ut, it


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--epochs', type=int, default=10)
  ap.add_argument('--batch-size', type=int, default=512)
  ap.add_argument('--hidden', type=int, default=64)
  ap.add_argument('--cpu', action='store_true')
  args = ap.parse_args()

  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import optax
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.loader import LinkNeighborLoader
  from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                     make_unsupervised_step)
  from graphlearn_tpu.sampler import NegativeSampling

  urow, icol, ut, it = synthetic()
  nu, ni = len(ut), len(it)
  n = nu + ni
  d = 32
  rng = np.random.default_rng(2)
  # homogenized ids: users [0, nu), items [nu, nu+ni)
  rows = np.concatenate([urow, icol + nu])
  cols = np.concatenate([icol + nu, urow])       # symmetric interactions
  # weakly informative features: a faint taste direction in noise.
  proto = rng.normal(0, 1, (int(max(ut.max(), it.max())) + 1, d)
                     ).astype(np.float32)
  feats = (0.5 * np.concatenate([proto[ut], proto[it]])
           + rng.standard_normal((n, d)).astype(np.float32))

  # hold out 10% of interactions for ranking eval
  m = len(urow)
  perm = rng.permutation(m)
  heldout = perm[:m // 10]
  train = perm[m // 10:]
  tr = np.concatenate([urow[train], icol[train] + nu])
  tc = np.concatenate([icol[train] + nu, urow[train]])

  ds = (Dataset()
        .init_graph((tr, tc), layout='COO', num_nodes=n)
        .init_node_features(feats, split_ratio=1.0))
  loader = LinkNeighborLoader(
      ds, [8, 8], (urow[train], icol[train] + nu),
      neg_sampling=NegativeSampling('binary', 1.0),
      batch_size=args.batch_size, shuffle=True, seed=0)

  model = GraphSAGE(hidden_features=args.hidden, out_features=args.hidden,
                    num_layers=2)
  tx = optax.adam(3e-3)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(loader)), tx)
  step = make_unsupervised_step(apply_fn, tx)

  for epoch in range(args.epochs):
    tot = cnt = 0
    for batch in loader:
      state, loss = step(state, batch)
      tot += float(loss)
      cnt += 1
    print(f'epoch {epoch}: link loss {tot / max(cnt, 1):.4f}')

  # rank held-out pairs against random pairs
  from graphlearn_tpu.loader import NeighborLoader
  emb = np.zeros((n, args.hidden), np.float32)
  for batch in NeighborLoader(ds, [8, 8], np.arange(n),
                              batch_size=args.batch_size):
    e = apply_fn(state.params, batch.x, batch.edge_index, batch.edge_mask)
    seeds = np.asarray(batch.batch)
    valid = seeds >= 0
    sl = np.asarray(batch.metadata['seed_local'])[valid]
    emb[seeds[valid]] = np.asarray(e)[sl]
  hu, hi = urow[heldout], icol[heldout] + nu
  pos_s = (emb[hu] * emb[hi]).sum(1)
  ru = rng.integers(0, nu, len(heldout))
  ri = rng.integers(nu, n, len(heldout))
  neg_s = (emb[ru] * emb[ri]).sum(1)
  auc = (pos_s[:, None] > neg_s[None, :]).mean()
  print(f'held-out interaction AUC: {auc:.4f}')


if __name__ == '__main__':
  main()
