"""HGT on an ogbn-mag-style heterogeneous graph.

TPU counterpart of reference `examples/hetero/train_hgt_mag.py:102-121`:
hetero `Dataset` (paper/author/institution node types, cites/writes/
affiliated edge types + reversed), hetero `NeighborLoader` with
per-edge-type fanouts, HGT classifying papers.  Zero-egress stand-in
for MAG: a synthetic citation graph whose paper venue (label) is
recoverable from citation clusters.

Usage::

    python examples/hetero/train_hgt_mag.py [--epochs 4] [--cpu]
"""
import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))

import numpy as np

P, A, I = 'paper', 'author', 'institution'
CITES = (P, 'cites', P)
WRITES = (A, 'writes', P)
REV_WRITES = (P, 'rev_writes', A)
AFFIL = (A, 'affiliated_with', I)
REV_AFFIL = (I, 'rev_affiliated_with', A)


def synthetic(npaper=2000, nauthor=800, ninst=40, classes=8, d=32, seed=0):
  rng = np.random.default_rng(seed)
  venue = rng.integers(0, classes, npaper)
  order = np.argsort(venue, kind='stable')
  ptr = np.searchsorted(venue[order], np.arange(classes + 1))

  def same_venue_targets(src_venue):
    out = np.empty(len(src_venue), np.int64)
    for c in range(classes):
      m = src_venue == c
      out[m] = order[rng.integers(ptr[c], ptr[c + 1], m.sum())]
    return out

  # papers cite papers of the same venue (mostly)
  crow = np.repeat(np.arange(npaper), 4)
  ccol = np.where(rng.random(npaper * 4) < 0.8,
                  same_venue_targets(venue[crow]),
                  rng.integers(0, npaper, npaper * 4))
  # authors write within one home venue
  avenue = rng.integers(0, classes, nauthor)
  wrow = np.repeat(np.arange(nauthor), 3)
  wcol = same_venue_targets(avenue[wrow])
  # authors affiliated with institutions
  arow = np.arange(nauthor)
  acol = rng.integers(0, ninst, nauthor)

  # weakly informative paper features: a faint venue direction in
  # noise (ogbn-mag's word2vec features carry topic signal likewise).
  proto = rng.normal(0, 1, (classes, d)).astype(np.float32)
  feats = {P: (0.5 * proto[venue]
               + rng.standard_normal((npaper, d)).astype(np.float32)),
           A: rng.standard_normal((nauthor, d)).astype(np.float32),
           I: rng.standard_normal((ninst, d)).astype(np.float32)}
  edges = {CITES: (crow, ccol), WRITES: (wrow, wcol),
           REV_WRITES: (wcol, wrow), AFFIL: (arow, acol),
           REV_AFFIL: (acol, arow)}
  nnodes = {P: npaper, A: nauthor, I: ninst}
  return edges, feats, nnodes, venue.astype(np.int32)


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--epochs', type=int, default=4)
  ap.add_argument('--batch-size', type=int, default=256)
  ap.add_argument('--hidden', type=int, default=64)
  ap.add_argument('--heads', type=int, default=2)
  ap.add_argument('--cpu', action='store_true')
  args = ap.parse_args()

  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  import optax
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.loader import NeighborLoader
  from graphlearn_tpu.models import HGT

  edges, feats, nnodes, venue = synthetic()
  npaper, classes = len(venue), int(venue.max()) + 1
  ds = (Dataset()
        .init_graph(edges, layout='COO', num_nodes=nnodes)
        .init_node_features(feats, split_ratio=1.0)
        .init_node_labels({P: venue}))

  idx = np.random.default_rng(1).permutation(npaper)
  train_idx, test_idx = idx[:int(npaper * 0.8)], idx[int(npaper * 0.8):]
  bs = args.batch_size
  loader = NeighborLoader(ds, [4, 4], (P, train_idx), batch_size=bs,
                          shuffle=True, seed=0)
  test_loader = NeighborLoader(ds, [4, 4], (P, test_idx), batch_size=bs)

  batch0 = next(iter(loader))
  etypes = tuple(batch0.edge_index_dict.keys())
  model = HGT(ntypes=(P, A, I), etypes=etypes,
              hidden_features=args.hidden, out_features=classes,
              num_layers=2, heads=args.heads, target_ntype=P)
  tx = optax.adam(1e-3)
  params = model.init(jax.random.key(0), batch0.x_dict,
                      batch0.edge_index_dict, batch0.edge_mask_dict)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      logits = model.apply(p, batch.x_dict, batch.edge_index_dict,
                           batch.edge_mask_dict)
      y = batch.y_dict[P][:bs]
      valid = (batch.batch_dict[P] >= 0).astype(logits.dtype)
      ce = optax.softmax_cross_entropy_with_integer_labels(logits[:bs], y)
      return (ce * valid).sum() / jnp.maximum(valid.sum(), 1.0)
    loss, g = jax.value_and_grad(loss_fn)(params)
    upd, opt = tx.update(g, opt, params)
    return optax.apply_updates(params, upd), opt, loss

  @jax.jit
  def logits_fn(params, batch):
    return model.apply(params, batch.x_dict, batch.edge_index_dict,
                       batch.edge_mask_dict)

  for epoch in range(args.epochs):
    tot = cnt = 0
    for batch in loader:
      params, opt, loss = step(params, opt, batch)
      tot += float(loss)
      cnt += 1
    print(f'epoch {epoch}: loss {tot / max(cnt, 1):.4f}')

  correct = total = 0
  for batch in test_loader:
    pred = np.argmax(np.asarray(logits_fn(params, batch))[:bs], axis=1)
    seeds = np.asarray(batch.batch_dict[P])
    valid = seeds >= 0
    correct += int((pred[valid] == np.asarray(batch.y_dict[P][:bs])[valid])
                   .sum())
    total += int(valid.sum())
  print(f'test acc: {correct / max(total, 1):.4f}')


if __name__ == '__main__':
  main()
