"""HGT on an ogbn-mag-style heterogeneous graph.

TPU counterpart of reference `examples/hetero/train_hgt_mag.py:102-121`:
hetero `Dataset` (paper/author/institution node types, cites/writes/
affiliated edge types + reversed), hetero `NeighborLoader` with
per-edge-type fanouts, HGT classifying papers.  Zero-egress stand-in
for MAG: a synthetic citation graph whose paper venue (label) is
recoverable from citation clusters.

Usage::

    python examples/hetero/train_hgt_mag.py [--epochs 4] [--cpu]
    python examples/hetero/train_hgt_mag.py --data mag.npz \
        [--expect-acc 0.4]     # real ogbn-mag export

The ``.npz`` schema is a straight ogbn-mag export — from a torch
environment::

    from ogb.nodeproppred import NodePropPredDataset
    dataset = NodePropPredDataset('ogbn-mag')
    d, labels = dataset[0]
    split = dataset.get_idx_split()
    np.savez('mag.npz',
             cites=d['edge_index_dict'][('paper', 'cites', 'paper')],
             writes=d['edge_index_dict'][('author', 'writes', 'paper')],
             affiliated=d['edge_index_dict'][
                 ('author', 'affiliated_with', 'institution')],
             paper_feat=d['node_feat_dict']['paper'],
             labels=labels['paper'],
             num_author=d['num_nodes_dict']['author'],
             num_institution=d['num_nodes_dict']['institution'],
             train_idx=split['train']['paper'],
             test_idx=split['test']['paper'])

Author/institution features are absent in MAG; this example feeds
ZEROS, so those nodes are indistinguishable at the input layer and
contribute only through structure (aggregated paper signal).  The
reference example gets further by precomputing metapath2vec features;
export richer `author_feat`/`inst_feat` columns (and extend
`load_mag_npz`) to match that recipe — set ``--expect-acc``
accordingly.
"""
import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))

import numpy as np

P, A, I = 'paper', 'author', 'institution'
CITES = (P, 'cites', P)
WRITES = (A, 'writes', P)
REV_WRITES = (P, 'rev_writes', A)
AFFIL = (A, 'affiliated_with', I)
REV_AFFIL = (I, 'rev_affiliated_with', A)


def synthetic(npaper=2000, nauthor=800, ninst=40, classes=8, d=32, seed=0):
  rng = np.random.default_rng(seed)
  venue = rng.integers(0, classes, npaper)
  order = np.argsort(venue, kind='stable')
  ptr = np.searchsorted(venue[order], np.arange(classes + 1))

  def same_venue_targets(src_venue):
    out = np.empty(len(src_venue), np.int64)
    for c in range(classes):
      m = src_venue == c
      out[m] = order[rng.integers(ptr[c], ptr[c + 1], m.sum())]
    return out

  # papers cite papers of the same venue (mostly)
  crow = np.repeat(np.arange(npaper), 4)
  ccol = np.where(rng.random(npaper * 4) < 0.8,
                  same_venue_targets(venue[crow]),
                  rng.integers(0, npaper, npaper * 4))
  # authors write within one home venue
  avenue = rng.integers(0, classes, nauthor)
  wrow = np.repeat(np.arange(nauthor), 3)
  wcol = same_venue_targets(avenue[wrow])
  # authors affiliated with institutions
  arow = np.arange(nauthor)
  acol = rng.integers(0, ninst, nauthor)

  # weakly informative paper features: a faint venue direction in
  # noise (ogbn-mag's word2vec features carry topic signal likewise).
  proto = rng.normal(0, 1, (classes, d)).astype(np.float32)
  feats = {P: (0.5 * proto[venue]
               + rng.standard_normal((npaper, d)).astype(np.float32)),
           A: rng.standard_normal((nauthor, d)).astype(np.float32),
           I: rng.standard_normal((ninst, d)).astype(np.float32)}
  edges = {CITES: (crow, ccol), WRITES: (wrow, wcol),
           REV_WRITES: (wcol, wrow), AFFIL: (arow, acol),
           REV_AFFIL: (acol, arow)}
  nnodes = {P: npaper, A: nauthor, I: ninst}
  return edges, feats, nnodes, venue.astype(np.int32)


def load_mag_npz(path):
  """Real ogbn-mag export (schema in the module docstring) -> the same
  (edges, feats, nnodes, labels, splits) shape as `synthetic`."""
  d = np.load(path)            # lazy NpzFile: arrays load on access
  cites = np.asarray(d['cites'], np.int64)
  writes = np.asarray(d['writes'], np.int64)
  affil = np.asarray(d['affiliated'], np.int64)
  labels = np.asarray(d['labels']).reshape(-1).astype(np.int32)
  pf = np.asarray(d['paper_feat'], np.float32)
  npaper = pf.shape[0]
  na, ni = int(d['num_author']), int(d['num_institution'])
  feats = {P: pf,
           A: np.zeros((na, pf.shape[1]), np.float32),
           I: np.zeros((ni, pf.shape[1]), np.float32)}
  edges = {CITES: (cites[0], cites[1]),
           WRITES: (writes[0], writes[1]),
           REV_WRITES: (writes[1], writes[0]),
           AFFIL: (affil[0], affil[1]),
           REV_AFFIL: (affil[1], affil[0])}
  nnodes = {P: npaper, A: na, I: ni}
  splits = (np.asarray(d['train_idx']).reshape(-1),
            np.asarray(d['test_idx']).reshape(-1))
  return edges, feats, nnodes, labels, splits


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--data', type=str, default=None,
                  help='real ogbn-mag .npz export (docstring schema)')
  ap.add_argument('--epochs', type=int, default=4)
  ap.add_argument('--batch-size', type=int, default=256)
  ap.add_argument('--hidden', type=int, default=64)
  ap.add_argument('--heads', type=int, default=2)
  ap.add_argument('--split-ratio', type=float, default=1.0)
  ap.add_argument('--expect-acc', type=float, default=None,
                  help='fail (exit 1) below this test accuracy — the '
                       'acceptance check on real data')
  ap.add_argument('--fused', action='store_true',
                  help='train each epoch as ONE fused lax.scan program '
                       '(loader.FusedHeteroEpoch; needs '
                       '--split-ratio 1.0)')
  ap.add_argument('--cpu', action='store_true')
  args = ap.parse_args()

  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  import optax
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.loader import NeighborLoader
  from graphlearn_tpu.models import HGT

  if args.data:
    edges, feats, nnodes, venue, (train_idx, test_idx) = load_mag_npz(
        args.data)
  else:
    edges, feats, nnodes, venue = synthetic()
    train_idx = test_idx = None
  npaper, classes = len(venue), int(venue.max()) + 1
  ds = (Dataset()
        .init_graph(edges, layout='COO', num_nodes=nnodes)
        .init_node_features(feats, split_ratio=args.split_ratio)
        .init_node_labels({P: venue}))

  if train_idx is None:
    idx = np.random.default_rng(1).permutation(npaper)
    train_idx, test_idx = (idx[:int(npaper * 0.8)],
                           idx[int(npaper * 0.8):])
  bs = args.batch_size
  loader = NeighborLoader(ds, [4, 4], (P, train_idx), batch_size=bs,
                          shuffle=True, seed=0)
  test_loader = NeighborLoader(ds, [4, 4], (P, test_idx), batch_size=bs)

  batch0 = next(iter(loader))
  etypes = tuple(batch0.edge_index_dict.keys())
  model = HGT(ntypes=(P, A, I), etypes=etypes,
              hidden_features=args.hidden, out_features=classes,
              num_layers=2, heads=args.heads, target_ntype=P)
  tx = optax.adam(1e-3)
  params = model.init(jax.random.key(0), batch0.x_dict,
                      batch0.edge_index_dict, batch0.edge_mask_dict)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      logits = model.apply(p, batch.x_dict, batch.edge_index_dict,
                           batch.edge_mask_dict)
      y = batch.y_dict[P][:bs]
      valid = (batch.batch_dict[P] >= 0).astype(logits.dtype)
      ce = optax.softmax_cross_entropy_with_integer_labels(logits[:bs], y)
      return (ce * valid).sum() / jnp.maximum(valid.sum(), 1.0)
    loss, g = jax.value_and_grad(loss_fn)(params)
    upd, opt = tx.update(g, opt, params)
    return optax.apply_updates(params, upd), opt, loss

  @jax.jit
  def logits_fn(params, batch):
    return model.apply(params, batch.x_dict, batch.edge_index_dict,
                       batch.edge_mask_dict)

  fused = None
  if args.fused:
    import jax.numpy as jnp
    from graphlearn_tpu.loader import FusedHeteroEpoch
    from graphlearn_tpu.models.train import TrainState
    fused = FusedHeteroEpoch(ds, [4, 4], (P, train_idx), model.apply,
                             tx, batch_size=bs, shuffle=True, seed=0,
                             remat=True)
    fstate = TrainState(params, opt, jnp.zeros((), jnp.int32))

  for epoch in range(args.epochs):
    if fused is not None:
      fstate, stats = fused.run(fstate)
      print(f'epoch {epoch}: loss {stats["loss"]:.4f}')
      params = fstate.params
      continue
    tot = cnt = 0
    for batch in loader:
      params, opt, loss = step(params, opt, batch)
      tot += float(loss)
      cnt += 1
    print(f'epoch {epoch}: loss {tot / max(cnt, 1):.4f}')

  if fused is not None:
    acc = fused.evaluate(params, test_idx)
  else:
    correct = total = 0
    for batch in test_loader:
      pred = np.argmax(np.asarray(logits_fn(params, batch))[:bs], axis=1)
      seeds = np.asarray(batch.batch_dict[P])
      valid = seeds >= 0
      correct += int((pred[valid]
                      == np.asarray(batch.y_dict[P][:bs])[valid]).sum())
      total += int(valid.sum())
    acc = correct / max(total, 1)
  print(f'test acc: {acc:.4f}')
  if args.expect_acc is not None and acc < args.expect_acc:
    raise SystemExit(
        f'test accuracy {acc:.4f} below required {args.expect_acc}')


if __name__ == '__main__':
  main()
