"""HGT trained from heterogeneous host sampling subprocesses.

The hetero host-runtime path end-to-end: a `HostHeteroDataset` is
inherited copy-on-write by a pool of sampling workers
(`MpDistSamplingWorkerOptions`), each running the native per-type
inducer engine (`HostHeteroNeighborSampler`); ragged messages cross
the shm channel and collate into static-shape `HeteroBatch`es that
feed the same HGT training step as the single-chip example.

Reference counterpart: `examples/hetero/train_hgt_mag_mp.py` (hetero
loading through mp sampling workers feeding the trainer).

Usage::

    python examples/hetero/dist_hgt_mp.py [--epochs 4] [--workers 2]
"""
import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))

import numpy as np

from examples.hetero.train_hgt_mag import A, I, P, synthetic


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--epochs', type=int, default=4)
  ap.add_argument('--batch-size', type=int, default=256)
  ap.add_argument('--hidden', type=int, default=64)
  ap.add_argument('--heads', type=int, default=2)
  ap.add_argument('--workers', type=int, default=2)
  ap.add_argument('--cpu', action='store_true')
  args = ap.parse_args()

  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  import optax
  from graphlearn_tpu.distributed import (DistNeighborLoader,
                                          HostHeteroDataset,
                                          MpDistSamplingWorkerOptions)
  from graphlearn_tpu.models import HGT

  edges, feats, nnodes, venue = synthetic()
  npaper, classes = len(venue), int(venue.max()) + 1
  ds = HostHeteroDataset.from_coo(edges, num_nodes_dict=nnodes,
                                  node_features=feats,
                                  node_labels={P: venue})

  idx = np.random.default_rng(1).permutation(npaper)
  train_idx, test_idx = idx[:int(npaper * 0.8)], idx[int(npaper * 0.8):]
  bs = args.batch_size
  opts = MpDistSamplingWorkerOptions(num_workers=args.workers)
  loader = DistNeighborLoader(ds, [4, 4], (P, train_idx), batch_size=bs,
                              shuffle=True, seed=0, worker_options=opts)
  # evaluation reuses the collocated (in-process) mode
  test_loader = DistNeighborLoader(ds, [4, 4], (P, test_idx),
                                   batch_size=bs)

  batch0 = next(iter(loader))
  etypes = tuple(batch0.edge_index_dict.keys())
  model = HGT(ntypes=(P, A, I), etypes=etypes,
              hidden_features=args.hidden, out_features=classes,
              num_layers=2, heads=args.heads, target_ntype=P)
  tx = optax.adam(1e-3)
  params = model.init(jax.random.key(0), batch0.x_dict,
                      batch0.edge_index_dict, batch0.edge_mask_dict)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      logits = model.apply(p, batch.x_dict, batch.edge_index_dict,
                           batch.edge_mask_dict)
      y = batch.y_dict[P][:bs]
      valid = (batch.batch_dict[P] >= 0).astype(logits.dtype)
      ce = optax.softmax_cross_entropy_with_integer_labels(logits[:bs], y)
      return (ce * valid).sum() / jnp.maximum(valid.sum(), 1.0)
    loss, g = jax.value_and_grad(loss_fn)(params)
    upd, opt = tx.update(g, opt, params)
    return optax.apply_updates(params, upd), opt, loss

  @jax.jit
  def logits_fn(params, batch):
    return model.apply(params, batch.x_dict, batch.edge_index_dict,
                       batch.edge_mask_dict)

  try:
    for epoch in range(args.epochs):
      tot = cnt = 0
      for batch in loader:
        params, opt, loss = step(params, opt, batch)
        tot += float(loss)
        cnt += 1
      print(f'epoch {epoch}: loss {tot / max(cnt, 1):.4f}')
  finally:
    loader.shutdown()

  correct = total = 0
  for batch in test_loader:
    pred = np.argmax(np.asarray(logits_fn(params, batch))[:bs], axis=1)
    seeds = np.asarray(batch.batch_dict[P])
    valid = seeds >= 0
    correct += int((pred[valid]
                    == np.asarray(batch.y_dict[P][:bs])[valid]).sum())
    total += int(valid.sum())
  print(f'test acc: {correct / max(total, 1):.4f}')


if __name__ == '__main__':
  main()
