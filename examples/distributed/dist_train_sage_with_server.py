"""Distributed GraphSAGE — server-client deployment mode.

TPU counterpart of reference `examples/distributed/
dist_train_sage_supervised_with_server.py:54-150`: dedicated sampling
*server* processes own the dataset and run producer pools; training
*client* processes (the TPU hosts) pull ready-made sample messages over
sockets through a prefetching `RemoteReceivingChannel` and spend their
cycles on model compute only.

This launcher runs both roles as local processes (the SURVEY §4
all-local pattern); on a real deployment run the two blocks on
different hosts with real addresses.

``--partitioned`` shows the r3 cross-server tier: the graph is
partitioned offline, every server owns ONE shard (not a full copy),
and producers fan each hop / feature lookup out to peer servers over
RPC (`HostSamplingConfig.peer_addrs` -> `HostDistNeighborSampler`) —
the reference's `_sample_one_hop` remote path
(`dist_neighbor_sampler.py:542-598`).

Usage::

    python examples/distributed/dist_train_sage_with_server.py \
        [--num-servers 2] [--epochs 2] [--partitioned]
"""
import argparse
import multiprocessing as mp
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))

import numpy as np


from examples._synthetic import clustered_graph


def synthetic(n):
  return clustered_graph(n=n)


def run_server(rank, num_servers, port_q, n, partition_dir=None):
  """One sampling host (reference `init_server` +
  `wait_and_shutdown_server`, `dist_server.py:158-211`).  With
  ``partition_dir`` the server owns ONE shard and also serves its
  partition to peers (auto-registered `PartitionService`)."""
  sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))
  from graphlearn_tpu.distributed import (HostDataset, init_server,
                                          wait_and_shutdown_server)
  if partition_dir is not None:
    ds = HostDataset.from_partition_dir(partition_dir, rank)
  else:
    rows, cols, feats, labels = synthetic(n)
    ds = HostDataset.from_coo(rows, cols, n, node_features=feats,
                              node_labels=labels)
  srv = init_server(num_servers=num_servers, num_clients=1, rank=rank,
                    dataset=ds, host='127.0.0.1', port=0)
  port_q.put((rank, srv.port))
  wait_and_shutdown_server(timeout=600)


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-servers', type=int, default=2)
  ap.add_argument('--epochs', type=int, default=2)
  ap.add_argument('--batch-size', type=int, default=128)
  ap.add_argument('--fanout', type=int, nargs='+', default=[10, 5])
  ap.add_argument('--hidden', type=int, default=64)
  ap.add_argument('--num-nodes', type=int, default=4096)
  ap.add_argument('--partitioned', action='store_true',
                  help='each server owns ONE shard; hops/features fan '
                       'out to peer servers over RPC (r3 cross-server '
                       'tier) instead of every server holding a full '
                       'graph copy')
  args = ap.parse_args()
  n = args.num_nodes

  partition_dir = None
  if args.partitioned:
    import tempfile
    from graphlearn_tpu.partition import RandomPartitioner
    rows, cols, feats, labels = synthetic(n)
    partition_dir = tempfile.mkdtemp(prefix='glt_parts_')
    RandomPartitioner(partition_dir, args.num_servers, n, (rows, cols),
                      node_feat=feats, node_label=labels,
                      seed=0).partition()

  ctx = mp.get_context('forkserver')
  port_q = ctx.Queue()
  servers = [ctx.Process(target=run_server,
                         args=(r, args.num_servers, port_q, n,
                               partition_dir),
                         daemon=False)
             for r in range(args.num_servers)]
  for p in servers:
    p.start()
  ports = dict(port_q.get(timeout=60) for _ in servers)

  # ---- client (the TPU host) ------------------------------------------
  import jax
  import optax
  from graphlearn_tpu.distributed import (
      DistNeighborLoader, HostSamplingConfig,
      RemoteDistSamplingWorkerOptions, init_client, shutdown_client)
  from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                     make_supervised_step)

  addrs = [('127.0.0.1', ports[r]) for r in range(args.num_servers)]
  init_client(addrs, rank=0, num_clients=1)
  cfg = (HostSamplingConfig(sampling_type='node',
                            peer_addrs=tuple(addrs))
         if args.partitioned else None)
  loader = DistNeighborLoader(
      None, args.fanout, np.arange(n), batch_size=args.batch_size,
      shuffle=True,
      worker_options=RemoteDistSamplingWorkerOptions(
          server_rank=list(range(args.num_servers)), num_workers=2,
          prefetch_size=4),
      sampling_config=cfg, seed=0)

  model = GraphSAGE(hidden_features=args.hidden, out_features=8,
                    num_layers=2)
  tx = optax.adam(1e-3)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(loader)), tx)
  step = make_supervised_step(apply_fn, tx, args.batch_size)

  for epoch in range(args.epochs):
    t0 = time.perf_counter()
    tot = cnt = 0
    for batch in loader:
      state, loss, _ = step(state, batch)
      tot += float(loss)
      cnt += 1
    print(f'epoch {epoch}: loss {tot / max(cnt, 1):.4f} '
          f'({time.perf_counter() - t0:.2f}s, {cnt} steps, '
          f'{args.num_servers} sampling servers)')

  loader.shutdown()
  shutdown_client()            # client-0 tells every server to exit
  for p in servers:
    p.join(timeout=30)
  if partition_dir is not None:
    import shutil
    shutil.rmtree(partition_dir, ignore_errors=True)
  print('done')


if __name__ == '__main__':
  main()
