"""Offline dataset partitioning for distributed training.

TPU counterpart of reference `examples/distributed/
partition_ogbn_dataset.py`: run once before launching the trainers;
writes the on-disk layout that `parallel.DistDataset` /
`partition.load_partition` consume.  Supports random and
frequency-based (hotness) partitioning — the latter samples with the
training fanout to estimate per-partition access probabilities and
co-locates + caches hot rows (reference `FrequencyPartitioner`).

Usage::

    python examples/distributed/partition_dataset.py \
        --out /tmp/parts --num-parts 4 [--frequency] [--data graph.npz]

``--mesh-demo`` additionally bridges the offline assignment into the
MESH plane: the written ``node_pb.npy`` is fed straight to
``DistDataset.from_full_graph(partitioner=node_pb)`` — the same
placement then drives the collective-exchange sampler, and the demo
prints its edge-cut against the mesh plane's own ``range`` and
``locality`` partitioners (``GLT_PARTITIONER`` selects those at
dataset build, no offline step needed).
"""
import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))

import numpy as np


from examples._synthetic import clustered_graph


def synthetic():
  # same construction as the training examples, so
  # `dist_train_sage.py --partition-dir` demonstrably learns on the
  # partitioned output
  return clustered_graph(n=20000, d=64, classes=16)


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--out', required=True)
  ap.add_argument('--num-parts', type=int, default=4)
  ap.add_argument('--data', type=str, default=None,
                  help='.npz with rows, cols, feats, labels')
  ap.add_argument('--frequency', action='store_true',
                  help='hotness-driven partitioning + feature caching')
  ap.add_argument('--cache-ratio', type=float, default=0.1)
  ap.add_argument('--fanout', type=int, nargs='+', default=[15, 10, 5])
  ap.add_argument('--mesh-demo', action='store_true',
                  help='after partitioning, build the mesh-plane '
                       'DistDataset from the written node_pb (both '
                       'planes share one placement) and print its '
                       'edge-cut vs the in-memory range/locality '
                       'partitioners')
  args = ap.parse_args()

  if args.data:
    d = dict(np.load(args.data))
    rows, cols, feats, labels = (d['rows'], d['cols'], d['feats'],
                                 d['labels'])
  else:
    rows, cols, feats, labels = synthetic()
  n = feats.shape[0]

  if args.frequency:
    # hotness: per-partition visit probability under the training
    # fanout (reference `NeighborSampler.sample_prob` ->
    # `FrequencyPartitioner`, SURVEY §3.5)
    from graphlearn_tpu.data import Dataset
    from graphlearn_tpu.partition import FrequencyPartitioner
    from graphlearn_tpu.sampler import NeighborSampler
    ds = Dataset().init_graph((rows, cols), layout='COO', num_nodes=n)
    sampler = NeighborSampler(ds.get_graph(), args.fanout, seed=0)
    seed_groups = [np.arange(n)[p::args.num_parts]
                   for p in range(args.num_parts)]
    probs = np.stack([np.asarray(sampler.sample_prob(g, n))
                      for g in seed_groups])
    p = FrequencyPartitioner(
        args.out, args.num_parts, n, (rows, cols), feats, labels,
        probs=probs, cache_ratio=args.cache_ratio)
  else:
    from graphlearn_tpu.partition import RandomPartitioner
    p = RandomPartitioner(args.out, args.num_parts, n, (rows, cols),
                          feats, labels, cache_ratio=args.cache_ratio)
  p.partition()
  pb = np.load(Path(args.out) / 'node_pb.npy')
  sizes = [int((pb == i).sum()) for i in range(args.num_parts)]
  print(f'wrote {args.num_parts} partitions to {args.out}; '
        f'sizes {sizes}')

  if args.mesh_demo:
    # offline -> mesh bridge (ISSUE 20): the SAME node_pb drives the
    # collective-exchange plane.  An explicit array short-circuits the
    # partitioner selection, so the offline FrequencyPartitioner's
    # hotness-aware placement carries over 1:1 (batches still surface
    # original ids via old2new/new2old).
    from graphlearn_tpu.parallel import DistDataset
    from graphlearn_tpu.parallel.locality import (edge_cut_frac,
                                                  locality_partition)
    ds = DistDataset.from_full_graph(args.num_parts, rows, cols,
                                     node_feat=feats, node_label=labels,
                                     num_nodes=n, partitioner=pb)
    pb_loc, _ = locality_partition(rows, cols, n, args.num_parts)
    rng = np.random.default_rng(0)
    pb_rand = rng.integers(0, args.num_parts, n).astype(np.int32)
    print(f'mesh-plane dataset: partitioner={ds.partitioner}, '
          f'{ds.num_partitions} shards, '
          f'bounds={np.diff(ds.graph.bounds).tolist()}')
    for name, assign in (('offline', pb), ('locality', pb_loc),
                         ('random', pb_rand)):
      print(f'  edge_cut[{name}] = '
            f'{edge_cut_frac(rows, cols, assign):.4f}')


if __name__ == '__main__':
  main()
