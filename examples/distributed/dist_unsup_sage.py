"""Distributed UNSUPERVISED GraphSAGE over the device mesh.

The distributed twin of `examples/unsup_sage_ppi.py` (reference
`examples/graph_sage_unsup_ppi.py`), built on the mesh link engine:
seed edges split across devices, strict negatives drawn collectively
(`dist_edge_exists` over the sharded CSR), endpoint neighborhoods
expanded with all_to_all exchanges, and the binary link loss trained
data-parallel with pmean gradients.

Run on the 8-device virtual CPU mesh::

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/distributed/dist_unsup_sage.py
"""
import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))

import numpy as np


def synthetic(n=2000, clusters=8, deg=6, d=32, seed=0):
  """Clustered graph: edges mostly intra-cluster, features noisy."""
  rng = np.random.default_rng(seed)
  cl = np.arange(n) % clusters
  rows = np.repeat(np.arange(n), deg)
  same = np.where(rng.random(n * deg) < 0.85,
                  (rows + clusters * rng.integers(1, n // clusters,
                                                  n * deg)) % n,
                  rng.integers(0, n, n * deg))
  # faint cluster direction in noisy features (the structural signal
  # alone is weak for a dot-product objective on random features)
  proto = rng.normal(0, 1, (clusters, d)).astype(np.float32)
  feats = (0.3 * proto[cl]
           + rng.standard_normal((n, d)).astype(np.float32))
  return rows, same, feats, cl


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--epochs', type=int, default=4)
  ap.add_argument('--batch-size', type=int, default=32)
  args = ap.parse_args()

  import jax
  import optax
  from graphlearn_tpu.models import GraphSAGE
  from graphlearn_tpu.models.train import TrainState
  from graphlearn_tpu.parallel import (DistDataset, DistLinkNeighborLoader,
                                       make_dp_unsupervised_step,
                                       make_mesh, replicate)

  n_dev = len(jax.devices())
  mesh = make_mesh(n_dev)
  rows, cols, feats, cl = synthetic()
  n = len(cl)
  dds = DistDataset.from_full_graph(n_dev, rows, cols, node_feat=feats,
                                    num_nodes=n)
  loader = DistLinkNeighborLoader(
      dds, [5, 5], (rows, cols), neg_sampling='binary',
      batch_size=args.batch_size, shuffle=True, mesh=mesh, seed=0)

  model = GraphSAGE(hidden_features=64, out_features=32, num_layers=2)
  tx = optax.adam(1e-3)
  batch0 = next(iter(loader))
  single = jax.tree_util.tree_map(lambda v: v[0], batch0)
  params = model.init(jax.random.key(0), single.x, single.edge_index,
                      single.edge_mask)
  state = replicate(TrainState(params, tx.init(params), 0), mesh)
  step = make_dp_unsupervised_step(model.apply, tx, mesh)

  for epoch in range(args.epochs):
    t0 = time.monotonic()
    tot = cnt = 0
    for batch in loader:
      state, loss = step(state, batch)
      tot += float(loss)
      cnt += 1
    print(f'epoch {epoch}: link loss {tot / max(cnt, 1):.4f} '
          f'({time.monotonic() - t0:.2f}s, {cnt} steps x {n_dev} devices)')

  # embedding quality probe: intra-cluster pairs should score higher
  # than random pairs under the trained dot-product model
  # embed every node through a full-neighborhood batch per device slice
  from graphlearn_tpu.parallel import DistNeighborLoader
  nl = DistNeighborLoader(dds, [5, 5], np.arange(n),
                          batch_size=64, mesh=mesh)
  emb = np.zeros((n, 32), np.float32)
  new2old = dds.new2old
  for batch in nl:
    out = jax.vmap(
        lambda x, ei, em: model.apply(state.params, x, ei, em))(
        batch.x, batch.edge_index, batch.edge_mask)
    seeds = np.asarray(batch.batch)
    for p in range(seeds.shape[0]):
      v = seeds[p] >= 0
      emb[new2old[seeds[p][v]]] = np.asarray(out[p][:seeds.shape[1]])[v]
  rng = np.random.default_rng(1)
  a = rng.integers(0, n, 2000)
  b = rng.integers(0, n, 2000)
  same_cl = (cl[a] == cl[b])
  score = (emb[a] * emb[b]).sum(1)
  pos, neg = score[same_cl], score[~same_cl]
  auc = (pos[:, None] > neg[None, :]).mean()
  print(f'intra-vs-inter cluster AUC: {auc:.4f}')


if __name__ == '__main__':
  main()
