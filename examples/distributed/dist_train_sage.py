"""Distributed supervised GraphSAGE — worker (collocated) mode.

TPU counterpart of reference `examples/distributed/
dist_train_sage_supervised.py`: the graph is partitioned across the
device mesh, every chip samples its own seed shard with cross-partition
neighbor exchange riding ICI collectives (`parallel.DistNeighborSampler`
— the `_sample_one_hop` + stitch dance as all-to-all instead of RPC),
and the train step is data-parallel with psum-averaged gradients.
Host-side mp sampling producers (the reference's sampling subprocess
pool) are the orthogonal pipeline knob — see
`dist_train_sage_with_server.py` for that plane.

Runs on a real TPU slice, or anywhere via the virtual CPU mesh::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/distributed/dist_train_sage.py --num-parts 8

With a pre-partitioned dataset (see `partition_dataset.py`)::

    python examples/distributed/dist_train_sage.py --partition-dir /tmp/parts
"""
import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))

import numpy as np


from examples._synthetic import clustered_graph as synthetic


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-parts', type=int, default=None,
                  help='mesh size; default = all local devices')
  ap.add_argument('--partition-dir', type=str, default=None)
  ap.add_argument('--epochs', type=int, default=4)
  ap.add_argument('--batch-size', type=int, default=128,
                  help='per-device seed batch')
  ap.add_argument('--fanout', type=int, nargs='+', default=[10, 5])
  ap.add_argument('--hidden', type=int, default=64)
  ap.add_argument('--tree', action='store_true',
                  help='TREE-layout fused mesh epochs '
                       '(parallel.FusedDistTreeEpoch + TreeSAGE): the '
                       'scatter-free/sort-free flagship, distributed '
                       '— measured 3.9x the subgraph fused rate on '
                       'the 8-device CPU mesh (r5)')
  ap.add_argument('--fused', action='store_true',
                  help='train each epoch as ONE SPMD lax.scan program '
                       '(parallel.FusedDistEpoch; non-tiered stores, '
                       'static exchange slack)')
  ap.add_argument('--split-ratio', type=float, default=1.0,
                  help='< 1 tiers the feature store: hottest rows per '
                       'shard in HBM, the rest in host DRAM (cold '
                       'overlay per batch) — serves tables beyond '
                       'aggregate HBM')
  ap.add_argument('--host-local', action='store_true',
                  help='with --partition-dir on a multi-host mesh: '
                       'materialize only THIS process\'s partitions '
                       '(tiered cold rows stay owner-side, edge '
                       'features and the offline cache plan are '
                       'served host-locally)')
  args = ap.parse_args()
  if args.tree and args.fused:
    ap.error('--tree and --fused are mutually exclusive')

  import jax
  import optax
  from graphlearn_tpu.models import GraphSAGE, create_train_state
  from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                       make_dp_supervised_step, make_mesh,
                                       replicate)

  num_parts = args.num_parts or len(jax.devices())
  mesh = make_mesh(num_parts)

  if args.partition_dir:
    from graphlearn_tpu.parallel import multihost
    ds = DistDataset.from_partition_dir(
        args.partition_dir, num_parts, split_ratio=args.split_ratio,
        host_parts=(multihost.host_partition_ids(mesh)
                    if args.host_local else None))
  else:
    rows, cols, feats, labels = synthetic()
    ds = DistDataset.from_full_graph(num_parts, rows, cols,
                                     node_feat=feats, node_label=labels,
                                     num_nodes=len(labels),
                                     split_ratio=args.split_ratio)
  assert ds.node_labels is not None, 'training needs labels'
  n = ds.graph.num_nodes
  # host-local shards see only local labels: the class count (and so
  # the model width) must agree GLOBALLY across processes
  from graphlearn_tpu.parallel import multihost
  num_classes = multihost.global_max(
      int(np.max(np.asarray(ds.node_labels))), mesh) + 1

  bs = args.batch_size
  tx = optax.adam(1e-3)

  if args.tree:
    # the tree path needs none of the per-batch loader/model setup
    from graphlearn_tpu.models import TreeSAGE
    from graphlearn_tpu.parallel import FusedDistTreeEpoch
    tmodel = TreeSAGE(hidden_features=args.hidden,
                      out_features=num_classes,
                      num_layers=len(args.fanout))
    tree = FusedDistTreeEpoch(ds, args.fanout, np.arange(n), tmodel,
                              tx, batch_size=bs, mesh=mesh,
                              shuffle=True, seed=0)
    tstate = tree.init_state(jax.random.key(0))
    for epoch in range(args.epochs):
      t0 = time.perf_counter()
      tstate, stats = tree.run(tstate)
      print(f'epoch {epoch}: loss {stats["loss"]:.4f}  '
            f'train acc {stats["accuracy"]:.4f}  '
            f'({time.perf_counter() - t0:.2f}s, {len(tree)} steps x '
            f'{num_parts} devices, tree-fused)')
    acc = tree.evaluate(tstate.params, np.arange(n))
    print(f'eval acc: {acc:.4f}')
    return

  loader = DistNeighborLoader(ds, args.fanout, np.arange(n),
                              batch_size=bs, shuffle=True, mesh=mesh,
                              seed=0)
  model = GraphSAGE(hidden_features=args.hidden,
                    out_features=num_classes, num_layers=2)
  b0 = next(iter(loader))
  single = jax.tree_util.tree_map(lambda v: v[0], b0)
  state, _ = create_train_state(model, jax.random.key(0), single, tx)
  step = make_dp_supervised_step(model.apply, tx, bs, mesh)
  state = replicate(state, mesh)

  fused = None
  if args.fused:
    from graphlearn_tpu.parallel import FusedDistEpoch
    fused = FusedDistEpoch(ds, args.fanout, np.arange(n), model.apply,
                           tx, batch_size=bs, mesh=mesh, shuffle=True,
                           seed=0)

  for epoch in range(args.epochs):
    t0 = time.perf_counter()
    if fused is not None:
      state, stats = fused.run(state)
      dt = time.perf_counter() - t0
      print(f'epoch {epoch}: loss {stats["loss"]:.4f}  '
            f'train acc {stats["accuracy"]:.4f}  '
            f'({dt:.2f}s, {len(fused)} steps x {num_parts} devices, '
            f'fused)')
      continue
    tot = cnt = correct = seen = 0
    for batch in loader:
      state, loss, c = step(state, batch)
      tot += float(loss)
      correct += int(c)
      # padded seed slots in tail batches are not predictions
      seen += int((np.asarray(batch.batch) >= 0).sum())
      cnt += 1
    dt = time.perf_counter() - t0
    print(f'epoch {epoch}: loss {tot / max(cnt, 1):.4f}  '
          f'train acc {correct / max(seen, 1):.4f}  '
          f'({dt:.2f}s, {cnt} steps x {num_parts} devices)')


if __name__ == '__main__':
  main()
