"""ogbn-products GraphSAGE accuracy harness — gated on data presence.

The reference's headline number: test accuracy ~0.7870 +- 0.0036 with
fanout [15, 10, 5], batch 1024, 3 layers, hidden 256
(`examples/train_sage_ogbn_products.py:16`).  This harness reproduces
that recipe against a LOCAL OGB dataset directory (no network, no
torch — `graphlearn_tpu.data.ogb` reads the raw CSV or binary layout)
and asserts the accuracy bar.

Offline environments (like this zero-egress box) have no data: the
script then prints SKIP and exits 0, so CI stays green while the
check stands ready wherever `dataset/ogbn_products/` exists.

Usage::

    python examples/acc_ogbn_products.py                  # auto-locate
    python examples/acc_ogbn_products.py --root ~/dataset/ogbn_products
    GLT_OGB_ROOT=... python examples/acc_ogbn_products.py --assert
"""
import argparse
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

#: the reference's published accuracy, minus its own std margin
ACCURACY_BAR = 0.78

SEARCH_PATHS = ('dataset/ogbn_products', 'dataset/products',
                '~/dataset/ogbn_products', '/data/ogbn_products')


def locate_root(cli_root):
  cands = ([cli_root] if cli_root else []) + \
      ([os.environ['GLT_OGB_ROOT']] if 'GLT_OGB_ROOT' in os.environ
       else []) + [os.path.expanduser(p) for p in SEARCH_PATHS]
  for c in cands:
    p = Path(c)
    if p.exists() and ((p / 'raw' / 'edge.csv.gz').exists()
                       or (p / 'edge_index.npy').exists()
                       or (p / 'edge_index.npz').exists()):
      return p
  return None


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--root', default=None,
                  help='OGB dataset dir (raw CSV or binary layout)')
  ap.add_argument('--epochs', type=int, default=10)
  ap.add_argument('--batch-size', type=int, default=1024)
  ap.add_argument('--split-ratio', type=float, default=1.0)
  ap.add_argument('--assert', dest='do_assert', action='store_true',
                  help=f'exit 1 if test accuracy < {ACCURACY_BAR}')
  ap.add_argument('--fused', action='store_true',
                  help='train each epoch as ONE fused lax.scan program '
                       '(loader.FusedEpoch, remat backward; needs '
                       '--split-ratio 1.0)')
  ap.add_argument('--tree', action='store_true',
                  help='tree-layout fused epochs (FusedTreeEpoch + '
                       'TreeSAGE, max_steps_per_program=100) — the '
                       'r5 flagship; asserts the same accuracy bar '
                       'on the original-GraphSAGE estimator')
  ap.add_argument('--cpu', action='store_true')
  args = ap.parse_args()
  if args.tree and args.fused:
    ap.error('--tree and --fused are mutually exclusive')

  root = locate_root(args.root)
  if root is None:
    print('SKIP: no ogbn-products data found (checked --root, '
          'GLT_OGB_ROOT, ' + ', '.join(SEARCH_PATHS) + '). '
          'Place the OGB raw/ CSV layout or a binary export '
          '(graphlearn_tpu.data.ogb.save_binary) there and re-run.')
    return 0

  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import optax
  from graphlearn_tpu.data import ogb_to_dataset
  from graphlearn_tpu.loader import NeighborLoader
  from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                     make_eval_step, make_supervised_step)

  print(f'loading {root} ...')
  ds, splits = ogb_to_dataset(root, split_ratio=args.split_ratio,
                              sort_hot=args.split_ratio < 1.0)
  if 'train' not in splits or 'test' not in splits:
    print('SKIP: dataset has no train/test split files')
    return 0
  labels = ds.get_node_label()
  classes = int(np.max(np.asarray(labels))) + 1
  bs = args.batch_size
  tx = optax.adam(3e-3)

  if args.tree:
    # needs none of the per-batch loader/model setup below
    from graphlearn_tpu.loader import FusedTreeEpoch
    from graphlearn_tpu.models import TreeSAGE
    tmodel = TreeSAGE(hidden_features=256, out_features=classes,
                      num_layers=3)
    tree = FusedTreeEpoch(ds, [15, 10, 5], splits['train'], tmodel, tx,
                          batch_size=bs, shuffle=True, seed=0,
                          max_steps_per_program=100)
    tstate = tree.init_state(jax.random.key(0))
    for epoch in range(args.epochs):
      t0 = time.perf_counter()
      tstate, stats = tree.run(tstate)
      print(f'epoch {epoch}: loss {stats["loss"]:.4f} '
            f'({time.perf_counter() - t0:.2f}s, tree-fused)')
    acc = tree.evaluate(tstate.params, splits['test'])
    print(f'ogbn-products test acc: {acc:.4f} (bar {ACCURACY_BAR}, '
          f'reference ~0.787, tree estimator)')
    if args.do_assert and acc < ACCURACY_BAR:
      raise SystemExit(f'accuracy {acc:.4f} below {ACCURACY_BAR}')
    return 0

  train_loader = NeighborLoader(ds, [15, 10, 5], splits['train'],
                                batch_size=bs, shuffle=True, seed=0)
  test_loader = NeighborLoader(ds, [15, 10, 5], splits['test'],
                               batch_size=bs)
  model = GraphSAGE(hidden_features=256, out_features=classes,
                    num_layers=3)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(train_loader)), tx)
  train_step = make_supervised_step(apply_fn, tx, bs)
  eval_step = make_eval_step(apply_fn, bs)

  fused = None
  if args.fused:
    from graphlearn_tpu.loader import FusedEpoch
    fused = FusedEpoch(ds, [15, 10, 5], splits['train'], apply_fn, tx,
                       batch_size=bs, shuffle=True, seed=0, remat=True)

  for epoch in range(args.epochs):
    t0 = time.perf_counter()
    if fused is not None:
      state, stats = fused.run(state)
      mean_loss = stats['loss']
    else:
      tot = cnt = 0
      for batch in train_loader:
        state, loss, _ = train_step(state, batch)
        tot += float(loss)
        cnt += 1
      mean_loss = tot / max(cnt, 1)
    print(f'epoch {epoch}: loss {mean_loss:.4f} '
          f'({time.perf_counter() - t0:.2f}s)')

  correct = total = 0
  for batch in test_loader:
    c, t = eval_step(state.params, batch)
    correct += int(c)
    total += int(t)
  acc = correct / max(total, 1)
  print(f'ogbn-products test acc: {acc:.4f} (bar {ACCURACY_BAR}, '
        f'reference ~0.787)')
  if args.do_assert and acc < ACCURACY_BAR:
    raise SystemExit(f'accuracy {acc:.4f} below {ACCURACY_BAR}')
  return 0


if __name__ == '__main__':
  sys.exit(main())
