"""Tabular ingestion demo: csv tables -> TableDataset -> training.

TPU counterpart of reference `examples/pai/` (ODPS `TableDataset`
ingestion): the same record formats — edge tables of ``src,dst`` rows
and node tables of ``id,"f0:f1:..."`` rows — read here from csv files
(swap in `OdpsTableReader` on PAI images, the schema is identical).

Usage::

    python examples/table_ingest.py [--cpu]
"""
import argparse
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


from examples._synthetic import clustered_graph


def write_tables(d: Path, n=2000, classes=8, deg=6, seed=0):
  rows, cols, feat, labels = clustered_graph(n=n, deg=deg,
                                             classes=classes, d=classes,
                                             intra_p=0.75, noise_std=0.3,
                                             seed=seed)
  with open(d / 'edges.csv', 'w') as f:
    for r, c in zip(rows, cols):
      f.write(f'{r},{c}\n')
  with open(d / 'nodes.csv', 'w') as f:
    for i in np.random.default_rng(seed).permutation(n):  # any order
      f.write(f'{i},' + ':'.join(f'{v:.5f}' for v in feat[i]) + '\n')
  return labels


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--epochs', type=int, default=3)
  ap.add_argument('--cpu', action='store_true')
  args = ap.parse_args()

  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import optax
  from graphlearn_tpu.data import TableDataset
  from graphlearn_tpu.loader import NeighborLoader
  from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                     make_supervised_step)

  with tempfile.TemporaryDirectory() as d:
    d = Path(d)
    labels = write_tables(d)
    n, classes = len(labels), int(labels.max()) + 1
    ds = TableDataset().load(edge_tables={'e': d / 'edges.csv'},
                             node_tables={'n': d / 'nodes.csv'},
                             label=labels)
  bs = 256
  loader = NeighborLoader(ds, [5, 5], np.arange(n), batch_size=bs,
                          shuffle=True, seed=0)
  model = GraphSAGE(hidden_features=64, out_features=classes, num_layers=2)
  tx = optax.adam(1e-3)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(loader)), tx)
  step = make_supervised_step(apply_fn, tx, bs)
  for epoch in range(args.epochs):
    tot = cnt = 0
    for batch in loader:
      state, loss, _ = step(state, batch)
      tot += float(loss)
      cnt += 1
    print(f'epoch {epoch}: loss {tot / max(cnt, 1):.4f}')


if __name__ == '__main__':
  main()
