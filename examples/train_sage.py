"""Supervised GraphSAGE training — the flagship workload.

TPU-native counterpart of reference `examples/train_sage_ogbn_products.py`
(fanout [15,10,5], batch 1024, 3 layers, hidden 256, reported test acc
~0.7870).  Zero-egress environments can't download OGB, so the script
accepts either an on-disk `.npz` (keys: rows, cols, feats, labels,
train_idx, val_idx, test_idx) or generates a synthetic clustered graph
whose labels are learnable (sanity-checking the full pipeline).

Usage::

    python examples/train_sage.py                      # synthetic
    python examples/train_sage.py --data products.npz  # real data

The ``.npz`` schema matches a straight ogbn-products export (keys:
``rows, cols`` int64 [E]; ``feats`` float32 [N, 100]; ``labels`` int64
[N] or OGB's [N, 1]; ``train_idx / val_idx / test_idx`` int64) —
from a torch environment::

    from ogb.nodeproppred import NodePropPredDataset
    d, labels = NodePropPredDataset('ogbn-products')[0]
    split = NodePropPredDataset('ogbn-products').get_idx_split()
    np.savez('products.npz', rows=d['edge_index'][0],
             cols=d['edge_index'][1], feats=d['node_feat'],
             labels=labels, train_idx=split['train'],
             val_idx=split['valid'], test_idx=split['test'])
"""
import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


from examples._synthetic import clustered_graph


def synthetic(n=20000, d=64, classes=16, deg=10, seed=0):
  rows, cols, feats, labels = clustered_graph(n=n, deg=deg,
                                              classes=classes, d=d,
                                              seed=seed)
  idx = np.random.default_rng(seed).permutation(n)
  return dict(rows=rows, cols=cols, feats=feats, labels=labels,
              train_idx=idx[:int(n * .6)], val_idx=idx[int(n * .6):
                                                       int(n * .8)],
              test_idx=idx[int(n * .8):])


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--data', type=str, default=None)
  ap.add_argument('--epochs', type=int, default=5)
  ap.add_argument('--batch-size', type=int, default=1024)
  ap.add_argument('--fanout', type=int, nargs='+', default=[15, 10, 5])
  ap.add_argument('--hidden', type=int, default=256)
  ap.add_argument('--lr', type=float, default=3e-3)
  ap.add_argument('--split-ratio', type=float, default=1.0,
                  help='fraction of features resident in HBM')
  ap.add_argument('--ckpt-dir', type=str, default=None,
                  help='checkpoint/resume directory (resumes if present)')
  ap.add_argument('--tree', action='store_true',
                  help='tree-layout fused epochs (FusedTreeEpoch + '
                       'TreeSAGE): scatter-free/sort-free, the '
                       'fastest single-chip path (r5: 12.4x the '
                       'subgraph fused step on v5e)')
  ap.add_argument('--fused', action='store_true',
                  help='train each epoch as ONE fused lax.scan program '
                       '(loader.FusedEpoch; needs --split-ratio 1.0)')
  ap.add_argument('--cpu', action='store_true')
  ap.add_argument('--expect-acc', type=float, default=None,
                  help='fail (exit 1) if final test accuracy is below '
                       'this threshold — the example-level acceptance '
                       'check (clustered-graph pattern from '
                       'tests/test_models.py)')
  args = ap.parse_args()

  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import optax
  from graphlearn_tpu.data import Dataset, sort_by_in_degree
  from graphlearn_tpu.loader import NeighborLoader
  from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                     make_eval_step, make_supervised_step)

  data = dict(np.load(args.data)) if args.data else synthetic()
  # Real-schema robustness (ogbn-products exports): OGB labels are
  # [N, 1] (squeeze), indices may be any integer dtype, and unlabeled
  # nodes are nan in some exports (cast via float -> -1 sentinel).
  labels = np.asarray(data['labels'])
  if labels.ndim == 2 and labels.shape[1] == 1:
    labels = labels[:, 0]
  if np.issubdtype(labels.dtype, np.floating):
    labels = np.where(np.isnan(labels), -1, labels)
  data['labels'] = labels.astype(np.int64)
  for k in ('rows', 'cols', 'train_idx', 'val_idx', 'test_idx'):
    if k in data:
      data[k] = np.asarray(data[k]).astype(np.int64).reshape(-1)
  classes = int(data['labels'].max()) + 1
  n = len(data['labels'])

  ds = (Dataset()
        .init_graph((data['rows'], data['cols']), layout='COO', num_nodes=n)
        .init_node_features(
            data['feats'],
            sort_func=sort_by_in_degree if args.split_ratio < 1.0 else None,
            split_ratio=args.split_ratio)
        .init_node_labels(data['labels']))

  bs = args.batch_size
  train_loader = NeighborLoader(ds, args.fanout, data['train_idx'],
                                batch_size=bs, shuffle=True, seed=0)
  test_loader = NeighborLoader(ds, args.fanout, data['test_idx'],
                               batch_size=bs)

  if args.tree:
    import jax.numpy as jnp  # noqa: F401
    from graphlearn_tpu.loader import FusedTreeEpoch
    from graphlearn_tpu.models import TreeSAGE
    tx = optax.adam(args.lr)
    tree_model = TreeSAGE(hidden_features=args.hidden,
                          out_features=classes,
                          num_layers=len(args.fanout))
    tree = FusedTreeEpoch(ds, args.fanout, data['train_idx'],
                          tree_model, tx, batch_size=bs, shuffle=True,
                          seed=0)
    state = tree.init_state(jax.random.key(0))
    for epoch in range(args.epochs):
      t0 = time.perf_counter()
      state, stats = tree.run(state)
      print(f'epoch {epoch}: loss {stats["loss"]:.4f}  '
            f'({time.perf_counter() - t0:.2f}s, {len(tree)} steps)')
    acc = tree.evaluate(state.params, data['test_idx'])
    print(f'test acc: {acc:.4f}')
    if args.expect_acc is not None and acc < args.expect_acc:
      raise SystemExit(
          f'test accuracy {acc:.4f} below required {args.expect_acc}')
    return

  model = GraphSAGE(hidden_features=args.hidden, out_features=classes,
                    num_layers=len(args.fanout))
  tx = optax.adam(args.lr)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(train_loader)), tx)
  train_step = make_supervised_step(apply_fn, tx, bs)
  eval_step = make_eval_step(apply_fn, bs)

  ckpt = start_epoch = None
  if args.ckpt_dir:
    from graphlearn_tpu.utils import Checkpointer
    ckpt = Checkpointer(args.ckpt_dir, max_to_keep=2)
    restored = ckpt.restore(template=state)
    start_epoch = ckpt.latest_step() or 0
    if restored is not None:
      state = jax.tree_util.tree_map(jax.numpy.asarray, restored)
      print(f'resumed from epoch {start_epoch}')

  fused = None
  if args.fused:
    from graphlearn_tpu.loader import FusedEpoch
    # remat: the merged epoch program needs the checkpointed backward
    # to fit HBM at products-scale batch x fanout (FusedEpoch docs)
    fused = FusedEpoch(ds, args.fanout, data['train_idx'], apply_fn, tx,
                       batch_size=bs, shuffle=True, seed=0, remat=True)

  for epoch in range(start_epoch or 0, args.epochs):
    t0 = time.perf_counter()
    if fused is not None:
      state, stats = fused.run(state)
      mean_loss, cnt = stats['loss'], len(fused)
    else:
      tot = cnt = 0
      for batch in train_loader:
        state, loss, _ = train_step(state, batch)
        tot += float(loss)
        cnt += 1
      mean_loss = tot / max(cnt, 1)
    dt = time.perf_counter() - t0
    print(f'epoch {epoch}: loss {mean_loss:.4f}  '
          f'({dt:.2f}s, {cnt} steps)')
    if ckpt is not None:
      ckpt.save(epoch + 1, state)

  correct = total = 0
  for batch in test_loader:
    c, t = eval_step(state.params, batch)
    correct += int(c)
    total += int(t)
  acc = correct / max(total, 1)
  print(f'test acc: {acc:.4f}')
  if args.expect_acc is not None and acc < args.expect_acc:
    raise SystemExit(
        f'test accuracy {acc:.4f} below required {args.expect_acc}')


if __name__ == '__main__':
  main()
