"""SEAL link prediction: per-link enclosing subgraphs + DRNL labels.

TPU counterpart of reference `examples/seal_link_pred.py`: for each
candidate edge (u, v), extract the k-hop enclosing subgraph with
`SubGraphLoader` (one batch of 2 seeds = one link's subgraph), label
nodes with Double-Radius Node Labeling, and classify the subgraph.
The classifier is the same DGCNN the reference trains, via the
static-shape TPU sort-pool in `graphlearn_tpu.models.DGCNN`; the SEAL
signal (DRNL structure labels) is preserved exactly.

Synthetic task: a clustered graph; existing intra-cluster edges are
positives, random non-edges negatives.

Usage::

    python examples/seal_link_pred.py [--epochs 3] [--cpu]
    python examples/seal_link_pred.py --data cora.npz \
        [--expect-acc 0.8]                 # real-graph run

    # pod-scale extraction: enclosing subgraphs sampled by the
    # device-mesh engine (P links in flight per SPMD step):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/seal_link_pred.py --mesh

The ``.npz`` schema is any COO edge list (the reference runs Cora;
positives/negatives are drawn from the given graph exactly like its
`train_test_split_edges` flow)::

    # torch environment
    from torch_geometric.datasets import Planetoid
    data = Planetoid('data', name='Cora')[0]
    np.savez('cora.npz', rows=data.edge_index[0],
             cols=data.edge_index[1])
"""
import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def drnl(nodes_valid, edge_index, edge_mask, s0, s1):
  """Double-Radius Node Labeling on one induced subgraph (host-side).

  label(v) = 1 + min(d0, d1) + (d//2) * (d//2 + d%2 - 1), with
  d = d0 + d1; unreachable nodes get 0 (reference SEAL's
  `drnl_node_labeling`).  Distances by BFS over the masked local COO.
  """
  nloc = len(nodes_valid)
  adj = [[] for _ in range(nloc)]
  for r, c in zip(edge_index[0][edge_mask], edge_index[1][edge_mask]):
    adj[int(r)].append(int(c))
    adj[int(c)].append(int(r))

  def bfs(src):
    dist = np.full(nloc, -1, np.int32)
    dist[src] = 0
    q = [src]
    while q:
      nxt = []
      for u in q:
        for w in adj[u]:
          if dist[w] < 0:
            dist[w] = dist[u] + 1
            nxt.append(w)
      q = nxt
    return dist

  d0, d1 = bfs(s0), bfs(s1)
  lab = np.zeros(nloc, np.int32)
  ok = (d0 >= 0) & (d1 >= 0) & nodes_valid
  d = d0 + d1
  dmin = np.minimum(d0, d1)
  lab[ok] = 1 + dmin[ok] + (d[ok] // 2) * ((d[ok] // 2) + (d[ok] % 2) - 1)
  lab[s0] = lab[s1] = 1
  return lab


def synthetic(n=600, clusters=6, deg=6, seed=0):
  rng = np.random.default_rng(seed)
  cl = rng.integers(0, clusters, n)
  rows = np.repeat(np.arange(n), deg)
  order = np.argsort(cl, kind='stable')
  ptr = np.searchsorted(cl[order], np.arange(clusters + 1))
  cols = np.empty(n * deg, dtype=np.int64)
  for c in range(clusters):
    m = cl[rows] == c
    cols[m] = order[rng.integers(ptr[c], ptr[c + 1], m.sum())]
  return rows, cols, cl


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--data', type=str, default=None,
                  help='real COO edge-list .npz (docstring schema)')
  ap.add_argument('--epochs', type=int, default=3)
  ap.add_argument('--num-links', type=int, default=256)
  ap.add_argument('--max-label', type=int, default=16)
  ap.add_argument('--expect-acc', type=float, default=None,
                  help='fail (exit 1) below this test accuracy')
  ap.add_argument('--cpu', action='store_true')
  ap.add_argument('--mesh', action='store_true',
                  help='extract enclosing subgraphs with the device-'
                       'mesh DistSubGraphLoader (SEAL at pod scale)')
  args = ap.parse_args()

  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  import optax
  import flax.linen as nn
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.loader import SubGraphLoader
  from graphlearn_tpu.models import DGCNN

  if args.data:
    d = np.load(args.data)
    rows = np.asarray(d['rows'], np.int64)
    cols = np.asarray(d['cols'], np.int64)
    n = int(max(rows.max(), cols.max())) + 1
  else:
    rows, cols, cl = synthetic()
    n = len(cl)
  edge_set = set(zip(rows.tolist(), cols.tolist()))

  rng = np.random.default_rng(1)
  m = args.num_links
  pos_idx = rng.choice(len(rows), m, replace=False)
  pos = np.stack([rows[pos_idx], cols[pos_idx]], 1)
  # the TARGET links (and their reverses) are REMOVED from the graph
  # the subgraphs are extracted from — otherwise the u-v edge itself
  # leaks the label and the classifier learns edge detection, not link
  # prediction (the reference's train_test_split_edges does the same)
  pos_pairs = set(map(tuple, pos.tolist()))
  drop = np.fromiter(
      ((r, c) in pos_pairs or (c, r) in pos_pairs
       for r, c in zip(rows.tolist(), cols.tolist())), bool, len(rows))
  obs_rows, obs_cols = rows[~drop], cols[~drop]
  ds = Dataset().init_graph((obs_rows, obs_cols), layout='COO',
                            num_nodes=n)
  neg = []
  while len(neg) < m:
    u, v = rng.integers(0, n, 2)
    # check BOTH directions: DRNL/BFS treats the graph as undirected,
    # so a one-direction export must not admit (v, u)-edges as
    # negatives
    if (u, v) not in edge_set and (v, u) not in edge_set and u != v:
      neg.append((u, v))
  pairs = np.concatenate([pos, np.asarray(neg)])
  labels = np.concatenate([np.ones(m), np.zeros(m)]).astype(np.int32)
  order = rng.permutation(2 * m)
  pairs, labels = pairs[order], labels[order]

  # one batch of 2 seeds == one link's enclosing subgraph; --mesh runs
  # P links per SPMD step on the sharded graph (reference `_subgraph`
  # across partitions, `dist_neighbor_sampler.py:456-516`)
  if args.mesh:
    from graphlearn_tpu.parallel import (DistDataset, DistSubGraphLoader,
                                         make_mesh)
    num_parts = len(jax.devices())
    dds = DistDataset.from_full_graph(num_parts, obs_rows, obs_cols,
                                      num_nodes=n)
    loader = DistSubGraphLoader(dds, [8], pairs.reshape(-1),
                                batch_size=2, mesh=make_mesh(num_parts),
                                collect_features=False, seed=0)
  else:
    loader = SubGraphLoader(ds, [8], pairs.reshape(-1), batch_size=2,
                            shuffle=False, seed=0)

  class SealDGCNN(nn.Module):
    """DRNL label embedding -> DGCNN (the reference's SEAL classifier:
    sort-pooling + Conv1d, `examples/seal_link_pred.py` via PyG)."""
    hidden: int = 32
    max_label: int = 16
    k: int = 30

    @nn.compact
    def __call__(self, lab, edge_index, edge_mask, node_mask):
      x = nn.Embed(self.max_label, self.hidden)(
          jnp.clip(lab, 0, self.max_label - 1))
      return DGCNN(hidden_features=self.hidden, out_features=2,
                   num_layers=3, k=self.k)(
                       x, edge_index, edge_mask, node_mask)

  model = SealDGCNN(max_label=args.max_label)

  # Pre-extract subgraphs + DRNL labels once (host-side prep).
  sub = []
  if args.mesh:
    num_parts = len(jax.devices())
    for i, batch in enumerate(loader):
      nmask = np.asarray(batch.node_mask)
      ei = np.asarray(batch.edge_index)
      em = np.asarray(batch.edge_mask)
      mapping = np.asarray(batch.metadata['mapping'])
      for p in range(num_parts):       # one link per device slice
        link = i * num_parts + p
        if link >= len(labels) or mapping[p, 0] < 0:
          continue
        lab = drnl(nmask[p], ei[p], em[p], int(mapping[p, 0]),
                   int(mapping[p, 1]))
        sub.append((lab, ei[p], em[p], nmask[p], labels[link]))
  else:
    for i, batch in enumerate(loader):
      nmask = np.asarray(batch.node_mask)
      ei = np.asarray(batch.edge_index)
      em = np.asarray(batch.edge_mask)
      mapping = np.asarray(batch.metadata['mapping'])
      lab = drnl(nmask, ei, em, int(mapping[0]), int(mapping[1]))
      sub.append((lab, ei, em, nmask, labels[i]))

  tx = optax.adam(1e-3)
  l0, e0, m0, nm0, _ = sub[0]
  params = model.init(jax.random.key(0), jnp.asarray(l0), jnp.asarray(e0),
                      jnp.asarray(m0), jnp.asarray(nm0))
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, lab, ei, em, nm, y):
    def loss_fn(p):
      logit = model.apply(p, lab, ei, em, nm)
      return optax.softmax_cross_entropy_with_integer_labels(logit, y)
    loss, g = jax.value_and_grad(loss_fn)(params)
    upd, opt = tx.update(g, opt, params)
    return optax.apply_updates(params, upd), opt, loss

  ntr = int(0.8 * len(sub))
  for epoch in range(args.epochs):
    tot = 0.0
    for lab, ei, em, nm, y in sub[:ntr]:
      params, opt, loss = step(params, opt, jnp.asarray(lab),
                               jnp.asarray(ei), jnp.asarray(em),
                               jnp.asarray(nm), jnp.asarray(y))
      tot += float(loss)
    print(f'epoch {epoch}: loss {tot / ntr:.4f}')

  @jax.jit
  def predict(params, lab, ei, em, nm):
    return jnp.argmax(model.apply(params, lab, ei, em, nm))

  correct = sum(
      int(predict(params, jnp.asarray(lab), jnp.asarray(ei),
                  jnp.asarray(em), jnp.asarray(nm))) == int(y)
      for lab, ei, em, nm, y in sub[ntr:])
  acc = correct / max(len(sub) - ntr, 1)
  print(f'test acc: {acc:.4f}')
  if args.expect_acc is not None and acc < args.expect_acc:
    raise SystemExit(
        f'test accuracy {acc:.4f} below required {args.expect_acc}')


if __name__ == '__main__':
  main()
