"""RGNN (RGAT / RSAGE) on an IGBH-style academic heterogeneous graph.

TPU counterpart of reference `examples/igbh/{dataset,rgnn,train_rgnn}.py`
— the BASELINE scaling workload: 4 node types (paper, author,
institute, fos), 4 relation types + reversed, hetero neighbor sampling
with per-hop fanouts, and a relational GNN classifying papers.
``--model rgat`` composes per-edge-type GAT attention via `HeteroConv`
(the reference's RGAT); ``--model rsage`` uses per-etype SAGE convs.
Zero-egress stand-in for IGBH-tiny: a synthetic academic graph whose
paper topic is encoded in its fos (field-of-study) links.

Usage::

    python examples/igbh/train_rgnn.py --model rgat [--epochs 4] [--cpu]
"""
import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))

import numpy as np

P, A, I, F = 'paper', 'author', 'institute', 'fos'
ETYPES = {
    'cites': (P, 'cites', P),
    'written_by': (P, 'written_by', A),
    'rev_written_by': (A, 'rev_written_by', P),
    'affiliated_to': (A, 'affiliated_to', I),
    'rev_affiliated_to': (I, 'rev_affiliated_to', A),
    'topic': (P, 'topic', F),
    'rev_topic': (F, 'rev_topic', P),
}


def synthetic(npaper=4000, nauthor=1600, ninst=80, nfos=64, classes=8,
              d=32, seed=0):
  rng = np.random.default_rng(seed)
  topic = rng.integers(0, classes, npaper)
  fos_of_class = nfos // classes

  def paper_peers(src_topic):
    order = np.argsort(topic, kind='stable')
    ptr = np.searchsorted(topic[order], np.arange(classes + 1))
    out = np.empty(len(src_topic), np.int64)
    for c in range(classes):
      m = src_topic == c
      out[m] = order[rng.integers(ptr[c], ptr[c + 1], m.sum())]
    return out

  crow = np.repeat(np.arange(npaper), 3)
  ccol = np.where(rng.random(npaper * 3) < 0.7, paper_peers(topic[crow]),
                  rng.integers(0, npaper, npaper * 3))
  wrow = np.repeat(np.arange(npaper), 2)
  wcol = rng.integers(0, nauthor, npaper * 2)
  arow = np.arange(nauthor)
  acol = rng.integers(0, ninst, nauthor)
  # fos links carry the class signal
  frow = np.repeat(np.arange(npaper), 2)
  fcol = (topic[frow] * fos_of_class
          + rng.integers(0, fos_of_class, npaper * 2))

  edges = {
      ETYPES['cites']: (crow, ccol),
      ETYPES['written_by']: (wrow, wcol),
      ETYPES['rev_written_by']: (wcol, wrow),
      ETYPES['affiliated_to']: (arow, acol),
      ETYPES['rev_affiliated_to']: (acol, arow),
      ETYPES['topic']: (frow, fcol),
      ETYPES['rev_topic']: (fcol, frow),
  }
  feats = {P: rng.standard_normal((npaper, d)).astype(np.float32),
           A: rng.standard_normal((nauthor, d)).astype(np.float32),
           I: rng.standard_normal((ninst, d)).astype(np.float32),
           F: rng.standard_normal((nfos, d)).astype(np.float32)}
  nnodes = {P: npaper, A: nauthor, I: ninst, F: nfos}
  return edges, feats, nnodes, topic.astype(np.int32)


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--model', choices=['rgat', 'rsage'], default='rgat')
  ap.add_argument('--epochs', type=int, default=4)
  ap.add_argument('--batch-size', type=int, default=256)
  ap.add_argument('--fanout', type=int, nargs='+', default=[4, 4])
  ap.add_argument('--hidden', type=int, default=64)
  ap.add_argument('--heads', type=int, default=2)
  ap.add_argument('--cpu', action='store_true')
  args = ap.parse_args()

  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  import optax
  import flax.linen as nn
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.loader import NeighborLoader
  from graphlearn_tpu.models import GATConv, HeteroConv, SAGEConv

  edges, feats, nnodes, topic = synthetic()
  npaper, classes = len(topic), int(topic.max()) + 1
  ds = (Dataset()
        .init_graph(edges, layout='COO', num_nodes=nnodes)
        .init_node_features(feats, split_ratio=1.0)
        .init_node_labels({P: topic}))

  idx = np.random.default_rng(1).permutation(npaper)
  train_idx, test_idx = idx[:int(npaper * .8)], idx[int(npaper * .8):]
  bs = args.batch_size
  loader = NeighborLoader(ds, args.fanout, (P, train_idx), batch_size=bs,
                          shuffle=True, seed=0)
  test_loader = NeighborLoader(ds, args.fanout, (P, test_idx),
                               batch_size=bs)
  batch0 = next(iter(loader))
  etypes = tuple(batch0.edge_index_dict.keys())

  assert args.hidden % args.heads == 0
  mk_gat = lambda: GATConv(args.hidden // args.heads,     # noqa: E731
                           heads=args.heads)              # concat -> hidden
  mk_sage = lambda: SAGEConv(args.hidden)                 # noqa: E731
  make_conv = mk_gat if args.model == 'rgat' else mk_sage

  class RGNN(nn.Module):
    """Reference `examples/igbh/rgnn.py` — per-etype convs merged
    per node type, stacked num_layers deep."""

    @nn.compact
    def __call__(self, x_dict, edge_index_dict, edge_mask_dict):
      h = {nt: nn.Dense(args.hidden)(x) for nt, x in x_dict.items()}
      for li in range(2):
        conv = HeteroConv(etypes, args.hidden,
                          make_conv=make_conv, name=f'conv{li}')
        h = conv(h, edge_index_dict, edge_mask_dict)
        h = {nt: nn.relu(v) for nt, v in h.items()}
      return nn.Dense(classes)(h[P])

  model = RGNN()
  tx = optax.adam(1e-3)
  params = model.init(jax.random.key(0), batch0.x_dict,
                      batch0.edge_index_dict, batch0.edge_mask_dict)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      logits = model.apply(p, batch.x_dict, batch.edge_index_dict,
                           batch.edge_mask_dict)
      y = batch.y_dict[P][:bs]
      valid = (batch.batch_dict[P] >= 0).astype(logits.dtype)
      ce = optax.softmax_cross_entropy_with_integer_labels(logits[:bs], y)
      return (ce * valid).sum() / jnp.maximum(valid.sum(), 1.0)
    loss, g = jax.value_and_grad(loss_fn)(params)
    upd, opt = tx.update(g, opt, params)
    return optax.apply_updates(params, upd), opt, loss

  @jax.jit
  def logits_fn(params, batch):
    return model.apply(params, batch.x_dict, batch.edge_index_dict,
                       batch.edge_mask_dict)

  for epoch in range(args.epochs):
    tot = cnt = 0
    for batch in loader:
      params, opt, loss = step(params, opt, batch)
      tot += float(loss)
      cnt += 1
    print(f'epoch {epoch}: loss {tot / max(cnt, 1):.4f}')

  correct = total = 0
  for batch in test_loader:
    pred = np.argmax(np.asarray(logits_fn(params, batch))[:bs], axis=1)
    seeds = np.asarray(batch.batch_dict[P])
    valid = seeds >= 0
    correct += int((pred[valid] == np.asarray(batch.y_dict[P][:bs])[valid])
                   .sum())
    total += int(valid.sum())
  print(f'{args.model} test acc: {correct / max(total, 1):.4f}')


if __name__ == '__main__':
  main()
