"""Distributed RGNN (RGAT/RSAGE) on an IGBH-style hetero graph.

TPU counterpart of reference `examples/igbh/dist_train_rgnn.py` — THE
BASELINE scaling workload: every node type range-sharded over the
device mesh, per-edge-type neighbor exchange on ICI collectives
(`parallel.DistHeteroNeighborLoader`), and a data-parallel hetero
train step with psum-averaged gradients.

Runs on a real TPU slice, or anywhere via the virtual CPU mesh::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/igbh/dist_train_rgnn.py --num-parts 8 --model rgat
"""
import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))

import numpy as np

from examples.igbh.train_rgnn import ETYPES, P as PAPER, synthetic


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--model', choices=['rgat', 'rsage'], default='rsage')
  ap.add_argument('--partition-dir', type=str, default=None,
                  help='hetero partition layout from RandomPartitioner')
  ap.add_argument('--igbh-root', type=str, default=None,
                  help='REAL IGBH directory (the reference npy layout, '
                       'examples/igbh/dataset.py) — loaded via '
                       'graphlearn_tpu.data.load_igbh_dir')
  ap.add_argument('--igbh-size', default='tiny',
                  choices=['tiny', 'small', 'medium', 'large', 'full'])
  ap.add_argument('--num-parts', type=int, default=None)
  ap.add_argument('--epochs', type=int, default=3)
  ap.add_argument('--batch-size', type=int, default=64,
                  help='per-device paper seeds')
  ap.add_argument('--fanout', type=int, nargs='+', default=[4, 4])
  ap.add_argument('--hidden', type=int, default=64)
  ap.add_argument('--heads', type=int, default=2)
  ap.add_argument('--split-ratio', type=float, default=1.0,
                  help='fraction of each node type\'s feature rows in '
                       'HBM; < 1 tiers the rest to host DRAM — the '
                       'IGBH-large "features exceed aggregate HBM" '
                       'lever (cold misses overlaid per batch, '
                       'hit rate in exchange_stats)')
  ap.add_argument('--host-local', action='store_true',
                  help='with --partition-dir on a multi-host pod: each '
                       'process materializes only ITS partitions '
                       '(per-host RAM = 1/num_hosts of the dataset)')
  args = ap.parse_args()

  import jax
  import jax.numpy as jnp
  import flax.linen as nn
  import optax
  from jax.sharding import NamedSharding, PartitionSpec
  from graphlearn_tpu.models import GATConv, HeteroConv, SAGEConv
  from graphlearn_tpu.parallel import (DistHeteroDataset,
                                       DistHeteroNeighborLoader, make_mesh,
                                       replicate)
  from graphlearn_tpu.parallel.shard_map_compat import shard_map

  num_parts = args.num_parts or len(jax.devices())
  mesh = make_mesh(num_parts)

  if args.partition_dir:
    import json
    with open(Path(args.partition_dir) / 'META.json') as f:
      disk_parts = json.load(f)['num_parts']
    assert disk_parts == num_parts, (
        f'partition layout has {disk_parts} parts but the mesh has '
        f'{num_parts} devices — repartition or set --num-parts')
    from graphlearn_tpu.parallel import multihost
    ds = DistHeteroDataset.from_partition_dir(
        args.partition_dir, num_parts, split_ratio=args.split_ratio,
        host_parts=(multihost.host_partition_ids(mesh)
                    if args.host_local else None))
    assert PAPER in ds.node_labels, 'training needs paper labels'
    npaper = ds.num_nodes_dict()[PAPER]
    # host-local shards see only local labels: the class count (and so
    # the model width) must agree GLOBALLY across processes
    classes = multihost.global_max(
        int(np.max(ds.node_labels[PAPER])), mesh) + 1
    train_idx = np.arange(npaper)
  elif args.igbh_root:
    from graphlearn_tpu.data import load_igbh_dir
    # default mmap: tables stay on disk until the shard build slices
    # them (at large/full, partition offline with
    # `graphlearn_tpu.data.partition_igbh` + --partition-dir instead
    # of this in-memory path)
    d = load_igbh_dir(args.igbh_root, args.igbh_size)
    npaper = d['num_nodes_dict'][PAPER]
    classes = int(d['paper_labels'].max()) + 1
    ds = DistHeteroDataset.from_full_graph(
        num_parts, d['edge_index_dict'],
        node_feat_dict=d['node_feat_dict'],
        node_label_dict={PAPER: d['paper_labels'].astype(np.int32)},
        num_nodes_dict=d['num_nodes_dict'],
        split_ratio=args.split_ratio)
    train_idx = d['train_idx']          # reference 60% convention
  else:
    edges, feats, nnodes, topic = synthetic()
    npaper, classes = len(topic), int(topic.max()) + 1
    ds = DistHeteroDataset.from_full_graph(
        num_parts, edges, node_feat_dict=feats,
        node_label_dict={PAPER: topic}, num_nodes_dict=nnodes,
        split_ratio=args.split_ratio)
    train_idx = np.arange(npaper)

  bs = args.batch_size
  loader = DistHeteroNeighborLoader(
      ds, args.fanout, (PAPER, train_idx), batch_size=bs,
      shuffle=True, mesh=mesh, seed=0)

  batch0 = next(iter(loader))
  etypes = tuple(batch0.edge_index_dict.keys())
  assert args.hidden % args.heads == 0
  mk = (lambda: GATConv(args.hidden // args.heads, heads=args.heads)) \
      if args.model == 'rgat' else (lambda: SAGEConv(args.hidden))

  class RGNN(nn.Module):
    @nn.compact
    def __call__(self, x_dict, ei_dict, em_dict):
      h = {nt: nn.Dense(args.hidden)(x) for nt, x in x_dict.items()}
      for li in range(2):
        conv = HeteroConv(etypes, args.hidden, make_conv=mk,
                          name=f'conv{li}')
        h = conv(h, ei_dict, em_dict)
        h = {nt: nn.relu(v) for nt, v in h.items()}
      return nn.Dense(classes)(h[PAPER])

  model = RGNN()
  tx = optax.adam(1e-3)
  single = jax.tree_util.tree_map(lambda v: v[0], batch0)
  params = model.init(jax.random.key(0), single.x_dict,
                      single.edge_index_dict, single.edge_mask_dict)
  opt = tx.init(params)

  def device_step(params, opt, batch):
    batch = jax.tree_util.tree_map(lambda v: v[0], batch)

    def loss_fn(p):
      logits = model.apply(p, batch.x_dict, batch.edge_index_dict,
                           batch.edge_mask_dict)
      y = batch.y_dict[PAPER][:bs]
      valid = (batch.batch_dict[PAPER].reshape(-1) >= 0).astype(
          logits.dtype)
      ce = optax.softmax_cross_entropy_with_integer_labels(logits[:bs], y)
      return (ce * valid).sum() / jnp.maximum(valid.sum(), 1.0)

    loss, g = jax.value_and_grad(loss_fn)(params)
    g = jax.lax.pmean(g, 'data')             # DP gradient sync
    loss = jax.lax.pmean(loss, 'data')
    upd, opt = tx.update(g, opt, params)
    return optax.apply_updates(params, upd), opt, loss[None]

  pspec = PartitionSpec('data')
  step = jax.jit(shard_map(
      device_step, mesh=mesh,
      in_specs=(PartitionSpec(), PartitionSpec(), pspec),
      out_specs=(PartitionSpec(), PartitionSpec(), pspec)))

  for epoch in range(args.epochs):
    t0 = time.perf_counter()
    tot = cnt = 0
    for batch in loader:
      params, opt, loss = step(params, opt, batch)
      tot += float(np.asarray(loss)[0])
      cnt += 1
    print(f'epoch {epoch}: loss {tot / max(cnt, 1):.4f} '
          f'({time.perf_counter() - t0:.2f}s, {cnt} steps x '
          f'{num_parts} devices, {args.model})')


if __name__ == '__main__':
  main()
