"""Feature store shared across processes.

TPU counterpart of reference `examples/feature_mp.py` (a `Feature`
IPC-shared into spawned workers via CUDA IPC handles +
ForkingPickler).  Without CUDA IPC the TPU-native sharing model is:

  * **host tier**: workers inherit the backing numpy array
    copy-on-write through ``fork`` — zero copies, zero serialization
    (the same mechanism the sampling producers use for whole
    datasets, `distributed/host_dataset.py`).
  * **device tier**: each process that touches the accelerator stages
    its own hot tier with `Feature.lazy_init` — device buffers are
    per-process on TPU; cross-process device sharing is the mesh's
    job (`parallel/dist_data.py::DistFeature`), not IPC's.

The demo forks workers that gather disjoint row slices from one
inherited `Feature` (host path) while the parent gathers on device,
and verifies provenance (row value encodes row id) everywhere.

Usage::

    python examples/feature_mp.py [--rows 100000] [--dim 64]
"""
import argparse
import multiprocessing as mp
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def _worker(feature, lo, hi, out_q):
  """Child process: host-tier gather from the CoW-inherited store."""
  ids = np.arange(lo, hi)
  rows = feature.host_get(ids)
  ok = bool(np.all(rows[:, 0] == ids))
  out_q.put((lo, hi, ok))


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--rows', type=int, default=100_000)
  ap.add_argument('--dim', type=int, default=64)
  ap.add_argument('--workers', type=int, default=4)
  ap.add_argument('--cpu', action='store_true')
  args = ap.parse_args()

  from graphlearn_tpu.data import Feature

  # row i's value encodes i, so any process can verify provenance
  feats = np.tile(np.arange(args.rows, dtype=np.float32)[:, None],
                  (1, args.dim))
  feature = Feature(feats, split_ratio=0.5)

  # fork BEFORE any device work: children stay host-only and inherit
  # the array copy-on-write
  ctx = mp.get_context('forkserver')
  out_q = ctx.Queue()
  per = args.rows // args.workers
  procs = []
  for w in range(args.workers):
    lo, hi = w * per, (w + 1) * per if w < args.workers - 1 else args.rows
    p = ctx.Process(target=_worker, args=(feature, lo, hi, out_q),
                    daemon=True)
    p.start()
    procs.append(p)
  for _ in procs:
    lo, hi, ok = out_q.get(timeout=60)
    assert ok, f'worker rows [{lo}, {hi}) failed provenance'
    print(f'worker rows [{lo:>7}, {hi:>7}): host gather ok')
  for p in procs:
    p.join(timeout=10)

  # parent: device-tier gather (hot rows from HBM, cold from host)
  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  rng = np.random.default_rng(0)
  ids = rng.integers(0, args.rows, 4096)
  got = np.asarray(feature[ids])
  assert np.all(got[:, 0] == ids), 'device gather provenance'
  print(f'parent 4096-row device gather ok on '
        f'{jax.devices()[0].platform} '
        f'(hot tier {feature.hot_rows}/{args.rows} rows)')


if __name__ == '__main__':
  main()
