"""Unsupervised GraphSAGE via link prediction.

TPU counterpart of reference `examples/graph_sage_unsup_ppi.py:41-45`:
a `LinkNeighborLoader` with ``neg_sampling='binary'`` feeds positive
edges + sampled non-edges; the model learns embeddings whose dot
product separates them.  Zero-egress stand-in for PPI: a synthetic
clustered graph (intra-cluster edges dominate), where good embeddings
must recover cluster structure.

Usage::

    python examples/unsup_sage_ppi.py [--epochs 5] [--cpu]
"""
import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


from examples._synthetic import clustered_graph


def synthetic():
  # weakly informative features (PPI features carry signal too):
  # a faint cluster direction buried in noise
  return clustered_graph(n=4000, deg=8, classes=8, d=32, intra_p=0.8,
                         feat_signal=0.5, noise_std=1.0)


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--data', type=str, default=None,
                  help='real PPI .npz export: rows, cols int64 [E] + '
                       'feats float32 [N, D] (torch env: '
                       'torch_geometric.datasets.PPI graphs merged '
                       'with per-graph node-id offsets)')
  ap.add_argument('--epochs', type=int, default=10)
  ap.add_argument('--batch-size', type=int, default=512)
  ap.add_argument('--hidden', type=int, default=64)
  ap.add_argument('--fused', action='store_true',
                  help='train each epoch as ONE fused lax.scan program '
                       '(loader.FusedLinkEpoch)')
  ap.add_argument('--cpu', action='store_true')
  args = ap.parse_args()

  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import optax
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.loader import LinkNeighborLoader
  from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                     make_unsupervised_step)
  from graphlearn_tpu.sampler import NegativeSampling

  if args.data:
    d = np.load(args.data)
    rows = np.asarray(d['rows'], np.int64)
    cols = np.asarray(d['cols'], np.int64)
    feats = np.asarray(d['feats'], np.float32)
    n = feats.shape[0]
    cl = None
    # HOLD OUT eval edges before training: the AUC below must measure
    # generalization, not reconstruction of training supervision
    srng = np.random.default_rng(7)
    held = srng.choice(len(rows), min(500, len(rows) // 10),
                       replace=False)
    held_mask = np.zeros(len(rows), bool)
    held_mask[held] = True
    eval_rows, eval_cols = rows[held_mask], cols[held_mask]
    train_rows, train_cols = rows[~held_mask], cols[~held_mask]
  else:
    rows, cols, feats, cl = synthetic()
    n = len(cl)
    train_rows, train_cols = rows, cols
    eval_rows = eval_cols = None
  ds = (Dataset()
        .init_graph((train_rows, train_cols), layout='COO', num_nodes=n)
        .init_node_features(feats, split_ratio=1.0))

  loader = LinkNeighborLoader(
      ds, [10, 10], (train_rows, train_cols),
      neg_sampling=NegativeSampling('binary', 1.0),
      batch_size=args.batch_size, shuffle=True, seed=0)

  model = GraphSAGE(hidden_features=args.hidden, out_features=args.hidden,
                    num_layers=2)
  tx = optax.adam(3e-3)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(loader)), tx)
  step = make_unsupervised_step(apply_fn, tx)

  fused = None
  if args.fused:
    from graphlearn_tpu.loader import FusedLinkEpoch
    fused = FusedLinkEpoch(
        ds, [10, 10], (train_rows, train_cols), apply_fn, tx,
        batch_size=args.batch_size,
        neg_sampling=NegativeSampling('binary', 1.0), shuffle=True,
        seed=0)

  for epoch in range(args.epochs):
    t0 = time.perf_counter()
    if fused is not None:
      state, stats = fused.run(state)
      mean_loss = stats['loss']
    else:
      tot = cnt = 0
      for batch in loader:
        state, loss = step(state, batch)
        tot += float(loss)
        cnt += 1
      mean_loss = tot / max(cnt, 1)
    print(f'epoch {epoch}: link loss {mean_loss:.4f} '
          f'({time.perf_counter() - t0:.2f}s)')

  # Eval: do learned embeddings score intra-cluster pairs above
  # random pairs?  (proxy for the PPI downstream F1)
  import jax.numpy as jnp
  from graphlearn_tpu.loader import NeighborLoader
  emb = np.zeros((n, args.hidden), np.float32)
  eval_loader = NeighborLoader(ds, [10, 10], np.arange(n),
                               batch_size=args.batch_size)
  for batch in eval_loader:
    e = apply_fn(state.params, batch.x, batch.edge_index, batch.edge_mask)
    seeds = np.asarray(batch.batch)
    valid = seeds >= 0
    sl = np.asarray(batch.metadata['seed_local'])[valid]
    emb[seeds[valid]] = np.asarray(e)[sl]
  rng = np.random.default_rng(1)
  if cl is not None:
    # synthetic: AUC of same-cluster pairs vs random pairs
    a = rng.integers(0, n, 4000)
    pos = np.array([rng.choice(np.nonzero(cl == cl[i])[0])
                    for i in a[:500]])
    neg = rng.integers(0, n, 500)
    label = 'cluster-pair AUC'
  else:
    # real data: HELD-OUT edges (excluded from training above) vs
    # random pairs — the reference's unsupervised link evaluation
    k = min(500, len(eval_rows))
    a = eval_rows[:k]
    pos = eval_cols[:k]
    neg = rng.integers(0, n, k)
    label = 'held-out-edge AUC'
  k = len(pos)
  pos_s = (emb[a[:k]] * emb[pos]).sum(1)
  neg_s = (emb[a[:k]] * emb[neg]).sum(1)
  auc = (pos_s[:, None] > neg_s[None, :]).mean()
  print(f'{label}: {auc:.4f}')


if __name__ == '__main__':
  main()
