"""Shared synthetic-graph generator for the examples.

Zero-egress stand-in for OGB-style datasets: a label-clustered COO
graph (intra-class edges dominate) with features carrying a faint
class direction in noise, so every example's objective is genuinely
learnable and partition/train pairs (`distributed/
partition_dataset.py` -> `dist_train_sage.py --partition-dir`) stay in
sync by construction.
"""
import numpy as np


def clustered_graph(n=8192, deg=8, classes=8, d=32, intra_p=0.7,
                    feat_signal=1.0, noise_std=0.5, seed=0):
  """Returns ``(rows, cols, feats, labels)``.

  Args:
    intra_p: probability an edge stays inside its source's class.
    feat_signal: scale of the class direction mixed into the features
      (0 = pure noise; 1 = the class prototype mix the supervised
      examples use).
    noise_std: feature noise scale (sets the SNR together with
      ``feat_signal``).
  """
  rng = np.random.default_rng(seed)
  labels = rng.integers(0, classes, n).astype(np.int32)
  rows = np.repeat(np.arange(n), deg)
  order = np.argsort(labels, kind='stable')
  ptr = np.searchsorted(labels[order], np.arange(classes + 1))
  intra = np.empty(n * deg, dtype=np.int64)
  for c in range(classes):
    m = labels[rows] == c
    intra[m] = order[rng.integers(ptr[c], ptr[c + 1], m.sum())]
  cols = np.where(rng.random(n * deg) < intra_p, intra,
                  rng.integers(0, n, n * deg))
  proto = rng.normal(0, 1, (classes, d)).astype(np.float32)
  feats = (feat_signal * proto[labels]
           + rng.normal(0, noise_std, (n, d)).astype(np.float32))
  return rows, cols, feats, labels
