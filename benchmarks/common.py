"""Shared benchmark scaffolding.

Mirrors the reference's harness conventions (`benchmarks/api/
bench_sampler.py:46-54`, `bench_feature.py:50-62`): wall-clock around
the op under test, device-synchronized, metric printed as one JSON
line per config so the results are machine-comparable across rounds.
"""
from __future__ import annotations

import json
import time

import numpy as np

NUM_NODES = 2_449_029          # ogbn-products node count
AVG_DEG = 25


def build_graph(num_nodes=NUM_NODES, avg_deg=AVG_DEG, seed=0):
  """Synthetic power-law-ish graph at ogbn-products scale (same
  construction as the root `bench.py`)."""
  rng = np.random.default_rng(seed)
  n = num_nodes
  e = n * avg_deg
  rows = rng.integers(0, n, e, dtype=np.int64)
  hubs = (rng.random(e) < 0.3)
  cols = np.where(hubs,
                  (rng.random(e) ** 2 * n).astype(np.int64),
                  rng.integers(0, n, e, dtype=np.int64))
  return rows, cols.astype(np.int64)


def emit(metric: str, value: float, unit: str, baseline: float = None,
         **extra):
  rec = {'metric': metric, 'value': round(float(value), 3), 'unit': unit}
  if baseline:
    rec['vs_baseline'] = round(float(value) / baseline, 4)
  rec.update(extra)
  print(json.dumps(rec), flush=True)


class Timer:
  """Wall-clock over N iters; call ``sync`` on a device array first."""

  def __enter__(self):
    self.t0 = time.perf_counter()
    return self

  def __exit__(self, *exc):
    self.dt = time.perf_counter() - self.t0
