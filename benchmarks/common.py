"""Shared benchmark scaffolding.

Mirrors the reference's harness conventions (`benchmarks/api/
bench_sampler.py:46-54`, `bench_feature.py:50-62`): wall-clock around
the op under test, device-synchronized, metric printed as one JSON
line per config so the results are machine-comparable across rounds.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

NUM_NODES = 2_449_029          # ogbn-products node count
AVG_DEG = 25


#: bump when the construction below changes — part of the cache key so
#: stale /tmp graphs can never masquerade as the current generator.
GRAPH_VERSION = 1


def build_graph(num_nodes=NUM_NODES, avg_deg=AVG_DEG, seed=0,
                cache: bool = True):
  """Synthetic power-law-ish graph at ogbn-products scale (same
  construction as the root `bench.py`).  Cached to /tmp so the
  per-config subprocesses of the sweep benchmarks (see
  `run_in_fresh_process`) skip the ~1 min regeneration."""
  import os
  path = (f'/tmp/.glt_bench_graph_v{GRAPH_VERSION}'
          f'_{num_nodes}_{avg_deg}_{seed}.npz')
  if cache and os.path.exists(path):
    d = np.load(path)
    return d['rows'].astype(np.int64), d['cols'].astype(np.int64)
  rng = np.random.default_rng(seed)
  n = num_nodes
  e = n * avg_deg
  rows = rng.integers(0, n, e, dtype=np.int64)
  hubs = (rng.random(e) < 0.3)
  cols = np.where(hubs,
                  (rng.random(e) ** 2 * n).astype(np.int64),
                  rng.integers(0, n, e, dtype=np.int64))
  cols = cols.astype(np.int64)
  if cache:
    # pid-unique temp + atomic replace (concurrent cold-cache writers
    # must not interleave); int32 storage halves the /tmp footprint
    tmp = f'{path}.{os.getpid()}.tmp.npz'
    np.savez(tmp[:-4], rows=rows.astype(np.int32),
             cols=cols.astype(np.int32))       # savez appends .npz
    os.replace(tmp, path)
  return rows, cols


def build_graph_csr(num_nodes=NUM_NODES, avg_deg=AVG_DEG, seed=0):
  """CSR form of `build_graph`, cached: the COO->CSR sort costs ~60s
  at products scale on this box and dominated the per-session cost of
  the multi-session bench harness.  Returns ``(indptr, indices,
  edge_ids)`` for ``Dataset.init_graph(layout='CSR')``."""
  import os
  path = (f'/tmp/.glt_bench_csr_v{GRAPH_VERSION}'
          f'_{num_nodes}_{avg_deg}_{seed}.npz')
  if os.path.exists(path):
    d = np.load(path)
    return (d['indptr'].astype(np.int64), d['indices'].astype(np.int64),
            d['eids'].astype(np.int64))
  rows, cols = build_graph(num_nodes, avg_deg, seed)
  order = np.argsort(rows, kind='stable')
  indices = cols[order]
  indptr = np.zeros(num_nodes + 1, np.int64)
  np.cumsum(np.bincount(rows, minlength=num_nodes), out=indptr[1:])
  tmp = f'{path}.{os.getpid()}.tmp.npz'
  np.savez(tmp[:-4], indptr=indptr, indices=indices.astype(np.int32),
           eids=order.astype(np.int32))
  os.replace(tmp, path)
  return indptr, indices.astype(np.int64), order.astype(np.int64)


def build_graph_csr_device(num_nodes=NUM_NODES, avg_deg=AVG_DEG, seed=0):
  """Device-side twin of `build_graph_csr`: the same power-law-ish
  edge recipe (0.3 hub mixture, squared-uniform hub targets) generated
  and CSR-sorted entirely on the accelerator.  Zero host↔device
  transfer — on a tunneled chip the host CSR's ~0.5 GB upload swings
  from ~3 s to minutes with tunnel weather, and it dominated the old
  per-session fixed cost.  The graph is statistically identical to the
  host generator's but NOT bit-identical (different RNG); same-seed
  calls are deterministic across sessions, which is what
  cross-session comparability needs.

  Returns device ``(indptr, indices, edge_ids)`` for
  ``Dataset.init_graph(layout='CSR')``'s device-native path.
  """
  import jax
  import jax.numpy as jnp

  @jax.jit
  def build(key):
    e = num_nodes * avg_deg
    k1, k2, k3 = jax.random.split(key, 3)
    rows = jax.random.randint(k1, (e,), 0, num_nodes, jnp.int32)
    hub = jax.random.uniform(k2, (e,)) < 0.3
    u = jax.random.uniform(k3, (e,))
    hub_cols = (u * u * num_nodes).astype(jnp.int32)
    unif_cols = (u * num_nodes).astype(jnp.int32)
    cols = jnp.where(hub, hub_cols, unif_cols)
    # canonical sorted-CSR (cols ascending within each row) via
    # two-pass stable lexsort — a fused int64 key would truncate to
    # int32 without jax_enable_x64; the strict-negative sampler's
    # `edge_in_csr` binary search requires the sorted form
    by_col = jnp.argsort(cols, stable=True)
    order = by_col[jnp.argsort(rows[by_col], stable=True)]
    indices = cols[order]
    rows_sorted = rows[order]
    indptr = jnp.searchsorted(
        rows_sorted, jnp.arange(num_nodes + 1, dtype=jnp.int32),
        side='left').astype(jnp.int32)
    return indptr, indices, order.astype(jnp.int32)

  return build(jax.random.key(seed))


def build_bipartite_csr_device(n_src: int, n_dst: int, avg_deg: int,
                               seed: int = 0, hub_frac: float = 0.3):
  """Device-built sorted-CSR for one (src -> dst) edge type — the
  hetero sibling of `build_graph_csr_device` (same hub mixture,
  zero host↔device transfer, deterministic per seed)."""
  import jax
  import jax.numpy as jnp

  @jax.jit
  def build(key):
    e = n_src * avg_deg
    k1, k2, k3 = jax.random.split(key, 3)
    rows = jax.random.randint(k1, (e,), 0, n_src, jnp.int32)
    hub = jax.random.uniform(k2, (e,)) < hub_frac
    u = jax.random.uniform(k3, (e,))
    cols = jnp.where(hub, (u * u * n_dst).astype(jnp.int32),
                     (u * n_dst).astype(jnp.int32))
    by_col = jnp.argsort(cols, stable=True)
    order = by_col[jnp.argsort(rows[by_col], stable=True)]
    indices = cols[order]
    rows_sorted = rows[order]
    indptr = jnp.searchsorted(
        rows_sorted, jnp.arange(n_src + 1, dtype=jnp.int32),
        side='left').astype(jnp.int32)
    return indptr, indices
  return build(jax.random.key(seed))


def sample_window_bytes(batch: int, fanouts) -> int:
  """Analytic upper bound on HBM bytes one multihop sample's window
  gathers move (`ops/neighbor.py` exact-without-replacement path) —
  the elision-floor basis for sampling walls (r5 protocol)."""
  from graphlearn_tpu.ops.neighbor import default_window
  frontier, total = batch, 0
  for k in fanouts:
    total += frontier * default_window(k) * 4
    frontier *= k
  return total


def make_sample_burst(fanouts, node_cap: int, iters: int):
  """The r5 sampling-throughput program, ONE definition for
  `bench.py` and `bench_sampler.py`: a scan over ``[iters, B]`` seed
  batches whose body is the fused multihop sampler, returning the
  accepted-edge total (the value pull that forces real execution).
  Named unpacking so a `_multihop_sample` signature change fails
  loudly instead of summing the wrong array."""
  import jax
  import jax.numpy as jnp
  from jax import lax
  from graphlearn_tpu.sampler.neighbor_sampler import _multihop_sample

  def burst(indptr, indices, seeds_all, key):
    def body(acc, xs):
      i, seeds = xs
      (_nodes, _count, _row, _col, _edge, emask, _seed_local, _nsn,
       _nse) = _multihop_sample(
           indptr, indices, None, seeds, jax.random.fold_in(key, i),
           fanouts=tuple(fanouts), node_cap=node_cap, with_edge=False,
           sort_locality=True)
      return acc + jnp.sum(emask, dtype=jnp.int32), None
    total, _ = lax.scan(body, jnp.int32(0), (
        jnp.arange(iters, dtype=jnp.int32), seeds_all))
    return total

  return burst


def emit(metric: str, value: float, unit: str, baseline: float = None,
         **extra):
  rec = {'metric': metric, 'value': round(float(value), 3), 'unit': unit}
  if baseline:
    rec['vs_baseline'] = round(float(value) / baseline, 4)
  rec.update(extra)
  print(json.dumps(rec), flush=True)
  tee_record(rec)


def run_id() -> str:
  """Stable identifier for THIS sweep run, minted once by the first
  process to ask and inherited by its fresh per-config subprocesses
  through the environment — the sidecar appends across runs, so every
  record needs a key consumers can group/dedupe by."""
  rid = os.environ.get('GLT_BENCH_RUN_ID')
  if not rid:
    rid = time.strftime('%Y%m%dT%H%M%S') + f'-{os.getpid()}'
    os.environ['GLT_BENCH_RUN_ID'] = rid
  return rid


def tee_record(rec: dict) -> None:
  """File-artifact tee for sweep records: every emitted config line
  also appends to the JSONL sidecar (`telemetry.sink.append_record`,
  `GLT_BENCH_RECORDS` overrides the path, default
  ``BENCH_ARTIFACT.jsonl``) — line-atomic across the sweeps' fresh
  subprocesses, so a truncated stdout capture no longer loses
  measurements.  Records carry a ``run`` id (`run_id`) so re-runs in
  one directory stay distinguishable.  Best-effort: a sink failure
  never kills a bench."""
  try:
    from graphlearn_tpu.telemetry import sink
    sink.append_record(dict(rec, run=run_id()))
  except Exception:               # noqa: BLE001 — telemetry is optional
    pass


class Timer:
  """Wall-clock over N iters; call ``sync`` on a device array first."""

  def __enter__(self):
    self.t0 = time.perf_counter()
    return self

  def __exit__(self, *exc):
    self.dt = time.perf_counter() - self.t0


def cpu_mesh_env(num_devices: int) -> dict:
  """Subprocess env forcing an ``num_devices``-device virtual CPU mesh.

  Must be applied at process SPAWN: a sitecustomize on PYTHONPATH
  pre-imports jax and latches the platform before user code runs, so
  in-process env changes are too late (see tests/conftest.py).
  """
  run_id()      # mint the sweep's run id HERE, in the parent, so the
                # env snapshot below hands every worker the same one
  env = dict(os.environ)
  env.pop('PALLAS_AXON_POOL_IPS', None)     # don't register the TPU plugin
  env['JAX_PLATFORMS'] = 'cpu'
  flags = env.get('XLA_FLAGS', '')
  flags = ' '.join(f for f in flags.split()
                   if '--xla_force_host_platform_device_count' not in f)
  env['XLA_FLAGS'] = (
      f'{flags} --xla_force_host_platform_device_count={num_devices}'
      .strip())
  return env


def run_in_fresh_process(script: str, args, env=None) -> bool:
  """Re-exec one benchmark config in a clean interpreter and stream
  its output; returns False (and keeps going) if the config failed,
  so one bad configuration never aborts the rest of a sweep.

  On tunneled chips only the FIRST timed burst of a process measures
  true device throughput — after it, dispatch degrades ~100x for the
  process lifetime (measured; see benchmarks/README).  Sweeps
  therefore isolate every configuration in its own process.
  """
  import subprocess
  import sys
  # every config must record the SAME run id: mint it in the parent
  # and plant it into the child env even when the caller snapshotted
  # that env before the id existed (env=None inherits os.environ,
  # which run_id() just stamped)
  rid = run_id()
  if env is not None and 'GLT_BENCH_RUN_ID' not in env:
    env = dict(env, GLT_BENCH_RUN_ID=rid)
  cmd = [sys.executable, script] + [str(a) for a in args]
  rc = subprocess.run(cmd, env=env).returncode
  if rc != 0:
    print(json.dumps({'metric': 'config_failed', 'args': list(map(str, args)),
                      'returncode': rc}), flush=True)
  return rc == 0
