"""Neighbor-sampling throughput across batch sizes and fanouts.

Reference counterpart: `benchmarks/api/bench_sampler.py` — metric
"Sampled Edges per secs (M)".  The root `bench.py` runs the single
flagship config; this sweeps the grid the reference's scale-up plot
covers.

Usage::

    python benchmarks/bench_sampler.py [--cpu] [--quick]
"""
import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from benchmarks.common import NUM_NODES, Timer, build_graph, emit


CONFIGS = [((15, 10, 5), 512), ((15, 10, 5), 1024), ((15, 10, 5), 4096),
           ((10, 10), 512), ((10, 10), 1024), ((10, 10), 4096),
           ((25, 10), 512), ((25, 10), 1024), ((25, 10), 4096)]


def run_one(fanout, batch, quick: bool, cpu: bool):
  import jax
  if cpu:
    jax.config.update('jax_platforms', 'cpu')
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.sampler import NeighborSampler

  import jax.numpy as jnp
  from benchmarks.common import make_sample_burst, sample_window_bytes

  n = 200_000 if quick else None
  iters = 5 if quick else 20
  rows, cols = (build_graph(n) if n else build_graph())
  n = n or int(max(rows.max(), cols.max())) + 1
  ds = Dataset().init_graph((rows, cols), layout='COO', num_nodes=n)
  g = ds.get_graph()
  g.lazy_init()
  rng = np.random.default_rng(1)
  sampler = NeighborSampler(g, list(fanout), seed=0)
  node_cap = sampler.node_capacity(batch)
  seeds_all = jnp.asarray(
      rng.integers(0, n, (iters, batch)).astype(np.int32))

  # r5 pull protocol (see bench.py / benchmarks/README): the whole
  # burst is ONE scan program — a per-batch dispatch loop measures
  # tunnel dispatch latency, and `block_until_ready` walls are not
  # trustworthy.  The FIRST execution carries ~5-7 s of program load
  # and the SECOND can be ELIDED — time both, keep the second only
  # if it clears the analytic window-bytes floor, else fall back to
  # the first (overstated by the load cost, flagged).
  burst = make_sample_burst(fanout, node_cap, iters)
  comp = jax.jit(burst).lower(g.indptr, g.indices, seeds_all,
                              jax.random.key(5)).compile()
  with Timer() as t1:
    edges = int(comp(g.indptr, g.indices, seeds_all,
                     jax.random.key(6)))
  with Timer() as t2:
    edges = int(comp(g.indptr, g.indices, seeds_all,
                     jax.random.key(7)))
  platform = jax.devices()[0].platform
  floor = (iters * sample_window_bytes(batch, fanout) / 819e9
           if platform == 'tpu' else 0.0)
  suspect = t2.dt < floor
  dt = t1.dt if suspect else t2.dt
  emit('sampler_edges_per_sec', edges / dt / 1e6, 'M edges/s',
       fanout=list(fanout), batch=batch,
       first_exec_secs=round(t1.dt, 4), steady_secs=round(t2.dt, 4),
       floor_secs=round(floor, 4), suspect_elision=bool(suspect),
       platform=platform)


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--cpu', action='store_true')
  ap.add_argument('--quick', action='store_true',
                  help='small graph, fewer iters')
  ap.add_argument('--one', type=str, default=None,
                  help='internal: "15,10,5:1024" runs one config inline')
  args = ap.parse_args()

  if args.one:
    fan, batch = args.one.split(':')
    run_one(tuple(int(k) for k in fan.split(',')), int(batch),
            args.quick, args.cpu)
    return

  from benchmarks.common import run_in_fresh_process
  build_graph(200_000 if args.quick else NUM_NODES)   # warm the cache
  failed = 0
  for fanout, batch in CONFIGS:
    extra = (['--quick'] if args.quick else []) + \
            (['--cpu'] if args.cpu else [])
    ok = run_in_fresh_process(
        __file__, ['--one', ','.join(map(str, fanout)) + f':{batch}']
        + extra)
    failed += not ok
  if failed:
    print(f'{failed}/{len(CONFIGS)} configs failed', file=sys.stderr)
    sys.exit(1)


if __name__ == '__main__':
  main()
