"""Neighbor-sampling throughput across batch sizes and fanouts.

Reference counterpart: `benchmarks/api/bench_sampler.py` — metric
"Sampled Edges per secs (M)".  The root `bench.py` runs the single
flagship config; this sweeps the grid the reference's scale-up plot
covers.

Usage::

    python benchmarks/bench_sampler.py [--cpu] [--quick]
"""
import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from benchmarks.common import Timer, build_graph, emit


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--cpu', action='store_true')
  ap.add_argument('--quick', action='store_true',
                  help='small graph, fewer iters')
  args = ap.parse_args()

  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.sampler import NeighborSampler, NodeSamplerInput

  n = 200_000 if args.quick else None
  iters = 5 if args.quick else 20
  rows, cols = (build_graph(n) if n else build_graph())
  n = n or int(max(rows.max(), cols.max())) + 1
  ds = Dataset().init_graph((rows, cols), layout='COO', num_nodes=n)
  g = ds.get_graph()
  g.lazy_init()
  rng = np.random.default_rng(1)

  for fanout in ([15, 10, 5], [10, 10], [25, 10]):
    for batch in (512, 1024, 4096):
      sampler = NeighborSampler(g, fanout, seed=0)

      def one(batch=batch):
        seeds = rng.integers(0, n, batch).astype(np.int32)
        return sampler.sample_from_nodes(NodeSamplerInput(node=seeds))

      out = one()
      out.row.block_until_ready()          # compile
      outs = []
      with Timer() as t:
        for _ in range(iters):
          outs.append(one())
        outs[-1].row.block_until_ready()
      edges = sum(int(np.asarray(o.edge_mask).sum()) for o in outs)
      emit(f'sampler_edges_per_sec', edges / t.dt / 1e6, 'M edges/s',
           fanout=fanout, batch=batch,
           platform=jax.devices()[0].platform)


if __name__ == '__main__':
  main()
