"""GraphSAGE training epoch time — the BASELINE.json headline metric.

Reference counterpart: per-epoch wall-clock of
`examples/train_sage_ogbn_products.py` (the number GLT's README quotes
against a single A100).  Full pipeline per batch: seed shuffle ->
multi-hop sampling -> feature/label collation -> fused train step
(forward, backward, adam) on device.

Usage::

    python benchmarks/bench_train.py [--cpu] [--quick]
"""
import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from benchmarks.common import NUM_NODES, build_graph, emit


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--cpu', action='store_true')
  ap.add_argument('--quick', action='store_true')
  ap.add_argument('--dim', type=int, default=100,    # ogbn-products dim
                  help='feature dim')
  ap.add_argument('--hidden', type=int, default=256)
  ap.add_argument('--classes', type=int, default=47)  # products classes
  ap.add_argument('--epochs', type=int, default=3)
  ap.add_argument('--bf16', action='store_true',
                  help='bfloat16 model compute (MXU half-width)')
  ap.add_argument('--fused', action='store_true',
                  help='time loader.FusedEpoch (whole-epoch lax.scan '
                       'program, remat backward) instead of the '
                       'per-batch loop')
  ap.add_argument('--tree', action='store_true',
                  help='time loader.FusedTreeEpoch + models.TreeSAGE '
                       '(scatter-free/sort-free tree layout — the r5 '
                       'flagship, 12.4x the subgraph fused step on '
                       'v5e); combine with --bf16 for MXU compute')
  args = ap.parse_args()
  if args.epochs < 1:
    ap.error('--epochs must be >= 1 (epoch 0 is the untimed warmup)')
  if args.tree and args.fused:
    ap.error('--tree and --fused are mutually exclusive')

  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import optax
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.loader import NeighborLoader
  from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                     make_supervised_step)

  n = 200_000 if args.quick else NUM_NODES
  rows, cols = build_graph(n)
  rng = np.random.default_rng(0)
  feats = rng.standard_normal((n, args.dim)).astype(np.float32)
  labels = rng.integers(0, args.classes, n).astype(np.int32)
  ds = (Dataset()
        .init_graph((rows, cols), layout='COO', num_nodes=n)
        .init_node_features(feats, split_ratio=1.0)
        .init_node_labels(labels))

  # ogbn-products train split is ~196k seeds (8%); mirror that ratio
  train_idx = rng.permutation(n)[:max(n // 12, 1)]
  bs = 1024
  import jax.numpy as jnp
  tx = optax.adam(3e-3)

  times = []
  if args.tree:
    # the tree path needs none of the per-batch loader/model setup
    from graphlearn_tpu.loader import FusedTreeEpoch
    from graphlearn_tpu.models import TreeSAGE
    tmodel = TreeSAGE(hidden_features=args.hidden,
                      out_features=args.classes, num_layers=3,
                      dtype=jnp.bfloat16 if args.bf16 else None)
    tree = FusedTreeEpoch(ds, [15, 10, 5], train_idx, tmodel, tx,
                          batch_size=bs, shuffle=True, seed=0,
                          max_steps_per_program=100)
    tstate = tree.init_state(jax.random.key(0))
    for _ in range(2):               # compile + program-load warmup
      tstate, _ = tree.run(tstate)
    float(jnp.sum(jax.tree_util.tree_leaves(tstate.params)[0]))
    for epoch in range(args.epochs):
      t0 = time.perf_counter()
      tstate, _ = tree.run(tstate)
      float(jnp.sum(jax.tree_util.tree_leaves(tstate.params)[0]))
      times.append(time.perf_counter() - t0)
    emit('train_epoch_secs', float(np.min(times)), 's',
         epochs=args.epochs, steps=len(tree), mode='tree-fused',
         dtype='bf16' if args.bf16 else 'f32',
         platform=jax.devices()[0].platform)
    return

  loader = NeighborLoader(ds, [15, 10, 5], train_idx, batch_size=bs,
                          shuffle=True, seed=0)
  model = GraphSAGE(hidden_features=args.hidden, out_features=args.classes,
                    num_layers=3,
                    dtype=jnp.bfloat16 if args.bf16 else None)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(loader)), tx)
  step = make_supervised_step(apply_fn, tx, bs)

  if args.fused:
    from graphlearn_tpu.loader import FusedEpoch
    fused = FusedEpoch(ds, [15, 10, 5], train_idx, apply_fn, tx,
                       batch_size=bs, shuffle=True, seed=0, remat=True)
    # two warmups: compile + the donated-input recompile
    for _ in range(2):
      state, _ = fused.run(state)
    jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
    for epoch in range(args.epochs):
      t0 = time.perf_counter()
      state, _ = fused.run(state)
      jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
      times.append(time.perf_counter() - t0)
  else:
    # epoch 0 = warmup/compile (not reported)
    for epoch in range(args.epochs + 1):
      t0 = time.perf_counter()
      for batch in loader:
        state, loss, _ = step(state, batch)
      jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
      dt = time.perf_counter() - t0
      if epoch > 0:
        times.append(dt)
  best = min(times)
  emit('train_epoch_secs', best, 's',
       seeds=len(train_idx), batch=bs,
       steps_per_sec=round(len(loader) / best, 2),
       dtype='bf16' if args.bf16 else 'f32',
       mode='fused' if args.fused else 'per-batch',
       platform=jax.devices()[0].platform)


if __name__ == '__main__':
  main()
