"""Streaming ingestion bench: freshness vs throughput under live
serving load (ISSUE 14).

The open-loop question the streaming plane exists to answer: how many
edge-insert events/s can the WAL → delta-CSR → publish pipeline
sustain while the Zipf serving tier keeps its p99?  Two phases, same
seeded open-loop schedule (`bench_serving.make_schedule` — the
coordinated-omission-resistant protocol):

  1. **baseline** — serving only, no ingest: the p99 reference line.
  2. **ingest** — the same traffic while an ingest thread drives
     `IngestPipeline.ingest` open-throttle (durable WAL append +
     merge + RCU publish per batch).  Reported: applied events/s,
     serving p50/p95/p99 DURING ingest, versions published, final
     lag.

Acceptance (the worker exits nonzero otherwise): ZERO sheds and zero
errors during steady-state ingest, zero recompiles after warmup (the
stream's ``reserve_edges`` headroom keeps every publish at one shape
— the ingest thread stops at the capacity fence rather than force a
mid-run recompile, and reports if it hit it), and zero final lag
(everything appended was applied).

Feeds ``dist.ingest.events_per_sec`` ('higher') and
``dist.ingest.p99_during_ingest_ms`` ('lower') through bench.py.

Knobs: CLI flags below; the pipeline reads ``GLT_INGEST_WAL_DIR`` /
``GLT_INGEST_COMPACT_EVERY`` / ``GLT_INGEST_MAX_LAG``
(benchmarks/README "Streaming ingestion (r15)").
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from benchmarks.bench_serving import (_percentile, drive_open_loop,  # noqa: E402
                                      make_schedule)


def build_streaming_dataset(n: int, dim: int, reserve: int, seed=0):
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.streaming import StreamingGraph
  rng = np.random.default_rng(seed)
  deg = 8
  rows = np.repeat(np.arange(n), deg)
  cols = rng.integers(0, n, rows.shape[0])
  feats = rng.random((n, dim), dtype=np.float32)
  stream = StreamingGraph.from_coo(rows, cols, num_nodes=n,
                                   reserve_edges=reserve * len(rows))
  ds = Dataset().init_node_features(feats).attach_stream(stream)
  return ds, stream


def run_serving_phase(label, frontend, engine, plan, result,
                      warm_compiles):
  t0 = time.perf_counter()
  outcomes = drive_open_loop(frontend, plan)
  run_s = time.perf_counter() - t0
  lats = sorted(l for l, o in outcomes if o == 'ok' and l is not None)
  row = {
      'label': label, 'open_loop': True,
      'requests': len(plan),
      'completed': len(lats),
      'shed': sum(1 for _, o in outcomes if o == 'shed'),
      'errors': sum(1 for _, o in outcomes if o == 'error'),
      'p50_ms': round(_percentile(lats, 0.50) or 0.0, 3),
      'p95_ms': round(_percentile(lats, 0.95) or 0.0, 3),
      'p99_ms': round(_percentile(lats, 0.99) or 0.0, 3),
      'qps': round(len(lats) / max(run_s, 1e-9), 1),
      'recompiles_after_warmup':
          engine.compile_count() - warm_compiles,
  }
  result[label] = row
  print(json.dumps(result), flush=True)
  return row


def main(argv=None):
  ap = argparse.ArgumentParser(description=__doc__.split('\n')[0])
  ap.add_argument('--nodes', type=int, default=8000)
  ap.add_argument('--dim', type=int, default=32)
  ap.add_argument('--fanout', type=int, nargs='+', default=[5, 3])
  ap.add_argument('--rate', type=float, default=150.0,
                  help='open-loop serving arrival rate, requests/s')
  ap.add_argument('--duration', type=float, default=2.5)
  ap.add_argument('--zipf-a', type=float, default=1.1)
  ap.add_argument('--batch-events', type=int, default=256,
                  help='edges per ingest() call (one WAL record)')
  ap.add_argument('--reserve', type=int, default=8,
                  help='edge-capacity headroom factor over the base '
                       'graph (publishes stay at ONE shape inside it)')
  ap.add_argument('--wal-dir', default=None,
                  help='WAL root (default: a fresh temp dir)')
  ap.add_argument('--cpu', action='store_true')
  args = ap.parse_args(argv)
  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  from graphlearn_tpu.models.tree import TreeSAGE
  from graphlearn_tpu.serving import ServingFrontend
  from graphlearn_tpu.serving.engine import ServingEngine
  from graphlearn_tpu.streaming import IngestPipeline
  from graphlearn_tpu.telemetry import recorder
  recorder.enable(None)

  n = args.nodes
  ds, stream = build_streaming_dataset(n, args.dim, args.reserve)
  model = TreeSAGE(hidden_features=32, out_features=16,
                   num_layers=len(args.fanout))
  eng = ServingEngine(ds, args.fanout, model=model, seed=11)
  eng.init_params(jax.random.key(0))
  t0 = time.perf_counter()
  eng.warmup()
  warm_secs = time.perf_counter() - t0
  warm_compiles = eng.compile_count()
  fe = ServingFrontend(eng, auto_start=True, warmup=False)
  result = {'num_nodes': n, 'fanout': list(args.fanout),
            'platform': jax.devices()[0].platform,
            'warmup_secs': round(warm_secs, 2),
            'base_edges': stream.num_edges,
            'edge_capacity': stream.edge_capacity,
            'base_version': stream.version}

  plan = make_schedule(args.rate, args.duration, n, args.zipf_a,
                       seed=3)
  base = run_serving_phase('baseline', fe, eng, plan, result,
                           warm_compiles)

  wal_dir = args.wal_dir or tempfile.mkdtemp(prefix='glt-ingest-')
  pipe = IngestPipeline(stream, wal_dir=wal_dir)
  stop = threading.Event()
  ing = {'events': 0, 'batches': 0, 'secs': 0.0, 'capacity_fence': 0}
  rng = np.random.default_rng(17)
  # the capacity fence: stop before a publish would cross the padded
  # edge capacity (a shape change would recompile the warm ladder
  # mid-run — that is a sizing decision, not a latency datum)
  fence = stream.edge_capacity - 2 * args.batch_events

  def ingest_loop():
    t0 = time.perf_counter()
    try:
      while not stop.is_set():
        if stream.num_edges >= fence:
          ing['capacity_fence'] = 1
          break
        src = rng.integers(0, n, args.batch_events)
        dst = rng.integers(0, n, args.batch_events)
        pipe.ingest(src, dst)
        ing['events'] += args.batch_events
        ing['batches'] += 1
    except Exception as e:               # noqa: BLE001 — reported
      ing['error'] = f'{type(e).__name__}: {e}'
    finally:
      # always stamp the wall: a raise mid-run must not leave 0.0
      # and turn events/max(secs, 1e-9) into an absurd throughput
      ing['secs'] = time.perf_counter() - t0

  v0 = stream.version
  t = threading.Thread(target=ingest_loop, daemon=True)
  t.start()
  row = run_serving_phase('ingest', fe, eng, plan, result,
                          warm_compiles)
  stop.set()
  t.join(30.0)
  fe.shutdown()
  lag = int(pipe.wal.lifetime_events - pipe.applied_events)
  ev_s = round(ing['events'] / max(ing['secs'], 1e-9), 1)
  result.update({
      'events_per_sec': ev_s,
      'p99_during_ingest_ms': row['p99_ms'],
      'p99_baseline_ms': base['p99_ms'],
      'ingested_events': ing['events'],
      'ingest_batches': ing['batches'],
      'versions_published': stream.version - v0,
      'graph_version': stream.version,
      'final_lag_events': lag,
      'capacity_fence_hit': ing['capacity_fence'],
      'compactions': pipe.stats()['compactions'],
      'shed': row['shed'], 'errors': row['errors'],
  })
  if 'error' in ing:
    result['ingest_error'] = ing['error']
  pipe.close()
  print(json.dumps(result), flush=True)
  rc = 0
  if row['shed'] or row['errors']:
    print(f"WARNING: serving shed {row['shed']} / errored "
          f"{row['errors']} request(s) during steady-state ingest — "
          'the serve-during-ingest contract is broken',
          file=sys.stderr)
    rc = 1
  if row['recompiles_after_warmup'] or base['recompiles_after_warmup']:
    print('WARNING: recompile(s) after warmup — a publish escaped '
          'the reserved edge capacity', file=sys.stderr)
    rc = 1
  if lag != 0:
    print(f'WARNING: {lag} appended event(s) never applied',
          file=sys.stderr)
    rc = 1
  if ing['events'] == 0:
    print('WARNING: ingest thread applied nothing — the events/s '
          'datum is vacuous', file=sys.stderr)
    rc = 1
  if 'error' in ing:
    print(f"WARNING: ingest thread died: {ing['error']}",
          file=sys.stderr)
    rc = 1
  return rc


if __name__ == '__main__':
  sys.exit(main())
