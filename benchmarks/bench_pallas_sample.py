"""Pallas fused sampling pipeline microbench (ISSUE 18, r19):
FusedEpoch step time through the `sample_one_hop_auto` dispatcher,
pinned-host cold-gather GB/s at split<1, delta-CSR merge events/s.

Three guarded rows (telemetry/regress.py "pallas." block):

  * ``fused_step_ms`` — knob-OFF FusedEpoch ms/step on the dispatcher-
    threaded path.  The r19 threading (window-table staging in the
    epoch's `_dev` dict, the trace-time dispatch) must cost the
    DEFAULT path nothing; this row is the watchdog.
  * ``feature_lookup_gbps`` — the pinned-host zero-copy cold gather at
    split_ratio 0.25, pinned against the FIXED 1.355 GB/s untiered
    XLA line (ROADMAP r18 roofline).  HARDWARE-ONLY: the pin is a TPU
    number, so the guarded key is stamped only when a TPU is attached;
    under JAX_PLATFORMS=cpu the row carries the raw CPU numbers
    unguarded (`*_cpu` keys) and the guard skips cleanly.
  * ``delta_merge_events_per_sec`` — the host delta-CSR merge rate
    (platform-independent; the device kernel row is TPU-only).

Kernel-ON timings (``fused_step_ms_kernel``, the device merge) are
likewise TPU-only: on CPU the kernels run in Pallas interpret mode,
whose walls measure the interpreter, not the lowering — a number
worse than meaningless in a trajectory.  The dispatch LADDER however
is platform-free and always reported: one tiny knob-ON trace with the
flight recorder on, counting ``pallas.dispatch`` / ``pallas.fallback``
events (plus one forced-fallback probe pinning the reason string).

Usage::

    python benchmarks/bench_pallas_sample.py [--cpu] [--quick]

Emits per-row `common.emit` lines; the LAST stdout line is the full
JSON row (bench.py's pallas-phase subprocess parses it bottom-up,
same salvage contract as every other phase).
"""
import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from benchmarks.common import Timer, build_graph, emit

#: the r18 untiered XLA feature-gather line (GB/s) the pinned path is
#: measured against on hardware — also the regress pin_baseline
XLA_UNTIERED_GBPS = 1.355


def _dispatch_ladder(jax, jnp):
  """Knob-ON dispatch accounting on a toy graph: one supported trace
  (-> pallas.dispatch) and one replace=True probe (-> pallas.fallback
  with the 'replace-arm' reason).  Pure tracing discipline — valid on
  every platform, CPU included (interpret mode makes the toy shapes
  cheap)."""
  from graphlearn_tpu.ops.pallas_sample import sample_one_hop_auto
  from graphlearn_tpu.telemetry.recorder import recorder
  rng = np.random.default_rng(0)
  n = 512
  deg = rng.poisson(10, n)
  indptr = np.zeros(n + 1, np.int64)
  np.cumsum(deg, out=indptr[1:])
  indices = jnp.asarray(
      rng.integers(0, n, int(indptr[-1])).astype(np.int32))
  indptr = jnp.asarray(indptr)
  seeds = jnp.asarray(rng.integers(0, n, 64).astype(np.int32))
  key = jax.random.PRNGKey(0)
  os.environ['GLT_PALLAS_SAMPLE'] = '1'
  was = recorder.enabled
  recorder.enable()
  try:
    recorder.clear()
    sample_one_hop_auto(indptr, indices, seeds, 8, key)
    sample_one_hop_auto(indptr, indices, seeds, 8, key, replace=True)
    evs = recorder.events()
    ladder = {
        'dispatch': sum(e['kind'] == 'pallas.dispatch' for e in evs),
        'fallback': sum(e['kind'] == 'pallas.fallback' for e in evs),
        'fallback_reasons': sorted({e['reason'] for e in evs
                                    if e['kind'] == 'pallas.fallback'}),
    }
  finally:
    recorder.clear()
    if not was:
      recorder.disable()
    os.environ.pop('GLT_PALLAS_SAMPLE', None)
  return ladder


def _fused_step_row(jax, jnp, row, n, on_tpu, quick):
  """FusedEpoch ms/step, knob OFF (guarded) and — on hardware — knob
  ON (the fused kernel path; rebuilt because the knob resolves at
  epoch __init__)."""
  import optax
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.loader import FusedEpoch, NeighborLoader
  from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                     make_supervised_step)  # noqa: F401

  dim, classes, batch = 64, 16, 256
  fanouts = [10, 5]
  rows, cols = build_graph(n)
  rng = np.random.default_rng(0)
  feats = rng.standard_normal((n, dim)).astype(np.float32)
  labels = (np.arange(n) % classes).astype(np.int32)
  ds = (Dataset()
        .init_graph((rows, cols), layout='COO', num_nodes=n)
        .init_node_features(feats, split_ratio=1.0)
        .init_node_labels(labels))
  steps = 4 if quick else 8
  train_idx = rng.permutation(n)[:batch * steps]
  loader = NeighborLoader(ds, fanouts, train_idx[:batch],
                          batch_size=batch, shuffle=False, seed=0)
  first = next(iter(loader))
  model = GraphSAGE(hidden_features=64, out_features=classes,
                    num_layers=2)
  tx = optax.adam(1e-3)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), first, tx)

  def timed_epoch(knob):
    if knob:
      os.environ['GLT_PALLAS_SAMPLE'] = '1'
    else:
      os.environ.pop('GLT_PALLAS_SAMPLE', None)
    try:
      ep = FusedEpoch(ds, fanouts, train_idx, apply_fn, tx,
                      batch_size=batch, shuffle=True, seed=0,
                      max_steps_per_program=steps)
      st = state
      st, _ = ep.run(st)            # compile + first epoch
      jax.tree_util.tree_leaves(st.params)[0].block_until_ready()
      t0 = time.perf_counter()
      st, _ = ep.run(st)
      jax.tree_util.tree_leaves(st.params)[0].block_until_ready()
      return 1000.0 * (time.perf_counter() - t0) / len(ep)
    finally:
      os.environ.pop('GLT_PALLAS_SAMPLE', None)

  row['fused_step_ms'] = round(timed_epoch(False), 3)
  emit('pallas_fused_step_ms', row['fused_step_ms'], 'ms/step',
       impl='xla-dispatcher', steps=steps, batch=batch)
  if on_tpu:
    row['fused_step_ms_kernel'] = round(timed_epoch(True), 3)
    emit('pallas_fused_step_ms', row['fused_step_ms_kernel'],
         'ms/step', impl='pallas', steps=steps, batch=batch)
  else:
    row['fused_step_ms_kernel'] = None
    row['fused_kernel_skipped'] = 'cpu-interpret'


def _cold_gather_row(jax, row, n, on_tpu, quick):
  """Feature-lookup GB/s at split_ratio 0.25: compact host path vs
  the pinned-host zero-copy gather, same id sets, cache OFF so the
  rows measure the miss path the pinned buffer serves."""
  from graphlearn_tpu.data import Feature
  dim = 128
  rng = np.random.default_rng(1)
  feats = rng.standard_normal((n, dim)).astype(np.float32)
  iters = 5 if quick else 20
  id_sets = [rng.integers(0, n, 4096).astype(np.int64)
             for _ in range(iters)]

  os.environ['GLT_COLD_CACHE_ROWS'] = '0'
  gbps = {}
  try:
    for impl, knob in (('xla', False), ('pinned', True)):
      if knob:
        os.environ['GLT_PALLAS_COLD'] = '1'
      else:
        os.environ.pop('GLT_PALLAS_COLD', None)
      f = Feature(feats, split_ratio=0.25)
      for ids in id_sets:
        f[ids].block_until_ready()          # warm / build the buffer
      nbytes = 0
      with Timer() as t:
        res = None
        for ids in id_sets:
          res = f[ids]
          nbytes += res.size * res.dtype.itemsize
        res.block_until_ready()
      gbps[impl] = nbytes / t.dt / 1e9
      emit('feature_lookup_gbps', gbps[impl], 'GB/s',
           split_ratio=0.25, impl=impl,
           baseline=XLA_UNTIERED_GBPS if on_tpu else None,
           platform=jax.devices()[0].platform)
  finally:
    os.environ.pop('GLT_PALLAS_COLD', None)
    os.environ.pop('GLT_COLD_CACHE_ROWS', None)
  if on_tpu:
    # the guarded key: pinned-path GB/s vs the FIXED 1.355 line
    row['feature_lookup_gbps'] = round(gbps['pinned'], 4)
    row['feature_lookup_gbps_xla_tiered'] = round(gbps['xla'], 4)
  else:
    row['feature_lookup_gbps'] = None
    row['feature_lookup_gbps_cpu'] = round(gbps['pinned'], 4)
    row['feature_lookup_gbps_xla_tiered_cpu'] = round(gbps['xla'], 4)
    row['cold_gather_skipped'] = 'cpu (1.355 pin is a TPU line)'


def _delta_merge_row(jax, row, n, on_tpu, quick):
  """Delta-CSR merge events/s: host merge always (guarded), the
  Pallas rank-kernel merge on hardware only."""
  from graphlearn_tpu.streaming.delta import DeltaSegment, merge_delta_csr
  rng = np.random.default_rng(2)
  deg = rng.poisson(8, n)
  indptr = np.zeros(n + 1, np.int64)
  np.cumsum(deg, out=indptr[1:])
  e = int(indptr[-1])
  indices = np.concatenate(
      [np.sort(rng.integers(0, n, d)) for d in deg if d]
  ).astype(np.int64) if e else np.zeros(0, np.int64)
  eids = np.arange(e, dtype=np.int64)
  events = 2048 if quick else 8192
  seg = DeltaSegment(src=rng.integers(0, n, events).astype(np.int64),
                     dst=rng.integers(0, n, events).astype(np.int64),
                     eids=(np.arange(events) + e).astype(np.int64))
  reps = 3 if quick else 5
  merge_delta_csr(indptr, indices, eids, seg)       # warm allocators
  with Timer() as t:
    for _ in range(reps):
      merge_delta_csr(indptr, indices, eids, seg)
  row['delta_merge_events_per_sec'] = round(reps * events / t.dt, 1)
  emit('delta_merge_events_per_sec', row['delta_merge_events_per_sec'],
       'events/s', impl='host', events=events)
  if on_tpu:
    from graphlearn_tpu.ops.pallas_delta import merge_delta_csr_device
    out = merge_delta_csr_device(indptr, indices, eids, seg,
                                 interpret=False)   # compile
    with Timer() as t:
      for _ in range(reps):
        out = merge_delta_csr_device(indptr, indices, eids, seg,
                                     interpret=False)
    del out
    row['delta_merge_device_events_per_sec'] = round(
        reps * events / t.dt, 1)
    emit('delta_merge_events_per_sec',
         row['delta_merge_device_events_per_sec'], 'events/s',
         impl='pallas', events=events)
  else:
    row['delta_merge_device_events_per_sec'] = None
    row['delta_merge_device_skipped'] = 'cpu-interpret'


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--cpu', action='store_true')
  ap.add_argument('--quick', action='store_true')
  args = ap.parse_args()

  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  on_tpu = jax.default_backend() == 'tpu'
  n = 20_000 if args.quick else 100_000

  row = {'metric': 'pallas_sample', 'platform': jax.devices()[0].platform,
         'nodes': n}
  row['dispatch_ladder'] = _dispatch_ladder(jax, jnp)
  _fused_step_row(jax, jnp, row, n, on_tpu, args.quick)
  _cold_gather_row(jax, row, n, on_tpu, args.quick)
  _delta_merge_row(jax, row, n, on_tpu, args.quick)
  print(json.dumps(row), flush=True)


if __name__ == '__main__':
  main()
