"""Measure the Pallas aligned-overfetch CSR window gather against the
XLA window gather on the REAL chip (VERDICT r2 item 6: turn the "XLA
beats Pallas for sampling" design assertion into a measurement).

Method per benchmarks/README "first-burst validity": device-resident
inputs, vary seeds with fold_in-free host rotation staged up front,
dispatch N async then block once, best of 3 windows.

Usage (plain python = the tunneled TPU; only one TPU process at once)::

    python benchmarks/bench_pallas_window.py [--quick]
"""
import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from benchmarks.common import build_graph_csr, emit


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--quick', action='store_true')
  ap.add_argument('--batch', type=int, default=8192)
  ap.add_argument('--window', type=int, default=128)
  ap.add_argument('--iters', type=int, default=30)
  args = ap.parse_args()

  import jax
  import jax.numpy as jnp
  from graphlearn_tpu.ops.pallas_window import (csr_window_gather,
                                                prepare_window_table,
                                                xla_window_gather)
  from graphlearn_tpu.ops.neighbor import sample_one_hop

  n = 500_000 if args.quick else 2_449_029
  indptr, indices, _ = build_graph_csr(n)
  indices = jnp.asarray(indices.astype(np.int32))
  indptr_d = jnp.asarray(indptr.astype(np.int32))
  rng = np.random.default_rng(0)
  iters = args.iters
  b, w = args.batch, args.window
  seed_sets = [jnp.asarray(rng.integers(0, n, b).astype(np.int32))
               for _ in range(iters)]
  start_sets = [indptr_d[s] for s in seed_sets]
  jax.block_until_ready(start_sets)
  bytes_per = b * w * 4

  def timeit(fn, inputs):
    fn(inputs[0]).block_until_ready()          # compile
    best = float('inf')
    for _ in range(3):
      t0 = time.perf_counter()
      outs = [fn(x) for x in inputs]
      outs[-1].block_until_ready()
      best = min(best, time.perf_counter() - t0)
    return best

  dt_x = timeit(lambda s: xla_window_gather(indices, s, w), start_sets)
  # repack ONCE outside the timing loop: the O(E) table build must not
  # masquerade as kernel time
  table = prepare_window_table(indices)
  jax.block_until_ready(table[0])
  dt_p, best_tile = float('inf'), None
  for tile in (8, 16, 32, 64):
    dt = timeit(lambda s: csr_window_gather(indices, s, w, tile=tile,
                                            interpret=False,
                                            table=table),
                start_sets)
    if dt < dt_p:
      dt_p, best_tile = dt, tile
  # context: the full sampler step (window + gumbel top-k + mask)
  key = jax.random.key(0)
  dt_full = timeit(
      lambda s: sample_one_hop(indptr_d, indices, s, 15, key).nbrs,
      seed_sets)

  emit('csr_window_gather_xla', iters * bytes_per / dt_x / 1e9, 'GB/s',
       batch=b, window=w, num_nodes=n,
       platform=jax.devices()[0].platform)
  emit('csr_window_gather_pallas_dma', iters * bytes_per / dt_p / 1e9,
       'GB/s', batch=b, window=w, best_tile=best_tile,
       overfetch_bytes_per_seed=2 * 4096,
       speedup_vs_xla=round(dt_x / dt_p, 3))
  emit('sample_one_hop_full', iters * b / dt_full / 1e6, 'M seeds/s',
       k=15, note='window gather + gumbel topk + mask, for context')


if __name__ == '__main__':
  main()
