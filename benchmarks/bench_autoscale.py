"""Elastic autoscaling + planned handoff bench (ISSUE 19).

Drives a DIURNAL open-loop schedule — a sinusoidal arrival rate,
trough -> peak -> trough, the compressed shape of a day of serving
traffic — twice:

  A. **static baseline**: one replica, fixed, the whole cycle.  The
     peak overloads it; its p99 is what an unmanaged fleet pays.
  B. **elastic drive**: the same schedule against a
     1..``--max-replicas`` fleet sized by the `ElasticController`
     (short SLO windows + widened budget — the bench compresses the
     diurnal cycle, so the burn windows compress with it).  A chaos
     ``scale.spawn`` fault fails the FIRST spawn attempt mid-ramp:
     the controller must roll back typed and re-arm (cooldown not
     spent), so capacity still lands one evaluation later.

A watcher samples the controller's signal plane through the drive;
the burn acceptance excludes the chaos incident window (from the
rolled-back decision until one short-window past the recovering
scale-out — the fault's spike is the INJECTED cost, the bar is what
the controller does about it).

  C. **planned handoff**: a P=8 `DistDataset` epoch with a mid-epoch
     `parallel.handoff` ownership move — the epoch must complete
     byte-identical to the no-handoff reference with ZERO degraded
     batches and exactly ONE book bump (needs an 8-device host mesh:
     run via bench.py, or set XLA_FLAGS --xla_force_host_platform_device_count=8).

Acceptance (WARNING + exit 1 on any miss):
  * >= 1 scale-out AND >= 1 scale-in (the fleet tracked the load);
  * the chaos spawn fault rolled back typed (>= 1 rolled_back);
  * elastic p99 holds vs the static baseline;
  * max burn OUTSIDE the incident window < 1.0;
  * zero failed requests (typed sheds excluded — drain sheds are
    resubmitted after ``retry_after_ms``);
  * handoff: 0 degraded batches, exactly 1 book bump.

Feeds ``dist.autoscale.p99_held_ms`` / ``.burn_max`` /
``.handoff_degraded_batches`` (regress.py, phase 3j).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bench_serving import _percentile, build_dataset, pace_schedule

#: compressed SLO windows for the compressed diurnal cycle: the
#: controller's short/long windows must fit inside a seconds-long
#: bench the way 60 s / 300 s windows fit inside a day
BENCH_SLO_WINDOWS = (1.0, 3.0)
#: widened budget (p90-style): burn 1.0 = 10% of a window violating
BENCH_SLO_BUDGET = 0.1
#: injected per-dispatch cost: with the bench's 8-seed bucket ladder
#: this pins single-replica capacity near ``(1/DISPATCH_DELAY_S) *
#: (8 / avg seeds per request)`` requests/s REGARDLESS of machine
#: speed — the diurnal peak deterministically overloads one replica
#: and two absorb it, so the controller's behavior (not the host's
#: CPU) decides the acceptance
DISPATCH_DELAY_S = 0.05


def make_diurnal_schedule(peak_rps: float, trough_rps: float,
                          duration_s: float, n: int, zipf_a: float,
                          seed: int):
  """Non-homogeneous Poisson arrivals by thinning: rate(t) rides one
  sinusoidal cycle trough -> peak -> trough.  Seeds are Zipf ranks
  through a fixed permutation, sizes skewed small — the bench_serving
  traffic shape on a diurnal envelope."""
  rng = np.random.default_rng(seed)
  arrivals, t = [], 0.0
  while True:
    t += rng.exponential(1.0 / peak_rps)
    if t >= duration_s:
      break
    rate = trough_rps + (peak_rps - trough_rps) * 0.5 * (
        1.0 - np.cos(2.0 * np.pi * t / duration_s))
    if rng.random() < rate / peak_rps:
      arrivals.append(t)
  perm = rng.permutation(n)
  plan = []
  for a in arrivals:
    k = int(rng.choice([1, 1, 1, 1, 2, 2, 4], 1)[0])
    ranks = (rng.zipf(zipf_a, k) - 1) % n
    plan.append((a, perm[ranks].astype(np.int64)))
  return plan


def _shrink_slo(frontend) -> None:
  """Compress the frontend's SLO tracker to the bench windows (the
  snapshot/burn paths read ``windows``/``budget`` live)."""
  frontend.slo.windows = BENCH_SLO_WINDOWS
  frontend.slo.budget = BENCH_SLO_BUDGET
  frontend.slo._tripped = {w: False for w in BENCH_SLO_WINDOWS}


def make_replica(name: str, args):
  """One serving replica: own dataset instance (same build seed —
  byte-identical answers fleet-wide), engine warmed through the
  shared ``GLT_AOT_CACHE_DIR`` (a spawn restores instead of
  compiling — the controller's warm pin), bench SLO windows."""
  from graphlearn_tpu.serving import (LocalReplica, ServingEngine,
                                      ServingFrontend)
  sr = args.split_ratio if 0.0 < args.split_ratio < 1.0 else 0.5
  ds = build_dataset(args.nodes, args.dim, split_ratio=sr)
  eng = ServingEngine(ds, args.fanout, seed=11)
  fe = ServingFrontend(eng, auto_start=True, warmup=True,
                       max_wait_ms=8.0, default_deadline_ms=2000.0)
  _shrink_slo(fe)
  return LocalReplica(name, fe)


def collect(pending, t0):
  """Resolve the paced futures: (sorted ok-latencies ms, counts,
  first error repr — the diagnosable face of a nonzero count)."""
  from graphlearn_tpu.serving import AdmissionRejected
  lats, ok, shed, errors, first = [], 0, 0, 0, None
  for offset, fut in pending:
    if isinstance(fut, str):
      shed += fut == 'shed'
      errors += fut == 'error'
      if fut == 'error' and first is None:
        first = 'door failure (see pace_schedule)'
      continue
    try:
      fut.result(30.0)
      lats.append(max(
          1e3 * ((fut.done_monotonic or 0.0) - (t0 + offset)), 0.0))
      ok += 1
    except AdmissionRejected:
      shed += 1
    except Exception as e:          # noqa: BLE001 — executor fault
      errors += 1
      if first is None:
        first = f'{type(e).__name__}: {e}'
  lats.sort()
  return lats, ok, shed, errors, first


def run_static_phase(args, plan) -> dict:
  """Phase A: ONE fixed replica through the whole diurnal cycle —
  the unmanaged baseline the elastic p99 is held against."""
  from graphlearn_tpu.serving import FleetRouter
  from graphlearn_tpu.testing import chaos
  rep = make_replica('s0', args)
  router = FleetRouter([rep], heartbeat_ms=40.0, dead_after=3,
                       auto_start=True)
  chaos.install({'faults': [
      {'site': 'serving.request', 'action': 'delay', 'op': 'dispatch',
       'nth': 1, 'count': 10**9, 'secs': DISPATCH_DELAY_S},
  ]})
  t_run = time.perf_counter()
  try:
    pending, t0 = pace_schedule(plan, router.submit)
    lats, ok, shed, errors, first = collect(pending, t0)
  finally:
    chaos.uninstall()
  run_s = time.perf_counter() - t_run
  router.close(close_replicas=True)
  return {'label': 'static', 'replicas': 1, 'requests': len(plan),
          'completed': ok, 'shed': shed, 'errors': errors,
          'first_error': first,
          'qps': round(ok / max(run_s, 1e-9), 1),
          'p50_ms': round(_percentile(lats, 0.50) or 0.0, 2),
          'p99_ms': round(_percentile(lats, 0.99) or 0.0, 2)}


def signal_watch(controller, stop, out):
  """Sample the controller's signal plane through the drive: (t,
  worst-window burn, live replicas) — the burn acceptance and the
  replica-tracking gate read this tape."""
  while not stop.is_set():
    try:
      sig = controller.signals()
      out.append((time.monotonic(),
                  max(sig['short_burn'], sig['long_burn']),
                  sig['replicas']))
    except Exception:               # noqa: BLE001 — a mid-teardown
      pass                          # sample is not a bench failure
    stop.wait(0.05)


def incident_windows(decisions):
  """The chaos exclusion intervals: each rolled-back scale-out opens
  an incident at its decision stamp minus one short window (the spike
  that triggered it is already in the window) and closes one short
  window after the NEXT successful scale-out (the recovery capacity
  needs a window-length to flush the spike out of the burn
  denominator)."""
  w = BENCH_SLO_WINDOWS[0]
  outs = [d for d in decisions if d['dir'] == 'out']
  spans = []
  for i, d in enumerate(outs):
    if d['outcome'] != 'rolled_back':
      continue
    end = d['at'] + 3.0             # fallback: no recovery seen
    for nxt in outs[i + 1:]:
      if nxt['outcome'] == 'ok':
        end = nxt['at'] + w
        break
    spans.append((d['at'] - w, end + w))
  return spans


def run_elastic_phase(args, plan) -> dict:
  """Phase B: the same cycle against the closed loop — min 1 replica,
  scale-out on burn/queue, scale-in at the trough, first spawn
  chaos-failed mid-ramp."""
  import threading
  from graphlearn_tpu.serving import ElasticController, FleetRouter
  from graphlearn_tpu.testing import chaos
  counter = {'n': 0}

  def spawn():
    counter['n'] += 1
    return make_replica(f'e{counter["n"]}', args)

  router = FleetRouter([make_replica('e0', args)], heartbeat_ms=40.0,
                       dead_after=3, auto_start=True)
  chaos.install({'faults': [
      # the same deterministic per-dispatch cost as the static phase
      # (spawned replicas pay it too — capacity scales linearly)
      {'site': 'serving.request', 'action': 'delay', 'op': 'dispatch',
       'nth': 1, 'count': 10**9, 'secs': DISPATCH_DELAY_S},
      # the mid-run fault: the FIRST spawn attempt dies — the
      # controller must roll back typed, re-arm, and land capacity on
      # the next evaluation
      {'site': 'scale.spawn', 'action': 'fail', 'nth': 1},
  ]})
  controller = ElasticController(
      router, spawn, min_replicas=1, max_replicas=args.max_replicas,
      eval_s=0.12, cooldown_s=(0.5, 1.5), out_burn=0.5, in_burn=0.15,
      # ~10 queued requests (two dispatches of backlog at the 8-seed
      # ladder): capacity lands BEFORE the queue wait approaches the
      # SLO target — the leading-indicator half of the hysteresis
      queue_ratio=0.15, quiesce_timeout_s=8.0, auto_start=True)
  samples = []
  stop = threading.Event()
  watcher = threading.Thread(target=signal_watch,
                             args=(controller, stop, samples),
                             daemon=True)
  watcher.start()
  t_run = time.perf_counter()
  try:
    pending, t0 = pace_schedule(plan, router.submit)
    lats, ok, shed, errors, first = collect(pending, t0)
    run_s = time.perf_counter() - t_run
    # the post-cycle trough: traffic ended, the long burn window
    # drains, fresh/idle replicas read burn 0 (the SloTracker idle
    # contract) — the scale-in decision must land HERE, inside a
    # bounded grace window, not "eventually"
    grace_deadline = time.monotonic() + 6.0
    while time.monotonic() < grace_deadline:
      if any(d['dir'] == 'in' and d['outcome'] == 'ok'
             for d in controller.decisions()):
        break
      time.sleep(0.1)
  finally:
    stop.set()
    watcher.join(5.0)
    controller.close()
    chaos.uninstall()
  decisions = controller.decisions()
  router.close(close_replicas=True)
  outs = sum(1 for d in decisions
             if d['dir'] == 'out' and d['outcome'] == 'ok')
  ins = sum(1 for d in decisions
            if d['dir'] == 'in' and d['outcome'] == 'ok')
  rolled = sum(1 for d in decisions if d['outcome'] == 'rolled_back')
  outcomes = {}
  for d in decisions:
    key = f"{d['dir']}:{d['outcome']}"
    outcomes[key] = outcomes.get(key, 0) + 1
  spans = incident_windows(decisions)
  outside = [b for t, b, _ in samples
             if not any(s <= t <= e for s, e in spans)]
  reps = [r for _, _, r in samples]
  return {'label': 'elastic', 'requests': len(plan), 'completed': ok,
          'shed': shed, 'errors': errors, 'first_error': first,
          'qps': round(ok / max(run_s, 1e-9), 1),
          'p50_ms': round(_percentile(lats, 0.50) or 0.0, 2),
          'p99_ms': round(_percentile(lats, 0.99) or 0.0, 2),
          'scale_outs': outs, 'scale_ins': ins,
          'rolled_back': rolled,
          'decisions_total': len(decisions),
          'decision_outcomes': outcomes,
          'replicas_min': min(reps) if reps else 0,
          'replicas_max': max(reps) if reps else 0,
          'burn_max': round(max(outside), 4) if outside else 0.0,
          'burn_samples': len(samples),
          'incident_windows': len(spans),
          'spawned': counter['n']}


def run_handoff_phase() -> dict:
  """Phase C: the planned-handoff acceptance on a P=8 mesh — a
  mid-epoch ownership move with zero degraded batches, one bump."""
  import jax
  if len(jax.devices()) < 8:
    return {'error': f'needs an 8-device host mesh '
                     f'(have {len(jax.devices())})'}
  from graphlearn_tpu.parallel.dist_data import DistDataset
  from graphlearn_tpu.parallel.dist_sampler import DistNeighborLoader
  from graphlearn_tpu.parallel.failover import ShardStore
  from graphlearn_tpu.parallel.handoff import handoff
  P, N, E = 8, 200, 1200
  rng = np.random.default_rng(0)
  rows = rng.integers(0, N, E)
  cols = rng.integers(0, N, E)
  feat = (np.arange(N)[:, None] + np.zeros((1, 6))).astype(np.float32)
  lab = (np.arange(N) % 4).astype(np.int64)

  def dataset():
    return DistDataset.from_full_graph(P, rows, cols, feat, lab)

  def loader(ds):
    return DistNeighborLoader(ds, [3, 2], np.arange(N), batch_size=4,
                              shuffle=True, seed=0)

  ref = [b for b in loader(dataset())]
  ds = dataset()
  it = iter(loader(ds))
  got = [next(it) for _ in range(3)]   # mid-epoch: the move lands
  t0 = time.perf_counter()
  with tempfile.TemporaryDirectory() as d:
    info = handoff(ds, 3, 5, store=ShardStore(d))
  secs = time.perf_counter() - t0
  got += list(it)                      # the rest fences + completes
  degraded = abs(len(ref) - len(got))
  for a, b in zip(ref, got):
    same = (np.array_equal(np.asarray(a.node), np.asarray(b.node))
            and np.array_equal(np.asarray(a.x), np.asarray(b.x))
            and np.array_equal(np.asarray(a.y), np.asarray(b.y))
            and np.array_equal(np.asarray(a.edge_index),
                               np.asarray(b.edge_index)))
    degraded += not same
  return {'label': 'handoff', 'batches': len(got),
          'degraded_batches': int(degraded),
          'book_bumps': int(ds.partition_book.version),
          'transfers': len(ds.partition_book.transfers()),
          'frm': info['frm'], 'to': info['to'],
          'secs': round(secs, 3)}


def main(argv=None):
  ap = argparse.ArgumentParser(description=__doc__.split('\n')[0])
  ap.add_argument('--nodes', type=int, default=20000)
  ap.add_argument('--dim', type=int, default=32)
  ap.add_argument('--fanout', type=int, nargs='+', default=[5, 3])
  ap.add_argument('--rate', type=float, default=160.0,
                  help='diurnal PEAK arrival rate, requests/s')
  ap.add_argument('--trough', type=float, default=20.0,
                  help='diurnal trough arrival rate, requests/s')
  ap.add_argument('--duration', type=float, default=9.0,
                  help='one diurnal cycle, seconds')
  ap.add_argument('--zipf-a', type=float, default=1.1)
  ap.add_argument('--max-replicas', type=int, default=3)
  ap.add_argument('--split-ratio', type=float, default=0.5)
  ap.add_argument('--cpu', action='store_true')
  args = ap.parse_args(argv)
  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  from graphlearn_tpu.telemetry import recorder
  recorder.enable(None)
  # the capacity model rides the injected dispatch cost: an 8-seed
  # bucket ladder bounds coalescing (a dispatch carries a handful of
  # requests, so the DISPATCH_DELAY_S injection caps per-replica
  # throughput deterministically), a small queue makes queue_frac a
  # leading indicator, and the latency SLO separates the regimes —
  # uncontended traffic (~coalesce wait + one dispatch delay, with
  # occasional host-scheduling spikes) clears 500 ms, a saturated
  # queue does not.  The QUEUE is the leading
  # indicator (a couple of dispatches of backlog trips scale-out
  # before latency ever reaches the target); burn is the lagging
  # confirmation and the acceptance gate
  os.environ.setdefault('GLT_SERVING_BUCKETS', '8')
  os.environ.setdefault('GLT_SERVING_QUEUE_DEPTH', '64')
  os.environ.setdefault('GLT_SERVING_SLO_P99_MS', '500')
  os.environ.setdefault('GLT_SERVING_SLO_QPS', str(args.rate / 2))
  result = {'num_nodes': args.nodes, 'fanout': list(args.fanout),
            'platform': jax.devices()[0].platform,
            'peak_rps': args.rate, 'trough_rps': args.trough,
            'duration_s': args.duration}
  plan = make_diurnal_schedule(args.rate, args.trough, args.duration,
                               args.nodes, args.zipf_a, seed=5)
  with tempfile.TemporaryDirectory() as aot_dir:
    # one shared AOT cache for the whole bench: the static replica
    # compiles + publishes, every elastic spawn warm-restores (the
    # controller's compile_count()==0 admission pin)
    os.environ['GLT_AOT_CACHE_DIR'] = aot_dir
    try:
      static = run_static_phase(args, plan)
      result['static'] = static
      print(json.dumps(result), flush=True)
      elastic = run_elastic_phase(args, plan)
      result['elastic'] = elastic
      print(json.dumps(result), flush=True)
    finally:
      os.environ.pop('GLT_AOT_CACHE_DIR', None)
  hand = run_handoff_phase()
  result['handoff'] = hand

  result['p99_static_ms'] = static['p99_ms']
  result['p99_held_ms'] = elastic['p99_ms']
  result['burn_max'] = elastic['burn_max']
  result['scale_outs'] = elastic['scale_outs']
  result['scale_ins'] = elastic['scale_ins']
  result['rolled_back'] = elastic['rolled_back']
  result['errors'] = static['errors'] + elastic['errors']
  result['handoff_degraded_batches'] = hand.get('degraded_batches')
  result['handoff_book_bumps'] = hand.get('book_bumps')
  print(json.dumps(result), flush=True)

  failures = []
  if elastic['completed'] == 0:
    failures.append('elastic drive served no requests')
  if elastic['errors'] or static['errors']:
    failures.append(f"failed requests (static={static['errors']}, "
                    f"elastic={elastic['errors']}) — must be 0")
  if elastic['scale_outs'] < 1 or elastic['scale_ins'] < 1:
    failures.append(f"fleet did not track the load (scale_outs="
                    f"{elastic['scale_outs']}, scale_ins="
                    f"{elastic['scale_ins']} — need >=1 each)")
  if elastic['rolled_back'] < 1:
    failures.append('the chaos scale.spawn fault never rolled back '
                    'typed (rolled_back == 0)')
  if elastic['burn_max'] >= 1.0:
    failures.append(f"burn {elastic['burn_max']} >= 1.0 outside the "
                    'chaos incident window — the controller let the '
                    'SLO budget burn through')
  if static['p99_ms'] > 0 and \
      elastic['p99_ms'] > static['p99_ms'] * 1.05 + 5.0:
    failures.append(f"elastic p99 {elastic['p99_ms']}ms did not hold "
                    f"vs static baseline {static['p99_ms']}ms")
  if 'error' in hand:
    failures.append(f"handoff phase: {hand['error']}")
  elif hand['degraded_batches'] != 0 or hand['book_bumps'] != 1:
    failures.append(f"handoff degraded_batches="
                    f"{hand['degraded_batches']} (need 0), "
                    f"book_bumps={hand['book_bumps']} (need 1)")
  if failures:
    for f in failures:
      print(f'WARNING: {f}', file=sys.stderr)
    return 1
  return 0


if __name__ == '__main__':
  sys.exit(main())
