"""Online serving bench: Zipf-skewed OPEN-LOOP traffic against the
coalescing tier (ISSUE 9).

Protocol — open-loop, not closed-loop: request arrival times are a
fixed-rate schedule drawn up front (seeded exponential interarrivals,
the Poisson-traffic model) and the driver submits at those times
whether or not earlier requests have finished.  A closed-loop driver
(wait for a reply, send the next) self-throttles exactly when the
tier slows down, which HIDES saturation and flatters p99 — the
classic coordinated-omission trap.  Latency is measured from each
request's SCHEDULED arrival to its resolve, so driver lag counts
against the tier, not for it.

Seed skew is Zipf (``--zipf-a``, default 1.1) over a fixed node
permutation — the traffic shape a serving tier actually sees
(PAPERS.md: GNS, arXiv 2106.06150), and what makes the tiered row's
cold cache earn its budget.

Phases (each prints one JSON line; the LAST line is cumulative):
  1. fully-HBM engine + fused TreeSAGE forward — the headline
     p50/p95/p99 latency + sustained QPS + shed rate, with the
     zero-recompile-after-warmup assertion
     (``recompiles_after_warmup`` MUST be 0: every shape in the
     traffic envelope is served by a warmed bucket);
  2. tiered engine (``--split-ratio``, default 0.5) — same traffic
     through the per-request hot-split + cold-cache path, reporting
     the serving-scope cache hit rate alongside the percentiles.

Knobs: CLI flags below; the serving tier itself reads
``GLT_SERVING_BUCKETS`` / ``GLT_SERVING_MAX_WAIT_MS`` /
``GLT_SERVING_QUEUE_DEPTH`` / ``GLT_SERVING_DEADLINE_MS``
(benchmarks/README "Online serving (r9)").
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _percentile(sorted_vals, p):
  # ONE quantile definition with the report CLI (its serving table
  # reads the same traffic's serving.request events)
  from graphlearn_tpu.telemetry.report import nearest_rank
  return nearest_rank(sorted_vals, p)


def build_dataset(n: int, dim: int, split_ratio: float = 1.0,
                  seed: int = 0):
  from graphlearn_tpu.data import Dataset
  rng = np.random.default_rng(seed)
  deg = 8
  rows = np.repeat(np.arange(n), deg)
  cols = rng.integers(0, n, rows.shape[0])
  feats = rng.random((n, dim), dtype=np.float32)
  ds = (Dataset()
        .init_graph((rows, cols), layout='COO', num_nodes=n)
        .init_node_features(feats, split_ratio=split_ratio))
  return ds


def make_schedule(rate_rps: float, duration_s: float, n: int,
                  zipf_a: float, seed: int):
  """The open-loop plan, drawn up front: (arrival offset, seeds) per
  request.  Seeds are Zipf ranks mapped through a fixed permutation
  (hotness decoupled from id order); request sizes are skewed small —
  single-seed queries dominate online traffic."""
  rng = np.random.default_rng(seed)
  arrivals, t = [], 0.0
  while True:
    t += rng.exponential(1.0 / rate_rps)
    if t >= duration_s:
      break
    arrivals.append(t)
  perm = rng.permutation(n)
  plan = []
  for a in arrivals:
    k = int(rng.choice([1, 1, 1, 1, 2, 2, 4], 1)[0])
    ranks = (rng.zipf(zipf_a, k) - 1) % n
    plan.append((a, perm[ranks].astype(np.int64)))
  return plan


def pace_schedule(plan, submit, honor_retry_after=True, max_retries=8):
  """Open-loop pacing shared by the single-engine and fleet drivers:
  submit each request at its SCHEDULED offset, never waiting on
  earlier ones, classifying door refusals typed.  Returns
  ``([(offset, future | 'shed' | 'error'), ...], t0)`` with ``t0``
  the monotonic schedule origin (latency = resolve - (t0 + offset)).

  ``reason='draining'`` refusals carry a ``retry_after_ms`` hint — a
  drain is a planned, bounded unavailability, not capacity loss — so
  the client resubmits after the hint instead of counting a shed
  (ISSUE 19).  Latency stays measured from the ORIGINAL offset: the
  wait behind the drain is real, client-visible time.  ``queue_full``
  and deadline refusals stay terminal sheds; after ``max_retries``
  drain bounces the request is a shed too."""
  import heapq
  from graphlearn_tpu.serving import AdmissionRejected
  out = []
  retryq = []   # (due_rel, seq, orig_offset, seeds, attempt)
  seq = 0
  t0 = time.monotonic()

  def attempt(orig_offset, seeds, tries):
    nonlocal seq
    try:
      out.append((orig_offset, submit(seeds)))
    except AdmissionRejected as e:
      hint = getattr(e, 'retry_after_ms', None)
      if (honor_retry_after and getattr(e, 'reason', '') == 'draining'
          and hint is not None and tries < max_retries):
        due = (time.monotonic() - t0) + float(hint) / 1e3
        heapq.heappush(retryq, (due, seq, orig_offset, seeds, tries + 1))
        seq += 1
      else:
        out.append((orig_offset, 'shed'))
    except Exception:               # noqa: BLE001 — door failure
      out.append((orig_offset, 'error'))

  for offset, seeds in plan:
    # Drain any retries that came due before this scheduled arrival.
    while retryq and retryq[0][0] <= time.monotonic() - t0:
      _, _, o, s, tries = heapq.heappop(retryq)
      attempt(o, s, tries)
    now = time.monotonic() - t0
    if offset > now:
      time.sleep(offset - now)
    attempt(offset, seeds, 0)
  while retryq:                     # flush stragglers after the plan
    due, _, o, s, tries = heapq.heappop(retryq)
    now = time.monotonic() - t0
    if due > now:
      time.sleep(due - now)
    attempt(o, s, tries)
  return out, t0


def drive_open_loop(frontend, plan):
  """Submit the plan at its scheduled times (open-loop); returns
  per-request (latency_ms | None, outcome) with latency measured from
  the SCHEDULED arrival (the future stamps its resolve time, so the
  driver's collection loop inflates nothing)."""
  from graphlearn_tpu.serving import AdmissionRejected
  pending, t0 = pace_schedule(plan, frontend.submit)
  out = []
  for offset, fut in pending:
    if isinstance(fut, str):
      out.append((None, fut))
      continue
    try:
      fut.result(30.0)
      lat_ms = 1e3 * ((fut.done_monotonic or 0.0) - (t0 + offset))
      out.append((max(lat_ms, 0.0), 'ok'))
    except AdmissionRejected:
      out.append((None, 'shed'))
    except Exception:               # noqa: BLE001 — executor fault
      out.append((None, 'error'))
  return out


def measure_tracing_overhead(frontend, n_nodes: int,
                             requests: int = 150, reps: int = 2):
  """Tracing-cost acceptance (ISSUE 17): drive the SAME closed-loop
  single-seed schedule with tracing off (sample=0 — the byte-identical
  fast path) and fully on (sample=1 — every request minted, span
  recording + ring retention + exemplar stamping all active), and
  return the traced/untraced wall-time ratio.  Best-of-``reps`` per
  mode damps scheduler noise; regress.py pins the ratio <= 1.05
  against a FIXED 1.0 baseline."""
  from graphlearn_tpu.telemetry import tracer
  rng = np.random.default_rng(7)
  seed_list = [np.asarray([s], dtype=np.int64)
               for s in rng.integers(0, n_nodes, size=requests)]

  def drive_once():
    t0 = time.perf_counter()
    for s in seed_list:
      frontend.submit(s).result(30.0)
    return time.perf_counter() - t0

  try:
    best = {}
    for rep in range(reps + 1):
      for mode, sample in (('untraced', 0), ('traced', 1)):
        tracer.configure(sample=sample, slow_ms=1e9, buffer=None)
        took = drive_once()
        if rep == 0:
          continue                    # warmup lap for both modes
        if mode not in best or took < best[mode]:
          best[mode] = took
    return best['traced'] / max(best['untraced'], 1e-9)
  finally:
    tracer.configure()                # back to the env-declared knobs
    tracer.clear()


def scrape_ops(ops, at_s: float, out: dict, require_cache=False):
  """Mid-run scrape thread body: after ``at_s`` seconds, pull
  /metrics + /varz off the live ops server and STRICTLY validate the
  Prometheus text (the acceptance check: live metrics are scrapeable
  and well-formed DURING traffic, not after)."""
  import json as _json
  import urllib.request
  from graphlearn_tpu.telemetry import parse_prometheus_text
  time.sleep(at_s)
  try:
    txt = urllib.request.urlopen(f'{ops.url}/metrics',
                                 timeout=10).read().decode()
    samples = parse_prometheus_text(txt)
    varz = _json.loads(urllib.request.urlopen(
        f'{ops.url}/varz', timeout=10).read())
    present = {
        'queue_depth': 'glt_serving_queue_depth' in samples,
        'shed_rate': 'glt_serving_shed_rate' in samples,
        'latency_hist': any(k.startswith(
            'glt_serving_request_latency_bucket') for k in samples),
        'slo_burn_rate': any(k.startswith('glt_serving_slo_burn_rate')
                             for k in samples),
    }
    if require_cache:
      # only the tiered phase has cache traffic; the derived gauge
      # stays absent (not a fake 0) while there is nothing to rate
      present['cache_hit_rate'] = 'glt_cache_hit_rate' in samples
    out.pop('error', None)          # clear the pre-filled sentinel
    out.update(scrape_ok=True, samples=len(samples),
               varz_keys=len(varz.get('metrics', {})),
               present=present, all_present=all(present.values()))
  except Exception as e:            # noqa: BLE001 — reported, scored
    out.update(scrape_ok=False, error=f'{type(e).__name__}: {e}')


def run_phase(label: str, ds, model, params, args, result: dict,
              ops=None):
  import threading

  import jax
  from graphlearn_tpu.serving import ServingEngine, ServingFrontend
  from graphlearn_tpu.telemetry import recorder
  eng = ServingEngine(ds, args.fanout, model=model, seed=11)
  if model is not None:
    if params is None:
      params = eng.init_params(jax.random.key(0))
    else:
      eng.params = params
  t0 = time.perf_counter()
  warm = eng.warmup()
  fe = ServingFrontend(eng, auto_start=True, warmup=False)
  warm_compiles = eng.compile_count()
  plan = make_schedule(args.rate, args.duration, ds.get_graph().num_nodes,
                       args.zipf_a, seed=3)
  # pre-filled FAILED so a scrape thread that outlives the join still
  # shows up (and fails) in the acceptance check, instead of the row
  # silently losing its 'ops' block
  scrape: dict = {}
  scraper = None
  if ops is not None:
    scrape = {'scrape_ok': False,
              'error': 'scrape thread did not complete'}
    # scrape mid-run (half the open-loop window in) — a stalled or
    # slow scrape runs on the ops server's own thread and must not
    # perturb the traffic it is observing
    scraper = threading.Thread(
        target=scrape_ops, args=(ops, args.duration / 2, scrape,
                                 label == 'tiered'),
        daemon=True)
    scraper.start()
  t_run = time.perf_counter()
  outcomes = drive_open_loop(fe, plan)
  run_s = time.perf_counter() - t_run
  if scraper is not None:
    scraper.join(timeout=30.0)
  overhead = None
  if label == 'hot':
    # tracing-cost ratio on the HEADLINE engine, measured after the
    # open-loop window so the two closed-loop laps see a warm, idle
    # tier (feeds dist.serving.tracing_overhead_ratio)
    overhead = measure_tracing_overhead(
        fe, ds.get_graph().num_nodes)
  fe.shutdown()
  lats = sorted(l for l, o in outcomes if o == 'ok' and l is not None)
  shed = sum(1 for _, o in outcomes if o == 'shed')
  errors = sum(1 for _, o in outcomes if o == 'error')
  cache_hits = sum(e.get('count', 0) for e in recorder.events('cache.hit')
                   if e.get('scope') == 'serving')
  cache_misses = sum(e.get('count', 0)
                     for e in recorder.events('cache.miss')
                     if e.get('scope') == 'serving')
  row = {
      'label': label,
      'open_loop': True,
      'rate_rps': args.rate, 'duration_s': args.duration,
      'zipf_a': args.zipf_a,
      'buckets': list(eng.buckets),
      'requests': len(plan),
      'completed': len(lats), 'shed': shed, 'errors': errors,
      'p50_ms': round(_percentile(lats, 0.50) or 0.0, 3),
      'p95_ms': round(_percentile(lats, 0.95) or 0.0, 3),
      'p99_ms': round(_percentile(lats, 0.99) or 0.0, 3),
      'qps': round(len(lats) / max(run_s, 1e-9), 1),
      'shed_rate': round(shed / max(len(plan), 1), 4),
      'warmup_secs': round(time.perf_counter() - t0, 2),
      'warmup_compiles': warm['compiles'],
      # THE acceptance pin: after warmup the whole traffic envelope
      # must hit warm executables (any nonzero here is a shape that
      # escaped the bucket ladder)
      'recompiles_after_warmup': eng.compile_count() - warm_compiles,
      'stats': fe.stats(),
  }
  if scrape:
    row['ops'] = scrape
  if overhead is not None:
    row['tracing_overhead_ratio'] = round(overhead, 4)
  if cache_hits or cache_misses:
    row['cache_hit_rate'] = round(
        cache_hits / max(cache_hits + cache_misses, 1), 4)
  result[label] = row
  # flat twins of the guarded dotted keys at the top level (the
  # regress gate reads dist.serving.p99_ms / .qps / .shed_rate /
  # .tracing_overhead_ratio from the HEADLINE fully-hot phase)
  if label == 'hot':
    for k in ('p50_ms', 'p95_ms', 'p99_ms', 'qps', 'shed_rate',
              'tracing_overhead_ratio'):
      result[k] = row[k]
  print(json.dumps(result), flush=True)
  return row


def federation_watch(scraper, ops, stop, out: dict) -> None:
  """Mid-traffic ``/fleet`` validation loop (ISSUE 16 acceptance):
  while the open-loop drive runs, repeatedly scrape the fleet and
  strict-parse the federated exposition — through the HTTP route when
  an ops server is up (the exact bytes an operator's scraper reads),
  else directly.  Any parse failure or a merge that never federates
  >= 2 replicas fails the bench (nonzero exit in `main`)."""
  import re
  import urllib.request
  from graphlearn_tpu.telemetry import parse_prometheus_text
  while not stop.is_set():
    try:
      scraper.scrape()
      if ops is not None:
        with urllib.request.urlopen(f'{ops.url}/fleet',
                                    timeout=5) as r:
          text = r.read().decode('utf-8')
      else:
        text = scraper.prometheus_text()
      parse_prometheus_text(text)     # strict: raises on junk
      seen = len(set(re.findall(r'replica="([^"]+)"', text)))
      out['scrapes'] = out.get('scrapes', 0) + 1
      out['max_replicas_federated'] = max(
          out.get('max_replicas_federated', 0), seen)
    except Exception as e:            # noqa: BLE001 — every failure
      out['parse_failures'] = out.get('parse_failures', 0) + 1
      out.setdefault('errors', []).append(f'{type(e).__name__}: {e}')
    stop.wait(0.15)


def run_fleet_phase(args, result: dict, ops=None) -> dict:
  """Fleet mode (ISSUE 13): the SAME Zipf open-loop schedule spread
  over N in-process replicas by a `FleetRouter`, with ONE replica
  chaos-killed mid-run.  The acceptance arithmetic: every submitted
  request resolves ok or typed-shed (zero failed/dropped/silently
  lost — redrive exactly-once via the router ledger), and the fleet's
  completion rate after the kill recovers to >= 0.6x the pre-kill
  rate within the run.  Feeds ``dist.serving.fleet_qps`` /
  ``.failover_failed_requests``.

  Fleet signal plane (ISSUE 16): a `FleetScraper` federates every
  replica (the scraping process's own registry rides along as
  ``self``) and a watcher thread strict-parses the merged ``/fleet``
  exposition for the whole drive — the federation acceptance runs
  against live traffic, not a quiesced fleet."""
  import threading
  import jax
  from graphlearn_tpu.serving import (AdmissionRejected, FleetRouter,
                                      LocalReplica, ServingEngine,
                                      ServingFrontend)
  from graphlearn_tpu.telemetry import tracer
  from graphlearn_tpu.telemetry.live import live
  from graphlearn_tpu.testing import chaos
  n_rep = args.fleet
  # the fleet serves the TIERED path: every traced request then owns
  # the full five-span tree (route -> queue_wait -> dispatch_slice ->
  # {sample_collect, cold_fill}) the mid-run tracing acceptance below
  # asserts on
  sr = args.split_ratio if 0.0 < args.split_ratio < 1.0 else 0.5
  n = args.nodes
  replicas, frontends = [], []
  t0 = time.perf_counter()
  for i in range(n_rep):
    # one seed across the fleet: replicas answer byte-identically, so
    # a redriven request's survivor answer matches the lost replica's.
    # Each replica owns its OWN dataset instance (same build seed):
    # the tiered feature holds live device buffers (cold-cache rows)
    # that the killed replica's teardown deletes — a shared instance
    # would yank them out from under the survivors mid-redrive
    ds = build_dataset(args.nodes, args.dim, split_ratio=sr)
    eng = ServingEngine(ds, args.fanout, seed=11)
    # a wider coalescing window than the single-engine phases keeps a
    # little queue occupancy per replica, so the mid-run kill strands
    # real in-flight requests for the redrive ledger to move
    fe = ServingFrontend(eng, auto_start=True, warmup=True,
                         max_wait_ms=10.0, default_deadline_ms=2000.0)
    replicas.append(LocalReplica(f'r{i}', fe))
    frontends.append(fe)
  warm_s = time.perf_counter() - t0
  # request tracing ON for the whole fleet drive (ISSUE 17): every
  # request carries a context, 1-in-10 head-sampled, and anything
  # slower than the SLO p99 (the chaos stall guarantees some) is
  # tail-retained — the acceptance below demands >=1 such slow-tail
  # trace with the full >=5-span tree captured mid-run
  trace_slow_ms = float(os.environ.get('GLT_SERVING_SLO_P99_MS',
                                       '100') or 100)
  tracer.configure(sample=10, slow_ms=trace_slow_ms, buffer=None)
  tracer.clear()
  plan = make_schedule(args.rate, args.duration, n, args.zipf_a,
                       seed=3)
  # mid-run kill, declared through the chaos plan: replica r0 first
  # STALLS (every dispatch from its Dth delays — queue backs up with
  # real in-flight requests, and the router's discriminator sees an
  # overloaded-not-dead replica), then DIES at its Kth submit arrival
  # (K = its expected share of the first half of the schedule, so the
  # kill lands mid-run with requests stranded for the redrive ledger)
  kill_t = args.duration / 2
  pre = sum(1 for a, _ in plan if a < kill_t)
  kill_nth = max(pre // n_rep, 2)
  # the dispatch seam counts COALESCED runs, and the tiered path's
  # coalescing ratio is load-dependent — stall from the victim's
  # FIRST dispatch so the overload window deterministically precedes
  # the kill (the discriminator sees overloaded-not-dead for the
  # whole first half, and the stalled riders are the guaranteed
  # slow-tail traces the tracing acceptance below asserts on)
  stall_nth = 1
  chaos.install({'faults': [
      {'site': 'serving.request', 'action': 'delay', 'op': 'dispatch',
       'replica': 'r0', 'nth': stall_nth, 'count': 10000,
       'secs': 0.12},
      {'site': 'serving.replica', 'action': 'kill', 'op': 'submit',
       'replica': 'r0', 'nth': kill_nth},
  ]})
  router = FleetRouter(replicas, heartbeat_ms=50.0, dead_after=2,
                       auto_start=True)
  # the signal plane: every replica federates under its own
  # replica= label; the driver process's registry (SLO gauges,
  # admission depth — the frontends all write into it) joins as
  # 'self' so per-process and per-replica views merge in one scrape
  scraper = router.make_scraper(registry=live)
  if ops is not None:
    ops.attach_fleet(scraper)
  fed = {}
  fed_stop = threading.Event()
  watcher = threading.Thread(target=federation_watch,
                             args=(scraper, ops, fed_stop, fed),
                             daemon=True)
  watcher.start()
  t_run = time.perf_counter()
  pending, _ = pace_schedule(plan, router.submit)
  outcomes = []
  for offset, fut in pending:
    if isinstance(fut, str):
      outcomes.append((offset, fut))
      continue
    try:
      fut.result(30.0)
      outcomes.append((offset, 'ok'))
    except AdmissionRejected:
      outcomes.append((offset, 'shed'))
    except Exception:               # noqa: BLE001
      outcomes.append((offset, 'error'))
  run_s = time.perf_counter() - t_run
  fed_stop.set()
  watcher.join(10.0)
  # the tracing acceptance reads the ring BEFORE teardown: slow-tail
  # traces (latency past the SLO p99 — retained regardless of the
  # 1-in-10 head sample) and the deepest captured span tree
  trace_index = tracer.traces()
  tail = [t for t in trace_index
          if (t.get('latency_ms') or 0.0) >= tracer.slow_ms]
  traced_tail_count = len(tail)
  traced_tail_max_spans = max((t['spans'] for t in tail), default=0)
  trace_stats = tracer.stats()
  # the capacity signal: per-replica EWMA headroom, summed over the
  # replicas still publishing one (the killed replica may be torn
  # down) — regress.py guards PRESENCE of this key whenever the
  # fleet phase ran
  headrooms = []
  for fe in frontends:
    try:
      h = fe.stats().get('headroom_qps')
    except Exception:                 # noqa: BLE001 — killed replica
      h = None
    if isinstance(h, (int, float)):
      headrooms.append(float(h))
  fleet_headroom = round(sum(headrooms), 1) if headrooms else None
  scraper.close()
  router_stats = router.stats()
  router.close(close_replicas=True)
  chaos.uninstall()
  tracer.configure()                  # back to the env-declared knobs
  ok = sum(1 for _, o in outcomes if o == 'ok')
  shed = sum(1 for _, o in outcomes if o == 'shed')
  errors = sum(1 for _, o in outcomes if o == 'error')
  pre_ok = sum(1 for t, o in outcomes if o == 'ok' and t < kill_t)
  post_ok = sum(1 for t, o in outcomes if o == 'ok' and t >= kill_t)
  pre_qps = pre_ok / max(kill_t, 1e-9)
  post_qps = post_ok / max(args.duration - kill_t, 1e-9)
  row = {
      'label': 'fleet', 'replicas': n_rep, 'open_loop': True,
      'rate_rps': args.rate, 'duration_s': args.duration,
      'zipf_a': args.zipf_a, 'warmup_secs': round(warm_s, 2),
      'requests': len(plan), 'completed': ok, 'shed': shed,
      'errors': errors,
      'kill_at_s': round(kill_t, 3), 'kill_nth_submit': kill_nth,
      'fleet_qps': round(ok / max(run_s, 1e-9), 1),
      'pre_kill_qps': round(pre_qps, 1),
      'post_kill_qps': round(post_qps, 1),
      'recovery_ratio': round(post_qps / max(pre_qps, 1e-9), 3),
      # the acceptance counter: anything but ok/typed-shed is a
      # failed/dropped request — MUST be 0 (exit nonzero below)
      'failover_failed_requests': errors,
      'redriven': router_stats['redriven'],
      'evictions': router_stats['evictions'],
      'router': router_stats,
      # the ISSUE 16 federation acceptance: every mid-traffic /fleet
      # exposition strict-parsed, and the merge federated >= 2
      # replicas at least once while traffic flowed
      'fleet_scrapes': fed.get('scrapes', 0),
      'fleet_parse_failures': fed.get('parse_failures', 0),
      'fleet_replicas_federated': fed.get('max_replicas_federated', 0),
      'fleet_scrape_errors': fed.get('errors', [])[:5],
      # the ISSUE 17 tracing acceptance inputs: slow-tail traces
      # captured mid-run + the deepest span tree among them, and the
      # fleet's summed capacity headroom (presence-guarded)
      'split_ratio': sr,
      'traced_tail_count': traced_tail_count,
      'traced_tail_max_spans': traced_tail_max_spans,
      'traces_minted': trace_stats['minted'],
      'traces_retained': trace_stats['retained'],
      'fleet_headroom_qps': fleet_headroom,
  }
  result['fleet'] = row
  for k in ('fleet_qps', 'failover_failed_requests', 'recovery_ratio',
            'redriven', 'evictions', 'traced_tail_count',
            'traced_tail_max_spans', 'fleet_headroom_qps'):
    result[k] = row[k]
  print(json.dumps(result), flush=True)
  return row


def main(argv=None):
  ap = argparse.ArgumentParser(description=__doc__.split('\n')[0])
  ap.add_argument('--nodes', type=int, default=20000)
  ap.add_argument('--dim', type=int, default=32)
  ap.add_argument('--fanout', type=int, nargs='+', default=[5, 3])
  ap.add_argument('--rate', type=float, default=200.0,
                  help='open-loop arrival rate, requests/s')
  ap.add_argument('--duration', type=float, default=3.0)
  ap.add_argument('--zipf-a', type=float, default=1.1)
  ap.add_argument('--fleet', type=int, default=0,
                  help='N>0: fleet mode — the same open-loop traffic '
                       'across N replicas behind a FleetRouter with '
                       'one mid-run chaos kill (replaces the '
                       'single-engine phases)')
  ap.add_argument('--split-ratio', type=float, default=0.5,
                  help='tiered phase hot fraction (0 skips the phase)')
  ap.add_argument('--ops-port', type=int, default=-1,
                  help='live ops endpoint: -1 (default) = ephemeral '
                       'port + mid-run scrape validation, 0 = no ops '
                       'plane, >0 = fixed port')
  ap.add_argument('--cpu', action='store_true')
  args = ap.parse_args(argv)
  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  from graphlearn_tpu.models.tree import TreeSAGE
  from graphlearn_tpu.telemetry import recorder
  recorder.enable(None)              # in-memory: serving cache events
  # SLO targets for the burn-rate gauges the scrape check asserts on
  # (operators set their own; the bench only needs the plumbing live)
  # — set BEFORE either mode so the fleet run exports them too
  os.environ.setdefault('GLT_SERVING_SLO_P99_MS', '100')
  os.environ.setdefault('GLT_SERVING_SLO_QPS', str(args.rate / 2))
  ops = None
  if args.ops_port != 0:
    from graphlearn_tpu.telemetry import OpsServer
    ops = OpsServer(port=max(args.ops_port, 0))
  if args.fleet > 0:
    result = {'num_nodes': args.nodes, 'fanout': list(args.fanout),
              'platform': jax.devices()[0].platform,
              'ops_enabled': ops is not None}
    try:
      row = run_fleet_phase(args, result, ops=ops)
    finally:
      if ops is not None:
        ops.close()
    if (row['fleet_parse_failures']
        or row['fleet_scrapes'] == 0
        or row['fleet_replicas_federated'] < 2):
      print('WARNING: /fleet federation failed mid-traffic '
            f"validation (scrapes={row['fleet_scrapes']}, "
            f"parse_failures={row['fleet_parse_failures']}, "
            f"replicas_federated={row['fleet_replicas_federated']}) "
            f"errors={row['fleet_scrape_errors']}", file=sys.stderr)
      return 1
    if row['failover_failed_requests']:
      print(f"WARNING: {row['failover_failed_requests']} request(s) "
            'failed/dropped across the mid-run replica kill — the '
            'redrive ledger lost traffic', file=sys.stderr)
      return 1
    if row['completed'] == 0 or row['post_kill_qps'] <= 0:
      # an all-shed run has zero errors but served nobody — that must
      # NOT pass the failover acceptance vacuously
      print('WARNING: fleet served no requests '
            f"(completed={row['completed']}, "
            f"post_kill_qps={row['post_kill_qps']})", file=sys.stderr)
      return 1
    if row['recovery_ratio'] < 0.6:
      print(f"WARNING: fleet qps recovered to only "
            f"{row['recovery_ratio']:.2f}x pre-kill (< 0.6x bar)",
            file=sys.stderr)
      return 1
    # tracing acceptance (ISSUE 17): the mid-run drive must have
    # captured at least one slow-tail trace carrying the full
    # >=5-span tree (route -> rpc-less local queue_wait ->
    # dispatch_slice -> sample_collect + cold_fill) — an empty ring
    # here means the tail-retention path silently broke under load
    if (row['traced_tail_count'] < 1
        or row['traced_tail_max_spans'] < 5):
      print('WARNING: no slow-tail trace with >=5 spans captured '
            f"mid-run (tail={row['traced_tail_count']}, "
            f"max_spans={row['traced_tail_max_spans']}, "
            f"minted={row['traces_minted']}, "
            f"retained={row['traces_retained']})", file=sys.stderr)
      return 1
    if row['fleet_headroom_qps'] is None:
      print('WARNING: no replica exported fleet.headroom_qps — the '
            'capacity model never observed a dispatch',
            file=sys.stderr)
      return 1
    return 0
  model = TreeSAGE(hidden_features=32, out_features=16,
                   num_layers=len(args.fanout))
  result = {'num_nodes': args.nodes, 'fanout': list(args.fanout),
            'platform': jax.devices()[0].platform,
            'ops_enabled': ops is not None}
  ds = build_dataset(args.nodes, args.dim)
  rows = [run_phase('hot', ds, model, None, args, result, ops=ops)]
  if args.split_ratio and 0.0 < args.split_ratio < 1.0:
    ds_t = build_dataset(args.nodes, args.dim,
                         split_ratio=args.split_ratio)
    # params re-initialize under the same key -> same params; the
    # tiered phase measures the feature path, not the model
    rows.append(run_phase('tiered', ds_t, model, None, args, result,
                          ops=ops))
  if ops is not None:
    ops.close()
  # the zero-recompile pin covers EVERY phase (the tiered path holds
  # the extra collect/consume programs — the likelier escape route)
  bad = {r['label']: r['recompiles_after_warmup'] for r in rows
         if r['recompiles_after_warmup']}
  if bad:
    print(f'WARNING: recompile(s) after warmup {bad} — a shape '
          'escaped the bucket ladder', file=sys.stderr)
    return 1
  # acceptance: the mid-run scrape must have parsed as valid
  # Prometheus text with the promised families present
  bad_scrapes = {r['label']: r['ops'] for r in rows
                 if 'ops' in r and not (r['ops'].get('scrape_ok')
                                        and r['ops'].get('all_present'))}
  if bad_scrapes:
    print(f'WARNING: mid-run ops scrape failed validation '
          f'{bad_scrapes}', file=sys.stderr)
    return 1
  return 0


if __name__ == '__main__':
  sys.exit(main())
