"""Feature-lookup throughput (GB/s) across hot/cold split ratios.

Reference counterpart: `benchmarks/api/bench_feature.py:27-62` — gather
the features of each sampled batch's node set, timed alone, reported
as GB/s.  Sweeps ``split_ratio`` (1.0 = all HBM, like the reference's
DMA mode; lower = two-tier with host gathers) and the Pallas DMA
kernel vs the XLA gather on the hot tier.

Usage::

    python benchmarks/bench_feature.py [--cpu] [--quick]

r5 PROTOCOL CAVEAT: this sweep still times dispatch loops with
`block_until_ready`, which the tunneled chip can under-report by
orders of magnitude (elided executions — see benchmarks/README
"r5 protocol note").  Its numbers are comparative between configs in
one run, NOT absolute; the authoritative pull-protocol numbers are
`bench.py`'s (gather roofline, epoch walls).
"""
import argparse
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from benchmarks.common import Timer, build_graph, emit


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--cpu', action='store_true')
  ap.add_argument('--quick', action='store_true')
  ap.add_argument('--dim', type=int, default=128)
  ap.add_argument('--overlap-only', action='store_true',
                  help='skip the lookup sweep; run only the prefetch '
                       'overlap measurement')
  args = ap.parse_args()

  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  from graphlearn_tpu.data import Dataset, sort_by_in_degree
  from graphlearn_tpu.sampler import NeighborSampler, NodeSamplerInput

  n = 200_000 if args.quick else 1_000_000
  iters = 5 if args.quick else 20
  rows, cols = build_graph(n)
  feats = np.random.default_rng(0).standard_normal(
      (n, args.dim)).astype(np.float32)
  rng = np.random.default_rng(1)

  if not args.overlap_only:
    # sampled node sets at the flagship config drive the lookups
    ds0 = Dataset().init_graph((rows, cols), layout='COO', num_nodes=n)
    sampler = NeighborSampler(ds0.get_graph(), [15, 10, 5], seed=0)
    node_sets = []
    for _ in range(iters):
      seeds = rng.integers(0, n, 1024).astype(np.int32)
      out = sampler.sample_from_nodes(NodeSamplerInput(node=seeds))
      node_sets.append(np.asarray(out.node))

  # legacy lookup sweep runs cache-OFF so its rows stay comparable
  # across bench rounds (the r10 cache sweep below measures budgets)
  os.environ['GLT_COLD_CACHE_ROWS'] = '0'
  for split_ratio in (() if args.overlap_only else (1.0, 0.5, 0.2)):
    for pallas in ((True, False) if split_ratio == 1.0 else (False,)):
      os.environ['GLT_PALLAS'] = '1' if pallas else '0'
      ds = Dataset().init_graph((rows, cols), layout='COO', num_nodes=n)
      ds.init_node_features(
          feats,
          sort_func=sort_by_in_degree if split_ratio < 1.0 else None,
          split_ratio=split_ratio)
      feat = ds.get_node_feature()
      # warm every node set once: the two-tier path buckets its compact
      # cold buffer by power-of-two size, so different sets may hit
      # different compiled variants — compiles must not land in the timer
      for ns in node_sets:
        feat[ns].block_until_ready()
      nbytes = 0
      with Timer() as t:
        res = None
        for ns in node_sets:
          res = feat[ns]
          nbytes += res.size * res.dtype.itemsize
        res.block_until_ready()
      emit('feature_lookup_gbps', nbytes / t.dt / 1e9, 'GB/s',
           split_ratio=split_ratio,
           impl=('pallas' if pallas else 'xla'),
           platform=jax.devices()[0].platform)
  os.environ.pop('GLT_PALLAS', None)

  # -- cold-cache budget sweep (r10): hit rate vs HBM spend --------------
  # The same sampled node sets against the split_ratio=0.2 store, with
  # the HBM victim cache (`data.cold_cache`) at 0 / 5% / 15% of the
  # cold rows — the BENCH_ARTIFACT row behind the "how much cache buys
  # how many hits" tradeoff (benchmarks/README "Cold-tier cache").
  # Timed pass runs WARM (cache populated by the warmup pass), so the
  # hit rate is the steady-state epoch>=2 number; stats reset between.
  if not args.overlap_only:
    split = 0.2
    cold_rows = n - int(round(n * split))
    for frac in (0.0, 0.05, 0.15):
      budget = int(cold_rows * frac)
      os.environ['GLT_COLD_CACHE_ROWS'] = str(budget)
      ds = Dataset().init_graph((rows, cols), layout='COO', num_nodes=n)
      ds.init_node_features(feats, sort_func=sort_by_in_degree,
                            split_ratio=split)
      feat = ds.get_node_feature()
      for ns in node_sets:
        feat[ns].block_until_ready()
      cache = feat._cold_cache
      if cache is not None:
        cache.stats.__init__()                    # steady-state window
      feat.cold_stats['lookups'] = 0
      feat.cold_stats['cold_lookups'] = 0
      nbytes = 0
      with Timer() as t:
        res = None
        for ns in node_sets:
          res = feat[ns]
          nbytes += res.size * res.dtype.itemsize
        res.block_until_ready()
      cold = max(feat.cold_stats['cold_lookups'], 1)
      hits = cache.stats.hits if cache is not None else 0
      emit('feature_cold_cache_gbps', nbytes / t.dt / 1e9, 'GB/s',
           split_ratio=split, cache_rows=budget,
           budget_frac=frac,
           cache_hit_rate=round(hits / cold, 4),
           cold_lookups=feat.cold_stats['cold_lookups'],
           admits=cache.stats.admits if cache is not None else 0,
           evicts=cache.stats.evicts if cache is not None else 0,
           platform=jax.devices()[0].platform)
    os.environ.pop('GLT_COLD_CACHE_ROWS', None)
  else:
    os.environ.pop('GLT_COLD_CACHE_ROWS', None)

  # -- cold-path overlap: prefetch=2 vs synchronous loader ---------------
  # The batch loop alternates a device compute step with the loader's
  # cold gather + transfer; double buffering should hide most of the
  # loader's host time behind the compute (the UVA-overlap parity gap,
  # `csrc/cuda/unified_tensor.cu:202+`).
  from graphlearn_tpu.loader import NeighborLoader
  import jax.numpy as jnp

  @jax.jit
  def compute(x):
    for _ in range(8):
      x = jnp.tanh(x @ x.T) @ x
    return x

  ds = Dataset().init_graph((rows, cols), layout='COO', num_nodes=n)
  ds.init_node_features(feats, sort_func=sort_by_in_degree,
                        split_ratio=0.2)
  ds.init_node_labels((np.arange(n) % 4).astype(np.int32))
  seeds = rng.integers(0, n, 1024 * (4 if args.quick else 16))
  # every timed pass below covers the SAME n_timed batches (the first
  # batch of each epoch is consumed untimed as warmup/compile)
  n_timed = len(seeds) // 1024 - 1

  # loader-only pass: the host+transfer time prefetch should hide —
  # measured FIRST and directly (deriving it from a subtraction is not
  # robust to tunnel variance between passes)
  loader = NeighborLoader(ds, [15, 10], seeds, batch_size=1024,
                          shuffle=True, seed=0)
  it = iter(loader)
  b0 = next(it)
  b0.x.block_until_ready()
  with Timer() as t:
    b = None
    for b in it:
      b.x.block_until_ready()
  loader_time = t.dt

  # calibrate device compute to ~the per-batch loader time, so the
  # pipeline has comparable stages and the overlap claim is testable
  x0 = b0.x[:512]
  compute(x0).block_until_ready()
  with Timer() as t:
    compute(x0).block_until_ready()
  reps = max(1, int(loader_time / n_timed / max(t.dt, 1e-6)))

  def step(x):
    for _ in range(reps):
      x = compute(x)
    return x

  with Timer() as t:
    out = None
    for _ in range(n_timed):
      out = step(x0)
    out.block_until_ready()
  compute_time = t.dt

  times = {}
  for depth in (0, 2):
    loader = NeighborLoader(ds, [15, 10], seeds, batch_size=1024,
                            shuffle=True, seed=0, prefetch=depth)
    it = iter(loader)
    b = next(it)
    step(b.x[:512]).block_until_ready()
    with Timer() as t:
      out = None
      for b in it:
        out = step(b.x[:512])
      out.block_until_ready()
    times[depth] = t.dt
  # perfect overlap drives total from L + C to max(L, C): the
  # hideable span is min(L, C)
  hideable = min(loader_time, compute_time)
  hidden = (times[0] - times[2]) / max(hideable, 1e-9)
  emit('feature_prefetch_overlap', min(hidden, 1.0) * 100,
       '% hideable time hidden',
       sync_s=round(times[0], 4), prefetch_s=round(times[2], 4),
       loader_s=round(loader_time, 4),
       compute_s=round(compute_time, 4),
       platform=jax.devices()[0].platform)


if __name__ == '__main__':
  main()
