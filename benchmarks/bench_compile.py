"""Compile-time accounting for the mesh programs (VERDICT r3 #4).

A pod-scale program whose compile takes tens of minutes per
(shape, P) config is a real deployment cost: this tool measures the
wall of `jit(...).lower(...).compile()` for the three big mesh
programs — the per-batch distributed step, the DP train step, and the
whole-epoch `FusedDistEpoch` scan (with/without remat) — across batch
sizes, printing one JSON line per config so the numbers are
machine-comparable across rounds.  The root `bench.py` tracks the
same quantities in the artifact (`compile_secs`,
`fused_compile_secs`, dist `compile_secs`); this is the standalone
sweep for locating the knee.

Usage::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/bench_compile.py [--batches 128,512] [--steps 2]
"""
import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from benchmarks.common import build_graph

NODES = 200_000
DIM = 64
CLASSES = 47
FANOUT = [15, 10, 5]


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--batches', default='128,512')
  ap.add_argument('--steps', type=int, default=2,
                  help='scan length for the fused epoch (compile time '
                       'must not depend on it — a scan compiles its '
                       'body once)')
  ap.add_argument('--skip-fused', action='store_true')
  args = ap.parse_args()

  import jax
  import optax
  from graphlearn_tpu.models import GraphSAGE, create_train_state
  from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                       FusedDistEpoch, local_batch_piece,
                                       make_mesh,
                                       make_dp_supervised_step,
                                       replicate)

  num_parts = len(jax.devices())
  mesh = make_mesh(num_parts)
  platform = jax.devices()[0].platform
  rows, cols = build_graph(NODES)
  rng = np.random.default_rng(0)
  feats = rng.random((NODES, DIM), dtype=np.float32)
  labels = rng.integers(0, CLASSES, NODES).astype(np.int32)
  ds = DistDataset.from_full_graph(num_parts, rows, cols,
                                   node_feat=feats, node_label=labels,
                                   num_nodes=NODES)
  model = GraphSAGE(hidden_features=256, out_features=CLASSES,
                    num_layers=3)
  tx = optax.adam(3e-3)

  def rec(kind, batch, secs, **extra):
    print(json.dumps({'metric': 'compile_secs', 'kind': kind,
                      'batch': batch, 'num_parts': num_parts,
                      'fanout': FANOUT, 'platform': platform,
                      'value': round(secs, 1), **extra}), flush=True)

  for batch in [int(b) for b in args.batches.split(',')]:
    seeds = rng.permutation(NODES)[:batch * num_parts * args.steps]
    loader = DistNeighborLoader(ds, FANOUT, seeds, batch_size=batch,
                                shuffle=True, mesh=mesh, seed=0)
    # per-batch dist step (sampler + collection, ONE SPMD program)
    t0 = time.perf_counter()
    b0 = next(iter(loader))
    b0.x.block_until_ready()
    rec('dist_step', batch, time.perf_counter() - t0)
    # DP train step
    b0_local = local_batch_piece(b0, num_parts)
    # same init key across loop variants BY DESIGN: compile timing
    # must compare identical programs  # glint: disable=rng-discipline
    state, apply_fn = create_train_state(model, jax.random.key(0),
                                         b0_local, tx)
    step = make_dp_supervised_step(apply_fn, tx, batch, mesh)
    state_r = replicate(state, mesh)
    t0 = time.perf_counter()
    state_r, _, _ = step(state_r, b0)
    jax.tree_util.tree_leaves(state_r.params)[0].block_until_ready()
    rec('dp_step', batch, time.perf_counter() - t0)
    if args.skip_fused:
      continue
    for remat, fastc in ((False, False), (True, False), (True, True)):
      fused = FusedDistEpoch(ds, FANOUT, seeds, apply_fn, tx,
                             batch_size=batch, mesh=mesh, shuffle=True,
                             seed=0, remat=remat, fast_compile=fastc)
      # glint: disable=rng-discipline — same rationale as above
      st, _ = create_train_state(model, jax.random.key(1), b0_local, tx)
      st = replicate(st, mesh)
      t0 = time.perf_counter()
      st, _ = fused.run(st)
      jax.tree_util.tree_leaves(st.params)[0].block_until_ready()
      rec('fused_dist_epoch', batch, time.perf_counter() - t0,
          steps=len(fused), remat=remat, fast_compile=fastc)


if __name__ == '__main__':
  main()
