"""Distributed loader throughput over a device mesh.

Reference counterpart: `benchmarks/api/bench_dist_neighbor_loader.py`
(2 nodes x 2 GPUs, RPC sampling) — here the mesh-collective engine:
graph sharded over N devices, per-device seed shards, cross-partition
neighbor exchange on ICI (or the virtual CPU mesh).

Usage::

    # virtual 8-device mesh anywhere:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/bench_dist_loader.py --quick
"""
import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from benchmarks.common import Timer, build_graph, emit


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--quick', action='store_true')
  ap.add_argument('--num-parts', type=int, default=None)
  ap.add_argument('--dim', type=int, default=64)
  args = ap.parse_args()

  import jax
  from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                       make_mesh)

  num_parts = args.num_parts or len(jax.devices())
  mesh = make_mesh(num_parts)
  n = 100_000 if args.quick else 500_000
  rows, cols = build_graph(n)
  feats = np.random.default_rng(0).standard_normal(
      (n, args.dim)).astype(np.float32)
  labels = (np.arange(n) % 47).astype(np.int32)
  ds = DistDataset.from_full_graph(num_parts, rows, cols,
                                   node_feat=feats, node_label=labels,
                                   num_nodes=n)

  seeds = np.random.default_rng(1).permutation(n)[:8192 if args.quick
                                                  else 65536]
  for batch_size in (256, 512):
    loader = DistNeighborLoader(ds, [10, 5], seeds,
                                batch_size=batch_size, shuffle=True,
                                mesh=mesh, seed=0)
    b = next(iter(loader))          # compile
    b.x.block_until_ready()
    batches = 0
    with Timer() as t:
      last = None
      for b in loader:
        last = b
        batches += 1
      last.x.block_until_ready()
    global_batch = batch_size * num_parts
    emit('dist_loader_seeds_per_sec',
         batches * global_batch / t.dt / 1e3, 'K seeds/s',
         batch=batch_size, num_parts=num_parts,
         platform=jax.devices()[0].platform)


if __name__ == '__main__':
  main()
